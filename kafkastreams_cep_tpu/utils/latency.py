"""Latency attribution — the ingest→emit segment ledger (ISSUE 18).

The SASE+ framing is *low-latency* detection, yet until this tier every
published number was a throughput line.  The runtime deliberately trades
latency for throughput in three places — reorder grace
(``runtime/ingest.py``), lazy-drain deferral (``drain_interval``, PR 4),
and gate chunking (PR 10) — and this module is what makes those trades
measurable.  Every record is stamped (host wall clock, injectable) at the
five lifecycle boundaries the runtime already owns:

======================  ======================================================
boundary                where the stamp is taken
======================  ======================================================
**admit**               ``IngestGuard.push`` — the stamp rides the guard's
                        heap entry (and therefore its checkpoint state)
**release**             reorder-buffer release (``IngestGuard.release`` /
                        ``drain``); equals *admit* when no guard is armed
**dispatch**            ``CEPProcessor._dispatch`` just before the device
                        scan is enqueued
**complete**            after the device phase — rides the existing gates
                        transfer (no extra ``device_get``; under pipelining
                        this is the enqueue-observed host time)
**emit**                when the batch's matches are decoded and handed to
                        the caller (for lazy extraction: when the drain that
                        carries the batch's handles is decoded)
======================  ======================================================

The deltas roll into fixed-log-bucket **segment histograms** on the PR 3
``Histogram`` machinery (identical ``LATENCY_EDGES_S`` edges, so ledgers
merge associatively across bank members and mesh shards):

* ``reorder_hold`` = release − admit   (0 when no guard is armed)
* ``queue``        = dispatch − release (host pack + batching wait)
* ``device``       = complete − dispatch
* ``drain_defer``  = emit − complete   (the PR 4 lazy-extraction tax)
* ``e2e_total``    = the *sum of the four deltas* per record — conservation
  holds by construction: segment histogram sums reconcile with
  ``e2e_total``'s sum to float tolerance (tested).

Commit is transactional: a batch's stamps live in a :class:`BatchLatency`
bundle that is only folded into the histograms at its emit point
(``commit``).  Lazy batches whose handles are still on device are
``defer``-ed and committed when the drain that emits them decodes; the
deferred list is part of ``to_state`` so the ledger survives
checkpoint→restore/migrate/evacuation with the same exactly-once
discipline as every other piece of durable state (a rolled-back batch's
bundle dies with the rollback and is re-observed on replay — counts are
exactly-once; values are honest wall clock, so a replayed batch's e2e
includes the stall that rolled it back).

Stall attribution: the supervisor feeds ``recover`` / ``evacuate`` /
``replan`` wall time into per-cause stall histograms tagged with the
``corr`` id of the batch they rolled back, so a latency exemplar always
resolves to a real trace span.

:class:`SLOTracker` turns the ledger into an alerting signal: a declared
target percentile + threshold and a rolling window of per-batch
(over-threshold, total) pairs yield a burn rate — the fraction of records
over threshold divided by the SLO's error budget ``1 − target`` — exported
as the ``cep_slo_burn`` gauge (>1.0 means the SLO is burning faster than
budget).

Everything here is host-side Python: no device work, no extra transfers,
and a disarmed ledger costs one ``None`` check per call site.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from kafkastreams_cep_tpu.utils.telemetry import (
    LATENCY_EDGES_S,
    Histogram,
)

#: Per-record segment names, in lifecycle order.  ``e2e_total`` is kept
#: separate: it is derived (sum of these four), not a fifth boundary.
SEGMENTS: Tuple[str, ...] = ("reorder_hold", "queue", "device", "drain_defer")

E2E = "e2e_total"

#: Recognised stall causes (supervisor lifecycle verbs).  Other causes are
#: accepted — these are just the ones the runtime emits today.
STALL_CAUSES: Tuple[str, ...] = ("recover", "evacuate", "replan")


class BatchLatency:
    """One micro-batch's boundary stamps, awaiting commit.

    ``admit`` is a per-record list of admit stamps aligned with the
    released records (``None`` entries — and a ``None`` list — mean "no
    guard: admit coincides with release").  The other stamps are shared by
    every record in the batch: the runtime packs a batch at one host
    instant, dispatches it at one instant, and emits it at one instant, so
    per-record resolution only exists (and is only paid for) on the
    reorder-hold segment.
    """

    __slots__ = ("corr", "n", "admit", "release", "dispatch", "complete")

    def __init__(
        self,
        corr: str,
        n: int,
        admit: Optional[List[Optional[float]]] = None,
        release: Optional[float] = None,
    ):
        self.corr = corr
        self.n = int(n)
        self.admit = admit
        self.release = release
        self.dispatch: Optional[float] = None
        self.complete: Optional[float] = None

    def to_state(self) -> Dict[str, Any]:
        return {
            "corr": self.corr,
            "n": self.n,
            "admit": None if self.admit is None else list(self.admit),
            "release": self.release,
            "dispatch": self.dispatch,
            "complete": self.complete,
        }

    @staticmethod
    def from_state(state: Dict[str, Any]) -> "BatchLatency":
        b = BatchLatency(
            state["corr"], state["n"], state["admit"], state["release"]
        )
        b.dispatch = state["dispatch"]
        b.complete = state["complete"]
        return b


class SLOTracker:
    """Rolling-window SLO burn rate for the ``e2e_total`` segment.

    Declared contract: ``target`` of records finish within ``threshold_s``
    end to end.  Each committed batch contributes an
    ``(over_threshold, total)`` pair to a bounded window; the burn rate is
    the windowed over-threshold fraction divided by the error budget
    ``1 − target``.  Burn 1.0 = exactly on budget; >1.0 = the SLO will be
    violated if the window is representative.  Same shape as a Prometheus
    multiwindow burn alert, minus the multiwindow.
    """

    __slots__ = ("threshold_s", "target", "window", "_pairs")

    def __init__(
        self, threshold_s: float, target: float = 0.99, window: int = 256
    ):
        if not 0.0 < target < 1.0:
            raise ValueError(f"SLO target must be in (0, 1): {target}")
        if threshold_s <= 0.0:
            raise ValueError(f"SLO threshold must be positive: {threshold_s}")
        self.threshold_s = float(threshold_s)
        self.target = float(target)
        self.window = int(window)
        self._pairs: List[Tuple[int, int]] = []

    def observe(self, over: int, total: int) -> None:
        if total <= 0:
            return
        self._pairs.append((int(over), int(total)))
        if len(self._pairs) > self.window:
            del self._pairs[: len(self._pairs) - self.window]

    def burn_rate(self) -> float:
        total = sum(t for _, t in self._pairs)
        if total == 0:
            return 0.0
        over = sum(o for o, _ in self._pairs)
        return (over / total) / (1.0 - self.target)

    def snapshot(self) -> Dict[str, Any]:
        total = sum(t for _, t in self._pairs)
        over = sum(o for o, _ in self._pairs)
        return {
            "target": self.target,
            "threshold_s": self.threshold_s,
            "window_records": total,
            "window_over": over,
            "burn_rate": round(self.burn_rate(), 6),
        }

    def to_state(self) -> Dict[str, Any]:
        return {
            "threshold_s": self.threshold_s,
            "target": self.target,
            "window": self.window,
            "pairs": list(self._pairs),
        }

    @staticmethod
    def from_state(state: Dict[str, Any]) -> "SLOTracker":
        t = SLOTracker(state["threshold_s"], state["target"], state["window"])
        t._pairs = [tuple(p) for p in state["pairs"]]
        return t


class LatencyLedger:
    """Segment histograms + transactional batch bundles + stall attribution.

    The clock is injectable (tests pin a fake; production uses
    ``time.time`` — wall clock, not ``perf_counter``, because stamps must
    stay comparable across a checkpoint→restore process boundary).

    ``merge`` is associative and non-destructive, mirroring
    ``MetricsRegistry.merge``: bank members / mesh shards each keep a local
    ledger and the reporting layer folds them (in-flight deferred bundles
    are live state, not observations, so they stay with their owner).
    """

    def __init__(
        self,
        clock: Callable[[], float] = time.time,
        slo: Optional[SLOTracker] = None,
        edges: Sequence[float] = LATENCY_EDGES_S,
    ):
        self.clock = clock
        self.slo = slo
        self.edges = tuple(float(e) for e in edges)
        self._hists: Dict[str, Histogram] = {
            name: Histogram(name, self.edges) for name in SEGMENTS + (E2E,)
        }
        self._stalls: Dict[str, Histogram] = {}
        self._per_query: Dict[str, Histogram] = {}
        self._deferred: List[BatchLatency] = []
        #: segment -> {"corr", "seconds"} of the worst observation so far;
        #: the corr id matches the batch's trace span (``corr=`` attr), so
        #: an exemplar always resolves to a real span.
        self.exemplars: Dict[str, Dict[str, Any]] = {}
        self.batches_committed = 0
        self.records_committed = 0

    # -- batch lifecycle ------------------------------------------------------

    def start_batch(
        self,
        corr: str,
        n: int,
        admit: Optional[List[Optional[float]]] = None,
        release: Optional[float] = None,
    ) -> BatchLatency:
        """A new bundle for ``n`` records released at ``release`` (now when
        omitted).  ``admit`` is the guard's per-record admit-stamp list (or
        ``None`` when no guard is armed)."""
        if release is None:
            release = self.clock()
        if admit is not None and len(admit) != n:
            # Admission-path drops (dedup inside pack) can desync the
            # stamp list from the packed count; collapse to the no-guard
            # semantics rather than mis-attribute holds across records.
            admit = None
        return BatchLatency(corr, n, admit, release)

    def defer(self, bundle: BatchLatency) -> None:
        """Park a lazy batch whose match handles are still on device; it
        commits when the drain that emits them decodes."""
        self._deferred.append(bundle)

    def commit_deferred(self, emit: Optional[float] = None) -> int:
        """Commit every parked bundle at ``emit`` (their matches just left
        the device in one drain).  Returns the number committed."""
        if emit is None:
            emit = self.clock()
        parked, self._deferred = self._deferred, []
        for bundle in parked:
            self.commit(bundle, emit)
        return len(parked)

    def commit(self, bundle: BatchLatency, emit: Optional[float] = None) -> None:
        """Fold one batch's deltas into the segment histograms.

        ``e2e_total`` is observed as the per-record *sum of the four
        segment deltas* — conservation by construction, not by hoping two
        clock reads agree."""
        n = bundle.n
        if n <= 0:
            return
        if emit is None:
            emit = self.clock()
        release = bundle.release if bundle.release is not None else emit
        dispatch = bundle.dispatch if bundle.dispatch is not None else release
        complete = bundle.complete if bundle.complete is not None else dispatch
        queue = max(dispatch - release, 0.0)
        device = max(complete - dispatch, 0.0)
        defer = max(emit - complete, 0.0)
        shared = queue + device + defer
        self._hists["queue"].observe_many(queue, n)
        self._hists["device"].observe_many(device, n)
        self._hists["drain_defer"].observe_many(defer, n)
        over = 0
        threshold = self.slo.threshold_s if self.slo is not None else None
        if bundle.admit is None:
            self._hists["reorder_hold"].observe_many(0.0, n)
            self._hists[E2E].observe_many(shared, n)
            max_hold, max_e2e = 0.0, shared
            if threshold is not None and shared > threshold:
                over = n
        else:
            e2e_hist = self._hists[E2E]
            hold_hist = self._hists["reorder_hold"]
            max_hold = max_e2e = 0.0
            for a in bundle.admit:
                hold = max(release - a, 0.0) if a is not None else 0.0
                hold_hist.observe(hold)
                e2e = hold + shared
                e2e_hist.observe(e2e)
                if hold > max_hold:
                    max_hold = hold
                if e2e > max_e2e:
                    max_e2e = e2e
                if threshold is not None and e2e > threshold:
                    over += 1
        if self.slo is not None:
            self.slo.observe(over, n)
        for seg, v in (
            ("reorder_hold", max_hold),
            ("queue", queue),
            ("device", device),
            ("drain_defer", defer),
            (E2E, max_e2e),
        ):
            cur = self.exemplars.get(seg)
            if cur is None or v > cur["seconds"]:
                self.exemplars[seg] = {
                    "corr": bundle.corr,
                    "seconds": round(v, 9),
                }
        self.batches_committed += 1
        self.records_committed += n

    # -- side channels --------------------------------------------------------

    def observe_stall(
        self, cause: str, seconds: float, corr: Optional[str] = None
    ) -> None:
        """Supervisor stall time (recover/evacuate/replan) attributed to the
        batch ``corr`` it rolled back."""
        hist = self._stalls.get(cause)
        if hist is None:
            hist = self._stalls[cause] = Histogram(f"stall.{cause}", self.edges)
        hist.observe(seconds)
        if corr is not None:
            key = f"stall.{cause}"
            cur = self.exemplars.get(key)
            if cur is None or seconds > cur["seconds"]:
                self.exemplars[key] = {
                    "corr": corr,
                    "seconds": round(float(seconds), 9),
                }

    def observe_query(self, query: str, seconds: float) -> None:
        """Per-query e2e latency (tenant-bank path: one label per query)."""
        hist = self._per_query.get(query)
        if hist is None:
            hist = self._per_query[query] = Histogram(
                f"query.{query}", self.edges
            )
        hist.observe(seconds)

    # -- aggregation / durability ---------------------------------------------

    def merge(self, other: "LatencyLedger") -> "LatencyLedger":
        """A NEW ledger holding both operands' committed observations.
        Associative and commutative (tested); deferred bundles and the
        clock stay with their owners — the merged view is for reporting."""
        if self.edges != other.edges:
            raise ValueError("cannot merge ledgers with different edges")
        out = LatencyLedger(clock=self.clock, slo=None, edges=self.edges)
        for name in self._hists:
            out._hists[name] = self._hists[name].merge(other._hists[name])
        for src in (self._stalls, other._stalls):
            for cause, hist in src.items():
                have = out._stalls.get(cause)
                out._stalls[cause] = hist if have is None else have.merge(hist)
        for src in (self._per_query, other._per_query):
            for q, hist in src.items():
                have = out._per_query.get(q)
                out._per_query[q] = hist if have is None else have.merge(hist)
        for src in (self.exemplars, other.exemplars):
            for seg, ex in src.items():
                cur = out.exemplars.get(seg)
                # Ties break on corr so the merge stays commutative.
                if cur is None or ex["seconds"] > cur["seconds"] or (
                    ex["seconds"] == cur["seconds"]
                    and ex["corr"] < cur["corr"]
                ):
                    out.exemplars[seg] = dict(ex)
        if self.slo is not None and other.slo is None:
            out.slo = SLOTracker.from_state(self.slo.to_state())
        elif self.slo is not None and other.slo is not None:
            out.slo = SLOTracker.from_state(self.slo.to_state())
            out.slo._pairs = (self.slo._pairs + other.slo._pairs)[
                -out.slo.window:
            ]
        elif other.slo is not None:
            out.slo = SLOTracker.from_state(other.slo.to_state())
        out.batches_committed = self.batches_committed + other.batches_committed
        out.records_committed = self.records_committed + other.records_committed
        return out

    def _hist_state(self, h: Histogram) -> Dict[str, Any]:
        return {"counts": list(h.counts), "total": h.total, "sum": h.sum}

    def to_state(self) -> Dict[str, Any]:
        """Picklable durable form — everything but the clock (a restored
        ledger runs on wall clock unless the caller re-injects one)."""
        return {
            "edges": list(self.edges),
            "hists": {n: self._hist_state(h) for n, h in self._hists.items()},
            "stalls": {n: self._hist_state(h) for n, h in self._stalls.items()},
            "per_query": {
                n: self._hist_state(h) for n, h in self._per_query.items()
            },
            "deferred": [b.to_state() for b in self._deferred],
            "exemplars": {k: dict(v) for k, v in self.exemplars.items()},
            "slo": None if self.slo is None else self.slo.to_state(),
            "batches_committed": self.batches_committed,
            "records_committed": self.records_committed,
        }

    @staticmethod
    def from_state(
        state: Dict[str, Any], clock: Callable[[], float] = time.time
    ) -> "LatencyLedger":
        slo = (
            SLOTracker.from_state(state["slo"])
            if state.get("slo") is not None
            else None
        )
        out = LatencyLedger(clock=clock, slo=slo, edges=state["edges"])

        def _load(name: str, hs: Dict[str, Any]) -> Histogram:
            h = Histogram(name, out.edges)
            h.counts = list(hs["counts"])
            h.total = hs["total"]
            h.sum = hs["sum"]
            return h

        for name, hs in state["hists"].items():
            out._hists[name] = _load(name, hs)
        for cause, hs in state["stalls"].items():
            out._stalls[cause] = _load(f"stall.{cause}", hs)
        for q, hs in state["per_query"].items():
            out._per_query[q] = _load(f"query.{q}", hs)
        out._deferred = [BatchLatency.from_state(b) for b in state["deferred"]]
        out.exemplars = {k: dict(v) for k, v in state["exemplars"].items()}
        out.batches_committed = state["batches_committed"]
        out.records_committed = state["records_committed"]
        return out

    # -- reporting ------------------------------------------------------------

    def _seg_snapshot(self, h: Histogram) -> Dict[str, Any]:
        snap = h.snapshot()
        snap["p95"] = h.percentile(0.95)
        snap["p999"] = h.percentile(0.999)
        return snap

    def snapshot(self) -> Dict[str, Any]:
        """Deterministic dict form (under a pinned clock, identical runs
        produce identical snapshots — tested).  Segment entries are full
        histogram snapshots plus p95/p999; ``render_prometheus`` turns the
        structure into ``cep_latency_seconds{segment=}``,
        ``cep_stall_seconds{cause=}``, ``cep_latency_query_seconds{query=}``
        and the ``cep_slo_burn`` gauge."""
        out: Dict[str, Any] = {
            "segments": {
                name: self._seg_snapshot(self._hists[name])
                for name in SEGMENTS + (E2E,)
            },
            "batches": self.batches_committed,
            "records": self.records_committed,
            "deferred_batches": len(self._deferred),
        }
        if self._stalls:
            out["stalls"] = {
                cause: self._seg_snapshot(h)
                for cause, h in sorted(self._stalls.items())
            }
        if self._per_query:
            out["per_query"] = {
                q: self._seg_snapshot(h)
                for q, h in sorted(self._per_query.items())
            }
        if self.exemplars:
            out["exemplars"] = {
                k: dict(v) for k, v in sorted(self.exemplars.items())
            }
        if self.slo is not None:
            out["slo"] = self.slo.snapshot()
        return out
