from kafkastreams_cep_tpu.utils.events import Event, Sequence

__all__ = ["Event", "Sequence"]
