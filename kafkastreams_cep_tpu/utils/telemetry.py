"""Telemetry subsystem — the ``StreamsMetrics`` registry the reference
exposes but never records into (SURVEY §5), rebuilt for this runtime.

Four pillars, each mapped to its Kafka Streams analog:

* **MetricsRegistry** (:class:`MetricsRegistry`) — named counters, gauges,
  and fixed-log-bucket histograms.  The analog of
  ``StreamsMetrics``/``Sensor``: where the reference hands processors a
  registry through ``ProcessorContext.metrics()`` and then records nothing
  (``CEPProcessor.java`` never calls it), every layer here owns or feeds a
  registry and the snapshots are real.  Histogram bucket edges are
  deterministic (log-spaced, computed once), so snapshots of identical
  runs are bit-identical and histograms **merge** across bank members and
  mesh shards (``merge`` is associative — tested).  :func:`positive_delta`
  is the registry-level diffing the supervisor's escalation detector uses
  (replacing its hand-rolled ``_capacity_counters`` subtraction).
* **Span tracing** (:class:`TraceSink` / :meth:`TraceSink.span`) — the
  analog of Kafka Streams' per-node ``process-latency`` sensors, but as
  correlated JSON-lines events: one ``batch`` span per micro-batch (batch
  id, journal seq, lane count) with nested phase spans for
  ``pack → dispatch → device → decode → gc``, plus supervisor lifecycle
  spans (``checkpoint`` / ``recover`` / ``escalate``) and armed failpoint
  hits.  A recovery span carries the ``corr`` id of the batch span it
  rolled back, so an operator can walk from a recovery straight to the
  batch that triggered it.
* **Attribution** — per-lane (the partition analog) and per-pattern (bank
  member) engine-counter breakdowns beside the lane-summed view, plus
  watermark / event-time-lag gauges and HBM gauges
  (``metrics.device_memory_stats``) — the ``*-rate`` /
  ``records-lag`` metrics Kafka Streams derives from the consumer.
* **Export** (:func:`render_prometheus`, :class:`Reporter`) — Prometheus
  text exposition of any snapshot, and a cadence-driven flusher that
  writes metrics snapshots into the same JSONL stream the spans use (the
  JMX-reporter analog, minus JMX).

Nothing here touches the device: all instruments are host-side Python, and
disarmed tracing costs one ``None`` check per call site.
"""

from __future__ import annotations

import contextlib
import itertools
import json
import math
import os
import threading
import time
from typing import Any, Callable, Dict, Iterator, List, Optional, Sequence, Tuple


# -- histogram bucket edges ---------------------------------------------------

def log_bucket_edges(
    lo: float = 1e-6, hi: float = 100.0, per_decade: int = 4
) -> Tuple[float, ...]:
    """Deterministic log-spaced bucket edges covering ``[lo, hi]``.

    Edges are ``10**(i / per_decade)`` for integer ``i`` — a pure function
    of the arguments, so two registries built anywhere produce identical
    edges and their histograms are mergeable.
    """
    i0 = math.floor(math.log10(lo) * per_decade)
    i1 = math.ceil(math.log10(hi) * per_decade)
    return tuple(10.0 ** (i / per_decade) for i in range(i0, i1 + 1))


#: Default edges for wall-time-in-seconds observations: 1µs .. 100s,
#: 4 buckets per decade.  Every phase/lifecycle histogram in the runtime
#: uses these, so any two are mergeable.
LATENCY_EDGES_S = log_bucket_edges(1e-6, 100.0, 4)


# -- instruments --------------------------------------------------------------

class Counter:
    """A monotonically increasing named value (int or float seconds)."""

    __slots__ = ("name", "value")

    def __init__(self, name: str):
        self.name = name
        self.value = 0

    def inc(self, n=1) -> None:
        self.value += n


class Gauge:
    """A set-to-current-value instrument (watermarks, HBM bytes)."""

    __slots__ = ("name", "value")

    def __init__(self, name: str):
        self.name = name
        self.value = 0

    def set(self, v) -> None:
        self.value = v


class Histogram:
    """Fixed-log-bucket histogram: deterministic edges, mergeable.

    ``counts[i]`` holds observations ``<= edges[i]``; ``counts[-1]`` is the
    overflow bucket.  Percentiles interpolate to the geometric midpoint of
    the covering bucket — coarse by design (the edges are the resolution
    contract), but deterministic and exact under merge: merging N shards'
    histograms and asking for p99 gives the same answer as one histogram
    fed all N streams.
    """

    __slots__ = ("name", "edges", "counts", "total", "sum")

    def __init__(self, name: str, edges: Sequence[float] = LATENCY_EDGES_S):
        self.name = name
        self.edges = tuple(float(e) for e in edges)
        if list(self.edges) != sorted(set(self.edges)):
            raise ValueError(f"histogram edges must be strictly increasing: {edges}")
        self.counts = [0] * (len(self.edges) + 1)
        self.total = 0
        self.sum = 0.0

    def observe(self, v: float) -> None:
        v = float(v)
        self.total += 1
        self.sum += v
        # Bisect over a couple dozen edges: fine at batch cadence.
        lo, hi = 0, len(self.edges)
        while lo < hi:
            mid = (lo + hi) // 2
            if v <= self.edges[mid]:
                hi = mid
            else:
                lo = mid + 1
        self.counts[lo] += 1

    def observe_many(self, v: float, n: int) -> None:
        """``n`` observations of the same value ``v`` — one bisect, not
        ``n``.  The latency ledger's shared-stamp segments (every record in
        a micro-batch dispatches/completes/emits at one host instant) make
        this the hot path for per-record attribution at batch cadence."""
        if n <= 0:
            return
        v = float(v)
        self.total += n
        self.sum += v * n
        lo, hi = 0, len(self.edges)
        while lo < hi:
            mid = (lo + hi) // 2
            if v <= self.edges[mid]:
                hi = mid
            else:
                lo = mid + 1
        self.counts[lo] += n

    def merge(self, other: "Histogram") -> "Histogram":
        """A NEW histogram holding both operands' observations.  Requires
        identical edges (the determinism contract that makes merging across
        bank members / shards exact).  Associative and commutative."""
        if self.edges != other.edges:
            raise ValueError(
                f"cannot merge histograms with different edges: "
                f"{self.name} vs {other.name}"
            )
        out = Histogram(self.name, self.edges)
        out.counts = [a + b for a, b in zip(self.counts, other.counts)]
        out.total = self.total + other.total
        out.sum = self.sum + other.sum
        return out

    def percentile(self, q: float) -> float:
        """The ``q``-quantile (``0 < q <= 1``) at bucket resolution: the
        geometric midpoint of the first bucket whose cumulative count
        reaches ``q * total`` (0.0 on an empty histogram)."""
        if self.total == 0:
            return 0.0
        target = q * self.total
        cum = 0
        for i, c in enumerate(self.counts):
            cum += c
            if cum >= target:
                if i == 0:
                    return self.edges[0]
                if i == len(self.edges):
                    return self.edges[-1]
                return math.sqrt(self.edges[i - 1] * self.edges[i])
        return self.edges[-1]

    def snapshot(self) -> Dict[str, Any]:
        """Deterministic dict form: totals, p50/p99, and the non-empty
        buckets as ``(upper_edge, cumulative_count)`` pairs (the overflow
        bucket renders with edge ``inf``)."""
        buckets: List[Tuple[float, int]] = []
        cum = 0
        for i, c in enumerate(self.counts):
            cum += c
            if c:
                edge = self.edges[i] if i < len(self.edges) else math.inf
                buckets.append((edge, cum))
        return {
            "count": self.total,
            "sum": round(self.sum, 9),
            "p50": self.percentile(0.50),
            "p99": self.percentile(0.99),
            "buckets": buckets,
        }


class MetricsRegistry:
    """Named instruments with deterministic snapshots.

    ``counter`` / ``gauge`` / ``histogram`` create-or-fetch by name (a name
    re-used with a different instrument type raises — names are the
    contract downstream dashboards key on).  ``snapshot()`` is sorted by
    name, so two registries that saw the same operations serialize
    identically; ``merge`` is the cross-member/cross-shard aggregation
    (counters and histograms add; gauges take the *other* registry's value
    when both carry one — last-writer, like a re-emitted gauge).
    """

    def __init__(self):
        self._instruments: Dict[str, Any] = {}
        self._lock = threading.Lock()

    def _get(self, name: str, cls, *args):
        with self._lock:
            inst = self._instruments.get(name)
            if inst is None:
                inst = cls(name, *args)
                self._instruments[name] = inst
            elif not isinstance(inst, cls):
                raise TypeError(
                    f"metric {name!r} already registered as "
                    f"{type(inst).__name__}, not {cls.__name__}"
                )
            return inst

    def counter(self, name: str) -> Counter:
        return self._get(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get(name, Gauge)

    def histogram(
        self, name: str, edges: Sequence[float] = LATENCY_EDGES_S
    ) -> Histogram:
        return self._get(name, Histogram, edges)

    def items(self) -> List[Tuple[str, Any]]:
        """``(name, instrument)`` pairs sorted by name."""
        return sorted(self._instruments.items())

    def snapshot(self) -> Dict[str, Any]:
        """Flat name->value dict (histograms nest their snapshot dict),
        sorted by name — identical runs produce identical snapshots."""
        out: Dict[str, Any] = {}
        for name, inst in self.items():
            out[name] = (
                inst.snapshot() if isinstance(inst, Histogram) else inst.value
            )
        return out

    def delta(self, base: Dict[str, Any]) -> Dict[str, Any]:
        """Positive counter/gauge movement since ``base`` (a prior
        ``snapshot()`` or any name->number dict) — the supervisor's
        capacity-trip detector in registry form."""
        return positive_delta(
            {
                n: i.value
                for n, i in self.items()
                if isinstance(i, (Counter, Gauge))
            },
            base,
        )

    def merge(self, other: "MetricsRegistry") -> "MetricsRegistry":
        """A NEW registry aggregating both operands (see class docstring
        for per-instrument semantics).  Associative over counter and
        histogram content."""
        out = MetricsRegistry()
        for name, inst in self.items():
            if isinstance(inst, Histogram):
                out._instruments[name] = inst.merge(
                    Histogram(name, inst.edges)
                )
            elif isinstance(inst, Counter):
                out.counter(name).value = inst.value
            else:
                out.gauge(name).value = inst.value
        for name, inst in other.items():
            if isinstance(inst, Histogram):
                mine = out._instruments.get(name)
                out._instruments[name] = (
                    inst.merge(Histogram(name, inst.edges))
                    if mine is None
                    else mine.merge(inst)
                )
            elif isinstance(inst, Counter):
                out.counter(name).value += inst.value
            else:
                out.gauge(name).value = inst.value
        return out


def positive_delta(
    curr: Dict[str, Any], base: Dict[str, Any]
) -> Dict[str, Any]:
    """``{k: curr[k] - base[k]}`` for every key that moved UP — the one
    diffing primitive behind capacity-trip detection (cumulative counters,
    so a trip is a positive per-batch delta)."""
    out = {}
    for k, v in curr.items():
        d = v - base.get(k, 0)
        if d > 0:
            out[k] = d
    return out


def merge_counter_dicts(dicts: Sequence[Dict[str, int]]) -> Dict[str, int]:
    """Key-wise sum of plain counter dicts (bank members, shard reports)."""
    out: Dict[str, int] = {}
    for d in dicts:
        for k, v in d.items():
            out[k] = out.get(k, 0) + v
    return out


# -- span tracing -------------------------------------------------------------

class TraceSink:
    """Base sink: correlated span/event emission with parent tracking.

    Span ids are per-sink monotone integers (deterministic given the same
    call sequence); the active-span stack supplies ``parent_id``, so
    phases opened inside a batch span nest under it without any explicit
    plumbing.  Subclasses implement :meth:`write`.
    """

    def __init__(self):
        self._ids = itertools.count(1)
        self._stack: List[int] = []
        self._lock = threading.Lock()

    # subclass hook
    def write(self, event: Dict[str, Any]) -> None:
        raise NotImplementedError

    def emit(self, event: Dict[str, Any]) -> None:
        self.write(event)

    def event(self, name: str, **attrs: Any) -> None:
        """A point event (no duration) — failpoint hits, warnings."""
        with self._lock:
            parent = self._stack[-1] if self._stack else None
        evt = {
            "type": "event",
            "name": name,
            "ts_ms": round(time.time() * 1000.0, 3),
            "parent_id": parent,
        }
        evt.update(attrs)
        self.emit(evt)

    @contextlib.contextmanager
    def span(self, name: str, **attrs: Any) -> Iterator[Dict[str, Any]]:
        """Time a region and emit one span record on exit.

        Yields a mutable dict; keys set on it during the span land in the
        emitted record (match counts, replay sizes — facts only known at
        the end).  Exceptions propagate; the span still emits, flagged
        with ``error`` so a trace never silently swallows a failure.
        """
        with self._lock:
            sid = next(self._ids)
            parent = self._stack[-1] if self._stack else None
            self._stack.append(sid)
        extra: Dict[str, Any] = {}
        wall = time.time()
        t0 = time.perf_counter()
        err: Optional[str] = None
        try:
            yield extra
        except BaseException as e:
            err = f"{type(e).__name__}: {e}"
            raise
        finally:
            dt = time.perf_counter() - t0
            with self._lock:
                if self._stack and self._stack[-1] == sid:
                    self._stack.pop()
            evt = {
                "type": "span",
                "name": name,
                "span_id": sid,
                "parent_id": parent,
                "ts_ms": round(wall * 1000.0, 3),
                "duration_ms": round(dt * 1000.0, 6),
            }
            evt.update(attrs)
            evt.update(extra)
            if err is not None:
                evt["error"] = err
            self.emit(evt)


class InMemoryTraceSink(TraceSink):
    """Collects events in ``self.events`` — tests and ad-hoc inspection."""

    def __init__(self):
        super().__init__()
        self.events: List[Dict[str, Any]] = []

    def write(self, event: Dict[str, Any]) -> None:
        self.events.append(event)

    def spans(self, name: Optional[str] = None) -> List[Dict[str, Any]]:
        return [
            e
            for e in self.events
            if e["type"] == "span" and (name is None or e["name"] == name)
        ]


class JsonlTraceSink(TraceSink):
    """JSON-lines sink: one compact JSON object per line to a path or any
    file-like object.  The same stream carries spans, point events,
    Reporter metrics snapshots, and (with
    ``configure_logging(json_lines=True)``) lifecycle logs — one
    machine-parseable firehose.

    Path-owned sinks write each fully-serialized line through ONE
    unbuffered binary write (open ``"ab", buffering=0``): a crash between
    records leaves whole lines only, never a torn tail — the append-side
    twin of the Reporter's atomic ``.prom`` replace (and failpoint-tested
    through ``report.write``).

    ``max_bytes`` / ``max_age_s`` bound a path-owned file: when either is
    exceeded *at a line boundary*, the current file rolls to ``<path>.1``
    (replacing any previous rollover — one retained generation) and a
    fresh file starts.  Long-running supervisors previously grew the
    JSONL without bound.
    """

    def __init__(
        self,
        target,
        max_bytes: Optional[int] = None,
        max_age_s: Optional[float] = None,
    ):
        super().__init__()
        self.max_bytes = max_bytes
        self.max_age_s = max_age_s
        self.rollovers = 0
        if isinstance(target, (str, bytes)):
            self._path = target if isinstance(target, str) else target.decode()
            self._owns = True
            self._open()
        else:
            self._path = None
            self._fh = target
            self._owns = False
            self._size = 0
            self._birth = time.monotonic()

    def _open(self) -> None:
        self._fh = open(self._path, "ab", buffering=0)
        self._size = self._fh.tell()
        self._birth = time.monotonic()

    def _maybe_rotate(self, incoming: int) -> None:
        if self._path is None or not self._size:
            return
        over_size = (
            self.max_bytes is not None
            and self._size + incoming > self.max_bytes
        )
        over_age = (
            self.max_age_s is not None
            and time.monotonic() - self._birth >= self.max_age_s
        )
        if not (over_size or over_age):
            return
        self._fh.close()
        os.replace(self._path, self._path + ".1")
        self.rollovers += 1
        self._open()

    def write(self, event: Dict[str, Any]) -> None:
        data = (json.dumps(event, default=str) + "\n").encode("utf-8")
        if self._owns:
            self._maybe_rotate(len(data))
            self._fh.write(data)  # single unbuffered write: whole lines only
        else:
            self._fh.write(data.decode("utf-8"))
            flush = getattr(self._fh, "flush", None)
            if flush is not None:
                flush()
        self._size += len(data)

    def close(self) -> None:
        if self._owns:
            self._fh.close()


@contextlib.contextmanager
def maybe_span(
    sink: Optional[TraceSink], name: str, **attrs: Any
) -> Iterator[Dict[str, Any]]:
    """``sink.span(...)`` when tracing is on; a throwaway dict when off —
    call sites stay branch-free."""
    if sink is None:
        yield {}
    else:
        with sink.span(name, **attrs) as extra:
            yield extra


@contextlib.contextmanager
def timed_histogram(
    registry: MetricsRegistry,
    name: str,
    edges: Sequence[float] = LATENCY_EDGES_S,
) -> Iterator[None]:
    """Observe the enclosed block's wall seconds into ``registry``'s
    histogram ``name`` (lifecycle latencies: checkpoint/recover/escalate)."""
    t0 = time.perf_counter()
    try:
        yield
    finally:
        registry.histogram(name, edges).observe(time.perf_counter() - t0)


# Default sink: the hook :mod:`utils.failpoints` reports armed-site hits
# through, so chaos traces show the injected fault next to the recovery
# span it provoked.  Explicitly installed (never implicit) — production
# runs with no sink pay nothing.
_DEFAULT_SINK: Optional[TraceSink] = None


def set_default_sink(sink: Optional[TraceSink]) -> Optional[TraceSink]:
    """Install (or clear, with None) the process-default trace sink;
    returns the previous one so callers can restore it."""
    global _DEFAULT_SINK
    prev = _DEFAULT_SINK
    _DEFAULT_SINK = sink
    return prev


def get_default_sink() -> Optional[TraceSink]:
    return _DEFAULT_SINK


# -- Prometheus export --------------------------------------------------------

def _sanitize(name: str) -> str:
    return "".join(
        c if (c.isalnum() or c in "_:") else "_" for c in name
    ).strip("_")


def _fmt(v) -> str:
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, float):
        return repr(v)
    return str(v)


def _is_hist_snap(v) -> bool:
    return isinstance(v, dict) and {"count", "sum", "buckets"} <= set(v)


#: Curated HELP text by unprefixed metric family name.  Families not
#: listed fall back to a deterministic pointer at the README reference —
#: the metrics-guard test (tests/test_metrics_guard.py) only requires that
#: *every* emitted family carries HELP/TYPE, which the fallback guarantees.
METRIC_HELP: Dict[str, str] = {
    "phase_seconds": (
        "Host wall time per processing phase (pack/dispatch/device/decode/"
        "gc and supervisor lifecycle verbs)"
    ),
    "latency_seconds": (
        "Per-record ingest-to-emit latency by lifecycle segment "
        "(reorder_hold/queue/device/drain_defer/e2e_total)"
    ),
    "stall_seconds": (
        "Supervisor stall wall time (recover/evacuate/replan) attributed "
        "to the batch it rolled back"
    ),
    "latency_query_seconds": (
        "Per-query end-to-end latency (multi-tenant bank)"
    ),
    "slo_burn": (
        "SLO burn rate: windowed over-threshold record fraction divided by "
        "the error budget (1 - target); >1 burns faster than budget"
    ),
    "slo_target": "Declared SLO target percentile (fraction in (0,1))",
    "slo_threshold_seconds": "Declared SLO end-to-end latency threshold",
    "dead_letters_total": "Ingestion-guard quarantined records by reason",
    "event_time_lag_ms": (
        "Milliseconds between the host clock and the event-time watermark"
    ),
    "watermark": (
        "Event-time watermark: max packed record timestamp (ms since epoch)"
    ),
    "key_hops_total": "Walk-kernel hop work summed over all keys",
    "key_hops": "Walk-kernel hop work for the top-K heaviest keys",
    "overload_level": (
        "Brownout ladder level (runtime/overload.py): 0 healthy, 1 "
        "telemetry/drain degraded, 2 admission squeezed, 3 shedding, "
        "4 emergency admission stop"
    ),
    "overload_pressure": (
        "Overload pressure scalar: max of the normalized controller "
        "signals (SLO burn, reorder hold depth/age, queue p99, drain "
        "backlog); 1.0 = at the L1 entry reference"
    ),
    "overload_transitions": (
        "Committed brownout ladder transitions (either direction), each "
        "pinned by a checkpoint"
    ),
    "overload_transition_failures": (
        "Aborted ladder transition protocols (failpoint or pin-snapshot "
        "failure); the previous level stayed authoritative"
    ),
    "overload_shed": (
        "Admissible records shed at the ingest door under brownout "
        "(L3+), each a typed overload_shed dead letter — offered == "
        "admitted + shed + dead_lettered reconciles exactly"
    ),
}


def render_prometheus(
    snapshot: Dict[str, Any], prefix: str = "cep"
) -> str:
    """A metrics snapshot (``MetricsRegistry.snapshot()`` or any
    ``metrics_snapshot()`` dict in this runtime) as Prometheus text
    exposition, deterministically ordered.

    Structural keys get labels instead of name-mangling:
    ``per_lane``  -> ``{lane="i"}``, ``per_pattern`` -> ``{pattern="name"}``,
    ``per_query`` -> ``{query="name"}`` (the multi-tenant bank),
    ``phases``    -> ``<prefix>_phase_seconds{phase="name"}`` histograms,
    ``latency``   -> ``<prefix>_latency_seconds{segment="name"}`` histograms
    plus stall/per-query histograms and the ``<prefix>_slo_burn`` gauge
    (the latency-attribution ledger, utils/latency.py),
    ``dead_letters`` -> ``<prefix>_dead_letters_total{reason="late"}``,
    ``hbm``       -> ``<prefix>_hbm_<stat>`` gauges.  Histogram snapshots
    render as cumulative ``_bucket{le=...}`` series plus ``_sum``/``_count``.
    ``None`` values are skipped (absent, not zero).

    Every emitted family is preceded (at first occurrence) by ``# HELP`` /
    ``# TYPE`` metadata: curated text from :data:`METRIC_HELP` where
    available, a deterministic README pointer otherwise; type is
    ``histogram`` for histogram families, ``counter`` for ``_total``
    names, ``gauge`` for the rest.
    """
    lines: List[str] = []
    seen_meta: set = set()

    def meta(name: str, mtype: str) -> None:
        if name in seen_meta:
            return
        seen_meta.add(name)
        base = name[len(prefix) + 1:] if name.startswith(f"{prefix}_") else name
        text = METRIC_HELP.get(
            base, "runtime metric (see README metrics reference)"
        )
        lines.append(f"# HELP {name} {text}")
        lines.append(f"# TYPE {name} {mtype}")

    def scalar(name: str, v, labels: str = "") -> None:
        if v is None or isinstance(v, str):
            return
        meta(name, "counter" if name.endswith("_total") else "gauge")
        lines.append(f"{name}{labels} {_fmt(v)}")

    def hist(name: str, snap: Dict[str, Any], labels: Dict[str, str]) -> None:
        meta(name, "histogram")
        base = ",".join(f'{k}="{v}"' for k, v in labels.items())
        pre = f"{base}," if base else ""
        for edge, cum in snap["buckets"]:
            le = "+Inf" if edge == math.inf else repr(edge)
            lines.append(f'{name}_bucket{{{pre}le="{le}"}} {cum}')
        if not snap["buckets"] or snap["buckets"][-1][0] != math.inf:
            lines.append(f'{name}_bucket{{{pre}le="+Inf"}} {snap["count"]}')
        suffix = f"{{{base}}}" if base else ""
        lines.append(f"{name}_sum{suffix} {_fmt(snap['sum'])}")
        lines.append(f"{name}_count{suffix} {snap['count']}")

    for key in sorted(snapshot):
        val = snapshot[key]
        name = f"{prefix}_{_sanitize(key)}"
        if key == "phases" and isinstance(val, dict):
            for phase in sorted(val):
                hist(f"{prefix}_phase_seconds", val[phase], {"phase": phase})
        elif key == "per_lane" and isinstance(val, dict):
            for cname in sorted(val):
                series = val[cname]
                for lane, v in enumerate(series):
                    if v:
                        scalar(
                            f"{prefix}_{_sanitize(cname)}",
                            v,
                            f'{{lane="{lane}"}}',
                        )
        elif key == "dead_letters" and isinstance(val, dict):
            # Ingestion-guard quarantine counts by typed reason
            # (runtime/ingest.py): one labeled series per reason.
            for reason in sorted(val):
                scalar(
                    f"{prefix}_dead_letters_total",
                    val[reason],
                    f'{{reason="{reason}"}}',
                )
        elif key == "per_stage" and isinstance(val, dict):
            # Per-stage selectivity/cost attribution
            # (EngineConfig.stage_attribution): one labeled series per
            # stage per metric.
            for stage in sorted(val):
                sub = val[stage]
                if not isinstance(sub, dict):
                    continue
                for cname in sorted(sub):
                    v = sub[cname]
                    if isinstance(v, (int, float)) and not isinstance(v, bool):
                        scalar(
                            f"{prefix}_{_sanitize(cname)}",
                            v,
                            f'{{stage="{stage}"}}',
                        )
                    elif cname == "conjuncts" and isinstance(v, dict):
                        # Measured per-conjunct tallies (lazy-chain
                        # ranking input): stage+conjunct labeled series.
                        for ckey in sorted(v):
                            row = v[ckey]
                            if not isinstance(row, dict):
                                continue
                            for mname in sorted(row):
                                mv = row[mname]
                                if isinstance(
                                    mv, (int, float)
                                ) and not isinstance(mv, bool):
                                    scalar(
                                        f"{prefix}_conjunct_"
                                        f"{_sanitize(mname)}",
                                        mv,
                                        f'{{stage="{stage}",'
                                        f'conjunct="{ckey}"}}',
                                    )
        elif key == "per_key" and isinstance(val, dict):
            # Heavy-hitter cost attribution by key (processor
            # ``per_key_cost``): the top-K lanes' walk work as gauges.
            scalar(f"{prefix}_key_hops_total", val.get("total_hops"))
            for ent in val.get("top", []):
                scalar(
                    f"{prefix}_key_hops",
                    ent.get("hops"),
                    f'{{key="{ent.get("key")}",lane="{ent.get("lane")}"}}',
                )
        elif key == "per_pattern" and isinstance(val, dict):
            for pat in sorted(val):
                sub = val[pat]
                if not isinstance(sub, dict):
                    continue
                for cname in sorted(sub):
                    v = sub[cname]
                    if isinstance(v, (int, float)) and not isinstance(v, bool):
                        scalar(
                            f"{prefix}_{_sanitize(cname)}",
                            v,
                            f'{{pattern="{pat}"}}',
                        )
        elif key == "per_query" and isinstance(val, dict):
            # Multi-tenant bank attribution (parallel/tenantbank.py):
            # per-query engine + tier counters under a ``query`` label,
            # so one scrape distinguishes tenants sharing a dispatch.
            for qname in sorted(val):
                sub = val[qname]
                if not isinstance(sub, dict):
                    continue
                for cname in sorted(sub):
                    v = sub[cname]
                    if isinstance(v, (int, float)) and not isinstance(v, bool):
                        scalar(
                            f"{prefix}_{_sanitize(cname)}",
                            v,
                            f'{{query="{qname}"}}',
                        )
        elif key == "latency" and isinstance(val, dict):
            # Latency-attribution ledger (utils/latency.py): one histogram
            # per lifecycle segment, per-cause stall histograms, per-query
            # e2e histograms, and the SLO burn gauge.  Exemplars stay in
            # the JSON snapshot (text exposition has no exemplar syntax).
            segs = val.get("segments", {})
            for seg in sorted(segs):
                if _is_hist_snap(segs[seg]):
                    hist(
                        f"{prefix}_latency_seconds", segs[seg],
                        {"segment": seg},
                    )
            stalls = val.get("stalls", {})
            for cause in sorted(stalls):
                if _is_hist_snap(stalls[cause]):
                    hist(
                        f"{prefix}_stall_seconds", stalls[cause],
                        {"cause": cause},
                    )
            pq = val.get("per_query", {})
            for qname in sorted(pq):
                if _is_hist_snap(pq[qname]):
                    hist(
                        f"{prefix}_latency_query_seconds", pq[qname],
                        {"query": qname},
                    )
            slo = val.get("slo")
            if isinstance(slo, dict):
                scalar(f"{prefix}_slo_burn", slo.get("burn_rate"))
                scalar(f"{prefix}_slo_target", slo.get("target"))
                scalar(
                    f"{prefix}_slo_threshold_seconds", slo.get("threshold_s")
                )
            scalar(f"{prefix}_latency_batches_total", val.get("batches"))
            scalar(f"{prefix}_latency_records_total", val.get("records"))
            scalar(
                f"{prefix}_latency_deferred_batches",
                val.get("deferred_batches"),
            )
        elif key == "hbm" and isinstance(val, dict):
            for stat in sorted(val):
                scalar(f"{prefix}_hbm_{_sanitize(stat)}", val[stat])
        elif _is_hist_snap(val):
            hist(name, val, {})
        elif isinstance(val, dict):
            for sub in sorted(val):
                v = val[sub]
                if isinstance(v, (int, float)):
                    scalar(f"{name}_{_sanitize(sub)}", v)
        else:
            scalar(name, val)
    return "\n".join(lines) + "\n"


# -- the Reporter -------------------------------------------------------------

class Reporter:
    """Cadence-driven snapshot flusher — the JMX-reporter analog.

    ``snapshot_fn`` is any zero-arg callable returning a metrics dict
    (``CEPProcessor.metrics_snapshot`` / ``Supervisor.metrics_snapshot``).
    Call :meth:`tick` once per processed batch: every ``every_batches``
    ticks (and/or whenever ``interval_s`` wall seconds elapsed) the
    snapshot is emitted to ``sink`` as a ``{"type": "metrics"}`` JSONL
    record and, when ``prometheus_path`` is set, rendered to that file
    atomically (write-tmp-then-replace, scrape-safe).
    """

    def __init__(
        self,
        snapshot_fn: Callable[[], Dict[str, Any]],
        sink: Optional[TraceSink] = None,
        every_batches: int = 16,
        interval_s: Optional[float] = None,
        prometheus_path: Optional[str] = None,
        prefix: str = "cep",
    ):
        self.snapshot_fn = snapshot_fn
        self.sink = sink
        self.every_batches = max(int(every_batches), 1)
        self.interval_s = interval_s
        self.prometheus_path = prometheus_path
        self.prefix = prefix
        self.ticks = 0
        self.flushes = 0
        self._last_flush = time.perf_counter()

    def tick(self) -> Optional[Dict[str, Any]]:
        """One batch processed; flush if the cadence says so.  Returns the
        snapshot when a flush happened, else None."""
        self.ticks += 1
        due = self.ticks % self.every_batches == 0
        if not due and self.interval_s is not None:
            due = time.perf_counter() - self._last_flush >= self.interval_s
        return self.flush() if due else None

    def flush(self) -> Dict[str, Any]:
        """Snapshot and emit unconditionally.

        The JSONL record is serialized *before* anything is written and
        lands through the sink's single-write append — a crash anywhere
        in this method leaves either the complete record or nothing,
        exactly like the ``.prom`` write's tmp-then-replace.  The
        ``report.write`` failpoint sits in the serialized-but-unwritten
        window (armed by the torn-line test in tests/test_telemetry.py).
        """
        from kafkastreams_cep_tpu.utils.failpoints import fire as _failpoint

        snap = self.snapshot_fn()
        self.flushes += 1
        self._last_flush = time.perf_counter()
        if self.sink is not None:
            record = {
                "type": "metrics",
                "ts_ms": round(time.time() * 1000.0, 3),
                "tick": self.ticks,
                "snapshot": snap,
            }
            json.dumps(record, default=str)  # serialization failures fire here
            # Fault site: the record exists only in memory; a crash here
            # must leave the JSONL stream without any partial line.
            _failpoint("report.write")
            self.sink.emit(record)
        if self.prometheus_path is not None:
            tmp = self.prometheus_path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                f.write(render_prometheus(snap, self.prefix))
            os.replace(tmp, self.prometheus_path)
        return snap
