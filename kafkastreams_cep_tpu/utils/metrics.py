"""Metrics & tracing — the ``StreamsMetrics`` analog the reference skips.

The reference exposes Kafka Streams' metrics registry via the processor
context but never records anything (SURVEY §5); here the runtime keeps real
counters (records, matches, batches, device wall time) and the engine's
overflow diagnostics are pulled into the same snapshot.  ``profile``
wraps ``jax.profiler`` so a processor window can be captured for
TensorBoard/XProf when tuning on real TPU hardware.
"""

from __future__ import annotations

import contextlib
import time
from dataclasses import dataclass, field
from typing import Dict, Iterator


@dataclass
class Metrics:
    """Mutable counters for one processor (or bank member)."""

    records_in: int = 0
    matches_out: int = 0
    batches: int = 0
    duplicates_dropped: int = 0
    decode_fallbacks: int = 0  # compacted decode overflowed its budget
    device_seconds: float = 0.0
    decode_seconds: float = 0.0

    def snapshot(self, engine_counters: Dict[str, int]) -> Dict[str, float]:
        """One flat dict: runtime counters + engine overflow counters +
        derived rates."""
        out: Dict[str, float] = {
            "records_in": self.records_in,
            "matches_out": self.matches_out,
            "batches": self.batches,
            "duplicates_dropped": self.duplicates_dropped,
            "decode_fallbacks": self.decode_fallbacks,
            "device_seconds": round(self.device_seconds, 6),
            "decode_seconds": round(self.decode_seconds, 6),
        }
        if self.device_seconds > 0:
            out["events_per_second_device"] = round(
                self.records_in / self.device_seconds, 1
            )
        out.update(engine_counters)
        return out

    @contextlib.contextmanager
    def timed(self, attr: str) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            setattr(self, attr, getattr(self, attr) + time.perf_counter() - t0)


@contextlib.contextmanager
def profile(log_dir: str) -> Iterator[None]:
    """Capture a ``jax.profiler`` trace of the enclosed block (viewable in
    TensorBoard/XProf); use around ``processor.process`` calls on TPU."""
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


@contextlib.contextmanager
def annotate(name: str) -> Iterator[None]:
    """Name a host-side region inside an active profiler trace."""
    import jax

    with jax.profiler.TraceAnnotation(name):
        yield


def device_memory_stats() -> Dict[str, int]:
    """HBM usage of the first device (empty dict when the backend doesn't
    report) — sizing aid for lane-count / slab-shape capacity planning."""
    import jax

    try:
        stats = jax.local_devices()[0].memory_stats() or {}
    except Exception:
        return {}
    return {
        k: int(v)
        for k, v in stats.items()
        if isinstance(v, (int, float)) and "bytes" in k
    }
