"""Metrics & tracing — the ``StreamsMetrics`` analog the reference skips.

The reference exposes Kafka Streams' metrics registry via the processor
context but never records anything (SURVEY §5).  :class:`Metrics` keeps the
runtime's counters (records, matches, batches, per-phase wall time) — now
backed by a :class:`~kafkastreams_cep_tpu.utils.telemetry.MetricsRegistry`
instead of ad-hoc dataclass fields, so every timed phase also lands in a
fixed-log-bucket latency histogram (p50/p99 in ``snapshot()["phases"]``)
and processor metrics merge across bank members (``registry.merge``).

The attribute API (``metrics.records_in += n``, ``metrics.timed(attr)``)
is unchanged; storage moved into the registry.  ``profile`` wraps
``jax.profiler`` so a processor window can be captured for
TensorBoard/XProf when tuning on real TPU hardware.
"""

from __future__ import annotations

import contextlib
import time
from typing import Dict, Iterator, Optional

from kafkastreams_cep_tpu.utils.telemetry import (
    LATENCY_EDGES_S,
    MetricsRegistry,
)

#: Integer runtime counters, in their historical snapshot order.
COUNTER_ATTRS = (
    "records_in",
    "matches_out",
    "batches",
    "duplicates_dropped",
    "decode_fallbacks",
)

#: Wall-time accumulators; each also feeds the phase histogram of the same
#: stem ("device_seconds" -> phases["device"]).
SECONDS_ATTRS = (
    "device_seconds",
    "decode_seconds",
    "pack_seconds",
    "dispatch_seconds",
    "drain_seconds",
    "gc_seconds",
)

#: The batch phases every processor pre-registers, so snapshots of runs
#: that never hit a phase (e.g. gc off, eager extraction) still carry
#: identical key sets.
PHASE_NAMES = ("pack", "dispatch", "drain", "device", "decode", "gc")


def _counter_property(name: str) -> property:
    def get(self) -> float:
        return self.registry.counter(name).value

    def set(self, v) -> None:
        self.registry.counter(name).value = v

    return property(get, set)


class Metrics:
    """Mutable counters for one processor (or bank member), registry-backed.

    Counter attributes read/write registry counters; ``timed(attr)``
    accumulates wall seconds into the ``attr`` counter AND observes the
    corresponding phase latency histogram, so a single context manager
    yields both the lifetime total and the percentile view.
    """

    def __init__(self, registry: Optional[MetricsRegistry] = None):
        self.registry = registry or MetricsRegistry()
        for n in COUNTER_ATTRS + SECONDS_ATTRS:
            self.registry.counter(n)
        for n in PHASE_NAMES:
            self.registry.histogram(f"phase.{n}", LATENCY_EDGES_S)

    def snapshot(self, engine_counters: Dict[str, int]) -> Dict[str, float]:
        """One flat dict: runtime counters + engine overflow counters +
        derived rates + per-phase latency histograms (``"phases"``)."""
        out: Dict[str, float] = {
            n: self.registry.counter(n).value for n in COUNTER_ATTRS
        }
        for n in SECONDS_ATTRS:
            out[n] = round(self.registry.counter(n).value, 6)
        if out["device_seconds"] > 0:
            out["events_per_second_device"] = round(
                out["records_in"] / out["device_seconds"], 1
            )
        out.update(engine_counters)
        out["phases"] = self.phases()
        return out

    def phases(self) -> Dict[str, dict]:
        """Per-phase latency histogram snapshots (count/sum/p50/p99)."""
        return {
            name[len("phase."):]: inst.snapshot()
            for name, inst in self.registry.items()
            if name.startswith("phase.")
        }

    @contextlib.contextmanager
    def timed(self, attr: str) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            self.registry.counter(attr).value += dt
            phase = attr[:-8] if attr.endswith("_seconds") else attr
            self.registry.histogram(f"phase.{phase}", LATENCY_EDGES_S).observe(
                dt
            )


for _n in COUNTER_ATTRS + SECONDS_ATTRS:
    setattr(Metrics, _n, _counter_property(_n))
del _n


@contextlib.contextmanager
def profile(log_dir: str) -> Iterator[None]:
    """Capture a ``jax.profiler`` trace of the enclosed block (viewable in
    TensorBoard/XProf); use around ``processor.process`` calls on TPU."""
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


@contextlib.contextmanager
def annotate(name: str) -> Iterator[None]:
    """Name a host-side region inside an active profiler trace."""
    import jax

    with jax.profiler.TraceAnnotation(name):
        yield


def device_memory_stats() -> Dict[str, int]:
    """HBM usage of the first device (empty dict when the backend doesn't
    report) — sizing aid for lane-count / slab-shape capacity planning."""
    import jax

    try:
        stats = jax.local_devices()[0].memory_stats() or {}
    except Exception:
        return {}
    return {
        k: int(v)
        for k, v in stats.items()
        if isinstance(v, (int, float)) and "bytes" in k
    }
