"""Logging configuration — the ``logback.xml`` analog.

The reference ships a console logback config at INFO with DEBUG-level
per-edge evaluation logs (``src/main/resources/logback.xml``,
``NFA.java:180,232``).  Here the engine hot path is compiled, so per-edge
logging is host-side only: lifecycle events (compiles, lane assignment,
checkpoints) at INFO, decode details at DEBUG.  Library code only creates
loggers; this helper is the opt-in console setup for applications.
"""

from __future__ import annotations

import logging

ROOT = "kafkastreams_cep_tpu"

_FORMAT = "%(asctime)s %(levelname)-5s %(name)s - %(message)s"


def configure_logging(level: int = logging.INFO) -> logging.Logger:
    """Attach a console handler to the package root logger (idempotent)."""
    logger = logging.getLogger(ROOT)
    logger.setLevel(level)
    # Exact-type check: FileHandler subclasses StreamHandler and must not
    # suppress the console handler this function owns.
    if not any(type(h) is logging.StreamHandler for h in logger.handlers):
        handler = logging.StreamHandler()
        handler.setFormatter(logging.Formatter(_FORMAT))
        logger.addHandler(handler)
    return logger


def get_logger(name: str) -> logging.Logger:
    """A child logger under the package root."""
    return logging.getLogger(f"{ROOT}.{name}")
