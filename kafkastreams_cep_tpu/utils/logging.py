"""Logging configuration — the ``logback.xml`` analog.

The reference ships a console logback config at INFO with DEBUG-level
per-edge evaluation logs (``src/main/resources/logback.xml``,
``NFA.java:180,232``).  Here the engine hot path is compiled, so per-edge
logging is host-side only: lifecycle events (compiles, lane assignment,
checkpoints) at INFO, decode details at DEBUG.  Library code only creates
loggers; this helper is the opt-in console setup for applications.

``configure_logging(json_lines=True)`` swaps the human format for one JSON
object per line (``{"type": "log", "ts": ..., "level": ..., ...}``) —
shape-compatible with the telemetry trace stream
(``utils/telemetry.JsonlTraceSink``), so lifecycle logs, spans, and
metrics snapshots can be tailed, filtered, and joined as ONE
machine-parseable stream.
"""

from __future__ import annotations

import json
import logging

ROOT = "kafkastreams_cep_tpu"

_FORMAT = "%(asctime)s %(levelname)-5s %(name)s - %(message)s"


class JsonLinesFormatter(logging.Formatter):
    """One compact JSON object per record, keyed like the trace events
    (``type`` discriminates logs from spans/metrics in a merged stream)."""

    def format(self, record: logging.LogRecord) -> str:
        out = {
            "type": "log",
            "ts": self.formatTime(record, "%Y-%m-%dT%H:%M:%S")
            + f".{int(record.msecs):03d}",
            "level": record.levelname,
            "logger": record.name,
            "msg": record.getMessage(),
        }
        if record.exc_info:
            out["exc"] = self.formatException(record.exc_info)
        return json.dumps(out, default=str)


def configure_logging(
    level: int = logging.INFO, json_lines: bool = False
) -> logging.Logger:
    """Attach a console handler to the package root logger (idempotent).

    Re-invoking with a different ``json_lines`` re-formats the existing
    handler in place rather than stacking a second one.
    """
    logger = logging.getLogger(ROOT)
    logger.setLevel(level)
    # Exact-type check: FileHandler subclasses StreamHandler and must not
    # suppress the console handler this function owns.
    handler = next(
        (h for h in logger.handlers if type(h) is logging.StreamHandler),
        None,
    )
    if handler is None:
        handler = logging.StreamHandler()
        logger.addHandler(handler)
    handler.setFormatter(
        JsonLinesFormatter() if json_lines else logging.Formatter(_FORMAT)
    )
    return logger


def get_logger(name: str) -> logging.Logger:
    """A child logger under the package root."""
    return logging.getLogger(f"{ROOT}.{name}")
