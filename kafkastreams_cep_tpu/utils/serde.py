"""Serde infrastructure — the boundary between bytes and records.

The reference's serde stack (``serde/KryoSerDe.java``,
``AbstractKryoSerde.java``) exists because every store/changelog round-trip
crosses a byte boundary.  Here the only byte boundaries are stream ingest
and checkpoints: state arrays serialize as numpy blobs inside checkpoints
(``runtime/checkpoint.py``), so the pluggable part is the *record* serde —
this module.  ``JsonSerde`` is the analog of the demo's ``StockEventSerDe``
(``demo/StockEventSerDe.java:50-89``): JSON object <-> dict-of-scalars
values, the shape the device engine consumes.
"""

from __future__ import annotations

import json
from typing import Any, Callable, Generic, Optional, TypeVar

T = TypeVar("T")


class Serde(Generic[T]):
    """A (serializer, deserializer) pair over ``bytes``."""

    def __init__(
        self,
        serialize: Callable[[T], bytes],
        deserialize: Callable[[bytes], T],
    ):
        self.serialize = serialize
        self.deserialize = deserialize


def json_serde(encoding: str = "utf-8") -> Serde[Any]:
    """JSON-over-utf8 for dict/list/scalar values (compact separators, so
    output matches the reference demo's JSON lines byte-for-byte)."""
    return Serde(
        serialize=lambda obj: json.dumps(
            obj, separators=(",", ":")
        ).encode(encoding),
        deserialize=lambda data: json.loads(data.decode(encoding)),
    )


def string_serde(encoding: str = "utf-8") -> Serde[str]:
    return Serde(
        serialize=lambda s: s.encode(encoding),
        deserialize=lambda b: b.decode(encoding),
    )
