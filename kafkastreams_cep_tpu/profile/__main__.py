"""``python -m kafkastreams_cep_tpu.profile`` entry point."""

import sys

from kafkastreams_cep_tpu.profile import main

if __name__ == "__main__":
    sys.exit(main())
