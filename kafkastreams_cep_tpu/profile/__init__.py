"""Programmatic profiler CLI — ``python -m kafkastreams_cep_tpu.profile``.

Folds the three hand-run profiling scripts (``profile_step.py``,
``profile_phases.py``, ``profile_ablate.py`` — kept as thin wrappers at
the repo root) into one entry point that emits **structured PROFILE
JSON**: exactly one JSON object on stdout, all diagnostics on stderr, so
the PROFILE_r0x reports and the bench regression gate can consume
profiler output programmatically instead of scraping logs.

Subcommands
-----------

``step``         K-scaling of the headline scan (flat step time ⇒
                 dispatch/op-count bound, linear ⇒ bandwidth bound).
``phases``       standalone batched slab-kernel timings with XLA
                 bytes/flops estimates (out-of-context — see ``ablate``).
``ablate``       the in-context ablation (chain → +puts → +branch →
                 +walks), each variant in its own process.
``selectivity``  the continuous-profiling readout (ISSUE 6): per-stage
                 selectivity & cost (``EngineConfig.stage_attribution``),
                 per-key heavy hitters, and the measured A/B overhead of
                 attribution on the same trace — the numbers PROFILE_r08
                 records and the ≤3 %-overhead acceptance bound checks.
``latency``      end-to-end latency attribution (ISSUE 18): drives a
                 ledger-instrumented ``CEPProcessor`` over synthetic
                 stock batches and reports per-segment percentiles
                 (reorder_hold/queue/device/drain_defer/e2e_total), SLO
                 burn, XLA ``cost_analysis()`` device-time attribution
                 for the compiled scan, and (``--trace-dir``) an
                 optional ``jax.profiler`` trace capture.

Every subcommand accepts ``--k/--t/--reps`` size knobs and ``--platform``
(e.g. ``cpu``) so the tier-1 smoke test can drive tiny shapes on CI.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Any, Dict, List, Optional


def _log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def _setup_jax(platform: Optional[str]) -> None:
    import jax

    if platform:
        jax.config.update("jax_platforms", platform)
    jax.config.update(
        "jax_compilation_cache_dir",
        os.environ.get(
            "CEP_BENCH_CACHE_DIR",
            os.path.join(
                os.environ.get("XDG_CACHE_HOME")
                or os.path.join(os.path.expanduser("~"), ".cache"),
                "cep_tpu_bench_cache",
            ),
        ),
    )
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)


def _stock_pattern():
    sys.path.insert(
        0,
        os.path.join(
            os.path.dirname(os.path.dirname(os.path.dirname(__file__))),
            "examples",
        ),
    )
    import stock_demo

    return stock_demo.stock_pattern()


def _stock_events(K: int, T: int, seed: int = 42):
    import jax.numpy as jnp
    import numpy as np

    from kafkastreams_cep_tpu.engine import EventBatch

    rng = np.random.default_rng(seed)
    prices = rng.integers(90, 131, size=(K, T)).astype(np.int32)
    volumes = rng.integers(600, 1101, size=(K, T)).astype(np.int32)
    return EventBatch(
        key=jnp.broadcast_to(jnp.arange(K, dtype=jnp.int32)[:, None], (K, T)),
        value={"price": jnp.asarray(prices), "volume": jnp.asarray(volumes)},
        ts=jnp.broadcast_to(
            jnp.arange(T, dtype=jnp.int32)[None, :] * 2, (K, T)
        ),
        off=jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32)[None, :], (K, T)),
        valid=jnp.ones((K, T), bool),
    )


def _timed_scan(batch, state0, events, reps: int):
    """(best seconds, compile seconds) of ``batch.scan`` on ``events``."""
    import jax

    t0 = time.perf_counter()
    state, out = batch.scan(state0, events)
    jax.block_until_ready(out.count)
    compile_s = time.perf_counter() - t0
    best = float("inf")
    for _ in range(max(reps, 1)):
        t0 = time.perf_counter()
        state, out = batch.scan(state0, events)
        jax.block_until_ready(out.count)
        best = min(best, time.perf_counter() - t0)
    return best, compile_s, state


# ---------------------------------------------------------------------------
# step — K-scaling (port of profile_step.py)
# ---------------------------------------------------------------------------


def run_step(args) -> Dict[str, Any]:
    from kafkastreams_cep_tpu.engine import EngineConfig
    from kafkastreams_cep_tpu.parallel import BatchMatcher

    cfg = EngineConfig(
        max_runs=24, slab_entries=48, slab_preds=8, dewey_depth=12,
        max_walk=12,
    )
    pattern = _stock_pattern()
    ks = [int(x) for x in args.k.split(",")]
    T = args.t
    points: List[Dict[str, Any]] = []
    for K in ks:
        batch = BatchMatcher(pattern, K, cfg)
        events = _stock_events(K, T)
        best, comp, _ = _timed_scan(batch, batch.init_state(), events,
                                    args.reps)
        pt = {
            "k": K,
            "t": T,
            "scan_ms": round(best * 1e3, 3),
            "ms_per_step": round(best / T * 1e3, 4),
            "evps": round(K * T / best, 1),
            "compile_s": round(comp, 2),
        }
        points.append(pt)
        _log(
            f"K={K:6d} T={T}: scan {pt['scan_ms']:8.1f} ms "
            f"({pt['ms_per_step']:6.2f} ms/step, {pt['evps'] / 1e3:8.0f}K "
            f"ev/s) [compile {comp:.0f}s]"
        )
    return {"profile": "step", "points": points}


# ---------------------------------------------------------------------------
# phases — standalone slab kernels (port of profile_phases.py)
# ---------------------------------------------------------------------------


def run_phases(args) -> Dict[str, Any]:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from kafkastreams_cep_tpu.ops import slab as slab_mod

    K = args.k if isinstance(args.k, int) else int(args.k.split(",")[0])
    R, E, MP, D, W = 24, 48, 8, 12, 12
    H = 2
    RH, PW = R * H, 3 * R
    rng = np.random.default_rng(0)
    i32 = jnp.int32

    def mk_slab():
        # Random content over a make()-shaped slab (internally inconsistent
        # — see `ablate` for in-context numbers); building on make() keeps
        # this in sync with SlabState's counter fields.
        one = slab_mod.make(E, MP, D)
        base = jax.tree_util.tree_map(
            lambda x: jnp.broadcast_to(x, (K,) + x.shape), one
        )
        n_live = E // 2
        stage = np.full((K, E), -1, np.int32)
        stage[:, :n_live] = rng.integers(0, 4, (K, n_live))
        off = np.full((K, E), -1, np.int32)
        off[:, :n_live] = rng.integers(0, 100, (K, n_live))
        return base._replace(
            stage=jnp.asarray(stage),
            off=jnp.asarray(off),
            refs=jnp.asarray(rng.integers(0, 3, (K, E)), i32),
            npreds=jnp.asarray(rng.integers(0, MP, (K, E)), i32),
            pstage=jnp.asarray(rng.integers(-1, 4, (K, E, MP)), i32),
            poff=jnp.asarray(rng.integers(0, 100, (K, E, MP)), i32),
            pver=jnp.asarray(rng.integers(0, 3, (K, E, MP, D)), i32),
            pvlen=jnp.asarray(rng.integers(1, 4, (K, E, MP)), i32),
        )

    results: Dict[str, Any] = {}

    def bench(name, fn, *fargs):
        jfn = jax.jit(fn)
        ca = {}
        try:
            comp = jfn.lower(*fargs).compile()
            c = comp.cost_analysis()
            if isinstance(c, list):
                c = c[0]
            ca = c or {}
        except Exception:
            pass
        out = jfn(*fargs)
        jax.block_until_ready(out)
        best = float("inf")
        for _ in range(max(args.reps, 1)):
            t0 = time.perf_counter()
            out = jfn(*fargs)
            jax.block_until_ready(out)
            best = min(best, time.perf_counter() - t0)
        row = {
            "ms": round(best * 1e3, 3),
            "bytes_accessed": ca.get("bytes accessed", 0),
            "flops": ca.get("flops", 0),
        }
        results[name] = row
        _log(
            f"{name:16s}: {best * 1e3:7.2f} ms   "
            f"bytes={row['bytes_accessed']:.2e} flops={row['flops']:.2e}"
        )

    slab = mk_slab()
    off = jnp.asarray(rng.integers(100, 200, (K,)), i32)
    ops = slab_mod.PutOps(
        en=jnp.asarray(rng.random((K, RH)) < 0.1),
        first=jnp.asarray(rng.random((K, RH)) < 0.3),
        cur_stage=jnp.asarray(rng.integers(0, 4, (K, RH)), i32),
        prev_stage=jnp.asarray(rng.integers(-1, 4, (K, RH)), i32),
        prev_off=jnp.asarray(rng.integers(0, 100, (K, RH)), i32),
        ver=jnp.asarray(rng.integers(0, 3, (K, RH, D)), i32),
        vlen=jnp.asarray(rng.integers(1, 4, (K, RH)), i32),
    )
    bench(
        "puts_batched",
        jax.vmap(lambda s, o, f: slab_mod.puts_batched(s, o, f)),
        slab, ops, off,
    )

    en_b = jnp.asarray(rng.random((K, R)) < 0.15)
    st_b = jnp.asarray(rng.integers(0, 4, (K, R)), i32)
    off_b = jnp.asarray(rng.integers(0, 100, (K, R)), i32)
    ver_b = jnp.asarray(rng.integers(0, 3, (K, R, D)), i32)
    vlen_b = jnp.asarray(rng.integers(1, 4, (K, R)), i32)
    bench(
        "branch_batched",
        jax.vmap(
            lambda s, e, st, o, v, vl: slab_mod.branch_batched(
                s, e, st, o, v, vl, W
            )
        ),
        slab, en_b, st_b, off_b, ver_b, vlen_b,
    )

    en_w = jnp.asarray(rng.random((K, PW)) < 0.15)
    st_w = jnp.asarray(rng.integers(0, 4, (K, PW)), i32)
    off_w = jnp.asarray(rng.integers(0, 100, (K, PW)), i32)
    ver_w = jnp.asarray(rng.integers(0, 3, (K, PW, D)), i32)
    vlen_w = jnp.asarray(rng.integers(1, 4, (K, PW)), i32)
    is_rm = jnp.concatenate(
        [jnp.zeros((K, R), bool), jnp.ones((K, 2 * R), bool)], axis=1
    )
    want = jnp.concatenate(
        [jnp.zeros((K, 2 * R), bool), jnp.ones((K, R), bool)], axis=1
    )
    bench(
        "walks_batched",
        jax.vmap(
            lambda s, e, st, o, v, vl, ir, wo: slab_mod.walks_batched(
                s, e, st, o, v, vl, ir, wo, W
            )
        ),
        slab, en_w, st_w, off_w, ver_w, vlen_w, is_rm, want,
    )
    gate = _measure_dispatch_gate(K, args.t, args.reps)
    return {
        "profile": "phases", "k": K, "kernels": results,
        "dispatch_gate": gate,
    }


def _measure_dispatch_gate(K: int, T: int, reps: int) -> Dict[str, Any]:
    """Measured chunk-gate elision (ISSUE 18 satellite): scan a tiered
    matcher and read back the PR 10 ``gate_chunks`` / ``nfa_dispatches``
    dispatch accounting as a fraction.  On a chunk-gated hybrid plan the
    fraction is NFA chunks actually dispatched over chunks offered
    (< 1.0 means the gate elided work); on whole-batch plans (pure NFA,
    stencil, whole-scan kernel) ``gate_chunks`` stays 0 and the fraction
    falls back to dispatches per scan call.  The stock pattern plans
    pure-NFA (no strict prefix), so this uses a strict-prefix + Kleene
    shape that plans hybrid, over a sparse trace where most chunks
    promote nothing."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from kafkastreams_cep_tpu import Query
    from kafkastreams_cep_tpu.engine import EngineConfig, EventBatch
    from kafkastreams_cep_tpu.parallel.tiered import TieredBatchMatcher

    def val(code):
        return lambda k, v, ts, st: v == code

    pattern = (
        Query()
        .select("a").where(val(0))
        .then()
        .select("b").where(val(1))
        .then()
        .select("c").one_or_more().where(val(2))
        .then()
        .select("d").where(val(3))
        .build()
    )
    cfg = EngineConfig(
        max_runs=24, slab_entries=48, slab_preds=8, dewey_depth=12,
        max_walk=12, tiering=True,
    )
    batch = TieredBatchMatcher(pattern, K, cfg)
    # Noise everywhere, a full a,b,c,d match planted at the head of every
    # OTHER gate_chunk-sized segment: promoting chunks must dispatch,
    # quiet chunks must be elided, so the measured fraction sits mid-range
    # by construction (~0.5) instead of degenerating to 0 or 1.
    C = max(int(cfg.gate_chunk), 1)
    vals = np.full((K, T), 4, np.int32)
    for c0 in range(0, T, 2 * C):
        if c0 + 4 <= T:
            vals[:, c0:c0 + 4] = np.array([0, 1, 2, 3], np.int32)
    i32 = jnp.int32
    events = EventBatch(
        key=jnp.broadcast_to(jnp.arange(K, dtype=i32)[:, None], (K, T)),
        value=jnp.asarray(vals),
        ts=jnp.broadcast_to(jnp.arange(T, dtype=i32)[None, :] * 2, (K, T)),
        off=jnp.broadcast_to(jnp.arange(T, dtype=i32)[None, :], (K, T)),
        valid=jnp.ones((K, T), bool),
    )
    state = batch.init_state()
    out = None
    for _ in range(max(reps, 1)):
        state, out = batch.scan(state, events)
    jax.block_until_ready(jax.tree_util.tree_leaves(out))
    calls = int(batch.scan_calls)
    chunks = int(batch.gate_chunks)
    dispatches = int(batch.nfa_dispatches)  # the one host sync
    denom = chunks if chunks else calls
    row = {
        "tier": str(batch.plan.tier),
        "scan_calls": calls,
        "gate_chunks": chunks,
        "nfa_dispatches": dispatches,
        "nfa_dispatch_fraction": (
            round(dispatches / denom, 4) if denom else None
        ),
    }
    _log(
        f"dispatch_gate: tier={row['tier']} chunks={chunks} "
        f"nfa_dispatches={dispatches} fraction={row['nfa_dispatch_fraction']}"
    )
    return row


# ---------------------------------------------------------------------------
# ablate — in-context ablation (port of profile_ablate.py)
# ---------------------------------------------------------------------------

_ABLATE_VARIANTS = ("A", "B", "C", "D")


def _run_ablate_variant(which: str, K: int, T: int, reps: int) -> float:
    import jax
    import jax.numpy as jnp

    from kafkastreams_cep_tpu.engine import EngineConfig
    from kafkastreams_cep_tpu.ops import slab as slab_mod
    from kafkastreams_cep_tpu.parallel import BatchMatcher

    real = {
        "puts": slab_mod.puts_batched,
        "branch": slab_mod.branch_batched,
        "walks": slab_mod.walks_batched,
    }

    def noop_puts(slab, ops, off, **kw):
        return slab

    def noop_branch(slab, en, stage, off, ver, vlen, max_walk, **kw):
        return slab

    def noop_walks(slab, en, stage, off, ver, vlen, is_remove, want_out,
                   max_walk, collect=True, **kw):
        P = jnp.asarray(stage).shape[0]
        i32 = jnp.int32
        return (
            slab,
            jnp.full((P, max_walk), -1, i32),
            jnp.full((P, max_walk), -1, i32),
            jnp.zeros((P,), i32),
        )

    patch = {
        "A": {"puts": noop_puts, "branch": noop_branch, "walks": noop_walks},
        "B": {"puts": "real", "branch": noop_branch, "walks": noop_walks},
        "C": {"puts": "real", "branch": "real", "walks": noop_walks},
        "D": {"puts": "real", "branch": "real", "walks": "real"},
    }[which]
    for k, v in patch.items():
        setattr(slab_mod, k + "_batched", real[k] if v == "real" else v)
    try:
        cfg = EngineConfig(
            max_runs=24, slab_entries=48, slab_preds=8, dewey_depth=12,
            max_walk=12,
        )
        batch = BatchMatcher(_stock_pattern(), K, cfg)
        events = _stock_events(K, T)
        best, comp, _ = _timed_scan(batch, batch.init_state(), events, reps)
        _log(f"ablate[{which}]: best {best * 1e3:.1f} ms (compile {comp:.1f}s)")
        return best
    finally:
        for k, fn in real.items():
            setattr(slab_mod, k + "_batched", fn)


def run_ablate(args) -> Dict[str, Any]:
    K = args.k if isinstance(args.k, int) else int(args.k.split(",")[0])
    T = args.t
    if args.variant:
        best = _run_ablate_variant(args.variant, K, T, args.reps)
        return {"profile": "ablate-variant", "variant": args.variant,
                "best_s": best}
    # Each variant in its own process (four matchers + executables do not
    # share HBM on a real chip; also isolates the monkeypatch).
    import subprocess

    results: Dict[str, float] = {}
    for v in _ABLATE_VARIANTS:
        cmd = [
            sys.executable, "-m", "kafkastreams_cep_tpu.profile", "ablate",
            "--variant", v, "--k", str(K), "--t", str(T),
            "--reps", str(args.reps),
        ]
        if args.platform:
            cmd += ["--platform", args.platform]
        out = subprocess.run(cmd, capture_output=True, text=True)
        for line in out.stderr.splitlines():
            if "WARNING" not in line:
                _log(line)
        try:
            doc = json.loads(out.stdout.strip().splitlines()[-1])
            results[v] = float(doc["best_s"])
        except Exception:
            _log(f"ablate[{v}]: no result (rc={out.returncode})")
    if len(results) < 4:
        return {"profile": "ablate", "error": "incomplete", "raw": results}
    a, b, c, d = (results[v] for v in _ABLATE_VARIANTS)
    per_step = lambda t: round(t / T * 1e3, 3)
    breakdown = {
        "chain_compaction": {"ms_per_step": per_step(a),
                             "share": round(a / d, 4)},
        "puts_batched": {"ms_per_step": per_step(b - a),
                         "share": round((b - a) / d, 4)},
        "branch_walks": {"ms_per_step": per_step(c - b),
                         "share": round((c - b) / d, 4)},
        "walks_batched": {"ms_per_step": per_step(d - c),
                          "share": round((d - c) / d, 4)},
    }
    _log(f"ablation K={K} T={T}: total {per_step(d):.2f} ms/step")
    return {
        "profile": "ablate", "k": K, "t": T,
        "total_ms_per_step": per_step(d), "breakdown": breakdown,
    }


# ---------------------------------------------------------------------------
# selectivity — the continuous-profiling readout (ISSUE 6)
# ---------------------------------------------------------------------------


def run_selectivity(args) -> Dict[str, Any]:
    import dataclasses

    import numpy as np

    from kafkastreams_cep_tpu.engine import EngineConfig
    from kafkastreams_cep_tpu.engine.matcher import per_lane_counter_arrays
    from kafkastreams_cep_tpu.parallel import BatchMatcher

    K = args.k if isinstance(args.k, int) else int(args.k.split(",")[0])
    T = args.t
    pattern = _stock_pattern()
    base = EngineConfig(
        max_runs=args.runs, slab_entries=args.slab, slab_preds=8,
        dewey_depth=12, max_walk=12,
    )
    events = _stock_events(K, T, seed=args.seed)

    off_b = BatchMatcher(pattern, K, base)
    best_off, comp_off, _ = _timed_scan(
        off_b, off_b.init_state(), events, args.reps
    )
    on_cfg = dataclasses.replace(base, stage_attribution=True)
    on_b = BatchMatcher(pattern, K, on_cfg)
    best_on, comp_on, state = _timed_scan(
        on_b, on_b.init_state(), events, args.reps
    )
    overhead = (best_on - best_off) / best_off * 100.0

    # Per-query compiler-tiering tag (ISSUE 7): which tier this query
    # would execute on, plus the lazy-chain conjunct ordering the pass
    # derives from THIS run's measured per-stage selectivity.
    from kafkastreams_cep_tpu.compiler.tables import lower
    from kafkastreams_cep_tpu.compiler.tiering import (
        apply_lazy_order,
        plan_tiering,
    )

    per_stage = on_b.stage_counters(state)
    tables = lower(pattern)
    _, lazy_report = apply_lazy_order(tables, per_stage)
    tier_tag = {
        "stock": {
            **plan_tiering(tables, base).describe(),
            "lazy_order": lazy_report,
        }
    }
    arrays = per_lane_counter_arrays(state)
    hops = (
        arrays["walk_hops"] + arrays["extract_hops"] + arrays["drain_hops"]
    ).reshape(-1)
    total = int(hops.sum())
    order = np.argsort(hops, kind="stable")[::-1][:8]
    per_key = {
        "total_hops": total,
        "top": [
            {
                "key": str(int(l)),  # bare matcher: key == lane id
                "lane": int(l),
                "hops": int(hops[l]),
                "share": round(hops[l] / total, 4) if total else 0.0,
            }
            for l in order
            if hops[l] > 0
        ],
    }
    _log(
        f"selectivity (K={K}, T={T}): attribution off "
        f"{K * T / best_off / 1e3:.0f}K ev/s vs on "
        f"{K * T / best_on / 1e3:.0f}K ev/s — overhead {overhead:.2f}%"
    )
    for stage, row in per_stage.items():
        _log(f"  stage {stage}: {row}")
    return {
        "profile": "selectivity",
        "k": K,
        "t": T,
        "evps_attr_off": round(K * T / best_off, 1),
        "evps_attr_on": round(K * T / best_on, 1),
        "overhead_pct": round(overhead, 2),
        "per_stage": per_stage,
        "per_key": per_key,
        # tier=stencil|hybrid|nfa per query + the lazy-chain conjunct
        # order derived from the measured selectivity above.
        "tier": tier_tag,
        "compile_s": {"off": round(comp_off, 2), "on": round(comp_on, 2)},
    }


# ---------------------------------------------------------------------------
# latency — end-to-end latency attribution (ISSUE 18)
# ---------------------------------------------------------------------------


def _cost_analysis(jfn, *fargs) -> Dict[str, Any]:
    """XLA cost-analysis row for one compiled program ({} when the
    backend exposes none — e.g. some CPU builds)."""
    try:
        comp = jfn.lower(*fargs).compile()
        c = comp.cost_analysis()
        if isinstance(c, list):
            c = c[0]
        ca = c or {}
    except Exception:
        return {}
    row = {
        "bytes_accessed": ca.get("bytes accessed", 0),
        "flops": ca.get("flops", 0),
    }
    if "optimal_seconds" in ca:
        row["optimal_seconds"] = ca["optimal_seconds"]
    return row


def run_latency(args) -> Dict[str, Any]:
    import numpy as np

    from kafkastreams_cep_tpu.engine import EngineConfig
    from kafkastreams_cep_tpu.runtime.ingest import IngestPolicy
    from kafkastreams_cep_tpu.runtime.processor import CEPProcessor, Record
    from kafkastreams_cep_tpu.utils.latency import LatencyLedger, SLOTracker

    K = args.k if isinstance(args.k, int) else int(args.k.split(",")[0])
    T = args.t
    cfg = EngineConfig(
        max_runs=24, slab_entries=48, slab_preds=8, dewey_depth=12,
        max_walk=12,
    )
    ingest = (
        IngestPolicy(grace_ms=args.grace_ms, reorder_depth=max(4 * K * T, 64))
        if args.grace_ms > 0
        else None
    )
    ledger = LatencyLedger(
        slo=SLOTracker(threshold_s=args.slo_ms / 1e3)
    )
    proc = CEPProcessor(
        _stock_pattern(), K, cfg, ingest=ingest, latency=ledger,
        drain_interval=args.drain_interval,
    )
    rng = np.random.default_rng(args.seed)
    tracing = False
    if args.trace_dir:
        import jax

        try:
            jax.profiler.start_trace(args.trace_dir)
            tracing = True
        except Exception as e:
            _log(f"latency: trace capture unavailable ({e})")
    matches = 0
    try:
        ts = 0
        for _ in range(args.batches):
            records = []
            for i in range(K * T):
                ts += int(rng.integers(1, 3))
                records.append(Record(
                    key=int(i % K),
                    value={
                        "price": int(rng.integers(90, 131)),
                        "volume": int(rng.integers(600, 1101)),
                    },
                    timestamp=ts,
                ))
            matches += len(proc.process(records))
        matches += len(proc.flush())
    finally:
        if tracing:
            import jax

            jax.profiler.stop_trace()
    snap = proc.metrics_snapshot(per_lane=False)
    lat = snap.get("latency") or {}
    segments = {
        name: {
            k: seg[k]
            for k in ("count", "p50", "p95", "p99", "p999")
            if k in seg
        }
        for name, seg in (lat.get("segments") or {}).items()
    }
    device_cost = {
        "scan": _cost_analysis(proc.batch.scan, proc.state,
                               _stock_events(K, T)),
    }
    for name, seg in segments.items():
        _log(
            f"latency[{name}]: n={seg.get('count', 0)} "
            f"p50={seg.get('p50')} p99={seg.get('p99')}"
        )
    return {
        "profile": "latency",
        "k": K,
        "t": T,
        "batches": args.batches,
        "drain_interval": args.drain_interval,
        "grace_ms": args.grace_ms,
        "matches": matches,
        "segments": segments,
        "slo": lat.get("slo"),
        "exemplars": lat.get("exemplars"),
        "device_cost": device_cost,
        "trace_dir": args.trace_dir or None,
    }


# ---------------------------------------------------------------------------


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m kafkastreams_cep_tpu.profile",
        description=__doc__.split("\n\n")[0],
    )
    sub = p.add_subparsers(dest="cmd", required=True)

    def common(sp, k_default):
        sp.add_argument("--k", default=k_default,
                        help="lane count (step: comma list)")
        sp.add_argument("--t", type=int, default=int(
            os.environ.get("PROF_T", "32")))
        sp.add_argument("--reps", type=int, default=2)
        sp.add_argument("--platform", default=os.environ.get("CEP_PLATFORM"))
        sp.add_argument("--seed", type=int, default=42)

    common(sub.add_parser("step"), "512,4096,16384")
    common(sub.add_parser("phases"), "4096")
    sp = sub.add_parser("ablate")
    common(sp, "4096")
    sp.add_argument("--variant", choices=_ABLATE_VARIANTS, default=None)
    sp = sub.add_parser("selectivity")
    common(sp, "256")
    sp.add_argument("--runs", type=int, default=16)
    sp.add_argument("--slab", type=int, default=32)
    sp = sub.add_parser("latency")
    common(sp, "64")
    sp.add_argument("--batches", type=int, default=4)
    sp.add_argument("--grace-ms", type=int, default=0,
                    help="reorder grace (0 = no ingest guard)")
    sp.add_argument("--drain-interval", type=int, default=1)
    sp.add_argument("--slo-ms", type=float, default=1000.0,
                    help="e2e SLO threshold for burn-rate tracking")
    sp.add_argument("--trace-dir", default=None,
                    help="capture a jax.profiler trace into this dir")

    args = p.parse_args(argv)
    # Normalize --k for single-int subcommands.
    if args.cmd != "step":
        try:
            args.k = int(str(args.k).split(",")[0])
        except ValueError:
            p.error(f"--k must be an integer for {args.cmd}")
    _setup_jax(args.platform)
    out = {
        "step": run_step,
        "phases": run_phases,
        "ablate": run_ablate,
        "selectivity": run_selectivity,
        "latency": run_latency,
    }[args.cmd](args)
    print(json.dumps(out), flush=True)
    return 0
