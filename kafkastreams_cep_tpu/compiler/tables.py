"""Stage graph -> dense transition tables for the array engine.

Lowers the object graph produced by :func:`compile_pattern` (the exact
``pattern/StatesFactory.java:41-119`` semantics) into fixed-shape numpy
arrays the device NFA step consumes:

* **Node enumeration.** The compiled stage *list* excludes ONE_OR_MORE Kleene
  loop stages — ``buildState`` returns only the mandatory entry state and the
  loop stage is reachable solely through its BEGIN edge
  (``StatesFactory.java:110-118``).  Nodes are therefore enumerated by DFS
  preorder over edge targets starting from the BEGIN-typed stage, which
  yields ``[begin, ..., $final]`` in chain order.
* **Identity.** Stage equality in the reference is ``(name, type)`` only
  (``Stage.java:116-127``); two positions can share an identity (a
  mid-pattern ONE_OR_MORE mandatory state and its loop stage).  ``ident[s]``
  is the canonical (first) position with the same ``(name, type)`` — the
  engine compares identities, not positions, wherever the reference calls
  ``Stage.equals`` (e.g. the PROCEED version rule, ``NFA.java:185``).
* **Edges.** Per position: at most one consuming edge (BEGIN or TAKE,
  ``StatesFactory.java:80-81``), one IGNORE, one PROCEED.  IGNORE edges on
  BEGIN-typed stages are dropped, mirroring the oracle's documented
  deviation (begin re-seed subsumes them; ``nfa/oracle.py``).
* **Predicates** are deduplicated by object identity into a dispatch list;
  the tables store predicate ids.
* **Aggregates** become a flat list of ``(stage, state, fn)`` triples so the
  engine can apply folds in the reference's per-stage declaration order
  (``NFA.java:260-265``).

Everything here is host-side numpy; no jax imports.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Tuple

import numpy as np

from kafkastreams_cep_tpu.compiler.stages import (
    EdgeOperation,
    Stage,
    StageType,
    compile_pattern,
)
from kafkastreams_cep_tpu.pattern.pattern import Pattern
from kafkastreams_cep_tpu.pattern.predicate import Matcher

# Stage type codes.
TYPE_BEGIN = 0
TYPE_NORMAL = 1
TYPE_FINAL = 2

_TYPE_CODE = {
    StageType.BEGIN: TYPE_BEGIN,
    StageType.NORMAL: TYPE_NORMAL,
    StageType.FINAL: TYPE_FINAL,
}

# Consuming-op codes.
OP_NONE = 0
OP_BEGIN = 1
OP_TAKE = 2


@dataclasses.dataclass(frozen=True)
class AggSlot:
    """One fold registration: stage position, state index, fold fn."""

    stage: int
    state: int
    fn: Callable
    name: str


def stackable(tables) -> bool:
    """Whether these compiled queries share a stackable table shape —
    the single source of truth for ``_build_step``'s stacked mode and
    ``parallel/stacked.py``."""
    t0 = tables[0]
    return all(
        t.num_stages == t0.num_stages
        and t.max_hops == t0.max_hops
        and int(t.begin_pos) == int(t0.begin_pos)
        and int(t.final_pos) == int(t0.final_pos)
        for t in tables[1:]
    )


@dataclasses.dataclass
class TransitionTables:
    """Dense NFA tables, position-indexed in chain order ``[begin .. $final]``."""

    stages: List[Stage]
    names: List[str]
    types: np.ndarray  # [S] int32 — TYPE_* codes
    ident: np.ndarray  # [S] int32 — canonical (name, type) position
    window_ms: np.ndarray  # [S] int64 — -1 when unset
    consume_op: np.ndarray  # [S] int32 — OP_* codes
    consume_pred: np.ndarray  # [S] int32 — predicate id, -1 absent
    consume_target: np.ndarray  # [S] int32 — eval position of the consuming
    #   successor: self for TAKE (eps(current, current)), edge target for BEGIN
    ignore_pred: np.ndarray  # [S] int32 — -1 absent
    proceed_pred: np.ndarray  # [S] int32 — -1 absent
    proceed_target: np.ndarray  # [S] int32 — -1 absent
    predicates: List[Matcher]  # predicate dispatch list (P entries)
    state_names: List[str]  # fold-state names, first-appearance order
    state_inits: List  # declared init per state name
    state_dtypes: List[str]  # "int32" | "float32" per state name
    aggs: List[AggSlot]  # flat fold list, per-stage declaration order
    begin_pos: int
    final_pos: int
    max_hops: int  # longest PROCEED chain (frames per run per event)
    can_branch: bool  # any branching op-pair statically reachable

    @property
    def num_stages(self) -> int:
        return len(self.stages)

    @property
    def num_predicates(self) -> int:
        return len(self.predicates)

    @property
    def num_states(self) -> int:
        return len(self.state_names)

    def agg_masks(self) -> np.ndarray:
        """[NA, S] bool — which stage owns each agg slot (engine convenience)."""
        mask = np.zeros((len(self.aggs), len(self.stages)), dtype=bool)
        for i, agg in enumerate(self.aggs):
            mask[i, agg.stage] = True
        return mask

    def is_strict_seq(self) -> bool:
        """True for the branch-free fragment (all cardinality ONE, strict
        contiguity, no folds) that the data-parallel stencil matcher handles."""
        # can_branch already covers any IGNORE edge, so no separate clause.
        return (
            not self.can_branch
            and not self.aggs
            and not np.any(self.consume_op == OP_TAKE)
        )


def _enumerate_nodes(compiled: List[Stage]) -> List[Stage]:
    """DFS preorder over edge targets from the BEGIN-typed stage.

    Follows edges in declaration order, which for this compiler's output
    (a linear chain with self-loops) produces ``[begin, ..., $final]``.
    """
    begins = [s for s in compiled if s.type is StageType.BEGIN]
    if len(begins) != 1:
        raise ValueError(f"expected exactly one BEGIN stage, got {len(begins)}")
    order: List[Stage] = []
    seen: set = set()

    def visit(stage: Stage) -> None:
        if id(stage) in seen:
            return
        seen.add(id(stage))
        order.append(stage)
        for edge in stage.edges:
            if edge.target is not None:
                visit(edge.target)

    visit(begins[0])
    for stage in compiled:
        if id(stage) not in seen:  # pragma: no cover - defensive; chain is connected
            visit(stage)
    return order


def lower(pattern_or_stages) -> TransitionTables:
    """Lower a :class:`Pattern` (or pre-compiled stage list) to dense tables."""
    if isinstance(pattern_or_stages, Pattern):
        compiled = compile_pattern(pattern_or_stages)
    else:
        compiled = list(pattern_or_stages)

    nodes = _enumerate_nodes(compiled)
    pos: Dict[int, int] = {id(s): i for i, s in enumerate(nodes)}
    S = len(nodes)

    names = [s.name for s in nodes]
    types = np.array([_TYPE_CODE[s.type] for s in nodes], dtype=np.int32)
    window_ms = np.array([s.window_ms for s in nodes], dtype=np.int64)

    ident = np.zeros(S, dtype=np.int32)
    first_by_identity: Dict[Tuple[str, StageType], int] = {}
    for i, s in enumerate(nodes):
        key = (s.name, s.type)
        ident[i] = first_by_identity.setdefault(key, i)

    predicates: List[Matcher] = []
    pred_ids: Dict[int, int] = {}

    def pred_id(matcher: Matcher) -> int:
        existing = pred_ids.get(id(matcher))
        if existing is not None:
            return existing
        predicates.append(matcher)
        pred_ids[id(matcher)] = len(predicates) - 1
        return len(predicates) - 1

    consume_op = np.zeros(S, dtype=np.int32)
    consume_pred = np.full(S, -1, dtype=np.int32)
    consume_target = np.full(S, -1, dtype=np.int32)
    ignore_pred = np.full(S, -1, dtype=np.int32)
    proceed_pred = np.full(S, -1, dtype=np.int32)
    proceed_target = np.full(S, -1, dtype=np.int32)

    state_names: List[str] = []
    state_inits: List = []
    state_dtypes: List[str] = []
    aggs: List[AggSlot] = []

    for i, stage in enumerate(nodes):
        for agg in stage.aggregates:
            if agg.name not in state_names:
                state_names.append(agg.name)
                state_inits.append(agg.init)
                state_dtypes.append(agg.resolved_dtype)
            elif state_dtypes[state_names.index(agg.name)] != agg.resolved_dtype:
                raise ValueError(
                    f"fold state {agg.name!r} declared with conflicting "
                    f"dtypes across stages"
                )
            aggs.append(AggSlot(i, state_names.index(agg.name), agg.fn, agg.name))

        for edge in stage.edges:
            if edge.op is EdgeOperation.BEGIN:
                if consume_op[i] != OP_NONE:
                    raise ValueError(f"stage {stage.name!r}: multiple consuming edges")
                consume_op[i] = OP_BEGIN
                consume_pred[i] = pred_id(edge.matcher)
                consume_target[i] = pos[id(edge.target)]
            elif edge.op is EdgeOperation.TAKE:
                if consume_op[i] != OP_NONE:
                    raise ValueError(f"stage {stage.name!r}: multiple consuming edges")
                consume_op[i] = OP_TAKE
                consume_pred[i] = pred_id(edge.matcher)
                # TAKE successors self-loop via eps(current, current)
                # (NFA.java:196); the edge's declared target is not the
                # successor's eval position.
                consume_target[i] = i
            elif edge.op is EdgeOperation.IGNORE:
                if stage.type is StageType.BEGIN:
                    # Deviation (shared with the oracle): begin-stage IGNORE
                    # edges are subsumed by the begin re-seed.
                    continue
                if ignore_pred[i] != -1:
                    raise ValueError(f"stage {stage.name!r}: multiple IGNORE edges")
                ignore_pred[i] = pred_id(edge.matcher)
            elif edge.op is EdgeOperation.PROCEED:
                if proceed_pred[i] != -1:
                    raise ValueError(f"stage {stage.name!r}: multiple PROCEED edges")
                proceed_pred[i] = pred_id(edge.matcher)
                proceed_target[i] = pos[id(edge.target)]

    finals = np.flatnonzero(types == TYPE_FINAL)
    if len(finals) != 1:
        raise ValueError(f"expected exactly one FINAL stage, got {len(finals)}")
    final_pos = int(finals[0])
    begin_pos = 0  # DFS starts at the begin stage

    # Longest PROCEED chain: frames visited by one run in one event.
    hops = np.ones(S, dtype=np.int64)
    for i in range(S - 1, -1, -1):  # proceed targets are later in chain order
        t = proceed_target[i]
        if t >= 0:
            if t <= i:
                raise ValueError("PROCEED edge does not advance the chain")
            hops[i] = 1 + hops[t]
    max_hops = int(hops.max())

    # Branching requires one of the op pairs {P,T} {I,T} {I,B} {I,P}
    # (NFA.java:280-289) to be matchable at a single stage.
    has_ignore = ignore_pred >= 0
    has_proceed = proceed_pred >= 0
    can_branch = bool(
        np.any(has_ignore) or np.any((consume_op == OP_TAKE) & has_proceed)
    )

    return TransitionTables(
        stages=nodes,
        names=names,
        types=types,
        ident=ident,
        window_ms=window_ms,
        consume_op=consume_op,
        consume_pred=consume_pred,
        consume_target=consume_target,
        ignore_pred=ignore_pred,
        proceed_pred=proceed_pred,
        proceed_target=proceed_target,
        predicates=predicates,
        state_names=state_names,
        state_inits=state_inits,
        state_dtypes=state_dtypes,
        aggs=aggs,
        begin_pos=begin_pos,
        final_pos=final_pos,
        max_hops=max_hops,
        can_branch=can_branch,
    )
