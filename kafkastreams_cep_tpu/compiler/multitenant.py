"""Bank-level compile pass: prefix trie + deduplicated predicate table.

The serial bank (``runtime/bank.py``) pays one dispatch per query; the
naive-fused stack (``parallel/stacked.py``) pays every query's predicates
on every lane.  Per the CEP join-query sharing results (arxiv 1801.09413)
the right unit of compilation for N concurrent queries is the *bank*:

* **Prefix trie.**  Each query's maximal strict-contiguity prefix
  (``compiler/tiering.py: plan_tiering``) is a path of predicate
  *columns*; queries whose prefixes share columns share the stencil
  screen work for them.  :func:`plan_bank` interns every distinct
  state-independent prefix predicate as one column of a bank-wide column
  table and renders each query's prefix as a path of column ids — the
  trie of those paths is the shared-screen structure
  (``parallel/tenantbank.py`` evaluates each column ONCE per batch).
* **Residual predicate dedup.**  The union of all queries' step-tier
  predicates is interned into one merged dispatch table with per-query
  indirection maps (:func:`plan_step_predicates`), split into the
  *event-level* half (provably independent of per-run fold state —
  evaluated once per event, the dense predicate-matrix rows of
  ``engine/predmatrix.py``) and the *run-level* half (reads fold state —
  evaluated per run under the owning query's dtype decode, exactly as
  before).  ``engine/matcher.py: _build_step`` consumes the plan for
  every matcher, so the single-query engine and both Pallas kernel paths
  inherit the split.

Sharing is proven, never assumed: a predicate is shared or hoisted to
event level only when :func:`reads_states` can prove from its bytecode
that the ``states`` argument is never touched, and two predicates unify
only when :func:`predicate_key` renders both to the same structural key
(code, constants, closure cell values, globals identity).  Anything
unprovable keeps today's behavior bit-for-bit.
"""

from __future__ import annotations

import dataclasses
import dis
from typing import Any, Dict, Hashable, List, NamedTuple, Optional, Sequence, Tuple

import numpy as np

from kafkastreams_cep_tpu.compiler.tables import TransitionTables, lower
from kafkastreams_cep_tpu.compiler.tiering import (
    TIER_NFA,
    TieringPlan,
    apply_lazy_order,
    plan_tiering,
)
from kafkastreams_cep_tpu.pattern.predicate import Matcher
from kafkastreams_cep_tpu.utils.logging import get_logger

logger = get_logger("compiler.multitenant")

#: Positional index of the ``states`` parameter in the predicate calling
#: convention ``pr(key, value, timestamp, states)``.
_STATES_ARG = 3


# ---------------------------------------------------------------------------
# Predicate analysis: state independence + structural identity
# ---------------------------------------------------------------------------


def _code_reads_param(code, index: int) -> bool:
    """Whether ``code`` can observe its positional parameter ``index``.

    True when the parameter name is loaded anywhere (including the fused
    ``LOAD_FAST_LOAD_FAST``-style ops whose argval is a name tuple), or
    is captured by a nested function (``co_cellvars``); stores also count
    (shadowing analysis is not worth the risk).  Conservative: any doubt
    returns True.
    """
    if code.co_argcount <= index:
        # Fewer than 4 positionals: either *args absorbs the states
        # argument (opaque — assume read) or the call would not bind.
        return True
    name = code.co_varnames[index]
    if name in code.co_cellvars:
        return True
    try:
        instructions = list(dis.get_instructions(code))
    except Exception:  # pragma: no cover - dis failure on exotic code
        return True
    for ins in instructions:
        argval = ins.argval
        if argval == name:
            return True
        if isinstance(argval, tuple) and name in argval:
            return True
    return False


def reads_states(matcher: Matcher) -> bool:
    """Whether ``matcher`` can observe the per-run ``states`` argument.

    ``False`` is a *proof* (bytecode never references the parameter, no
    nested closure captures it) that the predicate's value depends only
    on ``(key, value, timestamp)`` — the property that licenses hoisting
    it to one-evaluation-per-event and sharing it across queries.
    Combinators (``and_``/``or_``/``not_``) are state-independent iff
    every operand is; anything without inspectable bytecode is
    conservatively stateful.
    """
    op = getattr(matcher, "op", None)
    parts = getattr(matcher, "parts", None)
    if op in ("and", "or", "not") and parts:
        return any(reads_states(p) for p in parts)
    fn = getattr(matcher, "fn", matcher)
    code = getattr(fn, "__code__", None)
    if code is None:
        return True
    if code.co_flags & 0x08:  # CO_VARKEYWORDS: states may land in **kw
        return True
    return _code_reads_param(code, _STATES_ARG)


class _Unkeyable(Exception):
    """A predicate component with no safe structural key."""


def _freeze(x) -> Hashable:
    """A hashable, type-tagged rendering of one closure/constant value.

    Scalars carry their type name so ``1``, ``1.0`` and ``True`` stay
    distinct (equal-hashing values with different trace dtypes must not
    unify).  Containers freeze element-wise; functions freeze
    structurally; anything else must be hashable or the predicate is
    unkeyable (kept private — correct, just unshared).
    """
    if x is None or isinstance(x, (str, bytes)):
        return x
    if isinstance(x, (bool, int, float, complex)):
        return (type(x).__name__, x)
    if isinstance(x, tuple):
        return ("tuple",) + tuple(_freeze(v) for v in x)
    if isinstance(x, frozenset):
        return ("frozenset", frozenset(_freeze(v) for v in x))
    if isinstance(x, Matcher):
        k = predicate_key(x)
        if k is None:
            raise _Unkeyable
        return ("matcher", k)
    if callable(x):
        return ("fn", _fn_key(x))
    try:
        hash(x)
    except TypeError:
        raise _Unkeyable from None
    return (type(x).__name__, x)


def _fn_key(fn) -> Hashable:
    """Structural identity of one plain function: bytecode, constants,
    referenced global names + the identity of the globals namespace they
    resolve in, defaults, and (recursively frozen) closure cell values."""
    code = getattr(fn, "__code__", None)
    if code is None:
        raise _Unkeyable
    consts = tuple(
        _freeze(c) if not isinstance(c, type(code)) else c.co_code
        for c in code.co_consts
    )
    closure = getattr(fn, "__closure__", None) or ()
    cells = tuple(_freeze(c.cell_contents) for c in closure)
    defaults = tuple(_freeze(d) for d in (fn.__defaults__ or ()))
    return (
        code.co_code,
        consts,
        code.co_names,
        code.co_varnames[: code.co_argcount],
        defaults,
        cells,
        id(getattr(fn, "__globals__", None)),
    )


def predicate_key(matcher: Matcher) -> Optional[Hashable]:
    """A structural identity for ``matcher``, or ``None`` when no safe key
    exists.  Two predicates with equal keys compute the same function of
    ``(key, value, timestamp, states)``: same bytecode, same constants,
    same closure values, same globals namespace.  Combinators key on
    their operator and operand keys (the combinator closures themselves
    are generated per-instance and would never unify)."""
    op = getattr(matcher, "op", None)
    parts = getattr(matcher, "parts", None)
    try:
        if op in ("and", "or", "not") and parts:
            child = tuple(predicate_key(p) for p in parts)
            if any(k is None for k in child):
                return None
            return (op, child)
        fn = getattr(matcher, "fn", None)
        if fn is None:
            return None
        return ("pred", _fn_key(fn))
    except _Unkeyable:
        return None


# ---------------------------------------------------------------------------
# Step-tier predicate plan: merged dispatch table + per-query remaps
# ---------------------------------------------------------------------------


class PredEntry(NamedTuple):
    """One merged-dispatch-table entry."""

    owner: int  # query whose dtype/state conventions decode for it
    pred: Matcher
    stateful: bool  # True: per-run evaluation under the owner's decode


class StepPredPlan(NamedTuple):
    """The merged predicate table for one (possibly stacked) step build.

    ``event_entries`` (ids ``[0, num_event)``) are provably independent
    of per-run fold state: the engine evaluates them ONCE per event (the
    dense predicate-matrix rows).  ``run_entries`` (ids ``[num_event,
    num_event + num_run)``) follow, evaluated per run.  ``remaps[q]``
    maps query ``q``'s local predicate ids into the merged table.
    """

    event_entries: Tuple[PredEntry, ...]
    run_entries: Tuple[PredEntry, ...]
    remaps: Tuple[np.ndarray, ...]
    stats: Dict[str, Any]

    @property
    def num_event(self) -> int:
        return len(self.event_entries)

    @property
    def num_run(self) -> int:
        return len(self.run_entries)


def plan_step_predicates(tlist: Sequence[TransitionTables]) -> StepPredPlan:
    """Dedup + split the union of ``tlist``'s predicate dispatch lists.

    State-independent predicates with a structural key unify across (and
    within) queries and move to the event-level half; everything else
    stays a private run-level entry under its owner's decode — exactly
    today's evaluation, minus the provably redundant copies.
    """
    event_entries: List[PredEntry] = []
    run_entries: List[PredEntry] = []
    interned: Dict[Hashable, int] = {}  # key -> event-entry index
    remaps: List[np.ndarray] = []
    total = 0
    for q, t in enumerate(tlist):
        remap = np.empty(len(t.predicates), dtype=np.int64)
        for pid, pred in enumerate(t.predicates):
            total += 1
            key = predicate_key(pred)
            if key is not None and not reads_states(pred):
                hit = interned.get(key)
                if hit is None:
                    hit = len(event_entries)
                    event_entries.append(PredEntry(q, pred, False))
                    interned[key] = hit
                remap[pid] = hit
            else:
                remap[pid] = -1 - len(run_entries)  # patched below
                run_entries.append(PredEntry(q, pred, True))
        remaps.append(remap)
    # Run-level ids follow the event block; patch the placeholders.
    g0 = len(event_entries)
    for remap in remaps:
        neg = remap < 0
        remap[neg] = g0 + (-1 - remap[neg])
    distinct = g0 + len(run_entries)
    stats = {
        "total_predicates": total,
        "distinct_predicates": distinct,
        "event_level": g0,
        "run_level": len(run_entries),
        "dedup_ratio": (total / distinct) if distinct else 1.0,
    }
    return StepPredPlan(
        tuple(event_entries), tuple(run_entries),
        tuple(remaps), stats,
    )


# ---------------------------------------------------------------------------
# Structural fingerprints (the process-level trace-cache key)
# ---------------------------------------------------------------------------


def tables_key(tables: TransitionTables) -> Optional[Hashable]:
    """A structural fingerprint of one compiled query, or ``None`` when
    any component resists safe hashing.  Two tables with equal keys
    compile to identical step programs, so jitted callables built from
    one serve the other — the process-level trace cache's key
    (``utils/tracecache.py``)."""
    try:
        arrays = tuple(
            np.asarray(a).tobytes()
            for a in (
                tables.types, tables.ident, tables.window_ms,
                tables.consume_op, tables.consume_pred,
                tables.consume_target, tables.ignore_pred,
                tables.proceed_pred, tables.proceed_target,
            )
        )
        preds = tuple(predicate_key(p) for p in tables.predicates)
        if any(k is None for k in preds):
            return None
        aggs = tuple(
            (a.stage, a.state, a.name, _fn_key(a.fn)) for a in tables.aggs
        )
        return (
            tuple(tables.names),
            arrays,
            preds,
            tuple(tables.state_names),
            tuple(_freeze(x) for x in tables.state_inits),
            tuple(tables.state_dtypes),
            aggs,
            int(tables.begin_pos),
            int(tables.final_pos),
            int(tables.max_hops),
            bool(tables.can_branch),
        )
    except _Unkeyable:
        return None


def bank_key(tlist: Sequence[TransitionTables]) -> Optional[Hashable]:
    """Fingerprint of a stacked bank: the tuple of member fingerprints."""
    keys = tuple(tables_key(t) for t in tlist)
    if any(k is None for k in keys):
        return None
    return keys


# ---------------------------------------------------------------------------
# The bank plan: prefix trie + shared column table
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class PrefixColumn:
    """One column of the bank-wide prefix screen: a predicate plus the
    query whose fold-state inits form its evaluation environment (only
    observable when the predicate is stateful, i.e. private)."""

    pred: Matcher
    owner: int
    shared: bool  # interned across queries (state-independent + keyed)


@dataclasses.dataclass(frozen=True)
class TenantQuota:
    """Declared per-query resource shares, enforced at runtime by
    ``parallel/tenantbank.py: TenantBankMatcher`` (the tenant-isolation
    contract — README "Multi-tenant execution").

    Every knob is optional (None = unlimited).  Enforcement is a
    gather-level mask over the shared screen's prefix fires: an
    over-quota tenant's completions are shed (counted per tenant in
    ``quota_shed``) while compliant tenants' screen math is bit-identical
    to an unquotaed bank.

    ``max_live_lanes``    — lanes this query may hold live NFA runs on;
                            measured from the stacked engine state each
                            batch (enforced with a one-batch lag — the
                            usage readback rides the existing gate
                            transfer, costing no extra device sync).
    ``handle_ring_share`` — fraction of the query's aggregate lazy-
                            extraction handle-ring capacity
                            (``K * EngineConfig.handle_ring``) it may
                            hold pending; same one-batch lag.
    ``match_rate_budget`` — token-bucket refill per batch on prefix
                            fires; an empty bucket masks NEW prefix
                            completions (runs already admitted finish).
                            ``match_rate_burst`` caps the bucket
                            (default ``2 * budget`` — a budget of 0
                            sheds from the very first batch).
    ``pred_eval_budget``  — per-batch bound on this query's screen work,
                            counted on offered slots (``K * T *
                            prefix_len`` — deterministic, known before
                            dispatch); an over-budget batch has the
                            query's fires masked for that batch.
    """

    max_live_lanes: Optional[int] = None
    handle_ring_share: Optional[float] = None
    match_rate_budget: Optional[float] = None
    match_rate_burst: Optional[float] = None
    pred_eval_budget: Optional[int] = None

    def __post_init__(self):
        if self.max_live_lanes is not None and self.max_live_lanes < 0:
            raise ValueError("max_live_lanes must be >= 0")
        if self.handle_ring_share is not None and not (
            0.0 < self.handle_ring_share <= 1.0
        ):
            raise ValueError("handle_ring_share must be in (0, 1]")
        if self.match_rate_budget is not None and self.match_rate_budget < 0:
            raise ValueError("match_rate_budget must be >= 0")
        if self.match_rate_burst is not None and self.match_rate_burst < 0:
            raise ValueError("match_rate_burst must be >= 0")
        if self.pred_eval_budget is not None and self.pred_eval_budget < 0:
            raise ValueError("pred_eval_budget must be >= 0")

    @property
    def burst(self) -> float:
        """Token-bucket cap for ``match_rate_budget`` (explicit
        ``match_rate_burst``, else ``2 * budget``)."""
        if self.match_rate_burst is not None:
            return float(self.match_rate_burst)
        return 2.0 * float(self.match_rate_budget or 0.0)


@dataclasses.dataclass(frozen=True)
class QueryPlan:
    """One query's routing inside the bank."""

    tables: TransitionTables  # post lazy-order
    plan: TieringPlan
    prefix_cols: Tuple[int, ...]  # column ids, one per prefix stage
    quota: Optional[TenantQuota] = None  # declared isolation contract


@dataclasses.dataclass
class BankPlan:
    """The compiled bank: per-query plans over one shared column table.

    ``trie`` maps every prefix-column path (tuple of column ids) to the
    number of queries whose prefix passes through it; ``groups`` maps
    each *complete* prefix signature to its member query ids — the
    prefix-overlap structure the shared screen exploits and the
    telemetry the docs/bench report."""

    queries: List[QueryPlan]
    columns: List[PrefixColumn]
    trie: Dict[Tuple[int, ...], int]
    groups: Dict[Tuple[int, ...], List[int]]
    stats: Dict[str, Any]


def plan_bank(
    patterns: Sequence,
    config=None,
    profile: Optional[Dict] = None,
    reorder: bool = True,
    quotas: Optional[Sequence[Optional[TenantQuota]]] = None,
) -> BankPlan:
    """Compile N query plans into one bank plan.

    Per query: lazy-chain conjunct ordering (when ``reorder``), then the
    tier split (``plan_tiering``).  Across queries: every distinct
    state-independent prefix predicate becomes ONE shared screen column;
    stateful or unkeyable prefix predicates get private columns under
    their owner's init environment (still evaluated in the same fused
    matrix pass, just not shared).  Residual-tier dedup is reported in
    ``stats`` (the engine applies it per stacked group at build time via
    :func:`plan_step_predicates`).
    """
    tlist = [
        p if isinstance(p, TransitionTables) else lower(p) for p in patterns
    ]
    if quotas is None:
        qlist: List[Optional[TenantQuota]] = [None] * len(tlist)
    else:
        qlist = list(quotas)
        if len(qlist) != len(tlist):
            raise ValueError(
                f"quotas must have one entry per pattern: got {len(qlist)} "
                f"for {len(tlist)} patterns"
            )
    queries: List[QueryPlan] = []
    columns: List[PrefixColumn] = []
    interned: Dict[Hashable, int] = {}
    trie: Dict[Tuple[int, ...], int] = {}
    groups: Dict[Tuple[int, ...], List[int]] = {}
    shared_hits = 0
    total_prefix = 0
    for q, t in enumerate(tlist):
        if reorder:
            t, _ = apply_lazy_order(t, profile)
        plan = plan_tiering(t, config, profile)
        cols: List[int] = []
        for j in range(plan.prefix_len):
            pred = t.predicates[int(t.consume_pred[j])]
            total_prefix += 1
            key = predicate_key(pred)
            if key is not None and not reads_states(pred):
                cid = interned.get(key)
                if cid is None:
                    cid = len(columns)
                    columns.append(PrefixColumn(pred, q, True))
                    interned[key] = cid
                else:
                    shared_hits += 1
                cols.append(cid)
            else:
                cols.append(len(columns))
                columns.append(PrefixColumn(pred, q, False))
        sig = tuple(cols)
        for depth in range(1, len(sig) + 1):
            node = sig[:depth]
            trie[node] = trie.get(node, 0) + 1
        if plan.tier != TIER_NFA:
            groups.setdefault(sig, []).append(q)
        queries.append(QueryPlan(t, plan, sig, quota=qlist[q]))
    pred_plan = plan_step_predicates([qp.tables for qp in queries])
    tiers = [qp.plan.tier for qp in queries]
    stats = {
        "num_queries": len(queries),
        "tiers": {tier: tiers.count(tier) for tier in set(tiers)},
        "prefix_columns_total": total_prefix,
        "prefix_columns_distinct": len(columns),
        "prefix_shared_hit_rate": (
            shared_hits / total_prefix if total_prefix else 0.0
        ),
        "prefix_groups": len(groups),
        "trie_nodes": len(trie),
        "quotas_declared": sum(1 for q in qlist if q is not None),
        **{f"pred_{k}": v for k, v in pred_plan.stats.items()},
    }
    logger.info(
        "bank plan: %d queries, %d/%d distinct prefix columns, "
        "%d prefix groups, predicate dedup %.2fx",
        stats["num_queries"], stats["prefix_columns_distinct"],
        stats["prefix_columns_total"] or 0, stats["prefix_groups"],
        pred_plan.stats["dedup_ratio"],
    )
    return BankPlan(queries, columns, trie, groups, stats)
