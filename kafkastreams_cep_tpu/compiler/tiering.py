"""Compiler tiering: split each query at its maximal strict prefix.

The stencil fast path (``engine/stencil.py``) runs branch-free
strict-contiguity sequences two orders of magnitude faster than the
general NFA+slab engine — but only whole patterns qualified.  This pass
generalizes the split: per the DFA-vs-NFA automata-processing results
(arxiv 2210.10077) strict-contiguity fragments determinize cheaply, so
every query is split into

* its **maximal strict prefix** — the longest run of leading chain
  positions whose consuming edge is BEGIN with no IGNORE, no PROCEED, and
  no folds (every such position is exactly one stencil column), and
* the **residual suffix** — everything from the first Kleene/skip-till/
  fold stage on, which keeps the full NFA semantics.

The hybrid matcher (``parallel/tiered.py``) runs the prefix as a
data-parallel stencil over the whole ``[K, T]`` batch and *promotes* a
run into the NFA tier only at events where the prefix completes — events
the begin predicate rejects, and events consumed inside the prefix, never
cost a run-queue slot, a slab put, or a walk hop.

Window no-prune proof (asserted here, not assumed)
--------------------------------------------------
The stencil tier cannot prune by ``within()`` windows.  That is *correct*
under the faithful engine because every non-seed run in the reference is
an epsilon wrapper that never carries ``windowMs`` (``Stage.java:41-46``),
so ``isOutOfWindow`` can never fire — windows never prune.  Under
``EngineConfig.enforce_windows=True`` that proof fails (the engine opts
into functional pruning, including *inside* the prefix via inherited
windows), so :func:`plan_tiering` refuses to route a windowed pattern to
the stencil tier and degrades to the whole-NFA plan instead of silently
relying on the invariant.

Lazy-chain predicate ordering (arxiv 1612.05110)
------------------------------------------------
The same pass emits an evaluation order for each stage's conjunct chain:
``and_`` combinators record their operands (``pattern/predicate.py``), so
a stage predicate flattens into a commuting conjunct list which
:func:`apply_lazy_order` reorders so cheap, selective conjuncts gate
expensive ones.  Rank = estimated selectivity × estimated cost,
ascending: selectivity comes from the measured ``stage_attribution``
profile (PR 6's ``per_stage`` snapshot — ``metrics_snapshot()["per_stage"]``
or the profiler CLI's ``selectivity`` output) via per-conjunct
``selectivity_hint`` overrides, and cost from a static model
(``cost_hint`` if declared, else bytecode length of the closure).
Reordering a conjunction is semantics-preserving by commutativity; the
property test in ``tests/test_tiering.py`` pins that accept/ignore/reject
tallies and matches are bit-identical either way.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from kafkastreams_cep_tpu.compiler.tables import (
    OP_BEGIN,
    TransitionTables,
    lower,
)
from kafkastreams_cep_tpu.pattern.predicate import Matcher, _normalize
from kafkastreams_cep_tpu.utils.logging import get_logger

logger = get_logger("compiler.tiering")

# Tier labels — also the per-query ``tier=...`` tag in the profiler CLI.
TIER_STENCIL = "stencil"  # whole pattern on the stencil tier, no NFA
TIER_HYBRID = "hybrid"  # strict prefix on the stencil, suffix on the NFA
TIER_NFA = "nfa"  # no usable prefix: whole-NFA execution


@dataclasses.dataclass(frozen=True)
class TieringPlan:
    """One query's tier routing decision, host-side and immutable."""

    tier: str  # TIER_STENCIL | TIER_HYBRID | TIER_NFA
    prefix_len: int  # stages routed to the stencil tier (0 for TIER_NFA)
    reason: str  # why the plan is what it is (telemetry / debugging)

    def describe(self) -> Dict[str, Any]:
        return {
            "tier": self.tier,
            "prefix_len": self.prefix_len,
            "reason": self.reason,
        }


def strict_prefix_len(tables: TransitionTables) -> int:
    """The maximal strict-contiguity prefix of ``tables``: leading chain
    positions consuming via BEGIN with no IGNORE edge, no PROCEED edge,
    and no fold registered at the position.  Each such position is one
    stencil column (``TransitionTables.is_strict_seq`` is the
    whole-pattern special case: prefix == num_stages - 1)."""
    agg_stages = {slot.stage for slot in tables.aggs}
    n = tables.num_stages - 1  # exclude $final
    p = 0
    for j in range(n):
        if (
            tables.consume_op[j] != OP_BEGIN
            or tables.ignore_pred[j] >= 0
            or tables.proceed_pred[j] >= 0
            or j in agg_stages
        ):
            break
        p += 1
    return p


def check_no_prune(tables: TransitionTables, config) -> Optional[str]:
    """The window no-prune proof for routing a prefix onto the stencil
    tier.  Returns ``None`` when the proof holds, else the reason it
    fails.  Faithful mode (``enforce_windows=False``): epsilon wrappers
    never carry ``windowMs``, so ``within()`` never prunes — holds for
    any pattern, windowed or not.  ``enforce_windows=True`` opts into
    functional pruning the stencil does not implement (a partial prefix
    run can be pruned mid-prefix via inherited windows), so any set
    window fails the proof."""
    if not getattr(config, "enforce_windows", False):
        return None
    if np.any(tables.window_ms != -1):
        w = int(tables.window_ms[tables.window_ms != -1].max())
        return (
            f"enforce_windows=True with a {w} ms within() window: "
            "functional pruning can fire inside the prefix, which the "
            "stencil tier cannot reproduce"
        )
    return None


def plan_tiering(
    pattern_or_tables, config=None, profile: Optional[Dict] = None
) -> TieringPlan:
    """Decide the tier split for one compiled query under ``config``.

    Constraints beyond :func:`strict_prefix_len`:

    * the no-prune proof must hold (:func:`check_no_prune`) — else the
      whole query stays NFA;
    * ``prefix_len <= dewey_depth``: inside the prefix a run appends one
      stage digit per crossing, and promotion must inject a version the
      untiered run would carry without ever having overflowed;
    * pure-stencil routing needs ``prefix_len <= max_walk`` (the
      synthesized match rows stand in for a W-bounded extraction walk)
      and is off under ``lazy_extraction`` (pure-stencil matches emit
      eagerly; capping to a hybrid keeps the handle-ring contract) — both
      degrade to the hybrid split, never to silent truncation.

    ``profile`` is accepted for parity with :func:`apply_lazy_order` (a
    measured ``per_stage`` snapshot); the split itself is structural.
    """
    tables = (
        pattern_or_tables
        if isinstance(pattern_or_tables, TransitionTables)
        else lower(pattern_or_tables)
    )
    del profile  # the split is structural; ordering consumes the profile
    n = tables.num_stages - 1
    p = strict_prefix_len(tables)
    if p == 0:
        return TieringPlan(TIER_NFA, 0, "no strict-contiguity prefix")
    no_prune = check_no_prune(tables, config) if config is not None else None
    if no_prune is not None:
        return TieringPlan(TIER_NFA, 0, f"no-prune proof failed: {no_prune}")
    reason = f"maximal strict prefix {p}/{n}"
    if config is not None and p > config.dewey_depth:
        p = int(config.dewey_depth)
        reason += f", capped to dewey_depth={p}"
        if p == 0:
            return TieringPlan(TIER_NFA, 0, reason)
    if p == n:
        if config is not None and getattr(config, "lazy_extraction", False):
            p = n - 1
            reason += ", capped below n (lazy_extraction drains via the NFA)"
        elif config is not None and p > config.max_walk:
            p = n - 1
            reason += f", capped below n (max_walk={config.max_walk} < n)"
        else:
            return TieringPlan(TIER_STENCIL, p, reason + " (whole pattern)")
    if p == 0:
        return TieringPlan(TIER_NFA, 0, reason)
    return TieringPlan(TIER_HYBRID, p, reason)


# ---------------------------------------------------------------------------
# Lazy-chain predicate ordering
# ---------------------------------------------------------------------------


def conjuncts(matcher: Matcher) -> List[Matcher]:
    """Flatten an ``and_`` combinator tree into its commuting conjunct
    list (left-to-right declaration order).  Anything that is not an
    ``and_`` node — including ``or_``/``not_`` subtrees, which do not
    commute with the conjunction boundary — is one opaque conjunct."""
    if getattr(matcher, "op", None) == "and":
        out: List[Matcher] = []
        for part in matcher.parts:
            out.extend(conjuncts(part))
        return out
    return [matcher]


def predicate_cost(matcher: Matcher) -> float:
    """Static relative cost of evaluating ``matcher`` once.

    ``cost_hint`` wins when declared; combinators sum their parts; plain
    matchers fall back to the bytecode length of their closure — a crude
    but monotone proxy for trace-time op count that needs no execution."""
    if getattr(matcher, "cost_hint", None) is not None:
        return float(matcher.cost_hint)
    parts = getattr(matcher, "parts", ())
    if parts:
        return sum(predicate_cost(p) for p in parts)
    code = getattr(matcher.fn, "__code__", None)
    if code is None:  # builtins / partials: flat default
        return 16.0
    return float(len(code.co_code))


def conjunct_key(m: Matcher) -> str:
    """A stable, order-invariant identifier for one conjunct.

    Labels alone collide (every bare lambda is ``<lambda>``), and a
    positional suffix would change under reordering — breaking both the
    measured-selectivity lookup and the reorder-invariance of the
    attribution report.  The label is therefore disambiguated by the
    closure's code location, which is identical however the conjunction
    is ordered and across rebuilds of the same pattern object."""
    code = getattr(m.fn, "__code__", None)
    if code is None:
        return m.label
    import os as _os

    return (
        f"{m.label}@{_os.path.basename(code.co_filename)}"
        f":{code.co_firstlineno}"
    )


def _conjunct_selectivity(
    m: Matcher,
    stage_sel: Optional[float],
    conjunct_sel: Optional[Dict[str, float]] = None,
) -> float:
    """Estimated accept fraction of one conjunct.  Preference order:
    the *measured* per-conjunct selectivity (the ``[P]`` tally rows a
    ``stage_attribution`` run accumulates — ranking then rests on
    measurement alone, no annotations needed), else the declared
    ``selectivity_hint``, else the stage's measured selectivity (every
    conjunct of the stage then ties and cost alone decides), else 0.5."""
    if conjunct_sel:
        s = conjunct_sel.get(conjunct_key(m))
        if s is not None:
            return float(s)
    if getattr(m, "selectivity_hint", None) is not None:
        return float(m.selectivity_hint)
    if stage_sel is not None:
        return float(stage_sel)
    return 0.5


def order_conjuncts(
    matcher: Matcher,
    stage_sel: Optional[float] = None,
    conjunct_sel: Optional[Dict[str, float]] = None,
) -> Tuple[List[Matcher], bool]:
    """The lazy-chain order for one stage predicate: conjuncts ranked by
    estimated ``selectivity × cost`` ascending (cheap selective gates
    first — the expected-work ordering of arxiv 1612.05110's lazy
    chains), stable within ties.  Returns ``(ordered, changed)``."""
    parts = conjuncts(matcher)
    if len(parts) < 2:
        return parts, False
    ranked = sorted(
        range(len(parts)),
        key=lambda i: (
            _conjunct_selectivity(parts[i], stage_sel, conjunct_sel)
            * predicate_cost(parts[i]),
            i,
        ),
    )
    ordered = [parts[i] for i in ranked]
    return ordered, ranked != list(range(len(parts)))


def _ordered_and(parts: List[Matcher]) -> Matcher:
    """Rebuild a conjunction evaluating ``parts`` in list order: host
    values short-circuit left-to-right, traced values combine with ``&``
    in the same order.  Semantically identical to any other order of the
    same commuting conjuncts."""

    def fn(key, value, timestamp, states):
        acc: Any = True
        for p in parts:
            v = _normalize(p(key, value, timestamp, states))
            if isinstance(acc, bool) and isinstance(v, bool):
                if not v:
                    return False  # host short-circuit, in chain order
            else:
                acc = v if acc is True else acc & v
        return acc

    m = Matcher(fn, label="and(" + ",".join(p.label for p in parts) + ")")
    m.op = "and"
    m.parts = tuple(parts)
    return m


# ---------------------------------------------------------------------------
# Measured per-conjunct selectivity (the tally stage_attribution accumulates)
# ---------------------------------------------------------------------------


def conjunct_tally_plan(
    tables: TransitionTables,
) -> List[Tuple[str, str, Matcher]]:
    """The flat conjunct slot layout for ``tables``: one
    ``(stage_name, key, matcher)`` triple per distinct conjunct of each
    consuming-edge predicate, declaration-ordered.  Duplicate keys within
    a stage (the same closure declared twice in one conjunction) collapse
    to a single slot, so the layout — and therefore the tally report —
    is invariant under lazy-chain reordering of any stage's chain."""
    tables = (
        tables if isinstance(tables, TransitionTables) else lower(tables)
    )
    slots: List[Tuple[str, str, Matcher]] = []
    n = tables.num_stages - 1
    for j in range(n):
        pid = int(tables.consume_pred[j])
        if pid < 0:
            continue
        name = tables.names[j]
        seen = set()
        for m in conjuncts(tables.predicates[pid]):
            key = conjunct_key(m)
            if key in seen:
                continue
            seen.add(key)
            slots.append((name, key, m))
    return slots


def build_conjunct_tally(tables: TransitionTables):
    """A jit-able accumulator for *measured* per-conjunct selectivity.

    Returns ``(slots, tally)`` where ``slots`` is
    :func:`conjunct_tally_plan`'s layout and ``tally(counts, ev)`` adds
    one ``[K, T]`` :class:`EventBatch`'s contribution to a ``[2, P]``
    int32 counts array — row 0 the valid events each conjunct was
    offered (identical across slots), row 1 each conjunct's accepts.
    Every conjunct is evaluated *unconditionally* over the whole batch
    against the declared fold-state inits (the stencil tier's evaluation
    context, ``engine/stencil.py``), so the measured selectivity is the
    order-independent marginal accept fraction — the quantity the
    lazy-chain ranking needs, not the short-circuit-conditioned rate the
    sequential engine step observes.  ``tally`` is a pure device
    function; callers accumulate asynchronously and ``device_get`` only
    at telemetry reads."""
    import jax.numpy as jnp

    from kafkastreams_cep_tpu.engine.matcher import ArrayStates

    tables = (
        tables if isinstance(tables, TransitionTables) else lower(tables)
    )
    slots = conjunct_tally_plan(tables)
    matchers = [m for _, _, m in slots]
    states = ArrayStates(
        {
            name: (
                jnp.asarray(init, jnp.float32)
                if dt == "float32"
                else jnp.asarray(init, jnp.int32)
            )
            for name, init, dt in zip(
                tables.state_names, tables.state_inits, tables.state_dtypes
            )
        }
    )

    def tally(counts, ev):
        if not matchers:
            return counts
        valid = jnp.asarray(ev.valid, bool)
        evals = jnp.sum(valid.astype(jnp.int32))
        accepts = jnp.stack(
            [
                jnp.sum(
                    (
                        jnp.broadcast_to(
                            jnp.asarray(
                                m(ev.key, ev.value, ev.ts, states), bool
                            ),
                            valid.shape,
                        )
                        & valid
                    ).astype(jnp.int32)
                )
                for m in matchers
            ]
        )
        delta = jnp.stack(
            [jnp.full((len(matchers),), evals, jnp.int32), accepts]
        )
        return counts + delta

    return slots, tally


def apply_lazy_order(
    tables: TransitionTables, profile: Optional[Dict] = None
) -> Tuple[TransitionTables, Dict[str, Any]]:
    """Reorder every stage's commuting conjunct chain by measured
    selectivity and static cost.

    ``profile`` is a ``per_stage`` snapshot (``{stage_name:
    {"selectivity": s, ...}}``) from ``stage_attribution`` telemetry; when
    absent the static cost model alone ranks the conjuncts.  Only
    *consuming*-edge predicates are rebuilt (IGNORE/PROCEED predicates
    are compiler-derived combinations whose structure the engine step
    depends on for nothing, but which share no reorderable conjunct
    surface worth the churn).  Returns ``(new_tables, report)`` where
    ``report[stage] = {"order": [...labels], "reordered": bool,
    "selectivity": float|None}``; ``new_tables`` shares everything but
    its predicate dispatch list with the input."""
    preds = list(tables.predicates)
    report: Dict[str, Any] = {}
    changed_any = False
    n = tables.num_stages - 1
    for j in range(n):
        pid = int(tables.consume_pred[j])
        if pid < 0:
            continue
        name = tables.names[j]
        stage_sel = None
        conjunct_sel: Optional[Dict[str, float]] = None
        if profile and name in profile:
            row = profile[name]
            if isinstance(row, dict):
                stage_sel = row.get("selectivity")
                cj = row.get("conjuncts")
                if isinstance(cj, dict):
                    # Measured per-conjunct rows (build_conjunct_tally via
                    # stage_attribution): {key: {"selectivity": s, ...}}
                    # or a bare {key: s} mapping.
                    conjunct_sel = {
                        k: float(
                            v.get("selectivity")
                            if isinstance(v, dict)
                            else v
                        )
                        for k, v in cj.items()
                        if (
                            v.get("selectivity")
                            if isinstance(v, dict)
                            else v
                        )
                        is not None
                    }
        ordered, changed = order_conjuncts(preds[pid], stage_sel, conjunct_sel)
        report[name] = {
            "order": [m.label for m in ordered],
            "costs": [round(predicate_cost(m), 1) for m in ordered],
            "reordered": changed,
            "selectivity": stage_sel,
            "measured_conjuncts": sorted(conjunct_sel) if conjunct_sel else [],
        }
        if changed:
            preds[pid] = _ordered_and(ordered)
            changed_any = True
    if changed_any:
        logger.info(
            "lazy-chain ordering reordered stages: %s",
            [s for s, r in report.items() if r["reordered"]],
        )
    new_tables = dataclasses.replace(tables, predicates=preds)
    return new_tables, report
