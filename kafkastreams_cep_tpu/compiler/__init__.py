from kafkastreams_cep_tpu.compiler.stages import (
    Stage,
    StageType,
    Edge,
    EdgeOperation,
    compile_pattern,
)

__all__ = ["Stage", "StageType", "Edge", "EdgeOperation", "compile_pattern"]
