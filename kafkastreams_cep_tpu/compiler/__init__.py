from kafkastreams_cep_tpu.compiler.stages import (
    Stage,
    StageType,
    Edge,
    EdgeOperation,
    compile_pattern,
)
from kafkastreams_cep_tpu.compiler.tiering import (
    TIER_HYBRID,
    TIER_NFA,
    TIER_STENCIL,
    TieringPlan,
    apply_lazy_order,
    plan_tiering,
    strict_prefix_len,
)

__all__ = [
    "Stage",
    "StageType",
    "Edge",
    "EdgeOperation",
    "TIER_HYBRID",
    "TIER_NFA",
    "TIER_STENCIL",
    "TieringPlan",
    "apply_lazy_order",
    "compile_pattern",
    "plan_tiering",
    "strict_prefix_len",
]
