"""Pattern -> NFA stage-graph compiler.

Reproduces the SASE+ compilation scheme of ``pattern/StatesFactory.java``
exactly:

* a synthetic ``$final`` FINAL stage is appended (``StatesFactory.java:46-47``),
* one NORMAL stage per pattern stage, walking the ancestor chain backward,
  with the BEGIN stage last (``StatesFactory.java:52-60``),
* the consuming edge is BEGIN for cardinality ONE, TAKE otherwise
  (``StatesFactory.java:80-81``),
* IGNORE edge: ``true`` for skip-till-any-match, ``not(take)`` for
  skip-till-next-match, absent for strict contiguity
  (``StatesFactory.java:87-96``),
* TAKE stages get a PROCEED edge guarded by
  ``successor_predicate or not(take)`` (strict) /
  ``successor_predicate or (not(take) and not(ignore))`` (skip)
  (``StatesFactory.java:98-107``),
* ONE_OR_MORE prepends a mandatory same-named state with a single BEGIN edge
  (``StatesFactory.java:70-72,110-116``),
* window length is pushed onto stages, inherited from the successor pattern
  when unset (``StatesFactory.java:75-76,121-127``).

Stage equality is ``(name, type)`` only (``Stage.java:116-127``): epsilon
wrappers compare equal to their base stage, which the PROCEED version rule in
the engine depends on (``NFA.java:185``).
"""

from __future__ import annotations

import enum
from typing import List, Optional

from kafkastreams_cep_tpu.pattern.aggregator import StateAggregator
from kafkastreams_cep_tpu.pattern.pattern import Cardinality, Pattern, SelectStrategy
from kafkastreams_cep_tpu.pattern.predicate import Matcher, and_, not_, or_, true_


class StageType(enum.Enum):
    BEGIN = "begin"
    NORMAL = "normal"
    FINAL = "final"


class EdgeOperation(enum.IntEnum):
    """Edge semantics as documented at ``nfa/EdgeOperation.java:20-41``.

    BEGIN   forward edge: consume the event and buffer it.
    TAKE    looping edge: consume the event and buffer it.
    PROCEED forward edge without consuming.
    IGNORE  looping edge without consuming (selection-strategy dependent).
    """

    BEGIN = 0
    TAKE = 1
    PROCEED = 2
    IGNORE = 3


class Edge:
    __slots__ = ("op", "matcher", "target")

    def __init__(self, op: EdgeOperation, matcher: Matcher, target: Optional["Stage"]):
        if matcher is None:
            raise ValueError("edge predicate cannot be None")
        self.op = op
        self.matcher = matcher
        self.target = target

    def matches(self, key, value, timestamp, states) -> bool:
        return self.matcher(key, value, timestamp, states)

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        tgt = self.target.name if self.target is not None else None
        return f"Edge({self.op.name}->{tgt}:{self.matcher.label})"


class Stage:
    """A compiled NFA node; equality is (name, type) only (Stage.java:116-127)."""

    def __init__(self, name: str, type: StageType):
        self.name = name
        self.type = type
        self.window_ms: int = -1
        self.aggregates: List[StateAggregator] = []
        self.edges: List[Edge] = []

    @staticmethod
    def epsilon(current: "Stage", target: "Stage") -> "Stage":
        """An always-true PROCEED wrapper carrying ``current``'s identity
        (Stage.java:42-46)."""
        stage = Stage(current.name, current.type)
        stage.add_edge(Edge(EdgeOperation.PROCEED, true_(), target))
        return stage

    def add_edge(self, edge: Edge) -> "Stage":
        self.edges.append(edge)
        return self

    def is_begin(self) -> bool:
        return self.type is StageType.BEGIN

    def is_final(self) -> bool:
        return self.type is StageType.FINAL

    def is_epsilon(self) -> bool:
        return len(self.edges) == 1 and self.edges[0].op is EdgeOperation.PROCEED

    def target_by_op(self, op: EdgeOperation) -> Optional["Stage"]:
        target = None
        for edge in self.edges:
            if edge.op is op:
                target = edge.target
        return target

    def state_names(self) -> List[str]:
        return [agg.name for agg in self.aggregates]

    def __eq__(self, other: object) -> bool:
        if not isinstance(other, Stage):
            return NotImplemented
        return self.name == other.name and self.type is other.type

    def __hash__(self) -> int:
        return hash((self.name, self.type))

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"Stage({self.name}:{self.type.name}, edges={self.edges})"


FINAL_STAGE_NAME = "$final"


def compile_pattern(pattern: Pattern) -> List[Stage]:
    """Compile a pattern chain to stages ordered ``[$final, ..., begin]``
    like ``StatesFactory.make`` (``StatesFactory.java:41-63``)."""
    if pattern is None:
        raise ValueError("cannot compile a null pattern")

    sequence: List[Stage] = []
    successor_stage = Stage(FINAL_STAGE_NAME, StageType.FINAL)
    sequence.append(successor_stage)

    successor_pattern: Optional[Pattern] = None
    current = pattern
    while current.ancestor is not None:
        successor_stage = _build_stage(
            StageType.NORMAL, current, successor_stage, successor_pattern
        )
        sequence.append(successor_stage)
        successor_pattern = current
        current = current.ancestor

    sequence.append(_build_stage(StageType.BEGIN, current, successor_stage, successor_pattern))
    return sequence


def _build_stage(
    type: StageType,
    current: Pattern,
    successor_stage: Stage,
    successor_pattern: Optional[Pattern],
) -> Stage:
    # StatesFactory.buildState (StatesFactory.java:65-119).
    cardinality = current.cardinality
    has_mandatory = cardinality is Cardinality.ONE_OR_MORE
    if type is StageType.BEGIN and cardinality in (
        Cardinality.OPTIONAL,
        Cardinality.ZERO_OR_MORE,
    ):
        # The reference crashes at runtime on this shape (a first-stage
        # TAKE+PROCEED branch reaches newEpsilonState(null, ...) at
        # NFA.java:236); reject it at compile time instead.
        raise ValueError(
            f"stage {current.name!r}: the first pattern stage cannot be "
            "optional/zero_or_more (use one_or_more or cardinality ONE)"
        )
    current_type = StageType.NORMAL if has_mandatory else type

    stage = Stage(current.name, current_type)
    window_ms = _window_ms(current, successor_pattern)
    stage.window_ms = window_ms
    stage.aggregates = current.aggregates

    predicate = current.predicate
    if predicate is None:
        raise ValueError(f"pattern stage {current.name!r} has no predicate")

    op = EdgeOperation.BEGIN if cardinality is Cardinality.ONE else EdgeOperation.TAKE
    stage.add_edge(Edge(op, predicate, successor_stage))

    strategy = current.strategy
    ignore: Optional[Matcher] = None
    if strategy is SelectStrategy.SKIP_TIL_ANY_MATCH:
        ignore = true_()
        stage.add_edge(Edge(EdgeOperation.IGNORE, ignore, None))
    if strategy is SelectStrategy.SKIP_TIL_NEXT_MATCH:
        ignore = not_(predicate)
        stage.add_edge(Edge(EdgeOperation.IGNORE, ignore, None))

    if op is EdgeOperation.TAKE:
        # proceed = successor_begin or (not take [and not ignore])
        # (StatesFactory.java:98-107).  The reference dereferences
        # successorPattern unconditionally here, so a Kleene/optional *last*
        # stage is unsupported (latent NPE at StatesFactory.java:102); we make
        # the constraint explicit.
        if successor_pattern is None:
            raise ValueError(
                f"stage {current.name!r}: a pattern's last stage must have "
                "cardinality ONE (the reference compiler has the same constraint)"
            )
        if strategy is SelectStrategy.STRICT_CONTIGUITY:
            proceed = or_(successor_pattern.predicate, not_(predicate))
        else:
            proceed = or_(successor_pattern.predicate, and_(not_(predicate), not_(ignore)))
        stage.add_edge(Edge(EdgeOperation.PROCEED, proceed, successor_stage))

    if has_mandatory:
        # ONE_OR_MORE: a required same-named entry state precedes the Kleene
        # loop (StatesFactory.java:110-116).
        successor_stage = stage
        stage = Stage(current.name, type)
        stage.add_edge(Edge(EdgeOperation.BEGIN, current.predicate, successor_stage))
        stage.window_ms = window_ms
        stage.aggregates = current.aggregates

    return stage


def _window_ms(current: Pattern, successor: Optional[Pattern]) -> int:
    # Window inheritance from the successor pattern (StatesFactory.java:121-127).
    if current.window_time_ms is not None:
        return current.window_time_ms
    if successor is not None and successor.window_time_ms is not None:
        return successor.window_time_ms
    return -1
