"""Pattern-compiler structural goldens, hand-derived from
``pattern/StatesFactory.java:41-127`` semantics."""

from kafkastreams_cep_tpu import Query, compile_pattern
from helpers import value_is
from kafkastreams_cep_tpu.compiler.stages import EdgeOperation, Stage, StageType


def strict_three_stage():
    return (
        Query()
        .select("first").where(value_is("A"))
        .then()
        .select("second").where(value_is("B"))
        .then()
        .select("latest").where(value_is("C"))
        .build()
    )


def test_strict_three_stage_structure():
    stages = compile_pattern(strict_three_stage())
    # Java order: [$final, latest, second, first(begin)] (StatesFactory.java:44-62).
    assert [s.name for s in stages] == ["$final", "latest", "second", "first"]
    assert [s.type for s in stages] == [
        StageType.FINAL,
        StageType.NORMAL,
        StageType.NORMAL,
        StageType.BEGIN,
    ]
    # Cardinality ONE => single BEGIN edge per stage, no IGNORE/PROCEED.
    for stage in stages[1:]:
        assert [e.op for e in stage.edges] == [EdgeOperation.BEGIN]
    # Final stage has no edges.
    assert stages[0].edges == []
    # Edges chain to the successor.
    assert stages[3].edges[0].target is stages[2]
    assert stages[2].edges[0].target is stages[1]
    assert stages[1].edges[0].target is stages[0]


def test_one_or_more_adds_mandatory_state():
    # ONE_OR_MORE prepends a same-named BEGIN-edge state; buildState returns
    # the mandatory state, so the Kleene loop stage is reachable only through
    # its edge target (StatesFactory.java:110-118).
    query = (
        Query()
        .select("a").where(value_is("A"))
        .then()
        .select("b").one_or_more().where(value_is("B"))
        .then()
        .select("c").where(value_is("C"))
        .build()
    )
    stages = compile_pattern(query)
    assert [s.name for s in stages] == ["$final", "c", "b", "a"]
    mandatory = stages[2]
    assert mandatory.type is StageType.NORMAL
    assert [e.op for e in mandatory.edges] == [EdgeOperation.BEGIN]
    loop = mandatory.edges[0].target
    assert loop.name == "b"
    assert loop.type is StageType.NORMAL
    assert [e.op for e in loop.edges] == [EdgeOperation.TAKE, EdgeOperation.PROCEED]
    assert loop.edges[0].target.name == "c"
    assert loop.edges[1].target.name == "c"


def test_strategies_synthesize_ignore_edges():
    q_any = (
        Query()
        .select("x").where(value_is("A"))
        .then()
        .select("y").zero_or_more().skip_till_any_match().where(value_is("B"))
        .then()
        .select("z").where(value_is("C"))
        .build()
    )
    stages = compile_pattern(q_any)
    y = stages[2]
    assert [e.op for e in y.edges] == [
        EdgeOperation.TAKE,
        EdgeOperation.IGNORE,
        EdgeOperation.PROCEED,
    ]

    q_next = (
        Query()
        .select("x").where(value_is("A"))
        .then()
        .select("y").skip_till_next_match().where(value_is("B"))
        .build()
    )
    y2 = compile_pattern(q_next)[1]
    # Cardinality ONE: BEGIN consuming edge + IGNORE, no PROCEED.
    assert [e.op for e in y2.edges] == [EdgeOperation.BEGIN, EdgeOperation.IGNORE]


def test_optional_and_zero_or_more_compile_identically():
    # Quirk preserved from StatesFactory.java:70-81 (see SURVEY.md section 7).
    def build(card):
        sb = Query().select("x").where(value_is("A")).then().select("y")
        sb = getattr(sb, card)()
        return sb.where(value_is("B")).then().select("z").where(value_is("C")).build()

    s_opt = compile_pattern(build("optional"))
    s_zom = compile_pattern(build("zero_or_more"))
    assert [s.name for s in s_opt] == [s.name for s in s_zom]
    for a, b in zip(s_opt, s_zom):
        assert [e.op for e in a.edges] == [e.op for e in b.edges]


def test_window_is_pushed_and_inherited():
    # Window inheritance from successor (StatesFactory.java:121-127).
    query = (
        Query()
        .select("x").where(value_is("A"))
        .then()
        .select("y").where(value_is("B")).within(1, "h")
        .build()
    )
    stages = compile_pattern(query)
    y, x = stages[1], stages[2]
    assert y.window_ms == 3_600_000
    # x has no window of its own but inherits from its successor pattern y.
    assert x.window_ms == 3_600_000


def test_stage_equality_is_name_and_type():
    # Stage.java:116-127; epsilon wrappers compare equal to their base stage.
    base = Stage("s", StageType.NORMAL)
    target = Stage("t", StageType.NORMAL)
    eps = Stage.epsilon(base, target)
    assert eps == base
    assert hash(eps) == hash(base)
    assert eps.is_epsilon()


def test_first_stage_cannot_be_optional_or_zero_or_more():
    import pytest

    for card in ("optional", "zero_or_more"):
        sb = Query().select("x")
        sb = getattr(sb, card)()
        query = sb.where(value_is("A")).then().select("y").where(value_is("B")).build()
        with pytest.raises(ValueError):
            compile_pattern(query)
