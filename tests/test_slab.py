"""Device slab buffer vs the host shared versioned buffer.

Part 1 ports the reference buffer goldens
(``nfa/buffer/SharedVersionedBufferTest.java:28-68``) onto raw slab ops.
Part 2 mirrors every buffer call made by real oracle runs (the five golden
scenarios) into a slab and checks stores and extraction outputs stay
identical after every operation.
"""

from typing import Dict, Tuple

import jax
import numpy as np

from kafkastreams_cep_tpu import DeweyVersion, Event, OracleNFA, Query
from helpers import value_is
from kafkastreams_cep_tpu.compiler.stages import compile_pattern
from kafkastreams_cep_tpu.nfa.buffer import SharedVersionedBuffer
from kafkastreams_cep_tpu.ops import dewey_ops, slab

D = 8
E = 32
MP = 4
WALK = 16

FIRST, SECOND, LATEST = 0, 1, 2


def ver(s: str):
    return dewey_ops.make(DeweyVersion(s).components, D)


def test_extract_patterns_with_one_run():
    s = slab.make(E, MP, D)
    s = slab.put_first(s, FIRST, 0, *ver("1"))
    s = slab.put(s, SECOND, 1, FIRST, 0, *ver("1.0"))
    s = slab.put(s, LATEST, 2, SECOND, 1, *ver("1.0.0"))
    s, st, off, n = slab.peek(s, LATEST, 2, *ver("1.0.0"), max_walk=WALK, remove=False)
    assert int(n) == 3
    assert st[:3].tolist() == [LATEST, SECOND, FIRST]
    assert off[:3].tolist() == [2, 1, 0]
    assert int(s.missing) == 0


def test_extract_patterns_with_branching_run():
    s = slab.make(E, MP, D)
    s = slab.put_first(s, FIRST, 0, *ver("1"))
    s = slab.put(s, SECOND, 1, FIRST, 0, *ver("1.0"))
    s = slab.put(s, LATEST, 2, SECOND, 1, *ver("1.0.0"))
    s = slab.put(s, SECOND, 2, SECOND, 1, *ver("1.1"))
    s = slab.put(s, SECOND, 3, SECOND, 2, *ver("1.1"))
    s = slab.put(s, LATEST, 4, SECOND, 3, *ver("1.1.0"))

    s, st, off, n = slab.peek(s, LATEST, 2, *ver("1.0.0"), max_walk=WALK, remove=False)
    assert int(n) == 3
    assert st[:3].tolist() == [LATEST, SECOND, FIRST]

    s, st, off, n = slab.peek(s, LATEST, 4, *ver("1.1.0"), max_walk=WALK, remove=False)
    assert int(n) == 5
    assert st[:5].tolist() == [LATEST, SECOND, SECOND, SECOND, FIRST]
    assert off[:5].tolist() == [4, 3, 2, 1, 0]


def test_put_with_missing_predecessor_counts():
    # The reference throws (KVSharedVersionedBuffer.java:86-89); under jit the
    # slab counts and drops.
    s = slab.make(E, MP, D)
    s = slab.put(s, SECOND, 1, FIRST, 0, *ver("1.0"))
    assert int(s.missing) == 1
    assert int(slab.live_entries(s)) == 0


def test_remove_garbage_collects_unshared_path():
    s = slab.make(E, MP, D)
    s = slab.put_first(s, FIRST, 0, *ver("1"))
    s = slab.put(s, SECOND, 1, FIRST, 0, *ver("1.0"))
    s = slab.put(s, LATEST, 2, SECOND, 1, *ver("1.0.0"))
    s, _, _, n = slab.peek(s, LATEST, 2, *ver("1.0.0"), max_walk=WALK, remove=True)
    assert int(n) == 3
    assert int(slab.live_entries(s)) == 0


def test_branch_protects_shared_prefix_from_removal():
    s = slab.make(E, MP, D)
    s = slab.put_first(s, FIRST, 0, *ver("1"))
    s = slab.put(s, SECOND, 1, FIRST, 0, *ver("1.0"))
    s = slab.branch(s, SECOND, 1, *ver("1.0"), max_walk=WALK)
    s = slab.put(s, LATEST, 2, SECOND, 1, *ver("1.0.0"))
    s, _, _, _ = slab.peek(s, LATEST, 2, *ver("1.0.0"), max_walk=WALK, remove=True)
    s, st, off, n = slab.peek(s, SECOND, 1, *ver("1.1"), max_walk=WALK, remove=False)
    assert int(n) == 2
    assert st[:2].tolist() == [SECOND, FIRST]


def test_walk_bound_truncation_counts():
    # A 4-hop chain walked with max_walk=2 must flag the truncation.
    s = slab.make(E, MP, D)
    s = slab.put_first(s, FIRST, 0, *ver("1"))
    s = slab.put(s, SECOND, 1, FIRST, 0, *ver("1.0"))
    s = slab.put(s, SECOND, 2, SECOND, 1, *ver("1.0"))
    s = slab.put(s, LATEST, 3, SECOND, 2, *ver("1.0.0"))
    s2 = slab.branch(s, LATEST, 3, *ver("1.0.0"), max_walk=2)
    assert int(s2.trunc) == 1
    s3, _, _, n = slab.peek(s, LATEST, 3, *ver("1.0.0"), max_walk=2, remove=True)
    assert int(n) == 2 and int(s3.trunc) == 1
    # A full-length walk is not flagged.
    s4, _, _, n = slab.peek(s, LATEST, 3, *ver("1.0.0"), max_walk=WALK, remove=False)
    assert int(n) == 4 and int(s4.trunc) == 0


def test_slab_full_counts_drop():
    s = slab.make(2, MP, D)
    s = slab.put_first(s, FIRST, 0, *ver("1"))
    s = slab.put(s, SECOND, 1, FIRST, 0, *ver("1.0"))
    s = slab.put(s, LATEST, 2, SECOND, 1, *ver("1.0.0"))  # no slot left
    assert int(s.full_drops) == 1


# ---------------------------------------------------------------------------
# Differential: mirror every oracle buffer call into a slab.
# ---------------------------------------------------------------------------


class MirroredBuffer(SharedVersionedBuffer):
    """Host buffer that replays every call onto a slab and cross-checks."""

    def __init__(self):
        super().__init__()
        self.slab = slab.make(E, MP, D)
        self.stage_ids: Dict[Tuple[str, str], int] = {}
        self.offsets: Dict[Tuple[str, int, int], int] = {}

    def _sid(self, stage) -> int:
        key = (stage.name, stage.type.value)
        return self.stage_ids.setdefault(key, len(self.stage_ids))

    def _off(self, event: Event) -> int:
        return self.offsets.setdefault(event.position, len(self.offsets))

    def _ver(self, version: DeweyVersion):
        return dewey_ops.make(version.components, D)

    def put_first(self, stage, event, version):
        super().put_first(stage, event, version)
        self.slab = slab.put_first(self.slab, self._sid(stage), self._off(event), *self._ver(version))
        self.check()

    def put(self, curr_stage, curr_event, prev_stage, prev_event, version):
        super().put(curr_stage, curr_event, prev_stage, prev_event, version)
        self.slab = slab.put(
            self.slab,
            self._sid(curr_stage),
            self._off(curr_event),
            self._sid(prev_stage),
            self._off(prev_event),
            *self._ver(version),
        )
        self.check()

    def branch(self, stage, event, version):
        super().branch(stage, event, version)
        self.slab = slab.branch(
            self.slab, self._sid(stage), self._off(event), *self._ver(version), max_walk=WALK
        )
        self.check()

    def _peek(self, stage, event, version, remove):
        sequence = super()._peek(stage, event, version, remove)
        self.slab, st, off, n = slab.peek(
            self.slab,
            self._sid(stage),
            self._off(event),
            *self._ver(version),
            max_walk=WALK,
            remove=remove,
        )
        # Same hop count and same per-stage event groups in walk order.
        st, off, n = jax.device_get((st, off, n))
        assert int(n) == sequence.size(), "walk length diverged"
        by_name = {name: [] for name in sequence.stages()}
        names = {v: k for k, v in self.stage_ids.items()}
        offs = {v: k for k, v in self.offsets.items()}
        for i in range(int(n)):
            name = names[int(st[i])][0]
            by_name.setdefault(name, []).append(offs[int(off[i])])
        host = {
            name: [e.position for e in events]
            for name, events in sequence.as_map().items()
        }
        assert by_name == host, "extraction diverged"
        self.check()
        return sequence

    def check(self):
        """Slab store must equal the host dict store exactly."""
        s = jax.device_get(self.slab)  # one transfer; numpy thereafter
        live = {
            (int(s.stage[i]), int(s.off[i])): i for i in np.flatnonzero(s.stage >= 0)
        }
        host_keys = {
            (self.stage_ids[(k[0], k[1])], self.offsets[(k[2], k[3], k[4])])
            for k in self.store
        }
        assert set(live) == host_keys, "live entries diverged"
        for key, entry in self.store.items():
            sid = self.stage_ids[(key[0], key[1])]
            off = self.offsets[(key[2], key[3], key[4])]
            i = live[(sid, off)]
            assert int(s.refs[i]) == entry.refs, "refcount diverged"
            assert int(s.npreds[i]) == len(entry.preds), "npreds diverged"
            for m, pointer in enumerate(entry.preds):
                assert (
                    dewey_ops.to_tuple(s.pver[i, m], s.pvlen[i, m])
                    == pointer.version.components
                ), "pointer version diverged"
                if pointer.key is None:
                    assert int(s.pstage[i, m]) == -1
                else:
                    pk = pointer.key
                    assert int(s.pstage[i, m]) == self.stage_ids[(pk[0], pk[1])]
                    assert int(s.poff[i, m]) == self.offsets[(pk[2], pk[3], pk[4])]
        assert int(s.missing) == 0
        assert int(s.full_drops) == 0
        assert int(s.pred_drops) == 0
        assert int(s.trunc) == 0


def _run_mirrored(query, values):
    nfa = OracleNFA(compile_pattern(query), buffer=MirroredBuffer())
    out = []
    for i, v in enumerate(values):
        out.extend(nfa.match(None, v, 1000 + i, offset=i))
    return out


def test_mirrored_strict_contiguity():
    query = (
        Query()
        .select("first").where(value_is("A"))
        .then()
        .select("second").where(value_is("B"))
        .then()
        .select("latest").where(value_is("C"))
        .build()
    )
    matches = _run_mirrored(query, ["A", "B", "C", "A", "X", "A", "B", "C"])
    assert len(matches) == 2


def test_mirrored_one_or_more():
    query = (
        Query()
        .select("a").where(value_is("A"))
        .then()
        .select("b").one_or_more().where(value_is("B"))
        .then()
        .select("c").where(value_is("C"))
        .build()
    )
    matches = _run_mirrored(query, ["A", "B", "B", "C", "A", "B", "C"])
    assert len(matches) == 2


def test_mirrored_skip_till_any_branches():
    query = (
        Query()
        .select("first").where(value_is("A"))
        .then()
        .select("second").where(value_is("B"))
        .then()
        .select("three").skip_till_any_match().where(value_is("C"))
        .then()
        .select("latest").skip_till_any_match().where(value_is("D"))
        .build()
    )
    matches = _run_mirrored(query, ["A", "B", "C", "C", "D"])
    assert len(matches) == 2


def test_mirrored_stock_query():
    class Stock:
        def __init__(self, price, volume):
            self.price = price
            self.volume = volume

    query = (
        Query()
        .select()
        .where(lambda k, v, ts, store: v.volume > 1000)
        .fold("avg", lambda k, v, curr: v.price)
        .then()
        .select()
        .zero_or_more()
        .skip_till_next_match()
        .where(lambda k, v, ts, store: v.price > store.get("avg"))
        .fold("avg", lambda k, v, curr: (curr + v.price) // 2)
        .fold("volume", lambda k, v, curr: v.volume)
        .then()
        .select()
        .skip_till_next_match()
        .where(lambda k, v, ts, store: v.volume < 0.8 * store.get_or_else("volume", 0))
        .within(1, "h")
        .build()
    )
    stocks = [
        Stock(100, 1010),
        Stock(120, 990),
        Stock(120, 1005),
        Stock(121, 999),
        Stock(120, 999),
        Stock(125, 750),
        Stock(120, 950),
        Stock(120, 700),
    ]
    matches = _run_mirrored(query, stocks)
    assert len(matches) == 4
