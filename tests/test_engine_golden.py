"""Array-engine conformance goldens: the five reference scenarios
(``NFATest.java``) run differentially against the host oracle — every event's
match emission must be identical in count, order, and content."""

import engine_scenarios as sc
from kafkastreams_cep_tpu.engine import EngineConfig, MatcherSession, TPUMatcher

A, B, C, D, X = sc.A, sc.B, sc.C, sc.D, sc.X


def test_strict_contiguity_differential():
    matches = sc.run_differential(sc.strict3(), [A, B, C])
    assert len(matches) == 1
    assert sc.canon(matches[0]) == {"first": [0], "second": [1], "latest": [2]}


def test_strict_contiguity_rejects_gaps():
    assert sc.run_differential(sc.strict3(), [A, X, B, C, A, B, C]) != []


def test_kleene_one_or_more_differential():
    matches = sc.run_differential(sc.kleene_one_or_more(), [A, B, C, C, D])
    assert len(matches) == 1
    assert sc.canon(matches[0]) == {
        "firstStage": [0],
        "secondStage": [1],
        "thirdStage": [2, 3],
        "latestState": [4],
    }


def test_skip_till_next_match_differential():
    matches = sc.run_differential(sc.skip_till_next(), [A, B, C, C, D])
    assert len(matches) == 1
    assert sc.canon(matches[0]) == {"first": [0], "second": [2], "latest": [4]}


def test_skip_till_any_match_branches_differential():
    matches = sc.run_differential(sc.skip_till_any(), [A, B, C, C, D])
    assert len(matches) == 2
    assert sc.canon(matches[0]) == {
        "first": [0], "second": [1], "three": [2], "latest": [4]
    }
    assert sc.canon(matches[1]) == {
        "first": [0], "second": [1], "three": [3], "latest": [4]
    }


def test_stock_query_differential():
    matches = sc.run_differential(
        sc.stock_query(),
        sc.STOCKS,
        sc.default_config(max_runs=24, slab_entries=64, slab_preds=8,
                          dewey_depth=12, max_walk=12),
    )
    assert len(matches) == 4


def test_overflow_counters_surface():
    # An undersized run queue must *count* dropped runs, never silently
    # truncate (no reference analog — the Java queue is unbounded).
    session = MatcherSession(
        TPUMatcher(
            sc.skip_till_any(),
            EngineConfig(max_runs=2, slab_entries=16, slab_preds=4,
                         dewey_depth=6, max_walk=6),
        )
    )
    for i, v in enumerate([A, B, C, C, C, D]):
        session.match(None, v, 1000 + i)
    assert session.counters()["run_drops"] > 0
