"""Supervisor.resume crash-window semantics (ISSUE 2 satellite).

Three windows a process crash can land in, each with a distinct contract:

* between ``save_checkpoint`` and the journal rotation — the live
  journal still holds frames the snapshot already contains; resume must
  skip frames at/below the snapshot seq (no double replay);
* a seq gap in the journal (a lost frame with later frames present) —
  replay must stop at the last contiguous frame, never build a state
  that skipped history;
* a torn tail (crash mid-append) — replay repairs the file, and a
  SECOND crash/resume cycle on the repaired journal stays consistent;
* a corrupt snapshot (bit rot after a good save — ISSUE 5 satellite) —
  the sha256 integrity check fails loudly, and resume falls back to the
  previous-good snapshot (or fresh) with the journal CHAIN (``.prev``
  generation + live frames) replaying the full gap instead of crashing.
"""

import os
import pickle

import numpy as np

import engine_scenarios as sc
from kafkastreams_cep_tpu.native.journal import Journal
from kafkastreams_cep_tpu.runtime import Record, Supervisor
from kafkastreams_cep_tpu.runtime.migrate import canonical_state
from kafkastreams_cep_tpu.utils import failpoints as fp


def batches_for(values, t0=1000, off0=0):
    return [
        [Record("k", v, t0 + i, offset=off0 + i)]
        for i, v in enumerate(values)
    ]


def reference_state(values):
    """Device state after a clean, same-batching run."""
    sup = Supervisor(sc.strict3(), 1, sc.default_config(), gc_interval=0)
    out = []
    for b in batches_for(values):
        out += sup.process(b)
    return sup.processor.state, out


def assert_same_state(a, b):
    import jax

    ca, cb = canonical_state(a), canonical_state(b)
    for i, (x, y) in enumerate(
        zip(jax.tree_util.tree_leaves(ca), jax.tree_util.tree_leaves(cb))
    ):
        np.testing.assert_array_equal(
            np.asarray(x), np.asarray(y), err_msg=f"leaf {i}"
        )


def test_crash_between_snapshot_and_rotation_skips_contained_frames(
    tmp_path, monkeypatch
):
    """Checkpoint written, journal NOT yet rotated, crash: the journal
    frames at/below the snapshot seq must be skipped on resume — the
    no-double-replay half of the seq protocol."""
    values = [sc.A, sc.B, sc.C, sc.A, sc.B]
    ck, jr = str(tmp_path / "w1.ckpt"), str(tmp_path / "w1.jrnl")
    sup = Supervisor(
        sc.strict3(), 1, sc.default_config(),
        checkpoint_path=ck, journal_path=jr, checkpoint_every=100,
        gc_interval=0,
    )
    emitted = []
    for b in batches_for(values[:3]):
        emitted += sup.process(b)
    assert len(emitted) == 1  # A,B,C completed
    # Snapshot with the rotation suppressed = crash in the window.
    monkeypatch.setattr(sup, "_rotate_journal", lambda: None)
    sup.checkpoint()
    assert len(list(Journal(jr).replay())) == 3  # frames survived the crash
    for b in batches_for(values[3:], t0=1003, off0=3):
        emitted += sup.process(b)
    del sup  # crash

    res = Supervisor.resume(
        sc.strict3(), 1, sc.default_config(),
        checkpoint_path=ck, journal_path=jr, gc_interval=0,
    )
    # Were the pre-snapshot frames double-replayed, the dedup high-water
    # mark would differ and the C re-seen post-resume would re-match.
    ref_state, ref_out = reference_state(values)
    assert_same_state(res.processor.state, ref_state)
    more = res.process([Record("k", sc.C, 9000, offset=5)])
    assert len(more) == 1  # A,B at offsets 3,4 + this C: exactly one match
    assert len(emitted) == 1


def test_seq_gap_stops_replay_at_last_contiguous_frame(tmp_path):
    """A journal with frames 1,2,4 (frame 3 lost) must replay only 1,2:
    replaying past the gap would build a state that never saw batch 3."""
    values = [sc.A, sc.B, sc.C, sc.A]
    ck, jr = str(tmp_path / "w2.ckpt"), str(tmp_path / "w2.jrnl")
    sup = Supervisor(
        sc.strict3(), 1, sc.default_config(),
        checkpoint_path=ck, journal_path=jr, checkpoint_every=100,
        gc_interval=0,
    )
    for b in batches_for(values):
        sup.process(b)
    del sup
    # Forge the gap: rewrite the journal without frame seq==3.
    j = Journal(jr)
    frames = [pickle.loads(p) for p in j.replay()]
    assert [s for s, _ in frames] == [1, 2, 3, 4]
    j.truncate()
    for seq, batch in frames:
        if seq != 3:
            j.append(pickle.dumps((seq, batch)))

    res = Supervisor.resume(
        sc.strict3(), 1, sc.default_config(),
        checkpoint_path=ck, journal_path=jr, gc_interval=0,
    )
    assert res._seq == 2  # stopped at the last contiguous frame
    ref_state, _ = reference_state(values[:2])
    assert_same_state(res.processor.state, ref_state)


def test_torn_tail_repair_then_second_resume(tmp_path):
    """Crash mid-append (torn tail): resume replays the intact prefix and
    repairs the file; the in-flight batch was never acked, so the caller
    re-submits it; a second crash/resume over the repaired journal lands
    on the same state as a clean run."""
    values = [sc.A, sc.B, sc.C]
    ck, jr = str(tmp_path / "w3.ckpt"), str(tmp_path / "w3.jrnl")
    sup = Supervisor(
        sc.strict3(), 1, sc.default_config(),
        checkpoint_path=ck, journal_path=jr, checkpoint_every=100,
        gc_interval=0,
    )
    emitted = []
    for b in batches_for(values[:2]):
        emitted += sup.process(b)
    fp.tear_journal_tail(jr)  # batch 3 died mid-write, process with it
    del sup

    res = Supervisor.resume(
        sc.strict3(), 1, sc.default_config(),
        checkpoint_path=ck, journal_path=jr, gc_interval=0,
    )
    assert res._seq == 2  # only the intact frames
    # Caller re-submits the unacknowledged batch; the match completes
    # exactly once (it was never emitted pre-crash).
    emitted += res.process([Record("k", sc.C, 1002, offset=2)])
    assert len(emitted) == 1
    del res  # second crash, now over the repaired + appended journal

    res2 = Supervisor.resume(
        sc.strict3(), 1, sc.default_config(),
        checkpoint_path=ck, journal_path=jr, gc_interval=0,
    )
    assert res2._seq == 3
    ref_state, ref_out = reference_state(values)
    assert_same_state(res2.processor.state, ref_state)
    assert len(ref_out) == len(emitted) == 1


def _corrupt_file(path):
    """Flip bytes deep inside the snapshot's array payload (bit rot)."""
    with open(path, "r+b") as f:
        f.seek(-64, 2)
        f.write(b"\xff" * 16)


def test_corrupt_snapshot_detected_by_digest(tmp_path):
    from kafkastreams_cep_tpu.runtime import (
        CEPProcessor, CheckpointCorrupt, load_checkpoint, save_checkpoint,
    )
    import pytest

    proc = CEPProcessor(sc.strict3(), 1, sc.default_config(), gc_interval=0)
    proc.process([Record("k", sc.A, 1000, offset=0)])
    path = str(tmp_path / "d.ckpt")
    save_checkpoint(proc, path)
    assert load_checkpoint(path)["header"]["arrays_sha256"]
    _corrupt_file(path)
    with pytest.raises(CheckpointCorrupt):
        load_checkpoint(path)


def test_corrupt_first_snapshot_falls_back_to_fresh_plus_journal_chain(
    tmp_path,
):
    """Only one checkpoint ever taken, and it rots: resume must rebuild
    from scratch off the journal chain (the rotation retired the
    pre-snapshot frames into ``.prev``, so the chain covers seq 1..n)."""
    values = [sc.A, sc.B, sc.C, sc.A, sc.B]
    ck, jr = str(tmp_path / "c1.ckpt"), str(tmp_path / "c1.jrnl")
    sup = Supervisor(
        sc.strict3(), 1, sc.default_config(),
        checkpoint_path=ck, journal_path=jr, checkpoint_every=3,
        gc_interval=0,
    )
    emitted = []
    for b in batches_for(values):
        emitted += sup.process(b)
    assert sup.checkpoints == 1
    del sup
    _corrupt_file(ck)

    res = Supervisor.resume(
        sc.strict3(), 1, sc.default_config(),
        checkpoint_path=ck, journal_path=jr, gc_interval=0,
    )
    assert res._seq == 5  # full history: .prev frames 1-3 + live 4-5
    ref_state, ref_out = reference_state(values)
    assert_same_state(res.processor.state, ref_state)
    assert len(ref_out) == len(emitted) == 1


def test_corrupt_snapshot_falls_back_to_previous_good(tmp_path):
    """Two checkpoints, the newer one rots: resume restores the
    previous-good ``.prev`` snapshot and the journal chain replays the
    gap between the two, then the live tail."""
    values = [sc.A, sc.B, sc.C, sc.A, sc.B, sc.C, sc.A]
    ck, jr = str(tmp_path / "c2.ckpt"), str(tmp_path / "c2.jrnl")
    sup = Supervisor(
        sc.strict3(), 1, sc.default_config(),
        checkpoint_path=ck, journal_path=jr, checkpoint_every=3,
        gc_interval=0,
    )
    emitted = []
    for b in batches_for(values):
        emitted += sup.process(b)
    assert sup.checkpoints == 2
    del sup
    _corrupt_file(ck)

    res = Supervisor.resume(
        sc.strict3(), 1, sc.default_config(),
        checkpoint_path=ck, journal_path=jr, gc_interval=0,
    )
    assert res._seq == 7
    ref_state, ref_out = reference_state(values)
    assert_same_state(res.processor.state, ref_state)
    # Post-resume traffic matches exactly once.
    more = res.process([Record("k", sc.B, 9000, offset=7)])
    more += res.process([Record("k", sc.C, 9001, offset=8)])
    assert len(more) == 1
    assert len(emitted) == len(ref_out) == 2


def test_resume_on_shrunk_mesh(tmp_path):
    """Checkpoint portability across device counts (ISSUE 13 satellite):
    a snapshot written by a 2-device meshed supervisor resumes on a
    1-device mesh AND on no mesh at all — ``restore_processor`` routes
    the lane re-placement through ``migrate.repartition_state`` — with
    journal replay and post-resume matching identical to an
    uninterrupted single-device run."""
    import jax
    import pytest

    from kafkastreams_cep_tpu.parallel import key_mesh

    if jax.device_count() < 2:
        pytest.skip("needs 2 devices")
    keys = ("k0", "k1")
    vals = [sc.A, sc.B, sc.C, sc.A, sc.B]

    def two_lane_batches(off0=0):
        return [
            [Record(k, v, 1000 + 10 * i + j, offset=off0 + i)
             for j, k in enumerate(keys)]
            for i, v in enumerate(vals)
        ]

    ck, jr = str(tmp_path / "mesh.ckpt"), str(tmp_path / "mesh.jrnl")
    sup = Supervisor(
        sc.strict3(), len(keys), sc.default_config(),
        checkpoint_path=ck, journal_path=jr, checkpoint_every=3,
        gc_interval=0, mesh=key_mesh(jax.devices()[:2]),
    )
    emitted = []
    for b in two_lane_batches():
        emitted += sup.process(b)
    assert sup.checkpoints >= 1  # the snapshot records mesh_size=2
    del sup  # crash

    # Each resume target gets the pristine crash aftermath (a resume
    # mutates the journal/checkpoint it continues from).
    import shutil

    frozen = {}
    for p in (ck, jr, ck + ".prev", jr + ".prev"):
        if os.path.exists(p):
            frozen[p] = p + ".frozen"
            shutil.copy(p, p + ".frozen")

    tail = [[Record(k, sc.C, 9000 + j, offset=5) for j, k in enumerate(keys)]]
    for target_mesh in (key_mesh(jax.devices()[:1]), None):
        for p in (ck, jr, ck + ".prev", jr + ".prev"):
            if p in frozen:
                shutil.copy(frozen[p], p)
            elif os.path.exists(p):
                os.remove(p)
        kw = {} if target_mesh is None else {"mesh": target_mesh}
        res = Supervisor.resume(
            sc.strict3(), len(keys), sc.default_config(),
            checkpoint_path=ck, journal_path=jr, gc_interval=0, **kw,
        )
        got = []
        for b in tail:
            got += res.process(b)
        got += res.processor.flush()

        ref = Supervisor(
            sc.strict3(), len(keys), sc.default_config(),
            gc_interval=0,
        )
        ref_out = []
        for b in two_lane_batches() + tail:
            ref_out += ref.process(b)
        ref_out += ref.processor.flush()
        assert_same_state(res.processor.state, ref.processor.state)
        # Pre-crash emissions + post-resume emissions == clean run's.
        assert len(emitted) + len(got) == len(ref_out)
        assert not any(res.processor.counters().values())
