"""Tier-1 guard: the metrics surface cannot drift undocumented.

Modeled on ``test_failpoint_guard.py``: a metric that ships without
operator-facing docs is dead weight on the exact path that matters (the
3am dashboard).  Two invariants, both driven from one *fat* supervisor
snapshot (guard + tiering + attribution + latency ledger + a recovery):

1. Every top-level ``metrics_snapshot()`` key appears in the README
   metrics reference table (between the ``metrics-reference`` markers).
2. Every family ``render_prometheus`` emits carries ``# HELP`` and
   ``# TYPE`` metadata before its first sample.
"""

import dataclasses
import pathlib
import re

import engine_scenarios as sc
from kafkastreams_cep_tpu.runtime import Record, Supervisor
from kafkastreams_cep_tpu.runtime.ingest import IngestPolicy
from kafkastreams_cep_tpu.utils import failpoints as fp
from kafkastreams_cep_tpu.utils.latency import LatencyLedger, SLOTracker
from kafkastreams_cep_tpu.utils.telemetry import render_prometheus

README = pathlib.Path(__file__).parent.parent / "README.md"


def _fat_snapshot(tmp_path):
    """One snapshot exercising every producer: ingest guard, tiered plan,
    stage attribution, latency ledger with SLO, and a recovery."""
    cfg = dataclasses.replace(
        sc.default_config(), tiering=True, stage_attribution=True
    )
    sup = Supervisor(
        sc.strict3(), 1, cfg,
        checkpoint_path=str(tmp_path / "g.ckpt"), checkpoint_every=2,
        gc_interval=1, ingest=IngestPolicy(grace_ms=0),
        latency=LatencyLedger(slo=SLOTracker(threshold_s=1.0)),
        overload_policy=True,
    )
    vals = [sc.A, sc.B, sc.C, sc.X, sc.A, sc.B, sc.C, sc.X]
    with fp.FAILPOINTS.session({"device.result": [2]}):
        for i, v in enumerate(vals):
            sup.process([Record("k", v, 1000 + i, offset=i)])
    assert sup.recoveries == 1
    return sup.metrics_snapshot()


def _reference_table() -> str:
    text = README.read_text()
    m = re.search(
        r"<!-- metrics-reference-start -->(.*?)"
        r"<!-- metrics-reference-end -->",
        text, re.S,
    )
    assert m, "README.md lost its metrics-reference markers"
    return m.group(1)


def test_every_snapshot_key_is_documented_in_readme(tmp_path):
    table = _reference_table()
    snap = _fat_snapshot(tmp_path)
    undocumented = [
        key for key in snap if f"`{key}`" not in table
    ]
    assert not undocumented, (
        f"metrics_snapshot() keys {sorted(undocumented)} are not in the "
        "README metrics reference table — document each new metric "
        "(README.md, between the metrics-reference markers) before "
        "landing it"
    )


def test_every_prometheus_family_has_help_and_type(tmp_path):
    txt = render_prometheus(_fat_snapshot(tmp_path))
    helped = set()
    typed = set()
    missing = []
    for line in txt.splitlines():
        if line.startswith("# HELP "):
            helped.add(line.split()[2])
        elif line.startswith("# TYPE "):
            typed.add(line.split()[2])
        elif line:
            name = re.match(r"([a-zA-Z_:][a-zA-Z0-9_:]*)", line).group(1)
            family = re.sub(r"_(bucket|sum|count)$", "", name)
            if not (
                {name, family} & helped and {name, family} & typed
            ):
                missing.append(line)
    assert not missing, (
        "Prometheus samples emitted without # HELP/# TYPE metadata "
        f"(first few): {missing[:5]}"
    )
    # The latency families are present in the fat snapshot's rendering.
    for family in ("cep_latency_seconds", "cep_slo_burn",
                   "cep_phase_seconds"):
        assert family in helped and family in typed
