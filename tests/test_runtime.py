"""Host runtime tests: micro-batching processor, multi-key interleaving,
README-exact demo output, and checkpoint/restore (VERDICT items 6-7)."""

import os
import sys

import numpy as np
import pytest

import engine_scenarios as sc
from kafkastreams_cep_tpu import OracleNFA
from kafkastreams_cep_tpu.engine import EngineConfig
from kafkastreams_cep_tpu.runtime import (
    CEPProcessor,
    Record,
    restore_processor,
    save_checkpoint,
)

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "examples"))
import stock_demo


def stock_cfg():
    return EngineConfig(
        max_runs=32, slab_entries=64, slab_preds=8, dewey_depth=16, max_walk=16
    )


def test_stock_demo_readme_parity():
    """The demo prints the reference README's 4 JSON lines, byte for byte
    (/root/reference/README.md:93-96)."""
    assert stock_demo.run() == stock_demo.EXPECTED


def test_processor_micro_batch_split():
    """Splitting the trace across process() calls changes nothing."""
    proc = CEPProcessor(stock_demo.stock_pattern(), 1, stock_cfg())
    records = [
        Record("stocks", {"price": e["price"], "volume": e["volume"]}, 1000 + i)
        for i, e in enumerate(stock_demo.STOCK_EVENTS)
    ]
    out = []
    for i in range(0, len(records), 3):  # batches of 3, 3, 2
        out += proc.process(records[i : i + 3])
    name_of = {i: e["name"] for i, e in enumerate(stock_demo.STOCK_EVENTS)}
    lines = [stock_demo.format_match(seq, name_of) for _, seq in out]
    assert lines == stock_demo.EXPECTED


def test_processor_multi_key_interleaved():
    """Interleaved keys each replay the stock trace in their own lane and
    each produce the 4 reference matches; emission keeps arrival order."""
    keys = ["alpha", "beta", "gamma"]
    proc = CEPProcessor(stock_demo.stock_pattern(), 4, stock_cfg())
    records = []
    for i, e in enumerate(stock_demo.STOCK_EVENTS):
        for key in keys:
            records.append(
                Record(key, {"price": e["price"], "volume": e["volume"]}, 1000 + i)
            )
    out = proc.process(records)
    assert len(out) == 4 * len(keys)
    name_of = {i: e["name"] for i, e in enumerate(stock_demo.STOCK_EVENTS)}
    per_key = {k: [] for k in keys}
    for key, seq in out:
        per_key[key].append(stock_demo.format_match(seq, name_of))
    for key in keys:
        assert per_key[key] == stock_demo.EXPECTED, key
    # Arrival order: both e6-completed matches (all keys) precede e8's.
    kinds = ["e6" if '"2":["e6"]' in stock_demo.format_match(s, name_of) else "e8"
             for _, s in out]
    assert kinds == ["e6"] * 6 + ["e8"] * 6


def test_processor_key_overflow_raises():
    proc = CEPProcessor(sc.strict3(), 2, sc.default_config())
    proc.process([Record("a", 0, 1), Record("b", 0, 2)])
    with pytest.raises(ValueError, match="num_lanes"):
        proc.process([Record("c", 0, 3)])


def test_rejected_batch_does_not_leak_lane_slots():
    """A batch rejected during validation consumes no lane slots: the same
    new keys can be ingested later in a valid batch."""
    proc = CEPProcessor(sc.strict3(), 2, sc.default_config())
    with pytest.raises(ValueError, match="num_lanes"):
        proc.process([Record("a", 0, 1), Record("b", 0, 2), Record("c", 0, 3)])
    assert proc._lane_of == {}
    proc.process([Record("a", 0, 1), Record("b", 0, 2)])  # both fit now
    assert set(proc._lane_of) == {"a", "b"}


def test_processor_key_overflow_is_atomic():
    """A rejected batch ingests nothing: the valid record in it is not
    half-processed, and resubmitting it alone still works."""
    proc = CEPProcessor(sc.strict3(), 1, sc.default_config())
    with pytest.raises(ValueError, match="num_lanes"):
        proc.process([Record("a", sc.A, 1), Record("b", sc.B, 2)])
    assert proc._next_offset[0] == 0 and not proc._events[0]
    out = proc.process(
        [Record("a", sc.A, 1), Record("a", sc.B, 2), Record("a", sc.C, 3)]
    )
    assert len(out) == 1  # the full SEQ(A,B,C) still matches


def test_processor_epoch_millis_timestamps():
    """Realistic epoch-ms timestamps work: they are rebased to the first
    record's timestamp before hitting int32 device time."""
    proc = CEPProcessor(stock_demo.stock_pattern(), 1, stock_cfg())
    base = 1_700_000_000_000
    records = [
        Record("s", {"price": e["price"], "volume": e["volume"]}, base + i * 1000)
        for i, e in enumerate(stock_demo.STOCK_EVENTS)
    ]
    out = proc.process(records)
    name_of = {i: e["name"] for i, e in enumerate(stock_demo.STOCK_EVENTS)}
    assert [stock_demo.format_match(s, name_of) for _, s in out] == stock_demo.EXPECTED
    # Emitted events keep their original absolute timestamps.
    assert out[0][1].as_map()["2"][0].timestamp == base + 5000


def test_processor_timestamp_out_of_epoch_range_raises():
    proc = CEPProcessor(sc.strict3(), 1, sc.default_config(), epoch=0)
    with pytest.raises(ValueError, match="int32 device time"):
        proc.process([Record("a", sc.A, 1_700_000_000_000)])


def test_processor_integer_keys_reach_predicates():
    """Integer record keys pass through to predicates unchanged."""
    pattern = (
        __import__("kafkastreams_cep_tpu").Query()
        .select("only")
        .where(lambda k, v, ts, st: (k == 5) & (v == sc.A))
        .build()
    )
    proc = CEPProcessor(pattern, 2, sc.default_config())
    out = proc.process([Record(5, sc.A, 1), Record(7, sc.A, 2)])
    assert [key for key, _ in out] == [5]


def test_processor_rejects_float_into_int_schema():
    proc = CEPProcessor(stock_demo.stock_pattern(), 1, stock_cfg())
    proc.process([Record("s", {"price": 100, "volume": 1010}, 1)])
    with pytest.raises(ValueError, match="schema"):
        proc.process([Record("s", {"price": 100.7, "volume": 990}, 2)])


def test_processor_gc_bounds_host_event_store():
    """The host event mirror tracks device slab GC instead of growing
    without bound: noise events that never enter the buffer are dropped.
    The GC syncs the device, so it is amortized (``gc_events_interval``);
    interval=1 pins the per-batch behavior."""
    proc = CEPProcessor(
        sc.strict3(), 1, sc.default_config(), gc_events_interval=1
    )
    noise = [Record("k", sc.X, i) for i in range(64)]
    proc.process(noise)
    assert len(proc._events[0]) == 0  # nothing buffered, nothing retained
    out = proc.process(
        [Record("k", sc.A, 100), Record("k", sc.B, 101), Record("k", sc.C, 102)]
    )
    assert len(out) == 1
    # Matched events were extracted (removed) from the slab and released.
    assert len(proc._events[0]) == 0


def test_processor_gc_events_amortized_by_default():
    """With the default interval the mirror is retained between batches
    (no per-batch device sync) and released once the cadence hits."""
    proc = CEPProcessor(
        sc.strict3(), 1, sc.default_config(), gc_events_interval=4
    )
    for b in range(4):
        proc.process([Record("k", sc.X, 10 * b + i) for i in range(8)])
        if b < 3:
            assert len(proc._events[0]) > 0  # deferred
    assert len(proc._events[0]) == 0  # 4th batch triggered the GC


def test_checkpoint_restore_mid_trace(tmp_path):
    """Checkpoint after e4, restore into a fresh processor built from user
    code, finish the trace: identical matches to the uninterrupted run."""
    pattern = stock_demo.stock_pattern()
    records = [
        Record("stocks", {"price": e["price"], "volume": e["volume"]}, 1000 + i)
        for i, e in enumerate(stock_demo.STOCK_EVENTS)
    ]
    name_of = {i: e["name"] for i, e in enumerate(stock_demo.STOCK_EVENTS)}

    proc = CEPProcessor(pattern, 1, stock_cfg())
    early = proc.process(records[:4])
    assert early == []
    path = str(tmp_path / "ckpt.bin")
    save_checkpoint(proc, path)

    restored = restore_processor(stock_demo.stock_pattern(), path)
    out = restored.process(records[4:])
    lines = [stock_demo.format_match(seq, name_of) for _, seq in out]
    assert lines == stock_demo.EXPECTED


def test_replay_dedup_high_water_mark():
    """At-least-once replays are dropped (deviation fixing the reference's
    documented gap, README.md:108): resending processed offsets neither
    duplicates matches nor corrupts runs."""
    proc = CEPProcessor(sc.strict3(), 1, sc.default_config())
    first = [
        Record("k", sc.A, 1, offset=10),
        Record("k", sc.B, 2, offset=11),
    ]
    assert proc.process(first) == []
    # Replay the same offsets plus the completing event.
    out = proc.process(first + [Record("k", sc.C, 3, offset=12)])
    assert len(out) == 1
    assert proc.metrics.duplicates_dropped == 2
    # Full replay of everything: no new matches at all.
    assert proc.process(first + [Record("k", sc.C, 3, offset=12)]) == []
    assert proc.metrics.duplicates_dropped == 5


def test_replay_duplicates_without_dedup_mimics_reference():
    """dedup=False reproduces the reference's replay behavior: duplicated
    offsets re-enter the NFA (matches duplicate — the documented gap)."""
    proc = CEPProcessor(sc.strict3(), 1, sc.default_config(), dedup=False)
    trace = [
        Record("k", sc.A, 1, offset=0),
        Record("k", sc.B, 2, offset=1),
        Record("k", sc.C, 3, offset=2),
    ]
    assert len(proc.process(trace)) == 1
    assert len(proc.process(trace)) >= 1  # replay produces matches again


def test_processor_metrics_snapshot():
    proc = CEPProcessor(stock_demo.stock_pattern(), 1, stock_cfg())
    records = [
        Record("s", {"price": e["price"], "volume": e["volume"]}, 1000 + i)
        for i, e in enumerate(stock_demo.STOCK_EVENTS)
    ]
    proc.process(records[:4])
    proc.process(records[4:])
    snap = proc.metrics_snapshot()
    assert snap["records_in"] == 8
    assert snap["matches_out"] == 4
    assert snap["batches"] == 2
    assert snap["device_seconds"] > 0
    assert snap["run_drops"] == 0


def test_checkpoint_refuses_wrong_topology(tmp_path):
    proc = CEPProcessor(sc.strict3(), 1, sc.default_config())
    proc.process([Record("k", 0, 1)])
    path = str(tmp_path / "ckpt.bin")
    save_checkpoint(proc, path)
    with pytest.raises(ValueError, match="topology"):
        restore_processor(sc.skip_till_any(), path)


def test_checkpoint_refuses_fold_dtype_flip(tmp_path):
    """agg stores float32 fold states as int32 bit patterns; restoring
    under the other dtype convention would silently reinterpret bits, so
    a dtype flip (init 0 -> 0.0) is refused like a name mismatch."""
    from kafkastreams_cep_tpu import Query

    def fold_pattern(init):
        return (
            Query()
            .select("a").where(lambda k, v, ts, st: v["x"] > 0)
            .fold("s", lambda k, v, curr: curr + v["x"], init=init)
            .then()
            .select("b").where(lambda k, v, ts, st: v["x"] < 0)
            .build()
        )

    proc = CEPProcessor(fold_pattern(0), 1, sc.default_config())
    proc.process([Record("k", {"x": 1}, 1)])
    path = str(tmp_path / "ckpt.bin")
    save_checkpoint(proc, path)
    with pytest.raises(ValueError, match="dtypes"):
        restore_processor(fold_pattern(0.0), path)
    restore_processor(fold_pattern(0), path)  # same dtype restores fine


def test_checkpoint_refuses_array_dtype_mismatch(tmp_path):
    """ISSUE 2 satellite: the array-level twin of the header dtype rule —
    a checkpoint whose stored array dtype differs from the engine's is
    refused instead of silently cast (astype could reinterpret typed-agg
    bit patterns as values with no shape mismatch to catch it)."""
    import io
    import pickle

    proc = CEPProcessor(sc.strict3(), 1, sc.default_config())
    proc.process([Record("k", 0, 1)])
    path = str(tmp_path / "ckpt.bin")
    save_checkpoint(proc, path)
    # Forge a dtype flip on one state array (agg int32 -> float32), the
    # kind of corruption astype() used to paper over.
    with open(path, "rb") as f:
        blob = pickle.load(f)
    with np.load(io.BytesIO(blob["arrays"])) as z:
        arrays = {k: z[k] for k in z.files}
    arrays["agg"] = arrays["agg"].astype(np.float32)
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    blob["arrays"] = buf.getvalue()
    # Re-sign the forged payload: the integrity digest (ISSUE 5) would
    # otherwise catch the tamper first — this test is about the dtype
    # rule an *intact* but dtype-flipped checkpoint must still hit.
    import hashlib

    blob["header"]["arrays_sha256"] = hashlib.sha256(blob["arrays"]).hexdigest()
    with open(path, "wb") as f:
        pickle.dump(blob, f)
    with pytest.raises(ValueError, match="dtype"):
        restore_processor(sc.strict3(), path)


def _run_batches(proc, batches):
    out = [proc.process(b) for b in batches]
    return out


def _fmt_all(match_lists):
    return [
        [(k, [(n, tuple(e.offset for e in evs))
              for n, evs in seq.as_map().items()]) for k, seq in ms]
        for ms in match_lists
    ]


def _random_records(n, keys, seed):
    rng = np.random.default_rng(seed)
    return [
        Record(int(rng.integers(0, keys)),
               {"price": int(rng.integers(90, 131)),
                "volume": int(rng.integers(600, 1101))},
               1000 + i)
        for i in range(n)
    ]


def test_compacted_decode_matches_full_pull():
    """decode_budget on vs off must emit identical matches; a budget of 1
    overflows on match-dense batches and falls back (counted), still
    identical."""
    recs = _random_records(180, keys=8, seed=21)
    batches = [recs[i:i + 36] for i in range(0, len(recs), 36)]
    full = CEPProcessor(stock_demo.stock_pattern(), 8, stock_cfg(),
                        decode_budget=0)
    fast = CEPProcessor(stock_demo.stock_pattern(), 8, stock_cfg(),
                        decode_budget=4096)
    tiny = CEPProcessor(stock_demo.stock_pattern(), 8, stock_cfg(),
                        decode_budget=1)
    want = _fmt_all(_run_batches(full, batches))
    assert _fmt_all(_run_batches(fast, batches)) == want
    assert _fmt_all(_run_batches(tiny, batches)) == want
    assert fast.metrics.decode_fallbacks == 0
    assert tiny.metrics.decode_fallbacks > 0


def test_pipelined_processor_emits_identical_one_call_late():
    """pipeline=True returns batch N-1's matches from call N; with a
    final flush() the concatenated match stream is byte-identical to the
    serial processor's, including across the host-event GC drain."""
    recs = _random_records(240, keys=8, seed=22)
    batches = [recs[i:i + 30] for i in range(0, len(recs), 30)]
    serial = CEPProcessor(stock_demo.stock_pattern(), 8, stock_cfg())
    piped = CEPProcessor(stock_demo.stock_pattern(), 8, stock_cfg(),
                         pipeline=True, gc_events_interval=3)
    want = _fmt_all(_run_batches(serial, batches))
    got = _fmt_all(_run_batches(piped, batches) + [piped.flush()])
    flat_want = [m for ms in want for m in ms]
    flat_got = [m for ms in got for m in ms]
    assert flat_got == flat_want
    # The shift really happened: call 0 returned nothing.
    assert got[0] == []


def test_process_columns_matches_per_record_path(tmp_path):
    """Columnar ingestion must emit exactly the per-record path's matches
    (auto-offset mode), lazily materializing only touched events, and
    survive a checkpoint round-trip (columns drain into the mirror)."""
    from kafkastreams_cep_tpu.runtime import restore_processor, save_checkpoint

    rng = np.random.default_rng(31)
    N, KEYS = 240, 8
    keys = rng.integers(0, KEYS, size=N).astype(np.int64)
    prices = rng.integers(90, 131, size=N).astype(np.int64)
    volumes = rng.integers(600, 1101, size=N).astype(np.int64)
    ts = 1000 + np.arange(N, dtype=np.int64)

    ref = CEPProcessor(stock_demo.stock_pattern(), KEYS, stock_cfg())
    want = []
    for i in range(0, N, 48):
        want.append(ref.process([
            Record(int(keys[j]), {"price": int(prices[j]),
                                  "volume": int(volumes[j])}, int(ts[j]))
            for j in range(i, min(i + 48, N))
        ]))

    col = CEPProcessor(stock_demo.stock_pattern(), KEYS, stock_cfg())
    got = []
    for i in range(0, N, 48):
        sl = slice(i, min(i + 48, N))
        got.append(col.process_columns(
            keys[sl], {"price": prices[sl], "volume": volumes[sl]}, ts[sl]
        ))
    assert _fmt_all(got) == _fmt_all(want)
    # Event payloads match too (values rebuilt from columns).
    for (gk, gseq), (wk, wseq) in zip(got[-1], want[-1]):
        assert gk == wk
        for (gn, gevs), (wn, wevs) in zip(
            gseq.as_map().items(), wseq.as_map().items()
        ):
            assert gn == wn
            for ge, we in zip(gevs, wevs):
                assert ge.value == we.value
                assert ge.timestamp == we.timestamp
                assert ge.offset == we.offset

    # Checkpoint drains the lazy columns; restore + more columns works.
    path = str(tmp_path / "col.ckpt")
    save_checkpoint(col, path)
    ref2 = restore_processor(stock_demo.stock_pattern(), path)
    more_w = ref2.process([
        Record(1, {"price": 100, "volume": 1200}, 5000),
        Record(1, {"price": 120, "volume": 800}, 5001),
    ])
    col2 = restore_processor(stock_demo.stock_pattern(), path)
    more_g = col2.process_columns(
        np.asarray([1, 1]),
        {"price": np.asarray([100, 120]), "volume": np.asarray([1200, 800])},
        np.asarray([5000, 5001]),
    )
    assert _fmt_all([more_g]) == _fmt_all([more_w])


def test_process_columns_pipelined_and_gc():
    """Columnar + pipeline + host-event GC cadence together: same match
    stream as the serial per-record processor."""
    rng = np.random.default_rng(33)
    N, KEYS = 360, 8
    keys = rng.integers(0, KEYS, size=N).astype(np.int64)
    prices = rng.integers(90, 131, size=N).astype(np.int64)
    volumes = rng.integers(600, 1101, size=N).astype(np.int64)
    ts = 1000 + np.arange(N, dtype=np.int64)

    ref = CEPProcessor(stock_demo.stock_pattern(), KEYS, stock_cfg())
    want = []
    for i in range(0, N, 40):
        want += ref.process([
            Record(int(keys[j]), {"price": int(prices[j]),
                                  "volume": int(volumes[j])}, int(ts[j]))
            for j in range(i, min(i + 40, N))
        ])

    col = CEPProcessor(stock_demo.stock_pattern(), KEYS, stock_cfg(),
                       pipeline=True, gc_events_interval=3)
    got = []
    for i in range(0, N, 40):
        sl = slice(i, min(i + 40, N))
        got += col.process_columns(
            keys[sl], {"price": prices[sl], "volume": volumes[sl]}, ts[sl]
        )
    got += col.flush()
    assert _fmt_all([got]) == _fmt_all([want])
