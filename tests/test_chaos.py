"""Chaos suite: randomized fault schedules vs a fault-free oracle.

Each seeded schedule drives a journaled Supervisor through a randomized
record stream while injecting, at seed-chosen points: device faults (pre-
and post-scan), journal append/fsync failures, checkpoint save/rename
failures, process crashes between batches, and torn/corrupt journal
tails forged at crash points.  After every crash the harness resumes
from disk and — modeling a Kafka-style at-least-once source — re-submits
the whole stream from the start (offset dedup absorbs what the restored
state already contains).

Invariants asserted against a clean oracle run of the same stream:

* **state convergence** — the final device state is bit-identical
  (canonical projection) to the oracle's;
* **exactly-once emission** — the emitted match multiset equals the
  oracle's… except when a crash hit while journaling was suspended (an
  append failed AND the forced snapshot also failed), the documented
  double-fault at-least-once window: then duplicates are permitted but
  the match *set* must still equal the oracle's (nothing lost, nothing
  invented).

Tier-1 runs a fixed handful of seeds; the ≥200-schedule sweep the
acceptance criterion asks for is ``-m slow`` (same harness, more seeds).
"""

import collections
import dataclasses
import os

import jax
import numpy as np
import pytest

import engine_scenarios as sc
from kafkastreams_cep_tpu.engine import EngineConfig
from kafkastreams_cep_tpu.parallel import ShardLost, key_mesh
from kafkastreams_cep_tpu.runtime import CEPProcessor, Record, Supervisor
from kafkastreams_cep_tpu.runtime.migrate import canonical_state
from kafkastreams_cep_tpu.utils import failpoints as fp

# Sized for the trace below (no capacity drops: chaos isolates fault
# tolerance; escalation has its own suite).
CFG = EngineConfig(
    max_runs=16, slab_entries=48, slab_preds=8, dewey_depth=16, max_walk=12
)
# Lazy extraction under chaos: a crash can land between match completion
# (handles pinned in the ring) and the drain — the recovery must replay to
# exactly-once emission through the deferred path too.
LAZY_CFG = dataclasses.replace(CFG, lazy_extraction=True, handle_ring=16)
# Compiler tiering under chaos: the pattern's strict prefix runs on the
# stencil tier, so the state is a TieredState whose prefix carry must
# survive checkpoint/restore/replay bit-identically (the oracle runs the
# same tiered config — carry leaves are compared like any state leaf).
TIERED_CFG = dataclasses.replace(CFG, tiering=True)
KEYS = ("k0", "k1")
N_BATCHES = 6
BATCH_SIZE = 4

# Per-batch injectable faults and their probabilities.  Device faults arm
# a single hit (the supervisor's one retry then succeeds); "hard" device
# faults arm two hits (retry exhausted -> the exception escapes process()
# and the harness treats it as a crash point).
FAULTS = (
    ("device.dispatch", 0.10, 1),
    ("device.result", 0.10, 1),
    ("journal.append", 0.10, 1),
    ("journal.fsync", 0.08, 1),
    ("checkpoint.save", 0.10, 1),
    ("checkpoint.rename", 0.08, 1),
    ("device.dispatch", 0.05, 2),  # hard: survives the retry
)


def gen_batches(seed):
    """A seeded record stream with explicit offsets (dedup-replayable)."""
    rng = np.random.default_rng(seed)
    offs = collections.defaultdict(int)
    batches, t = [], 0
    for _ in range(N_BATCHES):
        recs = []
        for _ in range(BATCH_SIZE):
            k = KEYS[int(rng.integers(len(KEYS)))]
            v = int(rng.integers(0, 5))
            recs.append(Record(k, v, 1000 + t, offset=offs[k]))
            offs[k] += 1
            t += 1
        batches.append(recs)
    return batches


def canon_match(key, seq):
    return (key, tuple(sorted(
        (stage, tuple(sorted(e.offset for e in events)))
        for stage, events in seq.as_map().items()
    )))


def oracle_run(batches, cfg=CFG):
    """Clean same-batching run: final state + emitted match multiset."""
    proc = CEPProcessor(sc.skip_till_any(), len(KEYS), cfg, gc_interval=0)
    emitted = collections.Counter()
    for b in batches:
        for k, seq in proc.process(b):
            emitted[canon_match(k, seq)] += 1
    for k, seq in proc.flush():
        emitted[canon_match(k, seq)] += 1
    return proc.state, emitted


def make_supervisor(ck, jr, resume=False, cfg=CFG, mesh=None):
    args = (sc.skip_till_any(), len(KEYS), cfg)
    kw = dict(
        checkpoint_path=ck, journal_path=jr, checkpoint_every=2,
        gc_interval=0,
    )
    if mesh is not None:
        kw["mesh"] = mesh
    if resume:
        return Supervisor.resume(*args, **kw)
    return Supervisor(*args, **kw)


def run_chaos(seed, tmp_path, cfg=CFG):
    batches = gen_batches(seed)
    rng = np.random.default_rng(seed + 10_000)
    ck = str(tmp_path / f"chaos{seed}.ckpt")
    jr = str(tmp_path / f"chaos{seed}.jrnl")
    sup = make_supervisor(ck, jr, cfg=cfg)
    emitted = collections.Counter()
    dups_allowed = False
    faults_fired = 0
    crashes = 0
    i = 0
    guard = 0
    while i < len(batches):
        guard += 1
        assert guard < 200, "chaos schedule failed to make progress"
        armed = []
        for site, p, times in FAULTS:
            if rng.random() < p:
                fp.FAILPOINTS.arm(site, times=times)
                armed.append(site)
        crash_after = rng.random() < 0.18
        try:
            for k, seq in sup.process(batches[i]):
                emitted[canon_match(k, seq)] += 1
            i += 1
        except fp.InjectedFault:
            # Retry exhausted: the recovery already rolled the state back;
            # the batch is unacknowledged.  Crash here (or just retry —
            # both are legal caller behaviors; crashing exercises more).
            crash_after = True
        finally:
            faults_fired += sum(
                fp.FAILPOINTS.hits(s) for s in set(armed)
            )
            fp.FAILPOINTS.clear()
        if crash_after:
            crashes += 1
            if sup._journal_suspended:
                # Acked batches are missing from the crash history: the
                # documented double-fault at-least-once window.
                dups_allowed = True
            if rng.random() < 0.4:
                fp.tear_journal_tail(jr)  # die mid-append
            elif rng.random() < 0.2:
                fp.corrupt_journal_tail(jr, seed=seed)
            del sup
            sup = make_supervisor(ck, jr, resume=True, cfg=cfg)
            i = 0  # at-least-once source: re-submit all; dedup absorbs
    return sup, emitted, dups_allowed, faults_fired, crashes


def assert_chaos_invariants(seed, tmp_path, cfg=CFG):
    batches = gen_batches(seed)
    want_state, want_matches = oracle_run(batches, cfg)
    sup, emitted, dups_allowed, faults, crashes = run_chaos(
        seed, tmp_path, cfg
    )
    import jax

    ca = canonical_state(sup.processor.state)
    cb = canonical_state(want_state)
    for i, (x, y) in enumerate(
        zip(jax.tree_util.tree_leaves(ca), jax.tree_util.tree_leaves(cb))
    ):
        np.testing.assert_array_equal(
            np.asarray(x), np.asarray(y),
            err_msg=f"seed {seed}: state leaf {i} diverged "
                    f"(faults={faults}, crashes={crashes})",
        )
    if dups_allowed:
        assert set(emitted) == set(want_matches), (
            f"seed {seed}: match SET diverged in a dup-allowed run"
        )
    else:
        assert emitted == want_matches, (
            f"seed {seed}: exactly-once violated "
            f"(faults={faults}, crashes={crashes})"
        )
    assert not any(sup.processor.counters().values())


FAST_SEEDS = list(range(8))


@pytest.mark.parametrize("seed", FAST_SEEDS)
def test_chaos_schedule_fast(seed, tmp_path):
    assert_chaos_invariants(seed, tmp_path)


@pytest.mark.parametrize("seed", [4])
def test_chaos_schedule_lazy(seed, tmp_path):
    """The same schedules through the lazy-extraction engine: crashes
    between match completion (pinned handles) and drain must still
    converge to the oracle's state and exactly-once emission."""
    assert_chaos_invariants(seed, tmp_path, cfg=LAZY_CFG)


@pytest.mark.slow
@pytest.mark.parametrize("seed", range(100, 300))  # 200 schedules
def test_chaos_schedule_sweep(seed, tmp_path):
    assert_chaos_invariants(seed, tmp_path)


@pytest.mark.slow
@pytest.mark.parametrize("seed", [1, 6] + list(range(300, 320)))
def test_chaos_schedule_lazy_sweep(seed, tmp_path):
    assert_chaos_invariants(seed, tmp_path, cfg=LAZY_CFG)


@pytest.mark.parametrize("seed", [2, 5])
def test_chaos_schedule_tiered(seed, tmp_path):
    """The same schedules with compiler tiering on: crashes, recoveries,
    and resumes must reconstruct the TieredState — stencil prefix carry
    included — bit-identically to the fault-free tiered oracle."""
    assert_chaos_invariants(seed, tmp_path, cfg=TIERED_CFG)


@pytest.mark.slow
@pytest.mark.parametrize("seed", [0, 3, 7] + list(range(320, 340)))
def test_chaos_schedule_tiered_sweep(seed, tmp_path):
    assert_chaos_invariants(seed, tmp_path, cfg=TIERED_CFG)


# -- kill-one-shard chaos ----------------------------------------------------


def run_shard_chaos(seed, tmp_path, cfg=CFG, crash_prob=0.15):
    """The meshed variant: the stream runs on a 2-device mesh and, at a
    seed-chosen batch, the ``shard.dispatch`` failpoint kills one shard
    (``ShardLost``) mid-stream — the supervisor must evacuate onto the
    surviving device and continue degraded.  Process crashes (with
    resume) interleave exactly like the single-mesh harness; a resume
    after evacuation restores the pinned snapshot onto the shrunk mesh.
    """
    batches = gen_batches(seed)
    rng = np.random.default_rng(seed + 20_000)
    ck = str(tmp_path / f"shard{seed}.ckpt")
    jr = str(tmp_path / f"shard{seed}.jrnl")
    mesh = key_mesh(jax.devices()[:2])
    sup = make_supervisor(ck, jr, cfg=cfg, mesh=mesh)
    emitted = collections.Counter()
    kill_at = int(rng.integers(1, len(batches)))
    dead_shard = int(rng.integers(2))
    killed = False
    evacuations = 0
    crashes = 0
    i = 0
    guard = 0
    while i < len(batches):
        guard += 1
        assert guard < 200, "shard-chaos schedule failed to make progress"
        if i == kill_at and not killed:
            fp.FAILPOINTS.arm(
                "shard.dispatch", times=1,
                exc=lambda: ShardLost("injected device loss",
                                      shard=dead_shard),
            )
        crash_after = rng.random() < crash_prob
        try:
            for k, seq in sup.process(batches[i]):
                emitted[canon_match(k, seq)] += 1
            i += 1
        finally:
            killed = killed or fp.FAILPOINTS.hits("shard.dispatch") > 0
            fp.FAILPOINTS.clear()
        evacuations = max(evacuations, sup.evacuations)
        if crash_after:
            crashes += 1
            # The post-evacuation snapshot pinned the surviving mesh; the
            # resumed incarnation must come back onto it (a real deploy
            # knows its device inventory — the dead chip is still dead).
            cur_mesh = sup._proc_kwargs.get("mesh", mesh)
            del sup
            sup = make_supervisor(ck, jr, resume=True, cfg=cfg,
                                  mesh=cur_mesh)
            i = 0  # at-least-once source: re-submit all; dedup absorbs
    return sup, emitted, killed, evacuations, crashes


def assert_shard_chaos_invariants(seed, tmp_path, cfg=CFG, crash_prob=0.15):
    if jax.device_count() < 2:
        pytest.skip("needs 2 devices")
    batches = gen_batches(seed)
    want_state, want_matches = oracle_run(batches, cfg)
    sup, emitted, killed, evacuations, crashes = run_shard_chaos(
        seed, tmp_path, cfg, crash_prob
    )
    assert killed, f"seed {seed}: the shard kill never fired"
    assert evacuations >= 1, f"seed {seed}: shard loss did not evacuate"
    ca = canonical_state(sup.processor.state)
    cb = canonical_state(want_state)
    for i, (x, y) in enumerate(
        zip(jax.tree_util.tree_leaves(ca), jax.tree_util.tree_leaves(cb))
    ):
        np.testing.assert_array_equal(
            np.asarray(x), np.asarray(y),
            err_msg=f"seed {seed}: state leaf {i} diverged after "
                    f"evacuation (crashes={crashes})",
        )
    assert emitted == want_matches, (
        f"seed {seed}: exactly-once violated across the shard kill "
        f"(evacuations={evacuations}, crashes={crashes})"
    )
    assert not any(sup.processor.counters().values())


@pytest.mark.parametrize("seed", FAST_SEEDS)
def test_shard_chaos_kill_one_fast(seed, tmp_path):
    # Lower crash interleaving on the fast tier (budget): across 8 seeds
    # several schedules still crash+resume mid-stream; the slow sweeps
    # run the full 0.15 rate.
    assert_shard_chaos_invariants(seed, tmp_path, crash_prob=0.08)


@pytest.mark.slow
@pytest.mark.parametrize("seed", range(400, 450))
def test_shard_chaos_kill_one_sweep(seed, tmp_path):
    assert_shard_chaos_invariants(seed, tmp_path)


@pytest.mark.slow
@pytest.mark.parametrize("seed", [0, 4] + list(range(450, 460)))
def test_shard_chaos_kill_one_tiered_sweep(seed, tmp_path):
    """Shard death + evacuation with the stencil tier live: the moved
    TieredState carry stays bit-identical to the tiered oracle."""
    assert_shard_chaos_invariants(seed, tmp_path, cfg=TIERED_CFG)
