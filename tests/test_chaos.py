"""Chaos suite: randomized fault schedules vs a fault-free oracle.

Each seeded schedule drives a journaled Supervisor through a randomized
record stream while injecting, at seed-chosen points: device faults (pre-
and post-scan), journal append/fsync failures, checkpoint save/rename
failures, process crashes between batches, and torn/corrupt journal
tails forged at crash points.  After every crash the harness resumes
from disk and — modeling a Kafka-style at-least-once source — re-submits
the whole stream from the start (offset dedup absorbs what the restored
state already contains).

Invariants asserted against a clean oracle run of the same stream:

* **state convergence** — the final device state is bit-identical
  (canonical projection) to the oracle's;
* **exactly-once emission** — the emitted match multiset equals the
  oracle's… except when a crash hit while journaling was suspended (an
  append failed AND the forced snapshot also failed), the documented
  double-fault at-least-once window: then duplicates are permitted but
  the match *set* must still equal the oracle's (nothing lost, nothing
  invented).

Tier-1 runs a fixed handful of seeds; the ≥200-schedule sweep the
acceptance criterion asks for is ``-m slow`` (same harness, more seeds).
"""

import collections
import dataclasses
import os

import jax
import numpy as np
import pytest

import engine_scenarios as sc
from kafkastreams_cep_tpu.engine import EngineConfig
from kafkastreams_cep_tpu.parallel import ShardLost, key_mesh
from kafkastreams_cep_tpu.runtime import CEPProcessor, Record, Supervisor
from kafkastreams_cep_tpu.runtime.migrate import canonical_state
from kafkastreams_cep_tpu.utils import failpoints as fp

# Sized for the trace below (no capacity drops: chaos isolates fault
# tolerance; escalation has its own suite).
CFG = EngineConfig(
    max_runs=16, slab_entries=48, slab_preds=8, dewey_depth=16, max_walk=12
)
# Lazy extraction under chaos: a crash can land between match completion
# (handles pinned in the ring) and the drain — the recovery must replay to
# exactly-once emission through the deferred path too.
LAZY_CFG = dataclasses.replace(CFG, lazy_extraction=True, handle_ring=16)
# Compiler tiering under chaos: the pattern's strict prefix runs on the
# stencil tier, so the state is a TieredState whose prefix carry must
# survive checkpoint/restore/replay bit-identically (the oracle runs the
# same tiered config — carry leaves are compared like any state leaf).
TIERED_CFG = dataclasses.replace(CFG, tiering=True)
KEYS = ("k0", "k1")
N_BATCHES = 6
BATCH_SIZE = 4

# Per-batch injectable faults and their probabilities.  Device faults arm
# a single hit (the supervisor's one retry then succeeds); "hard" device
# faults arm two hits (retry exhausted -> the exception escapes process()
# and the harness treats it as a crash point).
FAULTS = (
    ("device.dispatch", 0.10, 1),
    ("device.result", 0.10, 1),
    ("journal.append", 0.10, 1),
    ("journal.fsync", 0.08, 1),
    ("checkpoint.save", 0.10, 1),
    ("checkpoint.rename", 0.08, 1),
    ("device.dispatch", 0.05, 2),  # hard: survives the retry
)


def gen_batches(seed):
    """A seeded record stream with explicit offsets (dedup-replayable)."""
    rng = np.random.default_rng(seed)
    offs = collections.defaultdict(int)
    batches, t = [], 0
    for _ in range(N_BATCHES):
        recs = []
        for _ in range(BATCH_SIZE):
            k = KEYS[int(rng.integers(len(KEYS)))]
            v = int(rng.integers(0, 5))
            recs.append(Record(k, v, 1000 + t, offset=offs[k]))
            offs[k] += 1
            t += 1
        batches.append(recs)
    return batches


def canon_match(key, seq):
    return (key, tuple(sorted(
        (stage, tuple(sorted(e.offset for e in events)))
        for stage, events in seq.as_map().items()
    )))


def oracle_run(batches, cfg=CFG):
    """Clean same-batching run: final state + emitted match multiset."""
    proc = CEPProcessor(sc.skip_till_any(), len(KEYS), cfg, gc_interval=0)
    emitted = collections.Counter()
    for b in batches:
        for k, seq in proc.process(b):
            emitted[canon_match(k, seq)] += 1
    for k, seq in proc.flush():
        emitted[canon_match(k, seq)] += 1
    return proc.state, emitted


def make_supervisor(ck, jr, resume=False, cfg=CFG, mesh=None):
    args = (sc.skip_till_any(), len(KEYS), cfg)
    kw = dict(
        checkpoint_path=ck, journal_path=jr, checkpoint_every=2,
        gc_interval=0,
    )
    if mesh is not None:
        kw["mesh"] = mesh
    if resume:
        return Supervisor.resume(*args, **kw)
    return Supervisor(*args, **kw)


def run_chaos(seed, tmp_path, cfg=CFG):
    batches = gen_batches(seed)
    rng = np.random.default_rng(seed + 10_000)
    ck = str(tmp_path / f"chaos{seed}.ckpt")
    jr = str(tmp_path / f"chaos{seed}.jrnl")
    sup = make_supervisor(ck, jr, cfg=cfg)
    emitted = collections.Counter()
    dups_allowed = False
    faults_fired = 0
    crashes = 0
    i = 0
    guard = 0
    while i < len(batches):
        guard += 1
        assert guard < 200, "chaos schedule failed to make progress"
        armed = []
        for site, p, times in FAULTS:
            if rng.random() < p:
                fp.FAILPOINTS.arm(site, times=times)
                armed.append(site)
        crash_after = rng.random() < 0.18
        try:
            for k, seq in sup.process(batches[i]):
                emitted[canon_match(k, seq)] += 1
            i += 1
        except fp.InjectedFault:
            # Retry exhausted: the recovery already rolled the state back;
            # the batch is unacknowledged.  Crash here (or just retry —
            # both are legal caller behaviors; crashing exercises more).
            crash_after = True
        finally:
            faults_fired += sum(
                fp.FAILPOINTS.hits(s) for s in set(armed)
            )
            fp.FAILPOINTS.clear()
        if crash_after:
            crashes += 1
            if sup._journal_suspended:
                # Acked batches are missing from the crash history: the
                # documented double-fault at-least-once window.
                dups_allowed = True
            if rng.random() < 0.4:
                fp.tear_journal_tail(jr)  # die mid-append
            elif rng.random() < 0.2:
                fp.corrupt_journal_tail(jr, seed=seed)
            del sup
            sup = make_supervisor(ck, jr, resume=True, cfg=cfg)
            i = 0  # at-least-once source: re-submit all; dedup absorbs
    return sup, emitted, dups_allowed, faults_fired, crashes


def assert_chaos_invariants(seed, tmp_path, cfg=CFG):
    batches = gen_batches(seed)
    want_state, want_matches = oracle_run(batches, cfg)
    sup, emitted, dups_allowed, faults, crashes = run_chaos(
        seed, tmp_path, cfg
    )
    import jax

    ca = canonical_state(sup.processor.state)
    cb = canonical_state(want_state)
    for i, (x, y) in enumerate(
        zip(jax.tree_util.tree_leaves(ca), jax.tree_util.tree_leaves(cb))
    ):
        np.testing.assert_array_equal(
            np.asarray(x), np.asarray(y),
            err_msg=f"seed {seed}: state leaf {i} diverged "
                    f"(faults={faults}, crashes={crashes})",
        )
    if dups_allowed:
        assert set(emitted) == set(want_matches), (
            f"seed {seed}: match SET diverged in a dup-allowed run"
        )
    else:
        assert emitted == want_matches, (
            f"seed {seed}: exactly-once violated "
            f"(faults={faults}, crashes={crashes})"
        )
    assert not any(sup.processor.counters().values())


FAST_SEEDS = list(range(8))


@pytest.mark.parametrize("seed", FAST_SEEDS)
def test_chaos_schedule_fast(seed, tmp_path):
    assert_chaos_invariants(seed, tmp_path)


@pytest.mark.parametrize("seed", [4])
def test_chaos_schedule_lazy(seed, tmp_path):
    """The same schedules through the lazy-extraction engine: crashes
    between match completion (pinned handles) and drain must still
    converge to the oracle's state and exactly-once emission."""
    assert_chaos_invariants(seed, tmp_path, cfg=LAZY_CFG)


@pytest.mark.slow
@pytest.mark.parametrize("seed", range(100, 300))  # 200 schedules
def test_chaos_schedule_sweep(seed, tmp_path):
    assert_chaos_invariants(seed, tmp_path)


@pytest.mark.slow
@pytest.mark.parametrize("seed", [1, 6] + list(range(300, 320)))
def test_chaos_schedule_lazy_sweep(seed, tmp_path):
    assert_chaos_invariants(seed, tmp_path, cfg=LAZY_CFG)


@pytest.mark.parametrize("seed", [2, 5])
def test_chaos_schedule_tiered(seed, tmp_path):
    """The same schedules with compiler tiering on: crashes, recoveries,
    and resumes must reconstruct the TieredState — stencil prefix carry
    included — bit-identically to the fault-free tiered oracle."""
    assert_chaos_invariants(seed, tmp_path, cfg=TIERED_CFG)


@pytest.mark.slow
@pytest.mark.parametrize("seed", [0, 3, 7] + list(range(320, 340)))
def test_chaos_schedule_tiered_sweep(seed, tmp_path):
    assert_chaos_invariants(seed, tmp_path, cfg=TIERED_CFG)


# -- kill-one-shard chaos ----------------------------------------------------


def run_shard_chaos(seed, tmp_path, cfg=CFG, crash_prob=0.15):
    """The meshed variant: the stream runs on a 2-device mesh and, at a
    seed-chosen batch, the ``shard.dispatch`` failpoint kills one shard
    (``ShardLost``) mid-stream — the supervisor must evacuate onto the
    surviving device and continue degraded.  Process crashes (with
    resume) interleave exactly like the single-mesh harness; a resume
    after evacuation restores the pinned snapshot onto the shrunk mesh.
    """
    batches = gen_batches(seed)
    rng = np.random.default_rng(seed + 20_000)
    ck = str(tmp_path / f"shard{seed}.ckpt")
    jr = str(tmp_path / f"shard{seed}.jrnl")
    mesh = key_mesh(jax.devices()[:2])
    sup = make_supervisor(ck, jr, cfg=cfg, mesh=mesh)
    emitted = collections.Counter()
    kill_at = int(rng.integers(1, len(batches)))
    dead_shard = int(rng.integers(2))
    killed = False
    evacuations = 0
    crashes = 0
    i = 0
    guard = 0
    while i < len(batches):
        guard += 1
        assert guard < 200, "shard-chaos schedule failed to make progress"
        if i == kill_at and not killed:
            fp.FAILPOINTS.arm(
                "shard.dispatch", times=1,
                exc=lambda: ShardLost("injected device loss",
                                      shard=dead_shard),
            )
        crash_after = rng.random() < crash_prob
        try:
            for k, seq in sup.process(batches[i]):
                emitted[canon_match(k, seq)] += 1
            i += 1
        finally:
            killed = killed or fp.FAILPOINTS.hits("shard.dispatch") > 0
            fp.FAILPOINTS.clear()
        evacuations = max(evacuations, sup.evacuations)
        if crash_after:
            crashes += 1
            # The post-evacuation snapshot pinned the surviving mesh; the
            # resumed incarnation must come back onto it (a real deploy
            # knows its device inventory — the dead chip is still dead).
            cur_mesh = sup._proc_kwargs.get("mesh", mesh)
            del sup
            sup = make_supervisor(ck, jr, resume=True, cfg=cfg,
                                  mesh=cur_mesh)
            i = 0  # at-least-once source: re-submit all; dedup absorbs
    return sup, emitted, killed, evacuations, crashes


def assert_shard_chaos_invariants(seed, tmp_path, cfg=CFG, crash_prob=0.15):
    if jax.device_count() < 2:
        pytest.skip("needs 2 devices")
    batches = gen_batches(seed)
    want_state, want_matches = oracle_run(batches, cfg)
    sup, emitted, killed, evacuations, crashes = run_shard_chaos(
        seed, tmp_path, cfg, crash_prob
    )
    assert killed, f"seed {seed}: the shard kill never fired"
    assert evacuations >= 1, f"seed {seed}: shard loss did not evacuate"
    ca = canonical_state(sup.processor.state)
    cb = canonical_state(want_state)
    for i, (x, y) in enumerate(
        zip(jax.tree_util.tree_leaves(ca), jax.tree_util.tree_leaves(cb))
    ):
        np.testing.assert_array_equal(
            np.asarray(x), np.asarray(y),
            err_msg=f"seed {seed}: state leaf {i} diverged after "
                    f"evacuation (crashes={crashes})",
        )
    assert emitted == want_matches, (
        f"seed {seed}: exactly-once violated across the shard kill "
        f"(evacuations={evacuations}, crashes={crashes})"
    )
    assert not any(sup.processor.counters().values())


@pytest.mark.parametrize("seed", FAST_SEEDS)
def test_shard_chaos_kill_one_fast(seed, tmp_path):
    # Lower crash interleaving on the fast tier (budget): across 8 seeds
    # several schedules still crash+resume mid-stream; the slow sweeps
    # run the full 0.15 rate.
    assert_shard_chaos_invariants(seed, tmp_path, crash_prob=0.08)


@pytest.mark.slow
@pytest.mark.parametrize("seed", range(400, 450))
def test_shard_chaos_kill_one_sweep(seed, tmp_path):
    assert_shard_chaos_invariants(seed, tmp_path)


@pytest.mark.slow
@pytest.mark.parametrize("seed", [0, 4] + list(range(450, 460)))
def test_shard_chaos_kill_one_tiered_sweep(seed, tmp_path):
    """Shard death + evacuation with the stencil tier live: the moved
    TieredState carry stays bit-identical to the tiered oracle."""
    assert_shard_chaos_invariants(seed, tmp_path, cfg=TIERED_CFG)


# -- adaptive replan chaos ----------------------------------------------------
#
# The profiler->compiler loop (AdaptPolicy, runtime/supervisor.py): a
# drifting stream trips a checkpoint-boundary replan that swaps the
# processor onto a plan re-derived from the measured selectivity profile
# (migrate.replan_processor).  The swap must be behaviorally invisible —
# matches, emission, and state identical to a replan-free oracle — no
# matter where it lands relative to faults, crashes, and resumes, and a
# swap that dies mid-flight (the ``replan.swap`` fault site) must leave
# the old plan fully intact.

from kafkastreams_cep_tpu import Query
from kafkastreams_cep_tpu.runtime.supervisor import AdaptPolicy

# dewey_depth widened for the denser drift stream below — sized so every
# sweep seed runs overflow-free (chaos isolates plan swaps, not capacity
# loss; escalation has its own suite).
ADAPT_CFG = dataclasses.replace(
    TIERED_CFG, stage_attribution=True, dewey_depth=48
)
# Aggressive hysteresis so the short test streams trip: any 5-point
# windowed drift over >= 2 evals replans at the very next boundary.
AGGRESSIVE = AdaptPolicy(
    drift_threshold=0.05, min_evals=2, replan_streak=1, cooldown=0
)


def adapt_pattern():
    """A conjunct-bearing tiered pattern (declared expensive-first on
    purpose) so the replan has a lazy chain to re-rank from the measured
    per-conjunct tallies."""
    from kafkastreams_cep_tpu.pattern.predicate import and_, hint

    pricey = hint(
        lambda k, v, ts, st: (v * v + 3 * v) % 97 != 11, cost=50.0
    )
    first_is = hint(lambda k, v, ts, st: v == 0, cost=1.0)
    return (
        Query()
        .select("first").where(and_(pricey, first_is))
        .then()
        .select("second").skip_till_next_match()
        .where(lambda k, v, ts, st: v == 1)
        .build()
    )


def gen_drift_batches(seed, batch_size=2 * BATCH_SIZE):
    """A seeded stream whose selectivity flips halfway: the first half is
    dense in matching codes, the second half nearly all noise — exactly
    the drift AdaptPolicy watches for."""
    rng = np.random.default_rng(seed)
    offs = collections.defaultdict(int)
    batches, t = [], 0
    n = 2 * N_BATCHES
    for bi in range(n):
        pool = (0, 1, 2, 3) if bi < n // 2 else (4, 4, 4, 4, 4, 4, 4, 0)
        recs = []
        for _ in range(batch_size):
            k = KEYS[int(rng.integers(len(KEYS)))]
            v = int(pool[int(rng.integers(len(pool)))])
            recs.append(Record(k, v, 1000 + t, offset=offs[k]))
            offs[k] += 1
            t += 1
        batches.append(recs)
    return batches


def oracle_run_pattern(pattern, batches, cfg):
    """oracle_run over an explicit pattern (the fault-free, replan-free
    baseline the adaptive runs are compared against)."""
    proc = CEPProcessor(pattern, len(KEYS), cfg, gc_interval=0)
    emitted = collections.Counter()
    for b in batches:
        for k, seq in proc.process(b):
            emitted[canon_match(k, seq)] += 1
    for k, seq in proc.flush():
        emitted[canon_match(k, seq)] += 1
    return proc.state, emitted


def assert_states_equal(state, want_state, msg):
    ca = canonical_state(state)
    cb = canonical_state(want_state)
    for i, (x, y) in enumerate(
        zip(jax.tree_util.tree_leaves(ca), jax.tree_util.tree_leaves(cb))
    ):
        np.testing.assert_array_equal(
            np.asarray(x), np.asarray(y),
            err_msg=f"{msg}: state leaf {i} diverged",
        )


def test_drift_triggers_replan_and_is_invariant(tmp_path):
    """Drift-then-replan differential (no faults): the flipped stream
    trips at least one adaptive replan, the swapped-in plan is derived
    from MEASURED selectivity, and matches + final state are identical
    to the replan-free oracle — the swap point is unobservable."""
    batches = gen_drift_batches(7)
    pat = adapt_pattern()
    want_state, want_matches = oracle_run_pattern(pat, batches, ADAPT_CFG)
    sup = Supervisor(
        pat, len(KEYS), ADAPT_CFG,
        checkpoint_path=str(tmp_path / "adapt.ckpt"),
        journal_path=str(tmp_path / "adapt.jrnl"),
        checkpoint_every=2, gc_interval=0, adapt_policy=AGGRESSIVE,
    )
    emitted = collections.Counter()
    for b in batches:
        for k, seq in sup.process(b):
            emitted[canon_match(k, seq)] += 1
    assert sup.replans >= 1 and sup.replan_failures == 0
    # The loop actually closed: the live plan was derived from measured
    # selectivity (the initial build has no profile, so its lazy_order
    # rows carry selectivity=None and no measured conjuncts).
    lz = sup.processor.batch.lazy_order
    assert any(r.get("selectivity") is not None for r in lz.values()), lz
    assert any(r.get("measured_conjuncts") for r in lz.values()), lz
    assert emitted == want_matches
    assert_states_equal(
        sup.processor.state, want_state, "across the replan swap"
    )
    snap = sup.metrics_snapshot(per_lane=False)
    assert snap["replans"] == sup.replans >= 1
    assert snap["phases"]["replan"]["count"] == sup.replans
    assert not any(sup.processor.counters().values())


def test_replan_swap_failure_keeps_the_old_plan(tmp_path):
    """A replan that dies at the ``replan.swap`` fault site is absorbed:
    the old processor/plan stay live, the failure is counted, and the
    stream's matches still equal the oracle's."""
    batches = gen_drift_batches(11)
    pat = adapt_pattern()
    _, want_matches = oracle_run_pattern(pat, batches, ADAPT_CFG)
    sup = Supervisor(
        pat, len(KEYS), ADAPT_CFG,
        checkpoint_path=str(tmp_path / "adaptf.ckpt"),
        journal_path=str(tmp_path / "adaptf.jrnl"),
        checkpoint_every=2, gc_interval=0, adapt_policy=AGGRESSIVE,
    )
    fp.FAILPOINTS.arm("replan.swap", times=10**9)  # every attempt dies
    emitted = collections.Counter()
    try:
        for b in batches:
            for k, seq in sup.process(b):
                emitted[canon_match(k, seq)] += 1
    finally:
        fp.FAILPOINTS.clear()
    assert sup.replans == 0 and sup.replan_failures >= 1
    # The plan never changed: still the profile-less build.
    assert all(
        r.get("selectivity") is None
        for r in sup.processor.batch.lazy_order.values()
    )
    assert emitted == want_matches
    assert not any(sup.processor.counters().values())


REPLAN_FAULTS = FAULTS + (("replan.swap", 0.30, 1),)


def run_replan_chaos(seed, tmp_path):
    """The single-mesh chaos harness over a drifting stream with the
    adaptive replanner live, fault schedules extended with the
    ``replan.swap`` site.  Supervisor counters reset on crash, so replan
    totals accumulate across incarnations."""
    batches = gen_drift_batches(seed)
    pat = adapt_pattern()
    rng = np.random.default_rng(seed + 30_000)
    ck = str(tmp_path / f"replan{seed}.ckpt")
    jr = str(tmp_path / f"replan{seed}.jrnl")

    def mk(resume=False):
        args = (pat, len(KEYS), ADAPT_CFG)
        kw = dict(
            checkpoint_path=ck, journal_path=jr, checkpoint_every=2,
            gc_interval=0, adapt_policy=AGGRESSIVE,
        )
        if resume:
            return Supervisor.resume(*args, **kw)
        return Supervisor(*args, **kw)

    sup = mk()
    emitted = collections.Counter()
    dups_allowed = False
    replans = failures = crashes = 0
    i = guard = 0
    while i < len(batches):
        guard += 1
        assert guard < 400, "replan-chaos schedule failed to make progress"
        for site, p, times in REPLAN_FAULTS:
            if rng.random() < p:
                fp.FAILPOINTS.arm(site, times=times)
        crash_after = rng.random() < 0.10
        try:
            for k, seq in sup.process(batches[i]):
                emitted[canon_match(k, seq)] += 1
            i += 1
        except fp.InjectedFault:
            crash_after = True
        finally:
            fp.FAILPOINTS.clear()
        if crash_after:
            crashes += 1
            if sup._journal_suspended:
                dups_allowed = True
            replans += sup.replans
            failures += sup.replan_failures
            del sup
            sup = mk(resume=True)
            i = 0  # at-least-once source: re-submit all; dedup absorbs
    replans += sup.replans
    failures += sup.replan_failures
    return sup, emitted, dups_allowed, replans, failures, crashes


def assert_replan_chaos_invariants(seed, tmp_path, require_replan=False):
    batches = gen_drift_batches(seed)
    want_state, want_matches = oracle_run_pattern(
        adapt_pattern(), batches, ADAPT_CFG
    )
    sup, emitted, dups_allowed, replans, failures, crashes = (
        run_replan_chaos(seed, tmp_path)
    )
    if require_replan:
        assert replans + failures >= 1, (
            f"seed {seed}: the drift never exercised the replan path"
        )
    assert_states_equal(
        sup.processor.state, want_state,
        f"seed {seed} (replans={replans}, failed={failures}, "
        f"crashes={crashes})",
    )
    if dups_allowed:
        assert set(emitted) == set(want_matches), (
            f"seed {seed}: match SET diverged in a dup-allowed run"
        )
    else:
        assert emitted == want_matches, (
            f"seed {seed}: exactly-once violated across replans "
            f"(replans={replans}, failed={failures}, crashes={crashes})"
        )
    assert not any(sup.processor.counters().values())


@pytest.mark.parametrize("seed", [0, 3])
def test_replan_under_chaos(seed, tmp_path):
    assert_replan_chaos_invariants(seed, tmp_path, require_replan=True)


@pytest.mark.slow
@pytest.mark.parametrize("seed", range(500, 540))
def test_replan_under_chaos_sweep(seed, tmp_path):
    assert_replan_chaos_invariants(seed, tmp_path)


# -- overload brownout chaos ---------------------------------------------------
#
# Chaos over the brownout ladder (runtime/overload.py): a seeded flood
# escalates the ladder to shedding levels while faults and crashes land
# at arbitrary points; traffic then subsides and the ladder must step
# back to L0.  The oracle is a FAULT-FREE run of the same supervisor
# config over the same stream: pressure here is event-time-driven (hold
# occupancy only; the wall-clock signals are neutralized), so the ladder
# trajectory — and with it the Bresenham shed subset — is a pure function
# of the record stream.  The chaotic run must therefore emit the
# identical match multiset, shed the identical records (same typed dead
# letters), keep the loss ledger reconciling, and converge to the
# identical device state and level.
#
# The palette deliberately omits the ``checkpoint.*`` and
# ``overload.enter``/``overload.exit`` sites: those faults DEFER a
# transition (the documented fallback — previous level stays
# authoritative), which legitimately changes the ladder trajectory and
# would diverge from the fault-free oracle.  Deferred-transition
# semantics are proved in tests/test_overload.py; here we prove that
# everything *else* can burn mid-brownout without breaking exactly-once.

from kafkastreams_cep_tpu.runtime.ingest import IngestPolicy
from kafkastreams_cep_tpu.runtime.overload import OverloadPolicy

OVL_POLICY = OverloadPolicy(
    burn_ref=1e9, queue_ref=1e9, ring_ref=1e9, hold_age_ref=1e9,
    hold_ref=0.05, enter_streak=1, exit_streak=2,
)
# Depth 64: the flood (96 records, minus sheds) fits without reorder
# evictions, and the steady-state subside pressure (one in-flight hold)
# sits below exit_at[0] so the ladder can recover all the way to L0.
OVL_INGEST = IngestPolicy(grace_ms=1000, reorder_depth=64)
OVL_KEYS = ("k0", "k1", "k2", "k3")
OVL_FAULTS = (
    ("device.dispatch", 0.10, 1),
    ("device.result", 0.10, 1),
    ("journal.append", 0.10, 1),
    ("journal.fsync", 0.08, 1),
    ("overload.shed", 0.10, 1),   # absorbed by restore+replay in-place
    ("device.dispatch", 0.03, 2),  # hard: survives the retry
)
# 26-batch stream with re-submission from offset 0 on every crash: the
# per-batch crash rate must stay low enough that a full pass completes
# ((1-p)^26), unlike the 6-batch harness above which tolerates 0.18.
OVL_CRASH_P = 0.06


def gen_overload_batches(seed):
    """Seeded flood (dense +1 ms ticks: everything is held, pressure
    climbs one level per batch) followed by a sparse subside tail
    (+5 s jumps: the watermark races ahead, the backlog drains, the
    ladder steps down).  Keys and values are seed-random; the timestamp
    schedule — which alone drives the ladder — is fixed."""
    rng = np.random.default_rng(seed)
    offs = collections.defaultdict(int)
    batches, t = [], 0
    for _ in range(6):  # flood: 6 batches x 16
        recs = []
        for _ in range(16):
            t += 1
            k = OVL_KEYS[int(rng.integers(len(OVL_KEYS)))]
            recs.append(
                Record(k, int(rng.integers(0, 3)), t, offset=offs[k])
            )
            offs[k] += 1
        batches.append(recs)
    for _ in range(20):  # subside
        t += 5000
        k = OVL_KEYS[int(rng.integers(len(OVL_KEYS)))]
        batches.append([Record(k, 4, t, offset=offs[k])])
        offs[k] += 1
    return batches


def make_overload_sup(ck, jr, resume=False):
    args = (sc.strict3(), len(OVL_KEYS), CFG)
    kw = dict(
        checkpoint_path=ck, journal_path=jr, checkpoint_every=2,
        gc_interval=0, overload_policy=OVL_POLICY, ingest=OVL_INGEST,
    )
    if resume:
        return Supervisor.resume(*args, **kw)
    return Supervisor(*args, **kw)


def drain_emitted(sup, emitted):
    for k, seq in sup.processor.drain_ingest():
        emitted[canon_match(k, seq)] += 1
    for k, seq in sup.processor.flush():
        emitted[canon_match(k, seq)] += 1


def run_overload_oracle(batches, tmp_path):
    sup = make_overload_sup(
        str(tmp_path / "ovl-oracle.ckpt"), str(tmp_path / "ovl-oracle.jrnl")
    )
    emitted = collections.Counter()
    levels = []
    for b in batches:
        for k, seq in sup.process(b):
            emitted[canon_match(k, seq)] += 1
        levels.append(sup._overload.level)
    drain_emitted(sup, emitted)
    return sup, emitted, levels


def run_overload_chaos(seed, tmp_path):
    batches = gen_overload_batches(seed)
    rng = np.random.default_rng(seed + 40_000)
    ck = str(tmp_path / f"ovl{seed}.ckpt")
    jr = str(tmp_path / f"ovl{seed}.jrnl")
    sup = make_overload_sup(ck, jr)
    emitted = collections.Counter()
    dups_allowed = False
    faults_fired = crashes = 0
    i = guard = 0
    while i < len(batches):
        guard += 1
        assert guard < 800, "overload-chaos schedule failed to progress"
        armed = []
        for site, p, times in OVL_FAULTS:
            if rng.random() < p:
                fp.FAILPOINTS.arm(site, times=times)
                armed.append(site)
        crash_after = rng.random() < OVL_CRASH_P
        try:
            for k, seq in sup.process(batches[i]):
                emitted[canon_match(k, seq)] += 1
            i += 1
        except fp.InjectedFault:
            crash_after = True
        finally:
            faults_fired += sum(
                fp.FAILPOINTS.hits(s) for s in set(armed)
            )
            fp.FAILPOINTS.clear()
        if crash_after:
            crashes += 1
            if sup._journal_suspended:
                dups_allowed = True
            if rng.random() < 0.4:
                fp.tear_journal_tail(jr)
            elif rng.random() < 0.2:
                fp.corrupt_journal_tail(jr, seed=seed)
            del sup
            sup = make_overload_sup(ck, jr, resume=True)
            # Resume from the restored consumer position (the committed
            # offset), NOT from 0: the ladder ticks once per processed
            # batch, so replaying already-counted duplicate batches
            # would inject extra pressure ticks — correct product
            # behavior (the hold backlog is real), but it shifts the
            # ladder trajectory relative to the fault-free oracle.  The
            # restored dedup state is batch-aligned (journal replay
            # reconstructs whole batches; a torn tail loses whole
            # records), so the scan lands exactly on the first batch the
            # restored state has not seen.  Blind from-0 re-submission
            # with dedup absorption is covered by run_chaos above and by
            # tests/test_overload.py's crash-at-level tests.
            def _seen(rec):
                lane = sup.processor._lane_of.get(rec.key)
                if lane is None:
                    return False
                return rec.offset < sup.processor._guard.source_hw.get(
                    lane, 0
                )

            i = 0
            while i < len(batches) and all(
                _seen(r) for r in batches[i]
            ):
                i += 1
    drain_emitted(sup, emitted)
    return sup, emitted, dups_allowed, faults_fired, crashes


def assert_overload_chaos_invariants(seed, tmp_path):
    batches = gen_overload_batches(seed)
    oracle, want, levels = run_overload_oracle(batches, tmp_path)
    assert max(levels) >= 3, levels  # shedding actually engaged
    assert levels[-1] == 0, levels  # and the fault-free run recovered
    sup, emitted, dups_allowed, faults, crashes = run_overload_chaos(
        seed, tmp_path
    )
    tag = f"seed {seed} (faults={faults}, crashes={crashes})"
    # The chaotic ladder landed where the fault-free ladder landed.
    assert sup._overload.level == 0, tag
    g, og = sup.processor._guard, oracle.processor._guard
    offered = sum(len(b) for b in batches)
    lc, olc = g.loss_counters(), og.loss_counters()
    # Loss ledger reconciles exactly — every unique offered record is
    # admitted, shed (typed), or dead-lettered (typed), once, no matter
    # how many times the at-least-once source re-submitted it.
    assert offered == g.admitted + lc["overload_shed"] + lc[
        "late_dropped"
    ] + lc["quarantined"], tag
    # ... and is identical to the fault-free ledger, record for record.
    assert lc == olc and g.admitted == og.admitted, tag
    assert {
        (d.record.key, d.record.offset, d.reason) for d in g.dead_letters
    } == {
        (d.record.key, d.record.offset, d.reason) for d in og.dead_letters
    }, tag
    if dups_allowed:
        assert set(emitted) == set(want), (
            f"{tag}: match SET diverged in a dup-allowed run"
        )
    else:
        assert emitted == want, f"{tag}: exactly-once violated"
    assert_states_equal(sup.processor.state, oracle.processor.state, tag)
    assert not any(sup.processor.counters().values())
    assert not any(oracle.processor.counters().values())


@pytest.mark.parametrize("seed", FAST_SEEDS)
def test_overload_chaos_fast(seed, tmp_path):
    assert_overload_chaos_invariants(seed, tmp_path)


@pytest.mark.slow
@pytest.mark.parametrize("seed", range(600, 640))
def test_overload_chaos_sweep(seed, tmp_path):
    assert_overload_chaos_invariants(seed, tmp_path)
