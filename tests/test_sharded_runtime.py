"""Sharded runtime: the processor/checkpoint/supervisor stack over a mesh.

The reference's scale-out contract is state-follows-partition
(``CEPProcessor.java:117-134``): each partition's NFA state lives with its
assignee and migrates via changelog restore on rebalance.  Here the lane
axis shards over a ``jax.sharding.Mesh`` (8 virtual CPU devices in the
suite), checkpoints gather to mesh-agnostic host arrays, and restore
re-places onto whatever mesh the new processor runs on.  Tests pin

* emission parity: the sharded processor emits exactly the single-device
  processor's matches, in the same order;
* crash recovery on a mesh: checkpoint -> new process -> restore -> replay
  continues identically (the supervisor flow, ``runtime/supervisor.py``);
* rebalance: a snapshot written on an 8-device mesh restores onto a
  4-device mesh (and back to a single device) with identical emissions.
"""

import os
import tempfile

import jax
import numpy as np
import pytest

from kafkastreams_cep_tpu import Query
from kafkastreams_cep_tpu.engine import EngineConfig
from kafkastreams_cep_tpu.parallel.sharding import key_mesh
from kafkastreams_cep_tpu.runtime import CEPProcessor, Record
from kafkastreams_cep_tpu.runtime.checkpoint import (
    restore_processor,
    save_checkpoint,
)

NUM_LANES = 16
CFG = EngineConfig(
    max_runs=8, slab_entries=24, slab_preds=4, dewey_depth=8, max_walk=8
)


def pattern():
    return (
        Query()
        .select("lo").where(lambda k, v, ts, st: v["x"] < 3)
        .then()
        .select("hi").skip_till_next_match()
        .where(lambda k, v, ts, st: v["x"] > 6)
        .build()
    )


def records(n, seed, keys=NUM_LANES):
    rng = np.random.default_rng(seed)
    return [
        Record(int(rng.integers(0, keys)), {"x": int(rng.integers(0, 10))},
               1000 + i)
        for i in range(n)
    ]


def fmt(matches):
    return [
        (key, [(name, tuple(e.offset for e in evs))
               for name, evs in seq.as_map().items()])
        for key, seq in matches
    ]


def batches(recs, size=24):
    return [recs[i:i + size] for i in range(0, len(recs), size)]


@pytest.fixture(scope="module")
def mesh8():
    if jax.device_count() < 8:
        pytest.skip("needs 8 devices")
    return key_mesh(jax.devices()[:8])


def test_sharded_processor_emission_parity(mesh8):
    recs = records(144, seed=1)
    single = CEPProcessor(pattern(), NUM_LANES, CFG)
    shard = CEPProcessor(pattern(), NUM_LANES, CFG, mesh=mesh8)
    for b in batches(recs):
        assert fmt(shard.process(b)) == fmt(single.process(b))
    assert shard.counters() == single.counters()


def test_sharded_checkpoint_crash_restore_replay(mesh8):
    """Process -> checkpoint -> 'crash' -> restore on the mesh -> replay:
    emissions continue exactly where the single-device reference run says
    they should."""
    recs = records(192, seed=2)
    bs = batches(recs)
    cut = len(bs) // 2

    # Ground truth: one uninterrupted single-device run.
    ref = CEPProcessor(pattern(), NUM_LANES, CFG)
    expected = [fmt(ref.process(b)) for b in bs]

    shard = CEPProcessor(pattern(), NUM_LANES, CFG, mesh=mesh8)
    got_before = [fmt(shard.process(b)) for b in bs[:cut]]
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "mesh.ckpt")
        save_checkpoint(shard, path)
        del shard  # the crash

        restored = restore_processor(pattern(), path, mesh=mesh8)
        got_after = [fmt(restored.process(b)) for b in bs[cut:]]
    assert got_before + got_after == expected


def test_checkpoint_rebalances_across_mesh_sizes(mesh8):
    """A snapshot written on 8 devices restores onto 4 devices and onto a
    single device with identical continued emissions — the consumer-group
    rebalance analog."""
    recs = records(144, seed=3)
    bs = batches(recs)
    cut = 3

    ref = CEPProcessor(pattern(), NUM_LANES, CFG)
    expected = [fmt(ref.process(b)) for b in bs]

    shard8 = CEPProcessor(pattern(), NUM_LANES, CFG, mesh=mesh8)
    before = [fmt(shard8.process(b)) for b in bs[:cut]]
    assert before == expected[:cut]
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "mesh8.ckpt")
        save_checkpoint(shard8, path)

        mesh4 = key_mesh(jax.devices()[:4])
        shard4 = restore_processor(pattern(), path, mesh=mesh4)
        single = restore_processor(pattern(), path)  # mesh=None: one device
        for i, b in enumerate(bs[cut:]):
            out4 = fmt(shard4.process(b))
            out1 = fmt(single.process(b))
            assert out4 == expected[cut + i]
            assert out1 == expected[cut + i]


def test_sharded_supervisor_crash_resume(mesh8):
    """The full supervisor flow (checkpoint + journal + process-crash
    resume) on a mesh-backed processor."""
    from kafkastreams_cep_tpu.runtime.supervisor import Supervisor

    recs = records(144, seed=4)
    bs = batches(recs)

    ref = CEPProcessor(pattern(), NUM_LANES, CFG)
    expected = [fmt(ref.process(b)) for b in bs]

    with tempfile.TemporaryDirectory() as d:
        ck = os.path.join(d, "sup.ckpt")
        jl = os.path.join(d, "sup.journal")
        sup = Supervisor(
            pattern(), NUM_LANES, CFG,
            checkpoint_path=ck, journal_path=jl, checkpoint_every=2,
            mesh=key_mesh(jax.devices()[:8]),
        )
        got = [fmt(sup.process(b)) for b in bs[:4]]
        del sup  # process crash

        sup2 = Supervisor.resume(
            pattern(), NUM_LANES, CFG,
            checkpoint_path=ck, journal_path=jl,
            mesh=key_mesh(jax.devices()[:8]),
        )
        got += [fmt(sup2.process(b)) for b in bs[4:]]
    assert got == expected


def test_sharded_walk_kernel_interpret_parity(mesh8, monkeypatch):
    """Pallas-inside-shard_map (the path a real TPU mesh auto-enables):
    128 lanes per shard, kernel forced in interpreter mode, emissions
    identical to the jnp sharded path."""
    monkeypatch.setenv("CEP_WALK_KERNEL", "0")
    K = 128 * 8
    jnp_proc = CEPProcessor(pattern(), K, CFG, mesh=mesh8)
    assert not jnp_proc.batch.uses_walk_kernel
    monkeypatch.setenv("CEP_WALK_KERNEL", "interpret")
    krn_proc = CEPProcessor(pattern(), K, CFG, mesh=mesh8)
    assert krn_proc.batch.uses_walk_kernel
    recs = records(192, seed=6, keys=K)
    for b in batches(recs, size=64):
        assert fmt(krn_proc.process(b)) == fmt(jnp_proc.process(b))
    assert krn_proc.counters() == jnp_proc.counters()


def test_sharded_scan_exact_stats_and_outputs(mesh8):
    """The semantic cover for ``check_vma=False`` (parallel/sharding.py):
    shard_map's static replication analysis is disabled at every site, so
    a misplaced collective would pass compilation — this test would catch
    it instead.  On a per-lane-distinct, counter-heavy kleene trace, the
    sharded scan's match outputs and psum'd stats must EXACTLY equal the
    single-device BatchMatcher run (not merely >= some floor)."""
    import jax.numpy as jnp

    from kafkastreams_cep_tpu.engine import EventBatch
    from kafkastreams_cep_tpu.parallel.batch import BatchMatcher
    from kafkastreams_cep_tpu.parallel.sharding import ShardedMatcher

    def kleene():
        return (
            Query()
            .select("a").where(lambda k, v, ts, st: v["x"] == 0)
            .then()
            .select("b").one_or_more().skip_till_any_match()
            .where(lambda k, v, ts, st: (0 < v["x"]) & (v["x"] < 8))
            .then()
            .select("c").where(lambda k, v, ts, st: v["x"] >= 8)
            .build()
        )

    K, T = 16, 48
    rng = np.random.default_rng(11)
    # Per-lane-distinct activity: lane L sees its own random stream, and
    # the tiny config overflows differently per lane (runs, slab, preds),
    # so any cross-shard mixup or double-count changes the totals.
    xs = rng.integers(0, 10, size=(K, T)).astype(np.int32)
    events = EventBatch(
        key=jnp.broadcast_to(jnp.arange(K, dtype=jnp.int32)[:, None], (K, T)),
        value={"x": jnp.asarray(xs)},
        ts=jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32)[None, :], (K, T)),
        off=jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32)[None, :], (K, T)),
        valid=jnp.ones((K, T), bool),
    )

    batch = BatchMatcher(kleene(), K, CFG)
    bstate, bout = batch.scan(batch.init_state(), events)
    ref_counters = batch.counters(bstate)
    # The trace must actually exercise the counters for the equality to
    # mean anything.
    assert sum(ref_counters.values()) > 0, ref_counters

    sharded = ShardedMatcher(kleene(), K, mesh8, CFG)
    sstate, sout = sharded.scan(
        sharded.init_state(), sharded.shard_events(events)
    )
    np.testing.assert_array_equal(np.asarray(sout.count), np.asarray(bout.count))
    np.testing.assert_array_equal(np.asarray(sout.stage), np.asarray(bout.stage))
    np.testing.assert_array_equal(np.asarray(sout.off), np.asarray(bout.off))
    expect = dict(ref_counters)
    expect["alive_runs"] = int(jnp.sum(bstate.alive))
    expect.update(batch.hot_counters(bstate))
    expect.update(batch.walk_counters(bstate))
    assert sharded.stats(sstate) == expect
