"""Fused Pallas walk kernel vs the sequential slab ops it replaces.

Ground truth is the per-op sequential path — ``slab.branch`` for increment
walkers and ``slab.peek(remove=True)`` for removal/extraction walkers,
applied one walker at a time in queue order per lane (the reference's
order, ``NFA.java:102-123``).  The kernel runs in interpreter mode on CPU
(the suite's platform); the real-chip path is exercised by the benchmarks
and the engine A/B test.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kafkastreams_cep_tpu.ops import dewey_ops
from kafkastreams_cep_tpu.ops import slab as slab_mod
from kafkastreams_cep_tpu.ops.walk_kernel import LANE_BLOCK, walk_pass_kernel

from test_slab_batched import assert_slab_equal, seed_slab

E, MP, D, W = 16, 4, 6, 8
OUT_BASE, OUT_ROWS = 4, 4  # candidate rows [4, 8) may emit
PW = OUT_BASE + OUT_ROWS


def random_walkers(rng):
    """One lane's candidate walker set in the engine's layout: increment
    walkers first, then remove walkers, the final ``OUT_ROWS`` extracting."""
    en = rng.random(PW) < 0.5
    stage = rng.integers(0, 4, size=PW).astype(np.int32)
    off = rng.integers(0, 5, size=PW).astype(np.int32)
    vers, vlens = [], []
    for _ in range(PW):
        comps = tuple(rng.integers(1, 3, size=rng.integers(1, 4)))
        v, l = dewey_ops.make(comps, D)
        vers.append(v)
        vlens.append(l)
    is_remove = np.arange(PW) >= 2  # rows [0,2): branch; [2,PW): remove
    want_out = np.arange(PW) >= OUT_BASE
    return dict(
        en=en, stage=stage, off=off,
        ver=np.stack(vers).astype(np.int32),
        vlen=np.asarray(vlens, np.int32),
        is_remove=is_remove, want_out=want_out,
    )


def sequential_lane(slab, wk):
    """Queue-order per-walker ground truth for one lane."""
    out_stage = np.full((OUT_ROWS, W), -1, np.int32)
    out_off = np.full((OUT_ROWS, W), -1, np.int32)
    count = np.zeros((OUT_ROWS,), np.int32)
    for p in range(PW):
        if not wk["en"][p]:
            continue
        if wk["is_remove"][p]:
            slab, st, of, cnt = slab_mod.peek(
                slab, int(wk["stage"][p]), int(wk["off"][p]),
                jnp.asarray(wk["ver"][p]), jnp.asarray(wk["vlen"][p]),
                W, remove=True, enable=True,
            )
            if wk["want_out"][p]:
                r = p - OUT_BASE
                out_stage[r] = np.asarray(st)
                out_off[r] = np.asarray(of)
                count[r] = int(cnt)
        else:
            slab = slab_mod.branch(
                slab, int(wk["stage"][p]), int(wk["off"][p]),
                jnp.asarray(wk["ver"][p]), jnp.asarray(wk["vlen"][p]),
                W, enable=True,
            )
    return slab, out_stage, out_off, count


def batch_lanes(lanes, field):
    return jnp.asarray(np.stack([l[field] for l in lanes]))


@pytest.mark.parametrize("seed", range(4))
def test_kernel_matches_sequential(seed):
    rng = np.random.default_rng(400 + seed)
    K = LANE_BLOCK
    # A handful of distinct lane slabs tiled over the block (the kernel is
    # elementwise over lanes; distinct-per-lane content catches cross-lane
    # mixups, full-K distinctness only costs test time).
    n_distinct = 8
    slabs, wksets, seq = [], [], []
    for i in range(n_distinct):
        s = seed_slab(rng)
        wk = random_walkers(rng)
        slabs.append(s)
        wksets.append(wk)
        seq.append(sequential_lane(s, wk))
    reps = K // n_distinct
    slab_K = jax.tree_util.tree_map(
        lambda *xs: jnp.asarray(
            np.tile(np.stack([np.asarray(x) for x in xs]), (reps,) + (1,) * xs[0].ndim)
        ),
        *slabs,
    )
    wk_K = {f: jnp.tile(batch_lanes(wksets, f), (reps,) + (1,) * (batch_lanes(wksets, f).ndim - 1)) for f in wksets[0]}

    new_slab, out_stage, out_off, count = walk_pass_kernel(
        slab_K, wk_K["en"], wk_K["stage"], wk_K["off"], wk_K["ver"],
        wk_K["vlen"], wk_K["is_remove"], wk_K["want_out"],
        max_walk=W, out_base=OUT_BASE, out_rows=OUT_ROWS, interpret=True,
    )

    for i in range(n_distinct):
        exp_slab, exp_st, exp_of, exp_ct = seq[i]
        for rep in (0, reps - 1):
            lane = rep * n_distinct + i
            got = jax.tree_util.tree_map(lambda x: x[lane], new_slab)
            # Sequential pads counters differently only in untouched fields.
            assert_slab_equal(exp_slab, got, f"seed={seed} lane={lane}")
            np.testing.assert_array_equal(
                np.asarray(out_stage[lane]), exp_st,
                err_msg=f"seed={seed} lane={lane} out_stage",
            )
            np.testing.assert_array_equal(
                np.asarray(out_off[lane]), exp_of,
                err_msg=f"seed={seed} lane={lane} out_off",
            )
            np.testing.assert_array_equal(
                np.asarray(count[lane]), exp_ct,
                err_msg=f"seed={seed} lane={lane} count",
            )


def test_kernel_put_phase_matches_sequential():
    """The in-kernel consuming-put phase vs slab.put/put_first applied one
    op at a time in rank order — including the rare branches: slab-full
    drops, pointer-list overflow, mid-rank put_first reset, and chained
    puts with missing predecessors."""
    from kafkastreams_cep_tpu.ops.slab import PutOps

    K = LANE_BLOCK
    PP = 10
    n_distinct = 8
    rng = np.random.default_rng(900)
    lanes = []
    for i in range(n_distinct):
        slab = seed_slab(rng)
        # Tiny slabs/pointer lists so full/pred drops actually fire.
        ops = dict(
            en=rng.random(PP) < 0.8,
            first=rng.random(PP) < 0.4,
            cur_stage=rng.integers(0, 3, size=PP).astype(np.int32),
            prev_stage=rng.integers(0, 3, size=PP).astype(np.int32),
            prev_off=rng.integers(0, 6, size=PP).astype(np.int32),
        )
        vers, vlens = [], []
        for _ in range(PP):
            comps = tuple(rng.integers(1, 3, size=rng.integers(1, 3)))
            v, l = dewey_ops.make(comps, D)
            vers.append(v)
            vlens.append(l)
        ops["ver"] = np.stack(vers).astype(np.int32)
        ops["vlen"] = np.asarray(vlens, np.int32)
        lanes.append((slab, ops))

    ev_off = 9  # current event offset, shared by every put of the step

    def sequential(slab, ops):
        for p in range(PP):
            if not ops["en"][p]:
                continue
            if ops["first"][p]:
                slab = slab_mod.put_first(
                    slab, int(ops["cur_stage"][p]), ev_off,
                    jnp.asarray(ops["ver"][p]), jnp.asarray(ops["vlen"][p]),
                )
            else:
                slab = slab_mod.put(
                    slab, int(ops["cur_stage"][p]), ev_off,
                    int(ops["prev_stage"][p]), int(ops["prev_off"][p]),
                    jnp.asarray(ops["ver"][p]), jnp.asarray(ops["vlen"][p]),
                )
        return slab

    seq = [sequential(s, o) for s, o in lanes]

    reps = K // n_distinct
    tile = lambda arrs: jnp.asarray(
        np.tile(np.stack(arrs), (reps,) + (1,) * arrs[0].ndim)
    )
    slab_K = jax.tree_util.tree_map(
        lambda *xs: jnp.asarray(
            np.tile(np.stack([np.asarray(x) for x in xs]),
                    (reps,) + (1,) * xs[0].ndim)
        ),
        *[s for s, _ in lanes],
    )
    put_ops = PutOps(
        en=tile([o["en"] for _, o in lanes]),
        first=tile([o["first"] for _, o in lanes]),
        cur_stage=tile([o["cur_stage"] for _, o in lanes]),
        prev_stage=tile([o["prev_stage"] for _, o in lanes]),
        prev_off=tile([o["prev_off"] for _, o in lanes]),
        ver=tile([o["ver"] for _, o in lanes]),
        vlen=tile([o["vlen"] for _, o in lanes]),
    )
    # No walkers: the kernel applies only the put phase.
    zeros = jnp.zeros((K, 1), jnp.int32)
    new_slab, _, _, _ = walk_pass_kernel(
        slab_K,
        jnp.zeros((K, 3), bool), jnp.zeros((K, 3), jnp.int32),
        jnp.zeros((K, 3), jnp.int32), jnp.zeros((K, 3, D), jnp.int32),
        jnp.zeros((K, 3), jnp.int32), jnp.zeros((K, 3), bool),
        jnp.zeros((K, 3), bool),
        max_walk=W, out_base=2, out_rows=1, interpret=True,
        put_ops=put_ops, ev_off=jnp.full((K,), ev_off, jnp.int32),
    )
    for i in range(n_distinct):
        for rep in (0, reps - 1):
            lane = rep * n_distinct + i
            got = jax.tree_util.tree_map(lambda x: x[lane], new_slab)
            assert_slab_equal(seq[i], got, f"lane={lane}")
