"""Durable journal: framing, torn-tail repair, native/Python interop, and
process-crash resume through the supervisor."""

import pickle

import numpy as np
import pytest

import engine_scenarios as sc
from kafkastreams_cep_tpu import native
from kafkastreams_cep_tpu.native.journal import Journal
from kafkastreams_cep_tpu.runtime.supervisor import Supervisor
from kafkastreams_cep_tpu.runtime.processor import Record


def _both_paths():
    yield "numpy", False
    if native.available():
        yield "native", True


def _with_path(use_native, fn):
    saved = native._lib
    try:
        if not use_native:
            native._lib = None
        return fn()
    finally:
        native._lib = saved


PAYLOADS = [b"alpha", b"", b"x" * 5000, pickle.dumps({"k": [1, 2, 3]})]


@pytest.mark.parametrize("label,use_native", list(_both_paths()))
def test_append_replay_round_trip(label, use_native, tmp_path):
    j = Journal(str(tmp_path / "j.log"))
    _with_path(use_native, lambda: [j.append(p) for p in PAYLOADS])
    got = _with_path(use_native, lambda: list(j.replay()))
    assert got == PAYLOADS


@pytest.mark.parametrize("wr,rd", [(False, True), (True, False)])
def test_native_python_interop(wr, rd, tmp_path):
    if not native.available():
        pytest.skip("native library unavailable")
    j = Journal(str(tmp_path / "j.log"))
    _with_path(wr, lambda: [j.append(p) for p in PAYLOADS])
    got = _with_path(rd, lambda: list(j.replay()))
    assert got == PAYLOADS


@pytest.mark.parametrize("label,use_native", list(_both_paths()))
def test_torn_tail_is_truncated(label, use_native, tmp_path):
    path = tmp_path / "j.log"
    j = Journal(str(path))
    _with_path(use_native, lambda: [j.append(p) for p in PAYLOADS])
    intact_size = path.stat().st_size
    # Simulate a crash mid-append: a partial frame at the tail.
    with open(path, "ab") as f:
        f.write(b"\x31\x50\x45\x43\xff\xff")  # magic + garbage length
    got = _with_path(use_native, lambda: list(j.replay()))
    assert got == PAYLOADS
    assert path.stat().st_size == intact_size  # repaired
    # Appends after repair land on a clean boundary.
    _with_path(use_native, lambda: j.append(b"after"))
    assert _with_path(use_native, lambda: list(j.replay())) == PAYLOADS + [b"after"]


@pytest.mark.parametrize("label,use_native", list(_both_paths()))
def test_corrupt_middle_frame_stops_replay(label, use_native, tmp_path):
    path = tmp_path / "j.log"
    j = Journal(str(path))
    _with_path(use_native, lambda: [j.append(b"one"), j.append(b"twoo")])
    data = bytearray(path.read_bytes())
    data[12] ^= 0xFF  # flip a payload byte of frame 1
    path.write_bytes(bytes(data))
    got = _with_path(use_native, lambda: list(j.replay(repair=False)))
    assert got == []  # first frame corrupt -> nothing after it is trusted


def test_truncate_and_missing_file(tmp_path):
    j = Journal(str(tmp_path / "j.log"))
    assert list(j.replay()) == []  # missing file is an empty journal
    j.append(b"a")
    j.truncate()
    assert list(j.replay()) == []


def test_fresh_supervisor_truncates_stale_journal(tmp_path):
    """Starting over an old journal abandons its history (a later resume
    must never replay a previous incarnation's frames into fresh state)."""
    import os

    jl = str(tmp_path / "j.jnl")
    sup = Supervisor(
        sc.strict3(), 1, sc.default_config(),
        journal_path=jl, checkpoint_path=str(tmp_path / "c.ckpt"),
    )
    sup.process([Record("k", 1, 1000, offset=0)])
    assert os.path.getsize(jl) > 0
    Supervisor(
        sc.strict3(), 1, sc.default_config(),
        journal_path=jl, checkpoint_path=str(tmp_path / "c2.ckpt"),
    )
    assert os.path.getsize(jl) == 0


def test_fresh_supervisor_removes_stale_checkpoint(tmp_path):
    """Starting fresh must abandon the old checkpoint too — otherwise a
    later resume() restores the previous incarnation's state and skips the
    new run's journal frames (their seqs fall below the old snapshot's)."""
    import os

    ck = str(tmp_path / "c.ckpt")
    jl = str(tmp_path / "j.jnl")
    sup = Supervisor(
        sc.strict3(), 1, sc.default_config(),
        checkpoint_path=ck, journal_path=jl, checkpoint_every=1,
    )
    sup.process([Record("k", 1, 1000, offset=0)])
    assert os.path.exists(ck)
    fresh = Supervisor(
        sc.strict3(), 1, sc.default_config(),
        checkpoint_path=ck, journal_path=jl, checkpoint_every=100,
    )
    assert not os.path.exists(ck)
    fresh.process([Record("k", 2, 2000, offset=0)])
    resumed = Supervisor.resume(
        sc.strict3(), 1, sc.default_config(),
        checkpoint_path=ck, journal_path=jl,
    )
    # The resumed instance carries the FRESH run's single batch.
    assert resumed._seq == 1


def test_failed_append_rolls_back_torn_frame(tmp_path, monkeypatch):
    """An append that fails mid-write must not leave a torn frame that
    orphans every later successful frame at replay time."""
    import os

    path = tmp_path / "j.log"
    j = Journal(str(path), sync=True)
    _with_path(False, lambda: j.append(b"good-1"))

    real_fsync = os.fsync
    calls = {"n": 0}

    def flaky_fsync(fd):
        calls["n"] += 1
        if calls["n"] == 1:
            raise OSError(28, "No space left on device")
        return real_fsync(fd)

    monkeypatch.setattr(os, "fsync", flaky_fsync)
    with pytest.raises(OSError):
        _with_path(False, lambda: j.append(b"failed"))
    monkeypatch.setattr(os, "fsync", real_fsync)

    _with_path(False, lambda: j.append(b"good-2"))
    got = _with_path(False, lambda: list(j.replay()))
    assert got == [b"good-1", b"good-2"]


def test_resume_skips_frames_already_in_snapshot(tmp_path):
    """A crash between snapshotting and journal truncation leaves the
    journal holding frames the checkpoint already contains; resume must
    skip them (sequence numbers), not double-ingest."""
    ck = str(tmp_path / "state.ckpt")
    jl = str(tmp_path / "records.jnl")
    sup = Supervisor(
        sc.strict3(), 1, sc.default_config(),
        checkpoint_path=ck, journal_path=jl, checkpoint_every=100,
    )
    vals = np.random.default_rng(9).integers(0, 5, size=12)
    for i in range(3):
        sup.process(
            [Record("k", int(v), 1000 + j, offset=None)
             for j, v in enumerate(vals[i * 4:(i + 1) * 4])]
        )
    # Snapshot succeeds but the "crash" hits before truncate(): rebuild the
    # journal file content as it was pre-checkpoint.
    journal_bytes = open(jl, "rb").read()
    sup.checkpoint()
    with open(jl, "wb") as f:
        f.write(journal_bytes)  # truncation "lost" in the crash
    state_before = sup.processor.state

    resumed = Supervisor.resume(
        sc.strict3(), 1, sc.default_config(),
        checkpoint_path=ck, journal_path=jl, checkpoint_every=100,
    )
    import jax

    for a, b in zip(
        jax.tree_util.tree_leaves(state_before),
        jax.tree_util.tree_leaves(resumed.processor.state),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_supervisor_resume_after_process_crash(tmp_path):
    """Kill-and-resume: a fresh Supervisor.resume from the on-disk
    checkpoint + journal must land in the crashed instance's exact state."""
    ck = str(tmp_path / "state.ckpt")
    jl = str(tmp_path / "records.jnl")

    def records(lo, hi):
        return [
            Record("k", int(v), 1000 + i, offset=i)
            for i, v in enumerate(
                np.random.default_rng(5).integers(0, 5, size=hi), start=0
            )
        ][lo:hi]

    sup = Supervisor(
        sc.strict3(), 1, sc.default_config(),
        checkpoint_path=ck, journal_path=jl, checkpoint_every=2,
    )
    all_matches = []
    for i in range(5):  # checkpoint after batches 2 and 4; journal holds 5th
        all_matches.extend(sup.process(records(i * 4, (i + 1) * 4)))
    state_before = sup.processor.state

    # "Crash": drop the supervisor, resume from disk in a new instance.
    resumed = Supervisor.resume(
        sc.strict3(), 1, sc.default_config(),
        checkpoint_path=ck, journal_path=jl, checkpoint_every=2,
    )
    for a, b in zip(
        __import__("jax").tree_util.tree_leaves(state_before),
        __import__("jax").tree_util.tree_leaves(resumed.processor.state),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    # Both continue identically on the next batch.
    nxt = records(20, 24)
    m1 = sup.process(list(nxt))
    m2 = resumed.process(list(nxt))
    assert [
        (k, sorted((n, tuple(e.offset for e in evs)) for n, evs in s.as_map().items()))
        for k, s in m1
    ] == [
        (k, sorted((n, tuple(e.offset for e in evs)) for n, evs in s.as_map().items()))
        for k, s in m2
    ]
