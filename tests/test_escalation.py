"""Elastic capacity escalation (Supervisor auto_escalate + sizing.escalate).

The contract under test (ISSUE 2 acceptance): on an adversarial trace
that overflows the seed config, an auto-escalating supervisor finishes
with **all loss counters zero** and a match stream **identical to a
fresh run at the final (wide) config** — the tripped batch is rolled
back to its pre-loss state, migrated wider, and re-processed, so the
branches a fixed-shape engine would have dropped are recovered, not
warned about.
"""

import dataclasses

import numpy as np
import pytest

import engine_scenarios as sc
from kafkastreams_cep_tpu.engine import (
    EngineConfig,
    EscalationPolicy,
    capacity_counters,
    escalate,
)
from kafkastreams_cep_tpu.runtime import Record, Supervisor

SEED_CFG = EngineConfig(
    max_runs=4, slab_entries=16, slab_preds=2, dewey_depth=8, max_walk=8
)
CEILING = EngineConfig(
    max_runs=64, slab_entries=128, slab_preds=16, dewey_depth=32, max_walk=32
)


def storm_batches(n_cycles=5):
    """skip_till_any branch storm: run count and pointer lists grow
    geometrically — overflows max_runs=4 within two cycles."""
    values = [sc.A, sc.B] + [sc.C, sc.D] * n_cycles
    return [
        [Record("k", v, 1000 + i, offset=i)] for i, v in enumerate(values)
    ]


def canon_stream(matches):
    return [(k, sc.canon(seq)) for k, seq in matches]


# -- policy unit behavior ----------------------------------------------------


def test_escalate_grows_tripped_dims_only():
    pol = EscalationPolicy(max_config=CEILING)
    out = escalate(SEED_CFG, {"run_drops": 3, "slab_pred_drops": 1}, pol)
    assert out.max_runs == 8 and out.slab_preds == 8  # rounded to tile
    assert out.slab_entries == SEED_CFG.slab_entries
    assert out.dewey_depth == SEED_CFG.dewey_depth


def test_escalate_respects_ceiling_and_exhausts():
    pol = EscalationPolicy(max_config=SEED_CFG)  # ceiling == current
    assert escalate(SEED_CFG, {"run_drops": 5}, pol) is None
    pol2 = EscalationPolicy(
        max_config=dataclasses.replace(SEED_CFG, max_runs=8)
    )
    out = escalate(SEED_CFG, {"run_drops": 5, "slab_trunc": 2}, pol2)
    assert out.max_runs == 8  # clamped
    assert out.max_walk == SEED_CFG.max_walk  # its ceiling: unchanged


def test_escalate_growth_factor():
    pol = EscalationPolicy(growth=4.0, max_config=CEILING)
    out = escalate(SEED_CFG, {"run_drops": 1}, pol)
    assert out.max_runs == 16


# -- end-to-end: the acceptance criterion ------------------------------------


def test_escalation_recovers_all_dropped_branches(tmp_path):
    """The headline property: lossy seed config + auto_escalate ends with
    zero loss counters and the exact match stream of a fresh wide run."""
    batches = storm_batches(5)
    sup = Supervisor(
        sc.skip_till_any(), 1, SEED_CFG,
        checkpoint_path=str(tmp_path / "esc.ckpt"),
        journal_path=str(tmp_path / "esc.jrnl"),
        checkpoint_every=3,
        auto_escalate=EscalationPolicy(max_config=CEILING),
        gc_interval=0,
    )
    got = []
    for b in batches:
        got += sup.process(b)
    assert sup.escalations >= 1
    final_counters = capacity_counters(sup.processor.counters())
    assert not any(final_counters.values()), final_counters

    final_cfg = sup.processor.batch.matcher.config
    ref = Supervisor(
        sc.skip_till_any(), 1, final_cfg,
        checkpoint_path=str(tmp_path / "ref.ckpt"),
        checkpoint_every=3, gc_interval=0,
    )
    want = []
    for b in batches:
        want += ref.process(b)
    assert canon_stream(got) == canon_stream(want)
    assert not any(capacity_counters(ref.processor.counters()).values())


def test_escalation_pins_wide_config_for_resume(tmp_path):
    """The post-escalation snapshot records the wide config, so a process
    crash right after an escalation resumes at the new width (replaying
    the old-width snapshot would re-drop the recovered branches)."""
    batches = storm_batches(4)
    ck, jr = str(tmp_path / "p.ckpt"), str(tmp_path / "p.jrnl")
    sup = Supervisor(
        sc.skip_till_any(), 1, SEED_CFG,
        checkpoint_path=ck, journal_path=jr, checkpoint_every=100,
        auto_escalate=EscalationPolicy(max_config=CEILING), gc_interval=0,
    )
    for b in batches:
        sup.process(b)
    assert sup.escalations >= 1
    wide = sup.processor.batch.matcher.config
    del sup  # crash
    res = Supervisor.resume(
        sc.skip_till_any(), 1, SEED_CFG, checkpoint_path=ck,
        journal_path=jr,
        auto_escalate=EscalationPolicy(max_config=CEILING), gc_interval=0,
    )
    assert res.processor.batch.matcher.config == wide
    assert not any(capacity_counters(res.processor.counters()).values())


def test_hysteresis_tolerates_trips_before_escalating(tmp_path):
    """hysteresis=2: the first tripping batch is warned (loss stands),
    the second consecutive trip escalates."""
    batches = storm_batches(5)
    sup = Supervisor(
        sc.skip_till_any(), 1, SEED_CFG,
        checkpoint_path=str(tmp_path / "h.ckpt"), checkpoint_every=100,
        auto_escalate=EscalationPolicy(max_config=CEILING, hysteresis=2),
        gc_interval=0,
    )
    trips_seen = 0
    for b in batches:
        before = sup.escalations
        sup.process(b)
        if sup._trip_streak == 1 and sup.escalations == before:
            trips_seen += 1  # a tolerated first trip
    assert sup.escalations >= 1  # eventually escalated
    assert trips_seen >= 1  # but at least one trip was tolerated first


def test_exhausted_escalation_degrades_to_warning(tmp_path):
    """At the policy ceiling the supervisor keeps the historical behavior:
    count, warn via health, stay alive."""
    sup = Supervisor(
        sc.skip_till_any(), 1, SEED_CFG,
        checkpoint_path=str(tmp_path / "x.ckpt"), checkpoint_every=100,
        auto_escalate=EscalationPolicy(max_config=SEED_CFG),  # no headroom
        gc_interval=0,
    )
    for b in storm_batches(4):
        sup.process(b)
    assert sup.escalations == 0
    assert sup.processor.counters()["run_drops"] > 0
    report = sup.health()
    assert report.healthy and report.warnings  # lossy, not corrupt
    # Still live: a fresh trace still matches.
    out = []
    for i, v in enumerate([sc.A, sc.B, sc.C, sc.D]):
        out += sup.process([Record("k", v, 9000 + i, offset=100 + i)])
    assert len(out) >= 1


def test_escalation_in_pipeline_mode_loses_no_matches(tmp_path):
    """Pipeline mode: the lossy batch's rollback must preserve the
    previous batch's (clean, already-decoded) matches and return the
    recovered batch's matches synchronously via a flush."""
    batches = storm_batches(5)
    sup = Supervisor(
        sc.skip_till_any(), 1, SEED_CFG,
        checkpoint_path=str(tmp_path / "pl.ckpt"), checkpoint_every=100,
        auto_escalate=EscalationPolicy(max_config=CEILING),
        pipeline=True, gc_interval=0,
    )
    got = []
    for b in batches:
        got += sup.process(b)
    got += sup.checkpoint()  # drain the pipeline tail
    assert sup.escalations >= 1
    final_cfg = sup.processor.batch.matcher.config
    ref = Supervisor(
        sc.skip_till_any(), 1, final_cfg,
        checkpoint_path=str(tmp_path / "plr.ckpt"), checkpoint_every=100,
        gc_interval=0,
    )
    want = []
    for b in batches:
        want += ref.process(b)
    assert sorted(map(repr, canon_stream(got))) == sorted(
        map(repr, canon_stream(want))
    )


def test_escalation_counts_in_metrics(tmp_path):
    sup = Supervisor(
        sc.skip_till_any(), 1, SEED_CFG,
        checkpoint_path=str(tmp_path / "m.ckpt"), checkpoint_every=100,
        auto_escalate=EscalationPolicy(max_config=CEILING), gc_interval=0,
    )
    for b in storm_batches(4):
        sup.process(b)
    snap = sup.metrics_snapshot()
    assert snap["escalations"] == sup.escalations >= 1
