"""Dense transition-table goldens for the five conformance scenarios.

Each test hand-derives the exact arrays the lowering must emit from the
``StatesFactory`` semantics (see ``compiler/stages.py`` goldens); the array
engine consumes these tables, so their shape is load-bearing.
"""

import numpy as np

from kafkastreams_cep_tpu import Query
from helpers import value_is
from kafkastreams_cep_tpu.compiler.tables import (
    OP_BEGIN,
    OP_NONE,
    OP_TAKE,
    TYPE_BEGIN,
    TYPE_FINAL,
    TYPE_NORMAL,
    lower,
)


def strict_three_stage():
    return (
        Query()
        .select("first").where(value_is("A"))
        .then()
        .select("second").where(value_is("B"))
        .then()
        .select("latest").where(value_is("C"))
        .build()
    )


def test_strict_three_stage_tables():
    t = lower(strict_three_stage())
    assert t.names == ["first", "second", "latest", "$final"]
    assert t.types.tolist() == [TYPE_BEGIN, TYPE_NORMAL, TYPE_NORMAL, TYPE_FINAL]
    assert t.ident.tolist() == [0, 1, 2, 3]
    assert t.consume_op.tolist() == [OP_BEGIN, OP_BEGIN, OP_BEGIN, OP_NONE]
    assert t.consume_pred.tolist() == [0, 1, 2, -1]
    assert t.consume_target.tolist() == [1, 2, 3, -1]
    assert t.ignore_pred.tolist() == [-1, -1, -1, -1]
    assert t.proceed_pred.tolist() == [-1, -1, -1, -1]
    assert t.begin_pos == 0 and t.final_pos == 3
    assert t.max_hops == 1
    assert not t.can_branch
    assert t.is_strict_seq()
    assert t.num_predicates == 3 and t.num_states == 0


def test_one_or_more_tables():
    query = (
        Query()
        .select("a").where(value_is("A"))
        .then()
        .select("b").one_or_more().where(value_is("B"))
        .then()
        .select("c").where(value_is("C"))
        .build()
    )
    t = lower(query)
    # The Kleene loop stage is edge-only in the compiled list but must get a
    # position: [a, b(mandatory), b(loop), c, $final].
    assert t.names == ["a", "b", "b", "c", "$final"]
    assert t.types.tolist() == [TYPE_BEGIN, TYPE_NORMAL, TYPE_NORMAL, TYPE_NORMAL, TYPE_FINAL]
    # mandatory and loop stage share the (name, type) identity.
    assert t.ident.tolist() == [0, 1, 1, 3, 4]
    assert t.consume_op.tolist() == [OP_BEGIN, OP_BEGIN, OP_TAKE, OP_BEGIN, OP_NONE]
    # TAKE successors self-loop: consume_target is the stage's own position.
    assert t.consume_target.tolist() == [1, 2, 2, 4, -1]
    # The mandatory BEGIN edge and the loop TAKE edge share one predicate object.
    assert t.consume_pred.tolist() == [0, 1, 1, 3, -1]
    assert t.proceed_pred.tolist() == [-1, -1, 2, -1, -1]
    assert t.proceed_target.tolist() == [-1, -1, 3, -1, -1]
    assert t.ignore_pred.tolist() == [-1, -1, -1, -1, -1]
    assert t.max_hops == 2  # loop -> c
    assert t.can_branch  # TAKE+PROCEED at the loop stage
    assert not t.is_strict_seq()


def test_skip_till_next_tables():
    query = (
        Query()
        .select("first").where(value_is("A"))
        .then()
        .select("second").skip_till_next_match().where(value_is("C"))
        .then()
        .select("latest").skip_till_next_match().where(value_is("D"))
        .build()
    )
    t = lower(query)
    assert t.names == ["first", "second", "latest", "$final"]
    assert t.consume_op.tolist() == [OP_BEGIN, OP_BEGIN, OP_BEGIN, OP_NONE]
    # Predicate ids in first-use order: A, C, not(C), D, not(D).
    assert t.consume_pred.tolist() == [0, 1, 3, -1]
    assert t.ignore_pred.tolist() == [-1, 2, 4, -1]
    assert [t.predicates[i].label for i in (2, 4)] == ["not(<lambda>)", "not(<lambda>)"]
    assert t.proceed_pred.tolist() == [-1, -1, -1, -1]
    assert t.max_hops == 1
    assert t.can_branch


def test_skip_till_any_tables():
    query = (
        Query()
        .select("first").where(value_is("A"))
        .then()
        .select("second").where(value_is("B"))
        .then()
        .select("three").skip_till_any_match().where(value_is("C"))
        .then()
        .select("latest").skip_till_any_match().where(value_is("D"))
        .build()
    )
    t = lower(query)
    assert t.names == ["first", "second", "three", "latest", "$final"]
    assert t.consume_op.tolist() == [OP_BEGIN] * 4 + [OP_NONE]
    assert t.consume_pred.tolist() == [0, 1, 2, 4, -1]
    # skip_till_any IGNORE guards are always-true matchers (distinct objects).
    assert t.ignore_pred.tolist() == [-1, -1, 3, 5, -1]
    assert t.predicates[3].label == "true" and t.predicates[5].label == "true"
    assert t.can_branch


def test_stock_query_tables():
    query = (
        Query()
        .select()
        .where(lambda k, v, ts, store: v["volume"] > 1000)
        .fold("avg", lambda k, v, curr: v["price"])
        .then()
        .select()
        .zero_or_more()
        .skip_till_next_match()
        .where(lambda k, v, ts, store: v["price"] > store.get("avg"))
        .fold("avg", lambda k, v, curr: (curr + v["price"]) // 2)
        .fold("volume", lambda k, v, curr: v["volume"])
        .then()
        .select()
        .skip_till_next_match()
        .where(lambda k, v, ts, store: v["volume"] < 0.8 * store.get_or_else("volume", 0))
        .within(1, "h")
        .build()
    )
    t = lower(query)
    # Unnamed stages default to level numbers (Pattern.java:160-162).
    assert t.names == ["0", "1", "2", "$final"]
    assert t.types.tolist() == [TYPE_BEGIN, TYPE_NORMAL, TYPE_NORMAL, TYPE_FINAL]
    # zero_or_more compiles to TAKE with no mandatory state (OPTIONAL quirk).
    assert t.consume_op.tolist() == [OP_BEGIN, OP_TAKE, OP_BEGIN, OP_NONE]
    assert t.consume_target.tolist() == [1, 1, 3, -1]
    # Predicates: p0, take1, not(take1), proceed-guard, p2, not(p2).
    assert t.consume_pred.tolist() == [0, 1, 4, -1]
    assert t.ignore_pred.tolist() == [-1, 2, 5, -1]
    assert t.proceed_pred.tolist() == [-1, 3, -1, -1]
    assert t.proceed_target.tolist() == [-1, 2, -1, -1]
    # Window: stage 2 declares 1h; stage 1 inherits from its successor
    # pattern; stage 0's successor pattern declares none -> -1.
    assert t.window_ms.tolist() == [-1, 3_600_000, 3_600_000, -1]
    # Fold state: avg first (stage 0), then volume (stage 1).
    assert t.state_names == ["avg", "volume"]
    assert [(a.stage, a.state, a.name) for a in t.aggs] == [
        (0, 0, "avg"),
        (1, 0, "avg"),
        (1, 1, "volume"),
    ]
    mask = t.agg_masks()
    assert mask.shape == (3, 4)
    assert mask[:, 0].tolist() == [True, False, False]
    assert mask[:, 1].tolist() == [False, True, True]
    assert t.max_hops == 2
    assert t.can_branch
    assert not t.is_strict_seq()


def test_one_or_more_multiple_kleene_hops():
    # Two consecutive Kleene stages chain PROCEED edges: max_hops grows.
    query = (
        Query()
        .select("a").where(value_is("A"))
        .then()
        .select("b").one_or_more().where(value_is("B"))
        .then()
        .select("c").one_or_more().where(value_is("C"))
        .then()
        .select("d").where(value_is("D"))
        .build()
    )
    t = lower(query)
    assert t.names == ["a", "b", "b", "c", "c", "d", "$final"]
    assert t.ident.tolist() == [0, 1, 1, 3, 3, 5, 6]
    # b-loop PROCEED -> c-mandatory (BEGIN, no proceed) => 2 frames;
    # c-loop PROCEED -> d => 2 frames.
    assert t.max_hops == 2
