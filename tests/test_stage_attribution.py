"""Per-stage selectivity & cost attribution (EngineConfig.stage_attribution).

The continuous-profiling contract (ISSUE 6):

1. *Bit-exact across paths*: the per-stage tallies (``stage_counts``) and
   per-stage walk-hop costs (``SlabState.stage_hops``) agree exactly
   between the jnp engine, the per-step walk kernel, and the whole-scan
   kernel on a pressured trace.
2. *Placement-free*: attribution never changes emissions or any drop
   counter.
3. *Zero device work when off*: every attribution array has zero size.
4. *Conservation*: stage-hop totals equal the walk-class hop totals
   (every hop attributed exactly once), and per-stage tallies obey
   accepts/ignores/rejects <= evals.
5. *Mergeability* (satellite): ShardedMatcher's psum-merge and CEPBank's
   member-merge stay associative with the new counters included.

All kernel runs use interpret mode (CPU CI checks parity, not perf).
"""

import dataclasses
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kafkastreams_cep_tpu.engine import EngineConfig, EventBatch
from kafkastreams_cep_tpu.engine.matcher import (
    STAGE_TALLY_NAMES,
    stage_counter_arrays,
)
from kafkastreams_cep_tpu.parallel.batch import BatchMatcher

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "examples"))
import stock_demo

ATTR_CFG = EngineConfig(
    max_runs=8, slab_entries=16, slab_hot_entries=8, slab_preds=4,
    dewey_depth=8, max_walk=8, stage_attribution=True,
)


def stock_events(K, T, seed):
    rng = np.random.default_rng(seed)
    prices = rng.integers(90, 131, size=(K, T)).astype(np.int32)
    vols = rng.integers(600, 1101, size=(K, T)).astype(np.int32)
    return EventBatch(
        key=jnp.broadcast_to(jnp.arange(K, dtype=jnp.int32)[:, None], (K, T)),
        value={"price": jnp.asarray(prices), "volume": jnp.asarray(vols)},
        ts=jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32)[None, :] * 2, (K, T)),
        off=jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32)[None, :], (K, T)),
        valid=jnp.ones((K, T), bool),
    )


def _attr_equal(st_a, st_b):
    np.testing.assert_array_equal(
        np.asarray(st_a.stage_counts), np.asarray(st_b.stage_counts),
        err_msg="stage_counts",
    )
    np.testing.assert_array_equal(
        np.asarray(st_a.slab.stage_hops), np.asarray(st_b.slab.stage_hops),
        err_msg="stage_hops",
    )


def test_disabled_attribution_is_zero_size():
    cfg = dataclasses.replace(ATTR_CFG, stage_attribution=False)
    m = BatchMatcher(stock_demo.stock_pattern(), 4, cfg)
    st = m.init_state()
    assert st.stage_counts.shape == (4, 4, 0)
    assert st.slab.stage_hops.shape == (4, 0)
    assert m.stage_counters(st) == {}
    assert m.matcher.stage_counters(st) == {}


def test_attribution_invariants_and_never_changes_matching():
    K, T = 8, 24
    events = stock_events(K, T, 5)
    os.environ["CEP_WALK_KERNEL"] = "0"
    off = BatchMatcher(
        stock_demo.stock_pattern(), K,
        dataclasses.replace(ATTR_CFG, stage_attribution=False),
    )
    on = BatchMatcher(stock_demo.stock_pattern(), K, ATTR_CFG)
    st0, out0 = off.scan(off.init_state(), events)
    st, out1 = on.scan(on.init_state(), events)
    for f in ("count", "stage", "off"):
        np.testing.assert_array_equal(
            np.asarray(getattr(out0, f)), np.asarray(getattr(out1, f)),
            err_msg=f,
        )
    assert off.counters(st0) == on.counters(st)
    assert off.hot_counters(st0) == on.hot_counters(st)

    arrays = stage_counter_arrays(st)
    assert set(arrays) == set(STAGE_TALLY_NAMES) | {"stage_walk_hops"}
    ev = arrays["stage_evals"]
    for k in ("stage_accepts", "stage_ignores", "stage_rejects"):
        assert (arrays[k] <= ev).all(), k
    assert ev.sum() > 0
    # Every walk hop attributed exactly once: per-stage totals equal the
    # class totals (walk + extract + drain).
    wc = on.walk_counters(st)
    assert int(arrays["stage_walk_hops"].sum()) == sum(wc.values())
    # The roll-up publishes a selectivity per stage.
    report = on.stage_counters(st)
    assert all("selectivity" in row for row in report.values())


def test_walk_kernel_attribution_parity():
    K, T = 128, 12
    events = stock_events(K, T, 21)
    os.environ["CEP_WALK_KERNEL"] = "0"
    ref = BatchMatcher(stock_demo.stock_pattern(), K, ATTR_CFG)
    st_r, out_r = ref.scan(ref.init_state(), events)
    os.environ["CEP_WALK_KERNEL"] = "interpret"
    try:
        krn = BatchMatcher(stock_demo.stock_pattern(), K, ATTR_CFG)
        assert krn.uses_walk_kernel
        st_k, out_k = krn.scan(krn.init_state(), events)
    finally:
        os.environ["CEP_WALK_KERNEL"] = "0"
    np.testing.assert_array_equal(
        np.asarray(out_r.count), np.asarray(out_k.count)
    )
    _attr_equal(st_r, st_k)
    assert int(np.asarray(st_r.slab.stage_hops).sum()) > 0


@pytest.mark.slow
def test_scan_kernel_attribution_parity():
    # Tier-2 (-m slow, ~12 s interpret): the walk-kernel parity above
    # keeps kernel attribution in tier-1 (ROADMAP tier-1 budget note,
    # PR 13).
    from kafkastreams_cep_tpu.compiler.tables import lower
    from kafkastreams_cep_tpu.ops.scan_kernel import build_scan

    K, T = 128, 8
    events = stock_events(K, T, 31)
    os.environ["CEP_WALK_KERNEL"] = "0"
    ref = BatchMatcher(stock_demo.stock_pattern(), K, ATTR_CFG)
    scan = build_scan(lower(stock_demo.stock_pattern()), ATTR_CFG)
    scan.interpret = True
    st_r, out_r = ref.scan(ref.init_state(), events)
    st_k, out_k = scan(ref.init_state(), events)
    np.testing.assert_array_equal(
        np.asarray(out_r.count), np.asarray(out_k.count)
    )
    _attr_equal(st_r, st_k)
    assert ref.counters(st_r) == ref.counters(st_k)


def test_lazy_drain_hops_are_attributed():
    K, T = 8, 24
    events = stock_events(K, T, 11)
    os.environ["CEP_WALK_KERNEL"] = "0"
    cfg = dataclasses.replace(
        ATTR_CFG, lazy_extraction=True, handle_ring=64,
        slab_entries=32, slab_hot_entries=8,
    )
    m = BatchMatcher(stock_demo.stock_pattern(), K, cfg)
    st, _ = m.scan(m.init_state(), events)
    st, drained = m.drain(st)
    arrays = stage_counter_arrays(st)
    wc = m.walk_counters(st)
    assert wc["drain_hops"] > 0
    assert int(arrays["stage_walk_hops"].sum()) == sum(wc.values())


def test_checkpoint_and_widen_roundtrip_with_attribution(tmp_path):
    from kafkastreams_cep_tpu.runtime import CEPProcessor, Record, checkpoint
    from kafkastreams_cep_tpu.runtime.migrate import (
        check_widens,
        widen_state,
    )

    os.environ["CEP_WALK_KERNEL"] = "0"
    proc = CEPProcessor(stock_demo.stock_pattern(), 4, ATTR_CFG, epoch=0)
    rng = np.random.default_rng(3)
    recs = [
        Record(int(k), {"price": int(p), "volume": int(v)}, i)
        for i, (k, p, v) in enumerate(
            zip(rng.integers(0, 4, 48), rng.integers(90, 131, 48),
                rng.integers(600, 1101, 48))
        )
    ]
    proc.process(recs)
    path = str(tmp_path / "a.ckpt")
    checkpoint.save_checkpoint(proc, path)
    proc2 = checkpoint.restore_processor(stock_demo.stock_pattern(), path)
    _attr_equal(proc.state, proc2.state)

    wide = dataclasses.replace(
        ATTR_CFG, max_runs=16, slab_entries=24, slab_hot_entries=8
    )
    widened = widen_state(proc.state, ATTR_CFG, wide)
    np.testing.assert_array_equal(
        np.asarray(proc.state.stage_counts), widened.stage_counts
    )
    np.testing.assert_array_equal(
        np.asarray(proc.state.slab.stage_hops), widened.slab.stage_hops
    )
    # Flipping attribution is a shape change with no live embedding.
    with pytest.raises(ValueError, match="stage_attribution"):
        check_widens(
            ATTR_CFG,
            dataclasses.replace(wide, stage_attribution=False),
        )


# ---------------------------------------------------------------------------
# Merge paths (satellite): psum-merge and member-merge stay associative
# with the per-stage / per-key counters included.
# ---------------------------------------------------------------------------


def test_sharded_psum_merge_matches_lane_sum():
    from kafkastreams_cep_tpu.parallel import ShardedMatcher, key_mesh

    if len(jax.devices()) < 2:
        pytest.skip("needs the virtual multi-device mesh")
    K, T = 8, 24
    events = stock_events(K, T, 13)
    os.environ["CEP_WALK_KERNEL"] = "0"
    mesh = key_mesh(jax.devices()[:4])
    sharded = ShardedMatcher(stock_demo.stock_pattern(), K, mesh, ATTR_CFG)
    st, _ = sharded.scan(
        sharded.init_state(), sharded.shard_events(events)
    )
    # The psum-merged roll-up must equal the host-side per-lane sum — the
    # merge is integer addition over disjoint lane blocks, so any shard
    # grouping gives the same totals (associativity).
    merged = sharded.stage_counters(st)
    host = {}
    arrays = stage_counter_arrays(st)
    from kafkastreams_cep_tpu.engine.matcher import stage_report

    host = stage_report(arrays, sharded.names)
    assert merged == host
    assert any(row["stage_evals"] for row in merged.values())
    snap = sharded.metrics_snapshot(st)
    assert snap["per_stage"] == merged


def test_bank_member_merge_is_associative():
    from kafkastreams_cep_tpu.runtime import Record
    from kafkastreams_cep_tpu.runtime.bank import CEPBank

    os.environ["CEP_WALK_KERNEL"] = "0"
    bank = CEPBank(
        {"a": stock_demo.stock_pattern(), "b": stock_demo.stock_pattern()},
        4, ATTR_CFG, epoch=0,
    )
    rng = np.random.default_rng(17)
    recs = [
        Record(int(k), {"price": int(p), "volume": int(v)}, i)
        for i, (k, p, v) in enumerate(
            zip(rng.integers(0, 4, 40), rng.integers(90, 131, 40),
                rng.integers(600, 1101, 40))
        )
    ]
    bank.process(recs)
    snap = bank.metrics_snapshot()
    members = [
        p.batch.stage_counters(p.state) for p in bank.processors.values()
    ]
    for stage, row in snap["per_stage"].items():
        for metric in ("stage_evals", "stage_accepts", "stage_walk_hops"):
            assert row[metric] == sum(m[stage][metric] for m in members), (
                stage, metric,
            )
    # Associativity of the underlying registry merge with the new
    # counters present: (a ⊕ b) equals (b ⊕ a) on every counter.
    procs = list(bank.processors.values())
    ab = procs[0].metrics.registry.merge(procs[1].metrics.registry)
    ba = procs[1].metrics.registry.merge(procs[0].metrics.registry)
    a_snap, b_snap = ab.snapshot(), ba.snapshot()
    assert {
        k: v for k, v in a_snap.items() if not isinstance(v, dict)
    } == {k: v for k, v in b_snap.items() if not isinstance(v, dict)}


def test_per_key_heavy_hitters():
    from kafkastreams_cep_tpu.runtime import CEPProcessor, Record

    os.environ["CEP_WALK_KERNEL"] = "0"
    proc = CEPProcessor(stock_demo.stock_pattern(), 4, ATTR_CFG, epoch=0)
    rng = np.random.default_rng(23)
    # Key "hot" gets 10x the traffic of the others — it must rank first.
    recs = []
    t = 0
    for _ in range(200):
        key = "hot" if rng.random() < 0.7 else f"cold{rng.integers(3)}"
        recs.append(
            Record(
                key,
                {"price": int(rng.integers(90, 131)),
                 "volume": int(rng.integers(600, 1101))},
                t,
            )
        )
        t += 1
    proc.process(recs)
    pk = proc.per_key_cost(top_k=4)
    assert pk["total_hops"] > 0
    assert pk["top"] and pk["top"][0]["key"] == "hot"
    assert pk["top"][0]["share"] >= max(e["share"] for e in pk["top"][1:])
    snap = proc.metrics_snapshot()
    assert snap["per_key"]["top"][0]["key"] == "hot"


# ---------------------------------------------------------------------------
# Measured per-conjunct selectivity (ISSUE 16 satellite): under
# stage_attribution every consuming-edge conjunct is tallied marginally
# (unconditioned, order-independent) on device and surfaces through
# stage_counters / metrics_snapshot per_stage.
# ---------------------------------------------------------------------------


def _pricey(k, v, ts, st):
    return v["price"] * 7 % 5 != 2


def _cheap(k, v, ts, st):
    return v["price"] > 110


def _conjunct_stock_pattern():
    from kafkastreams_cep_tpu import Query
    from kafkastreams_cep_tpu.pattern.predicate import and_, hint

    return (
        Query()
        .select("rise")
        .where(and_(hint(_pricey, cost=50.0), hint(_cheap, cost=1.0)))
        .then()
        .select("dip").skip_till_next_match()
        .where(lambda k, v, ts, st: v["price"] < 100)
        .build()
    )


def test_measured_conjunct_tally_is_exact_and_in_snapshot():
    os.environ["CEP_WALK_KERNEL"] = "0"
    pat = _conjunct_stock_pattern()
    K, T = 4, 24
    m = BatchMatcher(pat, K, ATTR_CFG)
    st = m.init_state()
    prices = []
    for seed in (1, 2):
        ev = stock_events(K, T, seed)
        prices.append(np.asarray(ev.value["price"]))
        st, _ = m.scan(st, ev)
    allp = np.concatenate(prices, axis=None).astype(np.int64)
    report = m.stage_counters(st)
    cj = report["rise"]["conjuncts"]
    assert len(cj) == 2 and len(report["dip"]["conjuncts"]) == 1
    by = {
        ("pricey" if "_pricey" in key else "cheap"): row
        for key, row in cj.items()
    }
    # Row 0 of the tally: every conjunct is offered every valid event —
    # the marginal (order-independent) denominator, identical per slot.
    assert all(row["evals"] == allp.size for row in by.values())
    assert by["cheap"]["accepts"] == int((allp > 110).sum())
    assert by["pricey"]["accepts"] == int((allp * 7 % 5 != 2).sum())
    for row in by.values():
        assert row["selectivity"] == pytest.approx(
            row["accepts"] / row["evals"]
        )

    # The processor snapshot carries the same rows under per_stage.
    from kafkastreams_cep_tpu.runtime import CEPProcessor, Record

    proc = CEPProcessor(pat, 4, ATTR_CFG, epoch=0)
    proc.process(
        [
            Record(int(i % 4), {"price": int(p), "volume": 800}, i)
            for i, p in enumerate(
                np.linspace(90, 130, 40).astype(int)
            )
        ]
    )
    snap = proc.metrics_snapshot()
    rows = snap["per_stage"]["rise"]["conjuncts"]
    assert set(rows) == set(cj)
    assert all(row["evals"] == 40 for row in rows.values())
