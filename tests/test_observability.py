"""Flight recorder + observability satellites (ISSUE 6).

Covers: the bounded per-batch flight ring and its JSONL dump schema; the
supervisor dump triggers (chaos crash, recovery, escalation) with batch
correlation ids; the quarantine-burst trigger; TraceSink JSONL rotation;
and the Reporter's atomic cadence write (a crash mid-report — the armed
``"report.write"`` failpoint — never leaves a torn line).
"""

import dataclasses
import json
import os
import sys

import numpy as np
import pytest

import engine_scenarios as sc
from kafkastreams_cep_tpu.engine import EngineConfig, EscalationPolicy
from kafkastreams_cep_tpu.runtime import CEPProcessor, Record, Supervisor
from kafkastreams_cep_tpu.runtime.flight import FlightRecorder, read_dump
from kafkastreams_cep_tpu.runtime.ingest import IngestPolicy
from kafkastreams_cep_tpu.utils.failpoints import FAILPOINTS
from kafkastreams_cep_tpu.utils.telemetry import JsonlTraceSink, Reporter

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "examples"))
import stock_demo

CFG = EngineConfig(
    max_runs=8, slab_entries=16, slab_preds=4, dewey_depth=8, max_walk=8
)


def stock_records(n, seed=0, t0=0, keys=4):
    rng = np.random.default_rng(seed)
    return [
        Record(
            int(rng.integers(0, keys)),
            {"price": int(rng.integers(90, 131)),
             "volume": int(rng.integers(600, 1101))},
            t0 + i,
        )
        for i in range(n)
    ]


# -- the ring -----------------------------------------------------------------


def test_flight_ring_is_bounded_and_dump_schema(tmp_path):
    fr = FlightRecorder(capacity=3, path=str(tmp_path / "fl"))
    proc = CEPProcessor(stock_demo.stock_pattern(), 4, CFG, epoch=0,
                        flight=fr)
    for b in range(5):
        proc.process(stock_records(16, seed=b, t0=b * 100))
    assert len(fr.records) == 3 and fr.dropped == 2
    path = fr.dump("demand", corr="manual-1")
    doc = read_dump(path)
    h = doc["header"]
    assert h["reason"] == "demand" and h["corr"] == "manual-1"
    assert h["records"] == 3 and h["dropped"] == 2
    # Records are the LAST N batches, oldest first, with the processor's
    # batch correlation ids and per-batch (not lifetime) deltas.
    assert [r["seq"] for r in doc["records"]] == [3, 4, 5]
    assert [r["corr"] for r in doc["records"]] == [
        "stream-3", "stream-4", "stream-5"
    ]
    for r in doc["records"]:
        assert r["records_in"] == 16  # the batch's delta, not 80
        assert "phase_seconds" in r and "slab_live" in r
    # Dumping again ships full context again (ring not cleared).
    assert read_dump(fr.dump("demand"))["header"]["records"] == 3


def test_flight_observe_without_path_returns_records():
    fr = FlightRecorder(capacity=8)
    proc = CEPProcessor(stock_demo.stock_pattern(), 2, CFG, epoch=0,
                        flight=fr)
    proc.process(stock_records(8, keys=2))
    out = fr.dump("demand")
    assert isinstance(out, list) and out[0]["type"] == "flight_dump"
    assert out[1]["type"] == "flight_record"


# -- supervisor triggers ------------------------------------------------------


def test_chaos_crash_and_recovery_dump_flight(tmp_path):
    """A device fault mid-stream: the recovery dump ships the last-N
    batch records with correct correlation ids; exhausted retries dump
    with reason=crash before the exception propagates."""
    fr = FlightRecorder(capacity=8, path=str(tmp_path / "fl"))
    sup = Supervisor(
        stock_demo.stock_pattern(), 4, CFG, epoch=0,
        checkpoint_path=str(tmp_path / "c.ckpt"),
        journal_path=str(tmp_path / "c.jrnl"),
        checkpoint_every=100, flight=fr, gc_interval=0,
    )
    for b in range(3):
        sup.process(stock_records(16, seed=b, t0=b * 100))
    with FAILPOINTS.session({"device.result": [0]}):
        sup.process(stock_records(16, seed=9, t0=900))
    assert sup.recoveries == 1
    dumps = [p for p in fr.dump_paths if "-recover-" in p]
    assert len(dumps) == 1
    doc = read_dump(dumps[0])
    assert doc["header"]["reason"] == "recover"
    # The supervisor's corr names the batch that provoked the recovery.
    assert doc["header"]["corr"] == "batch-4"
    # The ring holds the batches before the fault, with processor corrs
    # (the faulted batch itself never completed, so it has no record —
    # the dump runs before the rollback/replay overwrites the tail).
    corrs = [r["corr"] for r in doc["records"]]
    assert corrs == ["stream-1", "stream-2", "stream-3"]

    # Exhausted retries: dump reason=crash, then the exception surfaces.
    # Hits 1-4 are the recovery replay of the 4 journaled batches; hit 5
    # is the retry of the faulted batch — failing it exhausts
    # max_retries=1.
    with FAILPOINTS.session({"device.dispatch": [0, 5]}):
        with pytest.raises(Exception):
            sup.process(stock_records(16, seed=10, t0=1200))
    crash = [p for p in fr.dump_paths if "-crash-" in p]
    assert len(crash) == 1
    assert read_dump(crash[0])["header"]["reason"] == "crash"


def test_escalation_dumps_flight(tmp_path):
    seed_cfg = EngineConfig(
        max_runs=4, slab_entries=16, slab_preds=2, dewey_depth=8, max_walk=8
    )
    ceiling = EngineConfig(
        max_runs=64, slab_entries=128, slab_preds=16, dewey_depth=32,
        max_walk=32,
    )
    fr = FlightRecorder(capacity=8, path=str(tmp_path / "fl"))
    sup = Supervisor(
        sc.skip_till_any(), 1, seed_cfg,
        checkpoint_path=str(tmp_path / "e.ckpt"),
        checkpoint_every=100,
        auto_escalate=EscalationPolicy(max_config=ceiling),
        gc_interval=0, flight=fr,
    )
    values = [sc.A, sc.B] + [sc.C, sc.D] * 3
    for i, v in enumerate(values):
        sup.process([Record("k", v, 1000 + i, offset=i)])
    assert sup.escalations >= 1
    dumps = [p for p in fr.dump_paths if "-escalate-" in p]
    assert dumps, fr.dump_paths
    doc = read_dump(dumps[0])
    assert doc["header"]["reason"] == "escalate"
    assert doc["header"]["corr"].startswith("batch-")
    # The newest record carries the escalation annotation (note()).
    assert doc["records"][-1].get("tripped")


def test_quarantine_burst_dumps_flight(tmp_path):
    fr = FlightRecorder(capacity=8, path=str(tmp_path / "fl"),
                        quarantine_burst=4)
    proc = CEPProcessor(
        stock_demo.stock_pattern(), 4, CFG, epoch=0, flight=fr,
        ingest=IngestPolicy(grace_ms=0, on_bad_record="quarantine"),
    )
    proc.process(stock_records(8, seed=1, t0=0))
    # A burst of schema-defective records dead-letters in one batch.
    bad = [Record(0, {"wrong": 1}, 100 + i) for i in range(6)]
    proc.process(bad)
    bursts = [p for p in fr.dump_paths if "-quarantine_burst-" in p]
    assert bursts, fr.dump_paths
    doc = read_dump(bursts[0])
    assert doc["header"]["reason"] == "quarantine_burst"
    assert doc["records"][-1]["dead_letters"] >= 4


# -- TraceSink rotation (satellite) ------------------------------------------


def test_jsonl_sink_rotates_by_size(tmp_path):
    path = str(tmp_path / "t.jsonl")
    sink = JsonlTraceSink(path, max_bytes=256)
    for i in range(40):
        sink.event("tick", i=i)
    sink.close()
    assert sink.rollovers > 0
    assert os.path.exists(path + ".1")
    # Every retained line (both generations) is complete JSON.
    n = 0
    for p in (path, path + ".1"):
        with open(p) as f:
            for line in f:
                json.loads(line)
                n += 1
    assert n > 0
    assert os.path.getsize(path) <= 256 + 200  # one line of slack


def test_jsonl_sink_rotates_by_age(tmp_path, monkeypatch):
    import kafkastreams_cep_tpu.utils.telemetry as tel

    t = [1000.0]
    monkeypatch.setattr(tel.time, "monotonic", lambda: t[0])
    path = str(tmp_path / "t.jsonl")
    sink = JsonlTraceSink(path, max_age_s=30.0)
    sink.event("a")
    t[0] += 60.0
    sink.event("b")  # crosses the age bound -> rollover then write
    sink.close()
    assert sink.rollovers == 1
    assert json.loads(open(path).read())["name"] == "b"
    assert json.loads(open(path + ".1").read())["name"] == "a"


# -- Reporter atomic cadence write (satellite) --------------------------------


def test_reporter_crash_mid_flush_leaves_no_torn_line(tmp_path):
    """Armed ``report.write`` fires in the serialized-but-unwritten
    window of Reporter.flush: the failing flush must contribute NOTHING
    to the JSONL file — every retained line parses, and the flush count
    of complete records matches the successful flushes exactly."""
    path = str(tmp_path / "metrics.jsonl")
    sink = JsonlTraceSink(path)
    reporter = Reporter(lambda: {"records_in": 7}, sink, every_batches=1)
    with FAILPOINTS.session({"report.write": [1]}):
        reporter.tick()  # hit 0: succeeds
        with pytest.raises(OSError):
            reporter.tick()  # hit 1: injected crash mid-report
        reporter.tick()  # hit 2: succeeds
    sink.close()
    with open(path) as f:
        lines = f.read().splitlines()
    assert len(lines) == 2
    for line in lines:
        rec = json.loads(line)  # complete JSON — no torn tail
        assert rec["type"] == "metrics"
        assert rec["snapshot"] == {"records_in": 7}
