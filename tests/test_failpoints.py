"""Fault-injection harness (utils/failpoints.py): determinism of the
registry itself, and each production site observed failing the way its
real fault would."""

import os
import pickle

import pytest

import engine_scenarios as sc
from kafkastreams_cep_tpu.native.journal import Journal
from kafkastreams_cep_tpu.runtime import CEPProcessor, Record, Supervisor
from kafkastreams_cep_tpu.utils import failpoints as fp


@pytest.fixture(autouse=True)
def _clean_registry():
    fp.FAILPOINTS.clear()
    yield
    fp.FAILPOINTS.clear()


# -- the registry ------------------------------------------------------------


def test_disarmed_fire_is_noop():
    fp.fire("device.dispatch")  # no session: nothing counted, nothing raised
    assert fp.FAILPOINTS.hits("device.dispatch") == 0


def test_armed_hits_fire_exactly_on_schedule():
    fp.FAILPOINTS.arm("journal.append", hits=[1, 3])
    fired = []
    for i in range(5):
        try:
            fp.fire("journal.append")
        except fp.InjectedIOError:
            fired.append(i)
    assert fired == [1, 3]
    assert fp.FAILPOINTS.hits("journal.append") == 5


def test_times_mode_fires_first_n():
    fp.FAILPOINTS.arm("device.result", times=2)
    fired = []
    for i in range(4):
        try:
            fp.fire("device.result")
        except fp.InjectedFault:
            fired.append(i)
    assert fired == [0, 1]


def test_default_exception_family_by_site():
    fp.FAILPOINTS.arm("device.dispatch", times=1)
    fp.FAILPOINTS.arm("checkpoint.save", hits=[0])
    with pytest.raises(fp.InjectedFault):
        fp.fire("device.dispatch")
    with pytest.raises(fp.InjectedIOError):
        fp.fire("checkpoint.save")


def test_session_clears_on_exit():
    with fp.FAILPOINTS.session({"journal.append": [0]}):
        with pytest.raises(fp.InjectedIOError):
            fp.fire("journal.append")
    fp.fire("journal.append")  # disarmed again
    assert fp.FAILPOINTS.hits("journal.append") == 0


def test_random_schedule_is_seed_deterministic():
    a = fp.random_schedule(seed=7, horizon=40, rate=0.3)
    b = fp.random_schedule(seed=7, horizon=40, rate=0.3)
    c = fp.random_schedule(seed=8, horizon=40, rate=0.3)
    assert a == b
    assert a != c
    assert any(a.values())  # at 0.3 x 40 hits something fires


# -- sites observed through the real stack -----------------------------------


def test_journal_append_site_rolls_back_cleanly(tmp_path):
    """A failed append (either site) leaves the journal a clean frame
    prefix — later appends and replay see no residue."""
    path = str(tmp_path / "j.jrnl")
    j = Journal(path)
    j.append(b"one")
    for site in ("journal.append", "journal.fsync"):
        with fp.FAILPOINTS.session({site: [0]}):
            with pytest.raises(OSError):
                j.append(b"never-lands")
        j.append(f"after-{site}".encode())
    assert list(j.replay()) == [b"one", b"after-journal.append", b"after-journal.fsync"]


def test_device_fault_sites_trigger_supervisor_recovery(tmp_path):
    """Both dispatch-window faults recover: pre-scan (state untouched)
    and post-scan (state advanced, matches undelivered)."""
    for site in ("device.dispatch", "device.result"):
        sup = Supervisor(
            sc.strict3(), 1, sc.default_config(),
            checkpoint_path=str(tmp_path / f"{site}.ckpt"),
            checkpoint_every=100, gc_interval=0,
        )
        out = sup.process([Record("k", sc.A, 1, offset=0)])
        with fp.FAILPOINTS.session({site: [0]}):
            out += sup.process([Record("k", sc.B, 2, offset=1)])
        out += sup.process([Record("k", sc.C, 3, offset=2)])
        assert sup.recoveries == 1, site
        assert len(out) == 1, site  # the match survived, exactly once


def test_journal_failure_forces_immediate_checkpoint(tmp_path):
    """An append failure suspends journaling; the supervisor closes the
    durability window NOW by snapshotting instead of waiting out the
    cadence, and journaling re-arms."""
    sup = Supervisor(
        sc.strict3(), 1, sc.default_config(),
        checkpoint_path=str(tmp_path / "f.ckpt"),
        journal_path=str(tmp_path / "f.jrnl"),
        checkpoint_every=100, gc_interval=0,
    )
    with fp.FAILPOINTS.session({"journal.append": [0]}):
        sup.process([Record("k", sc.A, 1, offset=0)])
    assert sup.journal_failures == 1
    assert sup.checkpoints == 1  # forced, not cadence (cadence is 100)
    assert not sup._journal_suspended
    sup.process([Record("k", sc.B, 2, offset=1)])
    # The post-failure batch journals normally again.
    frames = list(Journal(str(tmp_path / "f.jrnl")).replay())
    assert len(frames) == 1
    seq, batch = pickle.loads(frames[0])
    assert [r.value for r in batch] == [sc.B]


def test_checkpoint_save_and_rename_sites_are_failures_not_corruption(tmp_path):
    """Snapshot faults at either site count as checkpoint_failures and
    leave the previous snapshot installed."""
    ck = str(tmp_path / "c.ckpt")
    sup = Supervisor(
        sc.strict3(), 1, sc.default_config(),
        checkpoint_path=ck, checkpoint_every=1, gc_interval=0,
    )
    sup.process([Record("k", sc.A, 1, offset=0)])
    assert sup.checkpoints == 1
    good = open(ck, "rb").read()
    for i, site in enumerate(("checkpoint.save", "checkpoint.rename")):
        with fp.FAILPOINTS.session({site: [0]}):
            sup.process([Record("k", sc.B, 2 + i, offset=1 + i)])
        assert sup.checkpoint_failures == i + 1, site
        assert open(ck, "rb").read() == good, site  # old snapshot intact
    # Next batch snapshots fine.
    sup.process([Record("k", sc.C, 9, offset=5)])
    assert sup.checkpoints == 2
    assert open(ck, "rb").read() != good


def test_torn_tail_forgery_is_repaired_on_replay(tmp_path):
    path = str(tmp_path / "t.jrnl")
    j = Journal(path)
    j.append(b"a")
    j.append(b"b")
    size_good = os.path.getsize(path)
    fp.tear_journal_tail(path)
    assert os.path.getsize(path) > size_good
    assert list(j.replay()) == [b"a", b"b"]  # intact prefix; tail repaired
    assert os.path.getsize(path) == size_good
    j.append(b"c")  # appends continue at the clean boundary
    assert list(j.replay()) == [b"a", b"b", b"c"]


def test_corrupt_tail_forgery_is_repaired_on_replay(tmp_path):
    path = str(tmp_path / "g.jrnl")
    j = Journal(path)
    j.append(b"a")
    fp.corrupt_journal_tail(path, nbytes=32, seed=3)
    assert list(j.replay()) == [b"a"]
    j.append(b"b")
    assert list(j.replay()) == [b"a", b"b"]
