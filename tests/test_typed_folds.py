"""Typed fold state — exact int32 folds past float32's 2^24 integer range.

The reference's ``Aggregator<K, V, T>`` is generic (``Aggregator.java:
22-25``); the array engine's analog is a per-state dtype declared by the
``init`` value's Python type (or an explicit ``dtype=``), stored
typed-encoded in one int32 array (``engine/matcher.py``).  The fuzz family
here drives an integer fold across 2^24 — where a float32-stored fold
loses exactness — and asserts exact oracle parity on matches whose
predicates read the fold value.
"""

import numpy as np
import pytest

from kafkastreams_cep_tpu import OracleNFA, Query, TPUMatcher
from kafkastreams_cep_tpu.engine import EngineConfig
from kafkastreams_cep_tpu.engine.matcher import MatcherSession
from kafkastreams_cep_tpu.pattern.aggregator import StateAggregator

# Sized for the 40-event fuzz horizon: a Kleene match can take at nearly
# every event, so walks reach ~#events hops.
CFG = EngineConfig(
    max_runs=12, slab_entries=96, slab_preds=6, dewey_depth=12, max_walk=44
)

# Step chosen so the running sum crosses 2^24 quickly and lands on values
# whose low bits float32 cannot represent (odd increments near 2^24).
BIG = (1 << 23) + 1


def sum_pattern():
    """Sum big odd increments; completion requires an exact parity test on
    the sum — any float32 rounding of the fold flips the predicate."""
    return (
        Query()
        .select("start").where(lambda k, v, ts, st: v["x"] == 5)
        .then()
        .select("acc").one_or_more().skip_till_next_match()
        .where(lambda k, v, ts, st: 0 < v["x"]).and_(
            lambda k, v, ts, st: v["x"] < 5
        )
        .fold("sum", lambda k, v, curr: curr + v["x"] * BIG, init=0)
        .then()
        .select("end")
        .where(lambda k, v, ts, st: (st.get("sum") % 4) == 2)
        .and_(lambda k, v, ts, st: v["x"] == 0)
        .build()
    )


@pytest.mark.parametrize("seed", range(4))
def test_int_fold_past_2_24_matches_oracle(seed):
    rng = np.random.default_rng(800 + seed)
    pattern = sum_pattern()
    oracle = OracleNFA.from_pattern(pattern)
    sess = MatcherSession(TPUMatcher(pattern, CFG))
    crossed = False
    for i in range(40):
        x = int(rng.integers(0, 6))  # 0 = probe, 1-4 = adds, 5 = start
        mo = oracle.match(None, {"x": x}, i, offset=i)
        me = sess.match(None, {"x": x}, i, offset=i)
        assert [m.as_map() for m in mo] == [m.as_map() for m in me], (
            f"seed={seed} event {i}: oracle {mo} engine {me}"
        )
        crossed = crossed or any(
            isinstance(v, int) and v > (1 << 24)
            for v in oracle._agg_state.values()
        )
    # The fold values really crossed float32's exact-integer range.
    assert crossed


def test_float_fold_keeps_float_semantics():
    pattern = (
        Query()
        .select("a").where(lambda k, v, ts, st: v["x"] > 0)
        .fold("ema", lambda k, v, curr: 0.5 * curr + 0.25 * v["x"], init=0.0)
        .then()
        .select("b").where(lambda k, v, ts, st: st.get("ema") > 0.7)
        .build()
    )
    oracle = OracleNFA.from_pattern(pattern)
    sess = MatcherSession(TPUMatcher(pattern, CFG))
    for i, x in enumerate([3, 2, 1, 5, 2, 1]):
        mo = oracle.match(None, {"x": x}, i, offset=i)
        me = sess.match(None, {"x": x}, i, offset=i)
        assert [m.as_map() for m in mo] == [m.as_map() for m in me], i


def test_conflicting_dtype_declarations_rejected():
    with pytest.raises(ValueError, match="conflicting"):
        TPUMatcher(
            Query()
            .select("a").where(lambda k, v, ts, st: v["x"] > 0)
            .fold("s", lambda k, v, curr: curr + 1, init=0)
            .then()
            .select("b").where(lambda k, v, ts, st: v["x"] < 0)
            .fold("s", lambda k, v, curr: curr + 0.5, init=0.0)
            .build(),
            CFG,
        )


def test_explicit_dtype_overrides_init_inference():
    agg = StateAggregator("s", lambda k, v, c: c + 1, init=0, dtype="float32")
    assert agg.resolved_dtype == "float32"
    with pytest.raises(ValueError, match="dtype"):
        StateAggregator("s", lambda k, v, c: c, dtype="int64").resolved_dtype


def test_numpy_scalar_init_infers_dtype():
    """np.float32(0.5) is not a Python float — inference must still see a
    float (int32 inference would truncate the init to 0 silently)."""
    f = StateAggregator("s", lambda k, v, c: c, init=np.float32(0.5))
    assert f.resolved_dtype == "float32"
    i = StateAggregator("s", lambda k, v, c: c, init=np.int64(3))
    assert i.resolved_dtype == "int32"
    b = StateAggregator("s", lambda k, v, c: c, init=np.bool_(True))
    assert b.resolved_dtype == "int32"
    with pytest.raises(ValueError, match="infer"):
        StateAggregator("s", lambda k, v, c: c, init="zero").resolved_dtype
