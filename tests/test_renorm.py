"""Version renormalization (``ops/renorm.py``) — bounded-width Dewey
versions on unbounded streams.

The reference's versions grow one ``.0`` per straddling event
(``NFA.java:185-188``); the fixed-width engine counts overflows instead
(``ops/dewey_ops.py``).  Renormalization deletes provably-dead zero
positions at sweep time.  Pinned here:

* the compaction primitive and every blocker of the safety condition;
* all-pairs ``is_compatible`` preservation, including versions *derived*
  from post-renorm run versions by future add_stage/add_run chains;
* the engine-level contract: a straddle-heavy stream swept between
  micro-batches stays overflow-free at a dewey_depth that overflows
  without renorm, with outputs identical to a wide-depth reference run.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kafkastreams_cep_tpu import Query
from kafkastreams_cep_tpu.engine import EngineConfig, EventBatch
from kafkastreams_cep_tpu.ops import dewey_ops
from kafkastreams_cep_tpu.ops import renorm
from kafkastreams_cep_tpu.ops import slab as slab_mod
from kafkastreams_cep_tpu.parallel.batch import BatchMatcher

D = 10


def ver(*comps):
    v, l = dewey_ops.make(comps, D)
    return jnp.asarray(v), jnp.asarray(l)


def pack(versions):
    vs, ls = zip(*[ver(*c) for c in versions])
    return jnp.stack(vs), jnp.stack(ls)


def test_delete_positions_compacts_and_zero_fills():
    v, l = pack([(1, 0, 0, 3, 0), (2, 0, 5)])
    safe = jnp.asarray([False, True, False, False, True] + [False] * (D - 5))
    nv, nl = renorm.delete_positions(v, l, safe)
    assert nl.tolist() == [3, 2]
    assert nv[0, :4].tolist() == [1, 0, 3, 0]
    assert nv[1, :3].tolist() == [2, 5, 0]
    # Tail stays zero (add_stage relies on it).
    assert not nv[:, 4:].any()


def empty_slab():
    return slab_mod.make(8, 4, D)


def slab_with(versions):
    """A slab whose live pointer slots carry ``versions`` (one entry each)."""
    slab = empty_slab()
    for i, comps in enumerate(versions):
        v, l = ver(*comps)
        slab = slab_mod.put_first(slab, i, i, v, l)
    return slab


def lane(run_versions, ptr_versions, seeds=()):
    """(run_ver, run_vlen, alive, id_pos, slab) for a crafted lane."""
    rv, rl = pack(list(run_versions) + [(9,)] * 0)
    R = rv.shape[0]
    alive = jnp.ones((R,), bool)
    id_pos = jnp.asarray(
        [-1 if i in seeds else 1 for i in range(R)], jnp.int32
    )
    return rv, rl, alive, id_pos, slab_with(ptr_versions)


def all_pairs_compat(run_vers, ptr_vers):
    out = []
    for q, ql in zip(*run_vers):
        for p, pl in zip(*ptr_vers):
            out.append(bool(dewey_ops.is_compatible(q, ql, p, pl)))
    return out


def test_safe_positions_finds_zero_runs():
    rv, rl, alive, idp, slab = lane(
        [(1, 0, 0, 0, 0, 0), (7,)], [(1,), (1, 0, 0, 0, 0)], seeds={1}
    )
    nrv, nrl, nslab, n = renorm.renorm_lane(rv, rl, alive, idp, slab)
    # Positions 1..2 are deletable (both crossers have zeros with slack);
    # position 3 is blocked by the pointer ending at length 5 (== k+2-1?
    # no: len 5 >= 3+2 passes) — compute: deletable k where every crosser
    # has 0 at k and len >= k+2: run len 6, ptr len 5 -> k in {1, 2, 3}.
    assert int(n) == 3
    assert nrl.tolist() == [3, 1]
    assert nrl[0] == 3 and nrv[0, :3].tolist() == [1, 0, 0]


def test_blockers_leave_versions_untouched():
    # (a) a pointer ENDING just past k (len == k+1) blocks k — the sibling
    # last-digit counterexample in ops/renorm.py's proof note.
    rv, rl, alive, idp, slab = lane(
        [(1, 0, 0, 0, 0)], [(1,), (1, 5)], seeds=set()
    )
    _, nrl, _, n = renorm.renorm_lane(rv, rl, alive, idp, slab)
    assert int(n) == 2  # k=2,3 deletable; k=1 blocked by (1,5) ending there
    # (a') a short non-seed RUN blocks even harder (fresh regrowth hazard).
    rv, rl, alive, idp, slab = lane(
        [(1, 0, 0, 0, 0), (1, 5)], [(1,)], seeds=set()
    )
    _, _, _, n = renorm.renorm_lane(rv, rl, alive, idp, slab)
    assert int(n) == 0
    # (b) a nonzero digit blocks its position.
    rv, rl, alive, idp, slab = lane(
        [(1, 0, 2, 0, 0, 0)], [(1,)], seeds=set()
    )
    _, nrl, _, n = renorm.renorm_lane(rv, rl, alive, idp, slab)
    assert int(n) == 3  # k in {1, 3, 4}; k=2 blocked by digit 2
    # (c) a short non-seed run blocks everything at/past its length.
    rv, rl, alive, idp, slab = lane(
        [(1, 0, 0, 0, 0, 0), (2, 0, 0)], [(1,)], seeds=set()
    )
    _, _, _, n = renorm.renorm_lane(rv, rl, alive, idp, slab)
    assert int(n) == 1  # only k=1 (both runs zero there with slack)
    # (d) a seed sharing a crossing version's first digit blocks.
    rv, rl, alive, idp, slab = lane(
        [(1, 0, 0, 0, 0, 0), (1,)], [(1,)], seeds={1}
    )
    _, _, _, n = renorm.renorm_lane(rv, rl, alive, idp, slab)
    assert int(n) == 0
    # ... but a fresh-digit seed doesn't.
    rv, rl, alive, idp, slab = lane(
        [(1, 0, 0, 0, 0, 0), (4,)], [(1,)], seeds={1}
    )
    _, _, _, n = renorm.renorm_lane(rv, rl, alive, idp, slab)
    assert int(n) > 0


def random_growth(rng, depth_cap):
    """A version grown the way the engine grows them: start (d0,), then a
    random add_stage / add_run chain."""
    comps = [int(rng.integers(1, 4))]
    for _ in range(int(rng.integers(0, depth_cap - 1))):
        if rng.random() < 0.75:
            comps.append(0)  # add_stage
        else:
            comps[-1] += 1  # add_run
    return tuple(comps)


@pytest.mark.parametrize("seed", range(10))
def test_renorm_preserves_all_pairs_compat_including_futures(seed):
    rng = np.random.default_rng(seed)
    runs = [random_growth(rng, D - 2) for _ in range(4)]
    ptrs = [random_growth(rng, D - 2) for _ in range(6)]
    rv, rl, alive, idp, slab = lane(runs, ptrs, seeds=set())
    nrv, nrl, nslab, n = renorm.renorm_lane(rv, rl, alive, idp, slab)

    MP = slab.pstage.shape[1]
    old_p = (slab.pver.reshape(-1, D)[::MP][: len(ptrs)],
             slab.pvlen.reshape(-1)[::MP][: len(ptrs)])
    new_p = (nslab.pver.reshape(-1, D)[::MP][: len(ptrs)],
             nslab.pvlen.reshape(-1)[::MP][: len(ptrs)])
    assert all_pairs_compat((rv, rl), old_p) == all_pairs_compat(
        (nrv, nrl), new_p
    ), f"seed={seed} current-pairs compat changed"

    # Future-derived versions: the same op chain applied pre and post
    # renorm must agree against every (pre/post) pointer.
    for r in range(len(runs)):
        ops = [rng.random() < 0.6 for _ in range(3)]
        qo, qol = rv[r], rl[r]
        qn, qnl = nrv[r], nrl[r]
        for is_stage in ops:
            if is_stage:
                qo, qol, _ = dewey_ops.add_stage(qo, qol)
                qn, qnl, _ = dewey_ops.add_stage(qn, qnl)
            else:
                qo = dewey_ops.add_run(qo, qol)
                qn = dewey_ops.add_run(qn, qnl)
        for p in range(len(ptrs)):
            got_o = bool(dewey_ops.is_compatible(
                qo, qol, old_p[0][p], old_p[1][p]))
            got_n = bool(dewey_ops.is_compatible(
                qn, qnl, new_p[0][p], new_p[1][p]))
            assert got_o == got_n, (
                f"seed={seed} run {r} future chain vs ptr {p}: "
                f"{got_o} -> {got_n}"
            )


def straddle_pattern():
    """Stock-shaped: zero_or_more makes BEGIN-advanced runs straddle and
    append a version digit per ignored event (the oracle reproduces the
    same ``1.0.0...`` growth — see ops/renorm.py)."""
    return (
        Query()
        .select("a").where(lambda k, v, ts, st: v["x"] == 0)
        .then()
        .select("b").zero_or_more().skip_till_next_match()
        .where(lambda k, v, ts, st: (0 < v["x"]) & (v["x"] < 6))
        .then()
        .select("c").skip_till_next_match()
        .where(lambda k, v, ts, st: v["x"] == 7)
        .build()
    )


def chunked_scan(cfg, xs, chunk):
    K, T = xs.shape
    batch = BatchMatcher(straddle_pattern(), K, cfg)
    state = batch.init_state()
    outs = []
    for t0 in range(0, T, chunk):
        sl = xs[:, t0:t0 + chunk]
        events = EventBatch(
            key=jnp.broadcast_to(
                jnp.arange(K, dtype=jnp.int32)[:, None], sl.shape),
            value={"x": jnp.asarray(sl)},
            ts=jnp.asarray(
                np.broadcast_to(np.arange(t0, t0 + sl.shape[1]),
                                sl.shape).astype(np.int32)),
            off=jnp.asarray(
                np.broadcast_to(np.arange(t0, t0 + sl.shape[1]),
                                sl.shape).astype(np.int32)),
            valid=jnp.ones(sl.shape, bool),
        )
        state, out = batch.scan(state, events)
        outs.append(jax.tree_util.tree_map(np.asarray, out))
        state = batch.sweep(state)
    return outs, batch.counters(state)


def test_long_stream_stays_overflow_free_with_renorm():
    """64 straddle-heavy events, swept every 8: dewey_depth=16 overflows
    WITHOUT renorm and stays overflow-free WITH it, and the renormalized
    run's outputs equal a wide-depth (D=80) reference run event-for-event."""
    # Growth happens while a BEGIN-advanced run straddles with zero takes
    # (1.0 -> 1.0.0 -> ... per ignored event, confirmed against the oracle);
    # 40 straddling events overflow D=12 sixfold without renorm, then the
    # take/complete tail exercises walks over the renormalized versions.
    base = [0] + [6] * 40 + [1, 6, 7] + [0] + [6] * 12 + [1, 7] + [6] * 5
    K, T = 4, len(base)
    xs = np.stack(
        [np.roll(np.asarray(base, np.int32), k) for k in range(K)]
    )
    xs[:, 0] = 0  # every lane opens with a begin event
    # Slim depth must cover per-chunk growth (8) plus the post-sweep
    # residual: concurrent straddlers keep their start-offset spread
    # (deletable positions stop at the shortest crossing version), and the
    # rolled lanes run two lineages ~3 events apart -> residual ~5.
    args = dict(max_runs=8, slab_entries=32, slab_preds=4, max_walk=16)
    wide = EngineConfig(dewey_depth=80, **args)
    slim = EngineConfig(dewey_depth=16, **args)
    slim_off = EngineConfig(
        dewey_depth=16, renorm_versions=False, **args)

    outs_ref, c_ref = chunked_scan(wide, xs, chunk=8)
    assert c_ref["ver_overflows"] == 0
    outs_off, c_off = chunked_scan(slim_off, xs, chunk=8)
    assert c_off["ver_overflows"] > 0, "trace must overflow without renorm"
    outs_on, c_on = chunked_scan(slim, xs, chunk=8)
    assert c_on["ver_overflows"] == 0, c_on

    for got, want in zip(outs_on, outs_ref):
        np.testing.assert_array_equal(got.count, want.count)
        np.testing.assert_array_equal(got.off, want.off)
        np.testing.assert_array_equal(got.stage, want.stage)


@pytest.mark.parametrize("seed", range(6))
def test_renorm_under_branching_matches_oracle_end_to_end(seed):
    """The sharpest soundness check available: a processor sweeping (and
    renormalizing) after EVERY batch, on a branching skip_till_any kleene
    pattern over random traces, must emit exactly the unbounded-version
    host oracle's matches.  An unsound position deletion would alias
    sibling versions and change the match set here."""
    from kafkastreams_cep_tpu import OracleNFA
    from kafkastreams_cep_tpu.runtime import CEPProcessor, Record

    def pat():
        return (
            Query()
            .select("a").where(lambda k, v, ts, st: v["x"] == 0)
            .then()
            .select("b").one_or_more().skip_till_any_match()
            .where(lambda k, v, ts, st: (0 < v["x"]) & (v["x"] < 8))
            .then()
            .select("c").where(lambda k, v, ts, st: v["x"] >= 8)
            .build()
        )

    cfg = EngineConfig(
        max_runs=24, slab_entries=96, slab_preds=8, dewey_depth=10,
        max_walk=24,
    )
    rng = np.random.default_rng(900 + seed)
    xs = [0] + list(rng.choice([0, 1, 2, 3, 9, 9], size=35))
    proc = CEPProcessor(pat(), 1, cfg, gc_interval=1, epoch=0)
    oracle = OracleNFA.from_pattern(pat())

    got, want = [], []
    for i in range(0, len(xs), 6):  # sweep + renorm every 6 events
        batch = [Record("k", {"x": int(x)}, 1000 + i + j)
                 for j, x in enumerate(xs[i:i + 6])]
        got += [seq.as_map() for _, seq in proc.process(batch)]
    for i, x in enumerate(xs):
        want += [m.as_map() for m in oracle.match(
            "k", {"x": int(x)}, 1000 + i, offset=i)]

    def fmt(ms):
        return [
            {n: [e.offset for e in evs] for n, evs in m.items()} for m in ms
        ]

    assert fmt(got) == fmt(want), f"seed={seed}"
