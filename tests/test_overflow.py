"""Capacity-overflow policy (VERDICT item 9): fixed shapes overflow by
*counting and dropping*, never silently and never by crashing.

The reference has no capacity limits (its queue and stores grow without
bound, ``NFA.java:100-106``); the device engine's policy is: candidates
beyond ``max_runs`` are dropped newest-last (the compaction keeps queue
order, so the oldest/earliest-emitted runs survive) and every drop is
counted in ``run_drops``; the same holds for slab entries, pointer lists,
Dewey depth, and walk bounds (``ops/slab.py`` counters)."""

import numpy as np

import engine_scenarios as sc
from kafkastreams_cep_tpu.engine import EngineConfig, MatcherSession, TPUMatcher


def branch_storm(n):
    """skip_till_any with repeated C/D: run count grows geometrically."""
    values = [sc.A, sc.B] + [sc.C, sc.D] * n
    return values


def test_run_overflow_is_counted_not_silent():
    cfg = EngineConfig(
        max_runs=6, slab_entries=64, slab_preds=8, dewey_depth=12, max_walk=12
    )
    session = MatcherSession(TPUMatcher(sc.skip_till_any(), cfg))
    for i, v in enumerate(branch_storm(6)):
        session.match(None, v, 1000 + i)
    counters = session.counters()
    assert counters["run_drops"] > 0
    # The engine is still live and sane after overflow: the seed run
    # remains, and new traces still match.
    assert bool(np.asarray(session.state.alive).any())
    late = []
    for i, v in enumerate([sc.A, sc.B, sc.C, sc.D]):
        late += session.match(None, v, 5000 + i, offset=1000 + i)
    assert len(late) >= 1


def test_oldest_runs_survive_overflow():
    """Queue-order compaction: with capacity for the first runs only, the
    earliest match still completes (drops shed the newest branches)."""
    cfg_small = EngineConfig(
        max_runs=4, slab_entries=64, slab_preds=8, dewey_depth=12, max_walk=12
    )
    cfg_big = EngineConfig(
        max_runs=64, slab_entries=128, slab_preds=16, dewey_depth=12, max_walk=12
    )
    values = branch_storm(3)
    small = MatcherSession(TPUMatcher(sc.skip_till_any(), cfg_small))
    big = MatcherSession(TPUMatcher(sc.skip_till_any(), cfg_big))
    small_matches, big_matches = [], []
    for i, v in enumerate(values):
        small_matches += [sc.canon(m) for m in small.match(None, v, 1000 + i)]
        big_matches += [sc.canon(m) for m in big.match(None, v, 1000 + i)]
    assert small.counters()["run_drops"] > 0
    assert big.counters()["run_drops"] == 0
    # Everything the overflowing engine emitted is a subset of the
    # unconstrained engine's matches, and the first match agrees.
    for m in small_matches:
        assert m in big_matches
    assert small_matches[0] == big_matches[0]


def test_dewey_overflow_zero_tail_is_match_neutral():
    """Dewey depth overflow, characterized (round-5 verdict item 3).

    Version growth is one appended ``.0`` per event a BEGIN-advanced run
    spends straddling a stage boundary (``NFA.java:185-188``) — unbounded
    in trace length, so any fixed ``dewey_depth`` can overflow.  At
    overflow the digit is dropped and counted, the run keeps its version.

    For lineages whose versions are pure zero tails — every pattern
    without a ``skip_till_any`` stage, since only branching ``add_run``s
    write nonzero digits past the root — truncation is *provably* match-
    neutral: within a lineage all stored pointer versions are prefixes of
    one another with equal digits, so every in-lineage compatibility check
    answers True in both the truncated and unbounded worlds (equal-length
    saturation turns longer-prefix into equal-with-last ``0 >= 0``), and
    cross-lineage checks fail on the first digit in both worlds.  This
    test pins that: a straddle-heavy trace overflows D=4 heavily while the
    match stream stays identical to the unbounded-version host oracle.
    Branching patterns have no such proof — there ``ver_overflows`` must
    be treated as a real hazard flag (renorm + sizing keep it zero; see
    tests/test_renorm.py).
    """
    from kafkastreams_cep_tpu import OracleNFA, Query

    def pat():
        return (
            Query()
            .select("a").where(lambda k, v, ts, st: v["x"] == 0)
            .then()
            .select("b").zero_or_more().skip_till_next_match()
            .where(lambda k, v, ts, st: (0 < v["x"]) & (v["x"] < 6))
            .then()
            .select("c").skip_till_next_match()
            .where(lambda k, v, ts, st: v["x"] == 7)
            .build()
        )

    cfg = EngineConfig(
        max_runs=8, slab_entries=32, slab_preds=4, dewey_depth=4,
        max_walk=24, renorm_versions=False,
    )
    xs = [0] + [6] * 14 + [1, 6, 7] + [0] + [6] * 9 + [1, 7, 6]
    session = MatcherSession(TPUMatcher(pat(), cfg))
    oracle = OracleNFA.from_pattern(pat())
    for i, x in enumerate(xs):
        got = session.match(None, {"x": x}, i, offset=i)
        want = oracle.match(None, {"x": x}, i, offset=i)
        assert [m.as_map() for m in got] == [m.as_map() for m in want], i
    assert session.counters()["ver_overflows"] > 5
