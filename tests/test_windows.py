"""Window (``within``) semantics, both modes (VERDICT item 8).

Faithful mode (default, oracle + engine): the reference never actually
prunes on windows, because every non-seed run is an epsilon wrapper and
``Stage.newEpsilonState`` does not copy ``windowMs`` (``Stage.java:41-46``),
so ``ComputationStage.isOutOfWindow`` (``:98-100``) compares against ``-1``.
These tests pin that quirk with genuinely advancing timestamps — the window
is exceeded by orders of magnitude and matches still complete identically
in the oracle and the array engine.

Functional mode (``EngineConfig.enforce_windows=True``, engine-only
deviation): runs are pruned using the evaluation stage's window, honouring
the BEGIN window-start reset (``NFA.java:347-349``): a run whose identity
stage is BEGIN-typed restarts its window at every event, so for a
first-stage-cardinality-ONE pattern the clock effectively starts at the
second event.
"""

import numpy as np

import engine_scenarios as sc
from kafkastreams_cep_tpu import OracleNFA, Query
from kafkastreams_cep_tpu.engine import EngineConfig, MatcherSession, TPUMatcher

A, B, C = sc.A, sc.B, sc.C


def strict3_within(amount, unit):
    return (
        Query()
        .select("first").where(sc.value_is(A))
        .then()
        .select("second").where(sc.value_is(B))
        .then()
        .select("latest").where(sc.value_is(C))
        .within(amount, unit)
        .build()
    )


def run_both(pattern, trace, config=None):
    """(values, ts) trace through oracle and faithful engine; assert
    identical per-event emission and return the canonical matches."""
    oracle = OracleNFA.from_pattern(pattern)
    sess = MatcherSession(TPUMatcher(pattern, config or sc.default_config()))
    out = []
    for i, (v, ts) in enumerate(trace):
        o = oracle.match(None, v, ts, offset=i)
        e = sess.match(None, v, ts, offset=i)
        assert [sc.canon(m) for m in o] == [sc.canon(m) for m in e], f"event {i}"
        out += [sc.canon(m) for m in o]
    return out


def test_faithful_mode_never_prunes_on_window():
    """Timestamps advance far past the 5ms window; the reference (hence
    oracle and engine) still completes the match — the quirk, pinned."""
    trace = [(A, 1000), (B, 5000), (C, 9_000_000)]
    matches = run_both(strict3_within(5, "ms"), trace)
    assert matches == [{"first": [0], "second": [1], "latest": [2]}]


def test_faithful_mode_stock_window_never_prunes():
    """The stock demo's WITHIN 1h with events spread over 10 hours still
    yields the reference's 4 matches in both implementations."""
    pattern = sc.stock_query()
    oracle = OracleNFA.from_pattern(pattern)
    sess = MatcherSession(
        TPUMatcher(pattern, sc.default_config(max_runs=32, slab_entries=64,
                                              dewey_depth=16, max_walk=16))
    )
    hour = 3_600_000
    o_all, e_all = [], []
    for i, v in enumerate(sc.STOCKS):
        ts = 1000 + i * hour + i  # >1h between consecutive events
        o_all += oracle.match(None, v, ts, offset=i)
        e_all += sess.match(None, v, ts, offset=i)
    assert len(o_all) == len(e_all) == 4
    assert [sc.canon(m) for m in o_all] == [sc.canon(m) for m in e_all]


def enforce_cfg():
    return EngineConfig(
        max_runs=16, slab_entries=48, slab_preds=6, dewey_depth=10,
        max_walk=10, enforce_windows=True,
    )


def run_enforced(pattern, trace):
    sess = MatcherSession(TPUMatcher(pattern, enforce_cfg()))
    out = []
    for i, (v, ts) in enumerate(trace):
        out += [sc.canon(m) for m in sess.match(None, v, ts, offset=i)]
    return out


def test_enforced_window_allows_in_window_match():
    # Window start = second event (BEGIN reset quirk): C is 3ms after B.
    trace = [(A, 1000), (B, 1001), (C, 1004)]
    assert run_enforced(strict3_within(5, "ms"), trace) == [
        {"first": [0], "second": [1], "latest": [2]}
    ]


def test_enforced_window_prunes_expired_run():
    # C arrives 7ms after B: outside the 5ms window -> run pruned, no match.
    trace = [(A, 1000), (B, 1001), (C, 1008)]
    assert run_enforced(strict3_within(5, "ms"), trace) == []


def test_enforced_window_begin_reset_starts_clock_at_second_event():
    """A->B gap larger than the window does NOT kill the run (the consuming
    run's identity stage is BEGIN-typed, so its window restarts every
    event); only the B->C gap is measured."""
    trace = [(A, 1000), (B, 1_000_000), (C, 1_000_003)]
    assert run_enforced(strict3_within(5, "ms"), trace) == [
        {"first": [0], "second": [1], "latest": [2]}
    ]


def test_enforced_window_prunes_then_new_match_still_possible():
    """After a pruned run, later in-window events still match fresh runs."""
    trace = [
        (A, 1000), (B, 1001), (C, 1020),  # expired -> pruned
        (A, 2000), (B, 2001), (C, 2003),  # fresh, in window
    ]
    assert run_enforced(strict3_within(5, "ms"), trace) == [
        {"first": [3], "second": [4], "latest": [5]}
    ]


# ---------------------------------------------------------------------------
# Differential fuzz: enforce_windows now exists on BOTH sides (oracle +
# engine), so functional pruning gets the same oracle-parity treatment as
# faithful mode (VERDICT round-4 item 7).
# ---------------------------------------------------------------------------


def run_both_enforced(pattern, trace):
    """Oracle(enforce_windows) vs engine(enforce_windows), per event."""
    oracle = OracleNFA.from_pattern(pattern, enforce_windows=True)
    sess = MatcherSession(TPUMatcher(pattern, enforce_cfg()))
    out = []
    for i, (v, ts) in enumerate(trace):
        o = oracle.match(None, v, ts, offset=i)
        e = sess.match(None, v, ts, offset=i)
        assert [sc.canon(m) for m in o] == [sc.canon(m) for m in e], f"event {i}"
        out += [sc.canon(m) for m in o]
    return out


def test_oracle_enforced_matches_engine_on_pinned_traces():
    """The hand-computed enforced-mode scenarios, now also oracle-checked."""
    for trace in (
        [(A, 0), (B, 2), (C, 4)],
        [(A, 0), (B, 2), (C, 100)],
        [(A, 0), (B, 9), (C, 12)],
        [(A, 0), (B, 100), (A, 200), (B, 202), (C, 204)],
    ):
        run_both_enforced(strict3_within(5, "ms"), trace)


def test_enforced_window_fuzz_strict3():
    rng = np.random.default_rng(77)
    values = [A, B, C]
    for _ in range(60):
        n = int(rng.integers(4, 12))
        ts, t = [], 0
        for _ in range(n):
            t += int(rng.integers(1, 8))
            ts.append(t)
        trace = [(values[int(rng.integers(0, 3))], ts[i]) for i in range(n)]
        run_both_enforced(strict3_within(6, "ms"), trace)


def test_enforced_window_fuzz_kleene():
    """Windowed Kleene closure under random gaps — branching runs inherit
    window starts; both modes must agree event by event."""
    pattern = (
        Query()
        .select("s").where(sc.value_is(A))
        .then()
        .select("k").one_or_more().skip_till_next_match()
        .where(sc.value_is(B))
        .then()
        .select("e").where(sc.value_is(C))
        .within(9, "ms")
        .build()
    )
    rng = np.random.default_rng(78)
    values = [A, B, C]
    for _ in range(40):
        n = int(rng.integers(4, 10))
        ts, t = [], 0
        for _ in range(n):
            t += int(rng.integers(1, 7))
            ts.append(t)
        trace = [(values[int(rng.integers(0, 3))], ts[i]) for i in range(n)]
        run_both_enforced(pattern, trace)
