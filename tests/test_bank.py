"""Multi-query bank: N patterns over one stream, independent state."""

import pytest

import engine_scenarios as sc
from kafkastreams_cep_tpu.runtime import CEPBank, Record


def test_bank_runs_queries_independently():
    bank = CEPBank(
        {"strict": sc.strict3(), "skip": sc.skip_till_next()},
        num_lanes=2,
        config=sc.default_config(),
    )
    # A B C D: strict3 matches ABC contiguously; skip_till_next matches
    # A..C..D skipping B.
    records = [
        Record("k", v, 1000 + i) for i, v in enumerate([sc.A, sc.B, sc.C, sc.D])
    ]
    out = bank.process(records)
    by_query = {}
    for name, key, seq in out:
        by_query.setdefault(name, []).append(sc.canon(seq))
    assert by_query["strict"] == [{"first": [0], "second": [1], "latest": [2]}]
    assert by_query["skip"] == [{"first": [0], "second": [2], "latest": [3]}]
    counters = bank.counters()
    assert set(counters) == {"strict", "skip"}
    assert all(v == 0 for c in counters.values() for v in c.values())


def test_bank_rejects_empty():
    with pytest.raises(ValueError, match="at least one"):
        CEPBank({}, num_lanes=1)
