"""Stencil matcher conformance: differential vs the oracle on random
traces, including micro-batch boundary spans and ragged valid prefixes."""

import jax.numpy as jnp
import numpy as np
import pytest

import engine_scenarios as sc
from kafkastreams_cep_tpu import OracleNFA, Query
from kafkastreams_cep_tpu.compiler.tables import lower
from kafkastreams_cep_tpu.engine import EventBatch
from kafkastreams_cep_tpu.engine.stencil import StencilMatcher


def batch_of(codes, offs, valid):
    codes = jnp.asarray(codes, jnp.int32)
    K, T = codes.shape
    return EventBatch(
        key=jnp.zeros((K, T), jnp.int32),
        value=codes,
        ts=jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32), (K, T)),
        off=jnp.asarray(offs, jnp.int32),
        valid=jnp.asarray(valid, bool),
    )


def oracle_hits(pattern, trace):
    """Per-event match offset-tuples from the oracle, first->last stage."""
    oracle = OracleNFA.from_pattern(pattern)
    hits = []
    for i, v in enumerate(trace):
        for m in oracle.match(None, int(v), 1000 + i, offset=i):
            stages = list(reversed(list(m.as_map().items())))
            hits.append(tuple(e.offset for _, events in stages for e in events))
    return hits


def stencil_hits(out, n):
    hit = np.asarray(out.hit)
    offs = np.asarray(out.offs)
    return [
        tuple(int(offs[k, t, i]) for i in range(n))
        for k, t in zip(*np.nonzero(hit))
    ]


def test_rejects_non_strict_patterns():
    with pytest.raises(ValueError, match="strict"):
        StencilMatcher(sc.kleene_one_or_more(), 1)
    with pytest.raises(ValueError, match="strict"):
        StencilMatcher(sc.skip_till_any(), 1)
    with pytest.raises(ValueError, match="strict"):
        StencilMatcher(sc.stock_query(), 1)


def test_is_strict_seq_accepts_strict3():
    assert lower(sc.strict3()).is_strict_seq()


def test_differential_single_batch():
    rng = np.random.default_rng(21)
    K, T = 16, 64
    codes = rng.choice(5, size=(K, T), p=[0.4, 0.3, 0.2, 0.05, 0.05])
    m = StencilMatcher(sc.strict3(), K)
    offs = np.broadcast_to(np.arange(T), (K, T))
    _, out = m.scan(m.init_state(), batch_of(codes, offs, np.ones((K, T), bool)))
    got = sorted(stencil_hits(out, m.n))
    want = []
    for k in range(K):
        want += oracle_hits(sc.strict3(), codes[k])
    assert got == sorted(want)
    assert len(got) > 0  # distribution chosen so matches actually occur


def test_differential_across_batches_and_ragged():
    """Matches spanning micro-batch boundaries are found via the carry;
    ragged per-lane valid prefixes neither break nor fake contiguity."""
    rng = np.random.default_rng(22)
    K, total = 8, 96
    codes = rng.choice(5, size=(K, total), p=[0.4, 0.3, 0.2, 0.05, 0.05])
    # Force a boundary-spanning match in lane 0: A at 31, B at 32, C at 33.
    codes[0, 31], codes[0, 32], codes[0, 33] = 0, 1, 2
    m = StencilMatcher(sc.strict3(), K)
    state = m.init_state()
    got = []
    consumed = np.zeros(K, dtype=int)
    for start in (0, 32, 64):
        T = 32
        # Ragged: each lane consumes a different number of events this batch.
        counts = rng.integers(T // 2, T + 1, size=K)
        vals = np.zeros((K, T), dtype=np.int64)
        offs = np.zeros((K, T), dtype=np.int64)
        valid = np.zeros((K, T), dtype=bool)
        for k in range(K):
            c = int(counts[k])
            c = min(c, total - consumed[k])
            seg = codes[k, consumed[k] : consumed[k] + c]
            vals[k, :c] = seg
            offs[k, :c] = np.arange(consumed[k], consumed[k] + c)
            valid[k, :c] = True
            consumed[k] += c
        state, out = m.scan(state, batch_of(vals, offs, valid))
        got += stencil_hits(out, m.n)
    want = []
    for k in range(K):
        want += oracle_hits(sc.strict3(), codes[k, : consumed[k]])
    assert sorted(got) == sorted(want)
    assert any(h == (31, 32, 33) for h in got)  # the forced boundary span


def test_single_stage_pattern():
    pattern = Query().select("only").where(lambda k, v, ts, st: v == 2).build()
    m = StencilMatcher(pattern, 2)
    codes = np.array([[2, 0, 2, 2], [0, 0, 0, 2]])
    offs = np.broadcast_to(np.arange(4), (2, 4))
    _, out = m.scan(m.init_state(), batch_of(codes, offs, np.ones((2, 4), bool)))
    assert sorted(stencil_hits(out, 1)) == [(0,), (2,), (3,), (3,)]
