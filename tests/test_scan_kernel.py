"""Whole-scan fused kernel (``ops/scan_kernel.py``) vs the jnp engine.

The scan kernel reimplements every engine phase (predicates, chain,
folds, puts, walks, compaction) as one Pallas program with state resident
across the time axis; these tests pin bit-exact parity of outputs AND the
full engine state (run queue, slab, counters) against ``BatchMatcher``'s
reference path, in interpreter mode on the CPU suite, across the
behaviors that have historically diverged first: kleene branching under
skip_till_any, typed (float) folds, padding steps, version overflow, and
state carried across multiple scans.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kafkastreams_cep_tpu import Query
from kafkastreams_cep_tpu.compiler.tables import lower
from kafkastreams_cep_tpu.engine import EngineConfig, EventBatch
from kafkastreams_cep_tpu.ops.scan_kernel import build_scan
from kafkastreams_cep_tpu.parallel.batch import BatchMatcher

K = 128  # one lane block


def events_of(xs, valid=None, ts_mult=1):
    K_, T = xs.shape
    return EventBatch(
        key=jnp.broadcast_to(jnp.arange(K_, dtype=jnp.int32)[:, None], (K_, T)),
        value={"x": jnp.asarray(xs)},
        ts=jnp.broadcast_to(
            jnp.arange(T, dtype=jnp.int32)[None, :] * ts_mult, (K_, T)),
        off=jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32)[None, :], (K_, T)),
        valid=jnp.ones((K_, T), bool) if valid is None else jnp.asarray(valid),
    )


def assert_state_equal(st_k, st_ref):
    for name in ("alive", "id_pos", "eval_pos", "vlen", "event_off",
                 "start_ts", "branching", "agg", "ver", "run_drops",
                 "ver_overflows"):
        np.testing.assert_array_equal(
            np.asarray(getattr(st_k, name)),
            np.asarray(getattr(st_ref, name)), err_msg=name,
        )
    for name in ("stage", "off", "refs", "npreds", "full_drops",
                 "pred_drops", "missing", "trunc", "hot_hits",
                 "hot_misses", "overflow_walks", "demotions"):
        np.testing.assert_array_equal(
            np.asarray(getattr(st_k.slab, name)),
            np.asarray(getattr(st_ref.slab, name)), err_msg=f"slab.{name}",
        )


def run_both(pattern, cfg, events, n_scans=1):
    os.environ["CEP_WALK_KERNEL"] = "0"
    batch = BatchMatcher(pattern, K, cfg)
    scan = build_scan(lower(pattern), cfg)
    scan.interpret = True
    st_r = st_k = batch.init_state()
    for _ in range(n_scans):
        st_r, out_r = batch.scan(st_r, events)
        st_k, out_k = scan(st_k, events)
        np.testing.assert_array_equal(
            np.asarray(out_k.count), np.asarray(out_r.count))
        np.testing.assert_array_equal(
            np.asarray(out_k.stage), np.asarray(out_r.stage))
        np.testing.assert_array_equal(
            np.asarray(out_k.off), np.asarray(out_r.off))
        # Offsets must advance across scans for a valid multi-scan replay.
        events = events._replace(off=events.off + int(events.off.shape[1]))
    assert_state_equal(st_k, st_r)


@pytest.mark.slow
def test_stock_pattern_with_padding():
    # Tier-2 (-m slow, ~21 s interpret): test_strict_contiguity_chain /
    # test_typed_float_folds keep the scan path in tier-1 (ROADMAP
    # tier-1 budget note, PR 13).
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "examples"))
    import stock_demo

    cfg = EngineConfig(
        max_runs=8, slab_entries=24, slab_preds=4, dewey_depth=8, max_walk=8
    )
    rng = np.random.default_rng(3)
    T = 12
    prices = rng.integers(90, 131, size=(K, T)).astype(np.int32)
    volumes = rng.integers(600, 1101, size=(K, T)).astype(np.int32)
    valid = np.ones((K, T), bool)
    valid[:, -2:] = False
    valid[::3, 5] = False  # per-lane padding holes
    events = EventBatch(
        key=jnp.broadcast_to(jnp.arange(K, dtype=jnp.int32)[:, None], (K, T)),
        value={"price": jnp.asarray(prices), "volume": jnp.asarray(volumes)},
        ts=jnp.broadcast_to(
            jnp.arange(T, dtype=jnp.int32)[None, :] * 2, (K, T)),
        off=jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32)[None, :], (K, T)),
        valid=jnp.asarray(valid),
    )
    run_both(stock_demo.stock_pattern(), cfg, events)


@pytest.mark.slow
def test_kleene_any_branching_two_scans():
    # Tier-2 (-m slow, ~45 s interpret) — the branching Kleene shape
    # also runs in the engine-fuzz kleene suite (ROADMAP tier-1 budget
    # note, PR 13).
    pattern = (
        Query()
        .select("a").where(lambda k, v, ts, st: v["x"] == 0)
        .then()
        .select("b").one_or_more().skip_till_any_match()
        .where(lambda k, v, ts, st: (0 < v["x"]) & (v["x"] < 8))
        .then()
        .select("c").where(lambda k, v, ts, st: v["x"] >= 8)
        .build()
    )
    cfg = EngineConfig(
        max_runs=16, slab_entries=32, slab_preds=6, dewey_depth=10,
        max_walk=12,
    )
    rng = np.random.default_rng(7)
    xs = rng.choice([0, 1, 2, 3, 9, 9], size=(K, 16)).astype(np.int32)
    run_both(pattern, cfg, events_of(xs), n_scans=2)


def test_typed_float_folds():
    pattern = (
        Query()
        .select("a").where(lambda k, v, ts, st: v["x"] > 0)
        .fold("ema", lambda k, v, curr: 0.5 * curr + 0.25 * v["x"], init=0.0)
        .fold("n", lambda k, v, curr: curr + 1, init=0)
        .then()
        .select("b").skip_till_next_match()
        .where(lambda k, v, ts, st: (st.get("ema") > 0.7) & (st.get("n") > 1))
        .build()
    )
    cfg = EngineConfig(
        max_runs=8, slab_entries=24, slab_preds=4, dewey_depth=8, max_walk=8
    )
    rng = np.random.default_rng(11)
    xs = rng.integers(0, 6, size=(K, 14)).astype(np.int32)
    run_both(pattern, cfg, events_of(xs))


@pytest.mark.slow
def test_version_overflow_counted_identically():
    # Tier-2 (-m slow, ~13 s interpret): overflow accounting stays in
    # tier-1 via test_renorm's long-stream contract (ROADMAP tier-1
    # budget note, PR 13).
    pattern = (
        Query()
        .select("a").where(lambda k, v, ts, st: v["x"] == 0)
        .then()
        .select("b").zero_or_more().skip_till_next_match()
        .where(lambda k, v, ts, st: (0 < v["x"]) & (v["x"] < 6))
        .then()
        .select("c").skip_till_next_match()
        .where(lambda k, v, ts, st: v["x"] == 7)
        .build()
    )
    cfg = EngineConfig(
        max_runs=8, slab_entries=24, slab_preds=4, dewey_depth=4,
        max_walk=12, renorm_versions=False,
    )
    xs = np.asarray(
        [[0] + [6] * 10 + [1, 6, 7, 6, 6]] * K, dtype=np.int32
    )
    os.environ["CEP_WALK_KERNEL"] = "0"
    batch = BatchMatcher(pattern, K, cfg)
    st_r, _ = batch.scan(batch.init_state(), events_of(xs))
    assert int(jnp.sum(st_r.ver_overflows)) > 0  # the trace really overflows
    run_both(pattern, cfg, events_of(xs))


def test_enforce_windows_mode():
    pattern = (
        Query()
        .select("a").where(lambda k, v, ts, st: v["x"] == 1)
        .then()
        .select("b").skip_till_next_match()
        .where(lambda k, v, ts, st: v["x"] == 2)
        .within(5, "ms")
        .build()
    )
    cfg = EngineConfig(
        max_runs=8, slab_entries=24, slab_preds=4, dewey_depth=8,
        max_walk=8, enforce_windows=True,
    )
    rng = np.random.default_rng(13)
    xs = rng.integers(0, 4, size=(K, 16)).astype(np.int32)
    run_both(pattern, cfg, events_of(xs, ts_mult=3))


def test_strict_contiguity_chain():
    pattern = (
        Query()
        .select("a").where(lambda k, v, ts, st: v["x"] == 1)
        .then()
        .select("b").where(lambda k, v, ts, st: v["x"] == 2)
        .then()
        .select("c").where(lambda k, v, ts, st: v["x"] == 3)
        .build()
    )
    cfg = EngineConfig(
        max_runs=8, slab_entries=24, slab_preds=4, dewey_depth=8, max_walk=8
    )
    rng = np.random.default_rng(17)
    xs = rng.integers(0, 5, size=(K, 16)).astype(np.int32)
    run_both(pattern, cfg, events_of(xs))


def test_scan_kernel_inside_shard_map():
    """Pallas-inside-shard_map for the whole-scan kernel: 8 shards x 128
    lanes each, emissions identical to the sharded jnp path."""
    from kafkastreams_cep_tpu.parallel.sharding import ShardedMatcher, key_mesh

    if jax.device_count() < 8:
        pytest.skip("needs 8 devices")
    pattern = (
        Query()
        .select("a").where(lambda k, v, ts, st: v["x"] < 3)
        .then()
        .select("b").skip_till_next_match()
        .where(lambda k, v, ts, st: v["x"] > 6)
        .build()
    )
    cfg = EngineConfig(
        max_runs=8, slab_entries=24, slab_preds=4, dewey_depth=8, max_walk=8
    )
    KS = 128 * 8
    rng = np.random.default_rng(23)
    xs = rng.integers(0, 10, size=(KS, 8)).astype(np.int32)
    mesh = key_mesh(jax.devices()[:8])

    os.environ["CEP_SCAN_KERNEL"] = "0"
    os.environ["CEP_WALK_KERNEL"] = "0"
    ref = ShardedMatcher(pattern, KS, mesh, cfg)
    assert not ref.uses_scan_kernel
    events = events_of(xs)
    st_r, out_r = ref.scan(ref.init_state(), ref.shard_events(events))

    os.environ["CEP_SCAN_KERNEL"] = "interpret"
    try:
        krn = ShardedMatcher(pattern, KS, mesh, cfg)
        assert krn.uses_scan_kernel
        st_k, out_k = krn.scan(krn.init_state(), krn.shard_events(events))
    finally:
        os.environ["CEP_SCAN_KERNEL"] = "0"
    np.testing.assert_array_equal(
        np.asarray(out_k.count), np.asarray(out_r.count))
    np.testing.assert_array_equal(
        np.asarray(out_k.stage), np.asarray(out_r.stage))
    assert krn.stats(st_k) == ref.stats(st_r)
