"""Shard fault tolerance: lane repartitioning, evacuation, rebalancing.

The tentpole claim (``runtime/migrate.py`` module comment): a lane
permutation is a *pure relabeling* — every state leaf carries a leading
``[K]`` lane axis, the engine is a ``vmap`` of a per-lane step, and lane
identity is internal (keys route through host maps, matches emit by key)
— so permuting state rows plus every lane-indexed host structure yields
bit-identical observable behavior.  Tested here as scan-commutes-with-
permutation on the jnp and interpret-kernel walk paths, the two-tier
slab, a live (undrained) lazy handle ring, and the tiered stencil carry;
then at the processor level (``move_lanes``) and the supervisor level
(shard evacuation onto a surviving sub-mesh, straggler declaration, and
skew-triggered hot-key rebalancing — exactly-once throughout).
"""

import dataclasses
import os
import sys

import jax
import numpy as np
import pytest

import engine_scenarios as sc
from kafkastreams_cep_tpu.engine import EngineConfig, capacity_counters
from kafkastreams_cep_tpu.parallel import ShardLost, key_mesh, surviving_mesh
from kafkastreams_cep_tpu.parallel.batch import (
    BatchMatcher,
    guarded_scan_fallback,
)
from kafkastreams_cep_tpu.runtime import (
    CEPProcessor,
    Record,
    ShardPolicy,
    Supervisor,
    move_lanes,
    plan_rebalance,
    repartition_state,
)
from kafkastreams_cep_tpu.runtime.migrate import canonical_state
from kafkastreams_cep_tpu.utils import failpoints as fp

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "examples"))
import stock_demo
from test_migrate import assert_state_equal, stock_events

CFG = EngineConfig(
    max_runs=16, slab_entries=32, slab_preds=16, dewey_depth=32, max_walk=16
)


def _perm(k):
    """A seeded non-trivial permutation of range(k)."""
    return np.random.default_rng(k).permutation(k)


# -- repartition_state: scan commutes with any lane permutation --------------


def _scan_permute_scan(cfg, K=8, T=10, drain=False):
    """Continue-scan on a permuted state (with identically permuted
    events) must equal the permuted continuation of the original —
    canonical state bit-equal per lane, outputs row-permuted, summed
    counters unchanged."""
    PERM = _perm(K)
    prefix = stock_events(K, T, seed=31)
    suffix = stock_events(K, T, seed=131, t0=T)
    m = BatchMatcher(stock_demo.stock_pattern(), K, cfg)
    mid, _ = m.scan(m.init_state(), prefix)
    st_a, out_a = m.scan(mid, suffix)

    mid_p = jax.device_put(repartition_state(mid, PERM))
    suffix_p = jax.device_put(repartition_state(suffix, PERM))
    st_b, out_b = m.scan(mid_p, suffix_p)

    for f in ("count", "stage", "off"):
        np.testing.assert_array_equal(
            np.asarray(getattr(out_a, f))[PERM],
            np.asarray(getattr(out_b, f)),
            err_msg=f"out.{f}",
        )
    assert_state_equal(
        jax.device_put(repartition_state(st_a, PERM)), st_b, msg="repart"
    )
    assert m.counters(st_a) == m.counters(st_b)  # lane sums are invariant
    assert not any(capacity_counters(m.counters(st_b)).values())
    if drain:
        st_a, d_a = m.drain(st_a)
        st_b, d_b = m.drain(st_b)
        for f in d_a._fields:
            np.testing.assert_array_equal(
                np.asarray(getattr(d_a, f))[PERM],
                np.asarray(getattr(d_b, f)),
                err_msg=f"drain.{f}",
            )
        assert_state_equal(
            jax.device_put(repartition_state(st_a, PERM)), st_b,
            msg="repart-drained",
        )


def test_repartition_parity_jnp():
    os.environ["CEP_WALK_KERNEL"] = "0"
    _scan_permute_scan(CFG)


def test_repartition_parity_walk_kernel_interpret():
    """The fused Pallas walk kernel sees permuted rows as ordinary lanes
    (interpret mode: CPU CI checks parity, not perf; K=128 is the
    kernel's minimum lane block)."""
    os.environ["CEP_WALK_KERNEL"] = "interpret"
    try:
        _scan_permute_scan(CFG, K=128)
    finally:
        os.environ["CEP_WALK_KERNEL"] = "0"


def test_repartition_parity_two_tier_slab():
    os.environ["CEP_WALK_KERNEL"] = "0"
    _scan_permute_scan(dataclasses.replace(CFG, slab_hot_entries=8))


def test_repartition_parity_live_handle_ring():
    """Lazy extraction with pinned, undrained handles: the ring rows
    permute with their lanes and drain to row-permuted matches."""
    os.environ["CEP_WALK_KERNEL"] = "0"
    lazy = dataclasses.replace(CFG, lazy_extraction=True, handle_ring=64)
    _scan_permute_scan(lazy, drain=True)


def test_repartition_rejects_non_permutations():
    m = BatchMatcher(stock_demo.stock_pattern(), 4, CFG)
    st = m.init_state()
    with pytest.raises(ValueError, match="permutation"):
        repartition_state(st, [0, 1, 1, 2])
    with pytest.raises(ValueError, match="lane axis"):
        repartition_state(st, [0, 1])  # wrong K


# -- plan_rebalance ----------------------------------------------------------


def test_plan_rebalance_spreads_hot_lanes():
    perm = plan_rebalance([50, 50, 1, 1], 2)
    assert perm is not None
    loads = np.array([50, 50, 1, 1])[perm].reshape(2, 2).sum(axis=1)
    assert loads.max() == 51  # one hot lane per shard
    assert sorted(perm.tolist()) == [0, 1, 2, 3]


def test_plan_rebalance_no_improvement_returns_none():
    assert plan_rebalance([1, 1, 1, 1], 2) is None  # already balanced
    assert plan_rebalance([100, 1, 1, 1], 2) is None  # dominated: no gain
    assert plan_rebalance([5, 4, 3], 2) is None  # K % n != 0
    assert plan_rebalance([5, 4], 1) is None  # nothing to spread across


def test_plan_rebalance_is_deterministic():
    a = plan_rebalance([9, 9, 2, 2, 1, 1, 0, 0], 4)
    b = plan_rebalance([9, 9, 2, 2, 1, 1, 0, 0], 4)
    assert a is not None and np.array_equal(a, b)


# -- surviving_mesh ----------------------------------------------------------


def test_surviving_mesh_drops_dead_and_keeps_divisibility():
    if jax.device_count() < 8:
        pytest.skip("needs 8 devices")
    mesh = key_mesh(jax.devices()[:8])
    dead_dev = mesh.devices.flat[3]
    sub = surviving_mesh(mesh, [3], num_lanes=16)
    # 7 survivors do not divide 16 lanes; the largest divisor wins.
    assert int(sub.devices.size) == 4
    assert dead_dev not in list(sub.devices.flat)
    assert sub.axis_names == mesh.axis_names
    sub2 = surviving_mesh(mesh, [0, 1, 2, 3, 4, 5], num_lanes=16)
    assert int(sub2.devices.size) == 2
    with pytest.raises(ValueError):
        surviving_mesh(mesh, range(8), num_lanes=16)


# -- the shared lowering-fallback policy (satellite: PR 1 alignment) ---------


def test_guarded_fallback_transient_errors_propagate():
    """A transient device error (RESOURCE_EXHAUSTED, ...) must NOT
    demote to the slow path — it reaches the supervisor retry instead.
    Single policy for BatchMatcher and ShardedMatcher
    (``parallel.batch.guarded_scan_fallback``)."""
    calls = {"slow": 0}

    def fast(state, events):
        raise RuntimeError("RESOURCE_EXHAUSTED: hbm oom while allocating")

    guarded = guarded_scan_fallback(
        fast, lambda: calls.__setitem__("slow", 1) or (lambda s, e: s)
    )
    with pytest.raises(RuntimeError, match="RESOURCE_EXHAUSTED"):
        guarded(1, 2)
    assert calls["slow"] == 0  # transient: no demotion built


def test_guarded_fallback_lowering_error_demotes_once():
    built = {"n": 0}
    noted = {"n": 0}

    def fast(state, events):
        raise NotImplementedError("cannot lower windowed gather")

    def make_slow():
        built["n"] += 1
        return lambda state, events: state * events

    guarded = guarded_scan_fallback(
        fast, make_slow, on_fallback=lambda: noted.__setitem__("n", 1)
    )
    assert guarded(3, 2) == 6
    assert guarded(4, 2) == 8  # sticky: the slow path is reused,
    assert built["n"] == 1  # built exactly once,
    assert noted["n"] == 1  # and the demotion was reported.


# -- move_lanes: processor-level pure relabeling -----------------------------


def _stream(keys, n, seed, start=0):
    rng = np.random.default_rng(seed)
    offs = {k: start for k in keys}
    out = []
    for i in range(n):
        k = keys[int(rng.integers(len(keys)))]
        out.append(Record(k, int(rng.integers(0, 5)), 1000 + start * 8 + i,
                          offset=offs[k]))
        offs[k] += 1
    return out


def _canon(matches):
    return sorted(
        (k, tuple(sorted(
            (stage, tuple(e.offset for e in evs))
            for stage, evs in seq.as_map().items()
        )))
        for k, seq in matches
    )


@pytest.mark.parametrize("tiered", [False, True])
def test_move_lanes_processor_parity(tiered):
    """A moved processor matches bit-identically to the unmoved one —
    same emissions, same canonical state (row-permuted), same counters —
    including the tiered stencil carry (``EngineConfig.tiering``), whose
    per-lane prefix state rides the same permutation."""
    cfg = sc.default_config(tiering=tiered, **SUP_DIMS)
    keys = ["k0", "k1", "k2", "k3"]
    pat = sc.skip_till_any
    a = CEPProcessor(pat(), 4, cfg, gc_interval=0)
    b = CEPProcessor(pat(), 4, cfg, gc_interval=0)
    head = _stream(keys, 24, seed=5)
    tail = _stream(keys, 24, seed=6, start=6)
    ma = list(a.process(head))
    mb = list(b.process(head))
    if tiered:
        assert getattr(a.state, "carry", None) is not None
    perm = np.array([2, 0, 3, 1])
    b = move_lanes(pat(), b, perm)
    assert b._lane_of == {k: int(np.argsort(perm)[a._lane_of[k]])
                          for k in keys}
    ma += a.process(tail) + a.flush()
    mb += b.process(tail) + b.flush()
    assert _canon(ma) == _canon(mb)
    assert_state_equal(
        jax.device_put(repartition_state(canonical_state(a.state), perm)),
        canonical_state(b.state),
        msg="move_lanes",
    )
    assert a.counters() == b.counters()
    assert not any(b.counters().values())


def test_move_lanes_fault_leaves_old_processor_intact():
    """The ``rebalance.move`` fault site fires before any state moves: a
    failed move must leave the old processor (and assignment) usable."""
    proc = CEPProcessor(sc.skip_till_any(), 2, sc.default_config(),
                        gc_interval=0)
    proc.process(_stream(["k0", "k1"], 8, seed=1))
    lanes_before = dict(proc._lane_of)
    with fp.FAILPOINTS.session({"rebalance.move": [0]}):
        with pytest.raises(fp.InjectedIOError):
            move_lanes(sc.skip_till_any(), proc, [1, 0])
    assert proc._lane_of == lanes_before
    more = proc.process(_stream(["k0", "k1"], 8, seed=2, start=4))
    assert isinstance(more, list)  # still processes after the failed move


# -- supervisor: evacuation, stragglers, rebalancing -------------------------


KEYS4 = ["k0", "k1", "k2", "k3"]

# Wide enough that these streams are loss-free: the exactly-once and
# bit-parity claims are only meaningful when nothing was dropped anyway.
SUP_DIMS = dict(
    max_runs=64, slab_entries=96, slab_preds=12, dewey_depth=24, max_walk=12
)
SUP_CFG = sc.default_config(**SUP_DIMS)


def _skew_batches(seed):
    """Warmup batch touches all four lanes; afterwards only k0/k1 —
    shard 0 of a 2-device mesh takes ~all the work."""
    rng = np.random.default_rng(seed)
    offs = {k: 0 for k in KEYS4}
    batches = []
    for i in range(8):
        recs = []
        for j in range(8):
            k = KEYS4[int(rng.integers(2))] if i else KEYS4[j % 4]
            recs.append(Record(k, int(rng.integers(0, 5)),
                               1000 + 8 * i + j, offset=offs[k]))
            offs[k] += 1
        batches.append(recs)
    return batches


def _oracle(batches, cfg=None, pat=sc.skip_till_any):
    proc = CEPProcessor(pat(), 4, cfg or SUP_CFG, gc_interval=0)
    out = []
    for b in batches:
        out += proc.process(b)
    out += proc.flush()
    return proc, out


_SKEW_WANT = {}


def _skew_want(seed):
    """Canonical fault-free matches for ``_skew_batches(seed)`` — two
    tests replay the same stream, so the oracle run is shared."""
    if seed not in _SKEW_WANT:
        _SKEW_WANT[seed] = _canon(_oracle(_skew_batches(seed))[1])
    return _SKEW_WANT[seed]


def _mesh2():
    if jax.device_count() < 2:
        pytest.skip("needs 2 devices")
    return key_mesh(jax.devices()[:2])


def _meshed_supervisor(tmp_path, mesh, **kw):
    return Supervisor(
        sc.skip_till_any(), 4, SUP_CFG,
        checkpoint_path=str(tmp_path / "s.ckpt"),
        journal_path=str(tmp_path / "s.jrnl"),
        checkpoint_every=2, gc_interval=0, mesh=mesh, **kw,
    )


def test_supervisor_evacuates_lost_shard(tmp_path):
    """A ShardLost out of the meshed dispatch (the ``shard.dispatch``
    failpoint) evacuates onto the surviving sub-mesh and continues
    degraded — final state and emissions bit-identical to a fault-free
    single-device run, exactly once."""
    mesh = _mesh2()
    batches = [_stream(KEYS4, 8, seed=40 + i, start=2 * i)
               for i in range(4)]
    sup = _meshed_supervisor(tmp_path, mesh)
    got = list(sup.process(batches[0]))
    with fp.FAILPOINTS.session(
        {"shard.dispatch": [0]},
        exc=lambda: ShardLost("injected device loss", shard=1),
    ):
        got += sup.process(batches[1])
    assert sup.evacuations == 1
    assert int(sup._mesh().devices.size) == 1  # degraded
    for b in batches[2:]:
        got += sup.process(b)
    got += sup.processor.flush()
    oracle_proc, want = _oracle(batches)
    assert _canon(got) == _canon(want)
    assert_state_equal(
        canonical_state(sup.processor.state),
        canonical_state(oracle_proc.state),
        msg="post-evacuation",
    )
    assert not any(sup.processor.counters().values())
    snap = sup.metrics_snapshot(per_lane=False)
    assert snap["evacuations"] == 1
    assert snap["phases"]["evacuate"]["count"] == 1


def test_supervisor_unmeshed_shard_loss_crashes(tmp_path):
    """With no mesh there is nothing to evacuate onto: ShardLost
    propagates like any exhausted-retries crash."""
    sup = Supervisor(
        sc.skip_till_any(), 2, SUP_CFG,
        checkpoint_path=str(tmp_path / "u.ckpt"), gc_interval=0,
        shard_policy=ShardPolicy(),
    )
    with fp.FAILPOINTS.session(
        {"device.dispatch": [0, 1]},
        exc=lambda: ShardLost("injected", shard=0),
    ):
        with pytest.raises(ShardLost):
            sup.process(_stream(["k0", "k1"], 8, seed=3))
    assert sup.evacuations == 0


def test_supervisor_shard_probe_routes_generic_error_to_evacuation(tmp_path):
    """A generic device error plus an external probe report of a dead
    shard evacuates instead of recovering onto the dead mesh."""
    mesh = _mesh2()
    batches = [_stream(KEYS4, 8, seed=60 + i, start=2 * i)
               for i in range(3)]
    sup = _meshed_supervisor(tmp_path, mesh, shard_probe=lambda: [0])
    got = list(sup.process(batches[0]))
    with fp.FAILPOINTS.session({"device.dispatch": [0]}):
        got += sup.process(batches[1])
    assert sup.evacuations == 1 and sup.recoveries == 0
    got += sup.process(batches[2]) + sup.processor.flush()
    _, want = _oracle(batches)
    assert _canon(got) == _canon(want)


def test_evacuation_span_and_stall_exemplar_carry_batch_correlation(tmp_path):
    """ISSUE 18 satellite: the evacuation trace span AND the latency
    ledger's ``stall.evacuate`` exemplar carry the correlation id of the
    batch the evacuation rolled back.  Evacuation rebuilds the processor
    from checkpoint + journal replay, so the ledger survives through its
    durable state (the checkpoint header), not by reference — committed
    observations from before the fault must still be present after."""
    from kafkastreams_cep_tpu.utils.telemetry import InMemoryTraceSink

    mesh = _mesh2()
    batches = [_stream(KEYS4, 8, seed=90 + i, start=2 * i)
               for i in range(2)]
    sink = InMemoryTraceSink()
    sup = _meshed_supervisor(tmp_path, mesh, trace_sink=sink, latency=True)
    sup.process(batches[0])
    with fp.FAILPOINTS.session(
        {"shard.dispatch": [0]},
        exc=lambda: ShardLost("injected device loss", shard=1),
    ):
        sup.process(batches[1])
    assert sup.evacuations == 1
    span = sink.spans("evacuate")[0]
    corr = span["corr"]
    twins = [
        s for s in sink.spans("supervisor.batch") if s["corr"] == corr
    ]
    assert len(twins) == 1  # resolves to exactly one real batch span
    ex = sup.processor.ledger.exemplars["stall.evacuate"]
    assert ex["corr"] == corr and ex["seconds"] > 0
    snap = sup.metrics_snapshot(per_lane=False)
    assert snap["latency"]["stalls"]["evacuate"]["count"] == 1
    # Batches committed before AND after the evacuation land in one
    # uninterrupted ledger.
    assert snap["latency"]["batches"] >= 2


def test_supervisor_straggler_declaration_and_evacuation(tmp_path):
    """Latency watermarks breaching factor x peer-median for
    ``straggler_streak`` observations declare the shard; the next batch
    boundary evacuates it (state parity preserved — evacuation is the
    same restore-replay spine as recovery)."""
    mesh = _mesh2()
    policy = ShardPolicy(straggler_factor=2.0, straggler_window=4,
                         straggler_streak=3)
    batches = [_stream(KEYS4, 8, seed=80 + i, start=2 * i)
               for i in range(3)]
    sup = _meshed_supervisor(tmp_path, mesh, shard_policy=policy)
    got = list(sup.process(batches[0]))
    declared = False
    for _ in range(5):
        sup.observe_shard_latency(0, 0.010)
        declared = sup.observe_shard_latency(1, 0.200) or declared
    assert declared and sup.stragglers == 1
    got += sup.process(batches[1])  # boundary: evacuation happens here
    assert sup.evacuations == 1
    assert not sup._lagging
    got += sup.process(batches[2]) + sup.processor.flush()
    _, want = _oracle(batches)
    assert _canon(got) == _canon(want)


def test_supervisor_hot_key_rebalance_lossfree(tmp_path):
    """The skew demo: one key takes ~all the work; at a checkpoint
    boundary the per-key heavy-hitter window trips the policy and hot
    lanes move — zero dropped or duplicated matches, counters clean."""
    mesh = _mesh2()
    policy = ShardPolicy(rebalance_skew=1.2, rebalance_min_hops=8,
                         rebalance_streak=1, rebalance_cooldown=0)
    sup = _meshed_supervisor(tmp_path, mesh, shard_policy=policy)
    batches = _skew_batches(seed=9)
    got = []
    for b in batches:
        got += sup.process(b)
    got += sup.processor.flush()
    assert sup.rebalances >= 1
    assert sup.lanes_moved >= 1
    assert _canon(got) == _skew_want(9)  # nothing dropped, nothing doubled
    assert not any(sup.processor.counters().values())
    snap = sup.metrics_snapshot(per_lane=False)
    assert snap["rebalances"] == sup.rebalances
    assert snap["lanes_moved"] == sup.lanes_moved
    assert snap["phases"]["rebalance"]["count"] >= 1


def test_supervisor_rebalance_move_fault_keeps_old_assignment(tmp_path):
    """An armed ``rebalance.move`` makes the move fail AFTER the decision:
    the supervisor counts the failure, keeps the old assignment, and the
    stream stays exactly-once."""
    mesh = _mesh2()
    policy = ShardPolicy(rebalance_skew=1.2, rebalance_min_hops=8,
                         rebalance_streak=1, rebalance_cooldown=0)
    sup = _meshed_supervisor(tmp_path, mesh, shard_policy=policy)
    batches = _skew_batches(seed=9)  # same stream that trips the policy
    got = []
    with fp.FAILPOINTS.session({"rebalance.move": list(range(99))}):
        for b in batches:
            got += sup.process(b)
    got += sup.processor.flush()
    assert sup.rebalances == 0
    assert sup.rebalance_failures >= 1
    assert _canon(got) == _skew_want(9)
