"""Live-state migration (runtime/migrate.py) — the embedding property.

The contract: widening any state dimension (run queue R, slab E, pointer
lists MP, Dewey width D, walk bound W — alone or combined) embeds the
live state such that the wide engine's future evolution is bit-identical
to the narrow engine's for as long as the narrow engine would not have
dropped — same emissions at the same run slots, same slab placement,
same counters — and the final narrow state re-embeds into exactly the
final wide state.  Checked over randomized traces on the jnp path, and
jnp-vs-Pallas-kernel on a migrated state (interpret mode; CPU CI checks
parity, not perf).
"""

import dataclasses
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import engine_scenarios as sc
from kafkastreams_cep_tpu.engine import (
    EngineConfig,
    EventBatch,
    capacity_counters,
)
from kafkastreams_cep_tpu.parallel.batch import BatchMatcher
from kafkastreams_cep_tpu.runtime import (
    CEPProcessor,
    Record,
    migrate_processor,
    widen_state,
)
from kafkastreams_cep_tpu.runtime.migrate import canonical_state, check_widens

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "examples"))
import stock_demo

# Narrow-but-sufficient on the traces below: the embedding claim is only
# bit-exact while the narrow side does not drop, so the property runs
# assert all-zero narrow counters as a precondition.
NARROW = EngineConfig(
    max_runs=16, slab_entries=32, slab_preds=16, dewey_depth=32, max_walk=16
)

WIDENINGS = {
    "runs": dict(max_runs=32),
    "slab": dict(slab_entries=64),
    "preds": dict(slab_preds=32),
    "dewey": dict(dewey_depth=48),
    "walk": dict(max_walk=24),
    "combined": dict(
        max_runs=32, slab_entries=64, slab_preds=32, dewey_depth=48,
        max_walk=24,
    ),
}


def stock_events(K, T, seed, t0=0):
    rng = np.random.default_rng(seed)
    prices = rng.integers(90, 131, size=(K, T)).astype(np.int32)
    vols = rng.integers(600, 1101, size=(K, T)).astype(np.int32)
    return EventBatch(
        key=jnp.broadcast_to(
            jnp.arange(K, dtype=jnp.int32)[:, None], (K, T)
        ),
        value={"price": jnp.asarray(prices), "volume": jnp.asarray(vols)},
        ts=jnp.broadcast_to(
            (t0 + jnp.arange(T, dtype=jnp.int32))[None, :] * 2, (K, T)
        ),
        off=jnp.broadcast_to(
            (t0 + jnp.arange(T, dtype=jnp.int32))[None, :], (K, T)
        ),
        valid=jnp.ones((K, T), bool),
    )


def assert_state_equal(a, b, msg=""):
    """Bit-equality of the observable state (dead run slots, free slab
    rows, and pointer slots beyond npreds hold implementation-dependent
    residue the engine can never read — canonical_state nulls them)."""
    a, b = canonical_state(a), canonical_state(b)
    fa, _ = jax.tree_util.tree_flatten(a)
    fb, _ = jax.tree_util.tree_flatten(b)
    for i, (x, y) in enumerate(zip(fa, fb)):
        np.testing.assert_array_equal(
            np.asarray(x), np.asarray(y), err_msg=f"{msg} leaf {i}"
        )


@pytest.mark.parametrize(
    "dim,seed",
    # Each dim alone on one randomized trace; the combined widening on a
    # second trace too (it subsumes the per-dim interactions).  The
    # combined runs are tier-2 (-m slow, ~11 s each): the per-dim params
    # keep the pure-embedding claim in tier-1 (ROADMAP tier-1 budget
    # note, PR 13).
    [
        (d, 3) if d != "combined"
        else pytest.param(d, 3, marks=pytest.mark.slow)
        for d in sorted(WIDENINGS)
    ] + [pytest.param("combined", 17, marks=pytest.mark.slow)],
)
def test_widening_is_pure_embedding(dim, seed):
    """Prefix on narrow -> widen -> suffix on wide == suffix on narrow:
    emissions bit-identical on the shared run slots, nothing beyond them,
    and embed(final_narrow) == final_wide exactly."""
    K, T = 8, 12
    wide_cfg = dataclasses.replace(NARROW, **WIDENINGS[dim])
    prefix = stock_events(K, T, seed)
    suffix = stock_events(K, T, seed + 100, t0=T)

    narrow = BatchMatcher(stock_demo.stock_pattern(), K, NARROW)
    mid, _ = narrow.scan(narrow.init_state(), prefix)
    st_n, out_n = narrow.scan(mid, suffix)
    assert not any(capacity_counters(narrow.counters(st_n)).values()), (
        "precondition: the narrow run must be loss-free for bit-exactness"
    )

    wide = BatchMatcher(stock_demo.stock_pattern(), K, wide_cfg)
    mid_w = jax.device_put(widen_state(mid, NARROW, wide_cfg))
    st_w, out_w = wide.scan(mid_w, suffix)

    R = NARROW.max_runs
    np.testing.assert_array_equal(
        np.asarray(out_n.count), np.asarray(out_w.count)[..., :R]
    )
    assert not np.asarray(out_w.count)[..., R:].any()
    W = NARROW.max_walk
    for f in ("stage", "off"):
        np.testing.assert_array_equal(
            np.asarray(getattr(out_n, f)),
            np.asarray(getattr(out_w, f))[..., :R, :W],
            err_msg=f,
        )
    assert_state_equal(
        jax.device_put(widen_state(st_n, NARROW, wide_cfg)), st_w,
        msg=f"widen[{dim}]",
    )


@pytest.mark.slow
def test_kernel_and_jnp_paths_agree_on_migrated_state():
    """A migrated state is an ordinary engine state: the fused Pallas walk
    kernel and the jnp pass must stay bit-identical running it.

    Tier-2 (``-m slow``): interpret-mode Pallas executes per step in
    Python and this is the single most expensive test in the suite
    (~166 s); the jnp migrate tests above keep tier-1 coverage
    (ROADMAP tier-1 budget note, PR 13)."""
    K, T = 128, 10
    wide_cfg = dataclasses.replace(NARROW, **WIDENINGS["combined"])
    prefix = stock_events(K, T, 7)
    suffix = stock_events(K, T, 107, t0=T)
    os.environ["CEP_WALK_KERNEL"] = "0"
    narrow = BatchMatcher(stock_demo.stock_pattern(), K, NARROW)
    mid, _ = narrow.scan(narrow.init_state(), prefix)
    mid_w = jax.device_put(widen_state(mid, NARROW, wide_cfg))
    wide_ref = BatchMatcher(stock_demo.stock_pattern(), K, wide_cfg)
    st_r, out_r = wide_ref.scan(mid_w, suffix)
    os.environ["CEP_WALK_KERNEL"] = "interpret"
    try:
        wide_krn = BatchMatcher(stock_demo.stock_pattern(), K, wide_cfg)
        assert wide_krn.uses_walk_kernel
        st_k, out_k = wide_krn.scan(mid_w, suffix)
    finally:
        os.environ["CEP_WALK_KERNEL"] = "0"
    for f in ("count", "stage", "off"):
        np.testing.assert_array_equal(
            np.asarray(getattr(out_r, f)), np.asarray(getattr(out_k, f)),
            err_msg=f,
        )
    assert_state_equal(st_r, st_k, msg="kernel-vs-jnp")
    assert wide_ref.counters(st_r) == wide_krn.counters(st_k)


def test_two_tier_slab_widens_with_hot_window_intact():
    """Widening E with the hot window kept: placement (and therefore the
    whole state) stays bit-exact — appended slots are free overflow rows
    that neither allocation-before-full nor demotion can see."""
    K, T = 8, 12
    narrow = dataclasses.replace(NARROW, slab_hot_entries=8)
    wide_cfg = dataclasses.replace(narrow, slab_entries=64)
    prefix = stock_events(K, T, 11)
    suffix = stock_events(K, T, 111, t0=T)
    a = BatchMatcher(stock_demo.stock_pattern(), K, narrow)
    mid, _ = a.scan(a.init_state(), prefix)
    st_n, out_n = a.scan(mid, suffix)
    assert not any(capacity_counters(a.counters(st_n)).values())
    b = BatchMatcher(stock_demo.stock_pattern(), K, wide_cfg)
    st_w, out_w = b.scan(
        jax.device_put(widen_state(mid, narrow, wide_cfg)), suffix
    )
    np.testing.assert_array_equal(
        np.asarray(out_n.count), np.asarray(out_w.count)
    )
    assert_state_equal(
        jax.device_put(widen_state(st_n, narrow, wide_cfg)), st_w,
        msg="two-tier",
    )


def test_handle_ring_widens_with_pending_handles():
    """Lazy extraction: widening (handle_ring alone, and combined with
    every other dim) with a NON-EMPTY handle ring embeds the pending
    handles — the wide engine drains them to bit-identical matches and
    keeps matching identically afterwards."""
    lazy_narrow = dataclasses.replace(
        NARROW, lazy_extraction=True, handle_ring=64
    )
    widenings = dict(
        ring=dict(handle_ring=96),
        combined=dict(handle_ring=96, **WIDENINGS["combined"]),
    )
    K, T = 8, 12
    prefix = stock_events(K, T, 23)
    suffix = stock_events(K, T, 123, t0=T)
    os.environ["CEP_WALK_KERNEL"] = "0"
    narrow = BatchMatcher(stock_demo.stock_pattern(), K, lazy_narrow)
    mid, _ = narrow.scan(narrow.init_state(), prefix)  # NOT drained
    assert int(jnp.sum(mid.hr_count)) > 0
    st_n, _ = narrow.scan(mid, suffix)
    st_n, d_n = narrow.drain(st_n)
    assert not any(capacity_counters(narrow.counters(st_n)).values())
    for name, w in widenings.items():
        wide_cfg = dataclasses.replace(lazy_narrow, **w)
        wide = BatchMatcher(stock_demo.stock_pattern(), K, wide_cfg)
        mid_w = jax.device_put(widen_state(mid, lazy_narrow, wide_cfg))
        st_w, _ = wide.scan(mid_w, suffix)
        st_w, d_w = wide.drain(st_w)
        HB, W0 = lazy_narrow.handle_ring, lazy_narrow.max_walk
        for f in d_n._fields:
            a = np.asarray(getattr(d_n, f))
            b = np.asarray(getattr(d_w, f))
            if b.ndim == 3:  # [K, HB', W'] hop rows
                assert (b[:, :HB, W0:] == -1).all(), f"{name}: drain.{f}"
                b = b[:, :HB, :W0]
            else:
                b = b[:, :HB]
            np.testing.assert_array_equal(
                a, b, err_msg=f"{name}: drain.{f}"
            )
            assert not (np.asarray(getattr(d_w, f))[:, HB:] > 0).any() \
                if f == "count" else True
        assert narrow.counters(st_n) == wide.counters(st_w), name


def test_check_widens_refusals():
    with pytest.raises(ValueError, match="shrink"):
        check_widens(NARROW, dataclasses.replace(NARROW, max_runs=8))
    with pytest.raises(ValueError, match="semantics"):
        check_widens(
            NARROW,
            dataclasses.replace(NARROW, max_runs=32, enforce_windows=True),
        )
    with pytest.raises(ValueError, match="equals"):
        check_widens(NARROW, NARROW)


def test_migrate_processor_preserves_history_and_counters():
    """Processor-level migration: a processor that already dropped keeps
    its counters (migration never forgives past loss), its key->lane map,
    its event mirror, and keeps matching across the boundary."""
    tiny = EngineConfig(
        max_runs=4, slab_entries=16, slab_preds=2, dewey_depth=8, max_walk=8
    )
    proc = CEPProcessor(sc.skip_till_any(), 2, tiny, gc_interval=0)
    storm = [sc.A, sc.B] + [sc.C, sc.D] * 4
    for i, v in enumerate(storm):
        proc.process([Record("k", v, 1000 + i, offset=i)])
    before = proc.counters()
    assert before["run_drops"] > 0
    wide = EngineConfig(
        max_runs=32, slab_entries=64, slab_preds=8, dewey_depth=16,
        max_walk=16,
    )
    proc2 = migrate_processor(sc.skip_till_any(), proc, wide)
    assert proc2.counters() == before
    assert proc2._lane_of == proc._lane_of
    assert proc2._next_offset.tolist() == proc._next_offset.tolist()
    n = len(storm)
    out = []
    for i, v in enumerate([sc.A, sc.B, sc.C, sc.D]):
        out += proc2.process([Record("k", v, 5000 + i, offset=n + i)])
    assert len(out) >= 1  # live and matching at the new width
    assert proc2.counters()["run_drops"] == before["run_drops"]  # no new loss


def test_migrate_refuses_pending_pipelined_batch():
    proc = CEPProcessor(
        sc.strict3(), 1, sc.default_config(), pipeline=True, gc_interval=0
    )
    proc.process([Record("k", sc.A, 1, offset=0)])
    wide = dataclasses.replace(sc.default_config(), max_runs=64)
    with pytest.raises(ValueError, match="flush"):
        migrate_processor(sc.strict3(), proc, wide)
    proc.flush()
    migrate_processor(sc.strict3(), proc, wide)  # clean after flush
