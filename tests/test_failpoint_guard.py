"""Tier-1 guard: every registered failpoint site must be exercised.

``utils/failpoints.py`` only has value if each named site is actually
driven to failure by some test — a site added with production wiring but
no arming test is dead code on the exact path that matters (the failure
path).  This walks ``SITES`` and greps ``tests/`` for each name, so a
new site (like the ingest ones) cannot land unexercised, and a renamed
site cannot silently orphan its schedules.
"""

import pathlib

from kafkastreams_cep_tpu.utils import failpoints as fp

_THIS = pathlib.Path(__file__)


def _tests_corpus() -> str:
    return "\n".join(
        p.read_text()
        for p in _THIS.parent.glob("*.py")
        if p.name != _THIS.name
    )


def test_every_registered_site_is_armed_by_some_test():
    corpus = _tests_corpus()
    unexercised = [
        site for site in fp.SITES if f'"{site}"' not in corpus
    ]
    assert not unexercised, (
        f"failpoint sites {unexercised} are registered in "
        "utils/failpoints.py SITES but no test names them — arm each new "
        "site in at least one test before landing it"
    )


def test_sites_registry_matches_production_fire_calls():
    """The reverse direction: every ``fire("...")`` call site in the
    package must be a registered name — a typo'd site would silently
    never fire under any schedule."""
    import re

    pkg = _THIS.parent.parent / "kafkastreams_cep_tpu"
    called = set()
    for p in pkg.rglob("*.py"):
        for m in re.finditer(
            r"_failpoint\(\s*[\"']([a-z_.]+)[\"']\s*\)", p.read_text()
        ):
            called.add(m.group(1))
    assert called, "no production failpoint call sites found"
    unknown = called - set(fp.SITES)
    assert not unknown, (
        f"production fire() sites {sorted(unknown)} are not in "
        "failpoints.SITES — register them (append-only)"
    )
