"""Process-level trace cache (ISSUE 16 satellite).

The contract (utils/tracecache.py): builders register jitted programs
under structural keys and equal keys share the cached callable verbatim;
LRU eviction bounds residency at ``CEP_TRACE_CACHE`` entries; ``0``/
``off`` disables the cache entirely; and the hit/miss/eviction stats
surface in ``CEPProcessor.metrics_snapshot`` so recompilation thrash —
the failure mode adaptive replanning could otherwise induce — is
observable from the same place as every other engine counter.
"""

import os

import numpy as np
import pytest

import engine_scenarios as sc
from kafkastreams_cep_tpu.engine import EngineConfig
from kafkastreams_cep_tpu.parallel.batch import BatchMatcher
from kafkastreams_cep_tpu.utils import tracecache

CFG = EngineConfig(
    max_runs=8, slab_entries=16, slab_preds=4, dewey_depth=8, max_walk=8,
)


@pytest.fixture(autouse=True)
def _fresh_cache(monkeypatch):
    """Each test sees an empty cache at default capacity, and leaves an
    empty cache behind (other test files only lose warm entries)."""
    monkeypatch.delenv("CEP_TRACE_CACHE", raising=False)
    tracecache.clear()
    yield
    tracecache.clear()


def test_lookup_caches_by_namespaced_key():
    built = []

    def build():
        built.append(1)
        return object()

    a = tracecache.lookup("ns", "k", build)
    b = tracecache.lookup("ns", "k", build)
    assert a is b and len(built) == 1
    # A different namespace is a different slot for the same key.
    c = tracecache.lookup("other", "k", build)
    assert c is not a and len(built) == 2
    s = tracecache.stats()
    assert s["hits"] == 1 and s["misses"] == 2 and s["entries"] == 2
    assert s["capacity"] == tracecache._DEFAULT_CAPACITY


def test_unkeyable_and_disabled_bypass(monkeypatch):
    built = []

    def build():
        built.append(1)
        return len(built)

    # key=None (tables_key refused the pattern): always rebuilds.
    assert tracecache.lookup("ns", None, build) == 1
    assert tracecache.lookup("ns", None, build) == 2
    monkeypatch.setenv("CEP_TRACE_CACHE", "0")
    assert tracecache.capacity() == 0
    assert tracecache.lookup("ns", "k", build) == 3
    assert tracecache.lookup("ns", "k", build) == 4
    assert tracecache.stats()["entries"] == 0


def test_lru_eviction_order(monkeypatch):
    monkeypatch.setenv("CEP_TRACE_CACHE", "2")
    built = []

    def build(k):
        def f():
            built.append(k)
            return ("prog", k)

        return f

    tracecache.lookup("ns", "a", build("a"))
    tracecache.lookup("ns", "b", build("b"))
    tracecache.lookup("ns", "a", build("a"))  # hit: a becomes MRU
    tracecache.lookup("ns", "c", build("c"))  # evicts b, the LRU
    tracecache.lookup("ns", "a", build("a"))  # still resident
    tracecache.lookup("ns", "b", build("b"))  # rebuilt after eviction
    assert built == ["a", "b", "c", "b"]
    s = tracecache.stats()
    assert s["entries"] == 2 and s["capacity"] == 2
    assert s["evictions"] == 2  # b once, then c
    assert s["hits"] == 2 and s["misses"] == 4


def test_matcher_rebuilds_hit_the_cache():
    """Rebuilding a matcher for an already-compiled (pattern, config) —
    the evacuation/recovery/replan path — reuses the cached programs
    instead of re-tracing."""
    os.environ["CEP_WALK_KERNEL"] = "0"
    pat = sc.strict3()
    BatchMatcher(pat, 4, CFG)
    mid = tracecache.stats()
    assert mid["misses"] > 0 and mid["entries"] > 0
    BatchMatcher(pat, 4, CFG)
    after = tracecache.stats()
    assert after["hits"] > mid["hits"]
    assert after["entries"] == mid["entries"]


def test_processor_snapshot_surfaces_cache_stats():
    from kafkastreams_cep_tpu.runtime import CEPProcessor, Record

    os.environ["CEP_WALK_KERNEL"] = "0"
    proc = CEPProcessor(sc.strict3(), 4, CFG, epoch=0)
    proc.process([Record(0, int(v), t) for t, v in enumerate((0, 1, 2))])
    snap = proc.metrics_snapshot()
    tc = snap["trace_cache"]
    assert set(tc) == {
        "entries", "hits", "misses", "evictions", "capacity",
    }
    assert tc["entries"] >= 1 and tc["misses"] >= 1
    assert np.isfinite(tc["capacity"])
    assert tc["capacity"] == tracecache._DEFAULT_CAPACITY
