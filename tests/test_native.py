"""Native ingest kernels: C++ vs NumPy-fallback differential tests.

Every public entry point of ``kafkastreams_cep_tpu.native`` must produce
identical results with the C++ library and with the NumPy fallbacks
(``CEP_NO_NATIVE=1``); these tests run both paths in-process by reaching
past the module's load cache.
"""

import json

import numpy as np
import pytest

from kafkastreams_cep_tpu import native


def _both_paths():
    """Yield (label, use_native) for the paths available here."""
    yield "numpy", False
    if native.available():
        yield "native", True


def _with_path(use_native, fn):
    """Run ``fn`` with the native library forced on/off."""
    saved = native._lib
    try:
        if not use_native:
            native._lib = None
        return fn()
    finally:
        native._lib = saved


def test_native_library_builds():
    # The environment has g++; the library must build and load.  If this
    # fails, every runtime user silently falls back to NumPy — worth a loud
    # signal rather than a skip.
    assert native.available(), "C++ ingest library failed to build/load"


@pytest.mark.parametrize("label,use_native", list(_both_paths()))
def test_queue_positions(label, use_native):
    lanes = np.array([0, 1, 0, 2, 1, 0, 2, 2], dtype=np.int32)
    keep = np.array([1, 1, 1, 0, 1, 1, 1, 1], dtype=np.uint8)
    pos, qlen, max_len = _with_path(
        use_native, lambda: native.queue_positions(lanes, keep, 4)
    )
    assert pos.tolist() == [0, 0, 1, -1, 1, 2, 0, 1]
    assert qlen.tolist() == [3, 2, 2, 0]
    assert max_len == 3


@pytest.mark.parametrize("label,use_native", list(_both_paths()))
def test_queue_positions_empty_and_all_dropped(label, use_native):
    lanes = np.array([0, 1], dtype=np.int32)
    keep = np.zeros(2, dtype=np.uint8)
    pos, qlen, max_len = _with_path(
        use_native, lambda: native.queue_positions(lanes, keep, 2)
    )
    assert pos.tolist() == [-1, -1]
    assert qlen.tolist() == [0, 0]
    assert max_len == 0


@pytest.mark.parametrize("label,use_native", list(_both_paths()))
@pytest.mark.parametrize("dtype", [np.int32, np.float32, np.int64])
def test_pack_column(label, use_native, dtype):
    rng = np.random.default_rng(3)
    n, K = 64, 8
    lanes = rng.integers(0, K, size=n).astype(np.int32)
    keep = (rng.random(n) < 0.8).astype(np.uint8)
    pos, _, max_len = native.queue_positions(lanes, keep, K)
    T = max(max_len, 1)
    src = rng.integers(0, 1000, size=n).astype(dtype)

    dst = np.zeros((K, T), dtype=dtype)
    _with_path(
        use_native, lambda: native.pack_column(dst, src, lanes, pos, keep)
    )
    expect = np.zeros((K, T), dtype=dtype)
    m = keep.astype(bool)
    expect[lanes[m], pos[m]] = src[m]
    np.testing.assert_array_equal(dst, expect)

    valid = np.zeros((K, T), dtype=bool)
    _with_path(
        use_native, lambda: native.pack_valid(valid, lanes, pos, keep)
    )
    evalid = np.zeros((K, T), dtype=bool)
    evalid[lanes[m], pos[m]] = True
    np.testing.assert_array_equal(valid, evalid)


@pytest.mark.parametrize("label,use_native", list(_both_paths()))
def test_parse_json_lines(label, use_native):
    lines = [
        {"name": "e1", "price": 100, "volume": 1010},
        {"name": "e2", "price": 120.5, "volume": 990},
        {"name": "e3", "price": -3, "volume": 1.5e3},
    ]
    text = "\n".join(json.dumps(o) for o in lines).encode()
    values, keys, ok = _with_path(
        use_native,
        lambda: native.parse_json_lines(text, ["price", "volume"], "name"),
    )
    assert ok.all()
    assert keys == ["e1", "e2", "e3"]
    np.testing.assert_allclose(
        values, [[100, 1010], [120.5, 990], [-3, 1500]]
    )


@pytest.mark.parametrize("label,use_native", list(_both_paths()))
def test_parse_json_lines_bad_lines_are_flagged(label, use_native):
    text = (
        b'{"price":1,"volume":2}\n'
        b"not json at all\n"
        b'{"price":3}\n'  # missing volume
        b'{"price":4,"volume":5}'
    )
    values, keys, ok = _with_path(
        use_native,
        lambda: native.parse_json_lines(text, ["price", "volume"]),
    )
    assert ok.tolist() == [True, False, False, True]
    np.testing.assert_allclose(values[0], [1, 2])
    np.testing.assert_allclose(values[3], [4, 5])


def test_parse_json_lines_whitespace_and_spacing():
    # json.dumps default spacing (", " separators) must parse too.
    text = b'  {"price": 7 , "volume": 8}  '
    values, keys, ok = native.parse_json_lines(text, ["price", "volume"])
    assert ok.tolist() == [True]
    np.testing.assert_allclose(values[0], [7, 8])


@pytest.mark.parametrize("label,use_native", list(_both_paths()))
def test_parse_json_lines_reject_contract(label, use_native):
    """Both paths must reject exactly the same out-of-fragment lines."""
    cases = [
        (b'{"name":"' + b"x" * 33 + b'","price":1,"volume":2}', False),  # key > 32
        (b'{"name":"e\\t1","price":1,"volume":2}', False),  # escape
        (b'{"price":true,"volume":2}', False),  # bool value
        (b'{"price":null,"volume":2}', False),  # null value
        (b'{"price":1,"volume":2,"extra":[1]}', False),  # nested array
        (b'{"price":"12","volume":2}', False),  # string-typed numeric field
        (b'{"price":inf,"volume":2}', False),  # not a JSON number
        (b'{"price":0x1A,"volume":2}', False),  # hex is not JSON
        (b'{"price":-1.5e2,"volume":2}', True),  # full JSON number grammar
        (b'{"price":1,"volume":2,"note":"ok"}', True),  # extra string field
        (b'{"price":01,"volume":2}', False),  # leading zero is not JSON
        (b'{"price":1.,"volume":2}', False),  # bare trailing dot
        (b'{"price":1.e3,"volume":2}', False),  # frac digits required
        (b'{"price":0.5e+1,"volume":2}', True),  # zero int part + signed exp
        (b'\xff{"price":1,"volume":2}', False),  # invalid bytes reject, not crash
    ]
    text = b"\n".join(c for c, _ in cases)
    values, keys, ok = _with_path(
        use_native,
        lambda: native.parse_json_lines(text, ["price", "volume"], "name"),
    )
    assert ok.tolist() == [want for _, want in cases]
    idx = [c for c, _ in cases].index(b'{"price":-1.5e2,"volume":2}')
    np.testing.assert_allclose(values[idx], [-150.0, 2.0])


@pytest.mark.parametrize("label,use_native", list(_both_paths()))
def test_parse_json_lines_huge_integer_is_inf(label, use_native):
    # strtod saturates huge literals to ±HUGE_VAL; the fallback must match
    # rather than crash with OverflowError.
    text = ('{"price":1' + "0" * 400 + ',"volume":-1' + "0" * 400 + "}").encode()
    values, keys, ok = _with_path(
        use_native,
        lambda: native.parse_json_lines(text, ["price", "volume"]),
    )
    assert ok.tolist() == [True]
    assert values[0, 0] == np.inf and values[0, 1] == -np.inf


@pytest.mark.parametrize("label,use_native", list(_both_paths()))
def test_parse_json_lines_empty_key_is_none(label, use_native):
    text = b'{"name":"","price":1,"volume":2}'
    values, keys, ok = _with_path(
        use_native,
        lambda: native.parse_json_lines(text, ["price", "volume"], "name"),
    )
    assert ok.tolist() == [True]
    assert keys == [None]


@pytest.mark.parametrize("label,use_native", list(_both_paths()))
def test_parse_json_lines_duplicate_key_field_last_wins(label, use_native):
    text = b'{"name":"abcdef","name":"x","price":1,"volume":2}'
    values, keys, ok = _with_path(
        use_native,
        lambda: native.parse_json_lines(text, ["price", "volume"], "name"),
    )
    assert ok.tolist() == [True]
    assert keys == ["x"]


@pytest.mark.parametrize("label,use_native", list(_both_paths()))
def test_parse_json_lines_empty_input(label, use_native):
    values, keys, ok = _with_path(
        use_native,
        lambda: native.parse_json_lines(b"", ["price", "volume"], "name"),
    )
    assert values.shape == (0, 2)
    assert keys == []
    assert ok.shape == (0,)
