"""Fixed-width Dewey kernels vs the host ``DeweyVersion`` algebra.

Covers the reference truth table (``nfa/DeweyVersionTest.java:39-44``) plus an
exhaustive differential sweep of ``is_compatible`` against the host class.
"""

import itertools

import jax
import jax.numpy as jnp
import pytest

from kafkastreams_cep_tpu import DeweyVersion
from kafkastreams_cep_tpu.ops import dewey_ops

D = 6


def _pair(s: str):
    return dewey_ops.make(DeweyVersion(s).components, D)


def test_make_round_trip():
    ver, vlen = _pair("1.0.1")
    assert dewey_ops.to_tuple(ver, vlen) == (1, 0, 1)


def test_add_run_matches_host():
    for s in ["1", "1.0", "1.0.1", "2.3"]:
        ver, vlen = _pair(s)
        out = dewey_ops.add_run(ver, vlen)
        assert dewey_ops.to_tuple(out, vlen) == DeweyVersion(s).add_run().components


def test_add_stage_matches_host():
    for s in ["1", "1.0", "1.0.1"]:
        ver, vlen = _pair(s)
        out_ver, out_len, overflow = dewey_ops.add_stage(ver, vlen)
        assert not bool(overflow)
        assert dewey_ops.to_tuple(out_ver, out_len) == DeweyVersion(s).add_stage().components


def test_add_stage_overflow_keeps_version():
    ver, vlen = dewey_ops.make((1, 0, 0, 0, 0, 0), D)
    out_ver, out_len, overflow = dewey_ops.add_stage(ver, vlen)
    assert bool(overflow)
    assert int(out_len) == D
    assert dewey_ops.to_tuple(out_ver, out_len) == (1, 0, 0, 0, 0, 0)


def test_compatibility_truth_table():
    # DeweyVersionTest.java:39-44.
    cases = [
        ("1.0", "2.0", False),
        ("1.0.0", "1.0", True),
        ("1.1", "1.0", True),
        ("1.0", "1.1", False),
        ("1.0", "1.0.0", False),
    ]
    fn = jax.jit(dewey_ops.is_compatible)
    for q, p, expected in cases:
        qv, ql = _pair(q)
        pv, pl = _pair(p)
        assert bool(fn(qv, ql, pv, pl)) == expected, (q, p)


def test_compatibility_exhaustive_vs_host():
    """Every version pair up to depth 3 with components in {1,2} ∪ {0 tail}."""
    pool = []
    for depth in (1, 2, 3):
        for combo in itertools.product((0, 1, 2), repeat=depth):
            if combo[0] == 0:
                continue  # leading component is always >= 1 in practice
            pool.append(combo)
    pairs = list(itertools.product(pool, repeat=2))
    host = [DeweyVersion(a).is_compatible(DeweyVersion(b)) for a, b in pairs]
    qv = jnp.stack([dewey_ops.make(a, D)[0] for a, _ in pairs])
    ql = jnp.asarray([len(a) for a, _ in pairs], dtype=jnp.int32)
    pv = jnp.stack([dewey_ops.make(b, D)[0] for _, b in pairs])
    pl = jnp.asarray([len(b) for _, b in pairs], dtype=jnp.int32)
    out = jax.jit(jax.vmap(dewey_ops.is_compatible))(qv, ql, pv, pl)
    assert out.tolist() == host


def test_vmap_batch():
    qs = jnp.stack([_pair("1.0.0")[0], _pair("1.1")[0], _pair("1.0")[0]])
    qls = jnp.asarray([3, 2, 2], dtype=jnp.int32)
    pv, pl = _pair("1.0")
    out = jax.vmap(lambda v, l: dewey_ops.is_compatible(v, l, pv, pl))(qs, qls)
    assert out.tolist() == [True, True, True]


def test_make_rejects_too_deep():
    with pytest.raises(ValueError):
        dewey_ops.make((1,) * (D + 1), D)
