"""Shared test helpers (kept out of conftest.py so they import cleanly
under any pytest import mode)."""


def value_is(expected):
    """Predicate factory used across the conformance suites."""
    return lambda k, v, ts, store: v == expected
