"""Failure detection & recovery (SURVEY §5): the supervisor restores the
last checkpoint and replays the journal after a device failure, landing in
exactly the pre-failure state — the Kafka Streams rebalance/changelog
contract (``CEPProcessor.java:117-134``) made explicit."""

import os
import sys

import numpy as np
import pytest

import engine_scenarios as sc
from kafkastreams_cep_tpu.runtime import CEPProcessor, Record
from kafkastreams_cep_tpu.runtime.supervisor import (
    HealthReport,
    Supervisor,
    check_health,
)

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "examples"))
import stock_demo


def stock_records():
    return [
        Record("stocks", {"price": e["price"], "volume": e["volume"]}, 1000 + i)
        for i, e in enumerate(stock_demo.STOCK_EVENTS)
    ]


def stock_cfg():
    from kafkastreams_cep_tpu.engine import EngineConfig

    return EngineConfig(
        max_runs=32, slab_entries=64, slab_preds=8, dewey_depth=16, max_walk=16
    )


class FailOnce:
    """Monkeypatch hook: makes the Nth device dispatch raise once."""

    def __init__(self, scan, fail_on_call: int):
        self.scan = scan
        self.calls = 0
        self.fail_on_call = fail_on_call
        self.failed = False

    def __call__(self, state, events):
        self.calls += 1
        if self.calls == self.fail_on_call and not self.failed:
            self.failed = True
            raise RuntimeError("injected device failure")
        return self.scan(state, events)


def test_recovery_matches_uninterrupted_run(tmp_path):
    """Fail the device dispatch mid-stream; the supervisor recovers from
    checkpoint + journal replay and total emissions equal a clean run's."""
    records = stock_records()
    name_of = {i: e["name"] for i, e in enumerate(stock_demo.STOCK_EVENTS)}

    sup = Supervisor(
        stock_demo.stock_pattern(), 1, stock_cfg(),
        checkpoint_path=str(tmp_path / "s.ckpt"), checkpoint_every=2,
    )
    out = []
    out += sup.process(records[:3])
    out += sup.process(records[3:5])  # triggers a checkpoint (every 2)
    assert sup.checkpoints == 1

    # Inject a failure on the next dispatch.
    hook = FailOnce(sup.processor.batch.scan, fail_on_call=1)
    sup.processor.batch.scan = hook
    out += sup.process(records[5:])
    assert hook.failed
    assert sup.recoveries == 1

    lines = [stock_demo.format_match(seq, name_of) for _, seq in out]
    assert lines == stock_demo.EXPECTED


def test_recovery_without_checkpoint_replays_full_journal(tmp_path):
    """Before the first checkpoint the journal is the whole history: a
    fresh processor replays it and the stream continues correctly."""
    sup = Supervisor(
        sc.strict3(), 1, sc.default_config(),
        checkpoint_path=str(tmp_path / "s.ckpt"), checkpoint_every=100,
    )
    out = []
    out += sup.process([Record("k", sc.A, 1), Record("k", sc.B, 2)])
    hook = FailOnce(sup.processor.batch.scan, fail_on_call=1)
    sup.processor.batch.scan = hook
    out += sup.process([Record("k", sc.C, 3)])
    assert sup.recoveries == 1 and sup.checkpoints == 0
    assert len(out) == 1  # SEQ(A, B, C) completed across the failure


def test_recovery_does_not_duplicate_replayed_matches(tmp_path):
    """A match emitted before the failure is not re-emitted by replay."""
    sup = Supervisor(
        sc.strict3(), 1, sc.default_config(),
        checkpoint_path=str(tmp_path / "s.ckpt"), checkpoint_every=100,
    )
    first = sup.process(
        [Record("k", sc.A, 1), Record("k", sc.B, 2), Record("k", sc.C, 3)]
    )
    assert len(first) == 1
    hook = FailOnce(sup.processor.batch.scan, fail_on_call=1)
    sup.processor.batch.scan = hook
    later = sup.process([Record("k", sc.X, 4)])
    assert later == [] and sup.recoveries == 1
    # The completed match was extracted once; replay did not resurrect it.
    final = sup.process(
        [Record("k", sc.A, 5), Record("k", sc.B, 6), Record("k", sc.C, 7)]
    )
    assert len(final) == 1


def test_persistent_failure_raises(tmp_path, monkeypatch):
    """A failure that survives recovery (rebuilt processors fail on the
    same batch too) propagates once max_retries is exhausted."""
    sup = Supervisor(
        sc.strict3(), 1, sc.default_config(),
        checkpoint_path=str(tmp_path / "s.ckpt"), max_retries=1,
    )
    sup.process([Record("k", sc.A, 1)])

    orig = CEPProcessor.process

    def poisoned(self, records):
        if any(r.value == sc.B for r in records):
            raise RuntimeError("permanent device loss")
        return orig(self, records)

    monkeypatch.setattr(CEPProcessor, "process", poisoned)
    with pytest.raises(RuntimeError, match="permanent device loss"):
        sup.process([Record("k", sc.B, 2)])
    assert sup.recoveries == 1  # it did try a recovery before giving up


def test_input_errors_do_not_trigger_recovery(tmp_path):
    """A deterministic input rejection (ValueError) propagates without a
    pointless restore-and-replay cycle."""
    sup = Supervisor(
        sc.strict3(), 1, sc.default_config(),
        checkpoint_path=str(tmp_path / "s.ckpt"),
    )
    sup.process([Record("k", sc.A, 1)])
    with pytest.raises(ValueError, match="num_lanes"):
        sup.process([Record("other_key", sc.A, 2)])
    assert sup.recoveries == 0


def test_checkpoint_failure_does_not_lose_matches(tmp_path, monkeypatch):
    """If the snapshot write fails, the batch's matches still return and
    the journal keeps covering the gap."""
    sup = Supervisor(
        sc.strict3(), 1, sc.default_config(),
        checkpoint_path=str(tmp_path / "s.ckpt"), checkpoint_every=1,
    )
    from kafkastreams_cep_tpu.runtime import supervisor as sup_mod

    def broken_save(processor, path):
        raise OSError("disk full")

    monkeypatch.setattr(sup_mod.ckpt_mod, "save_checkpoint", broken_save)
    out = sup.process(
        [Record("k", sc.A, 1), Record("k", sc.B, 2), Record("k", sc.C, 3)]
    )
    assert len(out) == 1  # the match was not lost
    assert sup.checkpoint_failures == 1 and sup.checkpoints == 0
    assert len(sup._journal) == 1  # journal retained for future recovery


def test_default_checkpoint_paths_are_per_instance():
    a = Supervisor(sc.strict3(), 1, sc.default_config())
    b = Supervisor(sc.strict3(), 1, sc.default_config())
    assert a.checkpoint_path != b.checkpoint_path


def test_health_clean_processor():
    proc = CEPProcessor(sc.strict3(), 1, sc.default_config())
    proc.process([Record("k", sc.A, 1), Record("k", sc.B, 2)])
    report = check_health(proc)
    assert isinstance(report, HealthReport)
    assert report.healthy and not report.warnings and not report.errors


def test_health_flags_capacity_drops():
    """Overflowing the run queue is a warning (capacity policy), not an
    error: matching lost branches but state is consistent."""
    from kafkastreams_cep_tpu.engine import EngineConfig

    cfg = EngineConfig(
        max_runs=2, slab_entries=8, slab_preds=2, dewey_depth=4, max_walk=4
    )
    proc = CEPProcessor(sc.skip_till_any(), 1, cfg)
    proc.process(
        [Record("k", v, i) for i, v in enumerate([sc.A, sc.B, sc.B, sc.B, sc.B])]
    )
    report = check_health(proc)
    assert report.healthy  # drops are lossy but not corruption
    assert report.warnings


def test_health_detects_nan_fold_state():
    # NaN is only representable in float-typed fold state (agg is
    # typed-encoded int32; float states are stored as bit patterns), so
    # the probe needs a pattern with a float-dtype fold.
    from kafkastreams_cep_tpu import Query

    pattern = (
        Query()
        .select("a").where(lambda k, v, ts, st: v["price"] > 0)
        .fold("ema", lambda k, v, curr: 0.5 * curr + 0.5 * v["price"],
              init=0.0)
        .then()
        .select("b").where(lambda k, v, ts, st: v["price"] < 0)
        .build()
    )
    proc = CEPProcessor(pattern, 1, stock_cfg())
    proc.process(stock_records()[:2])
    nan_bits = np.float32(np.nan).view(np.int32)
    poisoned = proc.state._replace(
        agg=np.full_like(np.asarray(proc.state.agg), nan_bits)
    )
    proc.state = poisoned
    report = check_health(proc)
    assert not report.healthy
    assert any("NaN" in e for e in report.errors)

    # An int-typed pattern's agg can hold the same bits without being NaN.
    proc2 = CEPProcessor(stock_demo.stock_pattern(), 1, stock_cfg())
    proc2.process(stock_records()[:2])
    proc2.state = proc2.state._replace(
        agg=np.full_like(np.asarray(proc2.state.agg), nan_bits)
    )
    assert check_health(proc2).healthy


def test_pipelined_supervisor_checkpoints_and_loses_nothing(tmp_path):
    """ISSUE 2 satellite: periodic snapshots of a pipeline=True processor
    used to be perpetual checkpoint_failures (save_checkpoint refuses a
    pending undecoded batch).  The supervisor now flushes first and the
    flushed matches still reach the caller."""
    records = stock_records()
    sup = Supervisor(
        stock_demo.stock_pattern(), 1, stock_cfg(),
        checkpoint_path=str(tmp_path / "p.ckpt"), checkpoint_every=2,
        pipeline=True,
    )
    out = []
    for i in range(0, len(records), 2):
        out += sup.process(records[i:i + 2])
    out += sup.checkpoint()  # drains the final in-flight batch
    assert sup.checkpoint_failures == 0
    assert sup.checkpoints >= 2
    name_of = {i: e["name"] for i, e in enumerate(stock_demo.STOCK_EVENTS)}
    lines = [stock_demo.format_match(seq, name_of) for _, seq in out]
    assert lines == stock_demo.EXPECTED


def test_pipelined_checkpoint_failure_keeps_flushed_matches(tmp_path, monkeypatch):
    """If the snapshot fails AFTER the flush, the flushed matches are not
    lost with it — they ride out on the same process() call."""
    sup = Supervisor(
        sc.strict3(), 1, sc.default_config(),
        checkpoint_path=str(tmp_path / "pf.ckpt"), checkpoint_every=1,
        pipeline=True,
    )
    from kafkastreams_cep_tpu.runtime import supervisor as sup_mod

    def broken_save(processor, path, extra=None):
        raise OSError("disk full")

    monkeypatch.setattr(sup_mod.ckpt_mod, "save_checkpoint", broken_save)
    out = sup.process(
        [Record("k", sc.A, 1), Record("k", sc.B, 2), Record("k", sc.C, 3)]
    )
    assert sup.checkpoint_failures == 1
    assert len(out) == 1  # flushed match delivered despite the failed save


def test_plain_valueerror_from_device_triggers_recovery(tmp_path):
    """ISSUE 2 satellite: only the typed InputRejected short-circuits
    recovery; a bare ValueError out of the dispatch (how JAX surfaces
    some device faults) must restore-and-replay like any device loss."""
    sup = Supervisor(
        sc.strict3(), 1, sc.default_config(),
        checkpoint_path=str(tmp_path / "v.ckpt"),
    )
    sup.process([Record("k", sc.A, 1)])
    hook = FailOnce(sup.processor.batch.scan, fail_on_call=1)

    def value_error_scan(state, events):
        try:
            return hook(state, events)
        except RuntimeError:
            raise ValueError("INTERNAL: device tunnel dropped")

    sup.processor.batch.scan = value_error_scan
    out = sup.process([Record("k", sc.B, 2), Record("k", sc.C, 3)])
    assert sup.recoveries == 1
    assert len(out) == 1  # the match completed across the recovery


def test_input_rejected_is_a_valueerror():
    """Compat: callers catching ValueError for validation errors keep
    working; the supervisor distinguishes by the narrower type."""
    from kafkastreams_cep_tpu.runtime import InputRejected

    assert issubclass(InputRejected, ValueError)
    proc = CEPProcessor(sc.strict3(), 1, sc.default_config())
    proc.process([Record("k", sc.A, 1)])
    with pytest.raises(InputRejected, match="num_lanes"):
        proc.process([Record("other", sc.A, 2)])


def test_supervisor_metrics_snapshot(tmp_path):
    sup = Supervisor(
        sc.strict3(), 1, sc.default_config(),
        checkpoint_path=str(tmp_path / "s.ckpt"), checkpoint_every=1,
    )
    sup.process([Record("k", sc.A, 1)])
    snap = sup.metrics_snapshot()
    assert snap["checkpoints"] == 1
    assert snap["recoveries"] == 0
    assert snap["records_in"] == 1


# -- retry backoff (ISSUE 5 satellite) ---------------------------------------


def _failing_supervisor(tmp_path, monkeypatch, fail_times, **kw):
    """A supervisor whose processor faults on the first ``fail_times``
    dispatches of value B, with sleeps captured instead of slept."""
    sup = Supervisor(
        sc.strict3(), 1, sc.default_config(),
        checkpoint_path=str(tmp_path / "b.ckpt"), max_retries=4, **kw,
    )
    slept = []
    sup._sleep = slept.append
    state = {"left": fail_times}
    orig = CEPProcessor.process

    def flaky(self, records):
        if state["left"] > 0 and any(r.value == sc.B for r in records):
            state["left"] -= 1
            raise RuntimeError("transient device loss")
        return orig(self, records)

    monkeypatch.setattr(CEPProcessor, "process", flaky)
    return sup, slept


def test_retry_backoff_is_exponential_capped_and_counted(
    tmp_path, monkeypatch
):
    sup, slept = _failing_supervisor(
        tmp_path, monkeypatch, fail_times=3,
        retry_backoff_ms=100.0, retry_backoff_cap_ms=250.0,
    )
    sup.process([Record("k", sc.A, 1)])
    out = sup.process([Record("k", sc.B, 2)])
    assert sup.recoveries == 3
    assert len(slept) == 3
    # Exponential-with-jitter: each delay in [0.5, 1.0) x min(cap, base*2^n).
    for n, s in enumerate(slept):
        hi = min(250.0, 100.0 * 2 ** n) / 1000.0
        assert hi * 0.5 <= s < hi, (n, s)
    assert slept[2] < 0.250  # the cap bit (800 ms uncapped)
    assert sup.retry_backoff_ms_total == pytest.approx(
        sum(slept) * 1000.0, rel=1e-6
    )
    assert sup.metrics_snapshot(per_lane=False)[
        "retry_backoff_ms_total"
    ] == pytest.approx(sum(slept) * 1000.0, rel=1e-6)
    # The batch eventually succeeded and the C completes the match.
    out += sup.process([Record("k", sc.C, 3)])
    assert len(out) == 1


def test_retry_backoff_jitter_is_deterministic(tmp_path, monkeypatch):
    waits = []
    for _ in range(2):
        sup, slept = _failing_supervisor(
            tmp_path, monkeypatch, fail_times=2, retry_backoff_ms=40.0,
        )
        sup.process([Record("k", sc.A, 1)])
        sup.process([Record("k", sc.B, 2)])
        waits.append(tuple(slept))
        monkeypatch.undo()
    assert waits[0] == waits[1]  # (seq, attempt)-seeded jitter


def test_retry_backoff_zero_disables(tmp_path, monkeypatch):
    sup, slept = _failing_supervisor(
        tmp_path, monkeypatch, fail_times=1, retry_backoff_ms=0.0,
    )
    sup.process([Record("k", sc.A, 1)])
    sup.process([Record("k", sc.B, 2)])
    assert sup.recoveries == 1
    assert slept == []
    assert sup.retry_backoff_ms_total == 0.0
