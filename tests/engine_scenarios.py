"""Shared numeric scenario definitions for the engine differential suites.

Device predicates must be traceable, so the reference scenarios
(``NFATest.java``) are re-expressed over numeric values: letters become int
codes (A=0, B=1, C=2, D=3, noise=4), the stock events become dicts of
scalars.  The SAME pattern objects run on both :class:`OracleNFA` (host
values) and :class:`TPUMatcher` (traced values) — the predicate algebra's
dual host/traced semantics (``pattern/predicate.py``) is what makes this
possible.
"""

from typing import List

from kafkastreams_cep_tpu import OracleNFA, Query
from kafkastreams_cep_tpu.engine import EngineConfig, MatcherSession, TPUMatcher

A, B, C, D, X = 0, 1, 2, 3, 4


def value_is(code):
    return lambda k, v, ts, st: v == code


def strict3():
    """NFATest.java:42-67 — strict contiguity SEQ(first, second, latest)."""
    return (
        Query()
        .select("first").where(value_is(A))
        .then()
        .select("second").where(value_is(B))
        .then()
        .select("latest").where(value_is(C))
        .build()
    )


def kleene_one_or_more():
    """NFATest.java:69-101 — SEQ(a, b, c+, d)."""
    return (
        Query()
        .select("firstStage").where(value_is(A))
        .then()
        .select("secondStage").where(value_is(B))
        .then()
        .select("thirdStage").one_or_more().where(value_is(C))
        .then()
        .select("latestState").where(value_is(D))
        .build()
    )


def skip_till_next():
    """NFATest.java:104-132."""
    return (
        Query()
        .select("first").where(value_is(A))
        .then()
        .select("second").skip_till_next_match().where(value_is(C))
        .then()
        .select("latest").skip_till_next_match().where(value_is(D))
        .build()
    )


def skip_till_any():
    """NFATest.java:134-172 — nondeterministic branching."""
    return (
        Query()
        .select("first").where(value_is(A))
        .then()
        .select("second").where(value_is(B))
        .then()
        .select("three").skip_till_any_match().where(value_is(C))
        .then()
        .select("latest").skip_till_any_match().where(value_is(D))
        .build()
    )


def stock_query():
    """The SASE stock query (NFATest.java:203-245, README.md:22-60) over
    dict-of-scalar values ``{"price", "volume"}``."""
    return (
        Query()
        .select()
        .where(lambda k, v, ts, st: v["volume"] > 1000)
        .fold("avg", lambda k, v, curr: v["price"])
        .then()
        .select()
        .zero_or_more()
        .skip_till_next_match()
        .where(lambda k, v, ts, st: v["price"] > st.get("avg"))
        .fold("avg", lambda k, v, curr: (curr + v["price"]) // 2)
        .fold("volume", lambda k, v, curr: v["volume"])
        .then()
        .select()
        .skip_till_next_match()
        .where(lambda k, v, ts, st: v["volume"] < 0.8 * st.get_or_else("volume", 0))
        .within(1, "h")
        .build()
    )


STOCKS = [
    {"price": 100, "volume": 1010},
    {"price": 120, "volume": 990},
    {"price": 120, "volume": 1005},
    {"price": 121, "volume": 999},
    {"price": 120, "volume": 999},
    {"price": 125, "volume": 750},
    {"price": 120, "volume": 950},
    {"price": 120, "volume": 700},
]


def default_config(**overrides) -> EngineConfig:
    base = dict(
        max_runs=16, slab_entries=48, slab_preds=6, dewey_depth=10, max_walk=10
    )
    base.update(overrides)
    return EngineConfig(**base)


def canon(seq) -> dict:
    """Canonical, order-insensitive form of a Sequence for comparison."""
    return {
        stage: sorted(e.offset for e in events)
        for stage, events in seq.as_map().items()
    }


def run_differential(
    pattern, values, config: EngineConfig = None, ts0: int = 1000
) -> List:
    """Step the oracle and the array engine over one trace, asserting
    identical match emission (count, order, content) at every event."""
    oracle = OracleNFA.from_pattern(pattern)
    session = MatcherSession(TPUMatcher(pattern, config or default_config()))
    matches = []
    for i, v in enumerate(values):
        o = oracle.match(None, v, ts0 + i)
        e = session.match(None, v, ts0 + i)
        assert len(o) == len(e), f"event {i}: oracle {o} vs engine {e}"
        for a, b in zip(o, e):
            assert a == b, f"event {i}: oracle {a} vs engine {b}"
        matches.extend(e)
    counters = session.counters()
    assert all(c == 0 for c in counters.values()), counters
    return matches
