"""Ingestion guard (runtime/ingest.py) — ISSUE 5 tentpole suites.

Contracts under test:

1. *Bounded-skew absorption*: any shuffle of a trace whose timestamp
   inversions are bounded by the grace drains **bit-identical matches,
   emission order, and loss counters** to the in-order run — on the jnp,
   fused walk-kernel, and whole-scan kernel paths (interpret mode; CPU
   CI checks parity, not perf).
2. *Per-record quarantine*: schema/lane/time defects and too-late events
   are diverted to the dead-letter queue with typed reasons — never a
   batch-level exception in the default mode; ``on_bad_record="raise"``
   preserves the strict behavior with record index + key in the message.
3. *Loss counters*: ``late_dropped`` / ``quarantined`` /
   ``reorder_evictions`` all zero ⇒ loss-free; depth-cap evictions are
   counted, never silent.
4. *Durability*: the reorder buffer is first-class state — it survives
   checkpoint/restore and live migration with records held, and chaos
   schedules that crash with a non-empty buffer (including the new
   ``ingest.admit`` / ``ingest.release`` failpoints) still converge to
   the fault-free oracle with exactly-once emission.
"""

import collections
import dataclasses
import os

import numpy as np
import pytest

import engine_scenarios as sc
from kafkastreams_cep_tpu.engine import EngineConfig
from kafkastreams_cep_tpu.engine import sizing
from kafkastreams_cep_tpu.runtime import (
    CEPProcessor,
    IngestPolicy,
    InputRejected,
    Record,
    Supervisor,
    restore_processor,
    save_checkpoint,
)
from kafkastreams_cep_tpu.runtime.ingest import (
    REASON_LANE_OVERFLOW,
    REASON_LATE,
    REASON_SCHEMA,
    REASON_TIME_RANGE,
    IngestGuard,
)
from kafkastreams_cep_tpu.runtime.migrate import (
    canonical_state,
    migrate_processor,
)
from kafkastreams_cep_tpu.utils import failpoints as fp

GRACE = 8


def trace(pattern_vals, keys=("k0", "k1"), ts0=1000, step=2):
    """Every key sees the full value sequence (so per-key patterns can
    match), interleaved with globally distinct, strictly increasing
    timestamps (ties would make 'the in-order run' ambiguous; the guard
    breaks ties by arrival)."""
    recs, t = [], 0
    for v in pattern_vals:
        for k in keys:
            recs.append(Record(k, v, ts0 + step * t))
            t += 1
    return recs


def bounded_shuffle(records, skew, seed):
    """Arrival order whose timestamp inversions are <= ``skew`` ms: sort
    by ts + U(0, skew) — if y precedes x with ts(y) > ts(x) then
    ts(y) - ts(x) <= skew (the classic bounded-disorder model)."""
    rng = np.random.default_rng(seed)
    key = [r.timestamp + rng.uniform(0, skew) for r in records]
    return [records[i] for i in np.argsort(key, kind="stable")]


def run_guarded(pattern, records, num_lanes=2, batch=5, grace=GRACE,
                config=None, **pol):
    proc = CEPProcessor(
        pattern, num_lanes, config or sc.default_config(), epoch=0,
        gc_interval=0, ingest=IngestPolicy(grace_ms=grace, **pol),
    )
    out = []
    for i in range(0, len(records), batch):
        out += proc.process(records[i:i + batch])
    out += proc.drain_ingest()
    out += proc.flush()
    return proc, [(k, sc.canon(s)) for k, s in out]


VALS = [sc.A, sc.B, sc.C, sc.X, sc.A, sc.B, sc.D, sc.C, sc.A, sc.B,
        sc.C, sc.X, sc.A, sc.D, sc.B, sc.C, sc.X, sc.A, sc.B, sc.C]


@pytest.mark.parametrize(
    "pattern,seed",
    [(sc.strict3, 0), (sc.strict3, 1), (sc.skip_till_any, 0),
     (sc.skip_till_any, 2)],
)
def test_bounded_skew_shuffle_is_bit_identical_jnp(pattern, seed):
    recs = trace(VALS)
    p_ref, m_ref = run_guarded(pattern(), recs)
    assert m_ref  # a vacuous (matchless) parity proves nothing
    p_sh, m_sh = run_guarded(
        pattern(), bounded_shuffle(recs, GRACE, seed)
    )
    assert m_sh == m_ref  # content AND emission order
    assert p_sh.batch.counters(p_sh.state) == p_ref.batch.counters(
        p_ref.state
    )
    assert not any(p_sh._guard.loss_counters().values())
    assert not any(p_ref._guard.loss_counters().values())


@pytest.mark.parametrize(
    "env,mode",
    [
        ("CEP_WALK_KERNEL", "interpret"),
        # Scan-kernel interpret parity is tier-2 (-m slow, ~15 s); the
        # walk-kernel variant keeps interpret coverage in tier-1
        # (ROADMAP tier-1 budget note, PR 13).
        pytest.param(
            "CEP_SCAN_KERNEL", "interpret", marks=pytest.mark.slow
        ),
    ],
)
def test_bounded_skew_shuffle_is_bit_identical_kernel(env, mode):
    """The same parity through the Pallas walk/scan kernels (128-lane
    floor is the kernels' LANE_BLOCK).  The in-order reference runs on
    the jnp path — jnp↔kernel parity is pinned by the kernel suites, so
    this closes the triangle: shuffled-through-kernel ≡ in-order-jnp.
    Trace kept small: interpret-mode whole-scan cost scales with T."""
    recs = trace([sc.A, sc.B, sc.C, sc.X, sc.A, sc.B, sc.C],
                 keys=("k0", "k1"))
    p_ref, m_ref = run_guarded(sc.strict3(), recs, num_lanes=128, batch=7)
    assert m_ref
    assert not p_ref.batch.uses_walk_kernel
    os.environ[env] = mode
    try:
        p_sh, m_sh = run_guarded(
            sc.strict3(), bounded_shuffle(recs, GRACE, 5), num_lanes=128,
            batch=7,
        )
        if env == "CEP_WALK_KERNEL":
            assert p_sh.batch.uses_walk_kernel
        else:
            assert p_sh.batch.uses_scan_kernel
    finally:
        os.environ[env] = "0"
    assert m_sh == m_ref
    assert p_sh.batch.counters(p_sh.state) == p_ref.batch.counters(
        p_ref.state
    )
    assert not any(p_sh._guard.loss_counters().values())


def test_release_batching_matches_watermark_not_arrival():
    """Records stay held until the watermark (max seen - grace) passes
    them; a later batch's newer timestamps release them."""
    proc = CEPProcessor(
        sc.strict3(), 1, sc.default_config(), epoch=0, gc_interval=0,
        ingest=IngestPolicy(grace_ms=10),
    )
    assert proc.process([Record("k", sc.A, 1000)]) == []
    assert proc._guard.held == 1
    proc.process([Record("k", sc.B, 1005)])
    assert proc._guard.held == 2  # watermark 995 < 1000
    proc.process([Record("k", sc.C, 1020)])  # watermark 1010: A,B release
    assert proc._guard.held == 1
    out = proc.drain_ingest()
    assert len(out) == 1  # A,B,C in timestamp order
    assert proc._guard.held == 0


# -- quarantine / dead-letter -------------------------------------------------


def test_quarantine_typed_reasons_never_batch_exception():
    proc = CEPProcessor(
        sc.strict3(), 1, sc.default_config(), epoch=0, gc_interval=0,
        ingest=IngestPolicy(grace_ms=2),
    )
    out = proc.process([
        Record("k0", sc.A, 1000),
        Record("k0", {"nested": 1}, 1001),       # schema: structure
        Record("k0", 2.5, 1002),                 # schema: float-in-int
        Record("k1", sc.X, 1003),                # lane overflow (1 lane)
        Record("k0", sc.B, 10**14, None),        # time range
        Record("k0", sc.B, 1004),
        Record("k0", sc.C, 1005),
    ])
    out += proc.drain_ingest()
    g = proc._guard
    assert g.reason_counts == {
        REASON_SCHEMA: 2, REASON_LANE_OVERFLOW: 1, REASON_TIME_RANGE: 1,
    }
    reasons = [d.reason for d in g.dead_letters]
    assert reasons == [
        REASON_SCHEMA, REASON_SCHEMA, REASON_LANE_OVERFLOW,
        REASON_TIME_RANGE,
    ]
    assert all(d.corr == "stream-1" for d in g.dead_letters)
    # The healthy remainder of the batch still matched.
    assert [(k, sc.canon(s)) for k, s in out] == [
        ("k0", {"first": [0], "second": [1], "latest": [2]})
    ]


def test_late_records_are_dead_lettered_not_raised():
    recs = [
        Record("k", sc.A, 1000),
        Record("k", sc.B, 1050),
        Record("k", sc.C, 1001),  # 41 ms behind watermark 1042
    ]
    proc, _ = run_guarded(sc.strict3(), recs, num_lanes=1, batch=1)
    g = proc._guard
    assert g.late_dropped == 1
    assert g.dead_letters[-1].reason == REASON_LATE
    assert "behind the watermark" in g.dead_letters[-1].detail


def test_strict_mode_raises_with_record_index_and_key():
    proc = CEPProcessor(
        sc.strict3(), 1, sc.default_config(), epoch=0, gc_interval=0,
        ingest=IngestPolicy(grace_ms=2, on_bad_record="raise"),
    )
    with pytest.raises(InputRejected) as ei:
        proc.process([
            Record("k0", sc.A, 1000),
            Record("k0", {"bad": 1}, 1001),
        ])
    msg = str(ei.value)
    assert "record 1" in msg and "'k0'" in msg and "schema" in msg


def test_dead_letter_cap_drops_oldest_and_counts():
    proc = CEPProcessor(
        sc.strict3(), 1, sc.default_config(), epoch=0, gc_interval=0,
        ingest=IngestPolicy(grace_ms=0, dead_letter_cap=2),
    )
    proc.process(
        [Record("k", sc.A, 1000)]
        + [Record("k", {"bad": i}, 1001 + i) for i in range(4)]
    )
    g = proc._guard
    assert len(g.dead_letters) == 2
    assert g.dead_letter_dropped == 2
    assert g.quarantined == 4  # the counter never forgets


def test_reorder_depth_eviction_is_counted_never_silent():
    recs = bounded_shuffle(trace(VALS, keys=("k",)), GRACE, 9)
    proc, _ = run_guarded(
        sc.strict3(), recs, num_lanes=1, grace=10**6, reorder_depth=4,
    )
    g = proc._guard
    assert g.reorder_evictions > 0
    # Nothing lost to the engine: every admitted record was released.
    assert g.admitted == g.released
    assert proc.metrics.records_in == g.admitted


def test_admission_dedup_absorbs_source_offset_replay():
    recs = [
        Record("k", v, 1000 + 2 * i, offset=i)
        for i, v in enumerate([sc.A, sc.B, sc.C])
    ]
    proc = CEPProcessor(
        sc.strict3(), 1, sc.default_config(), epoch=0, gc_interval=0,
        ingest=IngestPolicy(grace_ms=2),
    )
    out = proc.process(recs)
    out += proc.process(recs)  # at-least-once re-delivery
    out += proc.drain_ingest()
    assert proc.metrics.duplicates_dropped == 3
    assert len(out) == 1  # matched exactly once


def test_guard_rejects_columnar_path():
    proc = CEPProcessor(
        sc.strict3(), 1, sc.default_config(), epoch=0,
        ingest=IngestPolicy(),
    )
    with pytest.raises(ValueError, match="per-record path"):
        proc.process_columns(
            np.zeros(1, np.int64), np.zeros(1, np.int64),
            np.zeros(1, np.int64),
        )


# -- durability ---------------------------------------------------------------


def test_checkpoint_restore_with_held_records(tmp_path):
    recs = bounded_shuffle(trace(VALS, keys=("k0", "k1")), GRACE, 3)
    p_ref, m_ref = run_guarded(sc.strict3(), recs)

    proc = CEPProcessor(
        sc.strict3(), 2, sc.default_config(), epoch=0, gc_interval=0,
        ingest=IngestPolicy(grace_ms=GRACE),
    )
    out = []
    for i in range(0, 10, 5):
        out += proc.process(recs[i:i + 5])
    assert proc._guard.held > 0
    path = str(tmp_path / "held.ckpt")
    save_checkpoint(proc, path)

    res = restore_processor(sc.strict3(), path)
    assert res._guard.held == proc._guard.held
    assert res._guard.policy == proc._guard.policy
    for i in range(10, len(recs), 5):
        out += res.process(recs[i:i + 5])
    out += res.drain_ingest()
    assert [(k, sc.canon(s)) for k, s in out] == m_ref
    assert not any(res._guard.loss_counters().values())


def test_migration_carries_guard_with_held_records():
    recs = bounded_shuffle(trace(VALS, keys=("k0", "k1")), GRACE, 4)
    _, m_ref = run_guarded(sc.strict3(), recs)

    proc = CEPProcessor(
        sc.strict3(), 2, sc.default_config(), epoch=0, gc_interval=0,
        ingest=IngestPolicy(grace_ms=GRACE),
    )
    out = []
    for i in range(0, 10, 5):
        out += proc.process(recs[i:i + 5])
    held = proc._guard.held
    assert held > 0
    wide = dataclasses.replace(
        sc.default_config(), max_runs=32, slab_entries=64
    )
    proc = migrate_processor(sc.strict3(), proc, wide)
    assert proc._guard.held == held
    for i in range(10, len(recs), 5):
        out += proc.process(recs[i:i + 5])
    out += proc.drain_ingest()
    assert [(k, sc.canon(s)) for k, s in out] == m_ref


# -- supervisor integration ---------------------------------------------------


def test_supervisor_ingest_escalation_widens_grace(tmp_path):
    """A disordered stream against grace=0: late drops trip the
    sizing rows (late_dropped -> grace_ms) and the supervisor widens the
    live policy forward-only, pinning it with a snapshot."""
    recs = bounded_shuffle(trace(VALS, keys=("k",)), 6, 11)
    sup = Supervisor(
        sc.strict3(), 1, sc.default_config(),
        checkpoint_path=str(tmp_path / "esc.ckpt"),
        checkpoint_every=100, gc_interval=0, epoch=0,
        auto_escalate=True, ingest=IngestPolicy(grace_ms=0),
    )
    for i in range(0, len(recs), 4):
        sup.process(recs[i:i + 4])
    guard = sup.processor._guard
    assert guard.late_dropped > 0
    assert sup.ingest_escalations >= 1
    assert guard.policy.grace_ms >= 1000
    assert sup.checkpoints >= 1  # the widened policy is pinned

    # The pinned policy survives a resume.
    del sup
    res = Supervisor.resume(
        sc.strict3(), 1, sc.default_config(),
        checkpoint_path=str(tmp_path / "esc.ckpt"), gc_interval=0,
        epoch=0, ingest=IngestPolicy(grace_ms=0),
    )
    assert res.processor._guard.policy.grace_ms >= 1000


def test_escalate_ingest_rows():
    pol = IngestPolicy(grace_ms=0, reorder_depth=64)
    wider = sizing.escalate_ingest(pol, {"late_dropped": 3})
    assert wider.grace_ms >= 1000 and wider.reorder_depth == 64
    wider2 = sizing.escalate_ingest(pol, {"reorder_evictions": 1})
    assert wider2.reorder_depth > 64 and wider2.grace_ms == 0
    capped = sizing.escalate_ingest(
        pol, {"late_dropped": 1}, max_policy=IngestPolicy(grace_ms=0)
    )
    assert capped is None  # at the ceiling: nothing can grow


def test_guard_state_roundtrip_is_exact():
    g = IngestGuard(IngestPolicy(grace_ms=5, reorder_depth=8))
    for i, r in enumerate(trace(VALS[:8], keys=("k",))):
        g.push(r._replace(offset=i))
        g.source_hw[0] = i + 1
    g.quarantine(Record("k", 99, 1), REASON_SCHEMA, "detail", "corr-1")
    g.release()
    h = IngestGuard.from_state(g.to_state())
    assert h.to_state() == g.to_state()
    assert h.held == g.held and h.watermark == g.watermark
    assert h.drain() == g.drain()


# -- chaos: crashes with a non-empty reorder buffer ---------------------------

CHAOS_CFG = EngineConfig(
    max_runs=16, slab_entries=48, slab_preds=8, dewey_depth=16, max_walk=12
)
CHAOS_FAULTS = (
    ("ingest.admit", 0.12, 1),
    ("ingest.release", 0.12, 1),
    ("device.dispatch", 0.08, 1),
    ("device.result", 0.08, 1),
    ("checkpoint.save", 0.08, 1),
    ("journal.append", 0.08, 1),
)


def chaos_batches(seed, grace=6):
    """A seeded 2-key stream, bounded-skew shuffled, with explicit
    source offsets in ARRIVAL order (the Kafka model: offsets are log
    positions; event time is what's disordered)."""
    rng = np.random.default_rng(seed)
    vals = [int(rng.integers(0, 5)) for _ in range(12)]
    recs = trace(vals, keys=("k0", "k1"))
    shuffled = bounded_shuffle(recs, grace, seed + 77)
    offs = collections.defaultdict(int)
    withoff = []
    for r in shuffled:
        withoff.append(r._replace(offset=offs[r.key]))
        offs[r.key] += 1
    return [withoff[i:i + 4] for i in range(0, len(withoff), 4)]


def mk_guarded_sup(ck, jr, resume=False, grace=6):
    args = (sc.skip_till_any(), 2, CHAOS_CFG)
    kw = dict(
        checkpoint_path=ck, journal_path=jr, checkpoint_every=2,
        gc_interval=0, epoch=0, ingest=IngestPolicy(grace_ms=grace),
    )
    if resume:
        return Supervisor.resume(*args, **kw)
    return Supervisor(*args, **kw)


def canon_match(key, seq):
    return (key, tuple(sorted(
        (stage, tuple(sorted(e.offset for e in events)))
        for stage, events in seq.as_map().items()
    )))


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_ingest_chaos_crash_with_held_records(seed, tmp_path):
    batches = chaos_batches(seed)
    # Fault-free oracle, same batching, same guard.
    oracle = mk_guarded_sup(
        str(tmp_path / "o.ckpt"), str(tmp_path / "o.jrnl")
    )
    want = collections.Counter()
    for b in batches:
        for k, s in oracle.process(b):
            want[canon_match(k, s)] += 1
    for k, s in oracle.drain_ingest():
        want[canon_match(k, s)] += 1

    ck, jr = str(tmp_path / f"c{seed}.ckpt"), str(tmp_path / f"c{seed}.jrnl")
    sup = mk_guarded_sup(ck, jr)
    sup._sleep = lambda s: None  # no real backoff waits in CI
    rng = np.random.default_rng(seed + 500)
    emitted = collections.Counter()
    crashes_with_held = 0
    i, guard_iter = 0, 0
    while i < len(batches):
        guard_iter += 1
        assert guard_iter < 200, "chaos made no progress"
        for site, p, times in CHAOS_FAULTS:
            if rng.random() < p:
                fp.FAILPOINTS.arm(site, times=times)
        crash_after = rng.random() < 0.22
        try:
            for k, s in sup.process(batches[i]):
                emitted[canon_match(k, s)] += 1
            i += 1
        except (fp.InjectedFault, fp.InjectedIOError):
            crash_after = True
        finally:
            fp.FAILPOINTS.clear()
        if crash_after:
            if sup.processor._guard.held > 0:
                crashes_with_held += 1
            del sup
            sup = mk_guarded_sup(ck, jr, resume=True)
            sup._sleep = lambda s: None
            i = 0  # at-least-once source re-submits; dedup absorbs
    for k, s in sup.drain_ingest():
        emitted[canon_match(k, s)] += 1

    assert emitted == want, f"seed {seed}: exactly-once violated"
    import jax

    ca = canonical_state(sup.processor.state)
    cb = canonical_state(oracle.processor.state)
    for n, (x, y) in enumerate(
        zip(jax.tree_util.tree_leaves(ca), jax.tree_util.tree_leaves(cb))
    ):
        np.testing.assert_array_equal(
            np.asarray(x), np.asarray(y),
            err_msg=f"seed {seed}: state leaf {n} diverged",
        )
    assert not any(sup.processor.counters().values())
    # The suite as a whole must see crashes with records in the buffer;
    # per-seed it is stochastic, so stash the observation for the
    # aggregate assertion below.
    _HELD_CRASHES.append(crashes_with_held)


_HELD_CRASHES = []


def test_ingest_chaos_observed_crashes_with_held_records():
    """Aggregate over the seeds above: at least one crash landed while
    the reorder buffer was non-empty (the adversarial window the
    snapshot+journal protocol must cover)."""
    assert sum(_HELD_CRASHES) > 0


@pytest.mark.slow
@pytest.mark.parametrize("seed", range(40, 80))
def test_ingest_chaos_sweep(seed, tmp_path):
    test_ingest_chaos_crash_with_held_records(seed, tmp_path)
