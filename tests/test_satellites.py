"""Regression tests for the ISSUE 1 satellite fixes: columnar-ingestion
validation and key-code parity (runtime/processor.py), the sharded
scan-kernel fallback/warning (parallel/sharding.py), and the narrowed
fused-kernel fallback classification (parallel/batch.py)."""

import logging
import os

import jax
import numpy as np
import pytest

import engine_scenarios as sc
from kafkastreams_cep_tpu import Query
from kafkastreams_cep_tpu.engine import EngineConfig
from kafkastreams_cep_tpu.parallel import BatchMatcher, ShardedMatcher, key_mesh
from kafkastreams_cep_tpu.parallel.batch import is_lowering_error
from kafkastreams_cep_tpu.runtime import CEPProcessor, Record


def key_pair_pattern():
    """Two-stage pattern whose SECOND stage also needs the key code — a
    mixed record/column ingestion only matches if both paths encode the
    same key identically."""
    return (
        Query()
        .select("a").where(lambda k, v, ts, st: (k == 5) & (v == 0))
        .then()
        .select("b").where(lambda k, v, ts, st: (k == 5) & (v == 1))
        .build()
    )


# ---------------------------------------------------------------------------
# processor.py:408 — column length validation before the native pack
# ---------------------------------------------------------------------------


def test_process_columns_rejects_short_timestamps():
    proc = CEPProcessor(sc.strict3(), 2, sc.default_config())
    with pytest.raises(ValueError, match="timestamps"):
        proc.process_columns(
            np.array([1, 2]), np.array([0, 0], dtype=np.int32), [1]
        )


def test_process_columns_rejects_scalar_timestamps():
    proc = CEPProcessor(sc.strict3(), 2, sc.default_config())
    with pytest.raises(ValueError, match="timestamps"):
        proc.process_columns(
            np.array([1, 2]), np.array([0, 0], dtype=np.int32), 7
        )


def test_process_columns_rejects_2d_keys():
    proc = CEPProcessor(sc.strict3(), 2, sc.default_config())
    with pytest.raises(ValueError, match="keys"):
        proc.process_columns(
            np.zeros((2, 2), dtype=np.int32),
            np.array([0, 0], dtype=np.int32),
            [1, 2],
        )


def test_process_columns_rejection_is_atomic():
    """A rejected batch must not consume lane slots or advance offsets."""
    proc = CEPProcessor(sc.strict3(), 2, sc.default_config())
    with pytest.raises(ValueError):
        proc.process_columns(
            np.array([1, 2]), np.array([0, 0], dtype=np.int32), [1]
        )
    assert proc._lane_of == {}
    # A well-formed batch afterwards works normally.
    out = proc.process_columns(
        np.array([1, 2]), np.array([sc.A, sc.A], dtype=np.int32), [1, 1]
    )
    assert out == []


# ---------------------------------------------------------------------------
# processor.py:502 — object-dtype key columns keep per-element key codes
# ---------------------------------------------------------------------------


def test_object_keys_mixed_paths_same_key_codes():
    """An int key ingested via records and via an object-dtype column must
    present the same ``key`` value to predicates (the record path's
    _key_code rule, per element)."""
    proc = CEPProcessor(key_pair_pattern(), 4, sc.default_config())
    # Stage a: record path, key 5 (int -> code 5).
    assert proc.process([Record(5, 0, 1)]) == []
    # Stage b: columnar path; the object dtype (mixed with a string key)
    # must NOT degrade key 5's code to its lane index.
    out = proc.process_columns(
        np.array([5, "other"], dtype=object),
        np.array([1, 1], dtype=np.int32),
        [2, 2],
    )
    assert len(out) == 1 and out[0][0] == 5


def test_object_keys_column_only_match():
    """Same-key pair entirely through the columnar path with object keys."""
    proc = CEPProcessor(key_pair_pattern(), 4, sc.default_config())
    out = proc.process_columns(
        np.array([5, "other", 5], dtype=object),
        np.array([0, 0, 1], dtype=np.int32),
        [1, 1, 2],
    )
    assert len(out) == 1 and out[0][0] == 5


def test_object_keys_out_of_range_int_still_lane_coded():
    """An int key outside int32 keeps the lane-code rule, matching the
    record path for the same key."""
    proc = CEPProcessor(sc.strict3(), 2, sc.default_config())
    big = 2**40
    out = proc.process_columns(
        np.array([big, "x"], dtype=object),
        np.array([sc.A, sc.A], dtype=np.int32),
        [1, 1],
    )
    assert out == []
    assert proc._lane_of[big] == 0  # assigned; no crash, lane-coded


# ---------------------------------------------------------------------------
# sharding.py — scan-kernel parity with BatchMatcher: warning + fallback
# ---------------------------------------------------------------------------


@pytest.mark.skipif(jax.device_count() < 8, reason="needs 8 devices")
def test_sharded_scan_kernel_infeasible_shard_warns(caplog):
    cfg = EngineConfig(
        max_runs=8, slab_entries=16, slab_preds=4, dewey_depth=8, max_walk=8
    )
    mesh = key_mesh(jax.devices()[:8])
    os.environ["CEP_SCAN_KERNEL"] = "1"
    try:
        with caplog.at_level(
            logging.WARNING, logger="kafkastreams_cep_tpu.parallel.sharding"
        ):
            m = ShardedMatcher(sc.strict3(), 8, mesh, cfg)  # 1 lane/shard
    finally:
        os.environ["CEP_SCAN_KERNEL"] = "0"
    assert not m.uses_scan_kernel
    assert any("per-step path" in r.message for r in caplog.records)


# ---------------------------------------------------------------------------
# batch.py — fused-kernel fallback narrowed to lowering errors
# ---------------------------------------------------------------------------


def test_is_lowering_error_classification():
    assert is_lowering_error(NotImplementedError("no rule"))
    assert is_lowering_error(RuntimeError("Mosaic failed to compile"))
    assert is_lowering_error(ValueError("unsupported lowering for op"))
    # Transient runtime failures must NOT permanently disable the kernel.
    assert not is_lowering_error(
        RuntimeError("RESOURCE_EXHAUSTED: out of memory allocating")
    )
    assert not is_lowering_error(RuntimeError("operation was CANCELLED"))
    assert not is_lowering_error(KeyError("some-bug"))


def test_fallback_transient_error_keeps_kernel_armed():
    """A transient first-call failure propagates and the wrapper retries
    the kernel on the next call instead of permanently downgrading."""
    cfg = EngineConfig(
        max_runs=8, slab_entries=16, slab_preds=4, dewey_depth=8, max_walk=8
    )
    os.environ["CEP_WALK_KERNEL"] = "0"
    b = BatchMatcher(sc.strict3(), 4, cfg)
    calls = {"n": 0}

    def flaky_scan(state, events):
        calls["n"] += 1
        raise RuntimeError("RESOURCE_EXHAUSTED: transient")

    b.uses_scan_kernel = True
    wrapped = b._with_fallback(flaky_scan)
    for _ in range(2):
        with pytest.raises(RuntimeError, match="RESOURCE_EXHAUSTED"):
            wrapped(None, None)
    assert calls["n"] == 2  # retried the kernel, not the fallback
    assert b.uses_scan_kernel  # still armed


def test_fallback_lowering_error_downgrades_once():
    """A genuine lowering failure falls back permanently to the per-step
    path, which must produce the usual results."""
    import jax.numpy as jnp

    from kafkastreams_cep_tpu.engine import EventBatch

    cfg = EngineConfig(
        max_runs=8, slab_entries=16, slab_preds=4, dewey_depth=8, max_walk=8
    )
    os.environ["CEP_WALK_KERNEL"] = "0"
    b = BatchMatcher(sc.strict3(), 4, cfg)

    def unlowerable_scan(state, events):
        raise NotImplementedError("Unsupported lowering: fake Mosaic op")

    b.uses_scan_kernel = True
    wrapped = b._with_fallback(unlowerable_scan)
    K, T = 4, 6
    codes = np.tile(np.array([sc.A, sc.B, sc.C, 0, 0, 0], np.int32), (K, 1))
    events = EventBatch(
        key=jnp.zeros((K, T), jnp.int32),
        value=jnp.asarray(codes),
        ts=jnp.broadcast_to(
            1000 + jnp.arange(T, dtype=jnp.int32)[None, :], (K, T)
        ),
        off=jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32)[None, :], (K, T)),
        valid=jnp.ones((K, T), bool),
    )
    state, out = wrapped(b.init_state(), events)
    assert not b.uses_scan_kernel  # downgraded
    ref_state, ref_out = b.scan(b.init_state(), events)
    np.testing.assert_array_equal(
        np.asarray(out.count), np.asarray(ref_out.count)
    )
    assert int(np.asarray(out.count).sum()) > 0  # the trace really matches
