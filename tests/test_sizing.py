"""Capacity estimation (``engine/sizing.py``) — configs derived, not
hand-tuned.

The reference never sizes anything (heap-backed stores,
``CEPProcessor.java:144-149``); the array engine's static shapes are
derived here from a probe of representative traffic.  Pinned:

* ``probe`` reports counters + occupancy maxima;
* ``autosize`` grows exactly the overflowing dimension and lands on a
  config whose capacity counters are zero on the sample;
* the derived config reproduces the oracle's matches (sizing must be a
  pure capacity decision, never a semantics one).
"""

import numpy as np
import jax.numpy as jnp
import pytest

from kafkastreams_cep_tpu import OracleNFA, Query, TPUMatcher
from kafkastreams_cep_tpu.engine import EngineConfig, EventBatch, autosize, probe
from kafkastreams_cep_tpu.engine.matcher import MatcherSession
from kafkastreams_cep_tpu.engine.sizing import capacity_counters, suggest
from kafkastreams_cep_tpu.compiler.tables import lower


def kleene_pattern():
    return (
        Query()
        .select("a").where(lambda k, v, ts, st: v["x"] == 0)
        .then()
        .select("b").one_or_more().skip_till_any_match()
        .where(lambda k, v, ts, st: (0 < v["x"]) & (v["x"] < 8))
        .then()
        .select("c").where(lambda k, v, ts, st: v["x"] >= 8)
        .build()
    )


def sample_events(K=8, T=48, seed=3):
    rng = np.random.default_rng(seed)
    xs = np.concatenate(
        [np.zeros((K, 1), np.int32),
         rng.choice([0, 1, 2, 3, 9, 9], size=(K, T - 1)).astype(np.int32)],
        axis=1,
    )
    return xs, EventBatch(
        key=jnp.broadcast_to(jnp.arange(K, dtype=jnp.int32)[:, None], (K, T)),
        value={"x": jnp.asarray(xs)},
        ts=jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32)[None, :], (K, T)),
        off=jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32)[None, :], (K, T)),
        valid=jnp.ones((K, T), bool),
    )


def test_probe_reports_occupancy_and_counters():
    _, events = sample_events()
    tiny = EngineConfig(
        max_runs=4, slab_entries=8, slab_preds=2, dewey_depth=8, max_walk=6
    )
    rep = probe(kleene_pattern(), events, tiny, sweep_every=16)
    assert rep.counters["run_drops"] > 0  # branching storm overflows 4 runs
    assert rep.max_alive_runs >= 1
    assert rep.max_live_entries >= 1
    assert rep.max_vlen >= 2
    assert rep.config is tiny


def test_autosize_lands_loss_free_and_match_correct():
    xs, events = sample_events()
    tiny = EngineConfig(
        max_runs=4, slab_entries=8, slab_preds=2, dewey_depth=8, max_walk=6
    )
    cfg = autosize(kleene_pattern(), events, start=tiny, sweep_every=16)
    rep = probe(kleene_pattern(), events, cfg, sweep_every=16)
    assert not any(capacity_counters(rep.counters).values()), rep.counters

    # The derived config must agree with the oracle on a sample lane.
    session = MatcherSession(TPUMatcher(kleene_pattern(), cfg))
    oracle = OracleNFA.from_pattern(kleene_pattern())
    for t, x in enumerate(xs[0]):
        got = session.match(None, {"x": int(x)}, t, offset=t)
        want = oracle.match(None, {"x": int(x)}, t, offset=t)
        assert [m.as_map() for m in got] == [m.as_map() for m in want], t


def test_suggest_applies_structural_floors():
    pattern = kleene_pattern()
    tables = lower(pattern)
    _, events = sample_events(T=16)
    generous = EngineConfig(
        max_runs=64, slab_entries=128, slab_preds=16, dewey_depth=24,
        max_walk=32,
    )
    rep = probe(pattern, events, generous, sweep_every=8)
    cfg = suggest(tables, rep)
    # Floors: never below the chain depth + slack, and shapes 8-aligned.
    assert cfg.dewey_depth >= tables.max_hops + 2
    assert cfg.max_walk >= tables.max_hops + 2
    assert cfg.max_runs % 8 == 0 and cfg.slab_entries % 8 == 0
    # Tighter than the generous probe config in at least one dimension.
    assert (
        cfg.max_runs < generous.max_runs
        or cfg.slab_entries < generous.slab_entries
        or cfg.dewey_depth < generous.dewey_depth
    )
