"""Per-tenant isolation: quotas, admission shedding, quarantine.

The contract under test (``compiler/multitenant.py: TenantQuota`` +
``parallel/tenantbank.py: TenantIsolation`` + ``runtime/tenant.py``):

- **Quotas** mask an over-budget tenant's prefix fires in the shared
  screen; sheds are counted per tenant (``quota_shed``) and every other
  tenant's emissions stay bit-identical to an unquotaed bank.
- **Admission shedding** drops a flooding tenant's records at the front
  door with a typed ``tenant_quota`` dead letter, atomically per batch
  (a raise rolls the ledger back, so replay meets identical buckets).
- **Quarantine** circuit-breaks one query out of the bank — its columns
  go dark, its state freezes — and the rest of the bank is bit-identical
  to a bank that never contained it (the differential blast-radius
  proof, on the jnp path and both Pallas kernels).
- **Isolated escalation** attributes capacity trips per tenant and
  refuses bank-wide widening charged to an over-quota tenant.

Fixture idioms (CFG, traces, record batches) come from
test_multitenant — the loss-free precondition scoping serial parity is
the same.
"""

import dataclasses
import os

import jax
import numpy as np
import pytest

from test_multitenant import (
    CFG,
    MIXED,
    batches,
    canon,
    ge,
    lt,
    make_patterns,
    q_hybrid,
    q_stencil,
    trace,
)

from kafkastreams_cep_tpu import Query
from kafkastreams_cep_tpu.compiler.multitenant import TenantQuota
from kafkastreams_cep_tpu.engine.sizing import EscalationPolicy
from kafkastreams_cep_tpu.parallel.tenantbank import TenantBankMatcher
from kafkastreams_cep_tpu.runtime.ingest import (
    REASON_DOCS,
    REASON_TENANT_QUOTA,
    REASONS,
    policy_table_markdown,
)
from kafkastreams_cep_tpu.runtime.processor import Record
from kafkastreams_cep_tpu.runtime.tenant import (
    AdmissionPolicy,
    QuarantinePolicy,
    TenantCEP,
    TenantMisbehave,
    TenantSupervisor,
    restore_tenant,
    save_tenant_checkpoint,
)
from kafkastreams_cep_tpu.utils.failpoints import (
    FAILPOINTS,
    InjectedIOError,
    random_schedule,
)
from kafkastreams_cep_tpu.utils.telemetry import render_prometheus


# -- quota enforcement at the shared screen -----------------------------------


def test_match_rate_zero_sheds_and_isolates(monkeypatch):
    """A zero match-rate budget sheds a tenant's every prefix fire from
    the first batch; the other tenants' emissions are bit-identical to
    an unquotaed bank's and the sheds are ledgered per tenant."""
    monkeypatch.setenv("CEP_WALK_KERNEL", "0")
    K, T = 4, 16
    names = ["free", "capped", "other"]
    patterns = [MIXED[0], MIXED[1], MIXED[2]]
    bank = TenantBankMatcher(
        patterns, K, CFG, names=names,
        quotas={"capped": TenantQuota(match_rate_budget=0.0)},
    )
    ref = TenantBankMatcher(patterns, K, CFG, names=names)
    st, sr = bank.init_state(), ref.init_state()
    for b in range(3):
        ev = trace(K, T, 201 + b)
        st, out = bank.scan(st, ev)
        sr, outr = ref.scan(sr, ev)
        assert not np.asarray(out.count)[1].any(), "capped tenant emitted"
        for f in ("count", "stage", "off"):
            np.testing.assert_array_equal(
                np.asarray(getattr(out, f))[[0, 2]],
                np.asarray(getattr(outr, f))[[0, 2]],
                err_msg=f"batch {b} field {f}",
            )
    pq = bank.per_query_counters(st)
    assert pq["capped"]["quota_shed"] > 0
    assert pq["capped"]["quota_throttled"] == 1
    assert pq["free"]["quota_shed"] == 0 and pq["other"]["quota_shed"] == 0
    # Screen-level reconciliation: every offered fire was shed.
    assert bank.iso.offered_fires[1] == bank.iso.quota_shed[1] > 0
    snap = bank.metrics_snapshot(st)
    assert snap["quota_shed_total"] == int(bank.iso.quota_shed.sum())
    assert snap["quota_throttled_queries"] == 1
    text = render_prometheus(snap)
    assert 'cep_quota_shed{query="capped"}' in text


def test_pred_eval_budget_masks_offending_batch_itself(monkeypatch):
    """``pred_eval_budget`` is pre-dispatch (usage = K*T*p is known
    before the scan), so it masks the offending batch itself — no
    one-batch verdict lag, no throttle latch."""
    monkeypatch.setenv("CEP_WALK_KERNEL", "0")
    K, T = 4, 16
    names = ["free", "tiny"]
    patterns = [MIXED[0], MIXED[1]]
    # K*T*p = 4*16*2 = 128 > 100: every batch of this shape is masked.
    bank = TenantBankMatcher(
        patterns, K, CFG, names=names,
        quotas={"tiny": TenantQuota(pred_eval_budget=100)},
    )
    ref = TenantBankMatcher(patterns, K, CFG, names=names)
    st, sr = bank.init_state(), ref.init_state()
    for b in range(3):
        ev = trace(K, T, 71 + b)
        st, out = bank.scan(st, ev)
        sr, _ = ref.scan(sr, ev)
        assert not np.asarray(out.count)[1].any()
    # The mask is stateless per batch: sheds equal the unquotaed bank's
    # raw fire count exactly, and no throttle verdict is latched.
    assert bank.iso.quota_shed[1] == ref.iso.offered_fires[1] > 0
    assert bank.per_query_counters(st)["tiny"]["quota_throttled"] == 0


def test_live_lane_quota_throttles_with_one_batch_lag(monkeypatch):
    """``max_live_lanes``: the batch that first exceeds the quota
    completes (its usage rides the gate readback), the next is masked
    and its fires shed."""
    monkeypatch.setenv("CEP_WALK_KERNEL", "0")
    K, T = 4, 16
    sticky = q_hybrid(8, 3, 99)  # suffix never satisfied: runs stay live
    bank = TenantBankMatcher(
        [MIXED[0], sticky], K, CFG, names=["free", "sticky"],
        quotas={"sticky": TenantQuota(max_live_lanes=0)},
    )
    st = bank.init_state()
    # Batch 1 promotes runs; the usage bundle rides the gate readback,
    # so batch 2's scan is the first to SEE them live and latch the
    # verdict; batch 3 is the first masked one.
    st, _ = bank.scan(st, trace(K, T, 301))
    assert bank.iso.quota_shed[1] == 0
    st, _ = bank.scan(st, trace(K, T, 302))
    assert bank.iso.live_lanes[1] > 0, "fixture must leave live runs"
    assert bank.iso.throttled[1]
    assert bank.iso.over[1] == ("max_live_lanes",)
    assert bank.iso.quota_shed[1] == 0, "verdict batches complete unmasked"
    st, _ = bank.scan(st, trace(K, T, 303))
    assert bank.iso.quota_shed[1] > 0, "post-verdict fires must shed"


# -- quarantine: differential blast-radius proof ------------------------------


def _assert_quarantine_blast_radius(patterns, victim, K, T, n_batches,
                                    seed0, cfg=CFG):
    """Quarantine ``victim`` mid-stream and prove containment: every
    surviving tenant's emissions and counters are bit-identical, batch
    by batch, to a bank that NEVER contained the victim; the victim
    emits nothing once dark."""
    names = [f"q{i}" for i in range(len(patterns))]
    full = TenantBankMatcher(patterns, K, cfg, names=names)
    keep = [i for i in range(len(patterns)) if i != victim]
    ref = TenantBankMatcher([patterns[i] for i in keep], K, cfg)
    sf, sr = full.init_state(), ref.init_state()
    cut = n_batches // 2
    for b in range(n_batches):
        if b == cut:
            full.quarantine(victim)
        ev = trace(K, T, seed0 + b)
        sf, outf = full.scan(sf, ev)
        sr, outr = ref.scan(sr, ev)
        for f in ("count", "stage", "off"):
            np.testing.assert_array_equal(
                np.asarray(getattr(outf, f))[keep],
                np.asarray(getattr(outr, f)),
                err_msg=f"batch {b} field {f}",
            )
        if b >= cut:
            assert not np.asarray(outf.count)[victim].any(), (
                f"quarantined tenant emitted in batch {b}"
            )
    assert full.quarantined_qids == [victim]
    pf, pr = full.per_query_counters(sf), ref.per_query_counters(sr)
    iso_keys = ("quota_shed", "quota_throttled", "quarantined")
    for ri, qi in enumerate(keep):
        a = {k: v for k, v in pf[f"q{qi}"].items() if k not in iso_keys}
        b_ = {k: v for k, v in pr[f"q{ri}"].items() if k not in iso_keys}
        assert a == b_, f"survivor q{qi} counters diverged"
    return full, sf


@pytest.mark.parametrize("victim", [1, 3], ids=["shared-prefix", "private"])
def test_quarantine_blast_radius_jnp(monkeypatch, victim):
    """jnp path.  victim=1 shares its full prefix with query 0 (the
    shared columns must keep evaluating — the live tenant paid for
    them); victim=3 has a private prefix (its columns go dark)."""
    monkeypatch.setenv("CEP_WALK_KERNEL", "0")
    _assert_quarantine_blast_radius(
        MIXED, victim, K=5, T=20, n_batches=4, seed0=501
    )


def test_quarantine_blast_radius_walk_kernel(monkeypatch):
    from kafkastreams_cep_tpu.parallel.batch import _select_walk_kernel

    monkeypatch.setenv("CEP_WALK_KERNEL", "interpret")
    patterns = [q_hybrid(8, 3, 9), q_hybrid(9, 1, 7)]
    assert _select_walk_kernel(CFG, 2 * 64) == (True, True)
    _assert_quarantine_blast_radius(
        patterns, 0, K=64, T=12, n_batches=2, seed0=5
    )


def test_quarantine_blast_radius_scan_kernel(monkeypatch):
    monkeypatch.setenv("CEP_WALK_KERNEL", "0")
    monkeypatch.setenv("CEP_SCAN_KERNEL", "interpret")
    _assert_quarantine_blast_radius(
        MIXED[:3], 2, K=4, T=16, n_batches=2, seed0=11
    )


def test_quarantine_checkpoint_restore_and_reinstate(tmp_path):
    """Quarantine state (flags + reasons + shed ledgers) rides the
    checkpoint header; restore rebuilds enforcement WITHOUT re-entering
    the ``quarantine.enter`` failpoint, continuations are identical,
    and reinstate resumes the frozen tenant."""
    bs = batches(6, seed=7)
    t = TenantCEP(make_patterns(), 3, CFG)
    for b in bs[:2]:
        t.process(b)
    t.quarantine("crash", "manual")
    for b in bs[2:4]:
        t.process(b)
    assert t.quarantined_names() == ["crash"]
    path = str(tmp_path / "iso.ckpt")
    save_tenant_checkpoint(t, path)
    with FAILPOINTS.session():
        t2 = restore_tenant(make_patterns(), path)
        assert FAILPOINTS.hits("quarantine.enter") == 0, (
            "restore must rebuild quarantine state, not re-enter it"
        )
    assert t2.quarantined_names() == ["crash"]
    assert t2.quarantine_reasons == {"crash": "manual"}
    # Satellite: per-query counters and plan stats survive round-trip.
    assert t2.per_query_counters() == t.per_query_counters()
    assert t2.batch.bank.stats == t.batch.bank.stats
    m1 = [canon(t.process(b)) for b in bs[4:]]
    m2 = [canon(t2.process(b)) for b in bs[4:]]
    assert m1 == m2
    assert all(qn != "crash" for batch in m1 for qn, _, _ in batch)
    t.reinstate("crash")
    assert t.quarantined_names() == []
    assert t.quarantine_reasons == {}
    t.process(batches(1, seed=99)[0])  # reinstated bank stays live


def test_widen_with_quarantined_tenant(tmp_path, monkeypatch):
    """Capacity widening with a quarantined tenant present: the iso
    state (including the dark columns) migrates with the bank, the
    widened incarnation is pinned with a checkpoint, and emissions stay
    identical to an un-widened twin."""
    monkeypatch.setenv("CEP_WALK_KERNEL", "0")
    wide_cfg = dataclasses.replace(
        CFG, max_runs=16, slab_entries=48, max_walk=12
    )
    bs = batches(5, seed=41)
    ref = TenantCEP(make_patterns(), 3, CFG)
    sup = TenantSupervisor(
        make_patterns(), 3, CFG,
        checkpoint_path=str(tmp_path / "w.ckpt"), retry_backoff_ms=0.0,
    )
    for b in bs[:2]:
        assert canon(sup.process(b)) == canon(ref.process(b))
    ref.quarantine("crash", "capacity")
    sup._quarantine_for("crash", "capacity")
    sup._widen(wide_cfg)
    assert sup.tenant.batch.config.max_runs == 16
    assert sup.tenant.quarantined_names() == ["crash"]
    assert sup.checkpoints >= 1, "widening must pin a checkpoint"
    for b in bs[2:]:
        assert canon(sup.process(b)) == canon(ref.process(b))


# -- admission shedding at the front door -------------------------------------


def test_admission_shedding_ledger_and_atomic_rollback():
    """Token-bucket admission sheds a flooding tenant's records with a
    typed ``tenant_quota`` dead letter; per tenant
    ``offered == admitted + shed + quarantined_dropped``; an injected
    ``"quota.shed"`` fault rolls the whole batch's ledger back so the
    retried batch meets identical buckets."""
    t = TenantCEP(
        make_patterns(), 3, CFG,
        admission=AdmissionPolicy(rate_per_batch=2.0, burst=2.0),
    )
    bs = batches(4, per_batch=12, seed=7)
    for b in bs[:2]:
        t.process(b)
    led = t.admission_ledger()
    assert set(led) == {"alpha", "beta", "gamma"}
    for row in led.values():
        assert row["offered"] == (
            row["admitted"] + row["shed"] + row["quarantined_dropped"]
        )
    total_shed = sum(r["shed"] for r in led.values())
    assert total_shed > 0, "fixture must actually shed"
    snap = t.metrics_snapshot()
    assert snap["dead_letters"] == {REASON_TENANT_QUOTA: total_shed}
    assert snap["dead_letter_depth"] == total_shed
    assert snap["admission_shed_total"] == total_shed
    text = render_prometheus(snap)
    assert 'dead_letters_total{reason="tenant_quota"}' in text

    before = t.admission_ledger()
    with FAILPOINTS.session({"quota.shed": [0]}):
        with pytest.raises(InjectedIOError):
            t.process(bs[2])
        assert t.admission_ledger() == before, (
            "a failed batch must not half-count admission"
        )
        t.process(bs[2])  # retry replays against identical buckets
    after = t.admission_ledger()
    for k in after:
        assert after[k]["offered"] == (
            before[k]["offered"]
            + sum(1 for r in bs[2] if r.key == k)
        )


# -- supervisor: attribution, containment, recovery ---------------------------


def test_misbehave_quarantines_offender_and_defers_on_enter_fault(tmp_path):
    """A ``"tenant.misbehave"`` fault quarantines exactly the named
    tenant; a ``"quarantine.enter"`` fault during that quarantine
    leaves the bank live and un-quarantined, and the recorded decision
    is re-applied on recovery.  Compliant tenants' matches equal the
    fault-free oracle's throughout."""
    bs = batches(4, seed=19)
    ref = TenantCEP(make_patterns(), 3, CFG)
    ref_m = [canon(ref.process(b)) for b in bs]
    assert sum(len(m) for m in ref_m) > 0
    sup = TenantSupervisor(
        make_patterns(), 3, CFG,
        checkpoint_path=str(tmp_path / "q.ckpt"),
        checkpoint_every=100, max_retries=3, retry_backoff_ms=0.0,
    )
    with FAILPOINTS.session({"quarantine.enter": [0]}):
        FAILPOINTS.arm(
            "tenant.misbehave", hits=[1],
            exc=lambda: TenantMisbehave("crash"),
        )
        got = [canon(sup.process(b)) for b in bs]
        # First entry attempt faulted (deferred), recovery re-applied it.
        assert FAILPOINTS.hits("quarantine.enter") == 2
    assert sup.quarantines == {"crash": "misbehave"}
    assert sup.tenant.quarantined_names() == ["crash"]
    assert sup.tenant_quarantines == 1
    assert sup.recoveries == 1
    compliant = lambda ms: [m for m in ms if m[0] != "crash"]
    assert got[0] == ref_m[0]  # pre-quarantine batch fully intact
    assert [compliant(g) for g in got[1:]] == [
        compliant(r) for r in ref_m[1:]
    ]
    assert all(m[0] != "crash" for g in got[1:] for m in g)


def test_poisoned_predicate_attributed_and_quarantined(tmp_path):
    """A tenant predicate that starts raising at (re)trace time is
    attributed by ``find_poison`` host probing, its owner quarantined
    (columns dark — the poisoned predicate is never called again), and
    the compliant tenant's matches are unaffected even while the
    predicate keeps raising."""
    flag = {"on": False}

    def poison(th):
        def pred(k, v, ts, st, th=th):
            if flag["on"]:
                raise RuntimeError("tenant predicate corrupted")
            return v["x"] >= th

        return pred

    def make():
        return {
            "spike": q_stencil(8, 3, 7),
            "toxic": (
                Query()
                .select("a").where(ge(8)).then()
                .select("b").where(lt(3)).then()
                .select("c").where(poison(7)).build()
            ),
        }

    def kv(key, x, ts):
        return Record(key=key, value={"x": x}, timestamp=ts)

    xs1, xs2, xs3 = (
        [9, 2, 8],
        [9, 1, 7, 8, 0, 9, 9, 2, 8],  # 9 records: a bigger T bucket
        [8, 2, 7],
    )
    ts = iter(range(1, 100))
    b1 = [kv("alpha", x, next(ts)) for x in xs1]
    b2 = [kv("alpha", x, next(ts)) for x in xs2]
    b3 = [kv("alpha", x, next(ts)) for x in xs3]

    sup = TenantSupervisor(
        make(), 2, CFG,
        checkpoint_path=str(tmp_path / "p.ckpt"),
        checkpoint_every=10, max_retries=2, retry_backoff_ms=0.0,
    )
    got = [canon(sup.process(b1))]
    flag["on"] = True  # the retrace forced by b2's batch shape raises
    got.append(canon(sup.process(b2)))
    got.append(canon(sup.process(b3)))  # still poisoned, still contained
    assert sup.quarantines == {"toxic": "predicate_raise"}
    assert sup.tenant.quarantined_names() == ["toxic"]
    assert sup.recoveries >= 1

    flag["on"] = False
    oracle = TenantCEP(make(), 2, CFG)
    ref_m = [canon(oracle.process(b)) for b in (b1, b2, b3)]
    spikes = lambda ms: [m for m in ms if m[0] == "spike"]
    assert [spikes(g) for g in got] == [spikes(r) for r in ref_m]
    assert sum(len(spikes(r)) for r in ref_m) > 0


def test_escalation_denied_for_over_quota_tenant(tmp_path):
    """Capacity trips attributed to a tenant that is over its declared
    quota refuse the bank-wide widening (``tenant_escalation_denied``)
    and, at the denial streak, quarantine the offender — one tenant
    cannot grow everyone's engine."""
    patterns = {
        "spike": q_stencil(8, 3, 7),
        "flood": q_hybrid(0, 10, 99),  # every pair promotes, never closes
    }
    sup = TenantSupervisor(
        patterns, 3, CFG,
        checkpoint_path=str(tmp_path / "d.ckpt"), retry_backoff_ms=0.0,
        auto_escalate=EscalationPolicy(),
        quarantine_policy=QuarantinePolicy(trip_streak=1),
        quotas={"flood": TenantQuota(max_live_lanes=1)},
    )
    # Batch 1 stays under max_runs (no trip while the live-lane verdict
    # is still unlatched — usage rides the readback with a one-batch
    # lag); batch 2's promotions overflow the run queue WITH the quota
    # violation latched, so the trip is denied, not escalated.
    for b in batches(3, per_batch=16, seed=13):
        sup.process(b)
    assert sup.tenant_escalation_denied >= 1
    assert sup.quarantines.get("flood") == "capacity"
    assert sup.escalations == 0
    assert sup.tenant.batch.config.max_runs == CFG.max_runs, (
        "a denied escalation must leave the bank config untouched"
    )
    pq = sup.per_query_counters()
    assert pq["flood"]["run_drops"] > 0, "fixture must actually trip"
    assert pq["spike"]["run_drops"] == 0
    snap = sup.metrics_snapshot()
    assert snap["tenant_escalation_denied"] == sup.tenant_escalation_denied
    assert snap["tenant_quarantines"] == 1


def test_escalation_widens_for_compliant_trips(tmp_path):
    """The same trip pattern WITHOUT a violated quota escalates: the
    bank widens live (state migrated, checkpoint pinned) and keeps
    processing."""
    patterns = {
        "spike": q_stencil(8, 3, 7),
        "greedy": q_hybrid(0, 10, 99),
    }
    sup = TenantSupervisor(
        patterns, 3, CFG,
        checkpoint_path=str(tmp_path / "e.ckpt"), retry_backoff_ms=0.0,
        auto_escalate=EscalationPolicy(),
    )
    bs = batches(2, per_batch=45, seed=7)
    sup.process(bs[0])
    assert sup.escalations >= 1
    assert sup.tenant_escalation_denied == 0
    assert sup.quarantines == {}
    assert sup.tenant.batch.config.max_runs > CFG.max_runs
    assert sup.checkpoints >= 1, "widening must pin a checkpoint"
    sup.process(bs[1])  # the widened bank keeps processing


def test_retry_backoff_deterministic(tmp_path):
    """Retry and recovery-loop backoff follow the supervisor discipline:
    exponential in attempt, capped, jitter seeded by (batches, attempt)
    — two identical runs wait identically; 0 disables."""

    def run(tag):
        sup = TenantSupervisor(
            make_patterns(), 3, CFG,
            checkpoint_path=str(tmp_path / f"b{tag}.ckpt"),
            max_retries=3, retry_backoff_ms=100.0,
            retry_backoff_cap_ms=400.0,
        )
        sleeps = []
        sup._sleep = sleeps.append
        bs = batches(2, seed=19)
        sup.process(bs[0])
        with FAILPOINTS.session({"device.dispatch": [0, 1]}):
            sup.process(bs[1])
        return sup, sleeps

    sup1, s1 = run("x")
    sup2, s2 = run("y")
    assert s1 == s2, "backoff schedule must be deterministic"
    # One retry backoff plus one recovery-loop backoff (the journal
    # replay faulted once mid-recovery).
    assert len(s1) == 2
    rng = np.random.default_rng((2, 0))
    expected = 100.0 * (0.5 + 0.5 * float(rng.random())) / 1000.0
    assert s1[0] == pytest.approx(expected)
    assert 0.05 <= s1[0] < 0.1
    assert sup1.retry_backoff_ms_total == pytest.approx(sum(s1) * 1000.0)
    assert sup1.recoveries >= 1

    sup3 = TenantSupervisor(
        make_patterns(), 3, CFG,
        checkpoint_path=str(tmp_path / "bz.ckpt"),
        max_retries=2, retry_backoff_ms=0.0,
    )
    sleeps3 = []
    sup3._sleep = sleeps3.append
    with FAILPOINTS.session({"device.dispatch": [0]}):
        sup3.process(batches(1, seed=19)[0])
    assert sleeps3 == [], "retry_backoff_ms=0 must not sleep"


def test_chaos_flood_and_misbehave_exactly_once_for_compliant(tmp_path):
    """Seeded chaos (device + checkpoint faults) plus a misbehaving
    tenant, with quotas and admission limiting live: every compliant
    tenant's matches are emitted exactly once in oracle order, the
    admission ledger reconciles bit-identically with the fault-free
    run's, and the quarantine survives crash/restore."""
    pol = AdmissionPolicy(rate_per_batch=5.0, burst=6.0)
    quotas = {"crash": TenantQuota(match_rate_budget=2.0)}
    kwargs = dict(admission=pol, quotas=quotas)
    bs = batches(8, seed=19)
    ref = TenantCEP(make_patterns(), 3, CFG, **kwargs)
    ref_m = [canon(ref.process(b)) for b in bs]
    assert sum(len(m) for m in ref_m) > 0

    schedule = random_schedule(
        seed=3, horizon=8, rate=0.3,
        sites=("device.dispatch", "device.result", "checkpoint.save"),
    )
    assert schedule, "seed produced an empty schedule; pick another"
    with FAILPOINTS.session(schedule):
        sup = TenantSupervisor(
            make_patterns(), 3, CFG,
            checkpoint_path=str(tmp_path / "c.ckpt"),
            checkpoint_every=2, max_retries=8, retry_backoff_ms=0.0,
            **kwargs,
        )
        got = []
        for i, b in enumerate(bs):
            if i == 5:
                # Arm at the CURRENT hit count so the very next fire is
                # batch 5's top-level attempt (the site also fires on
                # recovery replays, where misbehave is swallowed).
                FAILPOINTS.arm(
                    "tenant.misbehave",
                    hits=[FAILPOINTS.hits("tenant.misbehave")],
                    exc=lambda: TenantMisbehave("crash"),
                )
            got.append(canon(sup.process(b)))
    assert sup.recoveries > 0, "schedule never faulted; chaos was vacuous"
    assert sup.quarantines == {"crash": "misbehave"}, (
        "the misbehave injection must land on a live batch attempt"
    )
    compliant = lambda ms: [m for m in ms if m[0] != "crash"]
    assert [compliant(g) for g in got] == [compliant(r) for r in ref_m]
    # Exactly-once admission accounting across crash/replay: the ledger
    # equals the fault-free oracle's, and reconciles per tenant.
    assert sup.admission_ledger() == ref.admission_ledger()
    for row in sup.admission_ledger().values():
        assert row["offered"] == (
            row["admitted"] + row["shed"] + row["quarantined_dropped"]
        )
    # Compliant tenants' per-query counters also survive exactly-once.
    pq_s, pq_r = sup.per_query_counters(), ref.per_query_counters()
    assert pq_s["spike"] == pq_r["spike"]
    assert pq_s["dip"] == pq_r["dip"]
    snap = sup.metrics_snapshot()
    assert snap["tenant_quarantines"] == 1
    assert snap["quarantined_queries"] == 1


# -- the dead-letter policy contract ------------------------------------------


def test_dead_letter_reason_policy_single_source_of_truth():
    """The typed reason enum, its docs, and the README policy table are
    one artifact: README embeds ``policy_table_markdown()`` verbatim."""
    assert set(REASON_DOCS) == set(REASONS)
    assert REASON_TENANT_QUOTA in REASONS
    table = policy_table_markdown()
    for reason in REASONS:
        assert f"`{reason}`" in table
    readme = os.path.join(os.path.dirname(__file__), "..", "README.md")
    with open(readme, encoding="utf-8") as fh:
        text = fh.read()
    assert table in text, (
        "README dead-letter policy table has drifted from "
        "runtime/ingest.py: REASON_DOCS; regenerate it with "
        "policy_table_markdown()"
    )
