"""Stacked multi-query bank vs per-query matchers — identical emissions.

BASELINE.json config 4 ("multi-pattern NFA bank, batched"): same-shape
queries stack on a leading query axis inside one compiled step
(``engine/matcher.py`` stacked mode, ``parallel/stacked.py``).  Ground
truth is one :class:`BatchMatcher` per query over the same events.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kafkastreams_cep_tpu import Query
from kafkastreams_cep_tpu.compiler.tables import lower
from kafkastreams_cep_tpu.engine import EngineConfig, EventBatch
from kafkastreams_cep_tpu.parallel import BatchMatcher
from kafkastreams_cep_tpu.parallel.stacked import (
    StackedBankMatcher,
    stackable,
)

CFG = EngineConfig(
    max_runs=8, slab_entries=24, slab_preds=4, dewey_depth=8, max_walk=8
)


def q_threshold(lo, hi):
    """A parameterized two-stage query — the typical bank member."""
    return (
        Query()
        .select("a").where(lambda k, v, ts, st, lo=lo: v["x"] < lo)
        .then()
        .select("b").skip_till_next_match()
        .where(lambda k, v, ts, st, hi=hi: v["x"] > hi)
        .build()
    )


def q_folded(mult):
    """Same shape, with a fold — exercises per-query agg merging."""
    return (
        Query()
        .select("a").where(lambda k, v, ts, st: v["x"] < 3)
        .fold("acc", lambda k, v, curr, m=mult: curr + m * v["x"], init=0)
        .then()
        .select("b").skip_till_next_match()
        .where(lambda k, v, ts, st: v["x"] > st.get("acc"))
        .build()
    )


def trace(K, T, seed):
    rng = np.random.default_rng(seed)
    xs = rng.integers(0, 10, size=(K, T)).astype(np.int32)
    return EventBatch(
        key=jnp.broadcast_to(jnp.arange(K, dtype=jnp.int32)[:, None], (K, T)),
        value={"x": jnp.asarray(xs)},
        ts=jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32)[None, :], (K, T)),
        off=jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32)[None, :], (K, T)),
        valid=jnp.ones((K, T), bool),
    )


@pytest.mark.parametrize("mk", [q_threshold, q_folded], ids=["plain", "fold"])
def test_stacked_bank_matches_per_query_matchers(mk):
    K, T = 8, 48
    params = [(2, 6), (3, 7), (4, 5)] if mk is q_threshold else [(1,), (2,), (3,)]
    patterns = [mk(*p) for p in params]
    ev = trace(K, T, seed=21)

    bank = StackedBankMatcher(patterns, K, CFG)
    state, out = bank.scan(bank.init_state(), ev)

    single_counters = []
    for q, pattern in enumerate(patterns):
        single = BatchMatcher(pattern, K, CFG)
        s1, o1 = single.scan(single.init_state(), ev)
        single_counters.append(single.counters(s1))
        for name, a, b in (
            ("count", out.count[q], o1.count),
            ("stage", out.stage[q], o1.stage),
            ("off", out.off[q], o1.off),
        ):
            np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b), err_msg=f"query {q} {name}"
            )
    assert bank.counters(state) == {
        k: sum(c[k] for c in single_counters)
        for k in bank.counters(state)
    }


def test_stacked_bank_kernel_interpret_parity(monkeypatch):
    """The fused walk kernel path with per-lane qids (interpret mode)."""
    K = 128
    params = [(2, 6), (4, 5)]
    patterns = [q_threshold(*p) for p in params]
    ev = trace(K, 32, seed=22)

    monkeypatch.setenv("CEP_WALK_KERNEL", "0")
    jnp_bank = StackedBankMatcher(patterns, K, CFG)
    assert not jnp_bank.uses_walk_kernel
    s0, o0 = jnp_bank.scan(jnp_bank.init_state(), ev)

    monkeypatch.setenv("CEP_WALK_KERNEL", "interpret")
    krn_bank = StackedBankMatcher(patterns, K, CFG)
    assert krn_bank.uses_walk_kernel
    s1, o1 = krn_bank.scan(krn_bank.init_state(), ev)

    np.testing.assert_array_equal(np.asarray(o0.count), np.asarray(o1.count))
    np.testing.assert_array_equal(np.asarray(o0.stage), np.asarray(o1.stage))
    np.testing.assert_array_equal(np.asarray(o0.off), np.asarray(o1.off))


def test_unstackable_shapes_rejected():
    p2 = q_threshold(2, 6)
    p3 = (
        Query()
        .select("a").where(lambda k, v, ts, st: v["x"] < 2)
        .then()
        .select("b").where(lambda k, v, ts, st: v["x"] > 4)
        .then()
        .select("c").where(lambda k, v, ts, st: v["x"] > 8)
        .build()
    )
    assert not stackable([lower(p2), lower(p3)])
    with pytest.raises(ValueError, match="stackable"):
        StackedBankMatcher([p2, p3], 8, CFG)


def test_choose_bank_modes():
    """Non-stackable banks are serial by necessity; stackable ones pick by
    measurement when a sample is given (either answer is legitimate on
    CPU — the API contract is a working mode plus its evidence)."""
    import jax.numpy as jnp

    from kafkastreams_cep_tpu.engine import EventBatch
    from kafkastreams_cep_tpu.parallel.stacked import choose_bank

    def q(i):
        return (
            Query()
            .select("a").where(lambda k, v, ts, st, i=i: v["x"] < 3 + i)
            .then()
            .select("b").skip_till_next_match()
            .where(lambda k, v, ts, st: v["x"] > 6)
            .build()
        )

    deep = (
        Query()
        .select("a").where(lambda k, v, ts, st: v["x"] == 0)
        .then()
        .select("b").where(lambda k, v, ts, st: v["x"] == 1)
        .then()
        .select("c").where(lambda k, v, ts, st: v["x"] == 2)
        .build()
    )
    mode, det = choose_bank([q(0), deep], CFG)
    assert mode == "serial" and det["reason"] == "not stackable"

    mode, det = choose_bank([q(0), q(1)], CFG)
    assert mode == "stacked"  # stackable, no sample: one compile beats Q

    K, T = 8, 12
    xs = np.arange(K * T, dtype=np.int32).reshape(K, T) % 10
    sample = EventBatch(
        key=jnp.zeros((K, T), jnp.int32),
        value={"x": jnp.asarray(xs)},
        ts=jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32)[None], (K, T)),
        off=jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32)[None], (K, T)),
        valid=jnp.ones((K, T), bool),
    )
    mode, det = choose_bank([q(0), q(1)], CFG, sample, reps=1)
    assert mode in ("serial", "stacked")
    assert det["serial_s"] > 0 and det["stacked_s"] > 0
