"""Overload control (ISSUE 20): the SLO-burn-driven brownout ladder.

Three layers of proof:

1. *Controller unit tests* — ladder mechanics (streaks, hysteresis,
   one-step moves), the begin/commit/abort transition protocol, the
   Bresenham shed stride, admission-pressure math, and state round-trip.
2. *Supervisor integration* — a flood escalates L1→L4 with exact loss
   accounting (``offered == admitted + shed + dead_lettered``), recovery
   is symmetric back to L0, a crash at any brownout level resumes in the
   same level with the actuators re-applied, and a fault injected
   mid-transition leaves the previous level authoritative.
3. *Differential proof* — on the jnp, walk-kernel, and scan-kernel
   paths, the survivor stream of a browned-out run is bit-equal to an
   unloaded run over the same admitted subset (determined post hoc from
   the typed ``overload_shed`` dead letters).

Pressure in every scenario is driven by the *event-time* reorder-hold
signal (the wall-clock signals — burn rate, queue p99 — are disabled via
huge references), so the ladder trajectory is deterministic: same
records, same levels, same sheds, on every machine.
"""

import collections
import json
import os
import pathlib

import numpy as np
import pytest

import engine_scenarios as sc
from kafkastreams_cep_tpu.engine import EngineConfig
from kafkastreams_cep_tpu.runtime import CEPProcessor, Record, Supervisor
from kafkastreams_cep_tpu.runtime.ingest import (
    AdmissionLimiter,
    IngestPolicy,
    REASON_OVERLOAD_SHED,
)
from kafkastreams_cep_tpu.runtime.overload import (
    MAX_LEVEL,
    OverloadController,
    OverloadPolicy,
    ladder_table_markdown,
    shed_keep,
)
from kafkastreams_cep_tpu.utils import failpoints as fp
from kafkastreams_cep_tpu.utils.telemetry import render_prometheus

CFG = EngineConfig(
    max_runs=16, slab_entries=48, slab_preds=8, dewey_depth=16, max_walk=12
)

# Event-time-driven policy: wall-clock signals neutralized (refs ~1e9),
# pressure comes from reorder-buffer occupancy only — deterministic for a
# given record stream.  enter_streak=1 moves one level per flood batch;
# exit_streak=2 keeps recovery deliberate but short enough to test.
POLICY = OverloadPolicy(
    burn_ref=1e9, queue_ref=1e9, ring_ref=1e9, hold_age_ref=1e9,
    hold_ref=0.05, enter_streak=1, exit_streak=2,
)
INGEST = IngestPolicy(grace_ms=1000, reorder_depth=64)


def flood_batches(n_batches, per_batch, n_keys=4, t0=0, val_mod=5,
                  offs=None):
    """Monotone-timestamp flood: +1 ms per record, so with a 1000 ms
    grace everything is held and hold pressure rises immediately."""
    offs = offs if offs is not None else collections.defaultdict(int)
    batches, t = [], t0
    for _ in range(n_batches):
        recs = []
        for i in range(per_batch):
            t += 1
            k = f"k{i % n_keys}"
            recs.append(Record(k, i % val_mod, t, offset=offs[k]))
            offs[k] += 1
        batches.append(recs)
    return batches, t, offs


def subside_batches(n, t0, offs, key="k0", step=5000):
    """Sparse trailing traffic with big timestamp jumps: the watermark
    races ahead, the held backlog drains, pressure subsides."""
    batches, t = [], t0
    for _ in range(n):
        t += step
        batches.append([Record(key, 4, t, offset=offs[key])])
        offs[key] += 1
    return batches, t


def reconciles(guard, offered):
    """The loss-accounting contract: every offered record is admitted,
    shed (typed), or dead-lettered (typed) — nothing silent.  Reorder
    evictions are an ORDER loss, not a record loss (the record was
    admitted, then force-released), so they don't enter this sum."""
    lc = guard.loss_counters()
    return offered == guard.admitted + lc["overload_shed"] + lc[
        "late_dropped"
    ] + lc["quarantined"]


# ---------------------------------------------------------------------------
# controller unit tests
# ---------------------------------------------------------------------------


def test_policy_validation():
    with pytest.raises(ValueError):
        OverloadPolicy(enter_at=(1.0, 2.0))  # wrong arity
    with pytest.raises(ValueError):
        OverloadPolicy(exit_at=(1.0, 2.0, 4.0, 8.0))  # no hysteresis
    with pytest.raises(ValueError):
        OverloadPolicy(drain_widen=(1, 2, 3))  # needs L0..L4
    with pytest.raises(ValueError):
        OverloadPolicy(enter_streak=0)


def test_pressure_is_max_of_normalized_signals():
    ctl = OverloadController(OverloadPolicy(
        burn_ref=2.0, hold_ref=0.5, hold_age_ref=4.0, queue_ref=1.0,
        ring_ref=16.0,
    ))
    assert ctl.pressure({}) == 0.0
    assert ctl.pressure({"burn_rate": 1.0}) == pytest.approx(0.5)
    # hold_frac 0.75 / 0.5 = 1.5 dominates burn 0.5.
    assert ctl.pressure(
        {"burn_rate": 1.0, "hold_frac": 0.75}
    ) == pytest.approx(1.5)
    assert ctl.pressure({"ring_depth": 32}) == pytest.approx(2.0)
    assert ctl.pressure({"queue_p99_s": None}) == 0.0  # missing -> 0


def step(ctl, pressure):
    """One tick + full transition protocol at a synthetic pressure."""
    prop = ctl.tick({"hold_frac": pressure * ctl.policy.hold_ref})
    if prop is not None:
        ctl.begin(prop[1])
        ctl.commit()
    return prop


def test_ladder_requires_streaks_and_moves_one_step():
    ctl = OverloadController(OverloadPolicy(
        burn_ref=1e9, queue_ref=1e9, ring_ref=1e9, hold_age_ref=1e9,
        hold_ref=0.5, enter_streak=2, exit_streak=3,
    ))
    # Huge pressure: still only one step per enter_streak ticks.
    assert step(ctl, 100.0) is None  # streak 1 of 2
    assert step(ctl, 100.0) == (0, 1)
    assert ctl.level == 1
    assert step(ctl, 100.0) is None  # streak resets after a commit
    assert step(ctl, 100.0) == (1, 2)
    # Exit needs exit_streak consecutive quiet ticks; a pressure blip
    # resets the streak.
    assert step(ctl, 0.0) is None
    assert step(ctl, 0.0) is None
    assert step(ctl, 100.0) is None  # blip: exit streak resets (enter 1/2)
    assert step(ctl, 0.0) is None
    assert step(ctl, 0.0) is None
    assert step(ctl, 0.0) == (2, 1)
    assert ctl.level == 1


def test_hysteresis_band_holds_the_level():
    """Pressure between exit_at and enter_at moves nothing, forever."""
    ctl = OverloadController(OverloadPolicy(
        burn_ref=1e9, queue_ref=1e9, ring_ref=1e9, hold_age_ref=1e9,
        hold_ref=0.5, enter_streak=1, exit_streak=1,
    ))
    assert step(ctl, 1.5) == (0, 1)
    for _ in range(20):  # enter_at[1]=2.0, exit_at[0]=0.5: 1.5 is inert
        assert step(ctl, 1.5) is None
    assert ctl.level == 1


def test_abort_keeps_previous_level_and_retains_streaks():
    ctl = OverloadController(OverloadPolicy(
        burn_ref=1e9, queue_ref=1e9, ring_ref=1e9, hold_age_ref=1e9,
        hold_ref=0.5, enter_streak=2, exit_streak=3,
    ))
    ctl.admission_pressure = (1.0, {})
    assert ctl.tick({"hold_frac": 50.0}) is None
    prop = ctl.tick({"hold_frac": 50.0})
    assert prop == (0, 1)
    ctl.begin(1)
    ctl.admission_pressure = (0.5, {"t": 1.0})  # transition side effect
    ctl.abort()
    assert ctl.level == 0
    assert ctl.admission_pressure == (1.0, {})  # side effect reverted
    assert ctl.transition_failures == 1
    assert ctl.transitions == 0
    # Streaks were retained at threshold: the very next tick re-proposes.
    assert ctl.tick({"hold_frac": 50.0}) == (0, 1)
    ctl.begin(1)
    ctl.commit()
    assert ctl.level == 1 and ctl.transitions == 1


@pytest.mark.parametrize("frac", [0.0, 0.1, 0.25, 0.5, 0.75, 0.9, 1.0])
def test_shed_keep_bresenham_is_exact_and_deterministic(frac):
    n = 1000
    kept = [shed_keep(i, frac) for i in range(n)]
    assert sum(kept) == int(np.floor(n * frac))  # exact, not approximate
    assert kept == [shed_keep(i, frac) for i in range(n)]  # pure
    if 0.0 < frac < 1.0:
        # Evenly spread: the longest kept-gap is bounded by the stride.
        gaps, last = [], -1
        for i, k in enumerate(kept):
            if k:
                gaps.append(i - last)
                last = i
        assert max(gaps) <= int(np.ceil(1.0 / frac)) + 1


def test_state_roundtrip_is_json_safe_and_exact():
    ctl = OverloadController(POLICY)
    ctl.begin(3)
    ctl.commit()
    ctl.base_drain = 2
    ctl.shed_total = 17
    ctl.admission_pressure = (0.25, {"t0": 0.6, "t1": 0.2})
    ctl._enter_streak = 1
    state = json.loads(json.dumps(ctl.to_state()))  # header-safe
    back = OverloadController.from_state(state, POLICY)
    assert back.to_state() == ctl.to_state()
    assert back.level == 3 and back.base_drain == 2
    assert back.admit_fraction() == pytest.approx(0.5)
    assert back.metrics()["overload_level"] == 3


def test_admission_limiter_pressure_squeezes_by_cost_share():
    lim = AdmissionLimiter(rate_per_batch=1.0, burst=4.0)
    for t in ("hog", "light", "zero"):
        assert lim.admit(t)  # buckets exist
    lim.tokens = {t: 0.0 for t in lim.tokens}
    lim.set_pressure(0.5, {"hog": 0.6, "light": 0.2, "zero": 0.0})
    lim.refill()
    # Heaviest share gets the full squeeze; lighter shares
    # proportionally less; zero share untouched; refill = rate * factor.
    assert lim.tokens["hog"] == pytest.approx(0.5)
    assert lim.tokens["light"] == pytest.approx(1 - 0.5 * (0.2 / 0.6))
    assert lim.tokens["zero"] == pytest.approx(1.0)
    # A tenant first seen under pressure starts with a squeezed burst —
    # unmeasured, so it gets the conservative full squeeze.
    assert lim.admit("newcomer")
    assert lim.tokens["newcomer"] == pytest.approx(4.0 * 0.5 - 1.0)
    # Pressure rides the state round-trip (replayed crash admits the
    # same records).
    back = AdmissionLimiter.from_state(
        json.loads(json.dumps(lim.to_state()))
    )
    assert back.pressure_scale == lim.pressure_scale
    assert back.pressure_shares == lim.pressure_shares
    # scale=1.0 clears the squeeze entirely.
    lim.set_pressure(1.0, {})
    lim.tokens = {t: 0.0 for t in lim.tokens}
    lim.refill()
    assert all(v == pytest.approx(1.0) for v in lim.tokens.values())


def test_ladder_table_is_pinned_in_readme():
    """The README "Overload & backpressure" ladder table embeds
    ``ladder_table_markdown()`` verbatim — doc drift fails here."""
    readme = (
        pathlib.Path(__file__).parent.parent / "README.md"
    ).read_text()
    assert ladder_table_markdown() in readme


# ---------------------------------------------------------------------------
# supervisor integration (jnp path)
# ---------------------------------------------------------------------------


def make_sup(tmp_path, tag, resume=False, **kw):
    args = (sc.strict3(), 4, CFG)
    base = dict(
        checkpoint_path=str(tmp_path / f"{tag}.ckpt"),
        journal_path=str(tmp_path / f"{tag}.jrnl"),
        checkpoint_every=100, gc_interval=0, overload_policy=POLICY,
        ingest=INGEST,
    )
    base.update(kw)
    if resume:
        return Supervisor.resume(*args, **base)
    return Supervisor(*args, **base)


def test_flood_escalates_sheds_recovers_and_reconciles(tmp_path):
    sup = make_sup(tmp_path, "flood")
    flood, t, offs = flood_batches(12, 40)
    offered = sum(len(b) for b in flood)
    levels = []
    for b in flood:
        sup.process(b)
        levels.append(sup._overload.level)
    assert levels[:4] == [1, 2, 3, 4]  # one deliberate step per batch
    assert max(levels) == MAX_LEVEL
    g = sup.processor._guard
    assert g.overload_shed > 0  # L3 stride + L4 refusal both fired
    assert reconciles(g, offered)
    # Every shed is a typed dead letter, not a silent drop.
    shed_dl = [
        d for d in g.dead_letters if d.reason == REASON_OVERLOAD_SHED
    ]
    assert len(shed_dl) == g.overload_shed
    # Actuators live while browned out.
    assert sup.processor.overload_admit_fraction == 0.0  # L4 door shut
    assert sup.processor.telemetry_defer
    assert sup.processor.drain_interval == POLICY.drain_widen[4]
    # Recovery is symmetric: pressure subsides, the ladder steps all the
    # way down, and the actuators come back to their base settings.
    sub, t = subside_batches(30, t, offs)
    offered += len(sub)
    for b in sub:
        sup.process(b)
    assert sup._overload.level == 0
    assert sup.processor.overload_admit_fraction is None
    assert not sup.processor.telemetry_defer
    assert sup.processor.drain_interval == 1
    assert reconciles(g, offered)
    # 4 up + 4 down, all committed, none failed.
    assert sup._overload.transitions == 8
    assert sup._overload.transition_failures == 0
    # Telemetry: gauges in the snapshot and the Prometheus rendering.
    snap = sup.metrics_snapshot(per_lane=False)
    assert snap["overload_level"] == 0
    assert snap["overload_transitions"] == 8
    assert snap["overload_shed"] == g.overload_shed
    txt = render_prometheus(snap)
    assert "# TYPE cep_overload_level gauge" in txt
    assert "cep_overload_transitions 8" in txt


@pytest.mark.parametrize("level", [1, 2, 3, 4])
def test_crash_at_any_level_resumes_in_that_level(tmp_path, level):
    """Transitions pin a checkpoint, so a crash at ANY brownout level
    resumes in exactly that level with the actuators re-applied — and
    recovery proceeds as if the crash never happened."""
    sup = make_sup(tmp_path, f"lvl{level}")
    flood, t, offs = flood_batches(level, 40)
    offered = sum(len(b) for b in flood)
    for b in flood:
        sup.process(b)
    assert sup._overload.level == level
    pre_shed = sup.processor._guard.overload_shed
    del sup  # crash
    sup2 = make_sup(tmp_path, f"lvl{level}", resume=True)
    ctl = sup2._overload
    assert ctl.level == level  # pinned level is authoritative
    # Actuators were re-wired from the restored controller state.
    assert sup2.processor.drain_interval == POLICY.drain_widen[level]
    assert sup2.processor.telemetry_defer
    assert sup2.processor.overload_admit_fraction == ctl.admit_fraction()
    assert sup2.processor._guard.overload_shed == pre_shed
    # The resumed ladder recovers symmetrically.
    sub, t = subside_batches(30, t, offs)
    offered += len(sub)
    for b in sub:
        sup2.process(b)
    assert sup2._overload.level == 0
    assert reconciles(sup2.processor._guard, offered)


def test_enter_fault_leaves_previous_level_authoritative(tmp_path):
    """Satellite 1: a fault at the "overload.enter" site (crash
    mid-transition, pin-checkpoint failure) defers the transition — the
    previous level stays live, the failure is counted, and the streak
    retention re-proposes on the next tick.  A crash right after the
    fault resumes in the PREVIOUS level (nothing was pinned)."""
    sup = make_sup(tmp_path, "efault")
    flood, t, offs = flood_batches(3, 40)
    fp.FAILPOINTS.arm("overload.enter", times=1)
    try:
        sup.process(flood[0])
    finally:
        fp.FAILPOINTS.clear()
    assert sup._overload.level == 0  # transition deferred, not taken
    assert sup._overload.transition_failures == 1
    assert sup.processor.overload_admit_fraction is None
    del sup  # crash after the failed transition
    sup2 = make_sup(tmp_path, "efault", resume=True)
    assert sup2._overload.level == 0  # previous level was authoritative
    # With the fault gone the ladder proceeds normally.
    sup2.process(flood[1])
    assert sup2._overload.level == 1
    assert sup2._overload.transitions == 1


def test_exit_fault_defers_recovery_one_tick(tmp_path):
    sup = make_sup(tmp_path, "xfault")
    flood, t, offs = flood_batches(1, 40)
    sup.process(flood[0])
    assert sup._overload.level == 1
    sub, t = subside_batches(4, t, offs)
    sup.process(sub[0])  # exit streak 1 of 2
    fp.FAILPOINTS.arm("overload.exit", times=1)
    try:
        sup.process(sub[1])  # proposes L1 -> L0; the failpoint kills it
    finally:
        fp.FAILPOINTS.clear()
    assert sup._overload.level == 1
    assert sup._overload.transition_failures == 1
    sup.process(sub[2])  # streak retained: re-proposes and commits
    assert sup._overload.level == 0


def test_shed_fault_recovers_to_exactly_once(tmp_path):
    """A fault at the "overload.shed" site mid-ingest is absorbed by the
    supervisor's recovery (restore + replay), and the retried batch
    sheds the identical subset — loss accounting still reconciles."""
    sup = make_sup(tmp_path, "sfault", checkpoint_every=1)
    flood, t, offs = flood_batches(5, 40)
    offered = sum(len(b) for b in flood)
    for b in flood[:4]:  # reach L4 (door shut; every record sheds)
        sup.process(b)
    assert sup._overload.level == 4
    fp.FAILPOINTS.arm("overload.shed", times=1)
    try:
        sup.process(flood[4])
    finally:
        fp.FAILPOINTS.clear()
    assert sup.recoveries == 1
    assert sup._overload.level == 4
    assert reconciles(sup.processor._guard, offered)


def test_every_transition_emits_a_trace_span_and_flight_dump(tmp_path):
    """L3+ entry is the incident boundary: the flight recorder dumps,
    and every transition (either direction) carries a trace span."""
    from kafkastreams_cep_tpu.runtime import FlightRecorder
    from kafkastreams_cep_tpu.utils.telemetry import InMemoryTraceSink

    sink = InMemoryTraceSink()
    flight = FlightRecorder(capacity=64, path=str(tmp_path / "fr"))
    sup = make_sup(
        tmp_path, "span", trace_sink=sink, flight=flight,
    )
    flood, t, offs = flood_batches(4, 40)
    for b in flood:
        sup.process(b)
    assert sup._overload.level == 4
    spans = sink.spans("overload.transition")
    assert [(s["from_level"], s["to_level"]) for s in spans] == [
        (0, 1), (1, 2), (2, 3), (3, 4),
    ]
    assert flight.dumps >= 2  # L3 entry and L4 entry each dump
    assert any("overload" in p for p in flight.dump_paths)


# ---------------------------------------------------------------------------
# differential proof: survivor stream == unloaded run of the admitted
# subset, on all three execution paths
# ---------------------------------------------------------------------------

# Compact flood for the kernel paths (interpret mode scales with T):
# values cycle 0..2, so each key's released stream is A,B,C repeating —
# strict3 matches keep the differential non-vacuous.  Depth 64 keeps the
# steady-state subside pressure (one in-flight hold, 1/64/hold_ref ~= 0.3)
# below exit_at[0]=0.5 so the ladder can step all the way back to L0; a
# tighter buffer would floor the pressure above an exit threshold and
# pin the ladder mid-descent.
DIFF_INGEST = IngestPolicy(grace_ms=1000, reorder_depth=64)


def run_brownout(num_lanes, tmp_path, tag):
    sup = Supervisor(
        sc.strict3(), num_lanes, CFG,
        checkpoint_path=str(tmp_path / f"{tag}.ckpt"),
        checkpoint_every=100, gc_interval=0, overload_policy=POLICY,
        ingest=DIFF_INGEST,
    )
    flood, t, offs = flood_batches(6, 16, val_mod=3)
    sub, t = subside_batches(20, t, offs)
    batches = flood + sub
    matches = []
    levels = []
    for b in batches:
        matches.extend(sup.process(b))
        levels.append(sup._overload.level)
    matches.extend(sup.processor.drain_ingest())
    matches.extend(sup.processor.flush())
    return sup, batches, matches, levels


def run_admitted_oracle(num_lanes, batches, dead):
    """The unloaded oracle: the same batches minus the records the
    browned-out run shed or dead-lettered (identified post hoc by
    (key, offset) from the typed dead letters)."""
    proc = CEPProcessor(
        sc.strict3(), num_lanes, CFG, gc_interval=0, ingest=DIFF_INGEST,
    )
    matches = []
    for b in batches:
        keep = [r for r in b if (r.key, r.offset) not in dead]
        if keep:
            matches.extend(proc.process(keep))
    matches.extend(proc.drain_ingest())
    matches.extend(proc.flush())
    return proc, matches


def canon_stream(matches):
    return [
        (k, tuple(sorted(
            (stage, tuple(sorted(e.offset for e in events)))
            for stage, events in seq.as_map().items()
        )))
        for k, seq in matches
    ]


def assert_survivor_differential(num_lanes, tmp_path, tag):
    sup, batches, got, levels = run_brownout(num_lanes, tmp_path, tag)
    assert max(levels) >= 3, levels  # shedding actually engaged
    assert levels[-1] == 0, levels  # and fully recovered
    g = sup.processor._guard
    offered = sum(len(b) for b in batches)
    assert reconciles(g, offered)
    dead = {(d.record.key, d.record.offset) for d in g.dead_letters}
    assert dead  # non-vacuous: some records were shed
    oracle_proc, want = run_admitted_oracle(num_lanes, batches, dead)
    assert canon_stream(got) == canon_stream(want)  # bit-equal, in order
    assert want, "vacuous differential: the admitted subset must match"
    # Engine-level loss counters agree (and are all zero) on both runs.
    assert not any(sup.processor.counters().values())
    assert not any(oracle_proc.counters().values())


def test_survivor_stream_differential_jnp(tmp_path):
    assert_survivor_differential(4, tmp_path, "diffjnp")


@pytest.mark.parametrize(
    "env,mode",
    [
        ("CEP_WALK_KERNEL", "interpret"),
        # Scan-kernel interpret differential is tier-2 (-m slow, ~46 s);
        # the jnp + walk-kernel differentials keep the proof in tier-1
        # (ROADMAP tier-1 budget note, PR 13).
        pytest.param(
            "CEP_SCAN_KERNEL", "interpret", marks=pytest.mark.slow
        ),
    ],
)
def test_survivor_stream_differential_kernels(tmp_path, env, mode):
    """The same proof through the Pallas walk/scan kernels (interpret
    mode; the 128-lane floor is the kernels' LANE_BLOCK).  Shedding is a
    host-side door decision, so the kernel paths must reproduce the jnp
    survivor stream record-for-record."""
    os.environ[env] = mode
    try:
        assert_survivor_differential(128, tmp_path, f"diff{env[-11:]}")
    finally:
        os.environ[env] = "0"
