"""Sequence parallelism: time-sharded stencil must equal the
single-device stencil element-for-element, including matches that span
chunk boundaries (halo-exchange correctness)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import engine_scenarios as sc
from kafkastreams_cep_tpu.engine import EventBatch
from kafkastreams_cep_tpu.engine.stencil import StencilMatcher
from kafkastreams_cep_tpu.parallel import TimeShardedStencil, key_mesh

pytestmark = pytest.mark.skipif(
    jax.device_count() < 8, reason="needs the 8-device virtual mesh"
)


def full_batch(codes):
    K, T = codes.shape
    return EventBatch(
        key=jnp.zeros((K, T), jnp.int32),
        value=jnp.asarray(codes, jnp.int32),
        ts=jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32), (K, T)),
        off=jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32), (K, T)),
        valid=jnp.ones((K, T), bool),
    )


def test_time_sharded_equals_single_device():
    rng = np.random.default_rng(31)
    K, T = 4, 256  # 8 chunks of 32 per device
    codes = rng.choice(5, size=(K, T), p=[0.4, 0.3, 0.2, 0.05, 0.05])
    # Force matches straddling every chunk boundary (chunk size 32).
    for b in range(31, T - 2, 32):
        codes[1, b - 1], codes[1, b], codes[1, b + 1] = 0, 1, 2  # A B C
    events = full_batch(codes)

    single = StencilMatcher(sc.strict3(), K)
    _, want = single.scan(single.init_state(), events)

    mesh = key_mesh(jax.devices()[:8], axis="time")
    sharded = TimeShardedStencil(sc.strict3(), K, mesh)
    got = sharded.match(sharded.shard_events(events))

    np.testing.assert_array_equal(np.asarray(got.hit), np.asarray(want.hit))
    # Offsets only meaningful where hit; compare masked.
    hit = np.asarray(want.hit)
    np.testing.assert_array_equal(
        np.asarray(got.offs)[hit], np.asarray(want.offs)[hit]
    )
    # Boundary-straddling matches were actually exercised.
    assert hit[1].sum() >= (T // 32) - 1


def test_time_sharded_output_is_sharded():
    mesh = key_mesh(jax.devices()[:8], axis="time")
    sharded = TimeShardedStencil(sc.strict3(), 2, mesh)
    codes = np.zeros((2, 64), dtype=np.int64)
    out = sharded.match(sharded.shard_events(full_batch(codes)))
    assert len(out.hit.sharding.device_set) == 8


def test_time_sharded_rejects_indivisible():
    mesh = key_mesh(jax.devices()[:8], axis="time")
    sharded = TimeShardedStencil(sc.strict3(), 2, mesh)
    codes = np.zeros((2, 60), dtype=np.int64)
    with pytest.raises(ValueError, match="divisible"):
        sharded.match(full_batch(codes))
