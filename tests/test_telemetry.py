"""End-to-end telemetry (ISSUE 3): registry determinism, histogram merge
algebra, span nesting/correlation, Prometheus rendering, attribution
(per-lane / per-pattern / hot-tier), and the chaos-trace acceptance
criterion — every recovery/escalation span carries the correlation id of
the batch it rolled back."""

import io
import json
import logging
import math
import os
import sys

import numpy as np
import pytest

import engine_scenarios as sc
from kafkastreams_cep_tpu.engine import EngineConfig
from kafkastreams_cep_tpu.engine.sizing import EscalationPolicy
from kafkastreams_cep_tpu.runtime import CEPBank, CEPProcessor, Record, Supervisor
from kafkastreams_cep_tpu.utils import failpoints as fp
from kafkastreams_cep_tpu.utils.logging import configure_logging
from kafkastreams_cep_tpu.utils.telemetry import (
    Histogram,
    InMemoryTraceSink,
    JsonlTraceSink,
    MetricsRegistry,
    Reporter,
    log_bucket_edges,
    merge_counter_dicts,
    positive_delta,
    render_prometheus,
    set_default_sink,
)

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "examples"))
import stock_demo


def stock_records():
    return [
        Record("s", {"price": e["price"], "volume": e["volume"]}, 1000 + i)
        for i, e in enumerate(stock_demo.STOCK_EVENTS)
    ]


def stock_cfg(**kw):
    base = dict(
        max_runs=8, slab_entries=16, slab_preds=4, dewey_depth=8, max_walk=8
    )
    base.update(kw)
    return EngineConfig(**base)


# -- registry / instruments ---------------------------------------------------


def test_counter_gauge_histogram_basics():
    reg = MetricsRegistry()
    reg.counter("c").inc()
    reg.counter("c").inc(4)
    reg.gauge("g").set(17)
    reg.histogram("h").observe(0.01)
    snap = reg.snapshot()
    assert snap["c"] == 5 and snap["g"] == 17
    assert snap["h"]["count"] == 1
    with pytest.raises(TypeError):
        reg.gauge("c")  # a name is one instrument type forever


def test_histogram_percentiles_deterministic():
    h = Histogram("lat", log_bucket_edges(1e-6, 10.0, 4))
    for v in [1e-4] * 98 + [5.0] * 2:
        h.observe(v)
    assert h.percentile(0.5) < 1e-3
    assert h.percentile(0.99) > 1.0
    # An empty histogram answers 0.0, not NaN.
    assert Histogram("e").percentile(0.99) == 0.0


def test_histogram_merge_associative_and_exact():
    def mk(vals):
        h = Histogram("x")
        for v in vals:
            h.observe(v)
        return h

    a, b, c = mk([1e-5, 0.2]), mk([0.3, 7.0, 150.0]), mk([1e-7])
    left = a.merge(b).merge(c)
    right = a.merge(b.merge(c))
    assert left.snapshot() == right.snapshot()
    # Merge equals one histogram having seen every stream.
    assert left.snapshot() == mk([1e-5, 0.2, 0.3, 7.0, 150.0, 1e-7]).snapshot()
    with pytest.raises(ValueError):
        a.merge(Histogram("y", log_bucket_edges(1e-3, 1.0, 2)))


def test_registry_snapshot_deterministic():
    def run():
        reg = MetricsRegistry()
        reg.counter("records").value = 42
        reg.gauge("watermark").set(1234)
        for v in [0.001, 0.02, 0.3]:
            reg.histogram("lat").observe(v)
        return reg

    assert run().snapshot() == run().snapshot()
    assert json.dumps(run().snapshot()) == json.dumps(run().snapshot())


def test_registry_merge_and_delta():
    a, b = MetricsRegistry(), MetricsRegistry()
    a.counter("n").value = 3
    b.counter("n").value = 4
    b.counter("only_b").value = 1
    a.histogram("h").observe(0.1)
    b.histogram("h").observe(0.2)
    m = a.merge(b)
    assert m.snapshot()["n"] == 7
    assert m.snapshot()["only_b"] == 1
    assert m.snapshot()["h"]["count"] == 2
    assert m.delta({"n": 5}) == {"n": 2, "only_b": 1}
    assert positive_delta({"x": 5, "y": 2}, {"x": 5, "y": 3}) == {}
    assert merge_counter_dicts([{"a": 1}, {"a": 2, "b": 3}]) == {"a": 3, "b": 3}


def test_prometheus_rendering_golden():
    reg = MetricsRegistry()
    reg.counter("records_in").value = 12
    reg.gauge("lag ms").set(7)
    reg.histogram("lat", (0.1, 1.0)).observe(0.05)
    reg.histogram("lat", (0.1, 1.0)).observe(5.0)
    got = render_prometheus(reg.snapshot(), prefix="cep")
    assert got == (
        "# HELP cep_lag_ms runtime metric (see README metrics reference)\n"
        "# TYPE cep_lag_ms gauge\n"
        "cep_lag_ms 7\n"
        "# HELP cep_lat runtime metric (see README metrics reference)\n"
        "# TYPE cep_lat histogram\n"
        'cep_lat_bucket{le="0.1"} 1\n'
        'cep_lat_bucket{le="+Inf"} 2\n'
        "cep_lat_sum 5.05\n"
        "cep_lat_count 2\n"
        "# HELP cep_records_in runtime metric (see README metrics reference)\n"
        "# TYPE cep_records_in gauge\n"
        "cep_records_in 12\n"
    )


def test_prometheus_structural_labels():
    snap = {
        "run_drops": 1,
        "per_lane": {"run_drops": [0, 3]},
        "per_pattern": {"q0": {"run_drops": 1}},
        "phases": {
            "device": {
                "count": 1,
                "sum": 0.5,
                "p50": 0.5,
                "p99": 0.5,
                "buckets": [(1.0, 1)],
            }
        },
        "hbm": {"bytes_in_use": 64},
        "note": "skipped-string",
    }
    txt = render_prometheus(snap)
    assert 'cep_run_drops{lane="1"} 3' in txt
    assert 'cep_run_drops{lane="0"}' not in txt  # zero lanes elided
    assert 'cep_run_drops{pattern="q0"} 1' in txt
    assert 'cep_phase_seconds_bucket{phase="device",le="1.0"} 1' in txt
    assert "cep_hbm_bytes_in_use 64" in txt
    assert "skipped-string" not in txt


# -- span tracing -------------------------------------------------------------


def test_span_nesting_and_ids():
    sink = InMemoryTraceSink()
    with sink.span("outer", tag="a") as sp:
        with sink.span("inner"):
            sink.event("ping", k=1)
        sp["late"] = True
    inner, outer = sink.spans("inner")[0], sink.spans("outer")[0]
    assert inner["parent_id"] == outer["span_id"]
    assert outer["parent_id"] is None
    assert outer["late"] is True and outer["tag"] == "a"
    ping = [e for e in sink.events if e["name"] == "ping"][0]
    assert ping["parent_id"] == inner["span_id"]
    assert outer["duration_ms"] >= inner["duration_ms"]


def test_span_error_flagged():
    sink = InMemoryTraceSink()
    with pytest.raises(RuntimeError):
        with sink.span("boom"):
            raise RuntimeError("nope")
    assert "RuntimeError" in sink.spans("boom")[0]["error"]


def test_jsonl_sink_round_trips():
    buf = io.StringIO()
    sink = JsonlTraceSink(buf)
    with sink.span("s", n=1):
        pass
    evt = json.loads(buf.getvalue().strip())
    assert evt["type"] == "span" and evt["name"] == "s" and evt["n"] == 1


# -- processor integration ----------------------------------------------------


def test_processor_batch_and_phase_spans():
    sink = InMemoryTraceSink()
    proc = CEPProcessor(
        stock_demo.stock_pattern(), 1, stock_cfg(), trace_sink=sink
    )
    assert len(proc.process(stock_records())) == 4
    batch = sink.spans("batch")[0]
    assert batch["records"] == 8 and batch["matches"] == 4
    assert batch["lanes"] == 1 and batch["batch"] == 1
    kids = [
        s["name"]
        for s in sink.spans()
        if s["parent_id"] == batch["span_id"]
    ]
    assert kids == ["phase.pack", "phase.dispatch", "phase.device",
                    "phase.decode"]


def test_processor_snapshot_hot_counters_and_attribution():
    proc = CEPProcessor(
        stock_demo.stock_pattern(), 2, stock_cfg(slab_hot_entries=8)
    )
    proc.process(stock_records())
    snap = proc.metrics_snapshot()
    # Satellite 1: two-tier telemetry reachable from the runtime snapshot.
    hops = snap["slab_hot_hits"] + snap["slab_hot_misses"]
    assert hops > 0
    # Attribution: per-lane lists sized K, per-pattern keyed by name.
    assert len(snap["per_lane"]["run_drops"]) == 2
    assert sum(snap["per_lane"]["slab_hot_hits"]) == snap["slab_hot_hits"]
    assert snap["per_pattern"]["stream"]["records_in"] == 8
    # Watermark/lag gauges from batch timestamps.
    assert snap["watermark"] == 1007
    assert snap["event_time_lag_ms"] >= 0
    # Phase histograms carry per-batch observations.
    assert snap["phases"]["device"]["count"] == 1
    assert snap["phases"]["pack"]["p99"] > 0
    assert isinstance(snap["hbm"], dict)
    # per_lane is opt-out for light snapshots.
    assert "per_lane" not in proc.metrics_snapshot(per_lane=False)


TIMING_KEYS = (
    "device_seconds", "decode_seconds", "pack_seconds", "dispatch_seconds",
    "gc_seconds", "events_per_second_device", "event_time_lag_ms", "hbm",
    "phases",
    # Latency-ledger segment values are wall clock; observation COUNTS are
    # deterministic and asserted separately (tests/test_latency.py).
    "latency",
    # Process-global LRU warmth: the second identical run hits programs
    # the first one traced, so hits/misses are order-dependent by design.
    "trace_cache",
)


def _deterministic_view(snap):
    out = {k: v for k, v in snap.items() if k not in TIMING_KEYS}
    out["phase_counts"] = {
        name: h["count"] for name, h in snap["phases"].items()
    }
    return out


def test_processor_snapshot_determinism_across_runs():
    """Two identical runs produce identical snapshots once wall-clock
    values are projected out — counters, attribution, watermark, and every
    histogram's observation counts."""

    def run():
        proc = CEPProcessor(stock_demo.stock_pattern(), 2, stock_cfg())
        proc.process(stock_records()[:5])
        proc.process(stock_records()[5:])
        return _deterministic_view(proc.metrics_snapshot())

    a, b = run(), run()
    assert a == b
    assert json.dumps(a, default=str) == json.dumps(b, default=str)


# -- supervisor integration ---------------------------------------------------


def test_supervisor_snapshot_exposes_phases_and_attribution(tmp_path):
    sup = Supervisor(
        stock_demo.stock_pattern(), 1, stock_cfg(),
        checkpoint_path=str(tmp_path / "s.ckpt"), checkpoint_every=1,
        epoch=0,
    )
    sup.process(stock_records())
    snap = sup.metrics_snapshot()
    # Acceptance: per-phase latency histograms with p50/p99, per-lane and
    # per-pattern breakdowns, hot-tier counters — all from one call.
    for phase in ("pack", "dispatch", "device", "decode",
                  "checkpoint", "recover", "escalate"):
        assert {"count", "p50", "p99"} <= set(snap["phases"][phase])
    assert snap["phases"]["checkpoint"]["count"] == 1
    assert snap["phases"]["checkpoint"]["p99"] > 0
    assert snap["per_lane"]["run_drops"] == [0]
    assert "stream" in snap["per_pattern"]
    assert "slab_hot_hits" in snap
    assert snap["checkpoints"] == 1


def test_chaos_recovery_span_carries_batch_correlation(tmp_path):
    """Acceptance criterion: a fault-injected run's JSONL trace holds a
    recovery span whose ``corr`` is exactly the correlation id of the
    batch span it rolled back, plus the armed failpoint hit event."""
    buf = io.StringIO()
    sink = JsonlTraceSink(buf)
    prev = set_default_sink(sink)
    try:
        sup = Supervisor(
            sc.strict3(), 1, sc.default_config(),
            checkpoint_path=str(tmp_path / "c.ckpt"), checkpoint_every=2,
            trace_sink=sink,
        )
        with fp.FAILPOINTS.session({"device.result": [2]}):
            for i, v in enumerate([sc.A, sc.B, sc.C, sc.A, sc.B, sc.C]):
                sup.process([Record("k", v, 1000 + i, offset=i)])
    finally:
        set_default_sink(prev)
    assert sup.recoveries == 1
    events = [json.loads(l) for l in buf.getvalue().splitlines()]
    recs = [e for e in events if e.get("name") == "recover"]
    assert len(recs) == 1
    corr = recs[0]["corr"]
    rolled_back = [
        e for e in events
        if e.get("name") == "supervisor.batch" and e.get("corr") == corr
    ]
    assert len(rolled_back) == 1  # the batch the recovery replayed into
    assert rolled_back[0]["seq"] == int(corr.split("-")[1])
    # The fault landed right after a checkpoint, so the replay tail was
    # empty — the span still reports the restore source and replay size.
    assert recs[0]["replayed_records"] == 0
    assert recs[0]["from_checkpoint"] is True
    hits = [e for e in events if e.get("name") == "failpoint"]
    assert any(h["site"] == "device.result" and h["raised"] for h in hits)


def test_escalation_span_carries_batch_correlation(tmp_path):
    seed = EngineConfig(
        max_runs=4, slab_entries=16, slab_preds=2, dewey_depth=8, max_walk=8
    )
    ceiling = EngineConfig(
        max_runs=64, slab_entries=128, slab_preds=16, dewey_depth=32,
        max_walk=32,
    )
    sink = InMemoryTraceSink()
    sup = Supervisor(
        sc.skip_till_any(), 1, seed,
        checkpoint_path=str(tmp_path / "e.ckpt"), checkpoint_every=100,
        auto_escalate=EscalationPolicy(max_config=ceiling), gc_interval=0,
        trace_sink=sink,
    )
    values = [sc.A, sc.B] + [sc.C, sc.D] * 5
    for i, v in enumerate(values):
        sup.process([Record("k", v, 1000 + i, offset=i)])
    assert sup.escalations >= 1
    esc = sink.spans("escalate")
    assert len(esc) >= 1
    for e in esc:
        # Every escalation span names the tripping batch it rolled back.
        twin = [
            s for s in sink.spans("supervisor.batch")
            if s["corr"] == e["corr"]
        ]
        assert len(twin) == 1
        assert e["tripped"] and e["new_config"]["max_runs"] > 4
    snap = sup.metrics_snapshot()
    assert snap["phases"]["escalate"]["count"] == sup.escalations


def test_replan_span_and_stall_exemplar_carry_batch_correlation(tmp_path):
    """ISSUE 18 satellite: an adaptive replan's trace span AND the latency
    ledger's ``stall.replan`` exemplar both carry the correlation id of
    the batch boundary that triggered the swap — and the ledger itself
    survives the ``replan_processor`` rebuild."""
    import dataclasses

    from kafkastreams_cep_tpu.runtime.supervisor import AdaptPolicy

    cfg = dataclasses.replace(
        sc.default_config(), tiering=True, stage_attribution=True
    )
    sink = InMemoryTraceSink()
    sup = Supervisor(
        sc.strict3(), 1, cfg,
        checkpoint_path=str(tmp_path / "r.ckpt"), checkpoint_every=1,
        gc_interval=0, trace_sink=sink, latency=True,
        adapt_policy=AdaptPolicy(
            drift_threshold=0.05, min_evals=1, replan_streak=1, cooldown=0
        ),
    )
    ledger_before = sup.processor.ledger
    # Boundary 1 pins the selectivity baseline, boundary 2 opens the
    # window, boundary 3's flipped stream drifts past the threshold.
    streams = [[sc.A, sc.B, sc.C], [sc.A, sc.B, sc.C], [sc.X] * 6,
               [sc.X] * 6]
    t = 1000
    for vals in streams:
        sup.process([Record("k", v, t + j) for j, v in enumerate(vals)])
        t += 10
        if sup.replans:
            break
    assert sup.replans >= 1 and sup.replan_failures == 0
    span = sink.spans("replan")[0]
    corr = span["corr"]
    twins = [
        s for s in sink.spans("supervisor.batch") if s["corr"] == corr
    ]
    assert len(twins) == 1  # resolves to exactly one real batch span
    # The rebuilt processor carries the SAME ledger (continuity by
    # reference, like the metrics registry) with the stall attributed.
    assert sup.processor.ledger is ledger_before
    ex = sup.processor.ledger.exemplars["stall.replan"]
    assert ex["corr"] == corr and ex["seconds"] > 0
    snap = sup.metrics_snapshot(per_lane=False)
    assert snap["latency"]["stalls"]["replan"]["count"] == sup.replans


# -- bank / sharded / stacked attribution -------------------------------------


def test_bank_metrics_snapshot_merges_members():
    bank = CEPBank(
        {"stock": stock_demo.stock_pattern(),
         "strict": sc.strict3()},
        num_lanes=1, epoch=0,
    )
    recs = stock_records()
    bank.process(recs)
    snap = bank.metrics_snapshot()
    assert set(snap["per_pattern"]) == {"stock", "strict"}
    # Merged counters are the member sums; histograms aggregate exactly.
    assert snap["records_in"] == sum(
        m["records_in"] for m in snap["per_pattern"].values()
    ) == 2 * len(recs)
    assert snap["phases"]["device"]["count"] == 2
    assert snap["per_pattern"]["stock"]["matches_out"] == 4


def test_sharded_matcher_metrics_snapshot():
    from kafkastreams_cep_tpu.parallel import ShardedMatcher, key_mesh

    import jax

    if len(jax.devices()) < 2:
        pytest.skip("needs a multi-device mesh")
    mesh = key_mesh()
    n = mesh.devices.size
    m = ShardedMatcher(sc.strict3(), n, mesh, sc.default_config())
    snap = m.metrics_snapshot(m.init_state())
    assert snap["run_drops"] == 0 and snap["alive_runs"] == n
    assert len(snap["per_lane"]["run_drops"]) == n
    assert "slab_hot_hits" in snap


def test_stacked_bank_metrics_snapshot():
    from kafkastreams_cep_tpu.parallel.stacked import StackedBankMatcher

    bank = StackedBankMatcher(
        [sc.strict3(), sc.strict3()], 2, sc.default_config()
    )
    snap = bank.metrics_snapshot(bank.init_state())
    assert set(snap["per_pattern"]) == {"q0", "q1"}
    for name, v in snap["per_pattern"]["q0"].items():
        assert snap["per_pattern"]["q0"][name] + snap["per_pattern"]["q1"][
            name
        ] == snap[name]


# -- reporter / logging / bench extra ----------------------------------------


def test_reporter_cadence_and_prometheus(tmp_path):
    buf = io.StringIO()
    sink = JsonlTraceSink(buf)
    reg = MetricsRegistry()
    reg.counter("n")
    prom = str(tmp_path / "metrics.prom")
    rep = Reporter(
        reg.snapshot, sink, every_batches=2, prometheus_path=prom
    )
    for _ in range(5):
        reg.counter("n").inc()
        rep.tick()
    assert rep.flushes == 2  # ticks 2 and 4
    rep.flush()
    lines = [json.loads(l) for l in buf.getvalue().splitlines()]
    assert [l["snapshot"]["n"] for l in lines] == [2, 4, 5]
    assert open(prom).read() == (
        "# HELP cep_n runtime metric (see README metrics reference)\n"
        "# TYPE cep_n gauge\n"
        "cep_n 5\n"
    )


def test_configure_logging_json_lines():
    logger = configure_logging(json_lines=True)
    try:
        handler = next(
            h for h in logger.handlers
            if type(h) is logging.StreamHandler
        )
        buf = io.StringIO()
        old_stream = handler.setStream(buf)
        logger.info("hello %s", "world")
        handler.setStream(old_stream)
        evt = json.loads(buf.getvalue().strip())
        assert evt["type"] == "log" and evt["msg"] == "hello world"
        assert evt["level"] == "INFO"
        assert evt["logger"] == "kafkastreams_cep_tpu"
        # Idempotent: reconfiguring restores the human format in place.
        configure_logging(json_lines=False)
        assert (
            sum(
                1 for h in logger.handlers
                if type(h) is logging.StreamHandler
            )
            == 1
        )
    finally:
        configure_logging(json_lines=False)


def test_bench_metrics_extra_smoke():
    """Tier-1 wiring for the CEP_BENCH_METRICS extra: drive the exact
    bench function at tiny shapes so the extra cannot silently rot."""
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
    import bench

    block, n_events = bench.bench_metrics(K=4, T=8, n_batches=3)
    assert block["device"]["count"] == 3
    assert block["device"]["p99_ms"] > 0
    assert {"pack", "dispatch", "decode"} <= set(block)
    # Spans + reporter snapshots landed in the JSONL stream.
    assert n_events > 3
