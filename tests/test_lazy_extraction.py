"""Lazy match extraction (EngineConfig.lazy_extraction) — differential
parity and robustness suites.

The contract (engine/matcher.py):

1. *Match parity*: with a handle ring sized for the trace
   (``handle_overflows == 0``), the drained match set — sequences, event
   offsets, completion order — is identical to the eager engine's, on the
   jnp path, the fused walk-kernel path, and the whole-scan kernel path.
2. *Loss parity*: every pre-existing loss counter is bit-identical to the
   eager engine on loss-free traces, and ``handle_overflows`` preserves
   the all-zero ⇒ loss-free contract (a full ring drops the match and
   counts it — never silent).
3. *Hop accounting*: the W-hop extraction walks move off the per-step
   critical path verbatim — ``extract_hops`` goes to zero and the same
   hops reappear as ``drain_hops`` in the batched drain pass.
4. *Robustness*: pinned handles survive the maintenance sweep
   (mark-sweep roots + version renorm), checkpoint/restore with a
   non-empty ring, and state migration (tests/test_migrate.py).

All kernel runs use interpret mode (CPU CI checks parity, not perf).
"""

import dataclasses
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import engine_scenarios as sc
from kafkastreams_cep_tpu.engine import (
    EngineConfig,
    EventBatch,
    MatcherSession,
    TPUMatcher,
)
from kafkastreams_cep_tpu.parallel.batch import BatchMatcher
from kafkastreams_cep_tpu.runtime import CEPProcessor, Record

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "examples"))
import stock_demo

# Loss-free on the traces below (preconditions asserted): the lazy slab
# holds completed chains until drain, so E carries headroom over the
# eager working set.
CFG = EngineConfig(
    max_runs=16, slab_entries=64, slab_preds=8, dewey_depth=12, max_walk=12,
    handle_ring=64,
)
LAZY = dataclasses.replace(CFG, lazy_extraction=True)


def stock_events(K, T, seed):
    rng = np.random.default_rng(seed)
    prices = rng.integers(90, 131, size=(K, T)).astype(np.int32)
    vols = rng.integers(600, 1101, size=(K, T)).astype(np.int32)
    return EventBatch(
        key=jnp.broadcast_to(
            jnp.arange(K, dtype=jnp.int32)[:, None], (K, T)
        ),
        value={"price": jnp.asarray(prices), "volume": jnp.asarray(vols)},
        ts=jnp.broadcast_to(
            jnp.arange(T, dtype=jnp.int32)[None, :] * 2, (K, T)
        ),
        off=jnp.broadcast_to(
            jnp.arange(T, dtype=jnp.int32)[None, :], (K, T)
        ),
        valid=jnp.ones((K, T), bool),
    )


def eager_matches(out):
    """Eager StepOutput -> per-lane ordered (stage-tuple, off-tuple) lists
    in (t, r) emission order."""
    c = np.asarray(out.count)
    st, of = np.asarray(out.stage), np.asarray(out.off)
    K, T, R = c.shape
    per_lane = []
    for k in range(K):
        rows = []
        for t in range(T):
            for r in range(R):
                n = int(c[k, t, r])
                if n:
                    rows.append(
                        (tuple(st[k, t, r, :n]), tuple(of[k, t, r, :n]))
                    )
        per_lane.append(rows)
    return per_lane


def drained_matches(dout):
    """DrainOutput -> per-lane ordered lists (ring order = completion
    order)."""
    c = np.asarray(dout.count)
    st, of = np.asarray(dout.stage), np.asarray(dout.off)
    K, HB = c.shape
    per_lane = []
    for k in range(K):
        rows = []
        for h in range(HB):
            n = int(c[k, h])
            if n:
                rows.append((tuple(st[k, h, :n]), tuple(of[k, h, :n])))
        per_lane.append(rows)
    return per_lane


def live_keys(slab):
    st, of = np.asarray(slab.stage), np.asarray(slab.off)
    return [
        {(int(s), int(o)) for s, o in zip(st[k], of[k]) if s >= 0}
        for k in range(st.shape[0])
    ]


# ---------------------------------------------------------------------------
# jnp-path differential parity
# ---------------------------------------------------------------------------


def test_lazy_drain_matches_eager_jnp():
    # One matcher pair serves all seeds (compiles dominate CPU CI time).
    K, T = 8, 32
    os.environ["CEP_WALK_KERNEL"] = "0"
    eager = BatchMatcher(stock_demo.stock_pattern(), K, CFG)
    lazy = BatchMatcher(stock_demo.stock_pattern(), K, LAZY)
    for seed in (3, 11, 29):
        events = stock_events(K, T, seed)
        st_e, out_e = eager.scan(eager.init_state(), events)
        st_l, out_l = lazy.scan(lazy.init_state(), events)

        # The lazy scan emits nothing in-step; all ring handles.
        assert int(jnp.sum(out_l.count)) == 0, seed
        assert int(jnp.sum(st_l.hr_count)) > 0, seed
        st_l, dout = lazy.drain(st_l)
        assert int(jnp.sum(st_l.hr_count)) == 0, seed  # drain clears

        # Match parity: identical sequences in completion order.
        assert eager_matches(out_e) == drained_matches(dout), seed
        # Loss parity: bit-identical counters, handle_overflows zero.
        assert eager.counters(st_e) == lazy.counters(st_l), seed
        assert lazy.counters(st_l)["handle_overflows"] == 0, seed
        # Hop accounting: extraction hops moved verbatim to the drain.
        we, wl = eager.walk_counters(st_e), lazy.walk_counters(st_l)
        assert we["extract_hops"] > 0 and we["drain_hops"] == 0, seed
        assert wl["extract_hops"] == 0, seed
        assert wl["drain_hops"] == we["extract_hops"], seed
        assert wl["walk_hops"] == we["walk_hops"], seed
        # Slab content parity (placement may differ — two-tier claim).
        assert live_keys(st_e.slab) == live_keys(st_l.slab), seed


@pytest.mark.parametrize(
    "pattern,codes",
    [
        # skip_till_any exercises the richest walker mix tier-1; the
        # strict/kleene variants ride the slow marker (compile-bound).
        (sc.skip_till_any, [0, 4, 1, 2, 4, 2, 3, 1, 2, 3]),
        pytest.param(
            sc.strict3, [0, 1, 2, 0, 1, 2, 4, 0, 1, 2],
            marks=pytest.mark.slow,
        ),
        pytest.param(
            sc.kleene_one_or_more, [0, 1, 2, 2, 3, 0, 1, 2, 3, 4],
            marks=pytest.mark.slow,
        ),
    ],
)
def test_lazy_session_matches_eager_per_event(pattern, codes):
    """MatcherSession drains per event, so the oracle-style match() API
    returns identical matches at identical events under both modes."""
    eager = MatcherSession(TPUMatcher(pattern(), CFG))
    lazy = MatcherSession(TPUMatcher(pattern(), LAZY))
    for t, v in enumerate(codes):
        me = eager.match(None, v, 10 * t, offset=t)
        ml = lazy.match(None, v, 10 * t, offset=t)
        assert [m.as_map() for m in me] == [m.as_map() for m in ml], t
    ce, cl = eager.counters(), lazy.counters()
    assert ce == cl


@pytest.mark.slow
def test_lazy_sequential_slab_matches_batched():
    """sequential_slab=True (the reference's literal op order) under lazy
    extraction: identical handles, identical drained matches."""
    K, T = 4, 24
    events = stock_events(K, T, 17)
    os.environ["CEP_WALK_KERNEL"] = "0"
    bat = BatchMatcher(stock_demo.stock_pattern(), K, LAZY)
    seq = BatchMatcher(
        stock_demo.stock_pattern(), K,
        dataclasses.replace(LAZY, sequential_slab=True),
    )
    st_b, _ = bat.scan(bat.init_state(), events)
    st_q, _ = seq.scan(seq.init_state(), events)
    np.testing.assert_array_equal(
        np.asarray(st_b.hr_count), np.asarray(st_q.hr_count)
    )
    st_b, d_b = bat.drain(st_b)
    st_q, d_q = seq.drain(st_q)
    assert drained_matches(d_b) == drained_matches(d_q)
    assert bat.counters(st_b) == seq.counters(st_q)


def test_stacked_bank_lazy_drain():
    """One drain pass serves every member of a stacked bank (the drain is
    table-free): drained matches equal the eager stacked outputs."""
    from kafkastreams_cep_tpu.parallel.stacked import StackedBankMatcher

    def q(i):
        lo, hi = 95 + i * 5, 120 - i * 3
        from kafkastreams_cep_tpu import Query

        return (
            Query()
            .select("a").where(lambda k, v, ts, st, lo=lo: v["price"] < lo)
            .then()
            .select("b").skip_till_next_match()
            .where(lambda k, v, ts, st, hi=hi: v["price"] > hi)
            .build()
        )

    K, T = 4, 16
    rng = np.random.default_rng(13)
    prices = rng.integers(80, 141, size=(K, T)).astype(np.int32)
    events = EventBatch(
        key=jnp.broadcast_to(
            jnp.arange(K, dtype=jnp.int32)[:, None], (K, T)
        ),
        value={"price": jnp.asarray(prices)},
        ts=jnp.broadcast_to(
            jnp.arange(T, dtype=jnp.int32)[None, :], (K, T)
        ),
        off=jnp.broadcast_to(
            jnp.arange(T, dtype=jnp.int32)[None, :], (K, T)
        ),
        valid=jnp.ones((K, T), bool),
    )
    os.environ["CEP_WALK_KERNEL"] = "0"
    cfg = EngineConfig(
        max_runs=8, slab_entries=32, slab_preds=4, dewey_depth=8,
        max_walk=8, handle_ring=32,
    )
    patterns = [q(0), q(1)]
    eager = StackedBankMatcher(patterns, K, cfg)
    st_e, out_e = eager.scan(eager.init_state(), events)
    lazy = StackedBankMatcher(
        patterns, K, dataclasses.replace(cfg, lazy_extraction=True)
    )
    st_l, _ = lazy.scan(lazy.init_state(), events)
    st_l, dout = lazy.drain(st_l)
    # out_e is [Q, K, T, R, W]; dout is [Q*K, HB, ...] (query-major).
    Q = len(patterns)
    flat_eager = eager_matches(
        type(out_e)(*[
            np.asarray(x).reshape((Q * K,) + x.shape[2:]) for x in out_e
        ])
    )
    assert flat_eager == drained_matches(dout)
    assert eager.counters(st_e) == lazy.counters(st_l)
    """A ring too small for the trace drops matches — counted, never
    silent (the all-zero ⇒ loss-free contract)."""
    K, T = 4, 32
    events = stock_events(K, T, 5)
    os.environ["CEP_WALK_KERNEL"] = "0"
    tiny = dataclasses.replace(LAZY, handle_ring=8)
    eager = BatchMatcher(stock_demo.stock_pattern(), K, CFG)
    st_e, out_e = eager.scan(eager.init_state(), events)
    lazy = BatchMatcher(stock_demo.stock_pattern(), K, tiny)
    st_l, _ = lazy.scan(lazy.init_state(), events)
    st_l, dout = lazy.drain(st_l)
    ovf = lazy.counters(st_l)["handle_overflows"]
    assert ovf > 0
    n_eager = sum(len(r) for r in eager_matches(out_e))
    n_lazy = sum(len(r) for r in drained_matches(dout))
    assert n_lazy < n_eager  # the dropped matches are really gone…
    assert n_lazy + ovf >= n_eager  # …and every loss was counted


def test_sweep_preserves_pinned_handles():
    """The maintenance sweep (mark-sweep + version renorm) must not
    reclaim a pinned-but-undrained chain: handles are liveness roots and
    their versions renormalize with the slab's."""
    K, T = 4, 24
    events = stock_events(K, T, 13)
    os.environ["CEP_WALK_KERNEL"] = "0"
    lazy = BatchMatcher(stock_demo.stock_pattern(), K, LAZY)
    st, _ = lazy.scan(lazy.init_state(), events)
    assert int(jnp.sum(st.hr_count)) > 0
    _, want = lazy.drain(st)  # reference drain, no sweep
    swept = lazy.sweep(st)  # sweep WITH pending handles
    _, got = lazy.drain(swept)
    assert drained_matches(want) == drained_matches(got)


# ---------------------------------------------------------------------------
# Kernel-path parity (interpret mode)
# ---------------------------------------------------------------------------

PRESSURE_LAZY = EngineConfig(
    max_runs=8, slab_entries=16, slab_hot_entries=8, slab_preds=4,
    dewey_depth=8, max_walk=8, lazy_extraction=True, handle_ring=32,
)

SLAB_FIELDS = (
    "stage", "off", "refs", "npreds", "full_drops", "pred_drops",
    "missing", "trunc", "hot_hits", "hot_misses", "overflow_walks",
    "demotions", "walk_hops", "extract_hops", "drain_hops",
)


def assert_lazy_same_run(ref, st_r, d_r, krn, st_k, d_k):
    for f in d_r._fields:
        np.testing.assert_array_equal(
            np.asarray(getattr(d_r, f)), np.asarray(getattr(d_k, f)),
            err_msg=f"drain.{f}",
        )
    for f in SLAB_FIELDS:
        np.testing.assert_array_equal(
            np.asarray(getattr(st_r.slab, f)),
            np.asarray(getattr(st_k.slab, f)), err_msg=f"slab.{f}",
        )
    assert ref.counters(st_r) == krn.counters(st_k)
    assert ref.hot_counters(st_r) == krn.hot_counters(st_k)
    assert ref.walk_counters(st_r) == krn.walk_counters(st_k)


def test_walk_kernel_lazy_parity_under_pressure():
    K, T = 128, 12
    events = stock_events(K, T, 21)
    os.environ["CEP_WALK_KERNEL"] = "0"
    ref = BatchMatcher(stock_demo.stock_pattern(), K, PRESSURE_LAZY)
    st_r, _ = ref.scan(ref.init_state(), events)
    st_r, d_r = ref.drain(st_r)
    os.environ["CEP_WALK_KERNEL"] = "interpret"
    try:
        krn = BatchMatcher(stock_demo.stock_pattern(), K, PRESSURE_LAZY)
        assert krn.uses_walk_kernel
        st_k, _ = krn.scan(krn.init_state(), events)
        st_k, d_k = krn.drain(st_k)  # kernel drain path
    finally:
        os.environ["CEP_WALK_KERNEL"] = "0"
    assert_lazy_same_run(ref, st_r, d_r, krn, st_k, d_k)
    assert ref.hot_counters(st_r)["slab_demotions"] > 0
    assert ref.walk_counters(st_r)["drain_hops"] > 0


@pytest.mark.slow
def test_scan_kernel_lazy_parity_under_pressure():
    # Tier-2 (-m slow, ~11 s interpret): the walk-kernel variant above
    # keeps kernel lazy-parity in tier-1 (ROADMAP tier-1 budget note,
    # PR 13).
    from kafkastreams_cep_tpu.compiler.tables import lower
    from kafkastreams_cep_tpu.ops.scan_kernel import build_scan

    K, T = 128, 8
    events = stock_events(K, T, 31)
    os.environ["CEP_WALK_KERNEL"] = "0"
    ref = BatchMatcher(stock_demo.stock_pattern(), K, PRESSURE_LAZY)
    scan = build_scan(lower(stock_demo.stock_pattern()), PRESSURE_LAZY)
    scan.interpret = True
    st_r, _ = ref.scan(ref.init_state(), events)
    st_k, _ = scan(ref.init_state(), events)
    # Ring parity BEFORE drain: the in-kernel append is bit-identical.
    for f in ("hr_stage", "hr_off", "hr_ver", "hr_vlen", "hr_ts",
              "hr_seq", "hr_row", "hr_count", "step_seq",
              "handle_overflows"):
        a = np.asarray(getattr(st_r, f))
        b = np.asarray(getattr(st_k, f))
        if f.startswith("hr_") and f not in ("hr_count",):
            pend = (
                np.arange(a.shape[1])[None, :]
                < np.asarray(st_r.hr_count)[:, None]
            )
            if a.ndim == 3:
                pend = pend[..., None]
            a, b = np.where(pend, a, 0), np.where(pend, b, 0)
        np.testing.assert_array_equal(a, b, err_msg=f)
    st_r, d_r = ref.drain(st_r)
    st_k, d_k = ref.drain(st_k)
    assert_lazy_same_run(ref, st_r, d_r, ref, st_k, d_k)


# ---------------------------------------------------------------------------
# Acceptance: the perf model, measured on CPU (platform-independent)
# ---------------------------------------------------------------------------


def _hit_rate(hot):
    hops = hot["slab_hot_hits"] + hot["slab_hot_misses"]
    return hot["slab_hot_hits"] / hops if hops else 1.0


def test_lazy_takes_extraction_off_the_step_critical_path():
    """The acceptance measurement (CPU; hop counts/rates are
    platform-independent): headline shapes with the slab sized loss-free
    for the match-dense stock trace at E_hot=16, drained at the
    processor's cadence.  Pins what PROFILE_r07.md records:

    * per-step device walk hops drop >= 40% (measured ~50% — extraction
      was ~half the step's hop budget and moves to the drain verbatim);
    * the moved hops are conserved: ``drain_hops`` equals the eager
      engine's ``extract_hops`` exactly;
    * matches and every loss counter are bit-identical.

    The ISSUE's companion hypothesis — that the step-phase hot-hit rate
    rises toward ~1.0 — measured FALSE on this trace: the remaining
    branch/dead walkers start at run *pointer* events (older than the hot
    window) and skip the extraction walks' hot head-of-chain hops, so the
    residual step mix is slightly colder (~0.31 vs ~0.44).  The ~1.0
    regime claim is pinned where it actually holds, on short-walk traces
    (strict3, test below), and PROFILE_r07.md names the residual deep
    walkers as the next leverage.
    """
    K, T, CH = 4, 128, 16
    events = stock_events(K, T, 42)
    os.environ["CEP_WALK_KERNEL"] = "0"
    shapes = dict(
        max_runs=24, slab_entries=96, slab_preds=8, dewey_depth=12,
        max_walk=12, slab_hot_entries=16,
    )

    def chunks(ev):
        for t0 in range(0, T, CH):
            yield jax.tree_util.tree_map(lambda x: x[:, t0:t0 + CH], ev)

    eager = BatchMatcher(
        stock_demo.stock_pattern(), K, EngineConfig(**shapes)
    )
    st_e, n_e = eager.init_state(), 0
    for c in chunks(events):
        st_e, out = eager.scan(st_e, c)
        n_e += int(jnp.sum(out.count > 0))
        st_e = eager.sweep(st_e)

    lazy = BatchMatcher(
        stock_demo.stock_pattern(), K,
        EngineConfig(**shapes, lazy_extraction=True, handle_ring=512),
    )
    st_l, n_l, hh, hm = lazy.init_state(), 0, 0, 0
    for c in chunks(events):
        pre = lazy.hot_counters(st_l)
        st_l, _ = lazy.scan(st_l, c)
        post = lazy.hot_counters(st_l)
        hh += post["slab_hot_hits"] - pre["slab_hot_hits"]
        hm += post["slab_hot_misses"] - pre["slab_hot_misses"]
        st_l, d = lazy.drain(st_l)
        n_l += int(jnp.sum(d.count > 0))
        st_l = lazy.sweep(st_l)

    # Parity first — the perf numbers mean nothing without it.
    assert n_e == n_l and n_e > 0
    assert eager.counters(st_e) == lazy.counters(st_l)
    assert lazy.counters(st_l)["handle_overflows"] == 0

    we, wl = eager.walk_counters(st_e), lazy.walk_counters(st_l)
    step_hops_eager = we["walk_hops"] + we["extract_hops"]
    step_hops_lazy = wl["walk_hops"] + wl["extract_hops"]
    reduction = 1 - step_hops_lazy / step_hops_eager
    assert reduction >= 0.40, (we, wl)
    # Conservation: the extraction work moved, it did not disappear.
    assert wl["extract_hops"] == 0
    assert wl["drain_hops"] == we["extract_hops"]
    assert wl["walk_hops"] == we["walk_hops"]
    # The measured step-phase rate delta PROFILE_r07 documents.
    rate_eager = _hit_rate(eager.hot_counters(st_e))
    rate_lazy = hh / (hh + hm)
    assert 0.3 < rate_eager < 0.7, rate_eager  # adversarial baseline
    assert rate_lazy > rate_eager - 0.2, (rate_eager, rate_lazy)


def test_lazy_keeps_short_walk_traces_in_the_hot_regime():
    """strict3 (PROFILE_r06: hot-hit rate 1.000 at E_hot=16): lazy
    extraction must keep the 1.0 step rate AND still move its extraction
    hops to the drain pass."""
    rng = np.random.default_rng(9)
    K, T = 8, 64
    codes = rng.integers(0, 5, size=(K, T)).astype(np.int32)
    events = EventBatch(
        key=jnp.broadcast_to(
            jnp.arange(K, dtype=jnp.int32)[:, None], (K, T)
        ),
        value=jnp.asarray(codes),
        ts=jnp.broadcast_to(
            jnp.arange(T, dtype=jnp.int32)[None, :] * 2, (K, T)
        ),
        off=jnp.broadcast_to(
            jnp.arange(T, dtype=jnp.int32)[None, :], (K, T)
        ),
        valid=jnp.ones((K, T), bool),
    )
    os.environ["CEP_WALK_KERNEL"] = "0"
    shapes = dict(
        max_runs=16, slab_entries=64, slab_hot_entries=16, slab_preds=8,
        dewey_depth=8, max_walk=8,
    )
    eager = BatchMatcher(sc.strict3(), K, EngineConfig(**shapes))
    st_e, out_e = eager.scan(eager.init_state(), events)
    lazy = BatchMatcher(
        sc.strict3(), K,
        EngineConfig(**shapes, lazy_extraction=True, handle_ring=64),
    )
    st_l, _ = lazy.scan(lazy.init_state(), events)
    rate_step = _hit_rate(lazy.hot_counters(st_l))  # before drain hops
    st_l, dout = lazy.drain(st_l)
    assert eager_matches(out_e) == drained_matches(dout)
    assert rate_step == 1.0
    we, wl = eager.walk_counters(st_e), lazy.walk_counters(st_l)
    if we["extract_hops"]:
        assert wl["extract_hops"] == 0
        assert wl["drain_hops"] == we["extract_hops"]


# ---------------------------------------------------------------------------
# Processor / runtime integration
# ---------------------------------------------------------------------------


def _mk_batches(n_batches, n, K, seed):
    rng = np.random.default_rng(seed)
    out = []
    for b in range(n_batches):
        keys = rng.integers(0, K, size=n)
        prices = rng.integers(90, 131, size=n)
        vols = rng.integers(600, 1101, size=n)
        out.append(
            [
                Record(
                    int(keys[i]),
                    {"price": int(prices[i]), "volume": int(vols[i])},
                    b * n + i,
                )
                for i in range(n)
            ]
        )
    return out


def _canon(ms):
    return [
        (
            k,
            tuple(
                (s, tuple(e.offset for e in evs))
                for s, evs in m.as_map().items()
            ),
        )
        for k, m in ms
    ]


BIG = EngineConfig(
    max_runs=32, slab_entries=128, slab_preds=16, dewey_depth=24,
    max_walk=16, handle_ring=256,
)
BIG_LAZY = dataclasses.replace(BIG, lazy_extraction=True)


def _run_proc(config, batches, K, **kw):
    proc = CEPProcessor(
        stock_demo.stock_pattern(), K, config, epoch=0, **kw
    )
    out = []
    for b in batches:
        out += proc.process(b)
    out += proc.flush()
    return proc, out


@pytest.mark.slow
def test_processor_lazy_emission_order_parity():
    # Tier-2 (-m slow, ~34 s): test_lazy_drain_matches_eager_jnp and
    # the pressure-parity pair keep lazy-vs-eager coverage in tier-1
    # (ROADMAP tier-1 budget note, PR 13).
    os.environ["CEP_WALK_KERNEL"] = "0"
    K = 4
    batches = _mk_batches(4, 64, K, 7)
    pe, me = _run_proc(BIG, batches, K)
    pl, ml = _run_proc(BIG_LAZY, batches, K)
    # Bit-identical counters (the shared drops are identical too) and
    # identical matches in identical order.
    assert pe.counters() == pl.counters()
    assert _canon(me) == _canon(ml)  # content AND order
    # Pipelined mode: same matches, one call later.  (Deferred drain
    # cadence is covered by test_checkpoint_restore_with_pending_handles
    # at drain_interval=4.)
    _, mp = _run_proc(BIG_LAZY, batches, K, pipeline=True)
    assert _canon(mp) == _canon(me)


def test_checkpoint_restore_with_pending_handles(tmp_path):
    """A checkpoint taken between match completion and drain carries the
    ring; the restored processor drains it to the identical matches."""
    from kafkastreams_cep_tpu.runtime.checkpoint import (
        restore_processor,
        save_checkpoint,
    )

    os.environ["CEP_WALK_KERNEL"] = "0"
    K = 4
    batches = _mk_batches(3, 32, K, 19)
    # Reference: one continuous lazy processor.
    _, want = _run_proc(BIG_LAZY, batches, K, drain_interval=4)

    proc = CEPProcessor(
        stock_demo.stock_pattern(), K, BIG_LAZY, epoch=0, drain_interval=4
    )
    got = []
    for b in batches[:2]:
        got += proc.process(b)
    assert int(jnp.sum(proc.state.hr_count)) > 0  # non-empty ring
    path = str(tmp_path / "ring.ckpt")
    save_checkpoint(proc, path)
    restored = restore_processor(stock_demo.stock_pattern(), path)
    assert int(jnp.sum(restored.state.hr_count)) > 0  # ring survived
    got += restored.process(batches[2])
    got += restored.flush()
    assert sorted(_canon(got)) == sorted(_canon(want))


def test_probe_and_suggest_size_the_ring():
    from kafkastreams_cep_tpu.compiler.tables import lower
    from kafkastreams_cep_tpu.engine import probe, suggest

    os.environ["CEP_WALK_KERNEL"] = "0"
    K, T = 4, 24
    events = stock_events(K, T, 3)
    report = probe(stock_demo.stock_pattern(), events, BIG, sweep_every=12)
    assert report.max_matches_chunk > 0
    cfg = suggest(lower(stock_demo.stock_pattern()), report)
    assert cfg.handle_ring >= 8 and cfg.handle_ring % 8 == 0
    # The derived ring is loss-free at the probed cadence, by construction.
    lazy_cfg = dataclasses.replace(
        cfg, lazy_extraction=True,
        slab_entries=max(cfg.slab_entries, 2 * report.max_live_entries),
    )
    lazy_report = probe(
        stock_demo.stock_pattern(), events, lazy_cfg, sweep_every=16
    )
    assert lazy_report.counters["handle_overflows"] == 0


def test_escalation_grows_the_ring():
    from kafkastreams_cep_tpu.engine import escalate

    grown = escalate(LAZY, {"handle_overflows": 5})
    assert grown is not None and grown.handle_ring > LAZY.handle_ring
