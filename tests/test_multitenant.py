"""Multi-tenant query bank — shared screen, per-query bit-exactness.

The contract under test (``compiler/multitenant.py`` +
``engine/predmatrix.py`` + ``parallel/tenantbank.py`` +
``runtime/tenant.py``): N queries sharing one predicate matrix and one
stencil screen emit, per query, *bit-identical* matches, emission order,
and loss counters to that query running alone on its own serial matcher
— across the jnp path, the fused walk kernel, and with the serial
reference on the whole-scan kernel path.  Durability rides the same
checkpoint idioms as the single-query runtime: a live shared-prefix
carry survives save/restore and capacity widening, and the tenant
supervisor recovers a chaos schedule exactly-once.

Workloads here are loss-free by construction (selective begin
predicates): the bank's parity claim vs *untiered* serial matchers is
scoped to runs the narrow engine would not have dropped, the same
precondition as test_tiering/test_migrate.
"""

import os
import tempfile

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kafkastreams_cep_tpu import Query
from kafkastreams_cep_tpu.engine import EngineConfig, EventBatch
from kafkastreams_cep_tpu.parallel.batch import BatchMatcher
from kafkastreams_cep_tpu.parallel.tenantbank import TenantBankMatcher
from kafkastreams_cep_tpu.runtime.migrate import widen_state
from kafkastreams_cep_tpu.runtime.processor import Record
from kafkastreams_cep_tpu.runtime.tenant import (
    TenantCEP,
    TenantSupervisor,
    restore_tenant,
    save_tenant_checkpoint,
)
from kafkastreams_cep_tpu.utils.failpoints import FAILPOINTS, random_schedule
from kafkastreams_cep_tpu.utils.telemetry import render_prometheus

CFG = EngineConfig(
    max_runs=8, slab_entries=24, slab_preds=4, dewey_depth=32, max_walk=8
)

# Zero on all of these certifies the serial reference dropped nothing —
# the precondition scoping the bit-exactness claim (test_tiering's
# DROP_COUNTERS plus dewey/walk capacity).
CAPACITY_COUNTERS = (
    "run_drops", "ver_overflows", "slab_full_drops", "slab_pred_drops",
    "slab_trunc", "handle_overflows",
)


def ge(th):
    return lambda k, v, ts, st, th=th: v["x"] >= th


def lt(th):
    return lambda k, v, ts, st, th=th: v["x"] < th


def q_stencil(a, b, c):
    """Pure strict-contiguity 3-stage query (stencil-tier candidate)."""
    return (
        Query()
        .select("a").where(ge(a)).then()
        .select("b").where(lt(b)).then()
        .select("c").where(ge(c)).build()
    )


def q_hybrid(a, b, z):
    """Strict 2-stage prefix + skip suffix (hybrid-tier candidate)."""
    return (
        Query()
        .select("a").where(ge(a)).then()
        .select("b").where(lt(b)).then()
        .select("z").skip_till_next_match().where(ge(z)).build()
    )


def q_folded():
    """State-dependent predicate — not screenable, lands off-stencil."""
    return (
        Query()
        .select("a").where(ge(8))
        .fold("acc", lambda k, v, curr: curr + v["x"], init=0)
        .then()
        .select("b").skip_till_next_match()
        .where(lambda k, v, ts, st: v["x"] > st.get("acc") % 4).build()
    )


# Thresholds keep begin stages selective (>= 8 on 0..9 ints) so nothing
# overflows max_runs=8 — the loss-free precondition for serial parity.
MIXED = [
    q_stencil(8, 3, 7),   # pure stencil
    q_hybrid(8, 3, 9),    # shares the full 2-stage prefix of query 0
    q_hybrid(9, 1, 7),    # same shape, different prefix
    q_stencil(9, 2, 8),   # second stencil, different prefix
    q_folded(),           # state-dependent: off the shared screen
]


def trace(K, T, seed):
    rng = np.random.default_rng(seed)
    xs = rng.integers(0, 10, size=(K, T)).astype(np.int32)
    base = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32)[None, :], (K, T))
    return EventBatch(
        key=jnp.broadcast_to(
            jnp.arange(K, dtype=jnp.int32)[:, None], (K, T)
        ),
        value={"x": jnp.asarray(xs)},
        ts=base, off=base, valid=jnp.ones((K, T), bool),
    )


def assert_bank_parity(patterns, K, T, n_batches, seed0, cfg=CFG):
    """The core oracle: tenant bank vs one serial matcher per query,
    multi-batch (carry state crosses batch boundaries), bit-exact
    emissions at identical [K, T, R, W] slots plus counter-sum parity."""
    bank = TenantBankMatcher(patterns, K, cfg)
    st = bank.init_state()
    serial = [BatchMatcher(p, K, cfg) for p in patterns]
    sst = [m.init_state() for m in serial]
    for b in range(n_batches):
        ev = trace(K, T, seed0 + b)
        st, out = bank.scan(st, ev)
        for q, m in enumerate(serial):
            sst[q], o1 = m.scan(sst[q], ev)
            for f in ("count", "stage", "off"):
                np.testing.assert_array_equal(
                    np.asarray(getattr(out, f)[q]),
                    np.asarray(getattr(o1, f)),
                    err_msg=f"batch {b} query {q} {f}",
                )
    bc = bank.counters(st)
    assert all(bc[n] == 0 for n in CAPACITY_COUNTERS), (
        f"workload must stay loss-free, got {bc}"
    )
    summed = {k: 0 for k in bc}
    for q, m in enumerate(serial):
        for k, v in m.counters(sst[q]).items():
            summed[k] += v
    # slab_missing is excluded: with every capacity counter zero it marks
    # reference-NPE trace states the *untiered* engine probes and misses —
    # prefix stages executed on the stencil never create them, so tiered
    # engines legitimately report fewer (engine/sizing.py scopes it out of
    # loss accounting for the same reason).  Everything that certifies
    # no-loss must match exactly.
    drop = lambda d: {k: v for k, v in d.items() if k != "slab_missing"}
    assert drop(bc) == drop(summed)
    return bank, st


def test_tenant_bank_matches_serial_jnp(monkeypatch):
    monkeypatch.setenv("CEP_WALK_KERNEL", "0")
    bank, st = assert_bank_parity(MIXED, K=6, T=24, n_batches=3, seed0=31)
    tiers = {bank.tier_of(q) for q in range(len(MIXED))}
    assert "stencil" in tiers and "hybrid" in tiers, (
        "fixture must exercise a mixed-tier bank, got "
        f"{[bank.tier_of(q) for q in range(len(MIXED))]}"
    )
    tc = bank.tier_counters(st)
    assert tc["prefix_events_screened"] > 0
    assert tc["tier_promotions"] > 0, (
        "hybrid members must actually promote through the shared screen"
    )


def test_tenant_bank_matches_serial_walk_kernel(monkeypatch):
    """Fused walk kernel (interpret mode) on a residual group whose lane
    count hits the kernel block size: 2 same-shape hybrids x 64 lanes."""
    from kafkastreams_cep_tpu.parallel.batch import _select_walk_kernel

    monkeypatch.setenv("CEP_WALK_KERNEL", "interpret")
    patterns = [q_hybrid(8, 3, 9), q_hybrid(9, 1, 7)]
    assert _select_walk_kernel(CFG, 2 * 64) == (True, True)
    assert_bank_parity(patterns, K=64, T=12, n_batches=2, seed0=5)


def test_tenant_bank_matches_serial_scan_kernel(monkeypatch):
    """Serial reference on the whole-scan kernel path (interpret): the
    deduplicated predicate plan must agree across implementations."""
    monkeypatch.setenv("CEP_WALK_KERNEL", "0")
    monkeypatch.setenv("CEP_SCAN_KERNEL", "interpret")
    assert_bank_parity(MIXED[:3], K=4, T=16, n_batches=2, seed0=11)


@pytest.mark.parametrize(
    "overlap,n_shared_groups",
    [
        # The all-shared variant is tier-2 (-m slow, ~16 s); the
        # pairs/none variants keep the planning claim in tier-1
        # (ROADMAP tier-1 budget note, PR 13).
        pytest.param("all", 1, marks=pytest.mark.slow),
        ("pairs", 2),
        ("none", 4),
    ],
    ids=["group-of-N", "groups-of-2", "groups-of-1"],
)
def test_prefix_overlap_group_sizes(monkeypatch, overlap, n_shared_groups):
    """Sharing structure is planned, not accidental: identical prefixes
    collapse to one column set; disjoint prefixes share nothing.  Parity
    holds at every overlap shape."""
    monkeypatch.setenv("CEP_WALK_KERNEL", "0")
    if overlap == "all":
        patterns = [q_hybrid(8, 3, 9 - i) for i in range(4)]
    elif overlap == "pairs":
        patterns = [
            q_hybrid(8, 3, 9), q_hybrid(8, 3, 8),
            q_hybrid(9, 1, 9), q_hybrid(9, 1, 8),
        ]
    else:
        # Distinct closures at BOTH prefix stages (eq vs ge differ in
        # bytecode; distinct thresholds differ in closure constants), so
        # column dedup finds nothing to share.
        eq = lambda th: lambda k, v, ts, st, th=th: v["x"] == th

        def q_custom(pa, pb, z):
            return (
                Query()
                .select("a").where(pa).then()
                .select("b").where(pb).then()
                .select("z").skip_till_next_match().where(ge(z)).build()
            )

        patterns = [
            q_custom(ge(8), lt(1), 9), q_custom(ge(9), lt(2), 9),
            q_custom(eq(8), lt(3), 9), q_custom(eq(9), lt(4), 9),
        ]
    bank, _ = assert_bank_parity(patterns, K=4, T=20, n_batches=2, seed0=43)
    stats = bank.bank.stats
    # 4 queries x 2 prefix stages; distinct column count reflects overlap.
    assert stats["prefix_columns_total"] == 8
    assert stats["prefix_columns_distinct"] == 2 * n_shared_groups
    if overlap == "all":
        assert stats["prefix_shared_hit_rate"] == pytest.approx(0.75)
    if overlap == "none":
        assert stats["prefix_shared_hit_rate"] == 0.0


# -- runtime: records in, (query, key, Sequence) out --------------------------


def make_patterns():
    return {
        "spike": q_stencil(8, 3, 7),
        "dip": q_hybrid(8, 3, 9),
        "crash": q_hybrid(9, 1, 7),
    }


def batches(n_batches, per_batch=20, seed=7):
    rng = np.random.default_rng(seed)
    keys = ["alpha", "beta", "gamma"]
    ts = 0
    out = []
    for _ in range(n_batches):
        recs = []
        for _ in range(per_batch):
            ts += int(rng.integers(1, 3))
            recs.append(
                Record(
                    key=keys[int(rng.integers(0, len(keys)))],
                    value={"x": int(rng.integers(0, 10))},
                    timestamp=ts,
                )
            )
        out.append(recs)
    return out


def canon(matches):
    return [
        (qn, k, tuple(sorted(
            (st, e.partition, e.offset)
            for st, evs in seq.as_map().items()
            for e in evs
        )))
        for qn, k, seq in matches
    ]


def test_checkpoint_restore_with_live_prefix_carry(tmp_path):
    """Mid-stream snapshot with a partially-advanced shared prefix: the
    restored bank's future emissions equal the uninterrupted run's."""
    bs = batches(6, seed=7)
    ref = TenantCEP(make_patterns(), 3, CFG)
    ref_matches = [ref.process(b) for b in bs]
    assert sum(len(m) for m in ref_matches) > 0
    assert ref.counters()["run_drops"] == 0

    t = TenantCEP(make_patterns(), 3, CFG)
    for b in bs[:3]:
        t.process(b)
    # The snapshot must carry live screen state, not a quiesced bank.
    assert any(
        bool(np.asarray(c.bools).any()) for c in t.state.carry
    ), "fixture failed to leave a partial prefix pending at the snapshot"
    path = str(tmp_path / "tenant.ckpt")
    save_tenant_checkpoint(t, path)
    t2 = restore_tenant(make_patterns(), path)
    assert t2.per_query_counters() == t.per_query_counters()
    for i, b in enumerate(bs[3:]):
        assert canon(t2.process(b)) == canon(ref_matches[3 + i]), (
            f"post-restore batch {i} diverged"
        )


def test_restore_refuses_mismatched_topology(tmp_path):
    t = TenantCEP(make_patterns(), 3, CFG)
    t.process(batches(1)[0])
    path = str(tmp_path / "tenant.ckpt")
    save_tenant_checkpoint(t, path)
    renamed = dict(make_patterns())
    renamed["burst"] = renamed.pop("crash")
    with pytest.raises(ValueError, match="names"):
        restore_tenant(renamed, path)
    reshaped = dict(make_patterns())
    reshaped["crash"] = q_stencil(9, 1, 7)
    with pytest.raises(ValueError, match="topology|stages"):
        restore_tenant(reshaped, path)


def test_widen_with_live_prefix_carry(monkeypatch):
    """Capacity widening mid-stream: engines widen per residual group,
    the shared-prefix carries copy verbatim, and the wide bank's future
    emissions stay bit-identical on the shared slots."""
    monkeypatch.setenv("CEP_WALK_KERNEL", "0")
    import dataclasses

    wide_cfg = dataclasses.replace(
        CFG, max_runs=16, slab_entries=48, max_walk=12
    )
    K, T = 5, 20
    patterns = MIXED[:4]
    prefix, suffix = trace(K, T, 61), trace(K, T, 62)

    narrow = TenantBankMatcher(patterns, K, CFG)
    mid, _ = narrow.scan(narrow.init_state(), prefix)
    assert any(bool(np.asarray(c.bools).any()) for c in mid.carry)
    st_n, out_n = narrow.scan(mid, suffix)
    assert narrow.counters(st_n)["run_drops"] == 0

    wide = TenantBankMatcher(patterns, K, wide_cfg)
    mid_w = jax.device_put(widen_state(mid, CFG, wide_cfg))
    for c_n, c_w in zip(mid.carry, mid_w.carry):
        for a, b in zip(
            jax.tree_util.tree_leaves(c_n), jax.tree_util.tree_leaves(c_w)
        ):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    st_w, out_w = wide.scan(mid_w, suffix)

    R, W = CFG.max_runs, CFG.max_walk
    np.testing.assert_array_equal(
        np.asarray(out_n.count), np.asarray(out_w.count)[..., :R]
    )
    assert not np.asarray(out_w.count)[..., R:].any()
    for f in ("stage", "off"):
        np.testing.assert_array_equal(
            np.asarray(getattr(out_n, f)),
            np.asarray(getattr(out_w, f))[..., :R, :W],
            err_msg=f,
        )


def test_supervisor_chaos_schedule_exactly_once():
    """Seeded chaos over the device + checkpoint sites: every batch's
    matches are emitted exactly once, in the uninterrupted run's order,
    with recoveries actually exercised."""
    bs = batches(8, seed=19)
    ref = TenantCEP(make_patterns(), 3, CFG)
    ref_matches = [canon(ref.process(b)) for b in bs]
    assert sum(len(m) for m in ref_matches) > 0

    schedule = random_schedule(
        seed=3, horizon=8, rate=0.3,
        sites=("device.dispatch", "device.result", "checkpoint.save"),
    )
    assert schedule, "seed produced an empty schedule; pick another"
    with FAILPOINTS.session(schedule):
        sup = TenantSupervisor(
            make_patterns(), 3, CFG, checkpoint_every=2, max_retries=6
        )
        got = [canon(sup.process(b)) for b in bs]
    assert got == ref_matches
    assert sup.recoveries > 0, "schedule never faulted; chaos was vacuous"
    assert sup.checkpoints > 0
    snap = sup.metrics_snapshot()
    assert snap["recoveries"] == sup.recoveries


def test_per_query_telemetry_labels():
    """metrics_snapshot carries the per_query breakdown and the
    Prometheus renderer emits it as {query="name"} labeled series."""
    t = TenantCEP(make_patterns(), 3, CFG)
    for b in batches(2, seed=23):
        t.process(b)
    snap = t.metrics_snapshot()
    assert set(snap["per_query"]) == {"spike", "dip", "crash"}
    for sub in snap["per_query"].values():
        assert "run_drops" in sub and "tier_promotions" in sub
    text = render_prometheus(snap)
    assert 'cep_run_drops{query="spike"} 0' in text
    assert 'cep_tier_promotions{query="dip"}' in text
    assert f'cep_bank_queries {len(make_patterns())}' in text
