"""Test harness configuration.

Tests run on a virtual 8-device CPU mesh so multi-chip sharding logic is
exercised without TPU hardware (the driver separately dry-run-compiles the
multi-chip path via ``__graft_entry__.dryrun_multichip``, and ``bench.py``
runs on the real chip).  Set ``CEP_TEST_TPU=1`` to run the suite on
whatever platform the environment provides instead (the sharding tests
then skip if fewer than 8 devices are present).

The environment's site hook pins ``JAX_PLATFORMS`` to the TPU plugin before
any code runs, so the env var alone is not enough — the platform is forced
through ``jax.config`` after import, before any backend is initialized.
"""

import os
import tempfile

if not os.environ.get("CEP_TEST_TPU"):
    os.environ["JAX_PLATFORMS"] = "cpu"
    _flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (
            _flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")
    # Persistent compilation cache: the suite compiles the same engine
    # programs (identical HLO, distinct Python closures) dozens of times;
    # caching them cuts suite wall time substantially across and within
    # runs.  Override the location with CEP_TEST_CACHE_DIR ('' disables).
    _cache = os.environ.get(
        "CEP_TEST_CACHE_DIR",
        os.path.join(tempfile.gettempdir(), "cep_tpu_jax_cache"),
    )
    if _cache:
        jax.config.update("jax_compilation_cache_dir", _cache)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        jax.config.update(
            "jax_persistent_cache_min_entry_size_bytes", -1
        )


def pytest_sessionfinish(session, exitstatus):
    """Remember the session's exit status for the fast exit below."""
    global _EXITSTATUS
    _EXITSTATUS = int(exitstatus)


_EXITSTATUS = None


def pytest_unconfigure(config):
    """Skip interpreter teardown: after a full suite run the final GC of
    accumulated JAX state (hundreds of jitted executables, interpret-mode
    Pallas traces, the process-level trace cache) takes 40 s+ — dead time
    that counts against the tier-1 wall budget after the last test has
    already passed.  The terminal summary is printed by the time
    ``pytest_unconfigure`` runs, so flush and exit with pytest's own
    status.  ``CEP_TEST_NO_FAST_EXIT=1`` restores the normal exit path
    (e.g. for plugins that need atexit hooks, like coverage)."""
    if _EXITSTATUS is None or os.environ.get("CEP_TEST_NO_FAST_EXIT"):
        return
    import sys

    sys.stdout.flush()
    sys.stderr.flush()
    os._exit(_EXITSTATUS)


def pytest_collection_modifyitems(config, items):
    """Run the newest (and compile-heaviest) suites last.

    Tier-1 runs under a fixed wall budget; ordering the newest suites
    after the long-standing ones means a budget truncation cuts the
    newest coverage first instead of displacing established tests —
    the no-worse-than-baseline dot count stays monotone as suites grow.
    Newest last: the PR 8 shard-fault suites follow the PR 7 tiering
    suite, which follows everything else in collection order.
    """
    def _age(it):
        nid = it.nodeid
        if "test_overload" in nid:
            return 6  # PR 13: overload control (incl. chaos section)
        if "test_latency" in nid or "test_metrics_guard" in nid:
            return 5  # PR 18: latency attribution
        if "test_tenant_isolation" in nid:
            return 4  # PR 11: per-tenant isolation
        if "test_multitenant" in nid:
            return 3  # PR 9: multi-tenant query bank
        if (
            "test_shard_fault" in nid
            or "test_shard_chaos" in nid
            or "test_chaos_schedule_tiered" in nid
            or "test_resume_on_shrunk_mesh" in nid
        ):
            return 2  # PR 8: shard fault tolerance
        if "test_tiering" in nid:
            return 1  # PR 7: compiler tiering
        return 0

    items.sort(key=_age)  # stable: collection order kept within a tier
