"""Test harness configuration.

Tests run on a virtual 8-device CPU mesh so multi-chip sharding logic is
exercised without TPU hardware (the driver separately dry-run-compiles the
multi-chip path via ``__graft_entry__.dryrun_multichip``, and ``bench.py``
runs on the real chip).  Set ``CEP_TEST_TPU=1`` to run the suite on
whatever platform the environment provides instead (the sharding tests
then skip if fewer than 8 devices are present).

The environment's site hook pins ``JAX_PLATFORMS`` to the TPU plugin before
any code runs, so the env var alone is not enough — the platform is forced
through ``jax.config`` after import, before any backend is initialized.
"""

import os

if not os.environ.get("CEP_TEST_TPU"):
    os.environ["JAX_PLATFORMS"] = "cpu"
    _flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (
            _flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")
