"""Oracle engine conformance goldens, ported from the reference
``nfa/NFATest.java`` — these scenarios are the behaviors the TPU matcher must
reproduce bit-for-bit (see SURVEY.md section 4)."""

import dataclasses
import time
from typing import List

from kafkastreams_cep_tpu import Event, OracleNFA, Query, Sequence
from helpers import value_is

NOW = int(time.time() * 1000)

EV1 = Event(None, "A", NOW, "test", 0, 0)
EV2 = Event(None, "B", NOW, "test", 0, 1)
EV3 = Event(None, "C", NOW, "test", 0, 2)
EV4 = Event(None, "C", NOW, "test", 0, 3)
EV5 = Event(None, "D", NOW, "test", 0, 4)


def simulate(nfa: OracleNFA, *events: Event) -> List[Sequence]:
    # NFATest.simulate (NFATest.java:174-182).
    out: List[Sequence] = []
    for event in events:
        out.extend(
            nfa.match(
                event.key,
                event.value,
                event.timestamp,
                topic=event.topic,
                partition=event.partition,
                offset=event.offset,
            )
        )
    return out


def test_one_run_strict_contiguity():
    # NFATest.java:42-67.
    query = (
        Query()
        .select("first").where(value_is("A"))
        .then()
        .select("second").where(value_is("B"))
        .then()
        .select("latest").where(value_is("C"))
        .build()
    )
    nfa = OracleNFA.from_pattern(query)
    matches = simulate(nfa, EV1, EV2, EV3)
    assert len(matches) == 1
    expected = Sequence().add("first", EV1).add("second", EV2).add("latest", EV3)
    assert matches[0] == expected


def test_one_run_multiple_match_one_or_more():
    # NFATest.java:69-101.
    query = (
        Query()
        .select("firstStage").where(value_is("A"))
        .then()
        .select("secondStage").where(value_is("B"))
        .then()
        .select("thirdStage").one_or_more().where(value_is("C"))
        .then()
        .select("latestState").where(value_is("D"))
        .build()
    )
    nfa = OracleNFA.from_pattern(query)
    matches = simulate(nfa, EV1, EV2, EV3, EV4, EV5)
    assert len(matches) == 1
    expected = (
        Sequence()
        .add("firstStage", EV1)
        .add("secondStage", EV2)
        .add("thirdStage", EV3)
        .add("thirdStage", EV4)
        .add("latestState", EV5)
    )
    assert matches[0] == expected


def test_skip_till_next_match():
    # NFATest.java:104-132.
    query = (
        Query()
        .select("first").where(value_is("A"))
        .then()
        .select("second").skip_till_next_match().where(value_is("C"))
        .then()
        .select("latest").skip_till_next_match().where(value_is("D"))
        .build()
    )
    nfa = OracleNFA.from_pattern(query)
    matches = simulate(nfa, EV1, EV2, EV3, EV4, EV5)
    assert len(matches) == 1
    expected = Sequence().add("first", EV1).add("second", EV3).add("latest", EV5)
    assert matches[0] == expected


def test_skip_till_any_match_branches():
    # NFATest.java:134-172 — nondeterministic branching yields two matches.
    query = (
        Query()
        .select("first").where(value_is("A"))
        .then()
        .select("second").where(value_is("B"))
        .then()
        .select("three").skip_till_any_match().where(value_is("C"))
        .then()
        .select("latest").skip_till_any_match().where(value_is("D"))
        .build()
    )
    nfa = OracleNFA.from_pattern(query)
    matches = simulate(nfa, EV1, EV2, EV3, EV4, EV5)
    assert len(matches) == 2
    expected1 = (
        Sequence().add("first", EV1).add("second", EV2).add("three", EV3).add("latest", EV5)
    )
    expected2 = (
        Sequence().add("first", EV1).add("second", EV2).add("three", EV4).add("latest", EV5)
    )
    assert matches[0] == expected1
    assert matches[1] == expected2


@dataclasses.dataclass(frozen=True)
class StockEvent:
    price: int
    volume: int


def test_complex_pattern_with_state():
    """The SASE stock query with folds, zeroOrMore and window
    (NFATest.java:203-245)::

        PATTERN SEQ(Stock+ a[ ], Stock b)
        WHERE skip_till_next_match(a[ ], b) {
            [symbol] and a[1].volume > 1000
            and a[i].price > avg(a[..i-1].price)
            and b.volume < 80% * a[a.LEN].volume }
        WITHIN 1 hour
    """
    stocks = [
        StockEvent(100, 1010),
        StockEvent(120, 990),
        StockEvent(120, 1005),
        StockEvent(121, 999),
        StockEvent(120, 999),
        StockEvent(125, 750),
        StockEvent(120, 950),
        StockEvent(120, 700),
    ]
    query = (
        Query()
        .select()
        .where(lambda k, v, ts, store: v.volume > 1000)
        .fold("avg", lambda k, v, curr: v.price)
        .then()
        .select()
        .zero_or_more()
        .skip_till_next_match()
        .where(lambda k, v, ts, store: v.price > store.get("avg"))
        .fold("avg", lambda k, v, curr: (curr + v.price) // 2)
        .fold("volume", lambda k, v, curr: v.volume)
        .then()
        .select()
        .skip_till_next_match()
        .where(lambda k, v, ts, store: v.volume < 0.8 * store.get_or_else("volume", 0))
        .within(1, "h")
        .build()
    )
    nfa = OracleNFA.from_pattern(query)
    events = [Event(None, s, NOW, "test", 0, i) for i, s in enumerate(stocks)]
    matches = simulate(nfa, *events)
    assert len(matches) == 4
    # Exact event content of all four matches, in emission order — the
    # reference README documents these as e1..e8 JSON lines
    # (/root/reference/README.md:93-96; stage names default to levels).
    def canon(seq):
        return {
            stage: sorted(e.offset for e in evs)
            for stage, evs in seq.as_map().items()
        }

    assert [canon(m) for m in matches] == [
        {"0": [0], "1": [1, 2, 3, 4], "2": [5]},
        {"0": [2], "1": [3], "2": [5]},
        {"0": [0], "1": [1, 2, 3, 4, 5, 6], "2": [7]},
        {"0": [2], "1": [3, 5], "2": [7]},
    ]


def test_independent_instances_per_partition():
    """Per-partition ownership (CEPProcessor.java:117-134): one NFA per
    partition, interleaved feeding, no cross-talk between instances."""
    query = (
        Query()
        .select("a").where(value_is("A"))
        .then()
        .select("b").where(value_is("B"))
        .build()
    )
    nfa_p0 = OracleNFA.from_pattern(query)
    nfa_p1 = OracleNFA.from_pattern(query)
    # p0 sees A then B (match); p1 sees B then A (no match) — interleaved.
    out0, out1 = [], []
    out0 += nfa_p0.match(None, "A", NOW, offset=0)
    out1 += nfa_p1.match(None, "B", NOW, offset=0)
    out0 += nfa_p0.match(None, "B", NOW + 1, offset=1)
    out1 += nfa_p1.match(None, "A", NOW + 1, offset=1)
    assert len(out0) == 1 and len(out1) == 0
    assert [e.offset for e in out0[0].as_map()["b"]] == [1]


def test_first_stage_skip_strategy_does_not_duplicate_begin_runs():
    # Documented deviation: begin-stage IGNORE edges are dropped (the begin
    # re-seed subsumes them; the reference would duplicate begin runs / NPE).
    query = (
        Query()
        .select("first").skip_till_next_match().where(value_is("A"))
        .then()
        .select("last").where(value_is("B"))
        .build()
    )
    nfa = OracleNFA.from_pattern(query)
    # Feed non-matching noise: the run queue must stay bounded.
    for i in range(50):
        nfa.match(None, "X", NOW + i)
    assert len(nfa.runs) == 1  # just the begin run
    matches = simulate(
        nfa,
        Event(None, "A", NOW + 100, "test", 0, 100),
        Event(None, "B", NOW + 101, "test", 0, 101),
    )
    assert len(matches) == 1


def test_fold_state_pruned_for_dead_runs():
    """Fold-state entries for dead runs are released each event (the
    reference leaks these into RocksDB; the host oracle must not)."""
    query = (
        Query()
        .select("a").where(value_is("A")).fold("n", lambda k, v, c: c + 1)
        .then()
        .select("b").where(value_is("B"))
        .build()
    )
    nfa = OracleNFA.from_pattern(query)
    for i in range(50):  # A runs start and die repeatedly (A then noise)
        nfa.match(None, "A", NOW + 2 * i)
        nfa.match(None, "X", NOW + 2 * i + 1)
    live = {r.seq for r in nfa.runs}
    assert all(seq in live for _, seq in nfa._agg_state)
    assert len(nfa._agg_state) <= len(nfa.runs)


def test_auto_offset_does_not_collide():
    query = (
        Query()
        .select("a").where(value_is("A"))
        .then()
        .select("b").one_or_more().where(value_is("B"))
        .then()
        .select("c").where(value_is("C"))
        .build()
    )
    nfa = OracleNFA.from_pattern(query)
    out = []
    for v in ["A", "B", "B", "C"]:
        out.extend(nfa.match(None, v, NOW))  # no explicit offsets
    assert len(out) == 1
    assert len(out[0].get("b")) == 2  # both B events kept distinct
