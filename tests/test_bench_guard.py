"""Tier-1 guard: every bench timing path forces materialization.

PROFILE_r05 finding 1: JAX dispatch is asynchronous, so a
``perf_counter`` span that never forces its outputs measures enqueue
time, not device time — lazy outputs once made ``block_until_ready``-free
timings physically impossible to trust, and a future edit could
reintroduce that silently.  This guard statically scans ``bench.py``:
every ``t = time.perf_counter()`` … ``time.perf_counter() - t`` span must
either force device work inside the span (``block_until_ready``,
``device_get``, or a helper that documents a consumed reduction) or be
explicitly annotated ``# host-timed`` at the start-of-span assignment —
so un-materialized device timings can't regress into fiction.
"""

import os
import re

BENCH = os.path.join(os.path.dirname(__file__), "..", "bench.py")

# Evidence that a span forces device results to exist before the clock
# stops: an explicit barrier, a host pull, or the chunked-scan helper
# whose contract is a consumed reduction per chunk (see bench.py
# ``_chunked_scan`` docstring).
_FORCERS = ("block_until_ready", "device_get", "_chunked_scan")

_ASSIGN = re.compile(r"^(\s*)(\w+)\s*=\s*time\.perf_counter\(\)\s*(#.*)?$")
_USE = re.compile(r"time\.perf_counter\(\)\s*-\s*(\w+)")


def _spans(lines):
    """Yield (var, assign_line_idx, use_line_idx, assign_comment) for each
    timing span: a use matched to the nearest preceding assignment of the
    same variable."""
    assigns = {}
    for i, line in enumerate(lines):
        m = _ASSIGN.match(line)
        if m:
            assigns[m.group(2)] = (i, m.group(3) or "")
            continue
        for m in _USE.finditer(line):
            var = m.group(1)
            if var in assigns:
                a_i, comment = assigns[var]
                yield var, a_i, i, comment


def test_every_bench_timing_span_materializes():
    with open(BENCH) as f:
        lines = f.read().splitlines()
    offenders = []
    for var, a_i, u_i, comment in _spans(lines):
        if "host-timed" in comment:
            continue
        body = "\n".join(lines[a_i:u_i + 1])
        if not any(f in body for f in _FORCERS):
            offenders.append(
                f"bench.py:{a_i + 1}-{u_i + 1} times {var!r} without "
                "forcing materialization (add block_until_ready/"
                "device_get inside the span, or annotate the assignment "
                "'# host-timed' if it intentionally measures host work)"
            )
    assert not offenders, "\n".join(offenders)


def test_guard_sees_the_real_spans():
    """The guard itself must not silently go blind: bench.py has many
    timing spans and at least one annotated host-timed span."""
    with open(BENCH) as f:
        lines = f.read().splitlines()
    spans = list(_spans(lines))
    assert len(spans) >= 20, len(spans)
    assert any("host-timed" in c for _, _, _, c in spans)


def test_lazy_bench_block_forces_drained_outputs():
    """The lazy A/B block's timing helper must consume the DRAIN outputs
    (the lazy engine's only emissions) — not just the eager grid."""
    with open(BENCH) as f:
        src = f.read()
    m = re.search(
        r"def _chunked_scan\(.*?\n(?:.*\n)*?    return state, n", src
    )
    assert m, "_chunked_scan missing from bench.py"
    body = m.group(0)
    assert "drained.count" in body and "int(" in body
    assert "block_until_ready" in body
