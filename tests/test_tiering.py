"""Compiler tiering (ISSUE 7) — differential corpus and satellites.

The contract (compiler/tiering.py, engine/tiered.py, parallel/tiered.py):

1. *Bit-identical execution*: for strict-prefix lengths 0, 1, n-1, and n
   (pure stencil), the tiered matcher's matches, emission order, and
   loss counters equal the untiered engine's on loss-free traces —
   across the jnp path, the fused walk-kernel path, and (untiered side)
   the whole-scan kernel path, including multi-batch boundaries with
   ragged valid prefixes.
2. *Durable carry*: checkpoint/restore and ``widen_state`` preserve a
   live stencil carry — a prefix straddling the snapshot still promotes
   and matches after resume/migration.
3. *Lazy-chain ordering*: reordering a stage's commuting conjuncts never
   changes matches or the accept/ignore/reject attribution tallies.
4. *No-prune assertion*: ``enforce_windows`` + ``within()`` refuses the
   stencil route at compile time instead of silently mis-pruning.
"""

import dataclasses
import os
import zlib

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import engine_scenarios as sc
from kafkastreams_cep_tpu import Query
from kafkastreams_cep_tpu.compiler.tables import lower
from kafkastreams_cep_tpu.compiler.tiering import (
    TIER_HYBRID,
    TIER_NFA,
    TIER_STENCIL,
    apply_lazy_order,
    check_no_prune,
    plan_tiering,
    strict_prefix_len,
)
from kafkastreams_cep_tpu.engine import EngineConfig, EventBatch
from kafkastreams_cep_tpu.engine.matcher import TIER_COUNTER_NAMES
from kafkastreams_cep_tpu.parallel.batch import BatchMatcher
from kafkastreams_cep_tpu.parallel.tiered import TieredBatchMatcher
from kafkastreams_cep_tpu.runtime import CEPProcessor, Record

A, B, C, D, X = 0, 1, 2, 3, 4

# Loss-free on every trace below (asserted): the corpus certifies the
# bit-identical contract in the regime both engines guarantee it.
# dewey_depth carries headroom over the per-batch digit growth of
# waiting skip-till runs (one digit per waited event between renorm
# sweeps): AT Dewey exhaustion the engines may count ver_overflows
# differently — the untiered queue's partial-prefix runs change what
# the renorm can delete — but that regime is already lossy by the
# counter's own definition.
CFG = EngineConfig(
    max_runs=32, slab_entries=96, slab_preds=12, dewey_depth=20,
    max_walk=12,
)
TCFG = dataclasses.replace(CFG, tiering=True)
# Capacity-shedding counters: zero certifies no state was dropped.
DROP_COUNTERS = (
    "run_drops", "slab_full_drops", "slab_pred_drops", "slab_trunc",
    "walk_collisions", "handle_overflows",
)
# Tiny shapes for the (slow) interpret-mode kernel parity runs.
KCFG = EngineConfig(
    max_runs=16, slab_entries=32, slab_preds=8, dewey_depth=8, max_walk=8,
)


def prefix0():
    """Strict-prefix length 0: a fold on the first stage blocks it."""
    return (
        Query()
        .select("a").where(sc.value_is(A))
        .fold("cnt", lambda k, v, c: c + 1)
        .then()
        .select("b").skip_till_next_match().where(sc.value_is(B))
        .build()
    )


def prefix_n_minus_1():
    """Strict A, B, C then skip-till-next D: prefix 3 of n=4."""
    return (
        Query()
        .select("pa").where(sc.value_is(A))
        .then()
        .select("pb").where(sc.value_is(B))
        .then()
        .select("pc").where(sc.value_is(C))
        .then()
        .select("sd").skip_till_next_match().where(sc.value_is(D))
        .build()
    )


# (name, pattern factory, expected tier, expected prefix length)
CORPUS = [
    ("p0_fold", prefix0, TIER_NFA, 0),
    ("p1_skip_next", sc.skip_till_next, TIER_HYBRID, 1),
    ("p2_skip_any", sc.skip_till_any, TIER_HYBRID, 2),
    ("p3_kleene", sc.kleene_one_or_more, TIER_HYBRID, 3),
    ("pn1_strict3_skip", prefix_n_minus_1, TIER_HYBRID, 3),
    ("pn_strict3", sc.strict3, TIER_STENCIL, 3),
]


def batch_of(codes, offs, valid, ts0=1000):
    codes = jnp.asarray(codes, jnp.int32)
    K, T = codes.shape
    return EventBatch(
        key=jnp.zeros((K, T), jnp.int32),
        value=codes,
        ts=jnp.asarray(ts0 + np.asarray(offs), jnp.int32),
        off=jnp.asarray(offs, jnp.int32),
        valid=jnp.asarray(valid, bool),
    )


def grid(out):
    """StepOutput -> {(k, t): [(stages, offs), ...]} in run-row order.

    Row *indices* may differ between the engines (the untiered queue also
    holds partial-prefix runs), but relative row order — the emission
    tie-break within one (k, t) — must not."""
    st, of, ct = (np.asarray(x) for x in (out.stage, out.off, out.count))
    res = {}
    for k, t, r in zip(*np.nonzero(ct)):
        n = int(ct[k, t, r])
        res.setdefault((int(k), int(t)), []).append(
            (tuple(st[k, t, r, :n]), tuple(of[k, t, r, :n]))
        )
    return res


def random_codes(K, total, seed):
    rng = np.random.default_rng(seed)
    return rng.choice(5, size=(K, total), p=[0.3, 0.25, 0.2, 0.2, 0.05]), rng


def ragged_batches(codes, rng, chunk):
    """Split [K, total] codes into ragged valid-prefix batches."""
    K, total = codes.shape
    consumed = np.zeros(K, dtype=int)
    batches = []
    while consumed.min() < total:
        counts = rng.integers(chunk // 2, chunk + 1, size=K)
        vals = np.zeros((K, chunk), np.int64)
        offs = np.zeros((K, chunk), np.int64)
        valid = np.zeros((K, chunk), bool)
        for k in range(K):
            c = min(int(counts[k]), total - consumed[k])
            vals[k, :c] = codes[k, consumed[k]:consumed[k] + c]
            offs[k, :c] = np.arange(consumed[k], consumed[k] + c)
            valid[k, :c] = True
            consumed[k] += c
        batches.append(batch_of(vals, offs, valid))
    return batches


def test_plans_cover_the_prefix_spectrum():
    for name, factory, tier, p in CORPUS:
        tables = lower(factory())
        assert strict_prefix_len(tables) == p, name
        plan = plan_tiering(tables, CFG)
        assert (plan.tier, plan.prefix_len) == (tier, p), (name, plan)


@pytest.mark.parametrize("name,factory,tier,p", CORPUS,
                         ids=[c[0] for c in CORPUS])
def test_tiered_bit_identical_jnp(name, factory, tier, p):
    """Matches, emission order, and counters equal the untiered engine
    over multi-batch ragged scans (jnp path)."""
    K = 6
    # skip-till-any branches exponentially in consumed events; a shorter
    # trace keeps the shared config drop-free for it too.
    total = 24 if name == "p2_skip_any" else 36
    # crc32, not hash(): str hash is randomized per process, and an
    # unlucky PYTHONHASHSEED draws a corpus that sheds capacity (the
    # drop-free assertion below then flakes run-to-run).
    codes, rng = random_codes(K, total, seed=zlib.crc32(name.encode()))
    pat = factory()
    b = BatchMatcher(pat, K, CFG)
    tm = TieredBatchMatcher(pat, K, CFG)
    assert tm.plan.tier == tier
    sb, st = b.init_state(), tm.init_state()
    n_matches = 0
    for ev in ragged_batches(codes, rng, 12):
        sb, ob = b.scan(sb, ev)
        st, ot = tm.scan(st, ev)
        gb, gt = grid(ob), grid(ot)
        assert gb == gt
        n_matches += sum(len(v) for v in gb.values())
        # Maintenance sweep between batches (the processor's cadence):
        # renorm keeps the fixed Dewey width sufficient on straddling
        # runs, and must preserve parity with a live stencil carry.
        sb = b.sweep(sb)
        st = tm.sweep(st)
    cb, ct = b.counters(sb), tm.counters(st)
    assert cb == ct  # bit-identical loss counters, ver_overflows included
    # Drop-free corpus: no capacity shedding on either side.  (A waiting
    # skip-till run appends one Dewey digit per event; ver_overflows may
    # tick — identically, asserted above — when a run waits longer than
    # the renorm cadence can compact.)
    assert all(cb[n] == 0 for n in DROP_COUNTERS), (name, cb)
    tc = tm.tier_counters(st)
    if tier == TIER_NFA:
        assert tc == {n_: 0 for n_ in TIER_COUNTER_NAMES}
    elif tier == TIER_STENCIL:
        # Pure stencil: completions ARE matches; nothing ever promotes.
        assert tc["prefix_fires"] == n_matches > 0
        assert tc["tier_promotions"] == 0
    else:
        assert tc["prefix_events_screened"] > 0
        assert tc["prefix_fires"] == tc["tier_promotions"]  # no drops
    if name in ("p1_skip_next", "p2_skip_any", "pn_strict3"):
        assert n_matches > 0  # the distribution produces real matches


def test_processor_emission_order_parity():
    """End-to-end: the tiered processor forwards (key, Sequence) pairs in
    exactly the untiered order, including same-event multi-match
    tie-breaks (skip-till-any branching)."""
    K = 4
    codes, _ = random_codes(K, 36, seed=77)

    def feed(proc):
        out = []
        for lo in range(0, 36, 12):
            recs = [
                Record(key=k, value=int(codes[k, t]), timestamp=1000 + t)
                for t in range(lo, lo + 12)
                for k in range(K)
            ]
            out.extend(proc.process(recs))
        return [
            (k, [(stg, [e.offset for e in evs])
                 for stg, evs in s.as_map().items()])
            for k, s in out
        ]

    pu = CEPProcessor(sc.skip_till_any(), K, CFG)
    pt = CEPProcessor(sc.skip_till_any(), K, TCFG)
    mu, mt = feed(pu), feed(pt)
    assert len(mu) > 1
    assert mu == mt
    assert pu.counters() == pt.counters()
    assert all(pu.counters()[n] == 0 for n in DROP_COUNTERS)
    snap = pt.metrics_snapshot()
    assert snap["prefix_fires"] > 0
    assert snap["tier_plan"]["tier"] == TIER_HYBRID
    # Labeled Prometheus series: the tier counters render per pattern.
    from kafkastreams_cep_tpu.utils.telemetry import render_prometheus

    text = render_prometheus(snap)
    assert 'cep_prefix_fires{pattern="stream"}' in text
    assert "cep_tier_promotions" in text


def _planted_codes(K, total):
    """Mostly noise, with full prefix+suffix occurrences planted so a
    prefix straddles the batch/checkpoint boundary at t=29/30."""
    codes = np.full((K, total), X, dtype=np.int64)
    for k in range(K):
        codes[k, 5], codes[k, 6], codes[k, 7], codes[k, 11] = A, B, C, D
        # Prefix A@28 B@29 | C@30 (boundary at 30), suffix D@34.
        codes[k, 28], codes[k, 29], codes[k, 30], codes[k, 34] = A, B, C, D
    return codes


def _feed(proc, codes, lo, hi, chunk=10):
    out = []
    for start in range(lo, hi, chunk):
        recs = [
            Record(key=k, value=int(codes[k, t]), timestamp=1000 + t)
            for t in range(start, min(start + chunk, hi))
            for k in range(codes.shape[0])
        ]
        out.extend(proc.process(recs))
    return [
        (k, [(stg, [e.offset for e in evs])
             for stg, evs in s.as_map().items()])
        for k, s in out
    ]


def test_checkpoint_restore_with_live_stencil_carry(tmp_path):
    """A prefix that straddles the snapshot still promotes after restore:
    the carry (trailing window, seed-version count, tier counters) is
    durable state."""
    from kafkastreams_cep_tpu.runtime.checkpoint import (
        restore_processor,
        save_checkpoint,
    )

    K = 3
    codes = _planted_codes(K, 50)
    pat = prefix_n_minus_1()
    proc = CEPProcessor(pat, K, TCFG)
    _ = _feed(proc, codes, 0, 30)  # ends mid-prefix (A@28, B@29 held)
    carry = proc.state.carry
    assert bool(np.asarray(carry.bools).any())  # live partial prefix
    path = str(tmp_path / "ck")
    save_checkpoint(proc, path)
    restored = restore_processor(pat, path)
    cont = _feed(proc, codes, 30, 50)
    rest = _feed(restored, codes, 30, 50)
    assert cont == rest
    # The boundary-spanning match (prefix 28-30, suffix D@34) emitted.
    assert any(
        ("pa", [28]) in m and ("sd", [34]) in m for _, m in rest
    )
    assert restored.tier_counters() == proc.tier_counters()


def test_widen_state_with_live_stencil_carry():
    """Migration onto a strictly-wider config embeds the engine half and
    carries the stencil window verbatim — the straddling prefix still
    completes bit-identically."""
    from kafkastreams_cep_tpu.runtime.migrate import migrate_processor

    K = 3
    codes = _planted_codes(K, 50)
    pat = prefix_n_minus_1()
    proc = CEPProcessor(pat, K, TCFG)
    _ = _feed(proc, codes, 0, 30)
    wide = dataclasses.replace(
        TCFG, max_runs=48, slab_entries=128, dewey_depth=20
    )
    migrated = migrate_processor(pat, proc, wide)
    cont = _feed(proc, codes, 30, 50)
    wide_cont = _feed(migrated, codes, 30, 50)
    assert cont == wide_cont
    assert any(
        ("pa", [28]) in m and ("sd", [34]) in m for _, m in wide_cont
    )


def test_tiering_cannot_flip_under_migration():
    from kafkastreams_cep_tpu.runtime.migrate import check_widens

    with pytest.raises(ValueError, match="tiering"):
        check_widens(TCFG, dataclasses.replace(CFG, max_runs=128))


# ---------------------------------------------------------------------------
# Kernel-path parity (interpret mode)
# ---------------------------------------------------------------------------


def _kernel_trace(K, T, seed):
    """A short trace with planted prefix completions (so promotions and
    suffix matches actually exercise the kernel) over mostly noise."""
    rng = np.random.default_rng(seed)
    codes = rng.choice(5, size=(K, T), p=[0.2, 0.2, 0.2, 0.2, 0.2])
    codes[0, 0], codes[0, 1], codes[0, 2], codes[0, 5] = A, B, C, D
    codes[1, 2], codes[1, 3], codes[1, 4], codes[1, 6] = A, B, C, D
    offs = np.broadcast_to(np.arange(T), (K, T))
    return batch_of(codes, offs, np.ones((K, T), bool))


def test_walk_kernel_tiered_parity():
    """Tiered vs untiered on the fused walk-kernel path: the hybrid scan
    drives the same kernel step, promotions ride jnp between steps."""
    K, T = 128, 8
    ev = _kernel_trace(K, T, 3)
    pat = prefix_n_minus_1()
    os.environ["CEP_WALK_KERNEL"] = "interpret"
    try:
        b = BatchMatcher(pat, K, KCFG)
        tm = TieredBatchMatcher(pat, K, KCFG)
        assert b.uses_walk_kernel and tm.inner.uses_walk_kernel
        sb, ob = b.scan(b.init_state(), ev)
        st, ot = tm.scan(tm.init_state(), ev)
    finally:
        os.environ["CEP_WALK_KERNEL"] = "0"
    g = grid(ob)
    assert g and g == grid(ot)
    assert b.counters(sb) == tm.counters(st)
    assert tm.tier_counters(st)["tier_promotions"] > 0


@pytest.mark.slow
def test_scan_kernel_untiered_vs_tiered_parity():
    """Under CEP_SCAN_KERNEL *both* sides run whole-scan Pallas programs:
    the untiered engine's, and the native tiered program — the stencil
    promotion feed joins the event stream, the promotion phase fuses
    after the engine phases, and every step is gated on device
    (``build_scan(..., promotion=p)``).  Matches, emission order, and
    loss counters must still be bit-identical.

    Slow-tier: the interpret-mode whole-scan programs cost ~1 min each on
    CPU CI; the jnp and walk-kernel differential corpus above stays
    tier-1 (and the untiered scan kernel is itself pinned bit-identical
    to the per-step path by tests/test_scan_kernel.py, so tier-1 already
    covers the composition transitively)."""
    K, T = 128, 8
    ev = _kernel_trace(K, T, 9)
    pat = prefix_n_minus_1()
    os.environ["CEP_WALK_KERNEL"] = "0"
    os.environ["CEP_SCAN_KERNEL"] = "interpret"
    try:
        b = BatchMatcher(pat, K, KCFG)
        assert b.uses_scan_kernel
        tm = TieredBatchMatcher(pat, K, KCFG)
        assert tm.uses_scan_kernel  # the native tiered program, no fallback
        sb, ob = b.scan(b.init_state(), ev)
        st, ot = tm.scan(tm.init_state(), ev)
    finally:
        del os.environ["CEP_SCAN_KERNEL"]
    g = grid(ob)
    assert g and g == grid(ot)
    assert b.counters(sb) == tm.counters(st)
    assert tm.tier_counters(st)["tier_promotions"] > 0
    # Whole-batch kernel dispatches are host-counted; no chunk gating ran.
    assert tm.nfa_dispatches == 1 and tm.gate_chunks == 0


@pytest.mark.slow
def test_scan_kernel_tiered_vs_chunked_parity():
    """The native tiered whole-scan program vs the chunk-gated per-step
    hybrid path: identical matches, loss counters, and promotion counts
    across a multi-batch scan (the kernel's per-step gate and fused
    promotion phase replay the chunked schedule's observable behaviour
    bit-exactly; dead slab entries may hold different inert pointer
    garbage between the two slab representations, so raw state equality
    is deliberately not asserted)."""
    K, T = 128, 8
    pat = prefix_n_minus_1()
    os.environ["CEP_WALK_KERNEL"] = "0"
    os.environ["CEP_SCAN_KERNEL"] = "interpret"
    try:
        tk = TieredBatchMatcher(pat, K, KCFG)
        assert tk.uses_scan_kernel
    finally:
        del os.environ["CEP_SCAN_KERNEL"]
    tc = TieredBatchMatcher(pat, K, KCFG)
    assert not tc.uses_scan_kernel
    sk, sc_ = tk.init_state(), tc.init_state()
    for seed in (9, 10):
        ev = _kernel_trace(K, T, seed)
        sk, ok = tk.scan(sk, ev)
        sc_, oc = tc.scan(sc_, ev)
        g = grid(ok)
        assert g and g == grid(oc)
    assert tk.counters(sk) == tc.counters(sc_)
    assert tk.tier_counters(sk) == tc.tier_counters(sc_)
    assert tk.tier_counters(sk)["tier_promotions"] > 0


# ---------------------------------------------------------------------------
# Lazy extraction under tiering
# ---------------------------------------------------------------------------


def test_hybrid_with_lazy_extraction_drains_identically():
    """Tiering composes with the deferred-drain engine: the tiered lazy
    processor emits the untiered lazy processor's exact stream, and a
    pure-stencil pattern is capped to a hybrid so matches keep flowing
    through the handle ring."""
    lazy = dataclasses.replace(
        TCFG, lazy_extraction=True, handle_ring=64
    )
    lazy_u = dataclasses.replace(lazy, tiering=False)
    plan = plan_tiering(lower(sc.strict3()), lazy)
    assert plan.tier == TIER_HYBRID and plan.prefix_len == 2
    K = 4
    codes, _ = random_codes(K, 36, seed=13)
    pu = CEPProcessor(sc.strict3(), K, lazy_u)
    pt = CEPProcessor(sc.strict3(), K, lazy)
    mu = _feed(pu, codes, 0, 36, chunk=12) + [
        (k, [(stg, [e.offset for e in evs])
             for stg, evs in s.as_map().items()])
        for k, s in pu.flush()
    ]
    mt = _feed(pt, codes, 0, 36, chunk=12) + [
        (k, [(stg, [e.offset for e in evs])
             for stg, evs in s.as_map().items()])
        for k, s in pt.flush()
    ]
    assert len(mu) > 0 and mu == mt
    assert pu.counters() == pt.counters()


# ---------------------------------------------------------------------------
# Lazy-chain predicate ordering
# ---------------------------------------------------------------------------


def _conjunct_pattern():
    """Stage predicates built from and_ chains with deliberately
    expensive-first declaration order, so the pass has work to do."""
    from kafkastreams_cep_tpu.pattern.predicate import and_, hint

    expensive = hint(
        lambda k, v, ts, st: (v * v + 3 * v) % 97 != 11, cost=100.0
    )
    cheap_a = hint(lambda k, v, ts, st: v == A, cost=1.0)
    cheap_b = hint(lambda k, v, ts, st: v <= B, cost=1.0)
    return (
        Query()
        .select("first").where(and_(expensive, cheap_a))
        .then()
        .select("second").skip_till_next_match()
        .where(and_(expensive, cheap_b))
        .build()
    )


def test_reordering_preserves_matches_and_tallies():
    """Property: conjunct reordering never changes matches or the
    accept/ignore/reject attribution tallies (commutativity, measured)."""
    attr = dataclasses.replace(CFG, stage_attribution=True)
    tables = lower(_conjunct_pattern())
    tables2, report = apply_lazy_order(tables)
    assert any(r["reordered"] for r in report.values()), report
    # Cheap conjuncts gate expensive ones after the pass.
    first = report["first"]
    assert first["costs"] == sorted(first["costs"])
    K = 6
    b1 = BatchMatcher(tables, K, attr)
    b2 = BatchMatcher(tables2, K, attr)
    for seed in (1, 2, 3):
        codes, rng = random_codes(K, 32, seed)
        s1, s2 = b1.init_state(), b2.init_state()
        for ev in ragged_batches(codes, rng, 16):
            s1, o1 = b1.scan(s1, ev)
            s2, o2 = b2.scan(s2, ev)
            assert grid(o1) == grid(o2)
        assert b1.stage_counters(s1) == b2.stage_counters(s2)
        assert b1.counters(s1) == b2.counters(s2)


def test_profile_drives_conjunct_selectivity():
    """A measured per_stage profile flows into the ordering decision via
    stage selectivity (ties broken by cost either way)."""
    from kafkastreams_cep_tpu.pattern.predicate import and_, hint

    sel = hint(lambda k, v, ts, st: v == A, cost=4.0, selectivity=0.1)
    loose = hint(lambda k, v, ts, st: v < X, cost=4.0)
    m = and_(loose, sel)
    from kafkastreams_cep_tpu.compiler.tiering import order_conjuncts

    ordered, changed = order_conjuncts(m, stage_sel=0.9)
    # The hinted 0.1-selectivity conjunct beats the profiled 0.9 default.
    assert changed and ordered[0] is m.parts[1]


# ---------------------------------------------------------------------------
# No-prune assertion + snapshot schema
# ---------------------------------------------------------------------------


def test_no_prune_assertion_refuses_windowed_prefix():
    pat = (
        Query()
        .select("a").where(sc.value_is(A))
        .then()
        .select("b").skip_till_next_match().where(sc.value_is(B))
        .within(60, "s")
        .build()
    )
    tables = lower(pat)
    faithful = CFG
    enforcing = dataclasses.replace(CFG, enforce_windows=True)
    assert check_no_prune(tables, faithful) is None
    assert "window" in check_no_prune(tables, enforcing)
    assert plan_tiering(tables, faithful).tier == TIER_HYBRID
    plan = plan_tiering(tables, enforcing)
    assert plan.tier == TIER_NFA and "no-prune" in plan.reason


def test_untiered_snapshots_carry_zero_tier_counters():
    """Schema uniformity: every matcher's metrics_snapshot exposes the
    tier counters (zeros when untiered)."""
    K = 4
    b = BatchMatcher(sc.strict3(), K, CFG)
    s, _ = b.scan(
        b.init_state(),
        batch_of(
            np.zeros((K, 4)), np.broadcast_to(np.arange(4), (K, 4)),
            np.ones((K, 4), bool),
        ),
    )
    snap = b.metrics_snapshot(s)
    for n in TIER_COUNTER_NAMES:
        assert snap[n] == 0


# ---------------------------------------------------------------------------
# Chunk-gated hybrid dispatch (ISSUE 16): the skip/run decision is a
# device-side lax.cond per gate_chunk-sized slice — no host round-trip.
# ---------------------------------------------------------------------------


def test_hybrid_gated_scan_never_syncs_host(monkeypatch):
    """Acceptance: zero per-scan host syncs in hybrid gating.  The chunk
    gate decides skip-vs-dispatch on device, so ``scan`` must never call
    ``jax.device_get`` — the engine's only host-sync primitive — and
    pipelined callers keep full dispatch/decode overlap.  Telemetry
    reads do sync, but only when asked, off the scan path."""
    os.environ["CEP_WALK_KERNEL"] = "0"
    monkeypatch.delenv("CEP_SCAN_KERNEL", raising=False)
    K = 4
    tm = TieredBatchMatcher(sc.skip_till_any(), K, TCFG)
    assert tm.plan.tier == TIER_HYBRID and not tm.uses_scan_kernel
    codes, rng = random_codes(K, 48, seed=7)
    batches = list(ragged_batches(codes, rng, 16))
    st = tm.init_state()
    st, _ = tm.scan(st, batches[0])  # compile outside the counted window
    syncs = []
    real = jax.device_get

    def counting_get(x):
        syncs.append(type(x).__name__)
        return real(x)

    monkeypatch.setattr(jax, "device_get", counting_get)
    for ev in batches[1:]:
        st, out = tm.scan(st, ev)
    # Force all queued device work to finish while the counter is armed:
    # any hidden sync inside scan would already have fired above.
    jax.block_until_ready(jax.tree_util.tree_leaves(st))
    assert syncs == [], syncs
    assert tm.gate_chunks == len(batches) * -(
        -16 // int(TCFG.gate_chunk)
    )
    # Reading the dispatch tally is where the (single) sync lives.
    n = tm.nfa_dispatches
    assert syncs, "nfa_dispatches must be the device read"
    assert 0 <= n <= tm.gate_chunks


def test_gate_chunk_size_is_pure_scheduling():
    """gate_chunk only changes how dispatch is amortised: every chunk
    size yields the untiered engine's exact matches and counters; only
    the gate telemetry differs (ceil(T/C) offered chunks per scan)."""
    os.environ["CEP_WALK_KERNEL"] = "0"
    os.environ.pop("CEP_SCAN_KERNEL", None)
    K = 6
    pat = sc.skip_till_any()
    # total=24 + per-batch sweeps keep the branchy skip-till-any trace
    # drop-free on the shared config (same sizing as the corpus test).
    codes, rng = random_codes(K, 24, seed=23)
    batches = list(ragged_batches(codes, rng, 16))
    ref = BatchMatcher(pat, K, CFG)
    sr = ref.init_state()
    want = []
    for ev in batches:
        sr, o = ref.scan(sr, ev)
        want.append(grid(o))
        sr = ref.sweep(sr)
    assert any(want), "trace must produce matches"
    assert all(ref.counters(sr)[n] == 0 for n in DROP_COUNTERS)
    # One per regime: per-event gating, mid-size (uneven 16/3 tail
    # chunk), and chunk > batch (whole-scan gate).  Each size is a
    # distinct compiled program, so the sweep is priced per entry.
    for chunk in (1, 3, 64):
        tm = TieredBatchMatcher(
            pat, K, dataclasses.replace(TCFG, gate_chunk=chunk)
        )
        st = tm.init_state()
        for ev, g in zip(batches, want):
            st, o = tm.scan(st, ev)
            assert grid(o) == g, chunk
            st = tm.sweep(st)
        assert tm.counters(st) == ref.counters(sr), chunk
        assert tm.gate_chunks == len(batches) * -(-16 // chunk)
        assert 0 <= tm.nfa_dispatches <= tm.gate_chunks


def test_pipelined_tiered_dispatch_never_blocks(monkeypatch):
    """Timing guard for the pipelined-overlap fix (PROFILE_r09 §4): with
    the per-scan host gate gone, a pipelined tiered processor's dispatch
    and device phases perform no host sync at all — batch N's scan stays
    in flight while decode pulls batch N-1's outputs.  Phase-tagging the
    sync primitives pins every pull to the decode/gc phases."""
    os.environ["CEP_WALK_KERNEL"] = "0"
    monkeypatch.delenv("CEP_SCAN_KERNEL", raising=False)
    K = 4
    proc = CEPProcessor(
        sc.skip_till_next(), K, TCFG, epoch=0, pipeline=True
    )
    assert proc.batch.plan.tier == TIER_HYBRID
    codes, _ = random_codes(K, 60, seed=3)
    _feed(proc, codes, 0, 10)  # compile outside the guarded window
    current = {"phase": None}
    orig_phase = proc._phase

    class _Tag:
        def __init__(self, name):
            self.name, self.cm = name, orig_phase(name)

        def __enter__(self):
            current["phase"] = self.name
            return self.cm.__enter__()

        def __exit__(self, *exc):
            current["phase"] = None
            return self.cm.__exit__(*exc)

    monkeypatch.setattr(proc, "_phase", _Tag)
    syncs = []
    real_get, real_block = jax.device_get, jax.block_until_ready
    monkeypatch.setattr(
        jax, "device_get",
        lambda x: (syncs.append(("get", current["phase"])), real_get(x))[1],
    )
    monkeypatch.setattr(
        jax, "block_until_ready",
        lambda x: (
            syncs.append(("block", current["phase"])), real_block(x)
        )[1],
    )
    matches = _feed(proc, codes, 10, 60)
    blocked = [s for s in syncs if s[1] in ("dispatch", "device", "drain")]
    assert blocked == [], blocked
    # Non-vacuous: real matches decoded inside the guarded window, so
    # the decode pull (batch N-1's outputs, overlapping batch N's
    # in-flight scan — a scalar int(c_n) plus the compacted rows) ran
    # without ever blocking the dispatch side.
    assert len(matches) > 0
