"""Two-tier slab (EngineConfig.slab_hot_entries) — property and parity
suites.

The two-tier layout claims (ops/slab.py "Two-tier layout"):

1. *Placement-only*: matches, emissions, and every overflow/drop counter
   are bit-identical to the single-tier engine; only the slot an entry
   occupies may differ.
2. *Promotion invariant*: a newly created entry always lands in the hot
   tier (slots ``[0, E_hot)``).
3. *Demotion invariant*: when the hot tier is full, the least-recent
   (minimum event offset, lowest index on ties) hot entry moves to a free
   overflow slot with its refcount and pointer list intact, and a drop
   happens only when the WHOLE slab is full.
4. *Counter accounting*: every active walk hop is classified exactly once
   (hot_hits + hot_misses = active hops; overflow_walks counts the
   hot-miss -> overflow-hit subset), and both Pallas kernels agree with
   the jnp path bit-for-bit.

All kernel runs use ``interpret=True`` (CPU CI checks parity, not perf).
"""

import dataclasses
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kafkastreams_cep_tpu.engine import EngineConfig, EventBatch, TPUMatcher
from kafkastreams_cep_tpu.ops import dewey_ops
from kafkastreams_cep_tpu.ops import slab as slab_mod
from kafkastreams_cep_tpu.parallel.batch import BatchMatcher

from test_slab_batched import assert_slab_equal

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "examples"))
import stock_demo

E, MP, D, W = 16, 4, 6, 8
EH = 8


def ver(*comps):
    v, l = dewey_ops.make(comps, D)
    return jnp.asarray(v), jnp.asarray(l)


def put_chain(slab, n, hot_entries, start_off=0):
    """n chained entries at offsets start_off.. (stage cycles 0..2)."""
    v1, l1 = ver(1)
    slab = slab_mod.put_first(
        slab, 0, start_off, v1, l1, hot_entries=hot_entries
    )
    v10, l10 = ver(1, 0)
    for i in range(1, n):
        slab = slab_mod.put(
            slab, i % 3, start_off + i, (i - 1) % 3, start_off + i - 1,
            v10, l10, hot_entries=hot_entries,
        )
    return slab


# ---------------------------------------------------------------------------
# Slab-level properties (jnp path)
# ---------------------------------------------------------------------------


def test_new_entries_land_hot_until_full():
    slab = slab_mod.make(E, MP, D)
    slab = put_chain(slab, EH, hot_entries=EH)
    live = np.flatnonzero(np.asarray(slab.stage) >= 0)
    assert live.tolist() == list(range(EH))  # promotion invariant
    assert int(slab.demotions) == 0


def test_demotion_moves_least_recent_hot_entry():
    slab = slab_mod.make(E, MP, D)
    slab = put_chain(slab, EH, hot_entries=EH)  # hot tier now full
    before = {
        (int(s), int(o))
        for s, o in zip(np.asarray(slab.stage), np.asarray(slab.off))
        if s >= 0
    }
    v10, l10 = ver(1, 0)
    slab = slab_mod.put(
        slab, 2, EH, (EH - 1) % 3, EH - 1, v10, l10, hot_entries=EH
    )  # needs a slot -> demotes
    assert int(slab.demotions) == 1
    stage = np.asarray(slab.stage)
    off = np.asarray(slab.off)
    # The new entry is hot; the demoted one is the min-off entry (off=0),
    # now resident in the overflow tier with nothing lost.
    hot = {(int(s), int(o)) for s, o in zip(stage[:EH], off[:EH]) if s >= 0}
    ovf = {(int(s), int(o)) for s, o in zip(stage[EH:], off[EH:]) if s >= 0}
    assert (2, EH) in hot
    assert ovf == {(0, 0)}
    assert hot | ovf == before | {(2, EH)}


def test_demoted_entry_keeps_refs_and_pointers():
    slab = slab_mod.make(E, MP, D)
    slab = put_chain(slab, EH, hot_entries=EH)
    # Bump the victim's refcount so the move has something to preserve.
    v1, l1 = ver(1)
    slab = slab_mod.branch(
        slab, 0, 0, v1, l1, max_walk=1, hot_entries=EH
    )
    refs0 = int(slab.refs[0])
    np0 = int(slab.npreds[0])
    pv0 = np.asarray(slab.pver[0])
    v10, l10 = ver(1, 0)
    slab = slab_mod.put(
        slab, 2, EH, (EH - 1) % 3, EH - 1, v10, l10, hot_entries=EH
    )
    e = int(np.flatnonzero(
        (np.asarray(slab.stage) == 0) & (np.asarray(slab.off) == 0)
    )[0])
    assert e >= EH  # demoted
    assert int(slab.refs[e]) == refs0
    assert int(slab.npreds[e]) == np0
    np.testing.assert_array_equal(np.asarray(slab.pver[e]), pv0)


def test_full_drop_only_when_whole_slab_full():
    small_e = 12  # hot 8 + overflow 4
    slab = slab_mod.make(small_e, MP, D)
    slab = put_chain(slab, small_e, hot_entries=EH)
    assert int(slab.full_drops) == 0
    assert int(slab.demotions) == small_e - EH
    v10, l10 = ver(1, 0)
    slab = slab_mod.put(
        slab, 2, small_e, (small_e - 1) % 3, small_e - 1, v10, l10,
        hot_entries=EH,
    )
    assert int(slab.full_drops) == 1  # now, and only now


def test_hot_miss_overflow_hit_walk_path():
    """A chain whose head stays hot but whose tail was demoted: the
    extraction walk must resolve the tail in the overflow tier (counted in
    overflow_walks) and still extract the identical match."""
    slab = slab_mod.make(E, MP, D)
    n = EH + 4  # 4 oldest entries get demoted
    slab = put_chain(slab, n, hot_entries=EH)
    assert int(slab.demotions) == 4
    # Same chain on a single-tier slab for the expected extraction.
    ref = put_chain(slab_mod.make(E, MP, D), n, hot_entries=0)
    v10, l10 = ver(1, 0)
    # Walk bound must cover the whole chain so the walk descends past the
    # hot window into the demoted tail.
    slab, st, off, cnt = slab_mod.peek(
        slab, (n - 1) % 3, n - 1, v10, l10, max_walk=2 * W, remove=False,
        hot_entries=EH,
    )
    ref, st_r, off_r, cnt_r = slab_mod.peek(
        ref, (n - 1) % 3, n - 1, v10, l10, max_walk=2 * W, remove=False,
    )
    assert int(cnt) == int(cnt_r)
    np.testing.assert_array_equal(np.asarray(st), np.asarray(st_r))
    np.testing.assert_array_equal(np.asarray(off), np.asarray(off_r))
    assert int(slab.overflow_walks) > 0
    # Accounting: every active hop classified exactly once.
    assert int(slab.hot_hits) + int(slab.hot_misses) == int(cnt)
    assert int(slab.overflow_walks) <= int(slab.hot_misses)


def test_tier_lookup_equivalence_random_ops():
    """Randomized put/branch/peek sequences: the two-tier slab must agree
    with the single-tier slab on every output, every drop counter, and the
    live-entry key set (placement-only difference)."""
    rng = np.random.default_rng(77)
    for trial in range(8):
        s2 = slab_mod.make(E, MP, D)
        s1 = slab_mod.make(E, MP, D)
        off_ctr = 0
        for step in range(30):
            op = rng.integers(0, 4)
            stage = int(rng.integers(0, 3))
            vv, vl = ver(*(int(x) for x in rng.integers(1, 3, size=2)))
            if op == 0:
                s2 = slab_mod.put_first(
                    s2, stage, off_ctr, vv, vl, hot_entries=EH
                )
                s1 = slab_mod.put_first(s1, stage, off_ctr, vv, vl)
                off_ctr += 1
            elif op == 1 and off_ctr:
                prev = int(rng.integers(0, off_ctr))
                s2 = slab_mod.put(
                    s2, stage, off_ctr, prev % 3, prev, vv, vl,
                    hot_entries=EH,
                )
                s1 = slab_mod.put(
                    s1, stage, off_ctr, prev % 3, prev, vv, vl
                )
                off_ctr += 1
            elif op == 2 and off_ctr:
                tgt = int(rng.integers(0, off_ctr))
                s2 = slab_mod.branch(
                    s2, tgt % 3, tgt, vv, vl, max_walk=W, hot_entries=EH
                )
                s1 = slab_mod.branch(s1, tgt % 3, tgt, vv, vl, max_walk=W)
            elif op == 3 and off_ctr:
                tgt = int(rng.integers(0, off_ctr))
                s2, st2, of2, n2 = slab_mod.peek(
                    s2, tgt % 3, tgt, vv, vl, max_walk=W, remove=True,
                    hot_entries=EH,
                )
                s1, st1, of1, n1 = slab_mod.peek(
                    s1, tgt % 3, tgt, vv, vl, max_walk=W, remove=True
                )
                assert int(n2) == int(n1), f"trial {trial} step {step}"
                np.testing.assert_array_equal(
                    np.asarray(st2), np.asarray(st1)
                )
                np.testing.assert_array_equal(
                    np.asarray(of2), np.asarray(of1)
                )
        for c in ("full_drops", "pred_drops", "missing", "trunc"):
            assert int(getattr(s2, c)) == int(getattr(s1, c)), (trial, c)
        live2 = {
            (int(s), int(o))
            for s, o in zip(np.asarray(s2.stage), np.asarray(s2.off))
            if s >= 0
        }
        live1 = {
            (int(s), int(o))
            for s, o in zip(np.asarray(s1.stage), np.asarray(s1.off))
            if s >= 0
        }
        assert live2 == live1, trial


# ---------------------------------------------------------------------------
# Kernel parity (interpret mode)
# ---------------------------------------------------------------------------


def stock_events(K, T, seed):
    rng = np.random.default_rng(seed)
    prices = rng.integers(90, 131, size=(K, T)).astype(np.int32)
    vols = rng.integers(600, 1101, size=(K, T)).astype(np.int32)
    return EventBatch(
        key=jnp.broadcast_to(
            jnp.arange(K, dtype=jnp.int32)[:, None], (K, T)
        ),
        value={"price": jnp.asarray(prices), "volume": jnp.asarray(vols)},
        ts=jnp.broadcast_to(
            jnp.arange(T, dtype=jnp.int32)[None, :] * 2, (K, T)
        ),
        off=jnp.broadcast_to(
            jnp.arange(T, dtype=jnp.int32)[None, :], (K, T)
        ),
        valid=jnp.ones((K, T), bool),
    )


# E=16 with an 8-row hot tier under the match-dense stock trace: every
# behavior fires — demotions, overflow-resident walks, full drops, prunes.
PRESSURE_CFG = EngineConfig(
    max_runs=8, slab_entries=16, slab_hot_entries=8, slab_preds=4,
    dewey_depth=8, max_walk=8,
)

SLAB_FIELDS = (
    "stage", "off", "refs", "npreds", "full_drops", "pred_drops",
    "missing", "trunc", "hot_hits", "hot_misses", "overflow_walks",
    "demotions",
)


def assert_same_run(ref, out_r, st_r, krn, out_k, st_k):
    for f in ("count", "stage", "off"):
        np.testing.assert_array_equal(
            np.asarray(getattr(out_r, f)), np.asarray(getattr(out_k, f)),
            err_msg=f,
        )
    for f in SLAB_FIELDS:
        np.testing.assert_array_equal(
            np.asarray(getattr(st_r.slab, f)),
            np.asarray(getattr(st_k.slab, f)), err_msg=f"slab.{f}",
        )
    assert ref.counters(st_r) == krn.counters(st_k)
    assert ref.hot_counters(st_r) == krn.hot_counters(st_k)


def test_walk_kernel_two_tier_parity_under_pressure():
    K, T = 128, 24
    events = stock_events(K, T, 21)
    os.environ["CEP_WALK_KERNEL"] = "0"
    ref = BatchMatcher(stock_demo.stock_pattern(), K, PRESSURE_CFG)
    st_r, out_r = ref.scan(ref.init_state(), events)
    os.environ["CEP_WALK_KERNEL"] = "interpret"
    try:
        krn = BatchMatcher(stock_demo.stock_pattern(), K, PRESSURE_CFG)
        assert krn.uses_walk_kernel
        st_k, out_k = krn.scan(krn.init_state(), events)
    finally:
        os.environ["CEP_WALK_KERNEL"] = "0"
    assert_same_run(ref, out_r, st_r, krn, out_k, st_k)
    hot = ref.hot_counters(st_r)
    assert hot["slab_demotions"] > 0, "pressure config must demote"
    assert hot["slab_overflow_walks"] > 0, "overflow walks must fire"
    assert ref.counters(st_r)["slab_full_drops"] > 0, "drops must fire"


@pytest.mark.slow
def test_scan_kernel_two_tier_parity_under_pressure():
    # Tier-2 (-m slow, ~12 s interpret): the walk-kernel variant above
    # keeps kernel two-tier coverage in tier-1 (ROADMAP tier-1 budget
    # note, PR 13).
    from kafkastreams_cep_tpu.compiler.tables import lower
    from kafkastreams_cep_tpu.ops.scan_kernel import build_scan

    K, T = 128, 12
    events = stock_events(K, T, 31)
    os.environ["CEP_WALK_KERNEL"] = "0"
    ref = BatchMatcher(stock_demo.stock_pattern(), K, PRESSURE_CFG)
    scan = build_scan(lower(stock_demo.stock_pattern()), PRESSURE_CFG)
    scan.interpret = True
    st_r, out_r = ref.scan(ref.init_state(), events)
    st_k, out_k = scan(ref.init_state(), events)
    assert_same_run(ref, out_r, st_r, ref, out_k, st_k)
    assert ref.hot_counters(st_r)["slab_demotions"] > 0


@pytest.mark.slow
def test_two_tier_vs_single_tier_engine_bit_exact():
    """The placement-only claim at engine level: same trace, same shapes,
    hot window on vs off — emissions and drop counters bit-identical.

    Tier-2 (``-m slow``, ~18 s): the walk/scan parity-under-pressure
    pair above keeps the two-tier claim in tier-1 (ROADMAP tier-1
    budget note, PR 13)."""
    K, T = 8, 48
    events = stock_events(K, T, 5)
    os.environ["CEP_WALK_KERNEL"] = "0"
    single = BatchMatcher(
        stock_demo.stock_pattern(), K,
        dataclasses.replace(PRESSURE_CFG, slab_hot_entries=0),
    )
    two = BatchMatcher(stock_demo.stock_pattern(), K, PRESSURE_CFG)
    st_s, out_s = single.scan(single.init_state(), events)
    st_t, out_t = two.scan(two.init_state(), events)
    for f in ("count", "stage", "off"):
        np.testing.assert_array_equal(
            np.asarray(getattr(out_s, f)), np.asarray(getattr(out_t, f)),
            err_msg=f,
        )
    assert single.counters(st_s) == two.counters(st_t)
    # Live-entry key sets equal lane by lane (placement may differ).
    st0, of0 = np.asarray(st_s.slab.stage), np.asarray(st_s.slab.off)
    st1, of1 = np.asarray(st_t.slab.stage), np.asarray(st_t.slab.off)
    for k in range(K):
        a = {(int(s), int(o)) for s, o in zip(st0[k], of0[k]) if s >= 0}
        b = {(int(s), int(o)) for s, o in zip(st1[k], of1[k]) if s >= 0}
        assert a == b, k


def test_sequential_slab_two_tier_matches_batched_placement():
    """sequential_slab=True (literal reference op order) must place every
    entry in the same slot as the batched path — the allocation policy is
    deterministic.  (Residency telemetry may differ by a few hops: the
    sequential path interleaves puts and walks per run, so an entry's tier
    AT WALK TIME can legitimately differ; demotion counts cannot.)"""
    K, T = 4, 32
    events = stock_events(K, T, 9)
    os.environ["CEP_WALK_KERNEL"] = "0"
    bat = BatchMatcher(stock_demo.stock_pattern(), K, PRESSURE_CFG)
    seq = BatchMatcher(
        stock_demo.stock_pattern(), K,
        dataclasses.replace(PRESSURE_CFG, sequential_slab=True),
    )
    st_b, out_b = bat.scan(bat.init_state(), events)
    st_q, out_q = seq.scan(seq.init_state(), events)
    for f in ("count", "stage", "off"):
        np.testing.assert_array_equal(
            np.asarray(getattr(out_b, f)), np.asarray(getattr(out_q, f)),
            err_msg=f,
        )
    np.testing.assert_array_equal(
        np.asarray(st_b.slab.stage), np.asarray(st_q.slab.stage)
    )
    np.testing.assert_array_equal(
        np.asarray(st_b.slab.off), np.asarray(st_q.slab.off)
    )
    assert bat.counters(st_b) == seq.counters(st_q)


# ---------------------------------------------------------------------------
# Config + sizing
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("bad", [4, 7, 16, 24])
def test_invalid_hot_entries_rejected(bad):
    cfg = EngineConfig(
        max_runs=8, slab_entries=16, slab_hot_entries=bad, slab_preds=4,
        dewey_depth=8, max_walk=8,
    )
    if bad % 8 == 0 and 0 < bad < 16:
        TPUMatcher(stock_demo.stock_pattern(), cfg)  # valid: builds
    else:
        with pytest.raises(ValueError, match="slab_hot_entries"):
            TPUMatcher(stock_demo.stock_pattern(), cfg)


def test_suggest_hot_entries_policy():
    from kafkastreams_cep_tpu.engine.sizing import suggest_hot_entries

    assert suggest_hot_entries(16, 8) == 0  # small slab: single tier
    assert suggest_hot_entries(24, 8) == 0
    e = suggest_hot_entries(64, 8)
    assert 0 < e < 64 and e % 8 == 0
    assert suggest_hot_entries(32, 100) == 24  # clamped below E
