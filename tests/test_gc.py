"""Slab mark-sweep GC — the deferred compaction scan (SURVEY §7 step 4).

The sweep's contract (``ops/slab.py:mark_sweep``): free exactly the entries
no future buffer operation can reach — everything beyond ``max_walk``
pointer hops of every live run's pointer offset.  Tests pin

* unit semantics (reachable kept, stranded freed, root = offset not stage),
* output invariance: a stream processed with periodic sweeps emits exactly
  the matches of an unswept run (the sweep is observably free), and
* the long-stream criterion: T >> E at fixed slab_entries with sweeps
  holds ``slab_full_drops == 0`` where the unswept engine saturates
  (``KVSharedVersionedBuffer.java:147-171`` is the reference GC the
  bounded-walk engine extends here).
"""

import jax
import jax.numpy as jnp
import numpy as np

from kafkastreams_cep_tpu import Query
from kafkastreams_cep_tpu.engine import EngineConfig, EventBatch
from kafkastreams_cep_tpu.ops import dewey_ops
from kafkastreams_cep_tpu.ops import slab as slab_mod
from kafkastreams_cep_tpu.parallel import BatchMatcher

E, MP, D = 16, 4, 6


def mkver(*comps):
    v, l = dewey_ops.make(comps, D)
    return jnp.asarray(v), jnp.asarray(l)


def chain_slab(offs):
    """A linear chain: entry i at (stage=i%3, off=offs[i]) pointing at i-1."""
    slab = slab_mod.make(E, MP, D)
    v, l = mkver(1)
    slab = slab_mod.put_first(slab, 0, offs[0], v, l)
    for i in range(1, len(offs)):
        slab = slab_mod.put(
            slab, i % 3, offs[i], (i - 1) % 3, offs[i - 1], v, l
        )
    return slab


def test_sweep_keeps_reachable_frees_stranded():
    slab = chain_slab([0, 1, 2, 3])
    # A second, disconnected chain — stranded (no run references it).
    v, l = mkver(2)
    slab = slab_mod.put_first(slab, 0, 10, v, l)
    slab = slab_mod.put(slab, 1, 11, 0, 10, v, l)

    # One live run whose pointer event is offset 3 (head of chain 1).
    swept = slab_mod.mark_sweep(slab, None, jnp.asarray([3, -1]), depth=8)
    st = np.asarray(swept.stage)
    off = np.asarray(swept.off)
    kept = {(int(s), int(o)) for s, o in zip(st, off) if s >= 0}
    assert kept == {(0, 0), (1, 1), (2, 2), (0, 3)}, kept


def test_sweep_depth_bound_frees_deep_tail():
    offs = list(range(10))
    slab = chain_slab(offs)
    # Run at the head, but sweep depth 3: entries deeper than 3 hops are
    # invisible to any (max_walk=3)-bounded future walk and are freed.
    swept = slab_mod.mark_sweep(slab, None, jnp.asarray([9]), depth=3)
    kept_offs = sorted(
        int(o) for s, o in zip(swept.stage, swept.off) if int(s) >= 0
    )
    assert kept_offs == [6, 7, 8, 9], kept_offs


def test_sweep_roots_are_offset_keyed():
    # Two entries share offset 5 under different stages; a run whose
    # pointer event is 5 must keep both (branch walks / chained puts may
    # start at either stage of that offset).
    slab = chain_slab([4, 5])
    v, l = mkver(3)
    slab = slab_mod.put_first(slab, 2, 5, v, l)
    swept = slab_mod.mark_sweep(slab, None, jnp.asarray([5]), depth=4)
    kept = {
        (int(s), int(o))
        for s, o in zip(swept.stage, swept.off)
        if int(s) >= 0
    }
    assert (1, 5) in kept and (2, 5) in kept
    assert (0, 4) in kept  # predecessor of (1, 5), within depth


def _kleene_pattern():
    return (
        Query()
        .select("a").where(lambda k, v, ts, st: v["x"] > 6)
        .then()
        .select("b").one_or_more().skip_till_next_match()
        .where(lambda k, v, ts, st: v["x"] > 3)
        .then()
        .select("c").where(lambda k, v, ts, st: v["x"] < 2)
        .build()
    )


def _trace(K, T, seed):
    rng = np.random.default_rng(seed)
    xs = rng.integers(0, 10, size=(K, T)).astype(np.int32)
    return EventBatch(
        key=jnp.broadcast_to(jnp.arange(K, dtype=jnp.int32)[:, None], (K, T)),
        value={"x": jnp.asarray(xs)},
        ts=jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32)[None, :], (K, T)),
        off=jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32)[None, :], (K, T)),
        valid=jnp.ones((K, T), bool),
    )


def _run_chunks(m, K, T, chunk, seed, sweep_every=0):
    """Scan in chunks, sweeping after every ``sweep_every``-th chunk
    (0 = never)."""
    state = m.init_state()
    ev = _trace(K, T, seed)
    outs = []
    for i in range(0, T, chunk):
        part = jax.tree_util.tree_map(lambda x: x[:, i:i + chunk], ev)
        state, out = m.scan(state, part)
        if sweep_every and (i // chunk + 1) % sweep_every == 0:
            state = m.sweep(state)
        outs.append(
            (np.asarray(out.stage), np.asarray(out.off), np.asarray(out.count))
        )
    return state, outs


def test_sweep_is_output_invariant():
    """Same matches AND counters with and without sweeps on a stream the
    unswept slab can hold (invariance only holds below saturation — a
    saturated unswept engine drops entries the swept one keeps, which is
    the sweep's point, covered by the long-stream test below)."""
    K, T, chunk = 8, 96, 16
    cfg = EngineConfig(
        max_runs=8, slab_entries=128, slab_preds=8, dewey_depth=8, max_walk=6
    )
    m = BatchMatcher(_kleene_pattern(), K, cfg)
    s0, outs0 = _run_chunks(m, K, T, chunk, seed=5, sweep_every=0)
    s1, outs1 = _run_chunks(m, K, T, chunk, seed=5, sweep_every=1)
    c_no = m.counters(s0)
    assert c_no["slab_full_drops"] == 0, (
        f"test shapes must not saturate the unswept slab: {c_no}"
    )
    assert c_no["slab_trunc"] > 0, (
        "trace should truncate walks (strand entries) for the sweep to act"
    )
    for (a0, b0, c0), (a1, b1, c1) in zip(outs0, outs1):
        np.testing.assert_array_equal(c0, c1)
        np.testing.assert_array_equal(a0, a1)
        np.testing.assert_array_equal(b0, b1)
    assert m.counters(s1) == c_no
    occ0 = int(jnp.sum(s0.slab.stage >= 0))
    occ1 = int(jnp.sum(s1.slab.stage >= 0))
    assert occ1 < occ0


def test_long_stream_fixed_E_no_full_drops():
    """T >> E: periodic sweeps hold slab_full_drops == 0 where the unswept
    engine saturates (the VERDICT round-4 'done' criterion)."""
    # Sizing: the swept slab's occupancy is bounded by the reachable set
    # (<= max_runs * max_walk = 36 entries) plus entries created between
    # sweeps (chunk events), so E=48 with chunk=8 never saturates while the
    # unswept slab (one stranded entry per truncated walk) does by T=256.
    K, T, chunk = 8, 256, 8
    cfg = EngineConfig(
        max_runs=6, slab_entries=48, slab_preds=4, dewey_depth=8, max_walk=6
    )
    m = BatchMatcher(_kleene_pattern(), K, cfg)

    s_no, _ = _run_chunks(m, K, T, chunk, seed=9, sweep_every=0)
    s_gc, _ = _run_chunks(m, K, T, chunk, seed=9, sweep_every=1)
    drops_no = int(jnp.sum(s_no.slab.full_drops))
    drops_gc = int(jnp.sum(s_gc.slab.full_drops))
    assert drops_no > 0, "trace should saturate the unswept slab (T >> E)"
    assert drops_gc == 0, f"swept engine still dropped: {drops_gc}"
