"""Randomized differential fuzz: the array engine vs the host oracle on
>=1000 random traces across the four scenario families (VERDICT item 1).

Engine traces run as one vmapped ``lax.scan`` dispatch per family; every
event's match emission must be identical in count, order, and content, and
no overflow counter may fire (sizes are chosen so the fixed shapes hold the
whole reachable state)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import engine_scenarios as sc
from kafkastreams_cep_tpu import OracleNFA
from kafkastreams_cep_tpu.engine import EngineConfig, EventBatch, TPUMatcher


def batch_scan(matcher: TPUMatcher, events: EventBatch):
    """Run [N, T]-stacked traces from fresh state; one compiled dispatch."""
    init = matcher.init_state()
    fn = jax.jit(jax.vmap(lambda ev: jax.lax.scan(matcher._step_fn, init, ev)))
    return fn(events)


def decode_batch(matcher, out):
    """[N, T, R, W] walk outputs -> per trace, per event, ordered canonical
    matches ``{stage: sorted offsets}``."""
    stage = np.asarray(out.stage)
    off = np.asarray(out.off)
    count = np.asarray(out.count)
    names = matcher.names
    N, T, R, _ = stage.shape
    all_traces = []
    for n in range(N):
        per_event = []
        for t in range(T):
            ms = []
            for r in range(R):
                c = int(count[n, t, r])
                if c == 0:
                    continue
                m = {}
                for w in range(c):
                    m.setdefault(names[int(stage[n, t, r, w])], []).append(
                        int(off[n, t, r, w])
                    )
                ms.append({k: sorted(v) for k, v in m.items()})
            per_event.append(ms)
        all_traces.append(per_event)
    return all_traces


def oracle_canon(pattern, values, ts):
    oracle = OracleNFA.from_pattern(pattern)
    per_event = []
    for i, v in enumerate(values):
        ms = oracle.match(None, v, int(ts[i]), offset=i)
        per_event.append([sc.canon(m) for m in ms])
    return per_event


def fuzz_family(pattern_fn, make_values, to_batch_value, N, T, cfg, seed):
    rng = np.random.default_rng(seed)
    values = make_values(rng, N, T)  # host-value list of lists
    ts = 1000 + np.cumsum(rng.integers(0, 3, size=(N, T)), axis=1)

    pattern = pattern_fn()
    matcher = TPUMatcher(pattern, cfg)
    events = EventBatch(
        key=jnp.zeros((N, T), jnp.int32),
        value=to_batch_value(values),
        ts=jnp.asarray(ts, jnp.int32),
        off=jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32), (N, T)),
        valid=jnp.ones((N, T), bool),
    )
    final_states, out = batch_scan(matcher, events)

    # No silent truncation anywhere in the batch.
    for name in ("run_drops", "ver_overflows"):
        assert int(np.sum(np.asarray(getattr(final_states, name)))) == 0, name
    slab = final_states.slab
    for name in ("full_drops", "pred_drops", "missing", "trunc"):
        assert int(np.sum(np.asarray(getattr(slab, name)))) == 0, name

    engine_traces = decode_batch(matcher, out)
    mismatches = 0
    for n in range(N):
        expected = oracle_canon(pattern, values[n], ts[n])
        if engine_traces[n] != expected:
            mismatches += 1
            if mismatches <= 3:
                print(f"trace {n}: values={values[n]}")
                print(f"  oracle: {expected}")
                print(f"  engine: {engine_traces[n]}")
    assert mismatches == 0, f"{mismatches}/{N} traces diverged"
    return N


def letters(weights):
    def make(rng, N, T):
        codes = rng.choice(len(weights), size=(N, T), p=weights)
        return [[int(c) for c in row] for row in codes]

    return make


def letters_batch(values):
    return jnp.asarray(np.array(values, dtype=np.int32))


def test_fuzz_strict3():
    n = fuzz_family(
        sc.strict3,
        letters([0.35, 0.25, 0.25, 0.05, 0.10]),
        letters_batch,
        N=300, T=16,
        cfg=EngineConfig(max_runs=8, slab_entries=64, slab_preds=4,
                         dewey_depth=8, max_walk=8),
        seed=11,
    )
    assert n == 300


def test_fuzz_kleene():
    n = fuzz_family(
        sc.kleene_one_or_more,
        letters([0.30, 0.25, 0.30, 0.10, 0.05]),
        letters_batch,
        N=240, T=16,
        cfg=EngineConfig(max_runs=16, slab_entries=96, slab_preds=8,
                         dewey_depth=16, max_walk=20),
        seed=12,
    )
    assert n == 240


@pytest.mark.slow
def test_fuzz_skip_till_any():
    # Tier-2 (-m slow, ~18 s): strict3 + kleene fuzz keep the oracle
    # fuzz loop in tier-1 (ROADMAP tier-1 budget note, PR 13).
    n = fuzz_family(
        sc.skip_till_any,
        letters([0.30, 0.25, 0.25, 0.15, 0.05]),
        letters_batch,
        N=240, T=12,
        cfg=EngineConfig(max_runs=48, slab_entries=96, slab_preds=12,
                         dewey_depth=16, max_walk=16),
        seed=13,
    )
    assert n == 240


@pytest.mark.slow
def test_fuzz_stock():
    # Tier-2 (-m slow, ~28 s): strict3 + kleene fuzz keep the oracle
    # fuzz loop in tier-1 (ROADMAP tier-1 budget note, PR 13).
    def make(rng, N, T):
        prices = rng.integers(90, 131, size=(N, T))
        volumes = rng.integers(600, 1101, size=(N, T))
        return [
            [
                {"price": int(prices[n, t]), "volume": int(volumes[n, t])}
                for t in range(T)
            ]
            for n in range(N)
        ]

    def to_batch(values):
        return {
            "price": jnp.asarray(
                [[v["price"] for v in row] for row in values], jnp.int32
            ),
            "volume": jnp.asarray(
                [[v["volume"] for v in row] for row in values], jnp.int32
            ),
        }

    n = fuzz_family(
        sc.stock_query,
        make,
        to_batch,
        N=260, T=14,
        cfg=EngineConfig(max_runs=40, slab_entries=96, slab_preds=10,
                         dewey_depth=20, max_walk=18),
        seed=14,
    )
    assert n == 260
