"""Bench regression gate + profiler CLI — tier-1 smoke (ISSUE 6 satellite).

The gate must accept the repo's real BENCH_r01→r05 trajectory replayed
against itself unchanged, pass on a fixture equal to its baseline, and
reject a fixture with an injected 2× slowdown.  The profiler CLI must
emit one parseable PROFILE JSON object with the per-stage selectivity
table on a tiny synthetic trace.
"""

import glob
import json
import os
import subprocess
import sys

import pytest

_ROOT = os.path.join(os.path.dirname(__file__), "..")
sys.path.insert(0, _ROOT)

import bench_gate

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures")


def _fx(name):
    return os.path.join(FIXTURES, name)


def test_gate_passes_on_equal_input():
    ok, report = bench_gate.gate_paths(
        _fx("bench_equal.json"), [_fx("bench_base.json")]
    )
    assert ok, report
    metrics = {c["metric"] for c in report["checks"]}
    assert {"value", "lossfree_evps", "lossfree_counters_zero"} <= metrics
    assert all(c["ok"] for c in report["checks"])


def test_gate_rejects_injected_2x_slowdown():
    ok, report = bench_gate.gate_paths(
        _fx("bench_slow_2x.json"), [_fx("bench_base.json")]
    )
    assert not ok
    bad = {c["metric"] for c in report["checks"] if not c["ok"]}
    assert {"value", "lossfree_evps"} <= bad


def test_gate_rejects_loss_flag_regression(tmp_path):
    doc = json.load(open(_fx("bench_equal.json")))
    doc["parsed"]["lossfree_counters_zero"] = False
    p = tmp_path / "lossy.json"
    p.write_text(json.dumps(doc))
    ok, report = bench_gate.gate_paths(str(p), [_fx("bench_base.json")])
    assert not ok
    assert any(
        c["metric"] == "lossfree_counters_zero" and not c["ok"]
        for c in report["checks"]
    )


def test_gate_accepts_real_trajectory_unchanged():
    """Each round gated against all earlier rounds must pass — the gate
    would have accepted the project's own history."""
    paths = sorted(glob.glob(os.path.join(_ROOT, "BENCH_r0*.json")))
    assert len(paths) >= 5
    docs = [bench_gate.load_doc(p) for p in paths]
    for k in range(1, len(docs)):
        ok, report = bench_gate.gate(docs[k], docs[:k])
        assert ok, (paths[k], report)


def test_gate_tolerates_noise_within_spread():
    base = bench_gate.load_doc(_fx("bench_base.json"))
    noisy = json.loads(json.dumps(base))
    noisy["parsed"]["value"] *= 0.95  # inside the 10% default tolerance
    ok, _ = bench_gate.gate(noisy, [base])
    assert ok
    worse = json.loads(json.dumps(base))
    worse["parsed"]["value"] *= 0.80  # outside it
    ok, _ = bench_gate.gate(worse, [base])
    assert not ok


def test_gate_cli_exit_codes(tmp_path):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    ok = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "bench_gate.py"),
         _fx("bench_equal.json"), _fx("bench_base.json")],
        capture_output=True, text=True, env=env,
    )
    assert ok.returncode == 0, ok.stdout + ok.stderr
    json.loads(ok.stdout)  # the verdict is machine-readable
    bad = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "bench_gate.py"),
         _fx("bench_slow_2x.json"), "--trajectory",
         os.path.join(FIXTURES, "bench_base.json")],
        capture_output=True, text=True, env=env,
    )
    assert bad.returncode == 1


def test_profiler_cli_selectivity_smoke():
    """``python -m kafkastreams_cep_tpu.profile selectivity`` on a tiny
    synthetic trace: one JSON object on stdout with the per-stage
    selectivity table and the attribution-overhead A/B."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable, "-m", "kafkastreams_cep_tpu.profile",
         "selectivity", "--k", "8", "--t", "16", "--reps", "1",
         "--platform", "cpu"],
        capture_output=True, text=True, cwd=_ROOT, env=env, timeout=600,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    doc = json.loads(out.stdout.strip().splitlines()[-1])
    assert doc["profile"] == "selectivity"
    assert doc["evps_attr_on"] > 0 and doc["evps_attr_off"] > 0
    per_stage = doc["per_stage"]
    assert per_stage, "per-stage table must not be empty"
    row = next(iter(per_stage.values()))
    for key in ("stage_evals", "stage_accepts", "stage_ignores",
                "stage_rejects", "stage_walk_hops", "selectivity"):
        assert key in row
    assert "top" in doc["per_key"]


def test_gate_guards_tier_parity_flags():
    """From BENCH_r06 on, the nested ``tier`` block's match-parity and
    counters-zero flags flatten into guarded ``tier_*`` flags: a later
    round may not regress them (ISSUE 7 satellite)."""
    r06 = bench_gate.load_doc(os.path.join(_ROOT, "BENCH_r06.json"))
    m = bench_gate.extract_metrics(r06)
    assert m["tier_match_parity"] is True
    assert m["tier_counters_zero"] is True
    bad = json.loads(json.dumps(r06))
    bad["parsed"]["tier"]["match_parity"] = False
    ok, report = bench_gate.gate(bad, [r06])
    assert not ok
    assert any(
        c["metric"] == "tier_match_parity" and not c["ok"]
        for c in report["checks"]
    )
    # Earlier rounds without a tier block are simply unguarded, so the
    # historical trajectory still replays clean (covered above).
    assert "tier_match_parity" not in (
        bench_gate.extract_metrics(
            bench_gate.load_doc(os.path.join(_ROOT, "BENCH_r05.json"))
        ) or {}
    )


def test_gate_guards_tenant_bank_flags():
    """From BENCH_r07 on, the nested ``tenants`` block's bit-exactness
    and all-counters-zero flags flatten into guarded ``tenant_*`` flags:
    the shared-screen bank may never silently diverge from the
    naive-fused oracle (ISSUE 14 satellite)."""
    r07 = bench_gate.load_doc(os.path.join(_ROOT, "BENCH_r07.json"))
    m = bench_gate.extract_metrics(r07)
    assert m["tenant_match_parity"] is True
    assert m["tenant_loss_flags"] is True
    bad = json.loads(json.dumps(r07))
    bad["parsed"]["tenants"]["match_parity"] = False
    ok, report = bench_gate.gate(bad, [r07])
    assert not ok
    assert any(
        c["metric"] == "tenant_match_parity" and not c["ok"]
        for c in report["checks"]
    )
    lossy = json.loads(json.dumps(r07))
    lossy["parsed"]["tenants"]["counters_zero"] = False
    ok, report = bench_gate.gate(lossy, [r07])
    assert not ok
    assert any(
        c["metric"] == "tenant_loss_flags" and not c["ok"]
        for c in report["checks"]
    )
    # Rounds predating the tenants block stay unguarded on these flags,
    # so the historical trajectory replays clean (covered above).
    assert "tenant_match_parity" not in (
        bench_gate.extract_metrics(
            bench_gate.load_doc(os.path.join(_ROOT, "BENCH_r06.json"))
        ) or {}
    )


def test_gate_guards_tenant_iso_flags():
    """From BENCH_r09 on, the nested ``resilience.tenant`` block's
    isolation flags flatten into guarded ``tenant_iso_*`` flags: with one
    tenant flooding past its quota, the compliant tenants' matches must
    stay bit-equal to the unquotaed clean bank's (parity) and lose
    nothing to shedding (compliant_lossfree) — a later round may not
    regress either (ISSUE 17 satellite)."""
    r09 = bench_gate.load_doc(os.path.join(_ROOT, "BENCH_r09.json"))
    m = bench_gate.extract_metrics(r09)
    assert m["tenant_iso_parity"] is True
    assert m["tenant_iso_compliant_lossfree"] is True
    bad = json.loads(json.dumps(r09))
    bad["parsed"]["resilience"]["tenant"]["parity"] = False
    ok, report = bench_gate.gate(bad, [r09])
    assert not ok
    assert any(
        c["metric"] == "tenant_iso_parity" and not c["ok"]
        for c in report["checks"]
    )
    lossy = json.loads(json.dumps(r09))
    lossy["parsed"]["resilience"]["tenant"]["compliant_lossfree"] = False
    ok, report = bench_gate.gate(lossy, [r09])
    assert not ok
    assert any(
        c["metric"] == "tenant_iso_compliant_lossfree" and not c["ok"]
        for c in report["checks"]
    )
    # Rounds predating the resilience.tenant block stay unguarded on
    # these flags, so the historical trajectory replays clean.
    assert "tenant_iso_parity" not in (
        bench_gate.extract_metrics(
            bench_gate.load_doc(os.path.join(_ROOT, "BENCH_r08.json"))
        ) or {}
    )


def test_gate_guards_latency_flags_and_p99_ceiling():
    """From BENCH_r10 on, the nested ``latency`` block flattens into the
    guarded ``latency_*`` flags (ledger on/off match+counter parity,
    within-config cadence/grace scheduling parity) and the
    ``latency_e2e_p99_s`` lower-is-better ceiling: observability may
    never change what the engine computes, and the end-to-end p99 may
    not silently blow past the trajectory's best (ISSUE 18 satellite)."""
    r10 = bench_gate.load_doc(os.path.join(_ROOT, "BENCH_r10.json"))
    m = bench_gate.extract_metrics(r10)
    assert m["latency_parity"] is True
    assert m["latency_ab_parity"] is True
    assert m["latency_e2e_p99_s"] > 0
    for key, metric in (
        ("parity", "latency_parity"),
        ("ab_match_parity", "latency_ab_parity"),
    ):
        bad = json.loads(json.dumps(r10))
        bad["parsed"]["latency"][key] = False
        ok, report = bench_gate.gate(bad, [r10])
        assert not ok
        assert any(
            c["metric"] == metric and not c["ok"]
            for c in report["checks"]
        )
    slow = json.loads(json.dumps(r10))
    # The ceiling's latency-specific tolerance is wide (tail latency is
    # log-bucket quantized); 5x p99 must still trip it.
    slow["parsed"]["latency"]["e2e_p99_s"] *= 5
    ok, report = bench_gate.gate(slow, [r10])
    assert not ok
    assert any(
        c["metric"] == "latency_e2e_p99_s" and not c["ok"]
        for c in report["checks"]
    )
    # Rounds predating the latency block stay unguarded on these
    # metrics, so the historical trajectory replays clean.
    assert "latency_parity" not in (
        bench_gate.extract_metrics(
            bench_gate.load_doc(os.path.join(_ROOT, "BENCH_r09.json"))
        ) or {}
    )


def test_gate_guards_overload_flags():
    """From BENCH_r11 on, the nested ``overload`` block flattens into
    the guarded ``overload_*`` flags: the brownout loss ledger must keep
    reconciling exactly (``offered == admitted + shed + dead_lettered``)
    and the ladder must keep recovering to L0 once the flood subsides —
    a later round may not regress either (ISSUE 20 satellite)."""
    r11 = bench_gate.load_doc(os.path.join(_ROOT, "BENCH_r11.json"))
    m = bench_gate.extract_metrics(r11)
    assert m["overload_ledger_reconciles"] is True
    assert m["overload_recovers"] is True
    # The new round itself gates clean against the full history.
    history = [
        bench_gate.load_doc(p)
        for p in sorted(glob.glob(os.path.join(_ROOT, "BENCH_r*.json")))
        if not p.endswith("BENCH_r11.json")
    ]
    ok, report = bench_gate.gate(r11, history)
    assert ok, report
    for key, metric in (
        ("ledger_reconciles", "overload_ledger_reconciles"),
        ("recovers", "overload_recovers"),
    ):
        bad = json.loads(json.dumps(r11))
        bad["parsed"]["overload"][key] = False
        ok, report = bench_gate.gate(bad, [r11])
        assert not ok
        assert any(
            c["metric"] == metric and not c["ok"]
            for c in report["checks"]
        )
    # Rounds predating the overload block stay unguarded on these flags,
    # so the historical trajectory replays clean.
    assert "overload_ledger_reconciles" not in (
        bench_gate.extract_metrics(
            bench_gate.load_doc(os.path.join(_ROOT, "BENCH_r10.json"))
        ) or {}
    )
