"""End-to-end latency attribution (ISSUE 18): segment conservation,
ledger merge algebra, transactional commit across lazy drains, durability
through checkpoint/restore and migration, SLO burn math, and the
Prometheus rendering of the latency families.

Every test pins an injectable fake clock, so segment values are
deterministic — wall-clock flake cannot enter these assertions."""

import dataclasses
import json
import os
import sys

import pytest

import engine_scenarios as sc
from kafkastreams_cep_tpu.runtime import CEPProcessor, Record
from kafkastreams_cep_tpu.runtime.checkpoint import (
    restore_processor,
    save_checkpoint,
)
from kafkastreams_cep_tpu.runtime.ingest import IngestPolicy
from kafkastreams_cep_tpu.runtime.migrate import migrate_processor
from kafkastreams_cep_tpu.utils.latency import (
    SEGMENTS,
    BatchLatency,
    LatencyLedger,
    SLOTracker,
)
from kafkastreams_cep_tpu.utils.telemetry import render_prometheus


class FakeClock:
    """Monotone fake wall clock: every read advances by ``step`` seconds,
    so identical call sequences produce identical stamp sequences."""

    def __init__(self, t0: float = 1000.0, step: float = 0.001):
        self.t = float(t0)
        self.step = float(step)

    def __call__(self) -> float:
        self.t += self.step
        return self.t


def trace(vals, key="k", t0=1000):
    return [Record(key, v, t0 + i) for i, v in enumerate(vals)]


VALS = [sc.A, sc.B, sc.C, sc.X, sc.A, sc.B, sc.C, sc.X, sc.A, sc.B,
        sc.C, sc.X]


def seg_sums(snap):
    segs = snap["latency"]["segments"]
    return {name: segs[name]["sum"] for name in segs}


# -- conservation -------------------------------------------------------------


@pytest.mark.parametrize("grace,drain", [(0, 1), (3, 1), (0, 2), (3, 2)])
def test_segment_sums_reconcile_with_e2e_total(grace, drain):
    """Acceptance: reorder_hold + queue + device + drain_defer sums equal
    e2e_total's sum to float tolerance — conservation holds with and
    without the reorder guard, eager and deferred drains."""
    ingest = IngestPolicy(grace_ms=grace) if grace else None
    proc = CEPProcessor(
        sc.strict3(), 2, sc.default_config(), gc_interval=0,
        ingest=ingest, drain_interval=drain, clock=FakeClock(),
        latency=True,
    )
    for i in range(0, len(VALS), 3):
        proc.process(trace(VALS)[i:i + 3])
    proc.flush()
    if ingest is not None:
        proc.drain_ingest()
    snap = proc.metrics_snapshot(per_lane=False)
    lat = snap["latency"]
    sums = seg_sums(snap)
    total = sum(sums[name] for name in SEGMENTS)
    assert total == pytest.approx(sums["e2e_total"], rel=1e-9, abs=1e-9)
    # Every record observed exactly once in every segment histogram.
    counts = {
        name: lat["segments"][name]["count"] for name in lat["segments"]
    }
    assert len(set(counts.values())) == 1
    assert counts["e2e_total"] == lat["records"] == len(VALS)
    assert lat["deferred_batches"] == 0  # flush commits everything


def test_reorder_hold_measured_under_guard():
    """With a grace window armed, held records accrue reorder_hold time
    (admit stamps ride the guard heap); without one the segment is
    identically zero."""
    clock = FakeClock(step=0.01)
    proc = CEPProcessor(
        sc.strict3(), 1, sc.default_config(), gc_interval=0,
        ingest=IngestPolicy(grace_ms=5), clock=clock, latency=True,
    )
    proc.process(trace([sc.A, sc.B, sc.C]))
    proc.drain_ingest()
    snap = proc.metrics_snapshot(per_lane=False)
    assert seg_sums(snap)["reorder_hold"] > 0
    bare = CEPProcessor(
        sc.strict3(), 1, sc.default_config(), gc_interval=0,
        clock=FakeClock(step=0.01), latency=True,
    )
    bare.process(trace([sc.A, sc.B, sc.C]))
    assert seg_sums(bare.metrics_snapshot(per_lane=False))[
        "reorder_hold"
    ] == 0.0


def test_lazy_drain_deferral_is_transactional():
    """Under lazy extraction with a drain cadence, undrained batches park
    their bundles (deferred, uncommitted) and the drain that emits them
    commits every parked bundle at one emit stamp — the PR 4 deferral
    becomes measured ``drain_defer`` time."""
    cfg = sc.default_config(lazy_extraction=True)
    clock = FakeClock(step=0.005)
    proc = CEPProcessor(
        sc.strict3(), 1, cfg, gc_interval=0, drain_interval=4,
        clock=clock, latency=True,
    )
    proc.process(trace([sc.A, sc.B]))
    proc.process(trace([sc.C, sc.X], t0=1010))
    snap = proc.metrics_snapshot(per_lane=False)["latency"]
    assert snap["deferred_batches"] == 2  # no drain yet: nothing committed
    assert snap["records"] == 0
    proc.flush()
    snap = proc.metrics_snapshot(per_lane=False)["latency"]
    assert snap["deferred_batches"] == 0
    assert snap["records"] == 4
    # The deferral wait is real measured time, not zero.
    assert snap["segments"]["drain_defer"]["sum"] > 0
    sums = seg_sums({"latency": snap})
    assert sum(sums[n] for n in SEGMENTS) == pytest.approx(
        sums["e2e_total"], rel=1e-9
    )


# -- determinism / parity -----------------------------------------------------


def _run(latency, clock=None, env=None, num_lanes=2, vals=VALS):
    if env:
        os.environ[env[0]] = env[1]
    try:
        proc = CEPProcessor(
            sc.strict3(), num_lanes, sc.default_config(), gc_interval=0,
            clock=clock, latency=latency,
        )
        matches = []
        for i in range(0, len(vals), 3):
            matches += proc.process(trace(vals)[i:i + 3])
        matches += proc.flush()
    finally:
        if env:
            os.environ[env[0]] = "0"
    return proc, matches


def test_snapshot_determinism_under_pinned_clock():
    """Identical runs under identical fake clocks produce bit-identical
    latency snapshots — values included, not just counts."""

    def snap():
        proc, _ = _run(True, clock=FakeClock())
        return proc.metrics_snapshot(per_lane=False)["latency"]

    a, b = snap(), snap()
    assert a == b
    assert json.dumps(a, sort_keys=True) == json.dumps(b, sort_keys=True)


def test_ledger_on_off_parity_jnp():
    """Acceptance: arming the ledger changes no observable behavior —
    matches, emission order, and loss counters bit-identical on vs off."""
    p_on, m_on = _run(True, clock=FakeClock())
    p_off, m_off = _run(None)
    assert m_on == m_off  # content AND order
    assert p_on.batch.counters(p_on.state) == p_off.batch.counters(
        p_off.state
    )
    assert p_off.ledger is None
    assert p_on.ledger.records_committed == len(VALS)


@pytest.mark.parametrize(
    "env,mode",
    [
        ("CEP_WALK_KERNEL", "interpret"),
        # The scan-kernel interpret variant is tier-2 (-m slow): it
        # re-executes the whole scan per step in Python (~104 s); the
        # walk-kernel variant keeps interpret parity in tier-1
        # (ROADMAP tier-1 budget note, PR 13).
        pytest.param(
            "CEP_SCAN_KERNEL", "interpret", marks=pytest.mark.slow
        ),
    ],
)
def test_ledger_on_off_parity_kernels(env, mode):
    """The same parity through the Pallas walk/scan kernels (interpret
    mode; 128-lane floor is the kernels' LANE_BLOCK).  Stamps are
    host-side, so the kernel path must be byte-for-byte unaffected."""
    vals = [sc.A, sc.B, sc.C, sc.X, sc.A, sc.B, sc.C]
    p_on, m_on = _run(
        True, clock=FakeClock(), env=(env, mode), num_lanes=128, vals=vals
    )
    p_off, m_off = _run(None, env=(env, mode), num_lanes=128, vals=vals)
    if env == "CEP_WALK_KERNEL":
        assert p_on.batch.uses_walk_kernel
    else:
        assert p_on.batch.uses_scan_kernel
    assert m_on == m_off and m_on  # non-vacuous
    assert p_on.batch.counters(p_on.state) == p_off.batch.counters(
        p_off.state
    )
    assert p_on.ledger.records_committed == len(vals)


# -- merge algebra ------------------------------------------------------------


def _ledger_with(corr, seconds, clock_t0=0.0, query=None, stall=None):
    led = LatencyLedger(clock=lambda: clock_t0)
    b = BatchLatency(corr, 2, None, release=clock_t0)
    b.dispatch = clock_t0 + seconds / 4
    b.complete = clock_t0 + seconds / 2
    led.commit(b, emit=clock_t0 + seconds)
    if query:
        led.observe_query(query, seconds)
    if stall:
        led.observe_stall(stall, seconds, corr=corr)
    return led


def test_merge_is_associative_and_commutative():
    a = _ledger_with("a-1", 0.004, query="q0", stall="recover")
    b = _ledger_with("b-1", 0.4, query="q0", stall="evacuate")
    c = _ledger_with("c-1", 4.0, query="q1")
    assert a.merge(b).merge(c).snapshot() == a.merge(
        b.merge(c)
    ).snapshot()
    ab, ba = a.merge(b).snapshot(), b.merge(a).snapshot()
    assert ab == ba
    assert ab["records"] == 4
    # The worst observation's exemplar wins the merge.
    assert a.merge(b).merge(c).exemplars["e2e_total"]["corr"] == "c-1"
    assert a.merge(b).exemplars["stall.recover"]["corr"] == "a-1"


def test_merge_rejects_mismatched_edges():
    a = LatencyLedger()
    b = LatencyLedger(edges=(0.1, 1.0))
    with pytest.raises(ValueError):
        a.merge(b)


# -- durability ---------------------------------------------------------------


def test_ledger_survives_checkpoint_restore_exactly_once(tmp_path):
    """The ledger rides the checkpoint header: a restore resumes the
    committed histograms, and replaying the post-checkpoint batch
    re-observes it exactly once on the restore timeline (no double
    counting, no loss)."""
    clock = FakeClock()
    proc = CEPProcessor(
        sc.strict3(), 1, sc.default_config(), gc_interval=0,
        ingest=IngestPolicy(grace_ms=0), clock=clock, latency=True,
    )
    pre = trace([sc.A, sc.B, sc.C])
    post = trace([sc.A, sc.B, sc.C], t0=1010)
    proc.process(pre)
    path = str(tmp_path / "lat.ckpt")
    save_checkpoint(proc, path)
    want_state = proc.ledger.to_state()
    proc.process(post)  # lost with the crash below
    res = restore_processor(sc.strict3(), path)
    assert res.ledger is not None
    assert res.ledger.to_state() == want_state
    res.set_clock(clock)  # re-inject: clocks are wiring, never pickled
    res.process(post)  # replay
    assert res.ledger.records_committed == len(pre) + len(post)
    # Segment values on the replayed batch are honest wall clock under
    # the re-injected pinned clock — conservation still holds.
    snap = res.metrics_snapshot(per_lane=False)
    sums = seg_sums(snap)
    assert sum(sums[n] for n in SEGMENTS) == pytest.approx(
        sums["e2e_total"], rel=1e-9
    )


def test_ledger_rides_migration_by_reference(tmp_path):
    """migrate_processor carries the live ledger object itself — an
    escalation mid-stream never resets latency attribution."""
    proc = CEPProcessor(
        sc.strict3(), 1, sc.default_config(), gc_interval=0,
        clock=FakeClock(), latency=True,
    )
    proc.process(trace([sc.A, sc.B, sc.C]))
    wider = dataclasses.replace(
        sc.default_config(), max_runs=32, slab_entries=64
    )
    moved = migrate_processor(sc.strict3(), proc, wider)
    assert moved.ledger is proc.ledger
    moved.process(trace([sc.A, sc.B, sc.C], t0=1010))
    assert moved.ledger.records_committed == 6


# -- SLO ----------------------------------------------------------------------


def test_slo_tracker_burn_math_and_window():
    t = SLOTracker(threshold_s=0.1, target=0.99, window=3)
    t.observe(1, 10)
    assert t.burn_rate() == pytest.approx((1 / 10) / 0.01)  # 10x budget
    for _ in range(5):
        t.observe(0, 10)
    assert len(t._pairs) == 3  # bounded window evicts the burn
    assert t.burn_rate() == 0.0
    with pytest.raises(ValueError):
        SLOTracker(threshold_s=0.1, target=1.5)
    with pytest.raises(ValueError):
        SLOTracker(threshold_s=0.0)


def test_slo_burn_exported_from_processor():
    """A threshold tighter than the fake clock's per-batch latency burns;
    the gauge reaches the snapshot and the Prometheus rendering."""
    led = LatencyLedger(
        clock=FakeClock(step=0.01), slo=SLOTracker(threshold_s=1e-6)
    )
    proc = CEPProcessor(
        sc.strict3(), 1, sc.default_config(), gc_interval=0,
        clock=FakeClock(step=0.01), latency=led,
    )
    proc.process(trace([sc.A, sc.B, sc.C]))
    snap = proc.metrics_snapshot(per_lane=False)
    slo = snap["latency"]["slo"]
    assert slo["window_over"] == slo["window_records"] == 3
    assert slo["burn_rate"] == pytest.approx(100.0)  # 1.0 / (1 - 0.99)
    txt = render_prometheus(snap)
    assert "cep_slo_burn 100" in txt
    assert "# TYPE cep_slo_burn gauge" in txt


def test_slo_burn_window_survives_supervisor_resume(tmp_path):
    """Regression (ISSUE 20 satellite): the SLO tracker's rolling window
    rides the checkpoint header, AND ``Supervisor.resume`` re-injects
    the pinned clock into the restored processor — without the clock
    re-injection the restored burn window would mix pinned-clock history
    with wall-clock stamps and the overload controller would read a
    garbage burn signal after every crash."""
    from kafkastreams_cep_tpu.runtime import Supervisor

    clock = FakeClock(step=0.01)
    kw = dict(
        checkpoint_path=str(tmp_path / "slo.ckpt"),
        journal_path=str(tmp_path / "slo.jrnl"),
        checkpoint_every=1, gc_interval=0,
        ingest=IngestPolicy(grace_ms=0), clock=clock,
        latency=LatencyLedger(
            slo=SLOTracker(threshold_s=1e-6), clock=clock
        ),
    )
    sup = Supervisor(sc.strict3(), 1, sc.default_config(), **kw)
    for i, v in enumerate([sc.A, sc.B, sc.C]):
        sup.process([Record("k", v, 1000 + i, offset=i)])
    want_burn = sup.processor.ledger.slo.burn_rate()
    assert want_burn > 0  # the tight threshold is burning
    del sup  # crash
    sup2 = Supervisor.resume(sc.strict3(), 1, sc.default_config(), **kw)
    led = sup2.processor.ledger
    assert led.slo.burn_rate() == pytest.approx(want_burn)
    # Clocks are wiring, never pickled: resume re-pins them everywhere.
    assert led.clock is clock
    assert sup2.processor._guard._clock is clock
    # Post-resume batches keep observing on the pinned timeline.
    sup2.process([Record("k", sc.A, 2000, offset=3)])
    assert led.records_committed == 4
    assert led.slo.burn_rate() > 0


# -- rendering / exemplars ----------------------------------------------------


def test_prometheus_renders_latency_families():
    led = _ledger_with("stream-1", 0.4, query="q0", stall="recover")
    led.slo = SLOTracker(threshold_s=0.1)
    led.slo.observe(1, 2)
    txt = render_prometheus({"latency": led.snapshot()})
    assert 'cep_latency_seconds_bucket{segment="e2e_total",le=' in txt
    assert 'cep_latency_seconds_count{segment="queue"} 2' in txt
    assert 'cep_stall_seconds_count{cause="recover"} 1' in txt
    assert 'cep_latency_query_seconds_count{query="q0"} 1' in txt
    assert "cep_slo_burn 50" in txt
    assert "cep_latency_batches_total 1" in txt
    assert "cep_latency_records_total 2" in txt
    assert "# TYPE cep_latency_seconds histogram" in txt
    assert "# HELP cep_latency_seconds" in txt


def test_exemplars_resolve_to_batch_correlation_ids():
    """Every segment exemplar names the ``corr`` of the worst-observed
    batch — the same ``<name>-<seq>`` id the batch trace span carries."""
    proc = CEPProcessor(
        sc.strict3(), 1, sc.default_config(), gc_interval=0,
        clock=FakeClock(), latency=True,
    )
    n_batches = 0
    for i in range(0, len(VALS), 3):
        proc.process(trace(VALS)[i:i + 3])
        n_batches += 1
    ex = proc.metrics_snapshot(per_lane=False)["latency"]["exemplars"]
    for seg in SEGMENTS + ("e2e_total",):
        name, seq = ex[seg]["corr"].rsplit("-", 1)
        assert name == proc.name
        assert 1 <= int(seq) <= n_batches
