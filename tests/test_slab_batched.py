"""Batched slab kernels vs the sequential ops they replace.

The batched kernels claim per-entry op ordering identical to applying the
sequential entry points one op at a time in the same order.  These tests
build randomized op sets — including adversarial shared-path/shared-entry
cases — and assert the resulting slab states match field-for-field.
Production coverage: the engine's batched path runs ``puts_batched``,
``branch_batched``, and ``walks_batched`` (``peek_batched`` is a wrapper
over the latter); each is differentially tested here, including
``walks_batched`` with mixed increment/remove walkers — the merged
branch+removal shape its docstring licenses.

The engine-level equivalence (sequential_slab=True vs False) is covered by
``test_ab_engine_paths`` on a branching-heavy trace.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kafkastreams_cep_tpu.engine import EngineConfig, TPUMatcher
from kafkastreams_cep_tpu.engine.matcher import MatcherSession
from kafkastreams_cep_tpu.ops import dewey_ops
from kafkastreams_cep_tpu.ops import slab as slab_mod

E, MP, D, W = 16, 4, 6, 8


def canon_slab(s):
    """Zero out semantically-dead storage so comparisons see only live state:
    pointer slots at index >= npreds (stale leftovers of overwrites/prunes)
    and all per-entry fields of free slots (stage < 0).  Both paths mask
    these regions on every read, so they are free to differ."""
    stage = np.asarray(s.stage)
    off = np.asarray(s.off)
    refs = np.asarray(s.refs).copy()
    npreds = np.asarray(s.npreds).copy()
    pstage = np.asarray(s.pstage).copy()
    poff = np.asarray(s.poff).copy()
    pver = np.asarray(s.pver).copy()
    pvlen = np.asarray(s.pvlen).copy()
    live = stage >= 0
    slot_live = live[:, None] & (np.arange(pstage.shape[1])[None, :] < npreds[:, None])
    pstage[~slot_live] = 0
    poff[~slot_live] = 0
    pver[~slot_live] = 0
    pvlen[~slot_live] = 0
    refs[~live] = 0
    npreds[~live] = 0
    return dict(
        stage=stage, off=np.where(live, off, -1), refs=refs, npreds=npreds,
        pstage=pstage, poff=poff, pver=pver, pvlen=pvlen,
        full_drops=np.asarray(s.full_drops), pred_drops=np.asarray(s.pred_drops),
        missing=np.asarray(s.missing), trunc=np.asarray(s.trunc),
    )


def assert_slab_equal(a, b, msg=""):
    ca, cb = canon_slab(a), canon_slab(b)
    for name in ca:
        np.testing.assert_array_equal(
            ca[name], cb[name], err_msg=f"{msg} field {name}"
        )


def mkver(*comps):
    v, l = dewey_ops.make(comps, D)
    return jnp.asarray(v), jnp.asarray(l)


def seed_slab(rng, n_entries=6, max_off=4):
    """A slab pre-populated through the sequential API (chains of puts)."""
    slab = slab_mod.make(E, MP, D)
    # A couple of chained runs sharing prefixes.
    v1, l1 = mkver(1)
    v10, l10 = mkver(1, 0)
    v11, l11 = mkver(1, 1)
    slab = slab_mod.put_first(slab, 0, 0, v1, l1)
    slab = slab_mod.put(slab, 1, 1, 0, 0, v10, l10)
    slab = slab_mod.put(slab, 1, 2, 1, 1, v10, l10)
    slab = slab_mod.put(slab, 2, 3, 1, 2, v11, l11)
    slab = slab_mod.put_first(slab, 0, 2, v11, l11)
    return slab


def random_put_ops(rng, P, cur_off):
    en = rng.random(P) < 0.8
    first = rng.random(P) < 0.3
    cur_stage = rng.integers(0, 4, size=P)
    prev_stage = rng.integers(0, 3, size=P)
    prev_off = rng.integers(0, 4, size=P)
    vers, vlens = [], []
    for _ in range(P):
        comps = tuple(rng.integers(1, 3, size=rng.integers(1, 4)))
        v, l = dewey_ops.make(comps, D)
        vers.append(v)
        vlens.append(l)
    return slab_mod.PutOps(
        en=jnp.asarray(en),
        first=jnp.asarray(first),
        cur_stage=jnp.asarray(cur_stage, jnp.int32),
        prev_stage=jnp.where(jnp.asarray(first), -1, jnp.asarray(prev_stage, jnp.int32)),
        prev_off=jnp.where(jnp.asarray(first), -1, jnp.asarray(prev_off, jnp.int32)),
        ver=jnp.asarray(np.stack(vers)),
        vlen=jnp.asarray(np.stack(vlens)),
    )


def puts_sequential(slab, ops, off):
    P = int(ops.en.shape[0])
    for p in range(P):
        slab = slab_mod.put_first(
            slab, ops.cur_stage[p], off, ops.ver[p], ops.vlen[p],
            enable=ops.en[p] & ops.first[p],
        )
        slab = slab_mod.put(
            slab, ops.cur_stage[p], off, ops.prev_stage[p], ops.prev_off[p],
            ops.ver[p], ops.vlen[p], enable=ops.en[p] & ~ops.first[p],
        )
    return slab


@pytest.mark.parametrize("seed", range(8))
def test_puts_batched_matches_sequential(seed):
    rng = np.random.default_rng(seed)
    slab0 = seed_slab(rng)
    ops = random_put_ops(rng, P=10, cur_off=7)
    seq = puts_sequential(slab0, ops, jnp.int32(7))
    bat = slab_mod.puts_batched(slab0, ops, jnp.int32(7))
    assert_slab_equal(seq, bat, f"seed={seed}")


def test_puts_batched_first_reset_erases_earlier_appends():
    rng = np.random.default_rng(0)
    slab0 = seed_slab(rng)
    v, l = mkver(2)
    ops = slab_mod.PutOps(
        en=jnp.asarray([True, True, True]),
        first=jnp.asarray([False, True, False]),
        cur_stage=jnp.asarray([3, 3, 3], jnp.int32),
        prev_stage=jnp.asarray([1, -1, 2], jnp.int32),
        prev_off=jnp.asarray([1, -1, 3], jnp.int32),
        ver=jnp.stack([v, v, v]),
        vlen=jnp.stack([l, l, l]),
    )
    seq = puts_sequential(slab0, ops, jnp.int32(9))
    bat = slab_mod.puts_batched(slab0, ops, jnp.int32(9))
    assert_slab_equal(seq, bat)
    # After the reset, the entry holds the null pointer then op 3's pointer.
    e = int(jnp.argmax((bat.stage == 3) & (bat.off == 9)))
    assert int(bat.npreds[e]) == 2
    assert int(bat.pstage[e, 0]) == -1 and int(bat.pstage[e, 1]) == 2


def branch_sequential(slab, en, stage, off, ver, vlen):
    for p in range(int(en.shape[0])):
        slab = slab_mod.branch(
            slab, stage[p], off[p], ver[p], vlen[p], W, enable=en[p]
        )
    return slab


@pytest.mark.parametrize("seed", range(6))
def test_branch_batched_matches_sequential(seed):
    rng = np.random.default_rng(100 + seed)
    slab0 = seed_slab(rng)
    P = 6
    en = jnp.asarray(rng.random(P) < 0.7)
    stage = jnp.asarray(rng.integers(0, 4, size=P), jnp.int32)
    off = jnp.asarray(rng.integers(0, 5, size=P), jnp.int32)
    vers, vlens = [], []
    for _ in range(P):
        comps = tuple(rng.integers(1, 3, size=rng.integers(1, 3)))
        v, l = dewey_ops.make(comps, D)
        vers.append(v)
        vlens.append(l)
    ver = jnp.asarray(np.stack(vers))
    vlen = jnp.asarray(np.stack(vlens))
    seq = branch_sequential(slab0, en, stage, off, ver, vlen)
    bat = slab_mod.branch_batched(slab0, en, stage, off, ver, vlen, W)
    assert_slab_equal(seq, bat, f"seed={seed}")


def peek_sequential(slab, en, stage, off, ver, vlen, remove=True):
    outs = []
    for p in range(int(en.shape[0])):
        slab, st, of, cnt = slab_mod.peek(
            slab, stage[p], off[p], ver[p], vlen[p], W,
            remove=remove, enable=en[p],
        )
        outs.append((np.asarray(st), np.asarray(of), int(cnt)))
    return slab, outs


@pytest.mark.parametrize("seed", range(8))
def test_peek_batched_matches_sequential(seed):
    """Random walkers, including deliberate shared-entry starts.

    Engine states maintain the invariant that every additional run lineage
    referencing a buffer node went through ``branch()`` (+1 refcount), so a
    node can never be deleted/pruned from under a walker that still has to
    traverse it.  The test reproduces that invariant by branching once per
    extra walker before removing — without it, sequential and lockstep
    removal orders are legitimately distinguishable (and such states are
    unreachable through the engine; see ``peek_batched``'s docstring).
    """
    rng = np.random.default_rng(200 + seed)
    slab0 = seed_slab(rng)
    P = 5
    # Half the walkers start at the shared chain head (2, 3) to force
    # same-entry same-hop conflicts.
    stage = np.where(rng.random(P) < 0.5, 2, rng.integers(0, 4, size=P))
    off = np.where(stage == 2, 3, rng.integers(0, 5, size=P))
    en = jnp.asarray(rng.random(P) < 0.8)
    vers, vlens = [], []
    for _ in range(P):
        comps = tuple(rng.integers(1, 3, size=rng.integers(1, 4)))
        v, l = dewey_ops.make(comps, D)
        vers.append(v)
        vlens.append(l)
    ver = jnp.asarray(np.stack(vers))
    vlen = jnp.asarray(np.stack(vlens))
    stage = jnp.asarray(stage, jnp.int32)
    off = jnp.asarray(off, jnp.int32)

    # Refcount invariant: one branch per walker beyond the first.
    for p in range(1, P):
        slab0 = slab_mod.branch(
            slab0, stage[p], off[p], ver[p], vlen[p], W, enable=en[p]
        )

    seq_slab, seq_outs = peek_sequential(slab0, en, stage, off, ver, vlen)
    bat_slab, b_st, b_of, b_cnt = slab_mod.peek_batched(
        slab0, en, stage, off, ver, vlen, W, remove=True
    )
    assert_slab_equal(seq_slab, bat_slab, f"seed={seed}")
    for p, (st, of, cnt) in enumerate(seq_outs):
        assert int(b_cnt[p]) == cnt, f"walker {p} count"
        np.testing.assert_array_equal(np.asarray(b_st[p]), st, f"walker {p}")
        np.testing.assert_array_equal(np.asarray(b_of[p]), of, f"walker {p}")


@pytest.mark.parametrize("seed", range(8))
def test_walks_batched_mixed_matches_sequential(seed):
    """Mixed increment (branch) + remove walkers in one merged pass vs the
    sequential branch-then-peek order, on invariant-respecting states."""
    rng = np.random.default_rng(300 + seed)
    slab0 = seed_slab(rng)
    PB, PR = 4, 4
    b_stage = jnp.asarray(rng.integers(0, 4, size=PB), jnp.int32)
    b_off = jnp.asarray(rng.integers(0, 5, size=PB), jnp.int32)
    b_en = jnp.asarray(rng.random(PB) < 0.7)
    r_stage = np.where(rng.random(PR) < 0.5, 2, rng.integers(0, 4, size=PR))
    r_off = np.where(r_stage == 2, 3, rng.integers(0, 5, size=PR))
    r_en = jnp.asarray(rng.random(PR) < 0.8)
    vers, vlens = [], []
    for _ in range(PB + PR):
        comps = tuple(rng.integers(1, 3, size=rng.integers(1, 4)))
        v, l = dewey_ops.make(comps, D)
        vers.append(v)
        vlens.append(l)
    ver = jnp.asarray(np.stack(vers))
    vlen = jnp.asarray(np.stack(vlens))
    r_stage = jnp.asarray(r_stage, jnp.int32)
    r_off = jnp.asarray(r_off, jnp.int32)

    # Refcount invariant for the removers (one branch per extra walker).
    for p in range(1, PR):
        slab0 = slab_mod.branch(
            slab0, r_stage[p], r_off[p], ver[PB + p], vlen[PB + p], W,
            enable=r_en[p],
        )

    seq = branch_sequential(slab0, b_en, b_stage, b_off, ver[:PB], vlen[:PB])
    seq, seq_outs = peek_sequential(
        seq, r_en, r_stage, r_off, ver[PB:], vlen[PB:]
    )

    bat, b_st, b_of, b_cnt = slab_mod.walks_batched(
        slab0,
        jnp.concatenate([b_en, r_en]),
        jnp.concatenate([b_stage, r_stage]),
        jnp.concatenate([b_off, r_off]),
        ver,
        vlen,
        is_remove=jnp.asarray([False] * PB + [True] * PR),
        want_out=jnp.asarray([False] * PB + [True] * PR),
        max_walk=W,
    )
    assert_slab_equal(seq, bat, f"seed={seed}")
    for p, (st, of, cnt) in enumerate(seq_outs):
        assert int(b_cnt[PB + p]) == cnt, f"walker {p} count"
        np.testing.assert_array_equal(np.asarray(b_st[PB + p]), st, f"walker {p}")
        np.testing.assert_array_equal(np.asarray(b_of[PB + p]), of, f"walker {p}")


def test_walk_collisions_counted_in_lockstep_only():
    """Two remove-walkers meeting at one entry in one hop is the exact
    trigger for lockstep prune/delete attribution deviating from the
    sequential order; the ``collisions`` counter must record it in the
    lockstep pass and stay zero when walkers run alone (budget=1, the
    engine default)."""
    rng = np.random.default_rng(7)
    v11, l11 = mkver(1, 1)
    P = 2
    stage = jnp.asarray([2, 2], jnp.int32)
    off = jnp.asarray([3, 3], jnp.int32)
    en = jnp.asarray([True, True])
    ver = jnp.stack([v11, v11])
    vlen = jnp.stack([l11, l11])
    ones = jnp.ones((P,), bool)

    def fresh():
        slab = seed_slab(rng)
        # Refcount invariant: the second lineage branched onto the chain.
        return slab_mod.branch(slab, stage[1], off[1], ver[1], vlen[1], W)

    bat, _, _, _ = slab_mod.walks_batched(
        fresh(), en, stage, off, ver, vlen,
        is_remove=ones, want_out=ones, max_walk=W,
    )
    assert int(bat.collisions) > 0, "lockstep meeting not counted"

    solo, _, _, _ = slab_mod.walks_compacted(
        fresh(), en, stage, off, ver, vlen,
        is_remove=ones, want_out=ones, max_walk=W,
        budget=1, out_base=0, out_rows=P,
    )
    assert int(solo.collisions) == 0, "budget=1 must be collision-free"

    wide, _, _, _ = slab_mod.walks_compacted(
        fresh(), en, stage, off, ver, vlen,
        is_remove=ones, want_out=ones, max_walk=W,
        budget=2, out_base=0, out_rows=P,
    )
    assert int(wide.collisions) > 0, "budget=2 same-entry meeting not counted"


def test_ab_engine_paths():
    """Engine-level A/B: sequential_slab True vs False on a branching-heavy
    skip-till-any trace must produce identical matches and counters."""
    from kafkastreams_cep_tpu import Query

    def pattern():
        return (
            Query()
            .select("a").skip_till_any_match()
            .where(lambda k, v, ts, st: (v % 3) == 0)
            .then()
            .select("b").skip_till_any_match()
            .where(lambda k, v, ts, st: (v % 3) == 1)
            .then()
            .select("c")
            .where(lambda k, v, ts, st: (v % 3) == 2)
            .build()
        )

    cfg_kw = dict(
        max_runs=24, slab_entries=96, slab_preds=8, dewey_depth=12,
        max_walk=12,
    )
    rng = np.random.default_rng(7)
    values = rng.integers(0, 6, size=40).tolist()

    results = []
    for sequential in (True, False):
        m = TPUMatcher(
            pattern(), EngineConfig(sequential_slab=sequential, **cfg_kw)
        )
        sess = MatcherSession(m)
        all_matches = []
        for i, v in enumerate(values):
            for s in sess.match(None, int(v), 1000 + i, offset=i):
                all_matches.append(
                    tuple(
                        (name, tuple(e.offset for e in evs))
                        for name, evs in s.as_map().items()
                    )
                )
        results.append((all_matches, sess.counters()))
    assert results[0][0] == results[1][0]
    # All capacity/overflow counters must agree.  `missing` may legitimately
    # differ: it diagnoses states where the reference NPEs (a dead run's
    # removal deleting an entry a later same-step op references,
    # KVSharedVersionedBuffer.java:86-89); the batched phase order reaches
    # fewer of those lookups than the literal per-run interleave, while
    # match output stays identical (asserted above).
    seq_counters, bat_counters = results[0][1], results[1][1]
    for name in seq_counters:
        if name != "slab_missing":
            assert seq_counters[name] == bat_counters[name], name
