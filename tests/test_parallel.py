"""Multi-chip sharding tests (VERDICT item 5): key lanes sharded over the
virtual 8-device CPU mesh must produce exactly the match sets of the
single-device batch matcher, and the oracle, lane for lane."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import engine_scenarios as sc
from kafkastreams_cep_tpu import OracleNFA
from kafkastreams_cep_tpu.engine import EngineConfig, EventBatch
from kafkastreams_cep_tpu.parallel import BatchMatcher, ShardedMatcher, key_mesh
from test_engine_fuzz import decode_batch, oracle_canon


def make_trace_batch(rng, K, T, weights):
    codes = rng.choice(len(weights), size=(K, T), p=weights)
    events = EventBatch(
        key=jnp.broadcast_to(jnp.arange(K, dtype=jnp.int32)[:, None], (K, T)),
        value=jnp.asarray(codes, jnp.int32),
        ts=jnp.broadcast_to(
            1000 + jnp.arange(T, dtype=jnp.int32)[None, :], (K, T)
        ),
        off=jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32)[None, :], (K, T)),
        valid=jnp.ones((K, T), bool),
    )
    return codes, events


pytestmark = pytest.mark.skipif(
    jax.device_count() < 8, reason="needs the 8-device virtual mesh"
)


def test_sharded_matches_single_device_and_oracle():
    K, T = 16, 12
    rng = np.random.default_rng(7)
    cfg = EngineConfig(
        max_runs=16, slab_entries=96, slab_preds=8, dewey_depth=16, max_walk=20
    )
    pattern = sc.kleene_one_or_more()
    codes, events = make_trace_batch(rng, K, T, [0.30, 0.25, 0.30, 0.10, 0.05])

    mesh = key_mesh(jax.devices()[:8])
    sharded = ShardedMatcher(pattern, K, mesh, cfg)
    st = sharded.scan(sharded.init_state(), sharded.shard_events(events))
    sh_state, sh_out = st

    batch = BatchMatcher(pattern, K, cfg)
    b_state, b_out = batch.scan(batch.init_state(), events)

    for a, b in zip(jax.tree_util.tree_leaves(sh_out), jax.tree_util.tree_leaves(b_out)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    # [K, T, R, W] decode + oracle parity per lane.
    traces = decode_batch(sharded, sh_out)
    ts = np.asarray(events.ts)
    for k in range(K):
        expected = oracle_canon(pattern, [int(c) for c in codes[k]], ts[k])
        assert traces[k] == expected, f"lane {k} diverged"

    stats = sharded.stats(sh_state)
    for name in (
        "run_drops",
        "ver_overflows",
        "slab_full_drops",
        "slab_pred_drops",
        "slab_missing",
        "slab_trunc",
    ):
        assert stats[name] == 0, (name, stats)
    assert stats["alive_runs"] >= K  # at least each lane's seed run


def test_sharded_state_is_actually_sharded():
    K = 8
    mesh = key_mesh(jax.devices()[:8])
    sharded = ShardedMatcher(sc.strict3(), K, mesh, sc.default_config())
    state = sharded.init_state()
    sharding = state.alive.sharding
    assert len(sharding.device_set) == 8
    # One lane per device: the addressable shard of each leaf has lead dim 1.
    shard = state.alive.addressable_shards[0]
    assert shard.data.shape[0] == K // 8


def test_sharded_step_single_event():
    """One sharded step (not scan) — the path dryrun_multichip exercises."""
    K = 8
    mesh = key_mesh(jax.devices()[:8])
    cfg = sc.default_config()
    sharded = ShardedMatcher(sc.strict3(), K, mesh, cfg)
    ev = EventBatch(
        key=jnp.arange(K, dtype=jnp.int32),
        value=jnp.zeros((K,), jnp.int32),  # all 'A' -> begin consumes
        ts=jnp.full((K,), 1000, jnp.int32),
        off=jnp.zeros((K,), jnp.int32),
        valid=jnp.ones((K,), bool),
    )
    state, out = sharded.step(
        sharded.init_state(), sharded.shard_events(ev)
    )
    assert int(jnp.sum(out.count)) == 0  # no match after one event
    stats = sharded.stats(state)
    assert stats["alive_runs"] == 2 * K  # seed + advanced run per lane
