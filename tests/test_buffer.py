"""Shared versioned buffer goldens, ported from the reference
``nfa/buffer/SharedVersionedBufferTest.java:28-68``."""

import pytest

from kafkastreams_cep_tpu import DeweyVersion, Event
from kafkastreams_cep_tpu.compiler.stages import Stage, StageType
from kafkastreams_cep_tpu.nfa.buffer import SharedVersionedBuffer

EV1 = Event("k1", "v1", 1000000001, "topic-test", 0, 0)
EV2 = Event("k2", "v2", 1000000002, "topic-test", 0, 1)
EV3 = Event("k3", "v3", 1000000003, "topic-test", 0, 2)
EV4 = Event("k4", "v4", 1000000004, "topic-test", 0, 3)
EV5 = Event("k5", "v5", 1000000005, "topic-test", 0, 4)

FIRST = Stage("first", StageType.BEGIN)
SECOND = Stage("second", StageType.NORMAL)
LATEST = Stage("latest", StageType.FINAL)


def test_extract_patterns_with_one_run():
    buffer = SharedVersionedBuffer()
    buffer.put_first(FIRST, EV1, DeweyVersion("1"))
    buffer.put(SECOND, EV2, FIRST, EV1, DeweyVersion("1.0"))
    buffer.put(LATEST, EV3, SECOND, EV2, DeweyVersion("1.0.0"))

    sequence = buffer.get(LATEST, EV3, DeweyVersion("1.0.0"))
    assert sequence.size() == 3
    assert sequence.get("latest") == [EV3]
    assert sequence.get("second") == [EV2]
    assert sequence.get("first") == [EV1]


def test_extract_patterns_with_branching_run():
    buffer = SharedVersionedBuffer()
    buffer.put_first(FIRST, EV1, DeweyVersion("1"))
    buffer.put(SECOND, EV2, FIRST, EV1, DeweyVersion("1.0"))
    buffer.put(LATEST, EV3, SECOND, EV2, DeweyVersion("1.0.0"))

    buffer.put(SECOND, EV3, SECOND, EV2, DeweyVersion("1.1"))
    buffer.put(SECOND, EV4, SECOND, EV3, DeweyVersion("1.1"))
    buffer.put(LATEST, EV5, SECOND, EV4, DeweyVersion("1.1.0"))

    sequence1 = buffer.get(LATEST, EV3, DeweyVersion("1.0.0"))
    assert sequence1.size() == 3
    assert sequence1.get("latest") == [EV3]
    assert sequence1.get("second") == [EV2]
    assert sequence1.get("first") == [EV1]

    sequence2 = buffer.get(LATEST, EV5, DeweyVersion("1.1.0"))
    assert sequence2.size() == 5
    assert len(sequence2.get("latest")) == 1
    assert len(sequence2.get("second")) == 3
    assert len(sequence2.get("first")) == 1


def test_put_with_missing_predecessor_is_a_hard_error():
    # KVSharedVersionedBuffer.java:86-89.
    buffer = SharedVersionedBuffer()
    with pytest.raises(RuntimeError):
        buffer.put(SECOND, EV2, FIRST, EV1, DeweyVersion("1.0"))


def test_remove_garbage_collects_unshared_path():
    buffer = SharedVersionedBuffer()
    buffer.put_first(FIRST, EV1, DeweyVersion("1"))
    buffer.put(SECOND, EV2, FIRST, EV1, DeweyVersion("1.0"))
    buffer.put(LATEST, EV3, SECOND, EV2, DeweyVersion("1.0.0"))

    sequence = buffer.remove(LATEST, EV3, DeweyVersion("1.0.0"))
    assert sequence.size() == 3
    assert len(buffer) == 0


def test_branch_protects_shared_prefix_from_removal():
    buffer = SharedVersionedBuffer()
    buffer.put_first(FIRST, EV1, DeweyVersion("1"))
    buffer.put(SECOND, EV2, FIRST, EV1, DeweyVersion("1.0"))
    # A sibling run branches off the shared prefix ev1<-ev2.
    buffer.branch(SECOND, EV2, DeweyVersion("1.0"))
    buffer.put(LATEST, EV3, SECOND, EV2, DeweyVersion("1.0.0"))

    buffer.remove(LATEST, EV3, DeweyVersion("1.0.0"))
    # The shared prefix survives for the sibling.
    assert buffer.get(SECOND, EV2, DeweyVersion("1.1")).size() == 2


def test_combinators_handle_plain_int_predicates():
    from kafkastreams_cep_tpu import and_, not_, or_

    int_true = lambda k, v, ts, st: 1
    int_false = lambda k, v, ts, st: 0
    args = (None, None, 0, None)
    assert not_(int_true)(*args) is False
    assert not_(int_false)(*args) is True
    assert and_(int_true, int_true)(*args) is True
    assert and_(int_true, int_false)(*args) is False
    assert or_(int_false, int_true)(*args) is True
