"""Graceful-ingestion demo: out-of-order absorption + dead-lettering.

Real streams are disordered in event time and occasionally poisoned per
record; the reference absorbs both at the Kafka layer.  This script runs
the TPU runtime's front-door analog end to end
(``CEP_PLATFORM=cpu python examples/ooo_pipeline.py``):

1. a stock stream whose arrival order is shuffled with bounded timestamp
   skew, fed through the watermark reorder buffer
   (:class:`IngestPolicy` — records held until ``max_seen - grace_ms``
   passes them, released in timestamp order);
2. poisoned records mixed in (wrong schema, impossible timestamps, a
   too-late straggler) — each diverted to the dead-letter queue with a
   typed reason while the rest of its batch proceeds;
3. the loss-counter contract printed at the end: the in-order and
   shuffled runs emit identical matches, and ``late_dropped`` /
   ``quarantined`` / ``reorder_evictions`` tell you exactly what (if
   anything) the guard had to shed.
"""

import os
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

if os.environ.get("CEP_PLATFORM"):
    import jax

    jax.config.update("jax_platforms", os.environ["CEP_PLATFORM"])

import numpy as np

from kafkastreams_cep_tpu.engine import EngineConfig
from kafkastreams_cep_tpu.runtime import CEPProcessor, IngestPolicy, Record

from stock_demo import stock_pattern

GRACE_MS = 40
CONFIG = EngineConfig(
    max_runs=24, slab_entries=48, slab_preds=8, dewey_depth=12, max_walk=12
)


def make_stream(n=400, seed=11):
    """A 4-symbol stock stream with distinct event times."""
    rng = np.random.default_rng(seed)
    symbols = ("AAPL", "GOOG", "MSFT", "AMZN")
    recs = []
    for i in range(n):
        recs.append(
            Record(
                symbols[int(rng.integers(len(symbols)))],
                {
                    "price": int(rng.integers(90, 131)),
                    "volume": int(
                        1100 if rng.random() < 0.02
                        else rng.integers(600, 1000)
                    ),
                },
                2 * i,  # event time, ms
            )
        )
    return recs


def bounded_shuffle(records, skew_ms, seed=3):
    """Shuffle arrival so timestamp inversions stay <= skew_ms."""
    rng = np.random.default_rng(seed)
    key = [r.timestamp + rng.uniform(0, skew_ms) for r in records]
    return [records[i] for i in np.argsort(key, kind="stable")]


def poison(records):
    """Sprinkle in records a real deployment would see."""
    out = list(records)
    out.insert(50, Record("AAPL", {"price": 100}, 101))       # schema
    out.insert(90, Record("AAPL", out[0].value, 10**15))      # time range
    out.insert(130, Record("GOOG", out[0].value, 0))          # too late
    return out


def run(records, label):
    proc = CEPProcessor(
        stock_pattern(), 4, CONFIG, epoch=0, gc_interval=0,
        ingest=IngestPolicy(grace_ms=GRACE_MS),
    )
    matches = []
    for i in range(0, len(records), 40):
        matches += proc.process(records[i:i + 40])
    matches += proc.drain_ingest()  # end of stream: release the buffer
    matches += proc.flush()
    guard = proc._guard
    print(f"\n== {label} ==")
    print(f"matches emitted : {len(matches)}")
    print(f"loss counters   : {guard.loss_counters()}  (all-zero => loss-free)")
    print(f"held at drain   : 0 (drained), watermark {guard.watermark} ms")
    for d in guard.dead_letters:
        print(
            f"dead letter     : reason={d.reason!r} corr={d.corr} "
            f"key={d.record.key!r} ts={d.record.timestamp}"
        )
    return matches


def main():
    stream = make_stream()

    clean = run(stream, "in-order, clean")
    shuffled = run(
        bounded_shuffle(stream, GRACE_MS), f"shuffled (skew <= {GRACE_MS} ms)"
    )

    def canon(matches):
        # Key + per-stage (offset, timestamp) lists: everything about a
        # match except the lane number, which — like a Kafka partition
        # assignment — follows key *arrival* order and is the one thing a
        # shuffle may legitimately permute.
        return [
            (k, {
                st: [(e.offset, e.timestamp) for e in ev]
                for st, ev in s.as_map().items()
            })
            for k, s in matches
        ]

    assert canon(clean) == canon(shuffled), (
        "bounded-skew shuffle must be bit-identical to the in-order run"
    )
    print(
        f"\nbounded-skew shuffle absorbed: {len(shuffled)} matches "
        "bit-identical to the in-order run"
    )

    run(poison(bounded_shuffle(stream, GRACE_MS)), "shuffled + poisoned")
    print(
        "\npoisoned records were quarantined per record with typed "
        "reasons; the batches they rode in still processed"
    )


if __name__ == "__main__":
    main()
