"""High-rate ingestion demo: derived capacity + columnar feed + pipelining.

The round-5 throughput surface, end to end in one script (run
``CEP_PLATFORM=cpu python examples/highrate_pipeline.py``):

1. **Capacity is derived, not guessed** — ``engine.autosize`` probes a
   sample of the real traffic and returns an :class:`EngineConfig` whose
   capacity counters are zero on it (the reference needs no sizing — its
   stores are heap-backed; this is the array-engine analog).
2. **Columns in, not records** — ``process_columns`` ingests ``[N]``
   arrays with vectorized validation; Event objects materialize lazily,
   only when a match (or the GC) touches them, so match-sparse streams
   never pay per-record Python.
3. **The device never waits for the host** — ``pipeline=True`` returns
   batch N-1's matches from call N, overlapping the scan with packing and
   decode; the decode itself pulls a globally compacted match buffer
   (``ops/decode.py``) instead of the raw ``[K, T, R, W]`` grid.

The pattern is the SASE stock query; the stream is spike-calibrated so
~1% of events complete a match (realistic CEP density).
"""

import os
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

if os.environ.get("CEP_PLATFORM"):
    import jax

    jax.config.update("jax_platforms", os.environ["CEP_PLATFORM"])

import numpy as np
import jax.numpy as jnp

from kafkastreams_cep_tpu import Query
from kafkastreams_cep_tpu.engine import EventBatch, autosize
from kafkastreams_cep_tpu.runtime import CEPProcessor


def stock_pattern():
    return (
        Query()
        .select("spike").where(lambda k, v, ts, st: v["volume"] > 1000)
        .fold("avg", lambda k, v, curr: v["price"])
        .then()
        .select("rise").zero_or_more().skip_till_next_match()
        .where(lambda k, v, ts, st: v["price"] > st.get("avg"))
        .fold("avg", lambda k, v, curr: (curr + v["price"]) // 2)
        .fold("volume", lambda k, v, curr: v["volume"])
        .then()
        .select("dip").skip_till_next_match()
        .where(lambda k, v, ts, st: v["volume"] < 0.8 * st.get_or_else("volume", 0))
        .build()
    )


def make_columns(rng, n, keys):
    return (
        rng.integers(0, keys, size=n),
        {
            "price": rng.integers(90, 131, size=n),
            "volume": np.where(
                rng.random(n) < 0.005, 1100, rng.integers(700, 1000, size=n)
            ),
        },
    )


def main():
    K = int(os.environ.get("HIGHRATE_LANES", "128"))
    BATCH = int(os.environ.get("HIGHRATE_BATCH", "2048"))
    N_BATCHES = int(os.environ.get("HIGHRATE_BATCHES", "4"))
    rng = np.random.default_rng(7)

    # 1. Derive the capacity config from a probe of sample traffic.
    skeys, svals = make_columns(rng, 4 * BATCH, K)
    T_s = 4 * BATCH // K
    sample = EventBatch(
        key=jnp.asarray(skeys.reshape(T_s, K).T.astype(np.int32)),
        value={
            n: jnp.asarray(v.reshape(T_s, K).T.astype(np.int32))
            for n, v in svals.items()
        },
        ts=jnp.broadcast_to(jnp.arange(T_s, dtype=jnp.int32)[None], (K, T_s)),
        off=jnp.broadcast_to(jnp.arange(T_s, dtype=jnp.int32)[None], (K, T_s)),
        valid=jnp.ones((K, T_s), bool),
    )
    cfg = autosize(stock_pattern(), sample, sweep_every=64)
    print(f"derived config: {cfg}")

    # 2 + 3. Pipelined processor fed columns.
    proc = CEPProcessor(stock_pattern(), K, cfg, epoch=0, pipeline=True)
    total = 0
    matches = 0
    for b in range(N_BATCHES):
        keys, vals = make_columns(rng, BATCH, K)
        ts = np.int64(b) * BATCH + np.arange(BATCH, dtype=np.int64)
        out = proc.process_columns(keys, vals, ts)
        matches += len(out)
        total += BATCH
    matches += len(proc.flush())

    snap = proc.metrics_snapshot()
    print(
        f"{total} events through {N_BATCHES} pipelined batches: "
        f"{matches} matches, counters zero="
        f"{all(snap[c] == 0 for c in ('run_drops', 'slab_full_drops', 'slab_pred_drops', 'slab_trunc'))}, "
        f"decode_fallbacks={snap['decode_fallbacks']}"
    )
    for key, seq in (out or [])[:3]:
        print(f"  e.g. key {key}: {seq.as_map()}")
    assert matches > 0, "the spike trace must produce matches"
    print("highrate pipeline: OK")


if __name__ == "__main__":
    main()
