"""Operational demo: a supervised multi-query CEP pipeline with durable
crash recovery.

Everything the reference delegates to Kafka Streams, end to end in one
script (run ``CEP_PLATFORM=cpu python examples/resilient_pipeline.py``):

1. two queries over one stock stream (the NFA-bank shape — one processor
   per query, like wiring two ``CEPProcessor`` instances onto one topic);
2. each wrapped in a :class:`Supervisor` with periodic checkpoints and a
   durable CRC-framed record journal (C++ write path when available);
3. a simulated hard process crash mid-stream, recovered with
   ``Supervisor.resume`` — state restored from snapshot + journal replay,
   then the stream continues with no lost or duplicated matches.
"""

import os
import sys
import tempfile
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

if os.environ.get("CEP_PLATFORM"):
    import jax

    jax.config.update("jax_platforms", os.environ["CEP_PLATFORM"])

import numpy as np

from kafkastreams_cep_tpu import Query
from kafkastreams_cep_tpu.engine import EngineConfig
from kafkastreams_cep_tpu.runtime import Record
from kafkastreams_cep_tpu.runtime.supervisor import Supervisor


def spike_query():
    return (
        Query()
        .select("spike").where(lambda k, v, ts, st: v["volume"] > 1000)
        .then()
        .select("drop").skip_till_next_match()
        .where(lambda k, v, ts, st: v["price"] < 100)
        .build()
    )


def rally_query():
    return (
        Query()
        .select("low").where(lambda k, v, ts, st: v["price"] < 95)
        .then()
        .select("high").skip_till_next_match()
        .where(lambda k, v, ts, st: v["price"] > 115)
        .build()
    )


QUERIES = {"spike-then-drop": spike_query, "rally": rally_query}
CFG = EngineConfig(max_runs=16, slab_entries=32, slab_preds=4, dewey_depth=8,
                   max_walk=8)


def make_supervisors(workdir, resume=False):
    sups = {}
    for name, q in QUERIES.items():
        paths = dict(
            checkpoint_path=os.path.join(workdir, f"{name}.ckpt"),
            journal_path=os.path.join(workdir, f"{name}.jnl"),
        )
        if resume:
            sups[name] = Supervisor.resume(
                q(), num_lanes=4, config=CFG, checkpoint_every=4, **paths
            )
        else:
            sups[name] = Supervisor(
                q(), num_lanes=4, config=CFG, checkpoint_every=4, **paths
            )
    return sups


def batches(rng, n_batches, start=0):
    keys = ["AAPL", "MSFT", "GOOG", "AMZN"]
    for b in range(n_batches):
        yield [
            Record(
                keys[int(rng.integers(0, len(keys)))],
                {
                    "price": int(rng.integers(85, 125)),
                    "volume": int(rng.integers(800, 1200)),
                },
                1_000 + (start + b) * 10 + i,
            )
            for i in range(8)
        ]


def main():
    workdir = tempfile.mkdtemp(prefix="cep_pipeline_")
    rng = np.random.default_rng(7)
    sups = make_supervisors(workdir)

    emitted = []
    for i, batch in enumerate(batches(rng, 10)):
        for name, sup in sups.items():
            for key, seq in sup.process(batch):
                emitted.append((name, key, sorted(seq.as_map().items())))
    print(f"phase 1: {len(emitted)} matches from 10 batches")
    for name, sup in sups.items():
        h = sup.health()
        print(f"  {name}: healthy={h.healthy} "
              f"metrics={sup.metrics_snapshot()['matches_out']} matches")

    # --- simulated hard crash: all in-process state is dropped -------------
    del sups
    print("crash! resuming from checkpoints + journals ...")
    sups = make_supervisors(workdir, resume=True)

    more = []
    for batch in batches(rng, 5, start=10):
        for name, sup in sups.items():
            for key, seq in sup.process(batch):
                more.append((name, key, sorted(seq.as_map().items())))
    print(f"phase 2 (post-recovery): {len(more)} further matches")
    for name, sup in sups.items():
        print(f"  {name}: recoveries={sup.recoveries}, "
              f"checkpoints={sup.checkpoints}")
    print("OK")


if __name__ == "__main__":
    main()
