"""The SASE stock demo, end-to-end through the TPU runtime.

Reproduces ``demo/CEPStockKStreamsDemo.java:25-103`` — the paper's stock
query over the 8-event trace documented at ``/root/reference/README.md:
69-97`` — and prints the same 4 JSON match lines, byte for byte.

Run: ``python examples/stock_demo.py`` (add ``CEP_PLATFORM=cpu`` to skip
the TPU compile wait; the environment's site hook pins ``JAX_PLATFORMS``,
so that variable alone cannot select the platform here).
"""

import json
import os
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

if os.environ.get("CEP_PLATFORM"):
    import jax

    jax.config.update("jax_platforms", os.environ["CEP_PLATFORM"])

from kafkastreams_cep_tpu import Query
from kafkastreams_cep_tpu.engine import EngineConfig
from kafkastreams_cep_tpu.runtime import CEPProcessor, Record

STOCK_EVENTS = [
    {"name": "e1", "price": 100, "volume": 1010},
    {"name": "e2", "price": 120, "volume": 990},
    {"name": "e3", "price": 120, "volume": 1005},
    {"name": "e4", "price": 121, "volume": 999},
    {"name": "e5", "price": 120, "volume": 999},
    {"name": "e6", "price": 125, "volume": 750},
    {"name": "e7", "price": 120, "volume": 950},
    {"name": "e8", "price": 120, "volume": 700},
]


def stock_pattern():
    """The demo query (``CEPStockKStreamsDemo.java:37-53``)."""
    return (
        Query()
        .select()
        .where(lambda k, v, ts, st: v["volume"] > 1000)
        .fold("avg", lambda k, v, curr: v["price"])
        .then()
        .select()
        .zero_or_more()
        .skip_till_next_match()
        .where(lambda k, v, ts, st: v["price"] > st.get("avg"))
        .fold("avg", lambda k, v, curr: (curr + v["price"]) // 2)
        .fold("volume", lambda k, v, curr: v["volume"])
        .then()
        .select()
        .skip_till_next_match()
        .where(lambda k, v, ts, st: v["volume"] < 0.8 * st.get_or_else("volume", 0))
        .within(1, "h")
        .build()
    )


def format_match(seq, name_of) -> str:
    """One match -> the demo's JSON line: stages first->last, events in
    arrival order (the demo reverses the backward-walk order,
    ``CEPStockKStreamsDemo.java:60-69``)."""
    obj = {}
    for stage, events in reversed(list(seq.as_map().items())):
        obj[stage] = [name_of[e.offset] for e in reversed(events)]
    return json.dumps(obj, separators=(",", ":"))


def make_processor() -> CEPProcessor:
    """The demo's processor: 1 lane, capacity sized for the 8-event trace."""
    return CEPProcessor(
        stock_pattern(),
        num_lanes=1,
        config=EngineConfig(
            max_runs=32, slab_entries=64, slab_preds=8, dewey_depth=16,
            max_walk=16,
        ),
        topic="StockEvents",
    )


def run(processor=None):
    """Feed the trace; return the JSON lines (shared with the test)."""
    proc = processor or make_processor()
    name_of = {i: ev["name"] for i, ev in enumerate(STOCK_EVENTS)}
    records = [
        Record("stocks", {"price": ev["price"], "volume": ev["volume"]}, 1000 + i)
        for i, ev in enumerate(STOCK_EVENTS)
    ]
    lines = []
    for key, seq in proc.process(records):
        lines.append(format_match(seq, name_of))
    counters = proc.counters()
    assert all(v == 0 for v in counters.values()), counters
    return lines


EXPECTED = [
    '{"0":["e1"],"1":["e2","e3","e4","e5"],"2":["e6"]}',
    '{"0":["e3"],"1":["e4"],"2":["e6"]}',
    '{"0":["e1"],"1":["e2","e3","e4","e5","e6","e7"],"2":["e8"]}',
    '{"0":["e3"],"1":["e4","e6"],"2":["e8"]}',
]


def run_stdin():
    """Console-producer mode: JSON lines ``{"name","price","volume"}`` on
    stdin (the README's input format, README.md:72-81), match JSON lines on
    stdout — the full Kafka topic->topic demo loop without a broker.

    Parsing goes through the native C++ fast path
    (``native.parse_json_lines``) in micro-batches, with the full JSON
    serde as the per-line fallback — the production ingest shape.
    """
    from kafkastreams_cep_tpu import native
    from kafkastreams_cep_tpu.utils.serde import json_serde

    serde = json_serde()
    proc = make_processor()
    name_of = {}
    i = 0
    chunk: list = []

    def flush_chunk():
        nonlocal i
        if not chunk:
            return
        text = "\n".join(chunk).encode()
        values, keys, ok = native.parse_json_lines(
            text, ["price", "volume"], key_field="name"
        )
        records = []
        for j, raw in enumerate(chunk):
            if ok[j]:
                name, price, volume = keys[j], values[j, 0], values[j, 1]
            else:  # fast path rejected the line — full JSON fallback
                ev = serde.deserialize(raw.encode())
                name, price, volume = ev["name"], ev["price"], ev["volume"]
            name_of[i] = name
            # Preserve the JSON number type: integral -> int (the demo's
            # schema), fractional -> float.
            price = int(price) if float(price).is_integer() else float(price)
            volume = (
                int(volume) if float(volume).is_integer() else float(volume)
            )
            records.append(
                Record("stocks", {"price": price, "volume": volume}, 1000 + i)
            )
            i += 1
        for _, seq in proc.process(records):
            print(format_match(seq, name_of), flush=True)
        chunk.clear()

    # Interactive console producers need per-line matches; piped input
    # micro-batches for throughput.
    batch_size = 1 if sys.stdin.isatty() else 64
    for raw in sys.stdin:
        raw = raw.strip()
        if not raw:
            continue
        chunk.append(raw)
        if len(chunk) >= batch_size:
            flush_chunk()
    flush_chunk()


if __name__ == "__main__":
    if "--stdin" in sys.argv:
        run_stdin()
        sys.exit(0)
    lines = run()
    for line in lines:
        print(line)
    ok = lines == EXPECTED
    print("README parity:", "OK" if ok else "MISMATCH", file=sys.stderr)
    sys.exit(0 if ok else 1)
