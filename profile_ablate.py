"""Ablation profile of the headline engine step — where do the ~40ms go?

Round 3's per-phase standalone bench (``profile_phases.py``) measured the
slab kernels out of context (0.7 ms of a ~40 ms step) but could not see the
phases *under real load inside the real scan* (data-dependent while-loop trip
counts, fusion effects).  This tool measures the real thing by subtraction:
it monkeypatches the batched slab kernels with no-ops and times the full
headline scan at each cumulative stage:

  A  chain+compaction only (all slab kernels no-op)
  B  A + puts_batched
  C  B + branch_batched
  D  C + walks_batched            == the shipped engine

Differences D-C, C-B, B-A attribute wall-clock to each phase.  A and D are
exact end-point measurements (A = no slab at all, D = the shipped engine), so
the slab total D-A is exact.  The B/C interior split is approximate: with
walks ablated nothing is ever removed from the slab, so it saturates within a
few steps and the puts/branch phases in B/C run against fuller-than-real
state (puts against a full slab do comparable match/alloc work but drop the
writes; the skew direction is unclear, and the affected deltas are <6% of
the step).  Run on the real chip.

Usage: python profile_ablate.py  [K] [T]
"""

import os
import sys
import time

import jax

jax.config.update(
    "jax_compilation_cache_dir",
    os.path.join(os.path.expanduser("~"), ".cache", "cep_tpu_bench_cache"),
)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)

import jax.numpy as jnp
import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(
    0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "examples")
)

import stock_demo
from kafkastreams_cep_tpu.engine import EngineConfig, EventBatch
from kafkastreams_cep_tpu.ops import slab as slab_mod
from kafkastreams_cep_tpu.parallel import BatchMatcher

REAL = {
    "puts": slab_mod.puts_batched,
    "branch": slab_mod.branch_batched,
    "walks": slab_mod.walks_batched,
}


def noop_puts(slab, ops, off):
    return slab


def noop_branch(slab, en, stage, off, ver, vlen, max_walk):
    return slab


def noop_walks(slab, en, stage, off, ver, vlen, is_remove, want_out,
               max_walk, collect=True):
    P = jnp.asarray(stage).shape[0]
    i32 = jnp.int32
    return (
        slab,
        jnp.full((P, max_walk), -1, i32),
        jnp.full((P, max_walk), -1, i32),
        jnp.zeros((P,), i32),
    )


def timed_scan(K, T, reps, label):
    cfg = EngineConfig(
        max_runs=24, slab_entries=48, slab_preds=8, dewey_depth=12,
        max_walk=12,
    )
    batch = BatchMatcher(stock_demo.stock_pattern(), K, cfg)
    state0 = batch.init_state()
    rng = np.random.default_rng(42)
    prices = rng.integers(90, 131, size=(K, T)).astype(np.int32)
    volumes = rng.integers(600, 1101, size=(K, T)).astype(np.int32)
    events = EventBatch(
        key=jnp.broadcast_to(jnp.arange(K, dtype=jnp.int32)[:, None], (K, T)),
        value={"price": jnp.asarray(prices), "volume": jnp.asarray(volumes)},
        ts=jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32)[None, :] * 2, (K, T)),
        off=jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32)[None, :], (K, T)),
        valid=jnp.ones((K, T), bool),
    )
    t0 = time.perf_counter()
    state, out = batch.scan(state0, events)
    jax.block_until_ready(out.count)
    compile_s = time.perf_counter() - t0
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        state, out = batch.scan(state0, events)
        jax.block_until_ready(out.count)
        times.append(time.perf_counter() - t0)
    best = min(times)
    print(
        f"{label:28s} compile {compile_s:6.1f}s  best {best * 1e3:8.1f} ms  "
        f"({K * T / best / 1e3:8.0f}K ev/s)  reps {['%.0f' % (t * 1e3) for t in times]}",
        file=sys.stderr, flush=True,
    )
    return best


VARIANTS = {
    "A": ("A chain+compact only", {"puts": noop_puts, "branch": noop_branch,
                                   "walks": noop_walks}),
    "B": ("B +puts", {"puts": "real", "branch": noop_branch,
                      "walks": noop_walks}),
    "C": ("C +puts+branch", {"puts": "real", "branch": "real",
                             "walks": noop_walks}),
    "D": ("D full (shipped)", {"puts": "real", "branch": "real",
                               "walks": "real"}),
}


def run_one(which, K, T, reps):
    label, patch = VARIANTS[which]
    for k, v in patch.items():
        setattr(slab_mod, k + "_batched", REAL[k] if v == "real" else v)
    best = timed_scan(K, T, reps, label)
    print(f"RESULT {which} {best!r}", flush=True)


def main():
    K = int(sys.argv[1]) if len(sys.argv) > 1 else 4096
    T = int(sys.argv[2]) if len(sys.argv) > 2 else 256
    reps = int(os.environ.get("CEP_PROFILE_REPS", "3"))

    which = os.environ.get("CEP_ABLATE")
    if which:
        run_one(which, K, T, reps)
        return

    # Each variant runs in its own process: four matchers' states plus four
    # compiled executables do not fit HBM together.
    import subprocess

    results = {}
    for v in "ABCD":
        env = dict(os.environ, CEP_ABLATE=v)
        out = subprocess.run(
            [sys.executable, os.path.abspath(__file__), str(K), str(T)],
            env=env, capture_output=True, text=True,
        )
        for line in out.stderr.splitlines():
            if "WARNING" not in line:
                print(line, file=sys.stderr)
        for line in out.stdout.splitlines():
            if line.startswith("RESULT"):
                _, vv, t = line.split()
                results[vv] = float(t)
    if len(results) < 4:
        print(f"incomplete: {results}")
        return

    a, b, c, d = results["A"], results["B"], results["C"], results["D"]
    per_step = lambda t: t / T * 1e3
    print(f"\n== ablation K={K} T={T} (ms/step of {per_step(d):.2f} total) ==")
    print(f"chain+preds+compaction : {per_step(a):6.2f} ms/step ({a/d*100:5.1f}%)")
    print(f"puts_batched           : {per_step(b - a):6.2f} ms/step ({(b-a)/d*100:5.1f}%)")
    print(f"branch-overflow walks  : {per_step(c - b):6.2f} ms/step ({(c-b)/d*100:5.1f}%)")
    print(f"walks_batched          : {per_step(d - c):6.2f} ms/step ({(d-c)/d*100:5.1f}%)")


if __name__ == "__main__":
    main()
