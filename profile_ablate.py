"""Thin wrapper — the profiler moved into the package CLI.

``python profile_ablate.py [K] [T]`` ≡ ``python -m
kafkastreams_cep_tpu.profile ablate --k K --t T`` (in-context ablation of
the headline step: chain → +puts → +branch → +walks, one subprocess per
variant; see the package docstring for the methodology caveats).
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from kafkastreams_cep_tpu.profile import main


def _argv():
    out = ["ablate"]
    pos = [a for a in sys.argv[1:] if not a.startswith("-")]
    flags = [a for a in sys.argv[1:] if a.startswith("-")]
    if len(pos) >= 1:
        out += ["--k", pos[0]]
    if len(pos) >= 2:
        out += ["--t", pos[1]]
    reps = os.environ.get("CEP_PROFILE_REPS")
    if reps:
        out += ["--reps", reps]
    return out + flags


if __name__ == "__main__":
    sys.exit(main(_argv()))
