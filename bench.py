"""Benchmark harness: events/sec/chip on the SASE stock pattern.

Prints ONE JSON line to stdout:
``{"metric": ..., "value": N, "unit": "events/s", "vs_baseline": N}``.

* **Headline config** (BASELINE.json configs[0]/[2] hybrid): the stock query
  over ``K`` vmapped key lanes × ``T`` scanned events per lane on one chip —
  the production dispatch shape (``parallel/batch.py``).
* **Parity gate**: before timing, the 8-event demo trace must reproduce the
  reference README's 4 match sequences exactly (README.md:93-96) through
  the same engine; a parity failure aborts the bench.
* **vs_baseline**: the reference publishes no numbers (BASELINE.md), so the
  ratio is measured against this repo's host oracle (``nfa/oracle.py``) — a
  faithful single-event-loop reimplementation of the reference engine
  (``NFA.java:94-289``) whose store-bound Java original is in the same
  throughput class (BASELINE.md "derived cost notes").

Environment knobs: ``CEP_BENCH_K`` (lanes, default 4096), ``CEP_BENCH_T``
(events/lane/scan, default 256), ``CEP_BENCH_REPS`` (timed scans, default
2), ``CEP_BENCH_ORACLE_N`` (oracle-timed events, default 1000 — the
oracle's unbounded state makes its per-event cost grow),
``CEP_BENCH_STENCIL_N`` / ``CEP_BENCH_STENCIL_INNER`` (strict-SEQ stencil
events and in-dispatch repeats), ``CEP_BENCH_EXTRAS`` /
``CEP_BENCH_BUDGET_S`` / ``CEP_BENCH_{KLEENE,BANK,SHARD}_*`` (configs 2-4),
``CEP_PLATFORM`` (force a JAX platform, e.g. ``cpu``).

All diagnostics go to stderr; stdout carries only the JSON line.
"""

import json
import os
import sys
import time

if os.environ.get("CEP_PLATFORM"):
    import jax

    jax.config.update("jax_platforms", os.environ["CEP_PLATFORM"])

import jax

# Persistent compilation cache: compiles through the device tunnel cost
# 25-100s each; cached executables bring repeat runs down to seconds.
jax.config.update(
    "jax_compilation_cache_dir",
    os.environ.get(
        "CEP_BENCH_CACHE_DIR",
        os.path.join(
            os.environ.get("XDG_CACHE_HOME")
            or os.path.join(os.path.expanduser("~"), ".cache"),
            "cep_tpu_bench_cache",
        ),
    ),
)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)

import jax.numpy as jnp
import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "examples"))

import stock_demo
from kafkastreams_cep_tpu import OracleNFA, Query
from kafkastreams_cep_tpu.engine import (
    EngineConfig,
    EventBatch,
    StencilMatcher,
)
from kafkastreams_cep_tpu.parallel import BatchMatcher


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def parity_gate():
    """The engine must reproduce the README's 4 stock matches exactly."""
    lines = stock_demo.run()
    if lines != stock_demo.EXPECTED:
        log(f"PARITY FAILURE: {lines}")
        raise SystemExit(2)
    log("parity gate: README 4-sequence output reproduced exactly")


def make_batch(rng, K, T):
    prices = rng.integers(90, 131, size=(K, T)).astype(np.int32)
    volumes = rng.integers(600, 1101, size=(K, T)).astype(np.int32)
    return EventBatch(
        key=jnp.broadcast_to(jnp.arange(K, dtype=jnp.int32)[:, None], (K, T)),
        value={"price": jnp.asarray(prices), "volume": jnp.asarray(volumes)},
        ts=jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32)[None, :] * 2, (K, T)),
        off=jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32)[None, :], (K, T)),
        valid=jnp.ones((K, T), bool),
    )


def bench_engine(K, T, reps):
    cfg = EngineConfig(
        max_runs=24, slab_entries=48, slab_preds=8, dewey_depth=12, max_walk=12
    )
    batch = BatchMatcher(stock_demo.stock_pattern(), K, cfg)
    state0 = batch.init_state()
    rng = np.random.default_rng(42)
    events = make_batch(rng, K, T)

    t0 = time.perf_counter()
    state, out = batch.scan(state0, events)
    jax.block_until_ready(out.count)
    compile_s = time.perf_counter() - t0
    log(f"engine: compile+first scan {compile_s:.1f}s on {jax.devices()[0]}")

    best = float("inf")
    for i in range(reps):
        t0 = time.perf_counter()
        state, out = batch.scan(state0, events)
        jax.block_until_ready(out.count)
        dt = time.perf_counter() - t0
        best = min(best, dt)
        log(f"engine: scan {i + 1}/{reps}: {dt * 1e3:.1f} ms "
            f"({K * T / dt / 1e6:.2f}M ev/s)")
    counters = batch.counters(state)
    log(f"engine: counters {counters} (capacity drops are policy, counted)")
    matches = int(jnp.sum(out.count > 0))
    log(f"engine: {matches} run-slots completed matches in final scan")
    return K * T / best


def bench_stencil(total_events, reps):
    """BASELINE.json config 2: strict-contiguity 3-stage SEQ over ~1M
    synthetic StockEvents (stencil fast path; stderr-reported secondary)."""
    pattern = (
        Query()
        .select("rise").where(lambda k, v, ts, st: v["price"] > 110)
        .then()
        .select("surge").where(lambda k, v, ts, st: v["volume"] > 900)
        .then()
        .select("drop").where(lambda k, v, ts, st: v["price"] < 105)
        .build()
    )
    K = 128
    T = max(total_events // K, 1)
    m = StencilMatcher(pattern, K)
    rng = np.random.default_rng(7)
    events = make_batch(rng, K, T)
    # Amortize inside ONE dispatch: per-dispatch latency through the device
    # tunnel (~100ms) otherwise dominates and understates the device rate
    # by an order of magnitude.
    inner = max(int(os.environ.get("CEP_BENCH_STENCIL_INNER", "10")), 1)

    @jax.jit
    def many(state):
        def body(s, _):
            s2, out = m.scan(s, events)
            return s2, jnp.sum(out.hit)
        return jax.lax.scan(body, state, None, length=inner)

    t0 = time.perf_counter()
    _, hits = many(m.init_state())
    jax.block_until_ready(hits)
    log(f"stencil: compile+first run {time.perf_counter() - t0:.1f}s")
    best = float("inf")
    for i in range(reps):
        t0 = time.perf_counter()
        _, hits = many(m.init_state())
        jax.block_until_ready(hits)
        best = min(best, time.perf_counter() - t0)
    n_hits = int(hits[0])
    total = K * T * inner
    log(
        f"stencil (strict 3-stage SEQ, {K}x{T} events x{inner} in-dispatch): "
        f"{total / best / 1e6:.1f}M ev/s, {n_hits} matches/scan"
    )
    return total / best


def bench_kleene(K, T, reps):
    """BASELINE.json config 2: skip_till_any_match + oneOrMore Kleene
    closure, vmapped over ~10K key lanes (stderr-reported secondary)."""
    pattern = (
        Query()
        .select("start").where(lambda k, v, ts, st: v["price"] > 120)
        .then()
        .select("run").one_or_more().skip_till_any_match()
        .where(lambda k, v, ts, st: v["volume"] > 900)
        .then()
        .select("end").where(lambda k, v, ts, st: v["price"] < 100)
        .build()
    )
    rng = np.random.default_rng(11)
    prices = rng.integers(80, 141, size=(K, T)).astype(np.int32)
    volumes = rng.integers(600, 1101, size=(K, T)).astype(np.int32)
    events = EventBatch(
        key=jnp.broadcast_to(jnp.arange(K, dtype=jnp.int32)[:, None], (K, T)),
        value={"price": jnp.asarray(prices), "volume": jnp.asarray(volumes)},
        ts=jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32)[None, :] * 3, (K, T)),
        off=jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32)[None, :], (K, T)),
        valid=jnp.ones((K, T), bool),
    )
    # Two capacity points make the throughput/fidelity tradeoff explicit:
    # the small shapes run ~2x faster but shed branches under this
    # branch-dense trace (counted); the large shapes keep drops near zero.
    rate = 0.0
    for label, cfg in (
        ("small", EngineConfig(max_runs=16, slab_entries=32, slab_preds=6,
                               dewey_depth=10, max_walk=10)),
        ("large", EngineConfig(max_runs=24, slab_entries=64, slab_preds=8,
                               dewey_depth=12, max_walk=12)),
    ):
        batch = BatchMatcher(pattern, K, cfg)
        state0 = batch.init_state()
        t0 = time.perf_counter()
        state, out = batch.scan(state0, events)
        jax.block_until_ready(out.count)
        log(f"kleene[{label}]: compile+first scan {time.perf_counter() - t0:.1f}s")
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            state, out = batch.scan(state0, events)
            jax.block_until_ready(out.count)
            best = min(best, time.perf_counter() - t0)
        matches = int(jnp.sum(out.count > 0))
        log(
            f"kleene[{label}] (skip_till_any + oneOrMore, {K} lanes x {T}): "
            f"{K * T / best / 1e3:.0f}K ev/s, {matches} match slots, "
            f"counters {batch.counters(state)}"
        )
        rate = max(rate, K * T / best)
    return rate


def bench_bank(n_queries, K, T, reps):
    """BASELINE.json config 3: multi-pattern NFA bank over ~100K total key
    lanes — N independent queries, each vmapped over K lanes (stderr)."""
    def q(i):
        lo, hi = 95 + i * 5, 120 - i * 3
        return (
            Query()
            .select("a").where(lambda k, v, ts, st, lo=lo: v["price"] < lo)
            .then()
            .select("b").skip_till_next_match()
            .where(lambda k, v, ts, st, hi=hi: v["price"] > hi)
            .build()
        )

    cfg = EngineConfig(
        max_runs=8, slab_entries=16, slab_preds=4, dewey_depth=6, max_walk=6
    )
    rng = np.random.default_rng(13)
    prices = rng.integers(80, 141, size=(K, T)).astype(np.int32)
    events = EventBatch(
        key=jnp.broadcast_to(jnp.arange(K, dtype=jnp.int32)[:, None], (K, T)),
        value={"price": jnp.asarray(prices)},
        ts=jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32)[None, :], (K, T)),
        off=jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32)[None, :], (K, T)),
        valid=jnp.ones((K, T), bool),
    )
    matchers = [BatchMatcher(q(i), K, cfg) for i in range(n_queries)]
    states = [m.init_state() for m in matchers]
    outs = [m.scan(s, events) for m, s in zip(matchers, states)]
    jax.block_until_ready([o[1].count for o in outs])
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        outs = [m.scan(s, events) for m, s in zip(matchers, states)]
        jax.block_until_ready([o[1].count for o in outs])
        best = min(best, time.perf_counter() - t0)
    total = n_queries * K * T
    log(
        f"bank ({n_queries} queries x {K} lanes = {n_queries * K} "
        f"query-lanes, {T} events): {total / best / 1e3:.0f}K query-events/s"
    )
    return total / best


def bench_sharded_folds(K, T, reps):
    """BASELINE.json config 4: WITHIN window + fold(avg,volume) predicates
    over ~1M key lanes, sharded over the available mesh (one chip here;
    the sharding layer is the same shard_map program that lays lanes over
    a v5e-8 — stderr-reported secondary)."""
    from kafkastreams_cep_tpu.parallel import ShardedMatcher, key_mesh

    cfg = EngineConfig(
        max_runs=8, slab_entries=16, slab_preds=4, dewey_depth=8, max_walk=8
    )
    mesh = key_mesh()
    m = ShardedMatcher(stock_demo.stock_pattern(), K, mesh, cfg)
    state0 = m.init_state()
    rng = np.random.default_rng(17)
    prices = rng.integers(90, 131, size=(K, T)).astype(np.int32)
    volumes = rng.integers(600, 1101, size=(K, T)).astype(np.int32)
    events = m.shard_events(EventBatch(
        key=jnp.broadcast_to(jnp.arange(K, dtype=jnp.int32)[:, None], (K, T)),
        value={"price": jnp.asarray(prices), "volume": jnp.asarray(volumes)},
        ts=jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32)[None, :] * 2, (K, T)),
        off=jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32)[None, :], (K, T)),
        valid=jnp.ones((K, T), bool),
    ))
    t0 = time.perf_counter()
    state, out = m.scan(state0, events)
    jax.block_until_ready(out.count)
    log(f"sharded-folds: compile+first scan {time.perf_counter() - t0:.1f}s "
        f"on mesh {mesh.devices.shape}")
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        state, out = m.scan(state0, events)
        jax.block_until_ready(out.count)
        best = min(best, time.perf_counter() - t0)
    from kafkastreams_cep_tpu.utils.metrics import device_memory_stats

    log(
        f"sharded folds+window ({K} lanes x {T} events, "
        f"{mesh.devices.size} device(s)): {K * T / best / 1e3:.0f}K ev/s, "
        f"stats {m.stats(state)}, hbm {device_memory_stats()}"
    )
    return K * T / best


def bench_oracle(n_events):
    rng = np.random.default_rng(42)
    prices = rng.integers(90, 131, size=n_events)
    volumes = rng.integers(600, 1101, size=n_events)
    oracle = OracleNFA.from_pattern(stock_demo.stock_pattern())
    t0 = time.perf_counter()
    n_matches = 0
    early_dt = None
    for i in range(n_events):
        n_matches += len(
            oracle.match(
                None,
                {"price": int(prices[i]), "volume": int(volumes[i])},
                2 * i,
                offset=i,
            )
        )
        if i == 499:
            early_dt = time.perf_counter() - t0
    dt = time.perf_counter() - t0
    early = f", first 500 at {500 / early_dt:.0f} ev/s" if early_dt else ""
    log(
        f"oracle: {n_events} events in {dt:.2f}s ({n_events / dt:.0f} ev/s"
        f"{early}; unbounded state grows per event, like the reference), "
        f"{n_matches} matches"
    )
    return n_events / dt


def main():
    t_start = time.perf_counter()
    K = int(os.environ.get("CEP_BENCH_K", "4096"))
    T = int(os.environ.get("CEP_BENCH_T", "256"))
    reps = int(os.environ.get("CEP_BENCH_REPS", "2"))
    # The oracle is faithful to the reference's unbounded-state design, so
    # its per-event cost GROWS on this match-dense trace (measured: 500
    # events in ~1s, 2000 in ~120s cumulative); 1000 events keeps the
    # comparison honest without dominating bench wall time.
    oracle_n = int(os.environ.get("CEP_BENCH_ORACLE_N", "1000"))

    parity_gate()
    bench_stencil(int(os.environ.get("CEP_BENCH_STENCIL_N", "1048576")), reps)
    engine_evps = bench_engine(K, T, reps)
    oracle_evps = bench_oracle(oracle_n)
    # BASELINE.json configs 2-4, stderr-reported; sized via env knobs so
    # smoke runs stay fast (CEP_BENCH_EXTRAS=0 skips them entirely).  Each
    # extra is skipped once the wall budget is spent — compiles through the
    # device tunnel are slow and the headline JSON must always be printed.
    if os.environ.get("CEP_BENCH_EXTRAS", "1") != "0":
        budget = float(os.environ.get("CEP_BENCH_BUDGET_S", "420"))
        extras = [
            (
                "bank",
                lambda: bench_bank(
                    int(os.environ.get("CEP_BENCH_BANK_N", "2")),
                    int(os.environ.get("CEP_BENCH_BANK_K", "51200")),
                    int(os.environ.get("CEP_BENCH_BANK_T", "64")),
                    max(reps - 1, 1),
                ),
            ),
            (
                "sharded-folds",
                lambda: bench_sharded_folds(
                    int(os.environ.get("CEP_BENCH_SHARD_K", "262144")),
                    int(os.environ.get("CEP_BENCH_SHARD_T", "16")),
                    max(reps - 1, 1),
                ),
            ),
            (
                "kleene",
                lambda: bench_kleene(
                    int(os.environ.get("CEP_BENCH_KLEENE_K", "10240")),
                    int(os.environ.get("CEP_BENCH_KLEENE_T", "64")),
                    max(reps - 1, 1),
                ),
            ),
        ]
        for name, fn in extras:
            if time.perf_counter() - t_start > budget:
                log(f"{name}: skipped (past {budget:.0f}s bench budget)")
                continue
            try:
                fn()
            except Exception as e:  # extras never break the headline line
                log(f"{name} bench failed: {type(e).__name__}: {e}")

    print(
        json.dumps(
            {
                "metric": (
                    "events/sec/chip, SASE stock pattern, "
                    f"{K} key lanes x {T}-event scan, README match parity"
                ),
                "value": round(engine_evps, 1),
                "unit": "events/s",
                "vs_baseline": round(engine_evps / oracle_evps, 2),
            }
        ),
        flush=True,
    )


if __name__ == "__main__":
    main()
