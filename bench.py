"""Benchmark harness: events/sec/chip on the SASE stock pattern.

Prints ONE JSON line to stdout:
``{"metric": ..., "value": N, "unit": "events/s", "vs_baseline": N}``.

* **Headline config** (BASELINE.json configs[0]/[2] hybrid): the stock query
  over ``K`` vmapped key lanes × ``T`` scanned events per lane on one chip —
  the production dispatch shape (``parallel/batch.py``).
* **Parity gate**: before timing, the 8-event demo trace must reproduce the
  reference README's 4 match sequences exactly (README.md:93-96) through
  the same engine; a parity failure aborts the bench.
* **vs_baseline**: the reference publishes no numbers (BASELINE.md), so the
  ratio is measured against this repo's host oracle (``nfa/oracle.py``) — a
  faithful single-event-loop reimplementation of the reference engine
  (``NFA.java:94-289``) whose store-bound Java original is in the same
  throughput class (BASELINE.md "derived cost notes").

Environment knobs: ``CEP_BENCH_K`` (lanes, default 4096), ``CEP_BENCH_T``
(events/lane/scan, default 256), ``CEP_BENCH_REPS`` (timed scans, default
5; min + spread reported), ``CEP_BENCH_ORACLE_N`` (oracle-timed events,
default 1000 — the oracle's unbounded state makes its per-event cost
grow), ``CEP_BENCH_LOSSFREE_K`` / ``_CYCLES`` / ``_PARITY`` (the
zero-counters staircase line; parity replays one lane through the host
oracle, ~2 min), ``CEP_BENCH_STENCIL_N`` / ``CEP_BENCH_STENCIL_INNER``
(strict-SEQ stencil events and in-dispatch repeats), ``CEP_BENCH_EXTRAS``
/ ``CEP_BENCH_BUDGET_S`` / ``CEP_BENCH_{KLEENE,BANK,SHARD}_*`` (configs
2-4), ``CEP_BENCH_HOT_ENTRIES`` (two-tier hot-window headline rerun,
default 16, 0 skips), ``CEP_BENCH_LAZY`` (lazy-extraction A/B on the
headline trace, default 1; ``CEP_BENCH_LAZY_{CHUNK,RING,E}`` set the
drain cadence, handle-ring size, and slab headroom),
``CEP_BENCH_FRONTIER`` ("E:EH,E:EH,…" — the (E, E_hot) frontier sweep,
off by default), ``CEP_BENCH_OOO`` (graceful-ingestion A/B: in-order vs
bounded-skew shuffled arrival through the watermark reorder buffer,
default 1; ``CEP_BENCH_OOO_{K,B,BATCHES,GRACE}`` size it),
``CEP_BENCH_METRICS=1`` (run the headline config
under the telemetry Reporter and print the per-phase p50/p99 block;
``CEP_BENCH_METRICS_{K,T,BATCHES}`` size it), ``CEP_BENCH_TIER``
(compiler-tiering A/B: untiered vs tiered on a strict-prefix-dominated
match-sparse trace, default 1; ``CEP_BENCH_TIER_{K,T,CHUNK,REPS}`` size
it), ``CEP_BENCH_SHARDF`` (shard fault tolerance probes: kill-one-shard
evacuation latency + degraded throughput, and the hot-key rebalance
loss contract, default 1 when >= 2 devices; ``CEP_BENCH_SHARDF_{K,B}``
size them), ``CEP_BENCH_TENANTS`` (multi-tenant bank sweep: N
Zipf-overlapping strict-sequence queries on the shared stencil screen vs
the naive-fused stacked bank, default 1;
``CEP_BENCH_TENANTS_{N,K,T,REPS,POOL,FUSED_MAX}`` size it),
``CEP_BENCH_ADAPT`` (adaptive recompilation: hybrid sweep under the
chunk-gated scan + drift A/B with/without ``AdaptPolicy`` replanning,
default 1; ``CEP_BENCH_ADAPT_{K,T,CHUNK,REPS,DRIFT_B}`` size it),
``CEP_BENCH_TENANT_ISO`` (per-tenant isolation: compliant-tenant
throughput with one quota-limited flooding tenant, shed accounting, and
quarantine-entry latency, default 1;
``CEP_BENCH_TENANT_ISO_{K,B,BATCHES}`` size it), ``CEP_BENCH_LATENCY``
(end-to-end latency attribution: ledger on/off parity + overhead,
per-segment p50/p99, drain-cadence and reorder-grace A/Bs, default 1;
``CEP_BENCH_LATENCY_{K,B,BATCHES,GRACE,DRAIN,RING}`` size it),
``CEP_BENCH_OVERLOAD`` (brownout ladder under flood: goodput with and
without the controller, auditable shed accounting, brownout batch-time
tail, recovery-to-L0, default 1;
``CEP_BENCH_OVERLOAD_{K,B,BATCHES,SUB,DEPTH}`` size it),
``CEP_PLATFORM`` (force a JAX platform, e.g. ``cpu``).

All diagnostics go to stderr; stdout carries only the JSON line.
"""

import json
import os
import sys
import time

if os.environ.get("CEP_PLATFORM"):
    import jax

    jax.config.update("jax_platforms", os.environ["CEP_PLATFORM"])

import jax

# Persistent compilation cache: compiles through the device tunnel cost
# 25-100s each; cached executables bring repeat runs down to seconds.
jax.config.update(
    "jax_compilation_cache_dir",
    os.environ.get(
        "CEP_BENCH_CACHE_DIR",
        os.path.join(
            os.environ.get("XDG_CACHE_HOME")
            or os.path.join(os.path.expanduser("~"), ".cache"),
            "cep_tpu_bench_cache",
        ),
    ),
)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)

import jax.numpy as jnp
import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "examples"))

import stock_demo
from kafkastreams_cep_tpu import OracleNFA, Query
from kafkastreams_cep_tpu.engine import (
    EngineConfig,
    EventBatch,
    StencilMatcher,
    autosize,
)
from kafkastreams_cep_tpu.engine.sizing import capacity_counters
from kafkastreams_cep_tpu.parallel import BatchMatcher


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def parity_gate():
    """The engine must reproduce the README's 4 stock matches exactly."""
    lines = stock_demo.run()
    if lines != stock_demo.EXPECTED:
        log(f"PARITY FAILURE: {lines}")
        raise SystemExit(2)
    log("parity gate: README 4-sequence output reproduced exactly")


def make_batch(rng, K, T):
    prices = rng.integers(90, 131, size=(K, T)).astype(np.int32)
    volumes = rng.integers(600, 1101, size=(K, T)).astype(np.int32)
    return EventBatch(
        key=jnp.broadcast_to(jnp.arange(K, dtype=jnp.int32)[:, None], (K, T)),
        value={"price": jnp.asarray(prices), "volume": jnp.asarray(volumes)},
        ts=jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32)[None, :] * 2, (K, T)),
        off=jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32)[None, :], (K, T)),
        valid=jnp.ones((K, T), bool),
    )


def staircase_trace(K, cycles, cyc_len=24):
    """A calibrated stock-pattern trace whose matching activity is bounded
    per cycle, so a finite engine config is *loss-free* (all six overflow
    counters exactly zero) over the whole stream.

    Decreasing price staircase: cycle c's runs' ``avg`` fold always exceeds
    every later price, so no run takes outside its own cycle (the demo
    fold ``avg=(avg+price)//2`` otherwise converges just below the take
    price and keeps matching forever).  Increasing take-volume staircase:
    cycle c's completion volume is below its own runs' ``0.8*volume``
    threshold but at or above every older cycle's, so lineages complete
    only in their own cycle.  Lane k shifts all prices by +k (comparisons
    are relative, so the match structure is preserved while lane values
    differ).
    """
    assert cycles <= 70
    evs = []
    for c in range(cycles):
        S = 2000 - 20 * c
        P = S + 2
        tv = 100 + 10 * c  # take volume; completion threshold 0.8*tv
        cv = 79 + 8 * c  # completes cycle c's lineages only
        cyc = [(S, 1200), (P, tv), (P, tv), (S - 5, cv)]
        cyc += [(500, 900)] * (cyc_len - len(cyc))
        evs += cyc
    tr = np.array(evs, dtype=np.int32)  # [T, 2]
    T = tr.shape[0]
    prices = tr[None, :, 0] + np.arange(K, dtype=np.int32)[:, None]
    volumes = np.broadcast_to(tr[None, :, 1], (K, T)).copy()
    return EventBatch(
        key=jnp.broadcast_to(jnp.arange(K, dtype=jnp.int32)[:, None], (K, T)),
        value={"price": jnp.asarray(prices), "volume": jnp.asarray(volumes)},
        ts=jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32)[None, :] * 2, (K, T)),
        off=jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32)[None, :], (K, T)),
        valid=jnp.ones((K, T), bool),
    )


def _oracle_lane_matches(prices, volumes):
    """Ground-truth per-event match lists for one lane via the host oracle."""
    from kafkastreams_cep_tpu import OracleNFA

    oracle = OracleNFA.from_pattern(stock_demo.stock_pattern())
    per_event = []
    for t in range(len(prices)):
        ms = oracle.match(
            None,
            {"price": int(prices[t]), "volume": int(volumes[t])},
            2 * t,
            offset=t,
        )
        per_event.append(
            [
                {name: [e.offset for e in evs] for name, evs in m.as_map().items()}
                for m in ms
            ]
        )
    return per_event


def bench_lossfree(K, cycles, reps):
    """Loss-free at scale: the stock pattern on the staircase trace with a
    config sized so ALL six overflow counters are exactly zero over the
    stream, plus sampled-lane exact match parity against the host oracle
    (``KVSharedVersionedBuffer.java:86-89`` — the reference never drops;
    this line demonstrates the engine fast AND match-identical)."""
    events = staircase_trace(K, cycles)
    T = int(events.ts.shape[1])
    # Round-4 hand calibration, now only the autosize seed (and the
    # CEP_BENCH_AUTOSIZE=0 fallback for smoke runs): the shipped config is
    # DERIVED by probing a 128-lane sample of the same trace
    # (engine/sizing.py — the reference needs no sizing, heap-backed
    # stores; this is the array-engine analog).
    seed_cfg = EngineConfig(
        max_runs=48, slab_entries=112, slab_preds=8, dewey_depth=10,
        max_walk=10,
    )
    if os.environ.get("CEP_BENCH_AUTOSIZE", "1") != "0":
        sample = staircase_trace(min(K, 128), cycles)
        cfg = autosize(
            stock_demo.stock_pattern(), sample, start=seed_cfg,
            margin=1.4, sweep_every=T,
        )
        log(f"lossfree: autosized config {cfg}")
    else:
        cfg = seed_cfg
    batch = BatchMatcher(stock_demo.stock_pattern(), K, cfg)
    state0 = batch.init_state()

    t0 = time.perf_counter()
    state, out = batch.scan(state0, events)
    jax.block_until_ready(out.count)
    compile_s = time.perf_counter() - t0
    counters = batch.counters(state)
    lossfree = all(v == 0 for v in counters.values())
    if not lossfree:
        log(f"lossfree: COUNTERS NOT ZERO: {counters}")

    # Exact parity vs the host oracle.  Lane price shifts preserve every
    # comparison, so all K lanes must emit identical match structures: one
    # full-stream oracle lane (the slow part — the oracle's state grows
    # like the reference's) plus a vectorized all-lanes-identical check
    # extends exactness to every lane.  CEP_BENCH_LOSSFREE_PARITY=0 skips
    # the oracle replay for quick runs.
    names = batch.names
    stage_np = np.asarray(out.stage)
    off_np = np.asarray(out.off)
    count_np = np.asarray(out.count)
    prices = np.asarray(events.value["price"])
    volumes = np.asarray(events.value["volume"])
    parity = True
    lanes_identical = bool(
        (stage_np == stage_np[:1]).all()
        and (off_np == off_np[:1]).all()
        and (count_np == count_np[:1]).all()
    )
    if not lanes_identical:
        parity = False
        log("lossfree: PARITY MISMATCH: lanes differ (should be isomorphic)")
    if parity and os.environ.get("CEP_BENCH_LOSSFREE_PARITY", "1") != "0":
        lane = 0
        expected = _oracle_lane_matches(prices[lane], volumes[lane])
        got_all = _decode_lane(out, names, lane)
        for t in range(T):
            got = got_all[t]
            if got != expected[t]:
                parity = False
                log(
                    f"lossfree: PARITY MISMATCH lane {lane} t {t}: "
                    f"engine {got} oracle {expected[t]}"
                )
                break
        if parity:
            log(
                "lossfree: oracle parity exact over the full stream "
                f"(lane 0 replayed; all {K} lanes emit identically)"
            )

    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        state, out = batch.scan(state0, events)
        jax.block_until_ready(out.count)
        times.append(time.perf_counter() - t0)
    best = min(times)
    spread = (max(times) - best) / best * 100 if reps > 1 else 0.0
    log(
        f"lossfree (stock staircase, {K} lanes x {T} events, all counters "
        f"zero={lossfree}): {K * T / best / 1e3:.0f}K ev/s "
        f"(min of {reps}, spread {spread:.0f}%, compile {compile_s:.1f}s)"
    )
    return K * T / best, lossfree, parity


def _decode_lane(out, names, lane):
    """Engine emissions of one lane as per-event lists of name->offsets
    dicts (the oracle's ``as_map`` structure; same decode the loss-free
    parity check uses)."""
    stage_np = np.asarray(out.stage[lane])  # [T, R, W]
    off_np = np.asarray(out.off[lane])
    count_np = np.asarray(out.count[lane])  # [T, R]
    T, R = count_np.shape
    per_event = []
    for t in range(T):
        got = []
        for r in range(R):
            n = int(count_np[t, r])
            if n == 0:
                continue
            m: dict = {}
            for w in range(n):
                m.setdefault(names[int(stage_np[t, r, w])], []).append(
                    int(off_np[t, r, w])
                )
            got.append(m)
        per_event.append(got)
    return per_event


def _freeze(m):
    return tuple(sorted((k, tuple(v)) for k, v in m.items()))


def measure_recall(out, names, prices, volumes, lanes):
    """Match recall/precision vs the host oracle on sampled lanes.

    The reference never drops (``KVSharedVersionedBuffer.java:86-89``);
    the headline config does (counted).  This quantifies the effect in
    match space: recall = fraction of oracle matches the engine emitted,
    precision = fraction of engine emissions the oracle agrees with —
    per-event multiset intersection, so order inside an event is free but
    nothing can be claimed across events."""
    from collections import Counter

    tot_o = tot_e = tot_hit = 0
    for lane in lanes:
        want = _oracle_lane_matches(prices[lane], volumes[lane])
        got = _decode_lane(out, names, lane)
        for t in range(len(want)):
            co = Counter(_freeze(m) for m in want[t])
            ce = Counter(_freeze(m) for m in got[t])
            tot_o += sum(co.values())
            tot_e += sum(ce.values())
            tot_hit += sum((co & ce).values())
    recall = tot_hit / tot_o if tot_o else 1.0
    precision = tot_hit / tot_e if tot_e else 1.0
    return recall, precision, tot_o


def bench_engine(K, T, reps):
    cfg = EngineConfig(
        max_runs=24, slab_entries=48, slab_preds=8, dewey_depth=12, max_walk=12
    )
    batch = BatchMatcher(stock_demo.stock_pattern(), K, cfg)
    state0 = batch.init_state()
    rng = np.random.default_rng(42)
    events = make_batch(rng, K, T)

    t0 = time.perf_counter()
    state, out = batch.scan(state0, events)
    jax.block_until_ready(out.count)
    compile_s = time.perf_counter() - t0
    # Cold/warm labels make round-over-round numbers comparable at a
    # glance (a warm persistent cache swings compile seconds wildly and
    # must never be misread as an engine change).
    cache = "warm-cache" if compile_s < 15 else "cold-cache"
    log(f"engine: compile+first scan {compile_s:.1f}s ({cache}) "
        f"on {jax.devices()[0]}")

    times = []
    for i in range(reps):
        t0 = time.perf_counter()
        state, out = batch.scan(state0, events)
        jax.block_until_ready(out.count)
        dt = time.perf_counter() - t0
        times.append(dt)
        log(f"engine: scan {i + 1}/{reps}: {dt * 1e3:.1f} ms "
            f"({K * T / dt / 1e6:.2f}M ev/s)")
    best = min(times)
    spread = (max(times) - best) / best * 100 if reps > 1 else 0.0
    log(f"engine: best {best * 1e3:.1f} ms of {reps} reps, spread "
        f"{spread:.1f}% over best")
    counters = batch.counters(state)
    log(f"engine: counters {counters} (capacity drops are policy, counted; "
        "the lossfree line below runs with all counters zero)")
    matches = int(jnp.sum(out.count > 0))
    log(f"engine: {matches} run-slots completed matches in final scan")
    # The headline trace is adversarial for loss-free operation: probing it
    # (engine/sizing.py) demands E=192/MP=32/D=48 — past the walk kernel's
    # VMEM budget — because the converging avg fold keeps every lane
    # match-dense for the whole scan (the reference holds the same state
    # heap-side, 37K matches/1000 events on one lane).  So the headline
    # number carries an explicit match recall against the oracle on
    # sampled lanes instead of a counters_zero claim.
    n_lanes = int(os.environ.get("CEP_BENCH_RECALL_LANES", "2"))
    recall = precision = None
    if n_lanes > 0:
        prices = np.asarray(events.value["price"])
        volumes = np.asarray(events.value["volume"])
        lanes = list(range(0, K, max(K // n_lanes, 1)))[:n_lanes]
        t0 = time.perf_counter()  # host-timed (oracle replay + host decode)
        recall, precision, n_oracle = measure_recall(
            out, batch.names, prices, volumes, lanes
        )
        log(
            f"engine: recall {recall:.4f} / precision {precision:.4f} vs "
            f"oracle on {len(lanes)} sampled lanes ({n_oracle} oracle "
            f"matches, {time.perf_counter() - t0:.1f}s)"
        )
        # Recall is a capacity knob, not an engine property: one larger
        # configuration shows the throughput/recall tradeoff on the same
        # trace (CEP_BENCH_RECALL_CURVE=0 skips).  Runs on a 1024-lane
        # slice — the R=64/W=16 match outputs at the full lane count are
        # multi-GB (a full-shape attempt RESOURCE_EXHAUSTED the chip) and
        # the per-event rate + sampled recall don't need more lanes.
        if os.environ.get("CEP_BENCH_RECALL_CURVE", "1") != "0":
            try:
                K2 = min(K, 1024)
                ev2 = jax.tree_util.tree_map(lambda x: x[:K2], events)
                lanes2 = [l for l in lanes if l < K2] or [0]
                big = EngineConfig(
                    max_runs=64, slab_entries=128, slab_preds=8,
                    dewey_depth=16, max_walk=16,
                )
                bb = BatchMatcher(stock_demo.stock_pattern(), K2, big)
                bs0 = bb.init_state()
                bstate, bout = bb.scan(bs0, ev2)
                jax.block_until_ready(bout.count)
                bbest = float("inf")
                for _ in range(max(reps - 2, 1)):
                    t0 = time.perf_counter()
                    bstate, bout = bb.scan(bs0, ev2)
                    jax.block_until_ready(bout.count)
                    bbest = min(bbest, time.perf_counter() - t0)
                r2, p2, _ = measure_recall(
                    bout, bb.names, prices, volumes, lanes2
                )
                log(
                    f"engine[R=64,E=128,W=16, {K2} lanes]: "
                    f"{K2 * T / bbest / 1e3:.0f}K ev/s, recall {r2:.4f} / "
                    f"precision {p2:.4f} — the capacity/recall tradeoff "
                    "on the same trace"
                )
                del bb, bs0, bstate, bout
            except Exception as e:  # never break the headline
                log(f"recall-curve point failed: {type(e).__name__}: {e}")

    # Two-tier hot-window headline (ISSUE 1): the same trace and shapes
    # with slab_hot_entries = CEP_BENCH_HOT_ENTRIES (default 16, 0 skips).
    # Matches are bit-identical by construction (parity suites); reported
    # here are the speed delta and the residency telemetry that explains
    # it (hot-hit rate = the fraction of walk hops that paid an E_hot-sized
    # reduce instead of an E-sized one).
    hot_n = int(os.environ.get("CEP_BENCH_HOT_ENTRIES", "16"))
    lazy_metrics = None
    hot_metrics = None
    if hot_n > 0 and hot_n % 8 == 0 and hot_n < cfg.slab_entries:
        try:
            import dataclasses

            hcfg = dataclasses.replace(cfg, slab_hot_entries=hot_n)
            hb = BatchMatcher(stock_demo.stock_pattern(), K, hcfg)
            hs0 = hb.init_state()
            hstate, hout = hb.scan(hs0, events)
            jax.block_until_ready(hout.count)
            hbest = float("inf")
            for _ in range(reps):
                t0 = time.perf_counter()
                hstate, hout = hb.scan(hs0, events)
                jax.block_until_ready(hout.count)
                hbest = min(hbest, time.perf_counter() - t0)
            hcounters = hb.counters(hstate)
            hhot = hb.hot_counters(hstate)
            hops = hhot["slab_hot_hits"] + hhot["slab_hot_misses"]
            hit_rate = hhot["slab_hot_hits"] / hops if hops else 1.0
            hmatches = int(jnp.sum(hout.count > 0))
            hot_evps = K * T / hbest
            log(
                f"engine[hot E_hot={hot_n}]: {hbest * 1e3:.1f} ms "
                f"({hot_evps / 1e6:.2f}M ev/s, {hot_evps / (K * T / best):.2f}x "
                f"single-tier), hot-hit rate {hit_rate:.3f}, "
                f"{hmatches} match slots (single-tier: {matches}), "
                f"hot counters {hhot}"
            )
            if hcounters != counters:
                log(
                    "engine[hot]: WARNING drop counters diverged from "
                    f"single-tier: {hcounters} vs {counters}"
                )
            hot_metrics = {
                "hot_entries": hot_n,
                "evps": round(hot_evps, 1),
                "speedup_vs_single_tier": round(hot_evps / (K * T / best), 3),
                "hot_hit_rate": round(hit_rate, 4),
                "match_slots": hmatches,
                "match_slots_single_tier": matches,
                "hot_counters": hhot,
                "counters_match_single_tier": hcounters == counters,
            }
            del hb, hs0, hstate, hout
        except Exception as e:  # never break the headline
            log(f"hot-tier bench failed: {type(e).__name__}: {e}")
    else:
        log(f"engine[hot]: skipped (CEP_BENCH_HOT_ENTRIES={hot_n})")

    # Per-stage attribution A/B (ISSUE 6): the same trace and shapes with
    # stage_attribution=True — reports the measured overhead (acceptance:
    # <= 3% on this headline) and the per-stage selectivity/cost table
    # the compiler-tiering work reads.  CEP_BENCH_ATTR=0 skips.
    attr_metrics = None
    if os.environ.get("CEP_BENCH_ATTR", "1") == "1":
        try:
            import dataclasses as _dc

            acfg = _dc.replace(cfg, stage_attribution=True)
            ab = BatchMatcher(stock_demo.stock_pattern(), K, acfg)
            as0 = ab.init_state()
            astate, aout = ab.scan(as0, events)
            jax.block_until_ready(aout.count)
            abest = float("inf")
            for _ in range(reps):
                t0 = time.perf_counter()
                astate, aout = ab.scan(as0, events)
                jax.block_until_ready(aout.count)
                abest = min(abest, time.perf_counter() - t0)
            attr_evps = K * T / abest
            overhead = (abest - best) / best * 100.0
            per_stage = ab.stage_counters(astate)
            attr_metrics = {
                "evps": round(attr_evps, 1),
                "overhead_pct": round(overhead, 2),
                "within_3pct": overhead <= 3.0,
                "counters_match_baseline": ab.counters(astate) == counters,
                "per_stage": per_stage,
            }
            log(
                f"engine[attribution]: {attr_evps / 1e6:.2f}M ev/s "
                f"({overhead:+.2f}% vs baseline, <=3% bound "
                f"{'OK' if overhead <= 3.0 else 'EXCEEDED'}); per-stage "
                f"selectivity "
                + ", ".join(
                    f"{s}={row['selectivity']}"
                    for s, row in per_stage.items()
                )
            )
            del ab, as0, astate, aout
        except Exception as e:  # never break the headline
            log(f"attribution bench failed: {type(e).__name__}: {e}")
    else:
        log("engine[attribution]: skipped (CEP_BENCH_ATTR=0)")

    # Lazy extraction A/B (ISSUE 4): the same trace eager vs lazy at the
    # same shapes, drained at a processor-like chunk cadence; reports the
    # per-step hop reduction (the device critical-path win), hot-hit-rate
    # delta, and match-slot parity.  CEP_BENCH_LAZY=0 skips.
    if os.environ.get("CEP_BENCH_LAZY", "1") == "1":
        try:
            lazy_metrics = bench_lazy_block(K, T, reps, cfg, events, hot_n)
        except Exception as e:  # never break the headline
            log(f"lazy bench failed: {type(e).__name__}: {e}")
    else:
        log("engine[lazy]: skipped (CEP_BENCH_LAZY=0)")
    # (E, E_hot) frontier sweep hook (PROFILE_r06 next-leverage item 3):
    # CEP_BENCH_FRONTIER="48:16,48:24,64:16" reruns the headline trace at
    # each point; off by default.
    frontier = os.environ.get("CEP_BENCH_FRONTIER", "")
    if frontier:
        try:
            pts = bench_frontier(K, T, reps, events, cfg, frontier)
            if lazy_metrics is not None:
                lazy_metrics["frontier"] = pts
        except Exception as e:
            log(f"frontier sweep failed: {type(e).__name__}: {e}")
    return (K * T / best, spread, counters, recall, precision, hot_metrics,
            lazy_metrics, attr_metrics)


def _chunked_scan(batch, events, chunk, lazy):
    """One chunk-cadence pass over ``events`` (drain between chunks when
    lazy — the processor's cadence), returning ``(state, match_slots)``.
    Every chunk's outputs materialize through a consumed reduction
    (``int(...)``), so the timing caller cannot be fooled by JAX's async
    dispatch (PROFILE_r05 finding 1)."""
    import jax as _jax

    state = batch.init_state()
    n = 0
    T = int(events.ts.shape[1])
    for t0 in range(0, T, chunk):
        ev = _jax.tree_util.tree_map(
            lambda x: x[:, t0:t0 + chunk], events
        )
        state, out = batch.scan(state, ev)
        if lazy:
            state, drained = batch.drain(state)
            n += int(jnp.sum(drained.count > 0))  # consumed reduction
        else:
            n += int(jnp.sum(out.count > 0))  # consumed reduction
    jax.block_until_ready(state.slab.stage)
    return state, n


def bench_lazy_block(K, T, reps, base_cfg, events, hot_n):
    """Eager vs lazy at identical shapes on the headline trace (ISSUE 4).

    Both sides run the same chunk cadence (scan chunk + [drain] per
    chunk) so the comparison isolates WHERE the extraction hops run, not
    how the scan is sliced.  Reported: ev/s both ways, per-step device
    hop reduction (walk_hops + extract_hops, the lockstep critical path),
    drain-hop conservation, hot-hit-rate delta at E_hot=hot_n, and
    match-slot parity; handle_overflows is printed so a too-small ring
    can never masquerade as a win.
    """
    import dataclasses

    chunk = int(os.environ.get("CEP_BENCH_LAZY_CHUNK", "64"))
    ring = int(os.environ.get("CEP_BENCH_LAZY_RING", "512"))
    # Slab headroom for BOTH sides (default 2x the headline E): the lazy
    # engine holds completed chains until the drain, so at the
    # capacity-crushed headline E the two sides shed different branches
    # and parity becomes a drop-policy comparison instead of an
    # extraction-placement one.  CEP_BENCH_LAZY_E=0 keeps the headline E
    # to see exactly that effect (reported, never hidden).
    lazy_e = int(
        os.environ.get("CEP_BENCH_LAZY_E", str(2 * base_cfg.slab_entries))
    )
    ecfg = dataclasses.replace(
        base_cfg,
        slab_hot_entries=hot_n,
        slab_entries=lazy_e or base_cfg.slab_entries,
    )
    lcfg = dataclasses.replace(
        ecfg, lazy_extraction=True, handle_ring=ring
    )
    out = {}
    runs = {}
    for label, cfg, lazy in (("eager", ecfg, False), ("lazy", lcfg, True)):
        batch = BatchMatcher(stock_demo.stock_pattern(), K, cfg)
        t0 = time.perf_counter()
        state, n = _chunked_scan(batch, events, chunk, lazy)
        log(f"engine[lazy A/B {label}]: compile+first "
            f"{time.perf_counter() - t0:.1f}s")
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            state, n = _chunked_scan(batch, events, chunk, lazy)
            best = min(best, time.perf_counter() - t0)
        runs[label] = (batch, state, n, best)
    (eb, es, en, ebest), (lb, ls, ln, lbest) = runs["eager"], runs["lazy"]
    we, wl = eb.walk_counters(es), lb.walk_counters(ls)
    step_e = we["walk_hops"] + we["extract_hops"]
    step_l = wl["walk_hops"] + wl["extract_hops"]
    reduction = 1 - step_l / step_e if step_e else 0.0

    def rate(h):
        t = h["slab_hot_hits"] + h["slab_hot_misses"]
        return h["slab_hot_hits"] / t if t else 1.0

    # NOTE: the lazy hot counters include drain-pass hops; the step-phase
    # rate (drain excluded) is what the two-tier reduce-width model sees —
    # approximate it by removing the drain share proportionally is wrong,
    # so report both raw rates and the hop classes for offline analysis.
    ovf = lb.counters(ls)["handle_overflows"]
    out = {
        "eager_evps": round(K * T / ebest, 1),
        "lazy_evps": round(K * T / lbest, 1),
        "speedup": round(ebest / lbest, 3),
        "step_hop_reduction": round(reduction, 4),
        "drain_hops_conserved": wl["drain_hops"] == we["extract_hops"],
        "hot_hit_rate_eager": round(rate(eb.hot_counters(es)), 4),
        "hot_hit_rate_lazy": round(rate(lb.hot_counters(ls)), 4),
        "match_slots_eager": en,
        "match_slots_lazy": ln,
        "match_slot_parity": en == ln,
        "handle_overflows": ovf,
        "walk_counters_eager": we,
        "walk_counters_lazy": wl,
        "chunk": chunk,
        "handle_ring": ring,
    }
    log(
        f"engine[lazy A/B, chunk={chunk}]: eager {K * T / ebest / 1e3:.0f}K"
        f" ev/s vs lazy {K * T / lbest / 1e3:.0f}K ev/s "
        f"({ebest / lbest:.2f}x); step-hop reduction {reduction:.1%}, "
        f"match slots {en} vs {ln} (parity={en == ln}, "
        f"handle_overflows={ovf}), hot-hit rate "
        f"{out['hot_hit_rate_eager']:.3f} -> {out['hot_hit_rate_lazy']:.3f}"
    )
    return out


def bench_frontier(K, T, reps, events, base_cfg, spec):
    """(E, E_hot) frontier sweep: rerun the headline trace at each
    ``E:EH`` point of ``spec`` (comma-separated) with the two-tier walk
    kernels enabled — places the new frontier next to PROFILE_r05's
    E-linear line on chip."""
    import dataclasses

    pts = {}
    for pair in spec.split(","):
        e_s, eh_s = pair.strip().split(":")
        E, EH = int(e_s), int(eh_s)
        cfg = dataclasses.replace(
            base_cfg, slab_entries=E, slab_hot_entries=EH
        )
        batch = BatchMatcher(stock_demo.stock_pattern(), K, cfg)
        state0 = batch.init_state()
        state, out = batch.scan(state0, events)
        jax.block_until_ready(out.count)
        best = float("inf")
        for _ in range(max(reps - 2, 1)):
            t0 = time.perf_counter()
            state, out = batch.scan(state0, events)
            jax.block_until_ready(out.count)
            best = min(best, time.perf_counter() - t0)
        hot = batch.hot_counters(state)
        hops = hot["slab_hot_hits"] + hot["slab_hot_misses"]
        rate = hot["slab_hot_hits"] / hops if hops else 1.0
        pts[f"{E}:{EH}"] = {
            "evps": round(K * T / best, 1),
            "hot_hit_rate": round(rate, 4),
        }
        log(f"frontier[E={E},EH={EH}]: {K * T / best / 1e3:.0f}K ev/s, "
            f"hot-hit rate {rate:.3f}")
        del batch, state0, state, out
    return pts


def bench_tier():
    """``CEP_BENCH_TIER``: compiler-tiering A/B (ISSUE 7).

    Strict-prefix-dominated, match-sparse workload — the production-
    monitoring shape: a 3-strict-stage prefix + skip-till-next suffix
    over a 64-symbol alphabet, so the begin predicate rejects ~98% of
    events and full prefixes fire ~4e-6/event; a handful of complete
    occurrences are planted so match parity is non-vacuous.  Untiered
    vs tiered BatchMatcher at identical shapes and chunk cadence (the
    processor's batch granularity, where the tiered matcher's NFA skip
    gate operates).  Reports ev/s both ways, the screened-event
    fraction, the NFA dispatch fraction, and a match-parity flag; both
    sides must finish loss-free (all counters zero) for the speedup to
    count.
    """
    from kafkastreams_cep_tpu.parallel.tiered import TieredBatchMatcher

    K = int(os.environ.get("CEP_BENCH_TIER_K", "32"))
    T = int(os.environ.get("CEP_BENCH_TIER_T", "4096"))
    chunk = int(os.environ.get("CEP_BENCH_TIER_CHUNK", "128"))
    reps = int(os.environ.get("CEP_BENCH_TIER_REPS", "3"))
    pattern = (
        Query()
        .select("pa").where(lambda k, v, ts, st: v == 1)
        .then()
        .select("pb").where(lambda k, v, ts, st: v == 2)
        .then()
        .select("pc").where(lambda k, v, ts, st: v == 3)
        .then()
        .select("sd").skip_till_next_match()
        .where(lambda k, v, ts, st: v == 7)
        .build()
    )
    rng = np.random.default_rng(17)
    codes = rng.integers(8, 64, size=(K, T)).astype(np.int32)
    # Planted full occurrences, clustered into a few chunks: most batches
    # then skip the NFA dispatch entirely (the match-sparse production
    # shape), while the hit chunks keep match parity non-vacuous.
    n_chunks = max(T // chunk, 1)
    hot_chunks = sorted(
        rng.choice(n_chunks, size=min(3, n_chunks), replace=False)
    )
    for i in range(12):
        c = int(hot_chunks[i % len(hot_chunks)])
        k = int(rng.integers(0, K))
        t = c * chunk + int(rng.integers(0, max(chunk - 16, 1)))
        codes[k, t], codes[k, t + 1], codes[k, t + 2] = 1, 2, 3
        codes[k, t + 9] = 7
    cfg = EngineConfig(
        max_runs=32, slab_entries=64, slab_preds=8, dewey_depth=12,
        max_walk=12,
    )
    tcfg = __import__("dataclasses").replace(cfg, tiering=True)
    events = EventBatch(
        key=jnp.zeros((K, T), jnp.int32),
        value=jnp.asarray(codes),
        ts=jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32), (K, T)),
        off=jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32), (K, T)),
        valid=jnp.ones((K, T), bool),
    )

    def _chunked_scan_tier(batch):
        # Same consumed-reduction contract as _chunked_scan: every chunk's
        # outputs materialize inside the span (int() pulls the reduction,
        # block_until_ready fences the final state).
        state = batch.init_state()
        n = 0
        hits = []
        for t0 in range(0, T, chunk):
            ev = jax.tree_util.tree_map(
                lambda x: x[:, t0:t0 + chunk], events
            )
            state, out = batch.scan(state, ev)
            n += int(jnp.sum(out.count > 0))  # consumed reduction
            ct = np.asarray(out.count)
            for k, t, r in zip(*np.nonzero(ct)):
                hits.append((int(k), t0 + int(t), int(ct[k, t, r])))
        jax.block_until_ready(
            state.slab.stage
            if not hasattr(state, "engine")
            else state.engine.slab.stage
        )
        return state, n, sorted(hits)

    runs = {}
    for label, b in (
        ("untiered", BatchMatcher(pattern, K, cfg)),
        ("tiered", TieredBatchMatcher(pattern, K, tcfg)),
    ):
        t0 = time.perf_counter()
        state, n, hits = _chunked_scan_tier(b)
        log(f"tier[{label}]: compile+first {time.perf_counter() - t0:.1f}s")
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            state, n, hits = _chunked_scan_tier(b)
            best = min(best, time.perf_counter() - t0)
        runs[label] = (b, state, n, hits, best)
    (ub, us, un, uh, ubest) = runs["untiered"]
    (tb, ts_, tn, th, tbest) = runs["tiered"]
    uc, tc = ub.counters(us), tb.counters(ts_)
    tier = tb.tier_counters(ts_)
    screened = tier["prefix_events_screened"]
    fires = tier["prefix_fires"]
    parity = uh == th and uc == tc
    zero = all(v == 0 for v in uc.values()) and all(
        v == 0 for v in tc.values()
    )
    # Denominator: under chunk-level gating (ISSUE 16) each scan offers
    # ceil(T'/gate_chunk) device-gated chunks, so the dispatched fraction
    # is per-chunk whenever the gate ran; pure-NFA plans and the
    # whole-scan kernel count whole batches (gate_chunks stays 0).
    gate_denom = tb.gate_chunks or tb.scan_calls
    dispatch_frac = tb.nfa_dispatches / gate_denom if gate_denom else 0.0
    out = {
        "k": K, "t": T, "chunk": chunk,
        "plan": tb.plan.describe(),
        "untiered_evps": round(K * T / ubest, 1),
        "tiered_evps": round(K * T / tbest, 1),
        "speedup": round(ubest / tbest, 3),
        "screened_fraction": (
            round(1.0 - fires / screened, 6) if screened else None
        ),
        "prefix_fires": fires,
        "tier_promotions": tier["tier_promotions"],
        "nfa_dispatch_fraction": round(dispatch_frac, 4),
        "match_slots": un,
        "match_parity": bool(parity),
        "counters_zero": bool(zero),
    }
    log(
        f"tier A/B ({K}x{T}, chunk={chunk}, {tb.plan.tier} "
        f"p={tb.plan.prefix_len}): untiered {K * T / ubest / 1e3:.0f}K "
        f"ev/s vs tiered {K * T / tbest / 1e3:.0f}K ev/s "
        f"({ubest / tbest:.2f}x); screened {out['screened_fraction']}, "
        f"NFA dispatched {dispatch_frac:.1%} of gated chunks, "
        f"{un} vs {tn} match slots (parity={parity}, zero={zero})"
    )
    return out


def bench_adapt():
    """``CEP_BENCH_ADAPT``: adaptive recompilation A/B (ISSUE 16).

    Two probes:

    1. *Hybrid sweep* — PROFILE_r09 §2's band re-run under the
       chunk-gated scan (the per-scan host gate is gone): 4-stage
       patterns with the first p of 4 stages strict, p = 1..3, untiered
       vs tiered at identical shapes/cadence.  Every point must sit at
       or above BENCH_r06's recorded 2.7-5.2x band, loss-free with
       match parity.
    2. *Drift A/B* — a two-conjunct workload whose accept mix inverts
       mid-stream, run twice on identical records: a supervised
       processor with ``AdaptPolicy`` (profiler-driven replans at
       checkpoint boundaries) vs the same supervisor with replanning
       off (the stale compile-time plan).  The adaptive side must fire
       >= 1 replan, stay bit-identical on matches and loss counters
       (exactly-once across the swap), and beat the stale declaration
       order on the lazy-chain objective — expected conjunct
       evaluation cost per event under the drifted mix (arxiv
       1612.05110's ranking quantity, computed from the measured
       marginal selectivities).  Wall-clock is reported for both sides
       but expected to tie: the array engine evaluates conjunct chains
       branch-free, so evaluation order is a host/short-circuit and
       future-gating lever, not a device-throughput one
       (PROFILE_r09 §3).

    ``CEP_BENCH_ADAPT_{K,T,CHUNK,REPS}`` size the sweep;
    ``CEP_BENCH_ADAPT_DRIFT_B`` sizes the drift stream (batches per
    phase).
    """
    import dataclasses
    import shutil
    import tempfile

    from kafkastreams_cep_tpu.parallel.tiered import TieredBatchMatcher
    from kafkastreams_cep_tpu.pattern.predicate import and_, hint
    from kafkastreams_cep_tpu.runtime import Record
    from kafkastreams_cep_tpu.runtime.supervisor import (
        AdaptPolicy,
        Supervisor,
    )

    K = int(os.environ.get("CEP_BENCH_ADAPT_K", "32"))
    T = int(os.environ.get("CEP_BENCH_ADAPT_T", "2048"))
    chunk = int(os.environ.get("CEP_BENCH_ADAPT_CHUNK", "128"))
    reps = int(os.environ.get("CEP_BENCH_ADAPT_REPS", "2"))

    # -- probe 1: hybrid sweep (strict-prefix length 1..3 of 4) ----------
    def sweep_pattern(p):
        q = Query()
        for i, (nm, code) in enumerate(
            zip(("pa", "pb", "pc", "sd"), (1, 2, 3, 7))
        ):
            q = q.select(nm) if i == 0 else q.then().select(nm)
            if i >= p:
                q = q.skip_till_next_match()
            q = q.where(lambda k, v, ts, st, c=code: v == c)
        return q.build()

    # dewey_depth 24: at 12 the seed-29 trace ticks ver_overflows (both
    # sides identically), and the loss contract here is all-zero.
    cfg = EngineConfig(
        max_runs=32, slab_entries=64, slab_preds=8, dewey_depth=24,
        max_walk=12,
    )
    tcfg = dataclasses.replace(cfg, tiering=True)
    rng = np.random.default_rng(29)
    codes = rng.integers(8, 64, size=(K, T)).astype(np.int32)
    n_chunks = max(T // chunk, 1)
    hot_chunks = sorted(
        rng.choice(n_chunks, size=min(3, n_chunks), replace=False)
    )
    for i in range(9):
        c = int(hot_chunks[i % len(hot_chunks)])
        k = int(rng.integers(0, K))
        t = c * chunk + int(rng.integers(0, max(chunk - 16, 1)))
        codes[k, t], codes[k, t + 1], codes[k, t + 2] = 1, 2, 3
        codes[k, t + 9] = 7
    events = EventBatch(
        key=jnp.zeros((K, T), jnp.int32),
        value=jnp.asarray(codes),
        ts=jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32), (K, T)),
        off=jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32), (K, T)),
        valid=jnp.ones((K, T), bool),
    )

    def _chunked_scan_adapt(batch):
        state = batch.init_state()
        n = 0
        hits = []
        for t0 in range(0, T, chunk):
            ev = jax.tree_util.tree_map(
                lambda x: x[:, t0:t0 + chunk], events
            )
            state, out = batch.scan(state, ev)
            n += int(jnp.sum(out.count > 0))
            ct = np.asarray(out.count)
            for k, t, r in zip(*np.nonzero(ct)):
                hits.append((int(k), t0 + int(t), int(ct[k, t, r])))
        jax.block_until_ready(
            state.slab.stage
            if not hasattr(state, "engine")
            else state.engine.slab.stage
        )
        return state, n, sorted(hits)

    sweep = {}
    sweep_parity = True
    sweep_zero = True
    for p in (1, 2, 3):
        pattern = sweep_pattern(p)
        runs = {}
        for label, b in (
            ("untiered", BatchMatcher(pattern, K, cfg)),
            ("tiered", TieredBatchMatcher(pattern, K, tcfg)),
        ):
            state, n, hits = _chunked_scan_adapt(b)  # compile + first
            best = float("inf")
            for _ in range(reps):
                t0 = time.perf_counter()
                state, n, hits = _chunked_scan_adapt(b)
                best = min(best, time.perf_counter() - t0)
            runs[label] = (b, state, n, hits, best)
        ub, us, un, uh, ubest = runs["untiered"]
        tb, ts_, tn, th, tbest = runs["tiered"]
        uc, tc = ub.counters(us), tb.counters(ts_)
        parity = uh == th and uc == tc
        zero = all(v == 0 for v in uc.values()) and all(
            v == 0 for v in tc.values()
        )
        sweep_parity &= parity
        sweep_zero &= zero
        gate_denom = tb.gate_chunks or tb.scan_calls
        sweep[f"p{p}"] = {
            "plan": tb.plan.describe(),
            "untiered_evps": round(K * T / ubest, 1),
            "tiered_evps": round(K * T / tbest, 1),
            "speedup": round(ubest / tbest, 3),
            "nfa_dispatch_fraction": round(
                tb.nfa_dispatches / gate_denom if gate_denom else 0.0, 4
            ),
            "match_slots": un,
            "match_parity": bool(parity),
            "counters_zero": bool(zero),
        }
        log(
            f"adapt sweep p={p}: untiered {K * T / ubest / 1e3:.1f}K "
            f"ev/s vs tiered {K * T / tbest / 1e3:.1f}K ev/s "
            f"({ubest / tbest:.2f}x, parity={parity}, zero={zero})"
        )
        del runs, ub, tb, us, ts_

    # -- probe 2: drift A/B (replanning vs the stale plan) ---------------
    DK = 8
    n_phase = int(os.environ.get("CEP_BENCH_ADAPT_DRIFT_B", "16"))
    batch_sz = 64  # records per process() call, per key below

    def f_narrow(k, v, ts, st):
        return v < 8

    def g_mod(k, v, ts, st):
        return v % 4 == 0

    drift_pattern = (
        Query()
        .select("first")
        # Declared order (f, g): equal costs, so only measured
        # selectivity can flip the chain — exactly what the drift does.
        .where(and_(hint(f_narrow, cost=4.0), hint(g_mod, cost=4.0)))
        .then()
        .select("second").skip_till_next_match()
        .where(lambda k, v, ts, st: v == 0)
        .build()
    )
    dcfg = EngineConfig(
        max_runs=32, slab_entries=96, slab_preds=12, dewey_depth=48,
        max_walk=12, tiering=True, stage_attribution=True,
    )
    # Phase 1: {0,4,8,12} -> sel(f)=0.5, sel(g)=1.0 (declared order
    # already optimal).  Phase 2: {0,1,2,3,5,6,7} -> sel(f)=1.0,
    # sel(g)=1/7 — the cheap-reject conjunct is now g, so the measured
    # plan flips the chain.  Phase 2 keeps an occasional 0 so pending
    # skip-till runs can still complete: a 0-free phase leaves every
    # open run skipping all phase-2 events and overflows dewey versions.
    rng2 = np.random.default_rng(41)
    pools = [(0, 4, 8, 12), (0, 1, 2, 3, 5, 6, 7)]
    batches = []
    t_base = 0
    for phase, pool in enumerate(pools):
        for _ in range(n_phase):
            recs = []
            for i in range(batch_sz):
                k = int(rng2.integers(0, DK))
                v = int(rng2.choice(pool))
                recs.append(Record(k, v, 1000 + t_base + i))
            t_base += batch_sz
            batches.append(recs)

    def run_side(policy):
        d = tempfile.mkdtemp(prefix="cep_adapt_")
        try:
            sup = Supervisor(
                drift_pattern, DK, dcfg,
                checkpoint_path=os.path.join(d, "ckpt"),
                checkpoint_every=2,
                adapt_policy=policy,
                gc_interval=0,
            )
            matches = []
            # host-timed: end-to-end supervisor records/s — decode pulls
            # every match to host, and the replan rebuild cost is part
            # of what this A/B measures.
            t0 = time.perf_counter()  # host-timed
            for recs in batches:
                matches.extend(sup.process(recs))
            matches.extend(sup.drain_ingest())
            wall = time.perf_counter() - t0
            snap = sup.metrics_snapshot()
            order = [
                r["order"]
                for r in (sup.processor.batch.lazy_order or {}).values()
                if r.get("order")
            ]
            counters = sup.processor.counters()
            return matches, wall, snap, order, counters
        finally:
            shutil.rmtree(d, ignore_errors=True)

    policy = AdaptPolicy(
        drift_threshold=0.2, min_evals=64, replan_streak=1, cooldown=0
    )
    a_matches, a_wall, a_snap, a_order, a_counters = run_side(policy)
    s_matches, s_wall, s_snap, s_order, s_counters = run_side(None)

    def keyed(ms):
        return sorted(
            (k, tuple(
                (stg, tuple(e.offset for e in evs))
                for stg, evs in s.as_map().items()
            ))
            for k, s in ms
        )

    drift_parity = keyed(a_matches) == keyed(s_matches)
    loss_names = (
        "run_drops", "ver_overflows", "slab_full_drops",
        "slab_pred_drops", "slab_trunc", "walk_collisions",
        "handle_overflows",
    )
    drift_zero = all(
        c.get(n_, 0) == 0
        for c in (a_counters, s_counters)
        for n_ in loss_names
    )
    n_records = len(batches) * batch_sz

    # Lazy-chain objective under the drifted (phase 2) mix: expected
    # per-event evaluation cost of each side's live chain order, using
    # the true marginal selectivities of the drifted pool.  Short-
    # circuit cost of order (c1, c2) = c1 + sel1 * c2.
    pool2 = np.asarray(pools[1])
    sel2 = {
        "f_narrow": float(np.mean(pool2 < 8)),
        "g_mod": float(np.mean(pool2 % 4 == 0)),
    }
    cost = {"f_narrow": 4.0, "g_mod": 4.0}

    def chain_cost(order_labels):
        total, reach = 0.0, 1.0
        for lbl in order_labels:
            name = "f_narrow" if "f_narrow" in lbl else "g_mod"
            total += reach * cost[name]
            reach *= sel2[name]
        return total

    stale_first = next(
        (o for o in s_order if len(o) == 2), ["f_narrow", "g_mod"]
    )
    adapt_first = next(
        (o for o in a_order if len(o) == 2), stale_first
    )
    stale_cost = chain_cost(stale_first)
    adapt_cost = chain_cost(adapt_first)
    out = {
        "sweep": sweep,
        "sweep_speedup_min": min(s["speedup"] for s in sweep.values()),
        "band_r06": [2.7, 5.2],
        "drift": {
            "k": DK,
            "batches": len(batches),
            "records": n_records,
            "adaptive_rps": round(n_records / a_wall, 1),
            "stale_rps": round(n_records / s_wall, 1),
            "replans": a_snap.get("replans", 0),
            "replan_failures": a_snap.get("replan_failures", 0),
            "stale_order": stale_first,
            "replanned_order": adapt_first,
            "stale_cost_per_event": round(stale_cost, 3),
            "replanned_cost_per_event": round(adapt_cost, 3),
            "lazy_cost_ratio": round(stale_cost / adapt_cost, 3),
        },
        "match_parity": bool(sweep_parity and drift_parity),
        "counters_zero": bool(sweep_zero and drift_zero),
    }
    log(
        f"adapt drift (K={DK}, {n_records} records): adaptive "
        f"{n_records / a_wall / 1e3:.1f}K rec/s ({a_snap.get('replans', 0)} "
        f"replans) vs stale {n_records / s_wall / 1e3:.1f}K rec/s; "
        f"lazy-chain cost {stale_cost:.2f} -> {adapt_cost:.2f} "
        f"({stale_cost / adapt_cost:.2f}x better on the drifted mix); "
        f"parity={drift_parity}, zero={drift_zero}"
    )
    return out


def bench_stencil(total_events, reps):
    """BASELINE.json config 2: strict-contiguity 3-stage SEQ over ~1M
    synthetic StockEvents (stencil fast path; stderr-reported secondary)."""
    pattern = (
        Query()
        .select("rise").where(lambda k, v, ts, st: v["price"] > 110)
        .then()
        .select("surge").where(lambda k, v, ts, st: v["volume"] > 900)
        .then()
        .select("drop").where(lambda k, v, ts, st: v["price"] < 105)
        .build()
    )
    K = 128
    T = max(total_events // K, 1)
    m = StencilMatcher(pattern, K)
    rng = np.random.default_rng(7)
    events = make_batch(rng, K, T)
    # Amortize inside ONE dispatch: per-dispatch latency through the device
    # tunnel (~100ms) otherwise dominates and understates the device rate
    # by an order of magnitude.
    inner = max(int(os.environ.get("CEP_BENCH_STENCIL_INNER", "10")), 1)

    @jax.jit
    def many(state):
        def body(s, _):
            s2, out = m.scan(s, events)
            return s2, jnp.sum(out.hit)
        return jax.lax.scan(body, state, None, length=inner)

    t0 = time.perf_counter()
    _, hits = many(m.init_state())
    jax.block_until_ready(hits)
    log(f"stencil: compile+first run {time.perf_counter() - t0:.1f}s")
    best = float("inf")
    for i in range(reps):
        t0 = time.perf_counter()
        _, hits = many(m.init_state())
        jax.block_until_ready(hits)
        best = min(best, time.perf_counter() - t0)
    n_hits = int(hits[0])
    total = K * T * inner
    log(
        f"stencil (strict 3-stage SEQ, {K}x{T} events x{inner} in-dispatch): "
        f"{total / best / 1e6:.1f}M ev/s, {n_hits} matches/scan"
    )
    return total / best


def bench_kleene(K, T, reps):
    """BASELINE.json config 2: skip_till_any_match + oneOrMore Kleene
    closure, vmapped over ~10K key lanes (stderr-reported secondary)."""
    pattern = (
        Query()
        .select("start").where(lambda k, v, ts, st: v["price"] > 120)
        .then()
        .select("run").one_or_more().skip_till_any_match()
        .where(lambda k, v, ts, st: v["volume"] > 900)
        .then()
        .select("end").where(lambda k, v, ts, st: v["price"] < 100)
        .build()
    )
    rng = np.random.default_rng(11)
    prices = rng.integers(80, 141, size=(K, T)).astype(np.int32)
    volumes = rng.integers(600, 1101, size=(K, T)).astype(np.int32)
    events = EventBatch(
        key=jnp.broadcast_to(jnp.arange(K, dtype=jnp.int32)[:, None], (K, T)),
        value={"price": jnp.asarray(prices), "volume": jnp.asarray(volumes)},
        ts=jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32)[None, :] * 3, (K, T)),
        off=jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32)[None, :], (K, T)),
        valid=jnp.ones((K, T), bool),
    )
    # Two capacity points make the throughput/fidelity tradeoff explicit:
    # the small shapes run ~2x faster but shed branches under this
    # branch-dense trace (counted); the second point's shapes are DERIVED
    # from a 128-lane probe of the same trace (engine/sizing.py) and run
    # with every capacity counter zero (slab_missing alone is semantic:
    # reference-NPE trace states, KVSharedVersionedBuffer.java:86-89).
    points = [
        ("small", EngineConfig(max_runs=16, slab_entries=32, slab_preds=6,
                               dewey_depth=10, max_walk=10)),
    ]
    if os.environ.get("CEP_BENCH_AUTOSIZE", "1") != "0":
        sK = min(K, 128)
        sample = jax.tree_util.tree_map(lambda x: x[:sK], events)
        derived = autosize(
            pattern, sample,
            start=EngineConfig(max_runs=24, slab_entries=64, slab_preds=8,
                               dewey_depth=12, max_walk=12),
            margin=1.4, sweep_every=T,
        )
        log(f"kleene: autosized config {derived}")
        points.append(("derived", derived))
    else:
        points.append(
            ("large", EngineConfig(max_runs=24, slab_entries=64,
                                   slab_preds=8, dewey_depth=12,
                                   max_walk=12)))
    rate = 0.0
    for label, cfg in points:
        batch = BatchMatcher(pattern, K, cfg)
        state0 = batch.init_state()
        t0 = time.perf_counter()
        state, out = batch.scan(state0, events)
        jax.block_until_ready(out.count)
        log(f"kleene[{label}]: compile+first scan {time.perf_counter() - t0:.1f}s")
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            state, out = batch.scan(state0, events)
            jax.block_until_ready(out.count)
            best = min(best, time.perf_counter() - t0)
        matches = int(jnp.sum(out.count > 0))
        counters = batch.counters(state)
        capacity_zero = not any(capacity_counters(counters).values())
        log(
            f"kleene[{label}] (skip_till_any + oneOrMore, {K} lanes x {T}): "
            f"{K * T / best / 1e3:.0f}K ev/s, {matches} match slots, "
            f"capacity_zero={capacity_zero}, counters {counters}"
        )
        rate = max(rate, K * T / best)
    return rate


def bench_bank(n_list, total_lanes, T, reps):
    """BASELINE.json config 3: multi-pattern NFA bank over ~100K total key
    lanes — N parameterized query variants over the same stream, serial
    (one dispatch per query, the reference's one-CEPProcessor-per-pattern
    composition) vs stacked (one dispatch for the whole bank,
    parallel/stacked.py), at each bank width in ``n_list``.  The
    auto-chooser (choose_bank) picks per width from a 128-lane sample;
    its pick is logged next to the full-size outcome."""
    from kafkastreams_cep_tpu.parallel.stacked import (
        StackedBankMatcher,
        choose_bank,
    )

    def q(i):
        lo, hi = 95 + i * 5, 120 - i * 3
        return (
            Query()
            .select("a").where(lambda k, v, ts, st, lo=lo: v["price"] < lo)
            .then()
            .select("b").skip_till_next_match()
            .where(lambda k, v, ts, st, hi=hi: v["price"] > hi)
            .build()
        )

    cfg = EngineConfig(
        max_runs=8, slab_entries=16, slab_preds=4, dewey_depth=6, max_walk=6
    )
    rng = np.random.default_rng(13)
    results = {}
    for N in n_list:
        K = max((total_lanes // N) // 128 * 128, 128)
        prices = rng.integers(80, 141, size=(K, T)).astype(np.int32)
        events = EventBatch(
            key=jnp.broadcast_to(
                jnp.arange(K, dtype=jnp.int32)[:, None], (K, T)),
            value={"price": jnp.asarray(prices)},
            ts=jnp.broadcast_to(
                jnp.arange(T, dtype=jnp.int32)[None, :], (K, T)),
            off=jnp.broadcast_to(
                jnp.arange(T, dtype=jnp.int32)[None, :], (K, T)),
            valid=jnp.ones((K, T), bool),
        )
        patterns = [q(i) for i in range(N)]
        sample = jax.tree_util.tree_map(lambda x: x[:128], events)
        mode, det = choose_bank(patterns, cfg, sample, reps=1)

        t0 = time.perf_counter()
        matchers = [BatchMatcher(p, K, cfg) for p in patterns]
        states = [m.init_state() for m in matchers]
        outs = [m.scan(s, events) for m, s in zip(matchers, states)]
        jax.block_until_ready([o[1].count for o in outs])
        serial_compile = time.perf_counter() - t0
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            outs = [m.scan(s, events) for m, s in zip(matchers, states)]
            jax.block_until_ready([o[1].count for o in outs])
            best = min(best, time.perf_counter() - t0)
        total = N * K * T
        serial = total / best
        del matchers, states, outs  # free HBM before the fused compile

        t0 = time.perf_counter()
        bank = StackedBankMatcher(patterns, K, cfg)
        bstate0 = bank.init_state()
        bstate, bout = bank.scan(bstate0, events)
        jax.block_until_ready(bout.count)
        fused_compile = time.perf_counter() - t0
        bbest = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            bstate, bout = bank.scan(bstate0, events)
            jax.block_until_ready(bout.count)
            bbest = min(bbest, time.perf_counter() - t0)
        fused = total / bbest
        del bank, bstate0, bstate, bout

        winner = "fused" if bbest < best else "serial"
        agreed = (mode == "stacked") == (winner == "fused")
        log(
            f"bank[N={N}] ({N} queries x {K} lanes, {T} events): "
            f"serial {serial / 1e3:.0f}K q-ev/s (compile {serial_compile:.0f}s"
            f" for {N} programs), fused {fused / 1e3:.0f}K q-ev/s (compile "
            f"{fused_compile:.0f}s for 1), fused/serial {best / bbest:.2f}x; "
            f"chooser picked {mode} on the 128-lane sample "
            f"({'agrees' if agreed else 'DISAGREES'} with full size)"
        )
        results[N] = {
            "serial_qevps": serial,
            "fused_qevps": fused,
            "winner": winner,
            "chooser": mode,
        }
    return results


def bench_tenants():
    """``CEP_BENCH_TENANTS``: multi-tenant bank sweep (ISSUE 14).

    N strict-sequence queries drawn Zipf-style from a small template
    pool — the SaaS-monitoring shape: thousands of tenants install
    near-identical alert rules, so prefixes repeat heavily with a long
    tail of variants.  Every query is pure strict contiguity, so the
    tenant bank (``parallel/tenantbank.py``) runs the ENTIRE bank on the
    shared stencil screen: one deduplicated predicate matrix + one
    vmapped prefix recurrence, no NFA stepping at all.  The baseline is
    the naive-fused :class:`StackedBankMatcher` — one dispatch, but every
    query's full NFA machinery on every lane (measured up to
    ``CEP_BENCH_TENANTS_FUSED_MAX`` queries; beyond that its compile
    dominates and only the tenant side is recorded).  Matches must be
    bit-identical and both sides loss-free for the speedup to count —
    ``tenant_match_parity`` / ``tenant_loss_flags`` join the bench gate.
    """
    from kafkastreams_cep_tpu.parallel.stacked import StackedBankMatcher
    from kafkastreams_cep_tpu.parallel.tenantbank import TenantBankMatcher

    n_list = [
        int(x)
        for x in os.environ.get(
            "CEP_BENCH_TENANTS_N", "100,300,1000"
        ).split(",")
    ]
    K = int(os.environ.get("CEP_BENCH_TENANTS_K", "8"))
    T = int(os.environ.get("CEP_BENCH_TENANTS_T", "64"))
    reps = int(os.environ.get("CEP_BENCH_TENANTS_REPS", "3"))
    pool_n = int(os.environ.get("CEP_BENCH_TENANTS_POOL", "16"))
    fused_max = int(
        os.environ.get("CEP_BENCH_TENANTS_FUSED_MAX", "300")
    )
    cfg = EngineConfig(
        max_runs=4, slab_entries=16, slab_preds=4, dewey_depth=8,
        max_walk=4,
    )
    rng = np.random.default_rng(29)
    # Template pool over a 64-symbol alphabet (the bench_tier shape):
    # (a, b) prefix pairs; each query appends its own final symbol, so
    # queries differ while prefixes collapse onto the pool.
    pool = [
        (int(a), int(b))
        for a, b in rng.integers(1, 8, size=(pool_n, 2))
    ]

    def q(a, b, c):
        return (
            Query()
            .select("pa").where(lambda k, v, ts, st, a=a: v == a)
            .then()
            .select("pb").where(lambda k, v, ts, st, b=b: v == b)
            .then()
            .select("pc").where(lambda k, v, ts, st, c=c: v == c)
            .build()
        )

    # Match-sparse traffic with planted full occurrences so parity is
    # non-vacuous: codes outside the predicate range almost everywhere.
    codes = rng.integers(8, 64, size=(K, T)).astype(np.int32)
    planted = []
    for i in range(6):
        k = int(rng.integers(0, K))
        t = int(rng.integers(0, T - 3))
        planted.append((k, t))
    events = None  # built per N after the plants target real queries

    sweep = {}
    all_parity, all_zero = True, True
    for N in n_list:
        # Zipf-heavy template draw: a few templates carry most tenants.
        z = rng.zipf(1.5, size=N)
        params = []
        for i in range(N):
            a, b = pool[int(z[i] - 1) % pool_n]
            c = int(rng.integers(1, 8))
            params.append((a, b, c))
        ev_codes = codes.copy()
        for j, (k, t) in enumerate(planted):
            a, b, c = params[j % len(params)]
            ev_codes[k, t], ev_codes[k, t + 1], ev_codes[k, t + 2] = (
                a, b, c,
            )
        events = EventBatch(
            key=jnp.broadcast_to(
                jnp.arange(K, dtype=jnp.int32)[:, None], (K, T)),
            value=jnp.asarray(ev_codes),
            ts=jnp.broadcast_to(
                jnp.arange(T, dtype=jnp.int32)[None, :], (K, T)),
            off=jnp.broadcast_to(
                jnp.arange(T, dtype=jnp.int32)[None, :], (K, T)),
            valid=jnp.ones((K, T), bool),
        )
        patterns = [q(*p) for p in params]

        t0 = time.perf_counter()
        bank = TenantBankMatcher(patterns, K, cfg)
        st0 = bank.init_state()
        st, out = bank.scan(st0, events)
        jax.block_until_ready(out.count)
        tb_compile = time.perf_counter() - t0
        tbest = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            st, out = bank.scan(st0, events)
            jax.block_until_ready(out.count)
            tbest = min(tbest, time.perf_counter() - t0)
        total = N * K * T
        tcount = np.asarray(out.count)
        tstage, toff = np.asarray(out.stage), np.asarray(out.off)
        tcounters = bank.counters(st)
        stats = bank.bank.stats
        del st0, st, out

        fused_qevps = None
        speedup = None
        parity = None
        zero = all(v == 0 for v in tcounters.values())
        if N <= fused_max:
            t0 = time.perf_counter()
            naive = StackedBankMatcher(patterns, K, cfg)
            ns0 = naive.init_state()
            ns, nout = naive.scan(ns0, events)
            jax.block_until_ready(nout.count)
            nv_compile = time.perf_counter() - t0
            nbest = float("inf")
            for _ in range(reps):
                t0 = time.perf_counter()
                ns, nout = naive.scan(ns0, events)
                jax.block_until_ready(nout.count)
                nbest = min(nbest, time.perf_counter() - t0)
            parity = (
                np.array_equal(tcount, np.asarray(nout.count))
                and np.array_equal(tstage, np.asarray(nout.stage))
                and np.array_equal(toff, np.asarray(nout.off))
            )
            ncounters = naive.counters(ns)
            zero = zero and all(v == 0 for v in ncounters.values())
            fused_qevps = total / nbest
            speedup = nbest / tbest
            all_parity &= bool(parity)
            del naive, ns0, ns, nout
        all_zero &= bool(zero)
        log(
            f"tenants[N={N}] ({N} queries x {K} lanes x {T} events, "
            f"{stats['prefix_columns_distinct']}/"
            f"{stats['prefix_columns_total']} distinct prefix columns, "
            f"dedup {stats['pred_dedup_ratio']:.1f}x): shared-screen "
            f"{total / tbest / 1e3:.0f}K q-ev/s (compile {tb_compile:.1f}s)"
            + (
                f", naive-fused {fused_qevps / 1e3:.0f}K q-ev/s, "
                f"speedup {speedup:.2f}x, parity={parity}, zero={zero}"
                if fused_qevps is not None
                else f", naive-fused skipped (N > {fused_max})"
            )
        )
        sweep[str(N)] = {
            "shared_qevps": round(total / tbest, 1),
            "fused_qevps": (
                round(fused_qevps, 1) if fused_qevps else None
            ),
            "speedup": round(speedup, 3) if speedup else None,
            "match_slots": int((tcount > 0).sum()),
            "match_parity": parity,
            "counters_zero": bool(zero),
            "prefix_columns_distinct": stats["prefix_columns_distinct"],
            "prefix_columns_total": stats["prefix_columns_total"],
            "prefix_shared_hit_rate": round(
                float(stats["prefix_shared_hit_rate"]), 4
            ),
            "pred_dedup_ratio": round(
                float(stats["pred_dedup_ratio"]), 3
            ),
        }
    return {
        "k": K, "t": T, "pool": pool_n,
        "sweep": sweep,
        # The gate flags: parity/loss over every N that ran the fused
        # baseline (bench_gate flattens these to tenant_*).
        "match_parity": bool(all_parity),
        "counters_zero": bool(all_zero),
    }


def bench_sharded_folds(K, T, reps):
    """BASELINE.json config 4: WITHIN window + fold(avg,volume) predicates
    over ~1M key lanes, sharded over the available mesh (one chip here;
    the sharding layer is the same shard_map program that lays lanes over
    a v5e-8 — stderr-reported secondary)."""
    from kafkastreams_cep_tpu.parallel import ShardedMatcher, key_mesh

    rng = np.random.default_rng(17)
    prices = rng.integers(90, 131, size=(K, T)).astype(np.int32)
    volumes = rng.integers(600, 1101, size=(K, T)).astype(np.int32)
    host_events = EventBatch(
        key=jnp.broadcast_to(jnp.arange(K, dtype=jnp.int32)[:, None], (K, T)),
        value={"price": jnp.asarray(prices), "volume": jnp.asarray(volumes)},
        ts=jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32)[None, :] * 2, (K, T)),
        off=jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32)[None, :], (K, T)),
        valid=jnp.ones((K, T), bool),
    )
    # Round 4 ran this line with dewey_depth=8 and carried 222K
    # ver_overflows (straddling runs append a version digit per event,
    # NFA.java:185-188) plus assorted capacity drops.  The config is now
    # DERIVED from a 128-lane probe of the same trace so the measured
    # number is overflow- and capacity-drop-free.
    if os.environ.get("CEP_BENCH_AUTOSIZE", "1") != "0":
        # 512-lane sample: a 128-lane probe missed a rare pointer-width
        # peak at 32768 lanes (slab_pred_drops 2 in 524K events); rare
        # maxima need a sample big enough to contain them.
        sample = jax.tree_util.tree_map(lambda x: x[:min(K, 512)], host_events)
        cfg = autosize(
            stock_demo.stock_pattern(), sample,
            start=EngineConfig(max_runs=8, slab_entries=16, slab_preds=4,
                               dewey_depth=24, max_walk=8),
            margin=1.5, sweep_every=T,
        )
        log(f"sharded-folds: autosized config {cfg}")
    else:
        cfg = EngineConfig(
            max_runs=8, slab_entries=16, slab_preds=4, dewey_depth=24,
            max_walk=8,
        )
    mesh = key_mesh()
    m = ShardedMatcher(stock_demo.stock_pattern(), K, mesh, cfg)
    state0 = m.init_state()
    events = m.shard_events(host_events)
    t0 = time.perf_counter()
    state, out = m.scan(state0, events)
    jax.block_until_ready(out.count)
    log(f"sharded-folds: compile+first scan {time.perf_counter() - t0:.1f}s "
        f"on mesh {mesh.devices.shape}")
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        state, out = m.scan(state0, events)
        jax.block_until_ready(out.count)
        best = min(best, time.perf_counter() - t0)
    from kafkastreams_cep_tpu.utils.metrics import device_memory_stats

    stats = m.stats(state)
    capacity_zero = not any(capacity_counters(stats).values())
    log(
        f"sharded folds+window ({K} lanes x {T} events, "
        f"{mesh.devices.size} device(s)): {K * T / best / 1e3:.0f}K ev/s, "
        f"capacity_zero={capacity_zero}, stats {stats}, "
        f"hbm {device_memory_stats()}"
    )
    return K * T / best


def phase_latency_block(snap):
    """Per-phase p50/p99 milliseconds out of a ``metrics_snapshot()``'s
    ``phases`` histograms — the headline JSON's tail-behavior block (the
    BENCH trajectory previously captured throughput only)."""
    out = {}
    for name, h in sorted(snap.get("phases", {}).items()):
        if h["count"]:
            out[name] = {
                "count": h["count"],
                "p50_ms": round(h["p50"] * 1e3, 3),
                "p99_ms": round(h["p99"] * 1e3, 3),
            }
    return out


def bench_processor(K, T, n_batches):
    """Processor-level throughput at the headline config (SURVEY §2.2 PP
    row): columnar ingestion + pipelined dispatch + compacted decode.
    The gap to the engine-level rate is the host runtime's overhead —
    round 4 paid pack + full-grid pull + sync serially on every batch.
    Returns ``(events/s, per-phase p50/p99 block)``."""
    from kafkastreams_cep_tpu.runtime import CEPProcessor

    cfg = EngineConfig(
        max_runs=24, slab_entries=48, slab_preds=8, dewey_depth=12,
        max_walk=12,
    )
    proc = CEPProcessor(
        stock_demo.stock_pattern(), K, cfg, epoch=0, pipeline=True,
        decode_budget=int(os.environ.get("CEP_BENCH_DECODE_BUDGET", "131072")),
    )
    rng = np.random.default_rng(23)
    N = K * T
    keys = np.tile(np.arange(K, dtype=np.int64), T)
    prices = rng.integers(90, 131, size=N).astype(np.int64)
    # Calibrated to ~1% match rate (0.5% begin spikes over a sub-
    # threshold base; the converging avg fold otherwise keeps every begun
    # lineage matching repeatedly — the headline trace's 139% match rate
    # measures Python match-object materialization, not the pipeline.
    # Every emitted match is a contractual host Sequence either way; this
    # line is about transport/packing/decode overlap, and the
    # engine-vs-oracle numbers cover matching cost).
    volumes = np.where(
        rng.random(N) < 0.005, 1100, rng.integers(700, 1000, size=N)
    ).astype(np.int64)

    def feed(b):
        ts = np.int64(b) * N + np.arange(N, dtype=np.int64)
        return proc.process_columns(
            keys, {"price": prices, "volume": volumes}, ts
        )

    t0 = time.perf_counter()  # host-timed (decode device_gets materialize)
    feed(0)
    proc.flush()
    log(f"processor: compile+first batch {time.perf_counter() - t0:.1f}s")
    t0 = time.perf_counter()  # host-timed (decode device_gets materialize)
    n_matches = 0
    for b in range(1, n_batches + 1):
        n_matches += len(feed(b))
    n_matches += len(proc.flush())
    dt = time.perf_counter() - t0
    snap = proc.metrics_snapshot(per_lane=False)
    phases = phase_latency_block(snap)
    log(
        f"processor (pipelined columnar, {K} lanes x {T} ev x "
        f"{n_batches} batches): {n_batches * N / dt / 1e3:.0f}K ev/s "
        f"end-to-end, {n_matches} matches, decode_fallbacks "
        f"{snap['decode_fallbacks']}, wall {dt:.2f}s (pipelined sections "
        f"overlap: device {snap['device_seconds']:.2f}s + decode "
        f"{snap['decode_seconds']:.2f}s measured independently; on this "
        "environment each batch pays a ~4s tunnel round-trip floor — "
        "bare engine rate on the same trace is ~1.6M ev/s)"
    )
    log(f"processor: per-phase latency {json.dumps(phases)}")
    return n_batches * N / dt, phases


def bench_metrics(K, T, n_batches, jsonl=None):
    """``CEP_BENCH_METRICS=1``: the headline stock config run under the
    full telemetry pipeline — JSONL trace sink + Reporter cadence +
    Prometheus rendering — printing the per-phase p50/p99 block.  Kept as
    a plain function over (K, T, n_batches) so the tier-1 smoke test
    (tests/test_telemetry.py) can drive it at tiny shapes — the extra
    cannot silently rot.  Returns ``(phase block, events written)``."""
    import io

    from kafkastreams_cep_tpu.runtime import CEPProcessor
    from kafkastreams_cep_tpu.utils.telemetry import (
        JsonlTraceSink,
        Reporter,
        render_prometheus,
    )

    cfg = EngineConfig(
        max_runs=24, slab_entries=48, slab_preds=8, dewey_depth=12,
        max_walk=12,
    )
    buf = jsonl if jsonl is not None else io.StringIO()
    sink = JsonlTraceSink(buf)
    proc = CEPProcessor(
        stock_demo.stock_pattern(), K, cfg, epoch=0, trace_sink=sink,
    )
    reporter = Reporter(
        proc.metrics_snapshot, sink,
        every_batches=max(n_batches // 2, 1),
    )
    rng = np.random.default_rng(31)
    N = K * T
    keys = np.tile(np.arange(K, dtype=np.int64), T)
    for b in range(n_batches):
        prices = rng.integers(90, 131, size=N).astype(np.int64)
        volumes = rng.integers(600, 1101, size=N).astype(np.int64)
        ts = np.int64(b) * N + np.arange(N, dtype=np.int64)
        proc.process_columns(keys, {"price": prices, "volume": volumes}, ts)
        reporter.tick()
    snap = reporter.flush()
    block = phase_latency_block(snap)
    n_events = (
        buf.getvalue().count("\n") if isinstance(buf, io.StringIO) else None
    )
    log(
        f"metrics ({K} lanes x {T} ev x {n_batches} batches under the "
        f"Reporter): {reporter.flushes} snapshot flushes, "
        f"{n_events} JSONL events; per-phase latency {json.dumps(block)}"
    )
    prom = render_prometheus(snap)
    log(
        f"metrics: prometheus exposition {len(prom.splitlines())} lines "
        f"(e.g. {prom.splitlines()[0]!r})"
    )
    return block, n_events


def bench_resilience():
    """Supervisor fault-path latencies (ISSUE 2: track them across PRs).

    Three numbers, all wall-clock on this environment:

    * ``checkpoint_s`` — one full snapshot (state device_get + pickle);
    * ``recover_s``    — one restore-and-replay cycle (checkpoint restore,
      which recompiles the matcher, + journal-tail replay);
    * ``escalate_s``   — one capacity escalation end-to-end: rollback,
      live-state migration onto the wider config (another compile),
      post-escalation snapshot, and the re-processed batch.

    Both recovery and escalation are compile-dominated: each builds a
    fresh matcher, so the persistent compilation cache is the main lever
    (PROFILE_r06.md context).  Sizes kept small — these are latency
    probes, not throughput lines.
    """
    import shutil
    import tempfile

    from kafkastreams_cep_tpu.engine.sizing import EscalationPolicy
    from kafkastreams_cep_tpu.runtime import Record, Supervisor

    workdir = tempfile.mkdtemp(prefix="cep_bench_resil_")
    out = {}
    try:
        K = int(os.environ.get("CEP_BENCH_RESIL_K", "64"))
        n_batches = 4
        batch_records = int(os.environ.get("CEP_BENCH_RESIL_B", "512"))
        cfg = EngineConfig(
            max_runs=24, slab_entries=48, slab_preds=8, dewey_depth=12,
            max_walk=12,
        )
        rng = np.random.default_rng(5)

        def mk_batch(b, spike=0.005):
            n = batch_records
            keys = rng.integers(0, K, size=n)
            prices = rng.integers(90, 131, size=n)
            vols = np.where(
                rng.random(n) < spike, 1100, rng.integers(700, 1000, size=n)
            )
            return [
                Record(
                    int(keys[i]),
                    {"price": int(prices[i]), "volume": int(vols[i])},
                    b * n + i,
                )
                for i in range(n)
            ]

        sup = Supervisor(
            stock_demo.stock_pattern(), K, cfg, epoch=0,
            checkpoint_path=os.path.join(workdir, "r.ckpt"),
            journal_path=os.path.join(workdir, "r.jrnl"),
            checkpoint_every=10**6,
        )
        for b in range(n_batches):
            sup.process(mk_batch(b))
        t0 = time.perf_counter()  # host-timed (checkpoint device_gets)
        sup.checkpoint()
        out["checkpoint_s"] = round(time.perf_counter() - t0, 3)
        for b in range(n_batches, 2 * n_batches):
            sup.process(mk_batch(b))
        t0 = time.perf_counter()  # host-timed (restore + replay)
        sup._recover()  # restore + replay the n_batches journal tail
        out["recover_s"] = round(time.perf_counter() - t0, 3)

        tiny = EngineConfig(
            max_runs=8, slab_entries=32, slab_preds=4, dewey_depth=12,
            max_walk=12,
        )
        esc = Supervisor(
            stock_demo.stock_pattern(), K, tiny, epoch=0,
            checkpoint_path=os.path.join(workdir, "e.ckpt"),
            checkpoint_every=10**6,
            auto_escalate=EscalationPolicy(max_config=cfg),
        )
        # Match-dense trace (20% begin spikes): run counts overflow
        # max_runs=8 within a few batches.
        esc.process(mk_batch(100, spike=0.2))
        b = 101
        t0 = time.perf_counter()  # host-timed (escalation cycle)
        while esc.escalations == 0 and b < 120:
            t0 = time.perf_counter()  # host-timed (escalation cycle)
            esc.process(mk_batch(b, spike=0.2))
            b += 1
        if esc.escalations:
            out["escalate_s"] = round(time.perf_counter() - t0, 3)
        log(
            f"resilience (K={K}, {batch_records}-record batches): "
            f"checkpoint {out.get('checkpoint_s')}s, recovery "
            f"{out.get('recover_s')}s (restore + {n_batches}-batch "
            f"replay), escalation {out.get('escalate_s')}s (rollback + "
            f"migrate + snapshot + re-process; escalations="
            f"{esc.escalations})"
        )
    finally:
        shutil.rmtree(workdir, ignore_errors=True)
    return out


def bench_shard_fault():
    """``CEP_BENCH_SHARDF``: shard fault tolerance probes (ISSUE 13).

    Two supervisor-level scenarios on a 2-device sub-mesh, each compared
    for match parity against a fault-free single-device run of the same
    stream:

    * **kill one shard** — a ``ShardLost`` out of the meshed dispatch
      mid-stream.  ``evacuate_s`` is the wall-clock of the batch that
      absorbs the loss (rollback + journal replay + re-pin onto the
      surviving sub-mesh + the re-processed batch); ``post_evac_evps``
      is the degraded throughput afterwards.  ``evac_parity`` requires
      exactly-once emission vs the fault-free run.
    * **hot-key rebalance** — a skewed stream (two keys take ~all the
      work, both on shard 0) trips the heavy-hitter policy at a
      checkpoint boundary.  ``rebalance_lossfree`` is the loss
      contract: at least one move happened, zero dropped or duplicated
      matches, capacity counters clean.

    Both flags are guarded by bench_gate.py once recorded.  Returns
    ``{}`` (and the whole block is absent from the JSON) on a
    single-device host.
    """
    import shutil
    import tempfile

    from kafkastreams_cep_tpu.parallel import ShardLost, key_mesh
    from kafkastreams_cep_tpu.runtime import (
        CEPProcessor,
        Record,
        ShardPolicy,
        Supervisor,
    )
    from kafkastreams_cep_tpu.utils import failpoints as fp

    if jax.device_count() < 2:
        log("shard-fault: skipped (needs >= 2 devices)")
        return {}

    K = int(os.environ.get("CEP_BENCH_SHARDF_K", "16"))
    batch_records = int(os.environ.get("CEP_BENCH_SHARDF_B", "256"))
    n_batches = 6
    cfg = EngineConfig(
        max_runs=24, slab_entries=48, slab_preds=8, dewey_depth=12,
        max_walk=12,
    )
    rng = np.random.default_rng(7)

    def mk_batches(n, offs, skew=False):
        # Explicit per-key offsets: rollback + journal replay must dedup
        # re-presented records, and auto offsets would double-emit.
        # ``skew``: batch 0 touches every lane round-robin (pinning key i
        # to lane i, so keys 0/1 share shard 0), later batches hit only
        # keys 0 and 1.
        out_b = []
        for i in range(n):
            recs = []
            for j in range(batch_records):
                if skew:
                    k = int(rng.integers(2)) if i else (j % K)
                else:
                    k = int(rng.integers(K))
                vol = 1100 if rng.random() < 0.01 else int(
                    rng.integers(700, 1000)
                )
                recs.append(Record(
                    k,
                    {"price": int(rng.integers(90, 131)), "volume": vol},
                    1000 + batch_records * i + j,
                    offset=offs.setdefault(k, 0),
                ))
                offs[k] += 1
            out_b.append(recs)
        return out_b

    def canon(matches):
        return sorted(
            (k, tuple(sorted(
                (stage, tuple(e.offset for e in evs))
                for stage, evs in seq.as_map().items()
            )))
            for k, seq in matches
        )

    def oracle(batches):
        proc = CEPProcessor(
            stock_demo.stock_pattern(), K, cfg, gc_interval=0
        )
        out_m = []
        for b in batches:
            out_m += proc.process(b)
        return canon(out_m + proc.flush())

    out = {}
    workdir = tempfile.mkdtemp(prefix="cep_bench_shardf_")
    try:
        batches = mk_batches(n_batches, {})
        sup = Supervisor(
            stock_demo.stock_pattern(), K, cfg,
            checkpoint_path=os.path.join(workdir, "s.ckpt"),
            journal_path=os.path.join(workdir, "s.jrnl"),
            checkpoint_every=2, gc_interval=0,
            mesh=key_mesh(jax.devices()[:2]),
        )
        got = []
        for b in batches[:2]:
            got += sup.process(b)
        t0 = time.perf_counter()  # host-timed (evacuation + re-process)
        with fp.FAILPOINTS.session(
            {"shard.dispatch": [0]},
            exc=lambda: ShardLost("bench-injected device loss", shard=1),
        ):
            got += sup.process(batches[2])
        out["evacuate_s"] = round(time.perf_counter() - t0, 3)
        t0 = time.perf_counter()  # host-timed (degraded throughput)
        for b in batches[3:]:
            got += sup.process(b)
        post_s = time.perf_counter() - t0
        got += sup.processor.flush()
        out["post_evac_evps"] = round(
            batch_records * (n_batches - 3) / post_s, 1
        )
        out["evac_parity"] = bool(
            sup.evacuations == 1 and canon(got) == oracle(batches)
        )

        skew = mk_batches(n_batches, {}, skew=True)
        sup2 = Supervisor(
            stock_demo.stock_pattern(), K, cfg,
            checkpoint_path=os.path.join(workdir, "r.ckpt"),
            journal_path=os.path.join(workdir, "r.jrnl"),
            checkpoint_every=2, gc_interval=0,
            mesh=key_mesh(jax.devices()[:2]),
            shard_policy=ShardPolicy(
                rebalance_skew=1.2, rebalance_min_hops=8,
                rebalance_streak=1, rebalance_cooldown=0,
            ),
        )
        got2 = []
        for b in skew:
            got2 += sup2.process(b)
        got2 += sup2.processor.flush()
        out["rebalance_moves"] = int(sup2.rebalances)
        out["rebalance_lanes_moved"] = int(sup2.lanes_moved)
        ph = sup2.metrics_snapshot(per_lane=False)["phases"].get(
            "rebalance"
        )
        if ph and ph.get("count"):
            out["rebalance_s"] = round(float(ph["p50"]), 3)
        out["rebalance_lossfree"] = bool(
            sup2.rebalances >= 1
            and not any(sup2.processor.counters().values())
            and canon(got2) == oracle(skew)
        )
        log(
            f"shard-fault (K={K}, {batch_records}-record batches): "
            f"evacuate {out['evacuate_s']}s (parity="
            f"{out['evac_parity']}), post-evacuation "
            f"{out['post_evac_evps']} events/s, rebalance moves="
            f"{out['rebalance_moves']} lanes={out['rebalance_lanes_moved']}"
            f" (lossfree={out['rebalance_lossfree']})"
        )
    finally:
        shutil.rmtree(workdir, ignore_errors=True)
    return out


def bench_tenant_iso():
    """``CEP_BENCH_TENANT_ISO``: per-tenant isolation probes (ISSUE 17).

    One tenant floods the bank — a promote-every-pair prefix whose
    suffix never closes, the run-queue-exhausting worst case — while
    compliant tenants run a normal workload with the flooder's quota
    enforced (``match_rate_budget=0``: every one of its prefix fires is
    shed at the shared screen).

    * ``clean_evps`` / ``flooded_evps`` — compliant-workload record
      throughput without and with the quota-limited flooding tenant;
    * ``shed_fires`` — the flooder's screen sheds (must be > 0 or the
      scenario was vacuous);
    * ``quarantine_s`` — quarantine-entry latency: the enforcement
      rebuild (column gating + fresh screen jit) plus the first batch
      dispatched with the tenant dark;
    * ``parity`` — compliant tenants' matches bit-equal to a bank that
      never contained the flooder (the blast-radius contract,
      guarded by bench_gate once recorded);
    * ``compliant_lossfree`` — compliant tenants shed nothing: zero
      ``quota_shed`` and zero capacity-loss counters.
    """
    from kafkastreams_cep_tpu import Query
    from kafkastreams_cep_tpu.compiler.multitenant import TenantQuota
    from kafkastreams_cep_tpu.runtime import Record
    from kafkastreams_cep_tpu.runtime.tenant import TenantCEP

    K = int(os.environ.get("CEP_BENCH_TENANT_ISO_K", "64"))
    n_batches = int(os.environ.get("CEP_BENCH_TENANT_ISO_BATCHES", "6"))
    batch_records = int(os.environ.get("CEP_BENCH_TENANT_ISO_B", "2048"))
    # Sized so the COMPLIANT workload is loss-free (the lossfree flag is
    # about isolation, not capacity): the flooder never reaches the
    # engine — its pressure lands on the shared screen and is shed there.
    cfg = EngineConfig(
        max_runs=16, slab_entries=64, slab_preds=8, dewey_depth=128,
        max_walk=8,
    )

    def _ge(th):
        return lambda k, v, ts, st, th=th: v["x"] >= th

    def _lt(th):
        return lambda k, v, ts, st, th=th: v["x"] < th

    def q3(a, b, c):
        return (
            Query()
            .select("a").where(_ge(a)).then()
            .select("b").where(_lt(b)).then()
            .select("c").where(_ge(c)).build()
        )

    def qh(a, b, z):
        return (
            Query()
            .select("a").where(_ge(a)).then()
            .select("b").where(_lt(b)).then()
            .select("z").skip_till_next_match().where(_ge(z)).build()
        )

    def compliant_patterns():
        return {"spike": q3(8, 3, 7), "dip": qh(8, 3, 9)}

    def flooded_patterns():
        out = compliant_patterns()
        out["flood"] = qh(0, 10, 99)  # fires every pair, never closes
        return out

    rng = np.random.default_rng(17)
    per_lane = max(batch_records // K, 2)
    ts = 0
    bs = []
    for _ in range(n_batches + 1):  # +1: the quarantine-entry batch
        recs = []
        for i in range(per_lane * K):
            ts += 1
            recs.append(
                Record(i % K, {"x": int(rng.integers(0, 10))}, ts)
            )
        bs.append(recs)

    def canon(matches):
        return [
            (qn, k, tuple(sorted(
                (st, e.partition, e.offset)
                for st, evs in seq.as_map().items()
                for e in evs
            )))
            for qn, k, seq in matches
        ]

    out = {}
    clean = TenantCEP(compliant_patterns(), K, cfg)
    clean.process(bs[0])  # warm the compile before timing
    t0 = time.perf_counter()  # host-timed (compliant-only throughput)
    clean_m = [canon(clean.process(b)) for b in bs[1:n_batches]]
    dt = time.perf_counter() - t0
    out["clean_evps"] = round(per_lane * K * (n_batches - 1) / dt, 1)

    flooded = TenantCEP(
        flooded_patterns(), K, cfg,
        quotas={"flood": TenantQuota(match_rate_budget=0.0)},
    )
    flooded.process(bs[0])
    t0 = time.perf_counter()  # host-timed (1 flooding tenant, quotaed)
    fl_m = [canon(flooded.process(b)) for b in bs[1:n_batches]]
    dt = time.perf_counter() - t0
    out["flooded_evps"] = round(per_lane * K * (n_batches - 1) / dt, 1)

    pq = flooded.per_query_counters()
    out["shed_fires"] = pq["flood"]["quota_shed"]

    t0 = time.perf_counter()  # host-timed (rebuild + first dark batch)
    flooded.quarantine("flood", "bench")
    q_m = canon(flooded.process(bs[n_batches]))
    out["quarantine_s"] = round(time.perf_counter() - t0, 3)
    clean_q = canon(clean.process(bs[n_batches]))

    compliant = lambda ms: [m for m in ms if m[0] != "flood"]
    out["parity"] = bool(
        [compliant(m) for m in fl_m] == clean_m
        and compliant(q_m) == clean_q
    )
    out["compliant_lossfree"] = bool(
        out["shed_fires"] > 0
        and all(
            pq[n]["quota_shed"] == 0
            and all(pq[n][c] == 0 for c in (
                "run_drops", "ver_overflows", "slab_full_drops",
                "slab_pred_drops", "slab_trunc", "handle_overflows",
            ))
            for n in ("spike", "dip")
        )
    )
    log(
        f"tenant-iso (K={K}, {per_lane * K}-record batches): compliant "
        f"{out['clean_evps']} ev/s clean vs {out['flooded_evps']} ev/s "
        f"with a quota-limited flooder ({out['shed_fires']} fires shed), "
        f"quarantine entry {out['quarantine_s']}s, parity="
        f"{out['parity']}, compliant_lossfree={out['compliant_lossfree']}"
    )
    return out


def bench_latency():
    """``CEP_BENCH_LATENCY``: end-to-end latency attribution (ISSUE 18).

    The segment ledger on the record-path processor, three ways:

    * **Ledger A/B** — the same in-order stream with the ledger off vs
      on: matches and loss counters must stay bit-identical
      (``parity``, guarded by bench_gate once recorded) and the
      host-side stamping cost is reported (``ledger_overhead_pct``);
    * **Drain-cadence A/B** — ``drain_interval`` 1 vs ``D`` under lazy
      extraction: deferral trades emit latency (the ``drain_defer``
      segment) for fewer device_get round-trips, and the ledger makes
      the trade visible per segment instead of folded into e2e;
    * **Reorder-grace A/B** — watermark guard with grace 0 vs ``G`` ms
      on the same in-order stream: the grace window surfaces as
      ``reorder_hold`` p99, the latency price of skew tolerance.

    ``e2e_p99_s`` (the ledgered baseline's end-to-end p99) joins
    bench_gate as a lower-is-better ceiling.  Record-path rates are
    host-bound (µs/record Python), so the overhead number is relative,
    like bench_ooo's.  ``CEP_BENCH_LATENCY_{K,B,BATCHES,GRACE,DRAIN,RING}``
    size it.
    """
    from kafkastreams_cep_tpu.runtime import CEPProcessor, IngestPolicy, Record
    from kafkastreams_cep_tpu.utils.latency import LatencyLedger

    K = int(os.environ.get("CEP_BENCH_LATENCY_K", "64"))
    n_batches = int(os.environ.get("CEP_BENCH_LATENCY_BATCHES", "8"))
    batch_records = int(os.environ.get("CEP_BENCH_LATENCY_B", "2048"))
    grace = int(os.environ.get("CEP_BENCH_LATENCY_GRACE", "64"))
    drain = int(os.environ.get("CEP_BENCH_LATENCY_DRAIN", "8"))
    cfg = EngineConfig(
        max_runs=24, slab_entries=48, slab_preds=8, dewey_depth=12,
        max_walk=12,
    )
    # The cadence A/B runs BOTH sides on this config so only
    # drain_interval differs: deferral parks completed chains and match
    # handles until the drain, so it needs slab headroom (2x, like the
    # lazy A/B's default) and a ring sized for `drain` batches of
    # handles — otherwise the comparison measures drop policy, not
    # scheduling, and parity stops meaning "cadence is pure scheduling".
    lazy_cfg = EngineConfig(
        max_runs=24, slab_entries=96, slab_preds=8, dewey_depth=12,
        max_walk=12, lazy_extraction=True,
        handle_ring=int(os.environ.get("CEP_BENCH_LATENCY_RING", "512")),
    )
    rng = np.random.default_rng(18)
    N = n_batches * batch_records
    keys = rng.integers(0, K, size=N)
    prices = rng.integers(90, 131, size=N)
    vols = np.where(
        rng.random(N) < 0.005, 1100, rng.integers(700, 1000, size=N)
    )
    ts = np.arange(N, dtype=np.int64) * 2  # distinct event times
    recs = [
        Record(
            int(keys[i]),
            {"price": int(prices[i]), "volume": int(vols[i])},
            int(ts[i]),
            offset=i,
        )
        for i in range(N)
    ]

    def canon(matches):
        # Emission order differs across drain cadences (deferred matches
        # flush late) and the ingest guard renumbers offsets per lane, so
        # parity compares the sorted canonical set keyed by event time —
        # globally distinct in this stream by construction.
        return sorted(
            (k, tuple(sorted(
                (st, e.timestamp)
                for st, evs in seq.as_map().items()
                for e in evs
            )))
            for k, seq in matches
        )

    def run(policy, drain_interval, config, ledger):
        proc = CEPProcessor(
            stock_demo.stock_pattern(), K, config, epoch=0, ingest=policy,
            drain_interval=drain_interval, latency=ledger,
        )
        warm = min(2, n_batches - 1)
        matches = []
        for b in range(warm):
            matches += proc.process(
                recs[b * batch_records:(b + 1) * batch_records]
            )
        t0 = time.perf_counter()  # host-timed (record path is host-bound)
        for b in range(warm, n_batches):
            matches += proc.process(
                recs[b * batch_records:(b + 1) * batch_records]
            )
        matches += proc.drain_ingest()
        matches += proc.flush()
        dt = time.perf_counter() - t0
        return proc, canon(matches), (n_batches - warm) * batch_records / dt

    def segs(proc):
        snap = proc.ledger.snapshot()["segments"]
        return {
            name: {
                "count": s["count"],
                "p50_s": round(s["p50"], 6),
                "p99_s": round(s["p99"], 6),
            }
            for name, s in snap.items() if s["count"]
        }

    out = {"records": N, "grace_ms": grace, "drain_interval": drain}
    p_off, m_off, evps_off = run(None, 1, cfg, None)
    p_on, m_on, evps_on = run(None, 1, cfg, LatencyLedger())
    out["parity"] = bool(
        m_off == m_on and p_off.counters() == p_on.counters()
    )
    out["matches"] = len(m_on)
    out["evps_ledger_off"] = round(evps_off, 1)
    out["evps_ledger_on"] = round(evps_on, 1)
    out["ledger_overhead_pct"] = round(100 * (1 - evps_on / evps_off), 1)
    base = segs(p_on)
    out["segments"] = base
    out["e2e_p99_s"] = base["e2e_total"]["p99_s"]

    p_d1, m_d1, _ = run(None, 1, lazy_cfg, LatencyLedger())
    p_dn, m_dn, _ = run(None, drain, lazy_cfg, LatencyLedger())
    out["drain_ab"] = {
        "interval_1": segs(p_d1),
        f"interval_{drain}": segs(p_dn),
    }
    p_g0, m_g0, _ = run(IngestPolicy(grace_ms=0), 1, cfg, LatencyLedger())
    p_gg, m_gg, _ = run(
        IngestPolicy(grace_ms=grace), 1, cfg, LatencyLedger()
    )
    out["grace_ab"] = {
        "grace_0": segs(p_g0),
        f"grace_{grace}": segs(p_gg),
    }
    # Within one engine config, cadence and grace change batching and
    # timing, never the match set: the guard releases the sorted stream
    # and the final flush drains every deferral.  (Across configs the
    # slab headroom itself shifts the drop policy, so the eager and lazy
    # sides are not compared to each other.)
    out["ab_match_parity"] = bool(
        m_d1 == m_dn and m_on == m_g0 == m_gg
    )
    log(
        f"latency ({N} records, {K} lanes): ledger overhead "
        f"{out['ledger_overhead_pct']}% ({out['evps_ledger_off']} -> "
        f"{out['evps_ledger_on']} ev/s), parity={out['parity']}, e2e p99 "
        f"{out['e2e_p99_s']}s; drain 1 vs {drain} defer p99 "
        f"{segs(p_dn).get('drain_defer', {}).get('p99_s')}s; grace 0 vs "
        f"{grace} ms hold p99 "
        f"{segs(p_gg).get('reorder_hold', {}).get('p99_s')}s; "
        f"ab_match_parity={out['ab_match_parity']}"
    )
    return out


def bench_overload():
    """``CEP_BENCH_OVERLOAD``: brownout ladder under flood (ISSUE 20).

    A dense flood (every record held by the watermark guard: the
    event-time pressure signal saturates) drives the ladder L1→L4, then
    a sparse subside tail lets it recover.  The same stream runs twice
    through the supervised record path:

    * **controller OFF** — the unprotected baseline: everything is
      admitted, the reorder buffer evicts past its depth (order loss),
      and the batch-time tail stretches with the backlog;
    * **controller ON** — the ladder sheds at the door as typed
      ``overload_shed`` dead letters; reported: admitted-goodput, the
      shed fraction, the batch-time p99 while browned out, and how many
      subside batches the ladder needs to step back to L0.

    Flags for bench_gate: ``ledger_reconciles`` (``offered == admitted
    + overload_shed + late_dropped + quarantined``, exactly — auditable
    shedding, nothing silent) and ``recovers`` (final level 0, zero
    failed transitions).  Record-path rates are host-bound
    (µs/record Python), so the off/on comparison is relative, like
    bench_ooo's.  ``CEP_BENCH_OVERLOAD_{K,B,BATCHES,SUB,DEPTH}`` size it.
    """
    import shutil
    import tempfile

    from kafkastreams_cep_tpu.runtime import Record, Supervisor
    from kafkastreams_cep_tpu.runtime.ingest import IngestPolicy
    from kafkastreams_cep_tpu.runtime.overload import OverloadPolicy

    K = int(os.environ.get("CEP_BENCH_OVERLOAD_K", "64"))
    n_batches = int(os.environ.get("CEP_BENCH_OVERLOAD_BATCHES", "8"))
    batch_records = int(os.environ.get("CEP_BENCH_OVERLOAD_B", "2048"))
    subside = int(os.environ.get("CEP_BENCH_OVERLOAD_SUB", "24"))
    depth = int(os.environ.get("CEP_BENCH_OVERLOAD_DEPTH", "4096"))
    cfg = EngineConfig(
        max_runs=24, slab_entries=48, slab_preds=8, dewey_depth=12,
        max_walk=12,
    )
    # Event-time-driven policy (the wall-clock signals are neutralized):
    # pressure = reorder-hold occupancy / hold_ref, so the ladder
    # trajectory is a pure function of the record stream — the same
    # deterministic setup the overload test suite proves against.
    policy = OverloadPolicy(
        burn_ref=1e9, queue_ref=1e9, ring_ref=1e9, hold_age_ref=1e9,
        hold_ref=0.05, enter_streak=1, exit_streak=2,
    )
    rng = np.random.default_rng(20)
    N = n_batches * batch_records
    grace = N  # ts advance +1/record: the whole flood sits in the window
    keys = rng.integers(0, K, size=N)
    prices = rng.integers(90, 131, size=N)
    vols = np.where(
        rng.random(N) < 0.005, 1100, rng.integers(700, 1000, size=N)
    )
    recs = [
        Record(
            int(keys[i]),
            {"price": int(prices[i]), "volume": int(vols[i])},
            i + 1,
            offset=i,
        )
        for i in range(N)
    ]
    sub_recs = [
        Record(
            int(rng.integers(0, K)), {"price": 100, "volume": 800},
            N + 1 + (j + 1) * 2 * grace, offset=N + j,
        )
        for j in range(subside)
    ]

    def run(overload):
        tmp = tempfile.mkdtemp(prefix="cep-bench-ovl-")
        try:
            kw = dict(
                checkpoint_path=os.path.join(tmp, "b.ckpt"),
                journal_path=os.path.join(tmp, "b.jrnl"),
                checkpoint_every=100, gc_interval=0,
                ingest=IngestPolicy(grace_ms=grace, reorder_depth=depth),
            )
            if overload:
                kw["overload_policy"] = policy
            sup = Supervisor(stock_demo.stock_pattern(), K, cfg, **kw)
            batch_s = []
            levels = []
            t0 = time.perf_counter()  # host-timed (record path)
            for b in range(n_batches):
                tb = time.perf_counter()  # host-timed (supervised batch)
                sup.process(
                    recs[b * batch_records:(b + 1) * batch_records]
                )
                batch_s.append(time.perf_counter() - tb)
                if overload:
                    levels.append(sup._overload.level)
            flood_dt = time.perf_counter() - t0
            recovery = None
            for j, r in enumerate(sub_recs):
                sup.process([r])
                if overload and recovery is None \
                        and sup._overload.level == 0:
                    recovery = j + 1
            sup.processor.drain_ingest()
            sup.processor.flush()
            g = sup.processor._guard
            lc = g.loss_counters()
            offered = N + subside
            return {
                "flood_dt": flood_dt,
                "batch_p99_s": float(np.percentile(batch_s, 99)),
                "levels": levels,
                "recovery": recovery,
                "admitted": g.admitted,
                "loss": lc,
                "reconciles": offered == g.admitted
                + lc["overload_shed"] + lc["late_dropped"]
                + lc["quarantined"],
                "transitions": (
                    sup._overload.transitions if overload else 0
                ),
                "transition_failures": (
                    sup._overload.transition_failures if overload else 0
                ),
                "final_level": sup._overload.level if overload else 0,
            }
        finally:
            shutil.rmtree(tmp, ignore_errors=True)

    off = run(False)
    on = run(True)
    out = {
        "records": N + subside,
        "reorder_depth": depth,
        "evps_controller_off": round(N / off["flood_dt"], 1),
        "goodput_controller_on": round(
            (on["admitted"] - subside) / on["flood_dt"], 1
        ),
        "shed": on["loss"]["overload_shed"],
        "shed_pct": round(
            100 * on["loss"]["overload_shed"] / (N + subside), 1
        ),
        "evictions_off": off["loss"]["reorder_evictions"],
        "evictions_on": on["loss"]["reorder_evictions"],
        "batch_p99_off_s": round(off["batch_p99_s"], 4),
        "batch_p99_on_s": round(on["batch_p99_s"], 4),
        "max_level": max(on["levels"]),
        "recovery_batches": on["recovery"],
        "transitions": on["transitions"],
        "ledger_reconciles": bool(on["reconciles"] and off["reconciles"]),
        "recovers": bool(
            on["final_level"] == 0
            and on["recovery"] is not None
            and on["transition_failures"] == 0
        ),
    }
    log(
        f"overload (K={K}, {N} flood records, depth {depth}): "
        f"{out['evps_controller_off']} ev/s unprotected "
        f"({out['evictions_off']} order-loss evictions) vs "
        f"{out['goodput_controller_on']} admitted-ev/s browned out "
        f"({out['shed']} shed = {out['shed_pct']}%, "
        f"{out['evictions_on']} evictions), batch p99 "
        f"{out['batch_p99_off_s']}s -> {out['batch_p99_on_s']}s, "
        f"max level {out['max_level']}, L0 after "
        f"{out['recovery_batches']} subside batches; ledger_reconciles="
        f"{out['ledger_reconciles']}, recovers={out['recovers']}"
    )
    return out


def bench_ooo():
    """``CEP_BENCH_OOO``: graceful-ingestion A/B (ISSUE 5).

    The same record stream three ways through the per-record processor
    path: (a) no guard, in-order — the historical front door; (b) the
    watermark reorder buffer, in-order — the guard's bookkeeping
    overhead; (c) the guard with a bounded-skew (<= grace) shuffled
    arrival — the production case the buffer exists for.  Reports ev/s
    for each, the reorder overhead, match-count parity (all three must
    agree: the release stream is the sorted stream), and the loss
    counters (all-zero ⇒ the shuffle was fully absorbed).

    ``CEP_BENCH_OOO_{K,B,BATCHES,GRACE}`` size it.  Record-path rates are
    host-bound (µs/record Python), so this measures the guard's relative
    cost, not engine throughput — the columnar numbers stay the
    throughput story.
    """
    from kafkastreams_cep_tpu.runtime import CEPProcessor, IngestPolicy, Record

    K = int(os.environ.get("CEP_BENCH_OOO_K", "64"))
    n_batches = int(os.environ.get("CEP_BENCH_OOO_BATCHES", "8"))
    batch_records = int(os.environ.get("CEP_BENCH_OOO_B", "2048"))
    grace = int(os.environ.get("CEP_BENCH_OOO_GRACE", "64"))
    cfg = EngineConfig(
        max_runs=24, slab_entries=48, slab_preds=8, dewey_depth=12,
        max_walk=12,
    )
    rng = np.random.default_rng(17)
    N = n_batches * batch_records
    keys = rng.integers(0, K, size=N)
    prices = rng.integers(90, 131, size=N)
    vols = np.where(
        rng.random(N) < 0.005, 1100, rng.integers(700, 1000, size=N)
    )
    ts = np.arange(N, dtype=np.int64) * 2  # distinct event times
    recs = [
        Record(
            int(keys[i]),
            {"price": int(prices[i]), "volume": int(vols[i])},
            int(ts[i]),
        )
        for i in range(N)
    ]
    skew_key = ts + rng.uniform(0, grace, size=N)
    shuffled = [recs[i] for i in np.argsort(skew_key, kind="stable")]

    def run(records, policy):
        proc = CEPProcessor(
            stock_demo.stock_pattern(), K, cfg, epoch=0, ingest=policy,
        )
        # Two warmup batches: the guard's watermark hold shifts released
        # batch sizes onto different T buckets than the raw path, and the
        # resulting recompiles belong to warmup, not the timed window.
        warm = min(2, n_batches - 1)
        n_matches = 0
        for b in range(warm):
            n_matches += len(
                proc.process(
                    records[b * batch_records:(b + 1) * batch_records]
                )
            )
        t0 = time.perf_counter()  # host-timed (record path is host-bound)
        for b in range(warm, n_batches):
            n_matches += len(
                proc.process(
                    records[b * batch_records:(b + 1) * batch_records]
                )
            )
        n_matches += len(proc.drain_ingest())
        n_matches += len(proc.flush())
        dt = time.perf_counter() - t0
        return proc, (n_batches - warm) * batch_records / dt, n_matches

    _, base_evps, base_m = run(recs, None)
    _, in_evps, in_m = run(recs, IngestPolicy(grace_ms=grace))
    p_sh, sh_evps, sh_m = run(shuffled, IngestPolicy(grace_ms=grace))
    loss = p_sh._guard.loss_counters()
    out = {
        "grace_ms": grace,
        "records": N,
        "evps_no_guard": round(base_evps, 1),
        "evps_guard_inorder": round(in_evps, 1),
        "evps_guard_shuffled": round(sh_evps, 1),
        "reorder_overhead_pct": round(100 * (1 - in_evps / base_evps), 1),
        "shuffled_overhead_pct": round(100 * (1 - sh_evps / base_evps), 1),
        "matches": base_m,
        "match_parity": bool(base_m == in_m == sh_m),
        "loss_counters": loss,
        "loss_free": not any(loss.values()),
    }
    log(
        f"ooo ({N} records, {K} lanes, grace {grace} ms): no-guard "
        f"{base_evps / 1e3:.0f}K ev/s, guard in-order {in_evps / 1e3:.0f}K "
        f"ev/s ({out['reorder_overhead_pct']}% overhead), guard shuffled "
        f"{sh_evps / 1e3:.0f}K ev/s ({out['shuffled_overhead_pct']}% "
        f"overhead); match parity {out['match_parity']} "
        f"({base_m}/{in_m}/{sh_m}), loss counters {loss}"
    )
    return out


def bench_oracle(n_events):
    rng = np.random.default_rng(42)
    prices = rng.integers(90, 131, size=n_events)
    volumes = rng.integers(600, 1101, size=n_events)
    oracle = OracleNFA.from_pattern(stock_demo.stock_pattern())
    t0 = time.perf_counter()  # host-timed (pure-Python oracle loop)
    n_matches = 0
    early_dt = None
    for i in range(n_events):
        n_matches += len(
            oracle.match(
                None,
                {"price": int(prices[i]), "volume": int(volumes[i])},
                2 * i,
                offset=i,
            )
        )
        if i == 499:
            early_dt = time.perf_counter() - t0
    dt = time.perf_counter() - t0
    early = f", first 500 at {500 / early_dt:.0f} ev/s" if early_dt else ""
    log(
        f"oracle: {n_events} events in {dt:.2f}s ({n_events / dt:.0f} ev/s"
        f"{early}; unbounded state grows per event, like the reference), "
        f"{n_matches} matches"
    )
    return n_events / dt


def main():
    t_start = time.perf_counter()  # host-timed (wall budget)
    K = int(os.environ.get("CEP_BENCH_K", "4096"))
    T = int(os.environ.get("CEP_BENCH_T", "256"))
    reps = int(os.environ.get("CEP_BENCH_REPS", "5"))
    # The oracle is faithful to the reference's unbounded-state design, so
    # its per-event cost GROWS on this match-dense trace (measured: 500
    # events in ~1s, 2000 in ~120s cumulative); 1000 events keeps the
    # comparison honest without dominating bench wall time.
    oracle_n = int(os.environ.get("CEP_BENCH_ORACLE_N", "1000"))

    parity_gate()
    bench_stencil(int(os.environ.get("CEP_BENCH_STENCIL_N", "1048576")), reps)
    (engine_evps, engine_spread, engine_counters, recall, precision,
     hot_metrics, lazy_metrics, attr_metrics) = bench_engine(K, T, reps)
    if os.environ.get("CEP_BENCH_LOSSFREE", "1") != "0":
        lf_evps, lf_zero, lf_parity = bench_lossfree(
            int(os.environ.get("CEP_BENCH_LOSSFREE_K", "1024")),
            int(os.environ.get("CEP_BENCH_LOSSFREE_CYCLES", "32")),
            reps,
        )
    else:
        lf_evps, lf_zero, lf_parity = 0.0, None, None
        log("lossfree: skipped (CEP_BENCH_LOSSFREE=0)")
    oracle_evps = bench_oracle(oracle_n)
    # BASELINE.json configs 2-4, stderr-reported; sized via env knobs so
    # smoke runs stay fast (CEP_BENCH_EXTRAS=0 skips them entirely).  Each
    # extra is skipped once the wall budget is spent — compiles through the
    # device tunnel are slow and the headline JSON must always be printed.
    resilience = {}
    proc_phases = {}
    ooo = {}
    tier = {}
    tenants = {}
    adapt = {}
    latency = {}
    overload = {}

    def _shard_fault_block():
        # Nested under ``resilience`` so the JSON groups every
        # fault-path number; absent entirely when skipped (single
        # device or CEP_BENCH_SHARDF=0), which bench_gate treats as a
        # missing metric, not a regression.
        if os.environ.get("CEP_BENCH_SHARDF", "1") != "1":
            log("shard-fault: skipped (CEP_BENCH_SHARDF=0)")
            return {}
        shard = bench_shard_fault()
        return {"shard": shard} if shard else {}

    def _tenant_iso_block():
        # Nested under ``resilience`` like the shard-fault probes:
        # absent entirely when skipped, which bench_gate treats as a
        # missing metric, not a regression.
        if os.environ.get("CEP_BENCH_TENANT_ISO", "1") != "1":
            log("tenant-iso: skipped (CEP_BENCH_TENANT_ISO=0)")
            return {}
        block = bench_tenant_iso()
        return {"tenant": block} if block else {}

    if os.environ.get("CEP_BENCH_EXTRAS", "1") != "0":
        budget = float(os.environ.get("CEP_BENCH_BUDGET_S", "1200"))
        extras = [
            (
                "tier",
                lambda: tier.update(
                    bench_tier()
                    if os.environ.get("CEP_BENCH_TIER", "1") == "1"
                    else {}
                ),
            ),
            (
                "adapt",
                lambda: adapt.update(
                    bench_adapt()
                    if os.environ.get("CEP_BENCH_ADAPT", "1") == "1"
                    else {}
                ),
            ),
            (
                "tenants",
                lambda: tenants.update(
                    bench_tenants()
                    if os.environ.get("CEP_BENCH_TENANTS", "1") == "1"
                    else {}
                ),
            ),
            (
                "ooo",
                lambda: ooo.update(
                    bench_ooo()
                    if os.environ.get("CEP_BENCH_OOO", "1") == "1"
                    else {}
                ),
            ),
            (
                "resilience",
                lambda: resilience.update(bench_resilience()),
            ),
            (
                "shard-fault",
                lambda: resilience.update(_shard_fault_block()),
            ),
            (
                "tenant-iso",
                lambda: resilience.update(_tenant_iso_block()),
            ),
            (
                "latency",
                lambda: latency.update(
                    bench_latency()
                    if os.environ.get("CEP_BENCH_LATENCY", "1") == "1"
                    else {}
                ),
            ),
            (
                "overload",
                lambda: overload.update(
                    bench_overload()
                    if os.environ.get("CEP_BENCH_OVERLOAD", "1") == "1"
                    else {}
                ),
            ),
            (
                "processor",
                # 128 events/lane/batch: this environment's device_get
                # carries a ~1.5s latency floor regardless of size and
                # admits one in-flight execution (tunnel properties,
                # measured — co-located hosts have neither), so the batch
                # must amortize a ~4s fixed round-trip cost; 256 would
                # amortize further but two in-flight [K,T,R,W] outputs
                # exceed HBM.
                lambda: proc_phases.update(
                    bench_processor(
                        int(os.environ.get("CEP_BENCH_PROC_K", str(K))),
                        int(os.environ.get("CEP_BENCH_PROC_T", "128")),
                        int(os.environ.get("CEP_BENCH_PROC_BATCHES", "4")),
                    )[1]
                ),
            ),
            (
                "bank",
                lambda: bench_bank(
                    [
                        int(x) for x in os.environ.get(
                            "CEP_BENCH_BANK_N", "2,8,16"
                        ).split(",")
                    ],
                    int(os.environ.get("CEP_BENCH_BANK_K", "102400")),
                    int(os.environ.get("CEP_BENCH_BANK_T", "64")),
                    max(reps - 1, 1),
                ),
            ),
            (
                "sharded-folds",
                lambda: bench_sharded_folds(
                    # 262144 lanes fit the round-4 hand config; the derived
                    # loss-free config is larger per lane (D=24, MP=16 from
                    # the probe — 65536 lanes still RESOURCE_EXHAUSTED on a
                    # v5e chip shared with earlier extras), so the default
                    # drops to 32768.  Throughput is per-event, not
                    # per-lane-count.
                    int(os.environ.get("CEP_BENCH_SHARD_K", "32768")),
                    int(os.environ.get("CEP_BENCH_SHARD_T", "16")),
                    max(reps - 1, 1),
                ),
            ),
            (
                "kleene",
                lambda: bench_kleene(
                    int(os.environ.get("CEP_BENCH_KLEENE_K", "10240")),
                    int(os.environ.get("CEP_BENCH_KLEENE_T", "64")),
                    max(reps - 1, 1),
                ),
            ),
        ]
        if os.environ.get("CEP_BENCH_METRICS", "0") == "1":
            # Telemetry-pipeline extra (tier-1 smoke-tested at tiny
            # shapes): first so the wall budget can't starve it out when
            # explicitly requested.
            extras.insert(0, (
                "metrics",
                lambda: bench_metrics(
                    int(os.environ.get("CEP_BENCH_METRICS_K", "256")),
                    int(os.environ.get("CEP_BENCH_METRICS_T", "64")),
                    int(os.environ.get("CEP_BENCH_METRICS_BATCHES", "4")),
                ),
            ))
        import gc

        for name, fn in extras:
            if time.perf_counter() - t_start > budget:
                log(f"{name}: skipped (past {budget:.0f}s bench budget)")
                continue
            try:
                fn()
            except Exception as e:  # extras never break the headline line
                log(f"{name} bench failed: {type(e).__name__}: {e}")
            # Drop the extra's device arrays before the next one compiles
            # (a prior extra's live buffers have caused RESOURCE_EXHAUSTED
            # cascades on the shared chip).
            gc.collect()

    print(
        json.dumps(
            {
                # "capacity-bounded": the measured trace sheds state past
                # the configured shapes (counted below + recall measured);
                # the lossfree_* keys carry the zero-counters line.
                "metric": (
                    "events/sec/chip, SASE stock pattern, "
                    f"{K} key lanes x {T}-event scan, capacity-bounded "
                    "(see recall_sampled + counters)"
                ),
                "value": round(engine_evps, 1),
                "unit": "events/s",
                # vs this repo's host oracle — a faithful reimplementation
                # of the reference engine's per-event loop, in the same
                # store-bound throughput class as the Java original
                # (BASELINE.md "derived cost notes"); the reference itself
                # publishes no numbers.
                "vs_baseline": round(engine_evps / oracle_evps, 2),
                "spread_pct": round(engine_spread, 1),
                # Match-space effect of the counted drops, vs the oracle
                # on sampled lanes (None when CEP_BENCH_RECALL_LANES=0).
                "recall_sampled": (
                    round(recall, 4) if recall is not None else None
                ),
                "precision_sampled": (
                    round(precision, 4) if precision is not None else None
                ),
                "counters": engine_counters,
                # Two-tier hot-window run on the same trace/shapes (None
                # when CEP_BENCH_HOT_ENTRIES=0 or the run failed).
                "hot_tier": hot_metrics,
                # Lazy-extraction A/B on the same trace/shapes (ISSUE 4;
                # None when CEP_BENCH_LAZY=0 or the run failed).
                "lazy": lazy_metrics,
                # Per-stage attribution A/B (ISSUE 6): measured overhead
                # of stage_attribution on this headline + the per-stage
                # selectivity/cost table (None when CEP_BENCH_ATTR=0 or
                # the run failed).
                "attribution": attr_metrics,
                "lossfree_evps": round(lf_evps, 1),
                "lossfree_counters_zero": bool(lf_zero),
                "lossfree_oracle_parity": bool(lf_parity),
                # Supervisor fault-path latencies (bench_resilience; None
                # when extras are skipped) — ISSUE 2 asks later PRs to
                # track recovery/escalation cost.
                "resilience": resilience or None,
                # Per-phase p50/p99 end-to-end latency from the processor
                # extra's telemetry histograms (ISSUE 3) — tail behavior,
                # not just throughput (None when extras are skipped).
                "phase_latency": proc_phases or None,
                # Graceful-ingestion A/B (ISSUE 5): in-order vs bounded-
                # skew shuffled arrival through the watermark reorder
                # buffer — reorder overhead, match parity, loss counters
                # (None when extras are skipped or CEP_BENCH_OOO=0).
                "ooo": ooo or None,
                # Compiler-tiering A/B (ISSUE 7): untiered vs tiered on a
                # strict-prefix-dominated match-sparse trace — speedup,
                # screened-event fraction, NFA dispatch fraction, match
                # parity (None when extras skipped or CEP_BENCH_TIER=0).
                "tier": tier or None,
                # Multi-tenant bank sweep (ISSUE 14): N Zipf-overlapping
                # queries, shared stencil screen + deduplicated predicate
                # matrix vs the naive-fused stacked bank — per-N q-ev/s,
                # speedup, match parity, loss flags (None when extras
                # skipped or CEP_BENCH_TENANTS=0).
                "tenants": tenants or None,
                # Adaptive recompilation (ISSUE 16): hybrid sweep under
                # the chunk-gated scan vs BENCH_r06's 2.7-5.2x band +
                # drift A/B (AdaptPolicy replans vs the stale plan) —
                # parity, loss flags, replan count, lazy-chain cost win
                # (None when extras skipped or CEP_BENCH_ADAPT=0).
                "adapt": adapt or None,
                # End-to-end latency attribution (ISSUE 18): per-segment
                # p50/p99 from the ingest->emit ledger, ledger on/off
                # match parity + overhead, drain-cadence and
                # reorder-grace A/Bs (None when extras skipped or
                # CEP_BENCH_LATENCY=0).
                "latency": latency or None,
                # Overload control (ISSUE 20): brownout ladder under
                # flood — goodput with/without the controller, typed
                # shed accounting (ledger_reconciles), brownout
                # batch-time tail, recovery-to-L0 (None when extras
                # skipped or CEP_BENCH_OVERLOAD=0).
                "overload": overload or None,
            }
        ),
        flush=True,
    )


if __name__ == "__main__":
    main()
