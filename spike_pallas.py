"""Mosaic feasibility spike for the fused engine kernel.

Exercises: 4D VMEM arrays, fori/while loops, static-unrolled mid-axis
reductions, triangular-matmul cumsum, masked-min 'first match' selection,
bool masks, per-lane trailing axis layout. Compares against pure-jnp
reference on the real TPU.
"""
import functools
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

E, MP, D, L = 16, 4, 6, 128
R = 8
T = 32


def kernel(ev_ref, stage_ref, pver_ref, out_ref, acc_ref):
    # acc: [R, L] f32 scratch persisting across T loop
    acc_ref[:] = jnp.zeros((R, L), jnp.float32)
    tri = (
        jax.lax.broadcasted_iota(jnp.int32, (R, R), 0)
        >= jax.lax.broadcasted_iota(jnp.int32, (R, R), 1)
    ).astype(jnp.float32)

    def step(t, _):
        ev = ev_ref[t]  # [L] i32
        # 4D elementwise + static-unrolled reduce over D (axis 2)
        pver = pver_ref[:]  # [E, MP, D, L] i32
        eq = (pver == ev[None, None, None, :]).astype(jnp.int32)
        s = jnp.zeros((E, MP, L), jnp.int32)
        for d in range(D):
            s = s + eq[:, :, d, :]
        ok = s > (D // 2)  # [E, MP, L] bool
        # first-match select via masked min over MP (axis 1)
        mp_idx = jax.lax.broadcasted_iota(jnp.int32, (E, MP, L), 1)
        j = jnp.min(jnp.where(ok, mp_idx, MP), axis=1)  # [E, L]
        any_ok = j < MP
        # while loop with scalar cond
        def cond(c):
            i, val = c
            return (i < 4) & (jnp.sum(val) < 1e9)

        def body(c):
            i, val = c
            return i + 1, val * 1.5 + jnp.sum(any_ok.astype(jnp.float32))

        _, w = jax.lax.while_loop(cond, body, (0, jnp.float32(1.0)))
        # cumsum over R via triangular matmul
        x = (stage_ref[:] == (ev % 3)[None, :]).astype(jnp.float32)[:R]  # [R, L]
        csum = jnp.dot(tri, x, preferred_element_type=jnp.float32)  # [R, L]
        acc_ref[:] = acc_ref[:] + csum * w + jnp.sum(j, axis=0)[None, :]
        return 0

    jax.lax.fori_loop(0, T, step, 0)
    out_ref[:] = acc_ref[:]


def ref_impl(ev, stage, pver):
    acc = jnp.zeros((R, L), jnp.float32)
    tri = (
        jax.lax.broadcasted_iota(jnp.int32, (R, R), 0)
        >= jax.lax.broadcasted_iota(jnp.int32, (R, R), 1)
    ).astype(jnp.float32)
    for t in range(T):
        e = ev[t]
        eq = (pver == e[None, None, None, :]).astype(jnp.int32)
        s = eq.sum(axis=2)
        ok = s > (D // 2)
        mp_idx = jax.lax.broadcasted_iota(jnp.int32, (E, MP, L), 1)
        j = jnp.min(jnp.where(ok, mp_idx, MP), axis=1)
        any_ok = j < MP
        i, w = 0, jnp.float32(1.0)
        while i < 4 and float(jnp.sum(w)) < 1e9:
            w = w * 1.5 + jnp.sum(any_ok.astype(jnp.float32))
            i += 1
        x = (stage == (e % 3)[None, :]).astype(jnp.float32)[:R]
        csum = jnp.dot(tri, x)
        acc = acc + csum * w + jnp.sum(j, axis=0)[None, :]
    return acc


def main():
    rng = np.random.default_rng(0)
    ev = jnp.asarray(rng.integers(0, 3, (T, L)), jnp.int32)
    stage = jnp.asarray(rng.integers(0, 3, (E, L)), jnp.int32)
    pver = jnp.asarray(rng.integers(0, 3, (E, MP, D, L)), jnp.int32)

    fn = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((R, L), jnp.float32),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        scratch_shapes=[pltpu.VMEM((R, L), jnp.float32)],
    )
    got = jax.jit(fn)(ev, stage, pver)
    want = ref_impl(ev, stage, pver)
    err = float(jnp.max(jnp.abs(got - want)))
    print("max abs err:", err)
    # MXU (preferred f32) vs default-precision dot may differ in low bits.
    assert err < 1e-3, "MISMATCH"
    print("SPIKE OK")


if __name__ == "__main__":
    main()
