"""Per-phase cost breakdown of the engine step (VERDICT r2 item 1).

Times each batched slab kernel standalone, vmapped over K lanes, on the
real device, and prints XLA's bytes/flops estimates. Diagnostics to stderr.
"""
import os
import sys
import time

import jax

jax.config.update(
    "jax_compilation_cache_dir",
    os.path.join(os.path.expanduser("~"), ".cache", "cep_tpu_bench_cache"),
)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)

import jax.numpy as jnp
import numpy as np

sys.path.insert(0, ".")
from kafkastreams_cep_tpu.ops import slab as slab_mod


def log(m):
    print(m, file=sys.stderr, flush=True)


K, R, H, E, MP, D, W = 4096, 24, 2, 48, 8, 12, 12
RH = R * H
PW = 3 * R  # merged walkers

rng = np.random.default_rng(0)


def mk_slab():
    # Caveat: this random slab is internally inconsistent (dangling pstage
    # pointers, refs on free entries), so data-dependent walk trip counts
    # here understate real load — use profile_ablate.py (ablation inside the
    # real scan) before optimization decisions; see PROFILE_r04.md.
    i32 = jnp.int32
    n_live = E // 2
    stage = np.full((K, E), -1, np.int32)
    stage[:, :n_live] = rng.integers(0, 4, (K, n_live))
    off = np.full((K, E), -1, np.int32)
    off[:, :n_live] = rng.integers(0, 100, (K, n_live))
    return slab_mod.SlabState(
        stage=jnp.asarray(stage),
        off=jnp.asarray(off),
        refs=jnp.asarray(rng.integers(0, 3, (K, E)), i32),
        npreds=jnp.asarray(rng.integers(0, MP, (K, E)), i32),
        pstage=jnp.asarray(rng.integers(-1, 4, (K, E, MP)), i32),
        poff=jnp.asarray(rng.integers(0, 100, (K, E, MP)), i32),
        pver=jnp.asarray(rng.integers(0, 3, (K, E, MP, D)), i32),
        pvlen=jnp.asarray(rng.integers(1, 4, (K, E, MP)), i32),
        full_drops=jnp.zeros((K,), i32),
        pred_drops=jnp.zeros((K,), i32),
        missing=jnp.zeros((K,), i32),
        trunc=jnp.zeros((K,), i32),
        collisions=jnp.zeros((K,), i32),
        hot_hits=jnp.zeros((K,), i32),
        hot_misses=jnp.zeros((K,), i32),
        overflow_walks=jnp.zeros((K,), i32),
        demotions=jnp.zeros((K,), i32),
        walk_hops=jnp.zeros((K,), i32),
        extract_hops=jnp.zeros((K,), i32),
        drain_hops=jnp.zeros((K,), i32),
    )


def bench(name, fn, *args):
    jfn = jax.jit(fn)
    lowered = jfn.lower(*args)
    comp = lowered.compile()
    ca = comp.cost_analysis()
    if isinstance(ca, list):
        ca = ca[0]
    ca = ca or {}  # some backends return None — timing still prints
    out = jfn(*args)
    jax.block_until_ready(out)
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        out = jfn(*args)
        jax.block_until_ready(out)
        best = min(best, time.perf_counter() - t0)
    log(
        f"{name:16s}: {best * 1e3:7.2f} ms   bytes={ca.get('bytes accessed', 0):.2e} "
        f"flops={ca.get('flops', 0):.2e}  -> {ca.get('bytes accessed', 0) / best / 1e9:.0f} GB/s achieved"
    )
    return best


def main():
    i32 = jnp.int32
    slab = mk_slab()
    off = jnp.asarray(rng.integers(100, 200, (K,)), i32)

    ops = slab_mod.PutOps(
        en=jnp.asarray(rng.random((K, RH)) < 0.1),
        first=jnp.asarray(rng.random((K, RH)) < 0.3),
        cur_stage=jnp.asarray(rng.integers(0, 4, (K, RH)), i32),
        prev_stage=jnp.asarray(rng.integers(-1, 4, (K, RH)), i32),
        prev_off=jnp.asarray(rng.integers(0, 100, (K, RH)), i32),
        ver=jnp.asarray(rng.integers(0, 3, (K, RH, D)), i32),
        vlen=jnp.asarray(rng.integers(1, 4, (K, RH)), i32),
    )
    bench(
        "puts_batched",
        jax.vmap(lambda s, o, f: slab_mod.puts_batched(s, o, f)),
        slab, ops, off,
    )

    en_b = jnp.asarray(rng.random((K, R)) < 0.15)
    st_b = jnp.asarray(rng.integers(0, 4, (K, R)), i32)
    off_b = jnp.asarray(rng.integers(0, 100, (K, R)), i32)
    ver_b = jnp.asarray(rng.integers(0, 3, (K, R, D)), i32)
    vlen_b = jnp.asarray(rng.integers(1, 4, (K, R)), i32)
    bench(
        "branch_batched",
        jax.vmap(
            lambda s, e, st, o, v, vl: slab_mod.branch_batched(s, e, st, o, v, vl, W)
        ),
        slab, en_b, st_b, off_b, ver_b, vlen_b,
    )

    en_w = jnp.asarray(rng.random((K, PW)) < 0.15)
    st_w = jnp.asarray(rng.integers(0, 4, (K, PW)), i32)
    off_w = jnp.asarray(rng.integers(0, 100, (K, PW)), i32)
    ver_w = jnp.asarray(rng.integers(0, 3, (K, PW, D)), i32)
    vlen_w = jnp.asarray(rng.integers(1, 4, (K, PW)), i32)
    is_rm = jnp.concatenate(
        [jnp.zeros((K, R), bool), jnp.ones((K, 2 * R), bool)], axis=1
    )
    want = jnp.concatenate(
        [jnp.zeros((K, 2 * R), bool), jnp.ones((K, R), bool)], axis=1
    )
    bench(
        "walks_batched",
        jax.vmap(
            lambda s, e, st, o, v, vl, ir, wo: slab_mod.walks_batched(
                s, e, st, o, v, vl, ir, wo, W
            )
        ),
        slab, en_w, st_w, off_w, ver_w, vlen_w, is_rm, want,
    )


if __name__ == "__main__":
    main()
