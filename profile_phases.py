"""Thin wrapper — the profiler moved into the package CLI.

``python profile_phases.py`` ≡ ``python -m kafkastreams_cep_tpu.profile
phases`` (standalone slab-kernel timings; out-of-context — prefer
``ablate`` before optimization decisions, see PROFILE_r04.md).
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from kafkastreams_cep_tpu.profile import main

if __name__ == "__main__":
    sys.exit(main(["phases"] + sys.argv[1:]))
