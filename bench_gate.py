"""Bench regression gate — compare a new bench JSON against the
BENCH_r0x trajectory with noise tolerance.

The BENCH_r0x files record each round's ``bench.py`` headline (wrapped as
``{"n": ..., "parsed": {...}}`` by the driver; a bare bench JSON with a
``"value"`` key is accepted too).  Nothing watched that trajectory for
regressions — a PR that halved throughput would land silently.  This gate
fails (exit 1) when the new run is *statistically meaningfully* worse
than the trajectory's best on any guarded metric:

* **Throughput metrics** (higher is better): ``value`` (the headline
  events/s) and ``lossfree_evps``.  The threshold is
  ``best_baseline * (1 - tol)`` where ``tol = max(--rel-tol,
  (baseline_spread + new_spread) / 100)`` — the reported rep-to-rep
  spreads are the run's own noise estimate, so a noisy environment
  widens its own tolerance instead of flapping the gate.
* **Loss metrics** (must not degrade): the boolean flags
  (``lossfree_counters_zero``, ``lossfree_oracle_parity``, the
  ``tier_*`` parity pair, the ``shard_*`` fault-tolerance pair —
  evacuation parity and the rebalance loss contract — and the
  ``adapt_*`` pair — replan match parity and drift-A/B loss flags, and
  the ``latency_*`` pair — ledger on/off parity and the cadence/grace
  scheduling parity) may not go true→false; ``recall_sampled`` may not
  drop by more than the same relative tolerance.
* **Latency ceilings** (lower is better): ``latency_e2e_p99_s`` (the
  ledgered baseline's end-to-end p99) may not rise above the
  trajectory's best by more than a wide latency-specific tolerance
  (tail latency is noisier than throughput and log-bucket quantized).

Missing metrics are skipped on either side (early rounds carry fewer
keys), so the gate accepts the existing r01→r05 trajectory replayed
against itself unchanged — pinned by the tier-1 smoke test
(tests/test_bench_gate.py) together with a reject on an injected 2×
slowdown fixture.

Usage::

    python bench_gate.py NEW.json BENCH_r01.json BENCH_r02.json ...
    python bench_gate.py NEW.json --trajectory 'BENCH_r0*.json'

One JSON verdict on stdout; exit 0 = pass, 1 = regression, 2 = usage.
"""

from __future__ import annotations

import argparse
import glob
import json
import sys
from typing import Any, Dict, List, Optional, Tuple

#: Throughput metrics guarded for "not meaningfully lower".
RATE_METRICS = ("value", "lossfree_evps")
#: Boolean metrics guarded for "never true -> false".  The ``tier_*``
#: flags flatten out of the headline's nested ``tier`` block (compiler
#: tiering, BENCH_r06+): once a round records tiered/untiered match
#: parity on loss-free state, later rounds may not regress it.
FLAG_METRICS = (
    "lossfree_counters_zero",
    "lossfree_oracle_parity",
    "tier_match_parity",
    "tier_counters_zero",
    "shard_evac_parity",
    "shard_rebalance_lossfree",
    "tenant_match_parity",
    "tenant_loss_flags",
    "adapt_match_parity",
    "adapt_loss_flags",
    "tenant_iso_parity",
    "tenant_iso_compliant_lossfree",
    "latency_parity",
    "latency_ab_parity",
    "overload_ledger_reconciles",
    "overload_recovers",
)
#: Ratio metrics guarded like rates (0..1, higher is better).
RATIO_METRICS = ("recall_sampled",)
#: Latency metrics guarded for "not meaningfully higher" (lower is
#: better): the ledgered baseline's end-to-end p99 from the ``latency``
#: block.  Tail latency is far noisier than throughput (log-bucket
#: quantization alone steps ~78% between adjacent edges), so the
#: ceiling uses its own wider relative tolerance.
CEILING_METRICS = ("latency_e2e_p99_s",)
CEILING_REL_TOL = 1.0


def extract_metrics(doc: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    """The comparable metrics of one bench document, or None when the
    document carries no parsed result (e.g. BENCH_r01's empty round)."""
    parsed = doc.get("parsed", doc) if isinstance(doc, dict) else None
    if not isinstance(parsed, dict) or "value" not in parsed:
        return None
    out: Dict[str, Any] = {}
    for k in RATE_METRICS + RATIO_METRICS:
        v = parsed.get(k)
        if isinstance(v, (int, float)) and not isinstance(v, bool) and v > 0:
            out[k] = float(v)
    tier = parsed.get("tier")
    flat = dict(parsed)
    if isinstance(tier, dict):
        # Nested tier block -> flat ``tier_*`` keys for the flag guard.
        flat["tier_match_parity"] = tier.get("match_parity")
        flat["tier_counters_zero"] = tier.get("counters_zero")
    resilience = parsed.get("resilience")
    shard = (
        resilience.get("shard") if isinstance(resilience, dict) else None
    )
    if isinstance(shard, dict):
        # Nested resilience.shard block (BENCH_r08+) -> flat ``shard_*``
        # keys: the exactly-once-under-fault flags join the flag guard.
        flat["shard_evac_parity"] = shard.get("evac_parity")
        flat["shard_rebalance_lossfree"] = shard.get("rebalance_lossfree")
    tenants = parsed.get("tenants")
    if isinstance(tenants, dict):
        # Nested tenants block (BENCH_r07+) -> flat ``tenant_*`` keys:
        # the multi-tenant bank's bit-exactness vs the naive-fused bank
        # and its all-counters-zero flag may never regress true -> false.
        flat["tenant_match_parity"] = tenants.get("match_parity")
        flat["tenant_loss_flags"] = tenants.get("counters_zero")
    tenant_iso = (
        resilience.get("tenant") if isinstance(resilience, dict) else None
    )
    if isinstance(tenant_iso, dict):
        # Nested resilience.tenant block (BENCH_r09+) -> flat
        # ``tenant_iso_*`` keys: with one tenant flooding, the compliant
        # tenants' matches stay bit-equal to the unquotaed fault-free
        # bank's (parity) and lose nothing (shed accounting reconciles).
        flat["tenant_iso_parity"] = tenant_iso.get("parity")
        flat["tenant_iso_compliant_lossfree"] = tenant_iso.get(
            "compliant_lossfree"
        )
    latency = parsed.get("latency")
    if isinstance(latency, dict):
        # Nested latency block (BENCH_r10+) -> flat ``latency_*`` keys:
        # the ledger on/off match+counter parity, the within-config
        # cadence/grace scheduling parity, and the end-to-end p99
        # ceiling (lower is better, CEILING_METRICS).
        flat["latency_parity"] = latency.get("parity")
        flat["latency_ab_parity"] = latency.get("ab_match_parity")
        p99 = latency.get("e2e_p99_s")
        if (
            isinstance(p99, (int, float))
            and not isinstance(p99, bool) and p99 > 0
        ):
            out["latency_e2e_p99_s"] = float(p99)
    overload = parsed.get("overload")
    if isinstance(overload, dict):
        # Nested overload block (BENCH_r11+) -> flat ``overload_*``
        # keys: the brownout loss ledger must keep reconciling exactly
        # (offered == admitted + shed + dead-lettered) and the ladder
        # must keep recovering to L0 once the flood subsides.
        flat["overload_ledger_reconciles"] = overload.get(
            "ledger_reconciles"
        )
        flat["overload_recovers"] = overload.get("recovers")
    adapt = parsed.get("adapt")
    if isinstance(adapt, dict):
        # Nested adapt block (BENCH_r08+) -> flat ``adapt_*`` keys: the
        # hybrid-sweep + drift-A/B parity (replanned matches bit-equal
        # to the stale plan's) and the all-loss-counters-zero flag.
        flat["adapt_match_parity"] = adapt.get("match_parity")
        flat["adapt_loss_flags"] = adapt.get("counters_zero")
    for k in FLAG_METRICS:
        v = flat.get(k)
        if isinstance(v, bool):
            out[k] = v
    sp = parsed.get("spread_pct")
    out["spread_pct"] = (
        float(sp) if isinstance(sp, (int, float)) else 0.0
    )
    return out


def load_doc(path: str) -> Dict[str, Any]:
    with open(path, "r", encoding="utf-8") as f:
        return json.load(f)


def gate(
    new: Dict[str, Any],
    baselines: List[Dict[str, Any]],
    rel_tol: float = 0.10,
) -> Tuple[bool, Dict[str, Any]]:
    """Compare ``new`` (a bench doc) against ``baselines`` (bench docs,
    trajectory order).  Returns ``(ok, report)``."""
    new_m = extract_metrics(new)
    checks: List[Dict[str, Any]] = []
    ok = True
    if new_m is None:
        return False, {
            "ok": False,
            "error": "new bench document carries no parsed result",
            "checks": checks,
        }
    base_ms = [m for m in (extract_metrics(b) for b in baselines) if m]
    if not base_ms:
        return True, {
            "ok": True,
            "note": "no baseline carries a parsed result; nothing to gate",
            "checks": checks,
        }
    new_spread = new_m.get("spread_pct", 0.0)

    for metric in RATE_METRICS + RATIO_METRICS:
        cands = [m for m in base_ms if metric in m]
        if not cands or metric not in new_m:
            continue
        best = max(cands, key=lambda m: m[metric])
        tol = max(rel_tol, (best["spread_pct"] + new_spread) / 100.0)
        floor = best[metric] * (1.0 - tol)
        passed = new_m[metric] >= floor
        ok &= passed
        checks.append(
            {
                "metric": metric,
                "new": new_m[metric],
                "baseline_best": best[metric],
                "tolerance": round(tol, 4),
                "floor": round(floor, 1),
                "ok": passed,
            }
        )
    for metric in CEILING_METRICS:
        cands = [m for m in base_ms if metric in m]
        if not cands or metric not in new_m:
            continue
        best = min(cands, key=lambda m: m[metric])
        tol = max(
            CEILING_REL_TOL, (best["spread_pct"] + new_spread) / 100.0
        )
        ceiling = best[metric] * (1.0 + tol)
        passed = new_m[metric] <= ceiling
        ok &= passed
        checks.append(
            {
                "metric": metric,
                "new": new_m[metric],
                "baseline_best": best[metric],
                "tolerance": round(tol, 4),
                "ceiling": round(ceiling, 6),
                "ok": passed,
            }
        )
    for metric in FLAG_METRICS:
        if not any(m.get(metric) is True for m in base_ms):
            continue
        if metric not in new_m:
            continue
        passed = bool(new_m[metric])
        ok &= passed
        checks.append(
            {
                "metric": metric,
                "new": new_m[metric],
                "baseline_best": True,
                "ok": passed,
            }
        )
    return ok, {"ok": ok, "rel_tol": rel_tol, "checks": checks}


def gate_paths(
    new_path: str, baseline_paths: List[str], rel_tol: float = 0.10
) -> Tuple[bool, Dict[str, Any]]:
    okflag, report = gate(
        load_doc(new_path),
        [load_doc(p) for p in sorted(baseline_paths)],
        rel_tol=rel_tol,
    )
    report["new"] = new_path
    report["baselines"] = sorted(baseline_paths)
    return okflag, report


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="bench_gate.py", description=__doc__.split("\n\n")[0]
    )
    p.add_argument("new", help="new bench JSON to gate")
    p.add_argument("baselines", nargs="*", help="baseline bench JSONs")
    p.add_argument(
        "--trajectory",
        help="glob of baseline files (e.g. 'BENCH_r0*.json')",
    )
    p.add_argument("--rel-tol", type=float, default=0.10)
    args = p.parse_args(argv)
    paths = list(args.baselines)
    if args.trajectory:
        paths += glob.glob(args.trajectory)
    paths = [p_ for p_ in paths if p_ != args.new]
    if not paths:
        print("bench_gate: no baseline files given", file=sys.stderr)
        return 2
    okflag, report = gate_paths(args.new, paths, rel_tol=args.rel_tol)
    print(json.dumps(report, indent=2))
    return 0 if okflag else 1


if __name__ == "__main__":
    sys.exit(main())
