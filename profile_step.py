"""Thin wrapper — the profiler moved into the package CLI.

``python profile_step.py`` ≡ ``python -m kafkastreams_cep_tpu.profile
step`` (structured PROFILE JSON on stdout, diagnostics on stderr).  Size
via ``--k/--t/--reps`` or the historical ``PROF_T`` env var.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from kafkastreams_cep_tpu.profile import main

if __name__ == "__main__":
    sys.exit(main(["step"] + sys.argv[1:]))
