"""Round-3 profiling: where does the 45ms/step go?

Phase A: K-scaling — flat step time => dispatch/op-count bound;
linear => bandwidth bound.
Phase B: per-phase cost via ablated step builds.
Diagnostics to stderr.
"""
import os
import sys
import time

import jax

jax.config.update(
    "jax_compilation_cache_dir",
    os.path.join(os.path.expanduser("~"), ".cache", "cep_tpu_bench_cache"),
)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)

import jax.numpy as jnp
import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "examples"))

import stock_demo
from kafkastreams_cep_tpu.engine import EngineConfig, EventBatch
from kafkastreams_cep_tpu.parallel import BatchMatcher


def log(m):
    print(m, file=sys.stderr, flush=True)


def make_batch(rng, K, T):
    prices = rng.integers(90, 131, size=(K, T)).astype(np.int32)
    volumes = rng.integers(600, 1101, size=(K, T)).astype(np.int32)
    return EventBatch(
        key=jnp.broadcast_to(jnp.arange(K, dtype=jnp.int32)[:, None], (K, T)),
        value={"price": jnp.asarray(prices), "volume": jnp.asarray(volumes)},
        ts=jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32)[None, :] * 2, (K, T)),
        off=jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32)[None, :], (K, T)),
        valid=jnp.ones((K, T), bool),
    )


def time_scan(K, T, cfg, reps=2):
    batch = BatchMatcher(stock_demo.stock_pattern(), K, cfg)
    state0 = batch.init_state()
    rng = np.random.default_rng(42)
    events = make_batch(rng, K, T)
    t0 = time.perf_counter()
    state, out = batch.scan(state0, events)
    jax.block_until_ready(out.count)
    compile_s = time.perf_counter() - t0
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        state, out = batch.scan(state0, events)
        jax.block_until_ready(out.count)
        best = min(best, time.perf_counter() - t0)
    return best, compile_s


def main():
    T = int(os.environ.get("PROF_T", "32"))
    cfg = EngineConfig(
        max_runs=24, slab_entries=48, slab_preds=8, dewey_depth=12, max_walk=12
    )
    for K in (512, 4096, 16384):
        best, comp = time_scan(K, T, cfg)
        log(
            f"K={K:6d} T={T}: scan {best * 1e3:8.1f} ms "
            f"({best / T * 1e3:6.2f} ms/step, {K * T / best / 1e3:8.0f}K ev/s) "
            f"[compile {comp:.0f}s]"
        )


if __name__ == "__main__":
    main()
